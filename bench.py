"""Benchmark: flagship GPT-350M-class training step on one TPU chip.

Prints ONE JSON line: tokens/sec/chip for a full fused training step
(fwd + bwd + FusedAdam) — the TPU counterpart of the reference's
"Average Iteration Time" GPT harness
(tests/L0/run_transformer/gpt_scaling_test.py:13-47) and the
images/sec Speed meter (examples/imagenet/main_amp.py:386-397).
The reference publishes no absolute numbers (BASELINE.md), so
vs_baseline reports the speedup over this framework's own non-fused
fp32 eager-style baseline measured in the same run when fast enough,
else 1.0.
"""

from __future__ import annotations

import json
import sys
import time

import jax
import jax.numpy as jnp


def main():
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    on_tpu = jax.default_backend() not in ("cpu",)
    if on_tpu:
        # batch 8 fits HBM without remat; donation keeps opt state in
        # place (remat=False + donate=True measured ~27% faster than the
        # remat=True/no-donate combination on v5e)
        batch, seq = 8, 1024
        cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                        num_layers=24, num_heads=16, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        remat=False, use_flash_attention=True)
        iters, warmup = 20, 3
    else:  # CPU smoke mode
        batch, seq = 2, 64
        cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0)
        iters, warmup = 3, 1

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=on_tpu)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params  # donated state owns the master copy

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)

    import numpy as np

    for _ in range(warmup):
        opt_state, loss = step(opt_state, tokens, labels)
    _ = np.asarray(loss)  # full sync (block_until_ready is unreliable
    # through the remote-tunnel backend)

    t0 = time.perf_counter()
    for _ in range(iters):
        opt_state, loss = step(opt_state, tokens, labels)
    _ = np.asarray(loss)
    dt = (time.perf_counter() - t0) / iters

    tokens_per_sec = batch * seq / dt
    print(json.dumps({
        "metric": "gpt350m_train_tokens_per_sec_per_chip",
        "value": round(tokens_per_sec, 1),
        "unit": "tokens/s",
        "vs_baseline": 1.0,
    }))


if __name__ == "__main__":
    sys.exit(main())
