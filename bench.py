"""Benchmark: flagship GPT-350M-class training step on one TPU chip.

Prints ONE JSON line: tokens/sec/chip for a full fused training step
(fwd + bwd + FusedAdam) — the TPU counterpart of the reference's
"Average Iteration Time" GPT harness
(tests/L0/run_transformer/gpt_scaling_test.py:13-47) and the
images/sec Speed meter (examples/imagenet_amp.py ≡ main_amp.py:386-397).

The reference publishes no absolute numbers (BASELINE.md), so
`vs_baseline` is MEASURED in the same run against this framework's own
non-fused fp32 eager-style baseline: fp32 params/compute, dense
(S x S materialized) attention, per-leaf unfused Adam, no buffer
donation — the shape of a pre-apex training loop, ≡ the fused-vs-torch
comparisons the reference harnesses print
(apex/contrib/examples/multihead_attn/perf_test_multihead_attn.py:101-110).
Secondary keys in the same line: fused/unfused MHA latency and the
fused-optimizer step time.
"""

from __future__ import annotations

import contextlib
import functools
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np


_TRANSIENT = ("remote_compile", "response body", "UNAVAILABLE",
              "DEADLINE_EXCEEDED", "Connection", "INTERNAL: http")


def _retry(fn, *args, attempts=3):
    """Bounded retry for transient remote-compile/tunnel flakes (the
    round-3 BERT number was lost to a single 'response body closed'
    read error — VERDICT r3 weak #2).  Non-transient errors raise
    immediately; transient ones get `attempts` tries with a pause."""
    import gc

    last = None
    for i in range(attempts):
        try:
            return fn(*args)
        except Exception as e:  # noqa: BLE001 — classify then re-raise
            msg = repr(e)
            if not any(t in msg for t in _TRANSIENT):
                raise
            last = e
            gc.collect()
            time.sleep(2.0 * (i + 1))
    raise last


# per-config RecompileSentry summaries, stamped into the result JSON as
# "n_compiles" (ISSUE 5 satellite): a config whose steady state
# recompiles is measuring XLA, not training, and _time_steps raises
_SENTRY = {}


def _time_steps(step, state, tokens, labels, iters, warmup, name=None,
                call=None):
    """Time `iters` steady-state steps under the RecompileSentry.

    call: optional adapter `(sentry, state) -> (state, loss)` for
    steps whose signature is not `step(state, tokens, labels)` (the
    MoE step threads a batch tuple + aux) — the warmup/sync/steady
    measurement policy stays in this ONE place either way."""
    from apex_tpu.monitor.compile import RecompileSentry

    sentry = RecompileSentry(step, name=name or "bench", warn=False)
    if call is None:
        def call(s, st):
            return s(st, tokens, labels)
    for _ in range(warmup):
        state, loss = call(sentry, state)
    # the sentry replaces the old hand-rolled "warmup 2: donated-state
    # second compile" dance: keep warming (bounded) while the last call
    # still compiled, whatever the reason — layout recompiles included
    extra = 0
    while (extra < 3 and sentry.events
           and sentry.events[-1]["call"] == sentry.calls):
        state, loss = call(sentry, state)
        extra += 1
    _ = np.asarray(loss)  # full sync (block_until_ready is unreliable
    # through the remote-tunnel backend)
    sentry.mark_steady()
    t0 = time.perf_counter()
    for _ in range(iters):
        state, loss = call(sentry, state)
    _ = np.asarray(loss)
    dt = (time.perf_counter() - t0) / iters
    if name:
        _SENTRY[name] = sentry.summary()
    if sentry.steady_recompiles:
        raise RuntimeError(
            f"{name or 'bench'}: {sentry.steady_recompiles} steady-state"
            f" recompile(s) during the timed window — the measurement is"
            f" compilation, not training; last signature: "
            f"{sentry.events[-1]['signature'][:120]}")
    return dt


def _fused_tokens_per_sec(on_tpu, batch, seq, cfg,
                          master_dtype=jnp.float32, name="gpt350m"):
    from apex_tpu.models.gpt import GPT
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=on_tpu, master_dtype=master_dtype)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params  # donated state owns the master copy

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    iters, warmup = (20, 3) if on_tpu else (3, 1)
    dt = _time_steps(step, opt_state, tokens, labels, iters, warmup,
                     name=name)
    M.destroy_model_parallel()
    return batch * seq / dt


def _baseline_tokens_per_sec(on_tpu, batch, seq, cfg_fused):
    """Non-fused fp32 baseline: dense (S x S) attention, per-leaf
    unfused Adam (one jnp op chain per tensor, no flat buffer).  State
    is still donated — without it the three fp32 state copies alive per
    step thrash the allocator (11 s/iter at batch 1), which would
    measure the allocator, not the missing fusion."""
    import dataclasses

    from apex_tpu.models.gpt import GPT
    from apex_tpu.parallel import mesh as M

    cfg = dataclasses.replace(cfg_fused, dtype=jnp.float32,
                              logits_dtype=None,
                              use_flash_attention=False)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))

    def adam_leaf(p, g, m, v, step_t):
        b1, b2, eps, lr = 0.9, 0.999, 1e-8, 1e-4
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mhat = m / (1 - b1 ** step_t)
        vhat = v / (1 - b2 ** step_t)
        return p - lr * mhat / (jnp.sqrt(vhat) + eps), m, v

    from jax import shard_map
    from jax.sharding import PartitionSpec as P
    specs = model.partition_specs()

    def local_step(state, tokens, labels):
        params, m, v, t = state
        loss, grads = jax.value_and_grad(
            lambda p: model.loss(p, tokens, labels))(params)
        t = t + 1
        out = jax.tree.map(lambda p, g, mm, vv: adam_leaf(p, g, mm, vv, t),
                           params, grads, m, v)
        new_p = jax.tree.map(lambda o: o[0], out,
                             is_leaf=lambda x: isinstance(x, tuple))
        new_m = jax.tree.map(lambda o: o[1], out,
                             is_leaf=lambda x: isinstance(x, tuple))
        new_v = jax.tree.map(lambda o: o[2], out,
                             is_leaf=lambda x: isinstance(x, tuple))
        return (new_p, new_m, new_v, t), loss

    zeros = jax.tree.map(jnp.zeros_like, params)
    state = (params, zeros, jax.tree.map(jnp.zeros_like, params),
             jnp.zeros((), jnp.int32))
    st_specs = (specs, specs, specs, P())
    step = jax.jit(shard_map(local_step, mesh=mesh,
                             in_specs=(st_specs, P(), P()),
                             out_specs=(st_specs, P()), check_vma=False),
                   donate_argnums=(0,))

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    # the recompile sentry inside _time_steps handles the donated-state
    # second compile (output layouts differing from the initial inputs)
    # by extending warmup while calls still compile — no hand-rolled
    # "warmup 2" needed, and a steady-state recompile now raises
    # instead of silently polluting the measurement
    iters, warmup = (3, 1) if on_tpu else (2, 1)
    dt = _time_steps(step, state, tokens, labels, iters, warmup,
                     name="baseline")
    M.destroy_model_parallel()
    return batch * seq / dt


def _baseline_best(on_tpu, batch, seq, cfg_fused):
    """fp32 state + activations need ~3x the fused path's HBM; fall back
    to smaller batches (tokens/s is per-token, so comparable) before
    giving up."""
    import gc

    err = "no batch attempted"
    # fp32 state + activations are ~3-4x the fused path's footprint:
    # batch/2 nominally fits but XLA spills and measures the allocator
    # (~15x slowdown observed), so start where there is real headroom
    b = max(1, batch // 4)
    while b >= 1:
        try:
            return _baseline_tokens_per_sec(on_tpu, b, seq, cfg_fused), b
        except Exception as e:
            # keep only the message: the traceback would pin the failed
            # attempt's multi-GB buffers across the retry
            err = repr(e)
            b //= 2
            gc.collect()
    raise RuntimeError(err)


def _mha_latencies(on_tpu):
    """Fused (flash kernel) vs unfused (dense jnp) attention fwd+bwd ms
    at B8 H16 S2048 D64 ≡ perf_test_multihead_attn's timing loop."""
    from apex_tpu.ops.flash_attention import (
        attention_reference,
        flash_attention,
    )
    B, H, S, D = (8, 16, 2048, 64) if on_tpu else (2, 2, 256, 64)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, D), jnp.bfloat16)
               for kk in ks)

    def timed(fn):
        g = jax.jit(jax.grad(
            lambda q, k, v: fn(q, k, v).astype(jnp.float32).mean(),
            argnums=(0, 1, 2)))
        out = g(q, k, v)
        _ = np.asarray(out[0].ravel()[0])
        iters = 10 if on_tpu else 2
        t0 = time.perf_counter()
        for _ in range(iters):
            out = g(q, k, v)
        _ = np.asarray(out[0].ravel()[0])
        return (time.perf_counter() - t0) / iters * 1e3

    fused = timed(functools.partial(flash_attention, causal=True))
    unfused = timed(functools.partial(attention_reference, causal=True))
    return fused, unfused


def _gpt1p3b_tokens_per_sec(on_tpu):
    """1.3B single-chip config (VERDICT r2 #1): h2048 L24 H32, batch 7 x
    seq 512, bf16 Adam state (p+m+v at 6 B/param fits one 16 GB chip),
    NO remat (b7 activations fit; the round-5 sweep: b8 dots 13.24k,
    b8 no-remat 13.17k, b7 no-remat 13.35k, names:all5 13.13k — the
    step is component-bound, not remat-bound; docs/PERF.md anatomy),
    bf16 LM-head logits."""
    from apex_tpu.models.gpt import GPT2_1p3B, GPTConfig
    if on_tpu:
        batch, seq = 7, 512
        cfg = GPTConfig(vocab_size=50304, seq_len=seq, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        remat=False,
                        use_flash_attention=True, **GPT2_1p3B)
    else:
        batch, seq = 2, 64
        cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0,
                        remat=True, remat_policy="dots")
    return _fused_tokens_per_sec(on_tpu, batch, seq, cfg,
                                 master_dtype=jnp.bfloat16,
                                 name="gpt1p3b")


def _bert_seq_per_sec(on_tpu):
    """BERT-Large MLM+NSP step with FusedLAMB (VERDICT r2 #5): flash
    padding-masked attention + MXU segment-sum trust ratios.  Round-3
    anatomy in docs/PERF.md: round 4 = 101 seq/s ~= 53% MFU at
    b32 x s512 with bf16 LAMB state."""
    from apex_tpu.models.bert import Bert, BertConfig
    from apex_tpu.optimizers.fused_lamb import FusedLAMB
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    # batch 32: LAMB exists FOR large batches — the optimizer pass
    # amortizes (b8: 79 seq/s, b16: 94.5, b32: 101; b64 fails compile),
    # bf16 master state halves the LAMB pass HBM traffic (round 4)
    batch, seq = (32, 512) if on_tpu else (2, 64)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    cfg = (BertConfig(seq_len=seq, dtype=jnp.bfloat16,
                      use_flash_attention=True) if on_tpu else
           BertConfig(seq_len=seq, hidden=128, num_layers=2, num_heads=4,
                      dtype=jnp.bfloat16))
    model = Bert(cfg)
    params = model.init(jax.random.PRNGKey(0))
    # standard BERT recipe: no weight decay for bias/LayerNorm params
    # (≡ _get_params_for_weight_decay_optimization's two param groups)
    from apex_tpu.transformer.pipeline_parallel.common import (
        get_params_for_weight_decay_optimization,
    )
    wd_mask = get_params_for_weight_decay_optimization(params)
    opt = FusedLAMB(lr=1e-4, weight_decay=0.01, use_pallas=on_tpu,
                    master_dtype=jnp.bfloat16 if on_tpu else jnp.float32,
                    wd_mask=wd_mask)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    del params
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    mlm_labels = jnp.roll(tokens, -1, axis=1)
    loss_mask = jax.random.bernoulli(jax.random.PRNGKey(2), 0.15,
                                     (batch, seq))
    nsp = jax.random.randint(jax.random.PRNGKey(3), (batch,), 0, 2)

    def loss_fn(p, t, l):
        return model.loss(p, t, l, loss_mask, nsp_labels=nsp)

    step = make_tp_dp_train_step(model, opt, mesh, loss_fn=loss_fn,
                                 donate=True)
    iters, warmup = (10, 2) if on_tpu else (2, 1)
    dt = _time_steps(step, opt_state, tokens, mlm_labels, iters, warmup,
                     name="bert")
    M.destroy_model_parallel()
    return batch / dt


def _resnet50_img_per_sec(on_tpu):
    """ResNet-50 AMP-O1 fused train step, synthetic data, batch 256 —
    the Speed meter of the reference's canonical example
    (examples/imagenet/main_amp.py:386-397; see examples/imagenet_amp.py
    for the full training loop).  Round-3 measurement: 1,649 img/s/chip
    (docs/PERF.md) — this puts it in the driver JSON."""
    from jax.sharding import PartitionSpec as P

    from apex_tpu import amp
    from apex_tpu.models.resnet import ResNet
    from apex_tpu.ops.xentropy import softmax_cross_entropy_loss
    from apex_tpu.optimizers.fused_sgd import FusedSGD
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M

    batch, size, arch = (256, 224, "resnet50") if on_tpu else \
        (4, 32, "resnet18")
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    # space_to_depth stem computes the IDENTICAL function (exact weight
    # rewrite, models/resnet.py) ~5 ms/step faster on v5e; round 5 also
    # moved BN batch stats off the Pallas welford kernel onto XLA's
    # fused reductions (ops/welford.py) — together 1,665 -> 2,305-2,319
    # img/s (3 runs; docs/PERF.md has the per-layer anatomy)
    model = ResNet(arch, num_classes=1000, axis_name="dp",
                   stem="space_to_depth" if on_tpu else "conv7")
    params, mstate = model.init(jax.random.PRNGKey(0))
    amp_state = amp.initialize(opt_level="O1")

    def loss_fn(p, ms, b):
        x, y = b
        logits, new_ms = model.apply(p, ms, x, training=True)
        return jnp.mean(softmax_cross_entropy_loss(
            logits.astype(jnp.float32), y)), new_ms

    opt = FusedSGD(lr=0.1, momentum=0.9, weight_decay=1e-4)
    state = opt.init(params)
    scaler = amp_state.loss_scalers[0]
    step = ddp.make_train_step(loss_fn, opt, mesh, amp_state=amp_state,
                               batch_spec=(P("dp"), P("dp")),
                               with_state=True)
    x = jax.random.normal(jax.random.PRNGKey(1), (batch, size, size, 3))
    y = jax.random.randint(jax.random.PRNGKey(2), (batch,), 0, 1000)
    iters, warmup = (20, 3) if on_tpu else (2, 1)
    for _ in range(warmup):
        state, scaler, mstate, loss = step(state, scaler, mstate, (x, y))
    _ = np.asarray(loss)
    t0 = time.perf_counter()
    for _ in range(iters):
        state, scaler, mstate, loss = step(state, scaler, mstate, (x, y))
    _ = np.asarray(loss)
    dt = (time.perf_counter() - t0) / iters
    M.destroy_model_parallel()
    return batch / dt


def _long_context_32k(on_tpu):
    """32k-token causal flash attention fwd+bwd on one chip (B1 H8 D64)
    — the long-context kernel north star (VERDICT r4 next-#4; dense
    attention cannot represent this: the bf16 score matrix alone would
    be 17 GB).  Returns (ms, tokens/s)."""
    from apex_tpu.ops.flash_attention import flash_attention

    B, H, S, D = (1, 8, 32768, 64) if on_tpu else (1, 2, 1024, 32)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, H, S, D), jnp.bfloat16)
               for kk in ks)

    g = jax.jit(jax.grad(
        lambda q, k, v: flash_attention(q, k, v, causal=True).astype(
            jnp.float32).mean(), argnums=(0, 1, 2)))
    out = g(q, k, v)
    _ = np.asarray(out[0].ravel()[0])
    iters = 5 if on_tpu else 2
    t0 = time.perf_counter()
    for _ in range(iters):
        out = g(q, k, v)
    _ = np.asarray(out[0].ravel()[0])
    dt = (time.perf_counter() - t0) / iters
    return dt * 1e3, B * S / dt


def _zero2_bucket_sweep(on_tpu):
    """ZeRO-2 DistributedFusedAdam wired through ddp.make_train_step
    (ISSUE 3 satellite): sweep the n_buckets backward-overlap knob over
    the local dp axis.  With one chip dp=1 — the sweep still exercises
    the per-bucket reduce-scatter/update/gather pipeline structure, and
    on multi-chip runs it measures the real overlap.  Returns
    {"dp": world, "tokens_per_sec": {n_buckets: value}}."""
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M
    # after apex_tpu: _compat shims `jax.shard_map` on jax 0.4.x
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if on_tpu:
        batch, seq = 8, 1024
        cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                        num_layers=8, num_heads=16, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        use_flash_attention=True)
    else:
        batch, seq = 2, 64
        cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0)
    out = {}
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel()
    dp = mesh.devices.size
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)

    def loss_fn(p, b):
        return model.loss(p, b[0], b[1])

    for nb in (1, 2, 4):
        opt = DistributedFusedAdam(
            num_shards=dp, lr=1e-4, n_buckets=nb,
            use_pallas=on_tpu or None,
            master_dtype=jnp.bfloat16 if on_tpu else jnp.float32)
        sspec = opt.state_partition_specs()
        # one-shot sharded init per bucket config: each nb is a fresh
        # optimizer, so the per-iteration jit is inherent, not a leak
        state = jax.jit(shard_map(  # lint: disable=HS405
            opt.init, mesh=mesh, in_specs=(P(),), out_specs=sspec,
            check_vma=False))(params)
        step = ddp.make_train_step(loss_fn, opt, mesh,
                                   batch_spec=(P("dp"), P("dp")))
        iters, warmup = (10, 2) if on_tpu else (2, 1)
        for _ in range(warmup):
            state, _, loss = step(state, None, (tokens, labels))
        _ = np.asarray(loss)
        t0 = time.perf_counter()
        for _ in range(iters):
            state, _, loss = step(state, None, (tokens, labels))
        _ = np.asarray(loss)
        dt = (time.perf_counter() - t0) / iters
        out[str(nb)] = round(batch * seq / dt, 1)
        del state
    M.destroy_model_parallel()
    return {"dp": dp, "tokens_per_sec": out}


def _serve_decode_bench(on_tpu):
    """Continuous-batching decode throughput + per-token latency at N
    concurrent ragged streams (ISSUE 8 — the serving bench axes the
    "millions of users" north star is judged by).  Each concurrency
    level builds the flagship serve engine (apex_tpu.serve; GPT-350M
    weights on TPU, the smoke config on CPU), submits N ragged-length
    prompts, and drives the engine to completion through
    `serve.measure_decode` — the shared drive-and-measure helper
    (examples/serve_gpt.py quotes the same convention): device-synced
    per-step timing, throughput over tokens ACTUALLY emitted, p50/p99
    per-token latency over pure decode steps with admission/
    retirement churn steps excluded.  The RecompileSentry verdict
    rides out as `recompile_ok` — False means churn retraced the
    decode step, which is a correctness bug, not a perf number."""
    import numpy as np

    from apex_tpu.serve import build_flagship_engine, measure_decode

    streams = (1, 8, 64, 256) if on_tpu else (1, 8)
    sweep = {}
    params = None                   # one flagship init, shared by the sweep
    for n in streams:
        eng = build_flagship_engine(on_tpu, n_slots=n, params=params)
        params = eng.params
        rng = np.random.RandomState(0)
        mp = eng.serve_cfg.max_prompt_len
        max_new = eng.serve_cfg.max_new_cap if on_tpu else 8
        for _ in range(n):
            plen = int(rng.randint(1, mp + 1))
            eng.submit(rng.randint(
                0, eng.model_cfg.vocab_size, plen).tolist(), max_new)
        m = measure_decode(eng, max_steps=16 * max_new + 64)
        entry = {
            "tokens_per_sec": round(m["tokens_per_sec"], 1),
            "p50_ms": round(m["p50_ms"], 3),
            "p99_ms": round(m["p99_ms"], 3),
            "steps": m["steps"],
            "churn_steps": m["churn_steps"],
            "recompile_ok": m["recompile_ok"],
        }
        # the request-lifecycle ledger summary (ISSUE 10): per-level
        # TTFT / queue-wait / per-token percentiles + pool/queue peaks
        # ride under the unreserved `serving` dict; _stamp_serve lifts
        # the largest-N scalars into the flat v7 `serve_*` fields
        if eng.telemetry is not None:
            led = eng.telemetry.ledger

            def ms(v):
                return None if v is None else round(1e3 * v, 3)
            entry["ledger"] = {
                "requests": led.n_retired,
                "tokens": led.tokens_emitted,
                "ttft_p50_ms": ms(led.ttft.percentile(50.0)),
                "ttft_p99_ms": ms(led.ttft.percentile(99.0)),
                "token_p50_ms": ms(led.token_lat.percentile(50.0)),
                "token_p99_ms": ms(led.token_lat.percentile(99.0)),
                "queue_wait_p99_ms": ms(led.queue_wait.percentile(99.0)),
                "queue_wait_max_ms": ms(led.queue_wait.max),
                "pool_util_peak": round(
                    eng.telemetry.peaks["pool_util"], 4),
                "queue_depth_peak": eng.telemetry.peaks["queue_depth"],
            }
        sweep[str(n)] = entry
    return sweep


def _serve_overload_bench(on_tpu):
    """The overload leg (ISSUE 14): a 4x-slot-capacity storm with
    mixed deadlines against a BOUNDED admission queue — what the
    serving plane does when traffic exceeds it, measured instead of
    assumed.  Stamps (via _stamp_serve_overload): `serve_shed_fraction`
    (shed+expired fraction of submissions — how much the engine
    refused to protect the rest) and `serve_goodput_tokens_per_sec`
    (tokens of requests that completed OK per wall second — the
    number overload control exists to protect; contrast with
    `serve_decode_tokens_per_sec`, which is raw decode throughput
    under healthy load).  The ledger's terminal-state balance and the
    page-pool reconciliation are correctness gates: a False voids the
    stamp."""
    import time as _t

    import numpy as np

    from apex_tpu.serve import build_flagship_engine
    from apex_tpu.serve.engine import flagship_n_slots

    n_slots = flagship_n_slots(on_tpu)
    eng = build_flagship_engine(
        on_tpu, serve_overrides={"max_queue_depth": 2 * n_slots,
                                 "shed_policy": "shed-lowest-deadline"})
    n_requests = 4 * n_slots
    max_new = eng.serve_cfg.max_new_cap if on_tpu else 8
    rng = np.random.RandomState(0)
    mp = eng.serve_cfg.max_prompt_len
    t0 = _t.perf_counter()
    for i in range(n_requests):
        plen = int(rng.randint(1, mp + 1))
        budget = int(rng.randint(1, max_new + 1))
        # mixed deadlines: half the storm carries a finite deadline
        # (the shed policy's victim-ordering pool), half is unbounded
        dl = 120_000.0 if i % 2 else None
        eng.submit(rng.randint(0, eng.model_cfg.vocab_size,
                               plen).tolist(), budget, deadline_ms=dl)
    fins = {}
    steps = 0
    while eng.pending:
        if steps >= n_requests * max_new + 64:
            raise RuntimeError("overload storm did not drain")
        eng.step()
        for f in eng.poll():
            fins[f.request_id] = f
        steps += 1
    wall = _t.perf_counter() - t0
    led = eng.telemetry.ledger
    good_tokens = sum(len(f.tokens) for f in fins.values()
                      if f.status == "ok")
    return {
        "n_requests": n_requests,
        "n_ok": led.n_retired,
        "n_shed": led.n_shed,
        "n_expired": led.n_expired,
        "shed_fraction": (led.n_shed + led.n_expired) / n_requests,
        "goodput_tokens_per_sec": round(good_tokens / wall, 1),
        "good_tokens": good_tokens,
        "steps": steps,
        "balance_ok": led.balance()["ok"],
        "pool_reconciled": (eng.cache.free_pages
                            == eng.kv_config.usable_pages),
        "recompile_ok": eng.recompile_ok,
        "queue_saturation_peak": round(
            eng.telemetry.peaks["queue_saturation"], 4),
    }


def _stamp_serve_overload(result, leg):
    """Flat v10 overload scalars + the dict under `serving_overload`.
    The correctness gates (balance/pool/sentry) must hold for the
    stamps to land — a storm that corrupted accounting has no
    goodput number worth publishing."""
    result["serving_overload"] = leg
    if (leg["balance_ok"] and leg["pool_reconciled"]
            and leg["recompile_ok"]):
        result["serve_shed_fraction"] = float(leg["shed_fraction"])
        result["serve_goodput_tokens_per_sec"] = float(
            leg["goodput_tokens_per_sec"])


def _stamp_serve(result, sweep):
    """Fold the serve sweep into the result JSON: the full dict under
    `serving` (deliberately OUTSIDE the `serve_` prefix — that prefix
    is reserved for JSON scalars by SCHEMA v5, the `comms_` rule) and
    the flat `serve_*` scalars from the LARGEST concurrency (the
    headline serving number).  The recompile verdict is the AND over
    the whole sweep — one churned concurrency poisons the stamp,
    deliberately."""
    result["serving"] = sweep
    top_n = max(sweep, key=int)
    top = sweep[top_n]
    result["serve_streams"] = int(top_n)
    result["serve_decode_tokens_per_sec"] = float(top["tokens_per_sec"])
    result["serve_p50_ms"] = float(top["p50_ms"])
    result["serve_p99_ms"] = float(top["p99_ms"])
    result["serve_recompile_ok"] = all(
        v["recompile_ok"] for v in sweep.values())
    # v7 (ISSUE 10): the largest-N ledger scalars — TTFT percentiles,
    # queue-wait p99, and the run's PEAK pool utilization.  The peak
    # gets its OWN field (`serve_pool_util_peak`): the live logger
    # stamps `serve_pool_util` as an instantaneous gauge, and one
    # field must not carry two semantics (the re-semanticize rule,
    # docs/observability.md).  Optional-never-null: a sweep without
    # ledger data (telemetry off) simply doesn't stamp them.
    led = top.get("ledger") or {}
    for src, dst in (("ttft_p50_ms", "serve_ttft_p50_ms"),
                     ("ttft_p99_ms", "serve_ttft_p99_ms"),
                     ("queue_wait_p99_ms", "serve_queue_wait_p99_ms"),
                     ("pool_util_peak", "serve_pool_util_peak")):
        v = led.get(src)
        if v is not None:
            result[dst] = float(v)


def _ckpt_cycle(on_tpu):
    """One async save → elastic restore cycle of the flagship ZeRO-2
    training state (ISSUE 9): prices the checkpoint cadence for the
    bench JSON.  Uses the same dp-sharded GPT config as the zero2
    bucket sweep (the shard-native path is what the tentpole is for;
    the replicated flagship state saves through the identical
    manager).  Stamps, via _stamp_ckpt: `ckpt_save_s` (writer-thread
    wall clock), `ckpt_blocking_s` (what the hot path paid —
    device→host snapshot; the write itself ran in the background),
    `ckpt_bytes`, restore seconds, and a bitwise round-trip verdict
    (False = the checkpoint that was just priced does not reproduce
    the state, which voids the number)."""
    import shutil
    import tempfile

    from apex_tpu.checkpoint import CheckpointManager
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M
    # after apex_tpu: _compat shims `jax.shard_map` on jax 0.4.x
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    if on_tpu:
        batch, seq = 8, 1024
        cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                        num_layers=8, num_heads=16, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        use_flash_attention=True)
    else:
        batch, seq = 2, 64
        cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0)
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel()
    dp = mesh.devices.size
    # batch must shard over dp (the comms_probe divisibility rule)
    batch = -(-batch // dp) * dp
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(
        num_shards=dp, lr=1e-4, n_buckets=2, use_pallas=on_tpu or None,
        master_dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(
        opt.init, mesh=mesh, in_specs=(P(),), out_specs=sspec,
        check_vma=False))(params)
    step = ddp.make_train_step(
        lambda p, b: model.loss(p, b[0], b[1]), opt, mesh,
        batch_spec=(P("dp"), P("dp")))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0,
                                cfg.vocab_size)
    labels = jnp.roll(tokens, -1, axis=1)
    state, _, loss = step(state, None, (tokens, labels))
    _ = np.asarray(loss)

    tmpd = tempfile.mkdtemp(prefix="apex_ckpt_bench_")
    try:
        mgr = CheckpointManager(tmpd, opt, every_n_steps=1)
        mgr.save(1, state)
        mgr.wait()
        st = mgr.stats()
        t0 = time.perf_counter()
        restored, _, _ = mgr.restore(mesh)
        jax.block_until_ready(restored)
        restore_s = time.perf_counter() - t0
        # EVERY state field: a verdict that only checked the params
        # would stamp ok=True over damaged moment shards
        ok = all(
            bool(np.array_equal(np.asarray(getattr(restored, f)),
                                np.asarray(getattr(state, f))))
            for f in state._fields)
    finally:
        shutil.rmtree(tmpd, ignore_errors=True)
    M.destroy_model_parallel()
    return {"dp": dp, "save_s": st["ckpt_save_s"],
            "blocking_s": st["ckpt_blocking_s"],
            "bytes": st["ckpt_bytes"],
            "restore_s": round(restore_s, 6), "roundtrip_ok": ok}


def _stamp_ckpt(result, cycle):
    """Flat v6 `ckpt_*` scalars (the prefix is JSON-scalar-reserved,
    the `comms_`/`serve_` rule) + the full cycle dict under
    `checkpointing`."""
    result["checkpointing"] = cycle
    result["ckpt_save_s"] = float(cycle["save_s"])
    result["ckpt_blocking_s"] = float(cycle["blocking_s"])
    result["ckpt_bytes"] = int(cycle["bytes"])
    result["ckpt_restore_s"] = float(cycle["restore_s"])
    result["ckpt_roundtrip_ok"] = bool(cycle["roundtrip_ok"])


def _fleet_cycle(on_tpu):
    """Multi-host commit + kill + elastic-resume mini-cycle (ISSUE 11):
    two emulated hosts commit one ZeRO-layout checkpoint through the
    sub-manifest → rank-0 barrier protocol, a half-fleet commit is
    REFUSED, and the `ElasticOrchestrator` drives one lost-rank
    recovery whose re-shard restore must reproduce the committed
    canonical flat bitwise.  Protocol-level (host arrays, no jit) so
    the stamp is cheap on every backend; the full fleet gate with real
    process kills is `scripts/fleet_probe.py`.  Stamps, via
    _stamp_fleet: `fleet_resume_ok`, `fleet_resumes`,
    `ckpt_commit_barrier_s` (schema v8)."""
    import shutil
    import tempfile

    from apex_tpu.checkpoint import ElasticOrchestrator
    from apex_tpu.checkpoint import multihost as MH
    from apex_tpu.checkpoint import sharded as S
    from apex_tpu.checkpoint.chaos import RankLostError

    dp = 4
    n = (1 << 20 if on_tpu else 1 << 12)
    layout = {"align": 64, "total": n, "n_tensors": 1, "num_shards": dp,
              "n_buckets": 1, "bucket_totals": [n], "bucket_padded": [n],
              "master_dtype": "float32"}
    rng = np.random.RandomState(11)
    flat = rng.randn(n).astype(np.float32)
    shards = {r: flat[r * n // dp:(r + 1) * n // dp] for r in range(dp)}
    tmp = tempfile.mkdtemp(prefix="apex_fleet_bench_")
    try:
        # 2-host commit: host 1's half, then host 0 commits
        MH.save_sharded_multihost(
            tmp, 1, {"params_shard": ("sharded",
                                      {2: shards[2], 3: shards[3]})},
            process_id=1, num_processes=2, flat_layout=layout)
        _, barrier_s = MH.save_sharded_multihost(
            tmp, 1, {"params_shard": ("sharded",
                                      {0: shards[0], 1: shards[1]})},
            process_id=0, num_processes=2, flat_layout=layout,
            timeout_s=30.0)
        # half-fleet commit of step 2 must be REFUSED (host 1 "dead")
        refused = False
        try:
            MH.save_sharded_multihost(
                tmp, 2, {"params_shard": ("sharded",
                                          {0: shards[0], 1: shards[1]})},
                process_id=0, num_processes=2, flat_layout=layout,
                timeout_s=0.2, poll_s=0.02)
        except MH.MultihostCommitError:
            refused = True
        refused = refused and S.latest_committed_step(tmp) == 1

        # one lost-rank recovery: session 1 dies, session 2 re-shards
        # the committed step to dp=2 and hands back the canonical flat
        dst = dict(layout, num_shards=2)

        def build(new_dp, resume_step, attempt):
            def session():
                if new_dp == dp:
                    raise RankLostError("rank 3 lost (bench cycle)",
                                        rank=3)
                p = S.step_dir(tmp, resume_step)
                m = S.read_manifest(p)
                host = S.load_field_host(p, m, "params_shard",
                                         check_crc=True)
                re2 = S.reshard(host, m["flat_layout"], dst)
                return S.canonical_flat(list(np.split(re2, 2)), dst)
            return session

        orch = ElasticOrchestrator(tmp, build, initial_dp=dp,
                                   choose_dp=lambda d, e: 2)
        canon = orch.run()
        resume_ok = bool(np.array_equal(canon, flat))
        return {"dp": dp, "n_hosts": 2,
                "barrier_s": round(barrier_s, 6),
                "refused_ok": bool(refused),
                "resumes": orch.stats()["fleet_resumes"],
                "resume_ok": resume_ok}
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _stamp_fleet(result, cycle):
    """Flat v8 `fleet_*` / barrier scalars (prefix JSON-scalar-reserved,
    the `ckpt_` rule) + the full cycle dict under `fleet`."""
    result["fleet"] = cycle
    result["fleet_resume_ok"] = bool(cycle["resume_ok"]
                                     and cycle["refused_ok"])
    result["fleet_resumes"] = int(cycle["resumes"])
    result["ckpt_commit_barrier_s"] = float(cycle["barrier_s"])


def _moe_gpt_bench(on_tpu):
    """Expert-parallel MoE-GPT training throughput (ISSUE 13): the
    flagship `models/moe_gpt.py` step — fp32 top-k router, capacity-
    factor dispatch into the static (E, C, H) buffer, ONE all_to_all
    over the ep axis each way, ZeRO-2 master state over the combined
    (dp, ep) axes — built by the SAME shared builder the lint/comms
    gates trace (`build_moe_train_step`; ep=2 on any even device
    count, CPU smoke shapes off-TPU) and timed under the
    RecompileSentry (a routing-dependent recompile would measure XLA,
    not training — the zero-steady-recompile acceptance criterion).
    Returns the dict `_stamp_moe` folds into the result: tokens/s plus
    the last step's aux scalars (drop fraction, load-balance loss,
    gate entropy)."""
    from apex_tpu.models.moe_gpt import build_moe_train_step
    from apex_tpu.parallel import mesh as M

    model, step, args, info = build_moe_train_step(on_tpu)
    state, _, (tok_sds, _) = args
    tokens = jax.random.randint(jax.random.PRNGKey(1), tok_sds.shape,
                                0, info["vocab_size"])
    labels = jnp.roll(tokens, -1, axis=1)
    iters, warmup = (20, 3) if on_tpu else (3, 1)
    last = {}

    def call(sentry, st):
        st, _, loss, aux = sentry(st, None, (tokens, labels))
        last["aux"] = aux
        return st, loss

    dt = _time_steps(step, state, None, None, iters, warmup,
                     name="moe_gpt", call=call)
    aux_host = {k: float(v)
                for k, v in jax.device_get(last["aux"]).items()}
    M.destroy_model_parallel()
    cfg = info["config"]
    return {
        "tokens_per_sec": round(info["batch"] * info["seq"] / dt, 1),
        "dp": info["dp"], "ep": info["ep"],
        "n_experts": cfg.n_experts, "top_k": cfg.top_k,
        "capacity_factor": cfg.capacity_factor,
        "drop_fraction": round(aux_host["moe_drop_fraction"], 6),
        "aux_loss": round(aux_host["moe_aux_loss"], 6),
        "gate_entropy": round(aux_host["moe_gate_entropy"], 6),
        "z_loss": round(aux_host["moe_z_loss"], 6),
    }


def _stamp_moe(result, d):
    """Flat v9 `moe_*` scalars (the prefix is JSON-scalar-reserved,
    the `comms_`/`serve_` rule) + the full dict under `moe_gpt`."""
    result["moe_gpt"] = d
    result["moe_tokens_per_sec"] = float(d["tokens_per_sec"])
    result["moe_drop_fraction"] = float(d["drop_fraction"])
    result["moe_aux_loss"] = float(d["aux_loss"])
    result["moe_gate_entropy"] = float(d["gate_entropy"])
    result["moe_z_loss"] = float(d["z_loss"])


def _overlap_measure(on_tpu):
    """Chunked-vs-monolithic TP step latency (ISSUE 18): the tp=2
    sequence-parallel GPT step — the SAME model/optimizer build as the
    comms/timeline probes' `gpt_tp_overlap` flagship — timed in BOTH
    collective spellings.  `overlap_chunks=1` keeps the ORIGINAL
    monolithic all-gather / reduce-scatter program (byte-identical HLO
    to the pre-chunking layers); `overlap_chunks=2` decomposes the
    column-parallel gather into a ppermute ring interleaved with
    partial GEMMs and chunks the row-parallel reduce-scatter.  Both
    legs run under the RecompileSentry; the speedup ratio is the
    number the chunking exists to move (>1 only where the backend
    actually runs collectives async — CPU rings add pure per-chunk
    latency, the honest c*alpha floor docs/PERF.md prices)."""
    from apex_tpu.models.gpt import GPT, GPTConfig
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    chunks = 2
    out = {"tp": 2, "chunks": chunks}
    iters, warmup = (20, 3) if on_tpu else (3, 1)
    for spelling, c in (("monolithic", 1), ("chunked", chunks)):
        if on_tpu:
            batch, seq = 12, 1024
            cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                            num_layers=24, num_heads=16, dropout=0.0,
                            dtype=jnp.bfloat16,
                            logits_dtype=jnp.bfloat16, remat=False,
                            use_flash_attention=True,
                            sequence_parallel=True, overlap_chunks=c)
        else:
            batch, seq = 2, 64
            cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                            num_layers=2, num_heads=4, dropout=0.0,
                            sequence_parallel=True, overlap_chunks=c)
        M.destroy_model_parallel()
        mesh = M.initialize_model_parallel(tensor_model_parallel_size=2)
        dp = mesh.devices.size // 2
        batch = -(-batch // max(1, dp)) * max(1, dp)
        model = GPT(cfg)
        params = model.init(jax.random.PRNGKey(0))
        opt = FusedAdam(lr=1e-4, use_pallas=on_tpu,
                        master_dtype=jnp.bfloat16 if on_tpu
                        else jnp.float32)
        state = init_sharded_optimizer(opt, model, params, mesh)
        step = make_tp_dp_train_step(model, opt, mesh, donate=True)
        del params
        tokens = jax.random.randint(jax.random.PRNGKey(1),
                                    (batch, seq), 0, cfg.vocab_size)
        labels = jnp.roll(tokens, -1, axis=1)
        dt = _time_steps(step, state, tokens, labels, iters, warmup,
                         name=f"gpt_tp_overlap_{spelling}")
        out[f"{spelling}_step_ms"] = round(dt * 1e3, 3)
        out[f"{spelling}_tokens_per_sec"] = round(batch * seq / dt, 1)
        M.destroy_model_parallel()
    out["speedup"] = round(
        out["monolithic_step_ms"] / out["chunked_step_ms"], 3)
    return out


def _overlap_chunks_bench(on_tpu):
    """Run `_overlap_measure`, in-process where the backend already
    exposes >= 2 devices (TPU), else in a fresh child with two forced
    host CPU devices — tp=2 needs a 2-device mesh, and XLA_FLAGS must
    be set before the child's jax import (the comms_probe trick; this
    parent imported jax long ago)."""
    if jax.device_count() >= 2:
        return _overlap_measure(on_tpu)
    import os
    import subprocess

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=2"
                        ).strip()
    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--overlap-child"],
        capture_output=True, text=True, timeout=900, check=True,
        env=env)
    # reverse-scan for the JSON line, the _run_isolated rule (plugin
    # log lines on stdout after the JSON are a known hazard)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and "chunked_step_ms" in d:
            return d
    raise ValueError("no JSON line in --overlap-child stdout")


def _stamp_overlap(result, d):
    """Flat `overlap_*` scalars for the chunked-TP leg + the full dict
    under `tp_overlap`.  Bench-result-only keys: `overlap_` is NOT one
    of the logger's reserved record prefixes — these never ride a
    MetricsLogger record, so SCHEMA_VERSION stays at 11."""
    result["tp_overlap"] = d
    result["overlap_chunks"] = int(d["chunks"])
    result["overlap_monolithic_step_ms"] = float(d["monolithic_step_ms"])
    result["overlap_chunked_step_ms"] = float(d["chunked_step_ms"])
    result["overlap_step_speedup"] = float(d["speedup"])


def _adam_1b_step_ms(on_tpu):
    """Fused flat-buffer Adam step at 1B params (fp32 p/m/v, bf16
    grads) — the large-param optimizer north star (BASELINE.md;
    ≡ tests/L0/run_optimizers scale point).  Round-3: 44.4 ms ≈ 721
    GB/s effective (docs/PERF.md)."""
    from apex_tpu.ops import optimizer_kernels as K

    n = 10 ** 9 if on_tpu else 10 ** 6
    n = -(-n // K.FLAT_TILE) * K.FLAT_TILE
    p = jnp.zeros((n,), jnp.float32)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    g = jnp.full((n,), 1e-3, jnp.bfloat16)

    def _step(p, m, v, g):
        return K.adam_flat(p, m, v, g, lr=1e-3, step=10,
                           weight_decay=0.01,
                           use_pallas_override=on_tpu or None)

    step = jax.jit(_step, donate_argnums=(0, 1, 2))
    iters, warmup = (20, 3) if on_tpu else (3, 1)
    for _ in range(warmup):
        p, m, v = step(p, m, v, g)
    np.asarray(p[:1])
    t0 = time.perf_counter()
    for _ in range(iters):
        p, m, v = step(p, m, v, g)
    np.asarray(p[:1])
    return (time.perf_counter() - t0) / iters * 1e3


def _run_isolated(metric):
    """Re-run one metric in a fresh subprocess (`bench.py --only X`) and
    return its value.  The ResNet number measures 2,305-2,319 img/s in a
    clean process but 2,206-2,294 after the GPT/BERT metrics have
    fragmented HBM in this one (docs/PERF.md round-5 note) — process
    isolation recovers the clean-machine number the reference's
    standalone main_amp.py harness would print.  Requires a runtime that
    admits a second TPU client while the parent's is alive (the tunnel
    backend here does; measured concurrent-process runs both produced
    real-chip numbers) — on process-exclusive runtimes the child exits
    nonzero and the caller falls back to the in-process measurement,
    marked `resnet50_isolated: false` in the JSON."""
    import os
    import subprocess

    out = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--only", metric],
        capture_output=True, text=True, timeout=900, check=True)
    # scan in REVERSE for the first line that parses to a dict holding
    # the metric: a plugin/absl log line printed to stdout AFTER the
    # JSON previously made splitlines()[-1] raise, silently defeating
    # isolation (ADVICE r5)
    for line in reversed(out.stdout.strip().splitlines()):
        try:
            d = json.loads(line)
        except ValueError:
            continue
        if isinstance(d, dict) and metric in d:
            return d[metric]
    raise ValueError(
        f"no JSON line containing {metric!r} in --only child stdout")


def _timeline_anatomy(on_tpu, batch, seq, cfg, master_dtype):
    """Measured runtime anatomy of the flagship program (ISSUE 15):
    the SAME tp_dp step `_compile_audit_350m` audits, executed for two
    warmup + three captured steady steps under a `ProfileCapture`, the
    trace parsed by `monitor.timeline`.  Returns the v11 `timeline_*`
    stamps + the full report dict.  Runs in its OWN `_timed` key, the
    compile_audit rule: trace capture adds profiler overhead to every
    step it wraps, and parsing walks the whole event list — neither
    may land inside a timed metric window the bench keeps comparable
    across rounds."""
    import tempfile

    from apex_tpu import monitor
    from apex_tpu.models.gpt import GPT
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=on_tpu, master_dtype=master_dtype)
    state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params
    tok = jnp.zeros((batch, seq), jnp.int32)
    cap = monitor.profile_capture(
        range(3), logdir=tempfile.mkdtemp(prefix="bench_timeline_"))
    try:
        # two warmups absorb the compile + the donated-layout second
        # compile so the captured window holds STEADY steps only
        for _ in range(2):
            state, loss = step(state, tok, tok)
        jax.block_until_ready(state)
        for i in range(3):
            with cap.step(i):
                state, loss = step(state, tok, tok)
                jax.block_until_ready(loss)
    finally:
        # a raise mid-capture must still stop the jax profiler: a
        # leaked open trace poisons _retry's next attempt
        # ("already started") and silently profiles every later leg
        cap.close()
        M.destroy_model_parallel()
    rep = monitor.analyze_trace(cap.trace_path())
    if rep.n_device_events == 0 or len(rep.steps) != 3:
        raise RuntimeError(
            f"timeline capture malformed: {rep.n_device_events} device "
            f"event(s), {len(rep.steps)} step(s) of 3")
    return {"record": rep.timeline_record(), "report": rep.to_dict()}


def _stamp_timeline(result, d):
    """Flat v11 timeline_* scalars (busy fraction, host gap,
    collective fraction, and — only where the schedule is measurable —
    the measured-overlap verdict) + the full per-step report under the
    unreserved `timeline` key."""
    result.update(d["record"])
    result["timeline"] = d["report"]


def _compile_audit_350m(on_tpu, batch, seq, cfg, master_dtype):
    """AOT compile & HBM audit of the flagship step (ISSUE 5): the
    memory/cost anatomy + the donation check + the flops cross-check
    that validates the MFU numbers derived from the flagship metric.
    master_dtype MUST be what main() passed `_fused_tokens_per_sec` —
    the audit only has value if it compiles the SAME program the
    flagship metric timed.  Runs in its OWN timed block —
    `analyze_step`'s lower().compile() does not seed the jit cache, so
    folding it into the flagship window would add a full duplicate XLA
    compile to a duration trajectory the bench keeps comparable across
    rounds."""
    from apex_tpu import monitor
    from apex_tpu.models.gpt import GPT
    from apex_tpu.optimizers.fused_adam import FusedAdam
    from apex_tpu.parallel import mesh as M
    from apex_tpu.transformer.training import (
        init_sharded_optimizer,
        make_tp_dp_train_step,
    )

    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(devices=jax.devices()[:1])
    model = GPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = FusedAdam(lr=1e-4, use_pallas=on_tpu, master_dtype=master_dtype)
    opt_state = init_sharded_optimizer(opt, model, params, mesh)
    step = make_tp_dp_train_step(model, opt, mesh, donate=True)
    del params
    tok = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    # lint=True: the static program passes (apex_tpu.lint, ISSUE 6)
    # run over the same traced step and attach to the report — the
    # JSON's `lint_ok` gate reads them (a flagged flagship program is
    # a correctness bug, not a perf number).  comms=True: the
    # collective inventory + overlap + ICI roofline (monitor.comms,
    # ISSUE 7) over the same compiled executable — the JSON's comms_*
    # stamps read them
    rep = monitor.analyze_step(
        step, (opt_state, tok, tok),
        analytic_flops=monitor.gpt_step_flops(cfg, batch), lint=True,
        comms=True)
    M.destroy_model_parallel()
    return rep.to_dict()


_ONLY = {
    "resnet50_img_per_sec": lambda on_tpu: round(
        _retry(_resnet50_img_per_sec, on_tpu), 1),
}


def _kernel_smoke():
    """Run the compiled-kernel smoke gates (examples/tpu_kernel_smoke.py)
    in a subprocess and return (ok, fail_lines).  Once per bench run, so
    a compiled-Mosaic regression is caught by the driver's JSON rather
    than by hand (VERDICT r5 next-round #7).  On a CPU backend the
    script skips (exit 0) — `kernel_smoke_ok` then just asserts the
    harness itself imports and dispatches."""
    import os
    import subprocess

    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "examples", "tpu_kernel_smoke.py")
    out = subprocess.run([sys.executable, script], capture_output=True,
                         text=True, timeout=900)
    # "FAIL " (with space) keeps the script's final "FAILURES: [...]"
    # summary line from duplicating the per-kernel lines
    fails = [l for l in out.stdout.splitlines() if l.startswith("FAIL ")]
    return out.returncode == 0, fails[:8]


@contextlib.contextmanager
def _timed(durations, name):
    """Record a metric block's wall-clock seconds (errors included —
    a 15-minute OOM-retry spiral should be visible in the trajectory)
    into the JSON's `metric_durations_s` (ISSUE 2 satellite)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        durations[name] = round(time.perf_counter() - t0, 2)


def main():
    from apex_tpu.models.gpt import GPTConfig
    # import up front (fail FAST, not after 30 min of TPU metrics): the
    # version stamps the result JSON at the end of this function
    from apex_tpu.monitor import SCHEMA_VERSION

    on_tpu = jax.default_backend() not in ("cpu",)
    if "--overlap-child" in sys.argv[1:]:
        # child of _overlap_chunks_bench: the parent exported XLA_FLAGS
        # forcing 2 host devices before this process's jax import
        print(json.dumps(_overlap_measure(on_tpu)))
        return
    if "--only" in sys.argv[1:]:
        if len(sys.argv) != 3 or sys.argv[1] != "--only":
            print("usage: bench.py [--only METRIC]", file=sys.stderr)
            sys.exit(2)
        metric = sys.argv[2]
        if metric not in _ONLY:
            print(f"unknown metric {metric}; choices: {sorted(_ONLY)}",
                  file=sys.stderr)
            sys.exit(2)
        if not on_tpu:
            # a --only child exists to give a TPU metric a fresh
            # process; landing on CPU here means backend acquisition
            # fell back — hard-fail so the parent's fallback runs
            # rather than recording a CPU number as the TPU metric
            print(f"--only {metric}: backend is "
                  f"{jax.default_backend()}, not TPU", file=sys.stderr)
            sys.exit(3)
        print(json.dumps({metric: _ONLY[metric](on_tpu)}))
        return
    if on_tpu:
        # batch 12 + bf16 Adam state (round 4): the optimizer+cast tail
        # drops from 17 ms to ~5 ms and batch 12 amortizes fixed costs
        # (b8 fp32: 46.1k, b8 bf16-state: 48.0k, b12 bf16-state: 48.7k
        # tok/s); remat=False + donate=True as before
        batch, seq = 12, 1024
        cfg = GPTConfig(vocab_size=50304, seq_len=seq, hidden=1024,
                        num_layers=24, num_heads=16, dropout=0.0,
                        dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                        remat=False, use_flash_attention=True)
    else:  # CPU smoke mode
        batch, seq = 2, 64
        cfg = GPTConfig(vocab_size=512, seq_len=seq, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0)

    durations = {}
    # ONE master-dtype decision, shared by the flagship metric and its
    # compile audit — the audit must compile the same program it audits
    master_dtype = jnp.bfloat16 if on_tpu else jnp.float32
    with _timed(durations, "gpt350m_train_tokens_per_sec_per_chip"):
        fused = _retry(_fused_tokens_per_sec, on_tpu, batch, seq, cfg,
                       master_dtype)
    result = {
        "metric": "gpt350m_train_tokens_per_sec_per_chip",
        "value": round(fused, 1),
        "unit": "tokens/s",
        "master_dtype": "bfloat16" if on_tpu else "float32",
        "vs_baseline": None,  # measured below; null = baseline didn't run
    }
    try:
        with _timed(durations, "baseline_tokens_per_sec"):
            baseline, bl_batch = _retry(_baseline_best, on_tpu, batch,
                                        seq, cfg)
        result["baseline_tokens_per_sec"] = round(baseline, 1)
        result["baseline_batch"] = bl_batch
        result["vs_baseline"] = round(fused / baseline, 2)
    except Exception as e:  # keep the primary metric even if the
        result["baseline_error"] = repr(e)[:120]  # baseline OOMs/fails
    try:
        with _timed(durations, "mha_fwd_bwd_ms"):
            mha_fused, mha_unfused = _retry(_mha_latencies, on_tpu)
        result["mha_fused_fwd_bwd_ms"] = round(mha_fused, 2)
        result["mha_unfused_fwd_bwd_ms"] = round(mha_unfused, 2)
    except Exception as e:
        result["mha_error"] = repr(e)[:120]
    try:
        with _timed(durations, "gpt1p3b_tokens_per_sec_per_chip"):
            result["gpt1p3b_tokens_per_sec_per_chip"] = round(
                _retry(_gpt1p3b_tokens_per_sec, on_tpu), 1)
    except Exception as e:
        result["gpt1p3b_error"] = repr(e)[:120]
    try:
        with _timed(durations, "bert_seq_per_sec"):
            result["bert_seq_per_sec"] = round(
                _retry(_bert_seq_per_sec, on_tpu), 1)
    except Exception as e:
        result["bert_error"] = repr(e)[:120]
    try:
        with _timed(durations, "resnet50_img_per_sec"):
            if on_tpu:
                try:
                    result["resnet50_img_per_sec"] = _run_isolated(
                        "resnet50_img_per_sec")
                    result["resnet50_isolated"] = True
                except Exception:
                    result["resnet50_img_per_sec"] = _ONLY[
                        "resnet50_img_per_sec"](on_tpu)
                    result["resnet50_isolated"] = False
            else:
                result["resnet50_img_per_sec"] = _ONLY[
                    "resnet50_img_per_sec"](on_tpu)
    except Exception as e:
        result["resnet50_error"] = repr(e)[:120]
    try:
        with _timed(durations, "adam_1b_step_ms"):
            result["adam_1b_step_ms"] = round(
                _retry(_adam_1b_step_ms, on_tpu), 2)
    except Exception as e:
        result["adam_1b_error"] = repr(e)[:120]
    try:
        with _timed(durations, "zero2_n_buckets"):
            result["zero2_n_buckets"] = _retry(_zero2_bucket_sweep,
                                               on_tpu)
    except Exception as e:
        result["zero2_n_buckets_error"] = repr(e)[:120]
    # expert-parallel MoE training (ISSUE 13): dp x ep MoE-GPT
    # tokens/s under the RecompileSentry, plus the routing-health aux
    # scalars (_stamp_moe: flat moe_* v9 scalars + the dict under
    # `moe_gpt`)
    try:
        with _timed(durations, "moe_gpt"):
            moe_d = _retry(_moe_gpt_bench, on_tpu)
        _stamp_moe(result, moe_d)
    except Exception as e:
        result["moe_error"] = repr(e)[:120]
    # chunked-collective overlap (ISSUE 18): the tp=2 SP flagship step
    # timed in BOTH spellings — monolithic collectives
    # (overlap_chunks=1, byte-identical to the pre-chunking program)
    # vs the ppermute-ring chunked pipeline (overlap_chunks=2, the
    # comms/timeline probes' gpt_tp_overlap target).  (_stamp_overlap:
    # flat overlap_* scalars + the dict under `tp_overlap`)
    try:
        with _timed(durations, "tp_overlap"):
            ov = _retry(_overlap_chunks_bench, on_tpu)
        _stamp_overlap(result, ov)
    except Exception as e:
        result["overlap_error"] = repr(e)[:120]
    # serving axes (ISSUE 8): decode tokens/s + p50/p99 per-token
    # latency at N concurrent streams, and the sentry's churn verdict
    # (_stamp_serve: flat serve_* scalars + the full sweep dict)
    try:
        with _timed(durations, "serve_decode"):
            sweep = _retry(_serve_decode_bench, on_tpu)
        _stamp_serve(result, sweep)
    except Exception as e:
        result["serve_error"] = repr(e)[:120]
    # serving overload leg (ISSUE 14): the 4x storm against a bounded
    # queue — shed fraction + goodput under overload control
    # (_stamp_serve_overload: flat v10 scalars + `serving_overload`)
    try:
        with _timed(durations, "serve_overload"):
            overload = _retry(_serve_overload_bench, on_tpu)
        _stamp_serve_overload(result, overload)
    except Exception as e:
        result["serve_overload_error"] = repr(e)[:120]
    # checkpoint-cadence pricing (ISSUE 9): one async save → elastic
    # restore cycle of the ZeRO-2 flagship state, stamped as flat
    # ckpt_* v6 scalars (+ the dict under `checkpointing`)
    try:
        with _timed(durations, "ckpt_cycle"):
            cycle = _retry(_ckpt_cycle, on_tpu)
        _stamp_ckpt(result, cycle)
    except Exception as e:
        result["ckpt_error"] = repr(e)[:120]
    # fleet fault tolerance (ISSUE 11): multi-host commit barrier +
    # refusal + one orchestrated lost-rank resume, stamped as flat
    # fleet_* v8 scalars (+ the dict under `fleet`)
    try:
        with _timed(durations, "fleet_cycle"):
            fcycle = _retry(_fleet_cycle, on_tpu)
        _stamp_fleet(result, fcycle)
    except Exception as e:
        result["fleet_error"] = repr(e)[:120]
    try:
        with _timed(durations, "long_context_32k"):
            lc_ms, lc_tps = _retry(_long_context_32k, on_tpu)
        result["long_context_32k_fwd_bwd_ms"] = round(lc_ms, 1)
        result["long_context_32k_tokens_per_sec"] = round(lc_tps, 1)
    except Exception as e:
        result["long_context_error"] = repr(e)[:120]
    # runtime timeline (ISSUE 15): 3 measured steady steps of the
    # flagship program under a ProfileCapture, parsed into the flat
    # v11 timeline_* scalars (+ the per-step report dict).  Own
    # _timed key — same rule as compile_audit: capture overhead never
    # lands in a timed metric window
    try:
        with _timed(durations, "timeline"):
            tl = _retry(_timeline_anatomy, on_tpu, batch, seq, cfg,
                        master_dtype)
        _stamp_timeline(result, tl)
    except Exception as e:
        result["timeline_error"] = repr(e)[:120]
    try:
        with _timed(durations, "kernel_smoke"):
            ok, fails = _kernel_smoke()
        result["kernel_smoke_ok"] = ok
        if fails:
            result["kernel_smoke_failures"] = fails
    except Exception as e:
        result["kernel_smoke_ok"] = False
        result["kernel_smoke_error"] = repr(e)[:120]
    # schema stamp + per-metric wall clock (ISSUE 2): keeps BENCH_*.json
    # trajectories comparable as metrics are added across rounds
    result["monitor_schema_version"] = SCHEMA_VERSION
    result["metric_durations_s"] = durations
    # compile & HBM observatory (ISSUE 5): the flagship step's AOT
    # memory/cost anatomy (argument/temp/alias bytes, donation check,
    # flops cross-check vs monitor.flops), per-config recompile-sentry
    # summaries, and the device-memory high-water mark after the run
    try:
        with _timed(durations, "compile_audit"):
            result["compile_audit"] = _retry(
                _compile_audit_350m, on_tpu, batch, seq, cfg,
                master_dtype)
    except Exception as e:
        result["compile_audit_error"] = repr(e)[:120]
    # static-lint gate (ISSUE 6): the flagship program's dtype-policy /
    # collective / donation passes, run on the exact audited step;
    # lint_ok=false means a run published numbers from a program the
    # linter would have rejected.  ok=None means the lint pass itself
    # crashed (advisory) — stamp the error, not a fake verdict.  Own
    # try so a stamp-side surprise never masquerades as an audit
    # failure (the audit dict is already in the result by now)
    try:
        lint = (result.get("compile_audit") or {}).get("lint") or {}
        if lint.get("ok") is None and lint.get("error"):
            result["lint_error"] = lint["error"][:120]
        elif lint:
            result["lint_ok"] = bool(lint.get("ok"))
        if lint.get("findings"):
            result["lint_findings"] = [
                f"{f.get('rule')} {f.get('location')}"
                for f in lint["findings"][:8]]
    except Exception as e:
        result["lint_error"] = repr(e)[:120]
    # comms observatory stamps (ISSUE 7): flat comms_* scalars from the
    # flagship audit's attached CommsReport — collective count/bytes,
    # the roofline's predicted comm seconds + fraction of step, and
    # the overlap verdict (null where unmeasurable: CPU emits no async
    # collectives; the prefix-scalar rule of SCHEMA v4 covers these).
    # Own try, like lint: a stamp-side surprise never voids the audit
    try:
        cm = (result.get("compile_audit") or {}).get("comms") or {}
        if cm.get("collectives") is None and cm.get("error"):
            result["comms_error"] = cm["error"][:120]
        elif cm:
            result["comms_n_collectives"] = int(
                sum((cm.get("counts") or {}).values()))
            result["comms_bytes"] = int(cm.get("total_comm_bytes") or 0)
            result["comms_predicted_comm_s"] = cm.get("predicted_comm_s")
            result["comms_comm_fraction"] = cm.get("comm_fraction")
            result["comms_overlap_ok"] = (
                bool(cm.get("overlap_ok"))
                if cm.get("async_supported") else None)
            ser = [c for c in cm.get("collectives", [])
                   if c.get("serialized")]
            if ser:
                # a single string scalar, not a list: the `comms_`
                # prefix is reserved for JSON scalars by SCHEMA v4
                result["comms_serialized"] = "; ".join(
                    f"{c.get('kind')} {c.get('name')} "
                    f"{c.get('operand_bytes')}B" for c in ser[:8])
    except Exception as e:
        result["comms_error"] = repr(e)[:120]
    if _SENTRY:
        result["n_compiles"] = {k: v["n_compiles"]
                                for k, v in _SENTRY.items()}
        result["recompile_sentry"] = _SENTRY
    try:
        from apex_tpu.monitor.compile import hbm_watermarks
        result["hbm"] = hbm_watermarks()
    except Exception as e:
        result["hbm_error"] = repr(e)[:120]
    # tuner cache state (ISSUE 3): which tuned configs were active and
    # how often the kernels hit them — runs with different fingerprints
    # are not comparing the same kernels
    try:
        from apex_tpu import tune
        result["tuner"] = tune.stats()
    except Exception as e:
        result["tuner_error"] = repr(e)[:120]
    print(json.dumps(result))


if __name__ == "__main__":
    sys.exit(main())
