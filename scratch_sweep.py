"""Scratch: flash-attention block sweep on the real chip (delete after)."""
import time, functools
import jax, jax.numpy as jnp
import numpy as np
from jax import lax


def timeit_injit(make_fn, reps=20, iters=5, meas=5):
    """make_fn() -> (step, x0): step chained inside lax.scan; min of `meas`."""
    step, x0 = make_fn()

    @jax.jit
    def run(x):
        def body(c, _):
            return step(c), None
        c, _ = lax.scan(body, x, None, length=reps)
        return jax.tree.map(lambda t: t.ravel()[0].astype(jnp.float32), c)

    out = run(x0)
    np.asarray(jax.tree.leaves(out)[0])
    best = float("inf")
    for _ in range(meas):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run(x0)
        np.asarray(jax.tree.leaves(out)[0])
        best = min(best, (time.perf_counter() - t0) / (iters * reps))
    return best


def main():
    from apex_tpu.ops.flash_attention import flash_attention
    B, H, S, D = 8, 16, 1024, 64
    k1, k2, k3 = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(k1, (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(k2, (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(k3, (B, H, S, D), jnp.bfloat16)
    flops_c = 4 * B * H * S * S * D / 2  # causal

    import sys
    configs = [(512, 512), (512, 1024), (1024, 1024), (256, 1024),
               (128, 1024), (1024, 512)]
    lo, hi = int(sys.argv[1]), int(sys.argv[2])
    for bq, bk in configs[lo:hi]:
        fn = functools.partial(flash_attention, causal=True,
                               block_q=bq, block_k=bk)

        def mk_fwd():
            return (lambda x: fn(x, k, v)), q
        dt = timeit_injit(mk_fwd)
        tf = flops_c / dt / 1e12

        def mk_fb():
            g = jax.grad(lambda qq, kk, vv: fn(qq, kk, vv).astype(
                jnp.float32).mean(), argnums=(0, 1, 2))

            def step(c):
                dq, dk, dv = g(*c)
                return (dq.astype(jnp.bfloat16), dk.astype(jnp.bfloat16),
                        dv.astype(jnp.bfloat16))
            return step, (q, k, v)
        dtb = timeit_injit(mk_fb)
        print(f"bq={bq:4d} bk={bk:4d}: fwd {dt*1e3:6.3f} ms ({tf:5.1f} TF/s causal-adj)  f+b {dtb*1e3:6.3f} ms", flush=True)


if __name__ == "__main__":
    main()
