// host_runtime — native host-side runtime helpers for apex_tpu.
//
// ≡ the reference's host-side native layer: apex_C flatten/unflatten
// (csrc/flatten_unflatten.cpp:16-17), the multi_tensor_apply chunk
// metadata computation (csrc/multi_tensor_apply.cuh:19-60 host loop),
// and the C++ side of its data pipeline.  On TPU the device-side work
// belongs to XLA/Pallas; what stays native is the host bookkeeping that
// runs every step/epoch on the critical path:
//
//   * flat_layout       — aligned offset table for pytree->flat-buffer
//                         packing (FlatSpec), with lane-aligned padding
//   * chunk_plan        — multi_tensor_apply-style chunking of a flat
//                         buffer into (tensor, chunk) work items
//   * shuffle_indices   — deterministic Fisher-Yates epoch shuffle
//                         (Megatron random sampler hot path)
//   * gather_rows_f32   — multi-threaded batch gather: dataset rows ->
//                         contiguous batch buffer (host data loader)
//
// Build: see build_host_runtime.sh (plain g++, no torch/pybind; the
// Python side binds with ctypes — fallback paths exist when the .so is
// absent).

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Aligned flat-buffer layout.  sizes[n] in elements; align in elements
// (e.g. 128 for TPU lanes).  Writes offsets[n] and returns the padded
// total element count.
int64_t flat_layout(const int64_t* sizes, int64_t n, int64_t align,
                    int64_t* offsets) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    offsets[i] = off;
    int64_t sz = sizes[i];
    if (align > 1) sz = ((sz + align - 1) / align) * align;
    off += sz;
  }
  return off;
}

// multi_tensor_apply chunking: splits each tensor into chunk_size
// pieces.  Writes (tensor_idx, chunk_offset_in_tensor, chunk_len)
// triples into out[3 * max_items]; returns the number of items or -1
// if max_items is too small.  ≡ csrc/multi_tensor_apply.cuh:41-60.
int64_t chunk_plan(const int64_t* sizes, int64_t n, int64_t chunk_size,
                   int64_t* out, int64_t max_items) {
  int64_t item = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t remaining = sizes[i];
    int64_t off = 0;
    while (remaining > 0) {
      if (item >= max_items) return -1;
      int64_t len = remaining < chunk_size ? remaining : chunk_size;
      out[3 * item + 0] = i;
      out[3 * item + 1] = off;
      out[3 * item + 2] = len;
      off += len;
      remaining -= len;
      ++item;
    }
  }
  return item;
}

// xorshift128+ deterministic PRNG (stable across platforms/versions,
// unlike np.random.RandomState which the reference's sampler pins to
// torch.randperm semantics anyway).
static inline uint64_t xorshift128p(uint64_t* s) {
  uint64_t x = s[0];
  uint64_t const y = s[1];
  s[0] = y;
  x ^= x << 23;
  s[1] = x ^ y ^ (x >> 17) ^ (y >> 26);
  return s[1] + y;
}

// Fisher-Yates shuffle of [0, n) with the given seed.
void shuffle_indices(int64_t n, uint64_t seed, int64_t* out) {
  for (int64_t i = 0; i < n; ++i) out[i] = i;
  uint64_t s[2] = {seed ^ 0x9E3779B97F4A7C15ULL,
                   (seed << 1) | 0x243F6A8885A308D3ULL};
  // warm up
  for (int k = 0; k < 8; ++k) (void)xorshift128p(s);
  for (int64_t i = n - 1; i > 0; --i) {
    int64_t j = (int64_t)(xorshift128p(s) % (uint64_t)(i + 1));
    std::swap(out[i], out[j]);
  }
}

// Multi-threaded gather: batch[b, :] = dataset[indices[b], :].
// dataset: (num_rows, row_len) f32 row-major.
void gather_rows_f32(const float* dataset, int64_t row_len,
                     const int64_t* indices, int64_t batch,
                     float* out, int64_t num_threads) {
  if (num_threads < 1) num_threads = 1;
  std::vector<std::thread> threads;
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int64_t b = next.fetch_add(1);
      if (b >= batch) break;
      std::memcpy(out + b * row_len, dataset + indices[b] * row_len,
                  sizeof(float) * (size_t)row_len);
    }
  };
  for (int64_t t = 0; t < num_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
}

// int32 variant for token datasets.
void gather_rows_i32(const int32_t* dataset, int64_t row_len,
                     const int64_t* indices, int64_t batch,
                     int32_t* out, int64_t num_threads) {
  if (num_threads < 1) num_threads = 1;
  std::vector<std::thread> threads;
  std::atomic<int64_t> next(0);
  auto worker = [&]() {
    for (;;) {
      int64_t b = next.fetch_add(1);
      if (b >= batch) break;
      std::memcpy(out + b * row_len, dataset + indices[b] * row_len,
                  sizeof(int32_t) * (size_t)row_len);
    }
  };
  for (int64_t t = 0; t < num_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();
}

}  // extern "C"
