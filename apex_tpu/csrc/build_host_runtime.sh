#!/bin/sh
# Build the native host-runtime shared library (no torch, no pybind —
# plain g++ + ctypes binding).  ≡ the reference's setup.py --cpp_ext
# path (setup.py:115-365) minus CUDA.
set -e
cd "$(dirname "$0")"
g++ -O3 -shared -fPIC -std=c++17 -pthread host_runtime.cpp \
    -o libapex_tpu_host.so
echo "built $(pwd)/libapex_tpu_host.so"
