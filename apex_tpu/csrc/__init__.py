"""ctypes binding for the native host runtime.

≡ the reference's pybind11 extension loading (`import apex_C` etc.) —
here a plain ctypes binding with automatic build-on-first-use and pure
Python fallbacks, so the package works with or without a toolchain.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libapex_tpu_host.so")
_LIB = None
_TRIED = False


def _load() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    if _LIB is not None or _TRIED:
        return _LIB
    _TRIED = True
    if not os.path.exists(_SO):
        try:
            subprocess.run(["sh", os.path.join(_DIR, "build_host_runtime.sh")],
                           check=True, capture_output=True, timeout=120)
        except Exception:
            return None
    try:
        lib = ctypes.CDLL(_SO)
    except OSError:
        return None
    i64p = ctypes.POINTER(ctypes.c_int64)
    lib.flat_layout.restype = ctypes.c_int64
    lib.flat_layout.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p]
    lib.chunk_plan.restype = ctypes.c_int64
    lib.chunk_plan.argtypes = [i64p, ctypes.c_int64, ctypes.c_int64, i64p,
                               ctypes.c_int64]
    lib.shuffle_indices.restype = None
    lib.shuffle_indices.argtypes = [ctypes.c_int64, ctypes.c_uint64, i64p]
    lib.gather_rows_f32.restype = None
    lib.gather_rows_f32.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, i64p,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
    lib.gather_rows_i32.restype = None
    lib.gather_rows_i32.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, i64p,
        ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
    _LIB = lib
    return _LIB


def available() -> bool:
    return _load() is not None


def flat_layout(sizes, align: int = 1):
    """(offsets, padded_total) — aligned flat-buffer layout.
    ≡ apex_C.flatten's layout math."""
    sizes = np.ascontiguousarray(sizes, np.int64)
    lib = _load()
    if lib is None:  # pure fallback
        offsets = []
        off = 0
        for s in sizes:
            offsets.append(off)
            ps = -(-int(s) // align) * align if align > 1 else int(s)
            off += ps
        return np.asarray(offsets, np.int64), off
    out = np.empty(len(sizes), np.int64)
    total = lib.flat_layout(
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(sizes),
        align, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return out, int(total)


def chunk_plan(sizes, chunk_size: int):
    """(tensor_idx, offset, len) work items ≡ multi_tensor_apply chunk
    metadata (csrc/multi_tensor_apply.cuh:19-60)."""
    sizes = np.ascontiguousarray(sizes, np.int64)
    max_items = int(sum(-(-int(s) // chunk_size) for s in sizes)) + 1
    lib = _load()
    if lib is None:
        items = []
        for i, s in enumerate(sizes):
            off = 0
            s = int(s)
            while s > 0:
                l = min(chunk_size, s)
                items.append((i, off, l))
                off += l
                s -= l
        return np.asarray(items, np.int64).reshape(-1, 3)
    out = np.empty((max_items, 3), np.int64)
    n = lib.chunk_plan(
        sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(sizes),
        chunk_size, out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        max_items)
    assert n >= 0
    return out[:n]


def shuffle_indices(n: int, seed: int):
    """Deterministic Fisher-Yates permutation of [0, n)."""
    lib = _load()
    if lib is None:
        rng = np.random.RandomState(seed & 0x7FFFFFFF)
        return rng.permutation(n).astype(np.int64)
    out = np.empty(n, np.int64)
    lib.shuffle_indices(n, seed,
                        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    return out


def gather_rows(dataset: np.ndarray, indices, num_threads: int = 4):
    """batch[b] = dataset[indices[b]] — threaded host gather (the data
    loader hot path)."""
    indices = np.ascontiguousarray(indices, np.int64)
    dataset = np.ascontiguousarray(dataset)
    lib = _load()
    if lib is None or dataset.dtype not in (np.float32, np.int32):
        return dataset[indices]
    out = np.empty((len(indices),) + dataset.shape[1:], dataset.dtype)
    row_len = int(np.prod(dataset.shape[1:]))
    if dataset.dtype == np.float32:
        lib.gather_rows_f32(
            dataset.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), row_len,
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(indices),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), num_threads)
    else:
        lib.gather_rows_i32(
            dataset.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), row_len,
            indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(indices),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), num_threads)
    return out
