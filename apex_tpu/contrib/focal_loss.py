"""Fused focal loss for detection.

≡ apex.contrib.focal_loss (apex/contrib/focal_loss/focal_loss.py:42,
kernel apex/contrib/csrc/focal_loss/focal_loss_cuda.cu): sigmoid focal
loss over anchor classification logits with label smoothing.  On TPU
the whole expression is one XLA fusion (elementwise + reduce) — a
custom kernel adds nothing over the compiler here; numerics match the
reference formula.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def focal_loss(cls_output, cls_targets_at_level, num_positives_sum,
               num_real_classes, alpha=0.25, gamma=2.0,
               label_smoothing=0.0):
    """≡ focal_loss_cuda.focal_loss_forward.

    cls_output: (..., num_classes_padded) raw logits.
    cls_targets_at_level: (...) int; -2 = ignore, -1 = background,
    >=0 = class id (reference semantics).
    Returns scalar loss normalized by num_positives_sum.
    """
    x = cls_output[..., :num_real_classes].astype(jnp.float32)
    t = cls_targets_at_level
    onehot = jax.nn.one_hot(jnp.maximum(t, 0), num_real_classes)
    y = jnp.where((t >= 0)[..., None], onehot, 0.0)  # background → zeros
    if label_smoothing > 0:
        y = y * (1.0 - label_smoothing) + 0.5 * label_smoothing
    p = jax.nn.sigmoid(x)
    ce = jnp.maximum(x, 0) - x * y + jnp.log1p(jnp.exp(-jnp.abs(x)))
    p_t = p * y + (1.0 - p) * (1.0 - y)
    mod = jnp.power(1.0 - p_t, gamma)
    alpha_t = alpha * y + (1.0 - alpha) * (1.0 - y)
    loss = alpha_t * mod * ce
    valid = (t != -2)[..., None]  # ignore entries contribute nothing
    loss = jnp.where(valid, loss, 0.0)
    return jnp.sum(loss) / jnp.maximum(num_positives_sum, 1.0)
