"""Peer-memory halo exchange facade.

≡ apex.contrib.peer_memory (apex/contrib/peer_memory/peer_memory.py:5
PeerMemoryPool over raw cudaMalloc'd IPC buffers;
peer_halo_exchanger_1d.py:5 PeerHaloExchanger1d;
csrc/peer_memory/peer_memory_cuda.cu:741): NVLink peer-to-peer halo
transport.  On TPU there is no user-managed peer memory — ICI transfers
are `lax.ppermute` and XLA owns buffers — so the pool is a documented
no-op and the halo exchanger maps to collectives.halo_exchange_1d.
"""

from __future__ import annotations

from apex_tpu.parallel.collectives import halo_exchange_1d, ring_exchange


class PeerMemoryPool:
    """≡ PeerMemoryPool (peer_memory.py:5-60): allocation pooling is
    XLA's job on TPU; kept for API parity (all methods are no-ops that
    return None or raise on CUDA-specific raw-pointer paths)."""

    def __init__(self, static_size: int = 0, dynamic_size: int = 0,
                 peer_ranks=None):
        self.peer_ranks = peer_ranks

    def allocate_peer_tensors(self, shape, dtype, channels_last, dynamic):
        raise NotImplementedError(
            "raw peer-memory tensors are a CUDA/NVLink concept; on TPU "
            "use lax.ppermute (see PeerHaloExchanger1d)")

    def reset(self):
        pass


class PeerHaloExchanger1d:
    """≡ PeerHaloExchanger1d (peer_halo_exchanger_1d.py:5): 1-D halo
    exchange along a sharded spatial dim, over the ICI ring."""

    def __init__(self, ranks=None, rank_in_group=None, peer_pool=None,
                 half_halo: int = 1, axis_name: str = "dp"):
        self.half_halo = half_halo
        self.axis_name = axis_name

    def __call__(self, y, H_split: bool = True, explicit_nhwc: bool = True,
                 numSM: int = 0, diagnostics: bool = False):
        dim = 1 if H_split else 2
        left, right = halo_exchange_1d(y, self.axis_name, self.half_halo,
                                       dim=dim)
        return left, right
