"""≡ apex.contrib.xentropy (apex/contrib/xentropy/__init__.py:1) —
re-export of the fused label-smoothed softmax cross entropy."""

from apex_tpu.ops.xentropy import (  # noqa: F401
    SoftmaxCrossEntropyLoss,
    softmax_cross_entropy_loss,
)
