"""Fused gather-multiply: out = in1[idx] * in2.

≡ apex.contrib.index_mul_2d (apex/contrib/index_mul_2d/index_mul_2d.py:5,
kernel apex/contrib/csrc/index_mul_2d/index_mul_2d_cuda.cu): fwd/bwd of
a gather followed by an elementwise multiply.  XLA fuses the gather into
the multiply on TPU; the custom_vjp mirrors the reference's hand-written
backward (scatter-add for d_in1, gather-multiply for d_in2).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.custom_vjp
def index_mul_2d(in1, in2, idx):
    """in1: (N, D); in2: (M, D); idx: (M,) int → (M, D)."""
    return jnp.take(in1, idx, axis=0) * in2


def _fwd(in1, in2, idx):
    return index_mul_2d(in1, in2, idx), (in1, in2, idx)


def _bwd(res, g):
    in1, in2, idx = res
    d_in2 = jnp.take(in1, idx, axis=0) * g
    d_in1 = jnp.zeros_like(in1).at[idx].add(in2 * g)
    return d_in1, d_in2, None


index_mul_2d.defvjp(_fwd, _bwd)
