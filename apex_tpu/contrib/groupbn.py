"""Group batch normalization (NHWC).

≡ apex.contrib.groupbn.BatchNorm2d_NHWC (apex/contrib/groupbn/batch_norm.py:101,
bnp extension: nhwc_batch_norm_kernel.h 2.7k LoC + CUDA-IPC peer stats)
and apex.contrib.cudnn_gbn.GroupBatchNorm2d
(apex/contrib/cudnn_gbn/batch_norm.py:44): BN whose statistics are
shared across a SUB-GROUP of ranks (bn_group) rather than all of dp.

TPU: stats merging across a subgroup is a psum over a dedicated mesh
sub-axis — build the mesh with the dp axis split as (dp_outer, bn) and
pass axis_name="bn"; the IPC peer-stat machinery is unnecessary.
Fused add+relu epilogues (use_addrelu) are XLA fusions.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from apex_tpu.parallel.sync_batchnorm import SyncBatchNorm, sync_batch_norm


class BatchNorm2d_NHWC(SyncBatchNorm):
    """≡ BatchNorm2d_NHWC (groupbn/batch_norm.py:7-101).

    fuse_relu / use_addrelu replicate the fused epilogues; bn_group>1
    requires `axis_name` naming the mesh sub-axis of the group.
    """

    def __init__(self, num_features, fuse_relu: bool = False,
                 bn_group: int = 1, axis_name: Optional[str] = None,
                 **kw):
        if bn_group > 1 and axis_name is None:
            raise ValueError(
                "bn_group > 1 needs a mesh sub-axis: build the mesh with "
                "the dp axis factored as (dp_outer, bn) and pass "
                "axis_name='bn'")
        super().__init__(num_features, axis_name=axis_name, **kw)
        self.fuse_relu = fuse_relu

    def apply(self, params, state, x, training=True, z=None,
              axis_name="__unset__"):
        ax = self.axis_name if axis_name == "__unset__" else axis_name
        y, rm, rv = sync_batch_norm(
            x, params.get("scale"), params.get("bias"),
            state["running_mean"], state["running_var"],
            training=training, momentum=self.momentum, eps=self.eps,
            axis_name=ax, channel_axis=self.channel_axis)
        if z is not None:  # use_addrelu: residual add before relu
            y = y + z
        if self.fuse_relu or z is not None:
            y = jnp.maximum(y, 0)
        return y, {"running_mean": rm, "running_var": rv}


GroupBatchNorm2d = BatchNorm2d_NHWC  # ≡ apex.contrib.cudnn_gbn
