"""Fused multihead attention modules — self and encdec variants.

≡ apex.contrib.multihead_attn (apex/contrib/multihead_attn/):
SelfMultiheadAttn (self_multihead_attn.py:21), EncdecMultiheadAttn, and
their six fused autograd variants (fast_*_func.py) built on 7.9k LoC of
cutlass/CUDA (csrc/multihead_attn/*).  TPU re-design: ONE parametrized
module over the blockwise flash-attention kernel; the variant matrix —
{self, encdec} × {bias} × {include-norm-add} × {mask} — becomes plain
composition (pre-LayerNorm + residual add, bias flags), since XLA fuses
the epilogues the CUDA code hand-wrote.
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.flash_attention import attention_reference, flash_attention
from apex_tpu.ops.layer_norm import fused_layer_norm


class SelfMultiheadAttn:
    """≡ SelfMultiheadAttn (self_multihead_attn.py:21-207).

    impl='fast' ≡ the fused CUDA path → flash attention;
    impl='default' → reference math.  include_norm_add prepends a
    LayerNorm and returns output + residual (≡ *_norm_add variants).
    Layout (S, B, H) like the reference (seq-first).
    """

    def __init__(self, embed_dim: int, num_heads: int, dropout: float = 0.0,
                 bias: bool = False, include_norm_add: bool = False,
                 impl: str = "fast", separate_qkv_params: bool = False):
        assert embed_dim % num_heads == 0
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.use_bias = bias
        self.include_norm_add = include_norm_add
        self.impl = impl
        self.separate_qkv_params = separate_qkv_params
        self.scaling = self.head_dim ** -0.5

    def init(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        std = 1.0 / math.sqrt(self.embed_dim)
        p = {
            "qkv_weight": jax.random.uniform(
                k1, (self.embed_dim, 3 * self.embed_dim), dtype, -std, std),
            "out_weight": jax.random.uniform(
                k2, (self.embed_dim, self.embed_dim), dtype, -std, std),
        }
        if self.use_bias:
            p["qkv_bias"] = jnp.zeros((3 * self.embed_dim,), dtype)
            p["out_bias"] = jnp.zeros((self.embed_dim,), dtype)
        if self.include_norm_add:
            p["ln"] = {"weight": jnp.ones((self.embed_dim,), dtype),
                       "bias": jnp.zeros((self.embed_dim,), dtype)}
        return p

    def apply(self, params, query, key=None, value=None, *,
              mask=None, is_training: bool = True,
              dropout_key=None, use_pallas_override=None):
        x = query
        residual = x
        if self.include_norm_add:
            x = fused_layer_norm(x, params["ln"]["weight"],
                                 params["ln"]["bias"])
        s, b, _ = x.shape
        qkv = x @ params["qkv_weight"].astype(x.dtype)
        if self.use_bias:
            qkv = qkv + params["qkv_bias"].astype(x.dtype)
        qkv = qkv.reshape(s, b, 3, self.num_heads, self.head_dim)
        q, k, v = (qkv[:, :, i].transpose(1, 2, 0, 3) for i in range(3))
        ctx = self._core(q, k, v, mask, is_training, dropout_key,
                         use_pallas_override)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, self.embed_dim)
        out = ctx @ params["out_weight"].astype(x.dtype)
        if self.use_bias:
            out = out + params["out_bias"].astype(x.dtype)
        if self.include_norm_add:
            out = out + residual
        return out

    def _core(self, q, k, v, mask, is_training, dropout_key,
              use_pallas_override):
        rate = self.dropout if (is_training and dropout_key is not None) \
            else 0.0
        dkey = dropout_key if rate > 0 else None
        common = dict(causal=False, softmax_scale=self.scaling,
                      dropout_rate=rate, dropout_key=dkey,
                      use_pallas_override=use_pallas_override)
        if mask is None:
            # dropout runs IN-kernel (counter-based mask, ≡ FMHA philox
            # dropout) so the no-mask path never materializes sq x sk
            return flash_attention(q, k, v, **common)
        b, sq, sk = q.shape[0], q.shape[2], k.shape[2]
        if mask.ndim == 2 and mask.shape == (b, sk):
            # (B, Sk) True = padded, the reference's key-padding mask
            # (self_multihead_attn.py unsqueezes it to (B,1,1,Sk)) →
            # segment ids: queries share id 0 with real keys, pads get
            # id 1 — still no sq x sk materialization.  When (B, Sk)
            # and (Sq, Sk) coincide, key-padding (reference semantics)
            # wins — pass a 4-D mask to disambiguate.
            return flash_attention(
                q, k, v,
                q_segment_ids=jnp.zeros((b, sq), jnp.int32),
                kv_segment_ids=mask.astype(jnp.int32), **common)
        # any other mask broadcastable to (b, n, sq, sk) — (sq, sk),
        # (n|1, sq, sk), (b|1, n|1, sq, sk) — becomes a fused additive
        # -10000 bias (≡ softmax.cuh's x*scale + mask); the mask the
        # caller built is already sq x sk-shaped, so the kernel adds no
        # score materialization on top
        while mask.ndim < 4:
            mask = mask[None]
        bias = jnp.where(mask, jnp.float32(-10000.0), jnp.float32(0.0))
        # the mask-derived bias is a constant: opt out of dbias work
        return flash_attention(q, k, v, bias=bias, bias_grad=False,
                               **common)


class EncdecMultiheadAttn(SelfMultiheadAttn):
    """≡ EncdecMultiheadAttn (encdec_multihead_attn.py): query from the
    decoder, key/value from the encoder — separate projections."""

    def init(self, key, dtype=jnp.float32):
        k1, k2, k3 = jax.random.split(key, 3)
        std = 1.0 / math.sqrt(self.embed_dim)
        p = {
            "q_weight": jax.random.uniform(
                k1, (self.embed_dim, self.embed_dim), dtype, -std, std),
            "kv_weight": jax.random.uniform(
                k2, (self.embed_dim, 2 * self.embed_dim), dtype, -std, std),
            "out_weight": jax.random.uniform(
                k3, (self.embed_dim, self.embed_dim), dtype, -std, std),
        }
        if self.use_bias:
            p["q_bias"] = jnp.zeros((self.embed_dim,), dtype)
            p["kv_bias"] = jnp.zeros((2 * self.embed_dim,), dtype)
            p["out_bias"] = jnp.zeros((self.embed_dim,), dtype)
        if self.include_norm_add:
            p["ln"] = {"weight": jnp.ones((self.embed_dim,), dtype),
                       "bias": jnp.zeros((self.embed_dim,), dtype)}
        return p

    def apply(self, params, query, key=None, value=None, *, mask=None,
              is_training: bool = True, dropout_key=None,
              use_pallas_override=None):
        enc = key if key is not None else query
        x = query
        residual = x
        if self.include_norm_add:
            x = fused_layer_norm(x, params["ln"]["weight"],
                                 params["ln"]["bias"])
        sq, b, _ = x.shape
        sk = enc.shape[0]
        q = x @ params["q_weight"].astype(x.dtype)
        kv = enc @ params["kv_weight"].astype(enc.dtype)
        if self.use_bias:
            q = q + params["q_bias"].astype(x.dtype)
            kv = kv + params["kv_bias"].astype(x.dtype)
        q = q.reshape(sq, b, self.num_heads, self.head_dim
                      ).transpose(1, 2, 0, 3)
        kv = kv.reshape(sk, b, 2, self.num_heads, self.head_dim)
        k_, v_ = (kv[:, :, i].transpose(1, 2, 0, 3) for i in range(2))
        ctx = self._core(q, k_, v_, mask, is_training, dropout_key,
                         use_pallas_override)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(sq, b, self.embed_dim)
        out = ctx @ params["out_weight"].astype(x.dtype)
        if self.use_bias:
            out = out + params["out_bias"].astype(x.dtype)
        if self.include_norm_add:
            out = out + residual
        return out
