"""RNN-T transducer joint + loss.

≡ apex.contrib.transducer (apex/contrib/transducer/transducer.py:5,68;
kernels apex/contrib/csrc/transducer/transducer_joint_kernel.cu and
transducer_loss_kernel.cu): the fused broadcast-add joint and the
alpha/beta forward-backward RNN-T loss.

TPU re-design: the joint is an XLA-fused broadcast add (+ReLU/dropout);
the loss's alpha DP — sequential in both T and U on CUDA — becomes a
`lax.scan` over T with a `lax.associative_scan` along U per row: the
within-row recurrence  x[u] = logaddexp(a[u], x[u-1] + b[u])  is a
composition of affine log-space maps (a, b), which compose
associatively, so each row is O(log U) depth on the VPU.  Gradients come
from AD through the scans (≡ the hand-written backward kernel).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

_NEG = -1e30


class TransducerJoint:
    """≡ TransducerJoint (transducer.py:5-66): h = f[:, :, None] +
    g[:, None, :] with optional relu/dropout (packing omitted — XLA has
    no padded-compute penalty worth the bookkeeping on TPU)."""

    def __init__(self, pack_output=False, relu=False, dropout=0.0):
        if pack_output:
            raise NotImplementedError(
                "packed output is a CUDA memory-layout optimization; "
                "on TPU use the padded layout")
        self.relu = relu
        self.dropout = dropout

    def __call__(self, f, g, f_len=None, g_len=None, dropout_key=None,
                 is_training=True):
        h = f[:, :, None, :] + g[:, None, :, :]
        if self.relu:
            h = jnp.maximum(h, 0)
        if self.dropout and is_training and dropout_key is not None:
            from apex_tpu.ops._common import dropout
            h = dropout(dropout_key, self.dropout, h)
        return h


def _row_scan(a, b, x0):
    """x[u] = logaddexp(a[u], x[u-1] + b[u]), x[-1] = x0, via
    associative composition of log-affine maps."""
    def combine(l, r):
        al, bl = l
        ar, br = r
        return jnp.logaddexp(ar, al + br), bl + br

    a0 = jnp.logaddexp(a[..., 0], x0 + b[..., 0])
    a_rest = a[..., 1:]
    b_rest = b[..., 1:]
    a_all = jnp.concatenate([a0[..., None], a_rest], axis=-1)
    b_all = jnp.concatenate([jnp.zeros_like(b[..., :1]), b_rest], axis=-1)
    res_a, _ = lax.associative_scan(combine, (a_all, b_all), axis=-1)
    return res_a


def transducer_loss(log_probs, labels, f_len, y_len, blank_idx: int = 0):
    """RNN-T loss ≡ TransducerLoss (transducer.py:68-130).

    log_probs: (B, T, U+1, V) log-softmax over vocab;
    labels: (B, U) int; f_len: (B,) valid T; y_len: (B,) valid U.
    Returns per-sample negative log likelihood (B,).
    """
    B, T, U1, V = log_probs.shape
    U = U1 - 1  # label positions
    blank = log_probs[..., blank_idx]                       # (B, T, U+1)
    lbl = jnp.take_along_axis(
        log_probs[:, :, :U, :],
        jnp.broadcast_to(labels[:, None, :, None], (B, T, U, 1)),
        axis=-1)[..., 0]                                    # (B, T, U)
    # mask invalid label positions (u >= y_len): emitting there is
    # impossible
    u_idx = jnp.arange(U)[None, None, :]
    lbl = jnp.where(u_idx < y_len[:, None, None], lbl, _NEG)

    # alpha[0, u] = cumsum of label emissions along u at t=0
    a0 = jnp.concatenate(
        [jnp.zeros((B, 1)), jnp.cumsum(lbl[:, 0, :], axis=-1)], axis=-1)

    def step(alpha_prev, t):
        # A[u] = alpha[t-1, u] + blank[t-1, u]  (time transition)
        A = alpha_prev + blank[:, t - 1, :]
        # row recurrence: alpha[t, u] = logaddexp(A[u], alpha[t,u-1]
        #                                         + lbl[t, u-1])
        a_first = A[:, :1]                                   # u = 0
        a_rest = _row_scan(A[:, 1:], lbl[:, t, :], a_first[:, 0])
        alpha_t = jnp.concatenate([a_first, a_rest], axis=-1)
        return alpha_t, alpha_t

    _, alphas = lax.scan(step, a0, jnp.arange(1, T))
    alphas = jnp.concatenate([a0[None], alphas], axis=0)     # (T, B, U+1)
    alphas = alphas.transpose(1, 0, 2)                       # (B, T, U+1)

    # NLL = -(alpha[f_len-1, y_len] + blank[f_len-1, y_len])
    t_last = jnp.maximum(f_len - 1, 0)
    a_final = jnp.take_along_axis(
        alphas, t_last[:, None, None], axis=1)[:, 0, :]      # (B, U+1)
    a_final = jnp.take_along_axis(a_final, y_len[:, None], axis=1)[:, 0]
    b_final = jnp.take_along_axis(
        blank, t_last[:, None, None], axis=1)[:, 0, :]
    b_final = jnp.take_along_axis(b_final, y_len[:, None], axis=1)[:, 0]
    return -(a_final + b_final)


class TransducerLoss:
    """Module facade ≡ TransducerLoss (transducer.py:68)."""

    def __init__(self, packed_input=False):
        if packed_input:
            raise NotImplementedError("packed input is a CUDA layout "
                                      "optimization; use padded on TPU")

    def __call__(self, x, label, f_len, y_len, blank_idx=0):
        return transducer_loss(x, label, f_len, y_len, blank_idx)
