"""FMHA facade ≡ apex.contrib.fmha (apex/contrib/fmha/fmha.py:33-72):
fixed-size fused MHA (seq ≤ 512, head dim 64, fp16, sm80+) over packed
variable-length batches.  The TPU kernel (ops/flash_attention.py) has no
size cap; this facade keeps the reference's packed-QKV call shape.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention


def segment_ids_from_cu_seqlens(cu_seqlens, seq_len: int):
    """cu_seqlens (n+1,) cumulative boundaries of n packed sequences →
    (1, seq_len) segment ids: the TPU-native form of the reference's
    varlen packing (fmha_api.cpp:18-160).  Positions past the last
    boundary get a fresh id (pad segment)."""
    pos = jnp.arange(seq_len)
    # id = number of boundaries <= pos (first sequence = 1, pads = n+1)
    return jnp.sum(pos[None, :] >= jnp.asarray(cu_seqlens)[1:, None],
                   axis=0, dtype=jnp.int32)[None, :] + 1


class FMHAFun:
    """≡ fmha.FMHAFun: qkv packed (total_tokens, 3, h, d) + cu_seqlens.
    TPU version takes the padded dense layout (B, S, 3, h, d) — packing
    into one row still works: pass `segment_ids` (or `cu_seqlens` for
    B == 1) and cross-sequence/pad attention is masked in-kernel, so
    packed tokens cost no cross attention (the reference's whole point).
    """

    @staticmethod
    def apply(qkv, causal=False, softmax_scale=None, segment_ids=None,
              cu_seqlens=None):
        if cu_seqlens is not None:
            if segment_ids is not None:
                raise ValueError("pass segment_ids or cu_seqlens, not both")
            if qkv.shape[0] != 1:
                raise ValueError("cu_seqlens packing implies batch 1 "
                                 "(one packed row); use segment_ids for "
                                 "batched packing")
            segment_ids = segment_ids_from_cu_seqlens(
                cu_seqlens, qkv.shape[1])
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        o = flash_attention(q, k, v, causal=causal,
                            softmax_scale=softmax_scale,
                            segment_ids=segment_ids)
        return o.transpose(0, 2, 1, 3)


class FMHA:
    """≡ fmha.FMHA (fmha.py:60)."""

    def __init__(self, causal: bool = False):
        self.causal = causal

    def __call__(self, qkv, softmax_scale=None, segment_ids=None,
                 cu_seqlens=None):
        return FMHAFun.apply(qkv, self.causal, softmax_scale,
                             segment_ids, cu_seqlens)
