"""FMHA facade ≡ apex.contrib.fmha (apex/contrib/fmha/fmha.py:33-72):
fixed-size fused MHA (seq ≤ 512, head dim 64, fp16, sm80+) over packed
variable-length batches.  The TPU kernel (ops/flash_attention.py) has no
size cap; this facade keeps the reference's packed-QKV call shape.
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.ops.flash_attention import flash_attention


class FMHAFun:
    """≡ fmha.FMHAFun: qkv packed (total_tokens, 3, h, d) + cu_seqlens.
    TPU version takes the padded dense layout (B, S, 3, h, d) — packing
    is a CUDA memory trick; XLA prefers static shapes."""

    @staticmethod
    def apply(qkv, causal=False, softmax_scale=None):
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        o = flash_attention(q, k, v, causal=causal,
                            softmax_scale=softmax_scale)
        return o.transpose(0, 2, 1, 3)


class FMHA:
    """≡ fmha.FMHA (fmha.py:60)."""

    def __init__(self, causal: bool = False):
        self.causal = causal

    def __call__(self, qkv, softmax_scale=None):
        return FMHAFun.apply(qkv, self.causal, softmax_scale)
