"""≡ apex.contrib.optimizers — the distributed (ZeRO) optimizers plus
the deprecated contrib Fused* aliases (apex/contrib/optimizers/__init__.py)."""

from apex_tpu.optimizers.distributed_fused_adam import (  # noqa: F401
    DistributedFusedAdam,
    DistributedFusedLAMB,
)
from apex_tpu.optimizers.fused_adam import FusedAdam  # noqa: F401
from apex_tpu.optimizers.fused_lamb import FusedLAMB  # noqa: F401
from apex_tpu.optimizers.fused_sgd import FusedSGD  # noqa: F401
from apex_tpu.amp.fp16_optimizer import FP16_Optimizer  # noqa: F401
