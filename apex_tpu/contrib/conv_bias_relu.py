"""Fused conv + bias (+ mask) + relu.

≡ apex.contrib.conv_bias_relu (apex/contrib/conv_bias_relu/conv_bias_relu.py:12-78,
cudnn-frontend kernels csrc/conv_bias_relu/conv_bias_relu.cpp 2.1k LoC):
on TPU every one of these is a single XLA fusion around the conv — the
custom_vjp mirrors the reference's saved-tensor choices.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.models.resnet import conv2d


def conv_bias_relu(x, w, b, stride: int = 1, padding: str = "SAME"):
    """≡ ConvBiasReLU_ (conv_bias_relu.py:12)."""
    y = conv2d(x, w, stride=stride, padding=padding)
    return jnp.maximum(y + b.reshape(1, 1, 1, -1), 0)


def conv_bias(x, w, b, stride: int = 1, padding: str = "SAME"):
    """≡ ConvBias_."""
    return conv2d(x, w, stride=stride, padding=padding) \
        + b.reshape(1, 1, 1, -1)


def conv_bias_mask_relu(x, w, b, mask, stride: int = 1,
                        padding: str = "SAME"):
    """≡ ConvBiasMaskReLU_ (dropout-style mask before relu)."""
    y = conv2d(x, w, stride=stride, padding=padding) \
        + b.reshape(1, 1, 1, -1)
    return jnp.maximum(y * mask, 0)


def conv_frozen_scale_bias_relu(x, w, scale, bias, stride: int = 1,
                                padding: str = "SAME"):
    """≡ ConvFrozenScaleBiasReLU_ (frozen-BN inference fusion)."""
    y = conv2d(x, w, stride=stride, padding=padding)
    return jnp.maximum(y * scale.reshape(1, 1, 1, -1)
                       + bias.reshape(1, 1, 1, -1), 0)
