"""≡ apex.contrib.layer_norm.FastLayerNorm
(apex/contrib/layer_norm/layer_norm.py:40; fast_layer_norm kernels
tuned per hidden size 768-12288): on TPU the single blocked Pallas
LayerNorm covers all hidden sizes — this is a re-export."""

from apex_tpu.ops.layer_norm import FusedLayerNorm as FastLayerNorm  # noqa: F401
from apex_tpu.ops.layer_norm import fused_layer_norm  # noqa: F401
