"""apex_tpu.contrib ≡ apex.contrib: optional fused components.

On TPU these are thin compositions over the core Pallas kernels —
the reference's per-feature CUDA extensions (apex/contrib/csrc/*)
collapse into flash_attention / welford / collectives / XLA fusions.
"""


def __getattr__(name):
    import importlib
    mods = ("multihead_attn", "focal_loss", "index_mul_2d", "transducer",
            "sparsity", "groupbn", "peer_memory", "bottleneck", "xentropy",
            "clip_grad", "conv_bias_relu", "fmha", "layer_norm",
            "optimizers", "cudnn_gbn")
    if name in mods:
        return importlib.import_module(f"apex_tpu.contrib.{name}")
    raise AttributeError(name)
