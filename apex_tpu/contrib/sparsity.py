"""ASP — automatic 2:4 structured sparsity.

≡ apex.contrib.sparsity (apex/contrib/sparsity/asp.py:40-213,
sparse_masklib.py, permutation_lib.py + CUDA search kernels): computes
2:4 (n:m) sparsity masks for weight matrices, wraps the optimizer step
to re-apply masks, and searches channel permutations that preserve
accuracy.  TPU version: mask computation and the greedy permutation
search are XLA reductions/sorts (the CUDA kernels were brute-force
scorers); the optimizer hook becomes a functional mask-apply after each
step.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def create_mask(weight, pattern: str = "m4n2_1d"):
    """2:4 mask along the input dim ≡ sparse_masklib.create_mask.

    m4n2_1d: in every group of 4 consecutive elements of each row, keep
    the 2 with largest magnitude.
    """
    if pattern not in ("m4n2_1d", "m4n2"):
        raise NotImplementedError(f"pattern {pattern}")
    w = jnp.abs(weight)
    orig = w.shape
    m, n = 4, 2
    flat = w.reshape(-1, m)
    # rank within each group; keep top-n
    order = jnp.argsort(flat, axis=-1)  # ascending
    ranks = jnp.zeros_like(order).at[
        jnp.arange(flat.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(m), flat.shape))
    mask = (ranks >= (m - n)).astype(weight.dtype)
    return mask.reshape(orig)


def apply_mask(weight, mask):
    return weight * mask


def magnitude_after_mask(weight, mask=None):
    if mask is None:
        mask = create_mask(weight)
    return jnp.sum(jnp.abs(weight) * mask)


def search_channel_permutation(weight, num_iters: int = 100,
                               seed: int = 0):
    """Greedy column-permutation search maximizing retained magnitude
    under the 2:4 mask ≡ permutation_lib.Permutation +
    permutation_search_kernels (CUDA brute-force scorers → vectorized
    jnp scoring).  Returns (permutation, score)."""
    c = weight.shape[-1]
    perm = np.arange(c)
    w = np.asarray(weight, np.float32)

    def score(p):
        return float(magnitude_after_mask(jnp.asarray(w[:, p])))

    best = score(perm)
    rng = np.random.RandomState(seed)
    for _ in range(num_iters):
        i, j = rng.randint(0, c, 2)
        if i == j:
            continue
        cand = perm.copy()
        cand[i], cand[j] = cand[j], cand[i]
        s = score(cand)
        if s > best:
            best, perm = s, cand
    return perm, best


class ASP:
    """≡ apex.contrib.sparsity.ASP (asp.py): functional variant.

    asp = ASP(); params = asp.init_model_for_pruning(params, whitelist)
    computes masks; asp.apply(params) re-applies them (call after every
    optimizer step ≡ the wrapped optimizer.step, asp.py:185-211).
    """

    def __init__(self, mask_calculator: str = "m4n2_1d",
                 allow_permutation: bool = False):
        self.pattern = mask_calculator
        self.allow_permutation = allow_permutation
        self.masks = {}

    def _eligible(self, path, leaf, whitelist):
        name = "/".join(str(p) for p in path).lower()
        if leaf.ndim < 2:
            return False
        if min(leaf.shape[-2:]) % 4 != 0:
            return False
        if whitelist is None:
            return "weight" in name or name.endswith("w")
        return any(w in name for w in whitelist)

    def init_model_for_pruning(self, params, whitelist=None):
        """Compute masks ≡ ASP.init_model_for_pruning (asp.py:40-182) +
        compute_sparse_masks (asp.py:213)."""
        self.masks = {}

        def visit(path, leaf):
            if self._eligible(path, leaf, whitelist):
                key = tuple(str(p) for p in path)
                self.masks[key] = create_mask(leaf, self.pattern)
                return leaf * self.masks[key]
            return leaf

        return jax.tree_util.tree_map_with_path(visit, params)

    def apply(self, params):
        """Re-apply masks after an optimizer step ≡ the wrapped step."""
        def visit(path, leaf):
            key = tuple(str(p) for p in path)
            if key in self.masks:
                return leaf * self.masks[key]
            return leaf

        return jax.tree_util.tree_map_with_path(visit, params)

    def sparsity(self, params):
        """Fraction of zeros in masked leaves."""
        zeros = total = 0
        for key, mask in self.masks.items():
            zeros += float(jnp.sum(mask == 0))
            total += mask.size
        return zeros / max(total, 1)
