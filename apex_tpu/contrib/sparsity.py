"""ASP — automatic 2:4 structured sparsity.

≡ apex.contrib.sparsity (apex/contrib/sparsity/asp.py:40-213,
sparse_masklib.py, permutation_lib.py + CUDA search kernels): computes
2:4 (n:m) sparsity masks for weight matrices, wraps the optimizer step
to re-apply masks, and searches channel permutations that preserve
accuracy.  TPU version: mask computation and the greedy permutation
search are XLA reductions/sorts (the CUDA kernels were brute-force
scorers); the optimizer hook becomes a functional mask-apply after each
step.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def create_mask(weight, pattern: str = "m4n2_1d"):
    """2:4 mask along the input dim ≡ sparse_masklib.create_mask.

    m4n2_1d: in every group of 4 consecutive elements of each row, keep
    the 2 with largest magnitude.
    """
    if pattern not in ("m4n2_1d", "m4n2"):
        raise NotImplementedError(f"pattern {pattern}")
    w = jnp.abs(weight)
    orig = w.shape
    m, n = 4, 2
    flat = w.reshape(-1, m)
    # rank within each group; keep top-n
    order = jnp.argsort(flat, axis=-1)  # ascending
    ranks = jnp.zeros_like(order).at[
        jnp.arange(flat.shape[0])[:, None], order].set(
        jnp.broadcast_to(jnp.arange(m), flat.shape))
    mask = (ranks >= (m - n)).astype(weight.dtype)
    return mask.reshape(orig)


def apply_mask(weight, mask):
    return weight * mask


def magnitude_after_mask(weight, mask=None):
    if mask is None:
        mask = create_mask(weight)
    return jnp.sum(jnp.abs(weight) * mask)


# ----------------------- stripe-group permutation search --------------------
#
# ≡ permutation_search_kernels/exhaustive_search.py's Exhaustive_Search:
# columns form stripes of 4; for every PAIR of stripes the best
# re-partition of their 8 columns into two 4-groups is found by bounded
# exhaustion (35 canonical splits), the best disjoint improvements are
# applied greedily, and the loop repeats until no pair improves —
# followed by random escape swaps to leave local optima.  The CUDA
# brute-force scorers become one vectorized jnp pass over (pairs x 35
# splits); large matrices are subdivided then fixed up globally, like
# the reference's >512-column split.

# the 35 canonical ways to split 8 columns into two unordered 4-groups
# (fix column 0 in group A to kill the A/B symmetry)
_SPLITS8 = None


def _splits8():
    global _SPLITS8
    if _SPLITS8 is None:
        import itertools
        combos = [(0,) + c for c in itertools.combinations(range(1, 8), 3)]
        rest = [tuple(sorted(set(range(8)) - set(c))) for c in combos]
        _SPLITS8 = (np.asarray(combos, np.int32),
                    np.asarray(rest, np.int32))
    return _SPLITS8


@jax.jit
def _mag4_groups(cols_abs):
    """cols_abs: (..., 4) magnitudes → retained sum keeping top-2."""
    srt = jnp.sort(cols_abs, axis=-1)
    return jnp.sum(srt[..., 2:], axis=-1)


def _stripe_scores(w_abs, perm):
    """Retained magnitude of each stripe under the 2:4 mask: (S,)."""
    cols = w_abs[:, perm].reshape(w_abs.shape[0], -1, 4)  # (R, S, 4)
    return np.asarray(jnp.sum(_mag4_groups(cols), axis=0))


@jax.jit
def _score_pairs(w_abs, cols8):
    """cols8: (P, 8) column ids per stripe pair → best split score and
    split index: (P,), (P,).  Gathering all 35 splits at once is
    memory-heavy; scan over them instead."""
    ga, gb = _splits8()
    w8 = w_abs[:, cols8]                                  # (R, P, 8)

    def body(best, i):
        sa = jnp.sum(_mag4_groups(w8[:, :, ga[i]]), axis=0)   # (P,)
        sb = jnp.sum(_mag4_groups(w8[:, :, gb[i]]), axis=0)
        s = sa + sb
        best_s, best_i = best
        better = s > best_s
        return (jnp.where(better, s, best_s),
                jnp.where(better, i, best_i)), None

    ga = jnp.asarray(ga)
    gb = jnp.asarray(gb)
    init = (jnp.full((cols8.shape[0],), -jnp.inf, w_abs.dtype),
            jnp.zeros((cols8.shape[0],), jnp.int32))
    (best_s, best_i), _ = jax.lax.scan(body, init,
                                       jnp.arange(ga.shape[0]))
    return best_s, best_i


def _pair_improvements(w_abs, perm, stripe_scores, pair_chunk=8192):
    """Best split score/index for every stripe pair, chunked to bound
    memory.  Returns (pairs, best_score, best_split, improvement)."""
    S = len(perm) // 4
    pairs = np.asarray([(a, b) for a in range(S) for b in range(a + 1, S)],
                       np.int32)
    cols = perm.reshape(S, 4)
    best_s = np.empty(len(pairs), np.float32)
    best_i = np.empty(len(pairs), np.int32)
    for lo in range(0, len(pairs), pair_chunk):
        chunk = pairs[lo:lo + pair_chunk]
        cols8 = np.concatenate([cols[chunk[:, 0]], cols[chunk[:, 1]]],
                               axis=1)                    # (P, 8)
        s, i = _score_pairs(w_abs, jnp.asarray(cols8))
        best_s[lo:lo + pair_chunk] = np.asarray(s)
        best_i[lo:lo + pair_chunk] = np.asarray(i)
    imp = best_s - (stripe_scores[pairs[:, 0]] + stripe_scores[pairs[:, 1]])
    return pairs, best_s, best_i, imp


def _greedy_rounds(w_abs, perm, rel_tol=1e-4, max_rounds=32):
    """Apply best disjoint pair re-partitions until (near-)converged.

    The greedy loop has a long tail of sub-0.01% improvements (pair
    re-partitions keep opening marginal opportunities for each other),
    so convergence is declared when the best remaining improvement
    drops below ``rel_tol`` of the retained magnitude, with a round cap
    as a backstop."""
    if len(perm) < 8:
        return perm  # a single stripe has no pairs to re-partition
    ga, gb = _splits8()
    S = len(perm) // 4
    for _ in range(max_rounds):
        scores = _stripe_scores(w_abs, perm)
        tol = rel_tol * float(scores.sum())
        pairs, best_s, best_i, imp = _pair_improvements(w_abs, perm,
                                                        scores)
        order = np.argsort(-imp)
        used = set()
        changed = False
        cols = perm.reshape(S, 4).copy()
        for idx in order:
            if imp[idx] <= tol:
                break
            a, b = pairs[idx]
            if a in used or b in used:
                continue
            cols8 = np.concatenate([cols[a], cols[b]])
            cols[a] = cols8[ga[best_i[idx]]]
            cols[b] = cols8[gb[best_i[idx]]]
            used.update((a, b))
            changed = True
        perm = cols.reshape(-1)
        if not changed:
            break
    return perm


def search_channel_permutation(weight, window: int = 8,
                               escape_attempts: int = 4,
                               seed: int = 0, max_cols: int = 512):
    """Stripe-group channel-permutation search maximizing retained
    magnitude under the 2:4 mask ≡ Exhaustive_Search
    (permutation_search_kernels/exhaustive_search.py:312-380: bounded
    exhaustive window over stripe groups + greedy disjoint application
    + random escape perturbations).  Returns (permutation, score) with
    ``score = magnitude_after_mask(weight[:, permutation])``.

    Matrices wider than ``max_cols`` are optimized as independent
    halves, then fixed up with a few bounded full-width rounds (≡ the
    reference's >512-column subdivision + global fixup).  Only window=8 (stripe pairs) is
    implemented: wider windows explode combinatorially and the
    reference itself falls back to 8 for its global fixup.
    """
    if window != 8:
        raise NotImplementedError("only the stripe-pair window (8) is "
                                  "supported")
    c = weight.shape[-1]
    if c % 4:
        raise ValueError(f"columns ({c}) must be a multiple of 4")
    w_abs = jnp.abs(jnp.asarray(weight, jnp.float32))
    w_np = np.asarray(w_abs)

    def run(perm0):
        if len(perm0) > max_cols:
            half = (len(perm0) // 8) * 4
            left = run(perm0[:half])
            right = run(perm0[half:])
            # bounded global fixup: the per-half searches did the bulk
            # of the work; a few full-width rounds catch cross-half
            # wins without re-running the O(S^2)-pair loop to
            # convergence at full width
            return _greedy_rounds(w_np, np.concatenate([left, right]),
                                  max_rounds=4)
        return _greedy_rounds(w_np, perm0)

    perm = run(np.arange(c))
    best = float(magnitude_after_mask(jnp.asarray(w_np)[:, perm]))

    rng = np.random.RandomState(seed)
    for _ in range(escape_attempts):
        cand = perm.copy()
        # cross-half column swap (≡ use_stripe_map's perturbation) then
        # re-converge; keep only strict improvements
        i = rng.randint(0, c // 2)
        j = c // 2 + rng.randint(0, c - c // 2)
        cand[i], cand[j] = cand[j], cand[i]
        # wide matrices keep the bounded-round budget here too — an
        # unbounded full-width re-convergence would dwarf the
        # subdivided main search
        cand = _greedy_rounds(w_np, cand,
                              max_rounds=4 if c > max_cols else 32)
        s = float(magnitude_after_mask(jnp.asarray(w_np)[:, cand]))
        if s > best + 1e-6:
            perm, best = cand, s
    return perm, best


class ASP:
    """≡ apex.contrib.sparsity.ASP (asp.py): functional variant.

    asp = ASP(); params = asp.init_model_for_pruning(params, whitelist)
    computes masks; asp.apply(params) re-applies them (call after every
    optimizer step ≡ the wrapped optimizer.step, asp.py:185-211).

    Tensor-parallel weights: masks are computed on the LOCAL shard
    inside shard_map.  This is exact for both TP layouts because the
    2:4 groups run along the INPUT dim (rows of a (in, out) kernel):
    ColumnParallel shards the output dim (groups intact per shard) and
    RowParallel shards the input dim in multiples of 4 (group
    boundaries never straddle shards).  Channel PERMUTATIONS
    (search_channel_permutation) act on the input dim: under TP apply
    the same permutation to the producer's output dim — for a
    RowParallel consumer this means permuting within each shard's
    column range only (search per-shard), mirroring the reference's
    per-GPU permutation domains (permutation_lib.py's C/K
    parent-children propagation).
    """

    def __init__(self, mask_calculator: str = "m4n2_1d",
                 allow_permutation: bool = False):
        self.pattern = mask_calculator
        self.allow_permutation = allow_permutation
        self.masks = {}

    def _eligible(self, path, leaf, whitelist):
        name = "/".join(str(p) for p in path).lower()
        if leaf.ndim < 2:
            return False
        if min(leaf.shape[-2:]) % 4 != 0:
            return False
        if whitelist is None:
            return "weight" in name or name.endswith("w")
        return any(w in name for w in whitelist)

    def init_model_for_pruning(self, params, whitelist=None):
        """Compute masks ≡ ASP.init_model_for_pruning (asp.py:40-182) +
        compute_sparse_masks (asp.py:213)."""
        self.masks = {}

        def visit(path, leaf):
            if self._eligible(path, leaf, whitelist):
                key = tuple(str(p) for p in path)
                self.masks[key] = create_mask(leaf, self.pattern)
                return leaf * self.masks[key]
            return leaf

        return jax.tree_util.tree_map_with_path(visit, params)

    def apply(self, params):
        """Re-apply masks after an optimizer step ≡ the wrapped step."""
        def visit(path, leaf):
            key = tuple(str(p) for p in path)
            if key in self.masks:
                return leaf * self.masks[key]
            return leaf

        return jax.tree_util.tree_map_with_path(visit, params)

    def sparsity(self, params):
        """Fraction of zeros in masked leaves."""
        zeros = total = 0
        for key, mask in self.masks.items():
            zeros += float(jnp.sum(mask == 0))
            total += mask.size
        return zeros / max(total, 1)
