"""≡ apex.contrib.clip_grad (apex/contrib/clip_grad/clip_grad.py:16) —
re-export of the fused clip_grad_norm."""

from apex_tpu.parallel.clip_grad import clip_grad_norm, clip_grad_norm_  # noqa: F401
