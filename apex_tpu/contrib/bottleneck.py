"""Fused ResNet bottleneck + spatial-parallel convolution with halo
exchange.

≡ apex.contrib.bottleneck (apex/contrib/bottleneck/bottleneck.py:134
Bottleneck, 603 SpatialBottleneck; halo_exchangers.py:11-127
HaloExchanger{NoComm,AllGather,SendRecv,Peer}; fast_bottleneck 4.1k LoC
cudnn-frontend CUDA): the fused block is apex_tpu.models.resnet.Bottleneck
(XLA fuses conv+BN+ReLU chains); this module adds the SPATIAL variant —
input images sharded along H across a mesh axis, 3x3 convs exchanging
one-row halos with ring neighbours.  The four CUDA halo transports
(allgather / sendrecv / NVLink peer memory / raw NCCL) collapse into one
`lax.ppermute` over ICI (parallel/collectives.halo_exchange_1d).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.models.resnet import Bottleneck, conv2d
from apex_tpu.parallel.collectives import halo_exchange_1d


def spatial_conv2d(x, w, axis_name: str, stride: int = 1):
    """Conv over H-sharded NHWC input with halo exchange.

    ≡ SpatialBottleneck's halo-exchanged 3x3 conv
    (bottleneck.py:603-980).  Non-periodic: edge shards see zero halos
    (SAME-padding semantics of the unsharded conv).
    """
    kh = w.shape[0]
    if kh == 1:
        return conv2d(x, w, stride=stride, padding="SAME")
    halo = (kh - 1) // 2
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    top, bot = halo_exchange_1d(x, axis_name, halo, dim=1)  # NHWC → H dim 1
    top = jnp.where(rank == 0, jnp.zeros_like(top), top)
    bot = jnp.where(rank == n - 1, jnp.zeros_like(bot), bot)
    xh = jnp.concatenate([top, x, bot], axis=1)
    # valid in H (halos provide the padding), SAME in W
    return lax.conv_general_dilated(
        xh, w, window_strides=(stride, stride),
        padding=[(0, 0), (kh // 2, kh // 2)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


class SpatialBottleneck(Bottleneck):
    """≡ SpatialBottleneck (bottleneck.py:603): Bottleneck whose 3x3 conv
    runs on H-sharded activations.  Use inside shard_map with the image
    H dim sharded on `spatial_axis`."""

    def __init__(self, cin, width, stride=1, downsample=False,
                 spatial_axis: str = "dp"):
        super().__init__(cin, width, stride, downsample)
        self.spatial_axis = spatial_axis

    def apply(self, params, state, x, training, axis_name):
        from apex_tpu.models.resnet import _bn_apply
        new_state = {}
        out = conv2d(x, params["conv1"])
        out, new_state["bn1"] = _bn_apply(params["bn1"], state["bn1"], out,
                                          training, axis_name)
        out = jnp.maximum(out, 0)
        out = spatial_conv2d(out, params["conv2"], self.spatial_axis,
                             stride=self.stride)
        out, new_state["bn2"] = _bn_apply(params["bn2"], state["bn2"], out,
                                          training, axis_name)
        out = jnp.maximum(out, 0)
        out = conv2d(out, params["conv3"])
        out, new_state["bn3"] = _bn_apply(params["bn3"], state["bn3"], out,
                                          training, axis_name)
        if self.downsample:
            sc = conv2d(x, params["conv_ds"], stride=self.stride)
            sc, new_state["bn_ds"] = _bn_apply(params["bn_ds"],
                                               state["bn_ds"], sc,
                                               training, axis_name)
        else:
            sc = x
        return jnp.maximum(out + sc, 0), new_state


class HaloExchanger:
    """Facade over the ppermute halo exchange ≡ the HaloExchanger family
    (halo_exchangers.py:11-127) — one transport on TPU."""

    def __init__(self, axis_name: str):
        self.axis_name = axis_name

    def left_right_halo_exchange(self, x, halo: int, dim: int = 1):
        return halo_exchange_1d(x, self.axis_name, halo, dim=dim)


HaloExchangerNoComm = HaloExchangerAllGather = HaloExchangerSendRecv = \
    HaloExchangerPeer = HaloExchanger
