"""Paged flash-decode attention — the serving-side counterpart of the
training flash kernel (ops/flash_attention.py, ISSUE 8 tentpole).

Decode-mode attention is a different shape class than training: ONE
query token (q_len = 1, or a handful under speculative decoding) per
sequence against a KV cache that GROWS every step, for thousands of
concurrent sequences of ragged length.  A dense (slots, max_seq)
cache would pin worst-case HBM per user; instead the cache is PAGED
(serve/kv_cache.py): a fixed pool of `(page_size, head_dim)` pages
shared by every sequence, with a per-slot block table naming which
pages hold its tokens.  The kernel gathers pages through the block
table at DMA time — the Pallas index map reads the table from SMEM
(scalar prefetch) and fetches page `block_table[slot, t]` for grid
step t — so the compiled program's shapes NEVER depend on sequence
length or concurrency churn: the continuous-batching engine
(serve/engine.py) admits and retires requests under a RecompileSentry
that proves no steady-state retrace.

Layout contract (shared with serve/kv_cache.py):

  q              (n_slots, q_len, n_q_heads, head_dim)
  k/v_pages      (n_kv_heads, n_pages, page_size, head_dim)
  block_table    (n_slots, pages_per_slot_max) int32 page ids
  lengths        (n_slots,) int32 — total visible tokens per slot,
                 INCLUDING the q_len new tokens (their K/V must
                 already be written into the pages; the engine writes
                 then attends).  0 marks an inactive slot.

Query row i of slot s sees cache positions p < lengths[s] - q_len + 1
+ i (causal within the new block); GQA rides as n_q_heads = G *
n_kv_heads with query head h reading kv head h // G.  Rows with no
visible position (inactive slots) return ZEROS — unlike the training
kernel's uniform-attention convention, a parked slot must contribute
exact zeros so the engine can keep stepping it for free.

The per-step masking is the segment-ids machinery of the training
kernel re-aimed at pages: a partial last page holds garbage beyond
`lengths` and stale table entries point at recycled pages — both are
masked by position, never by data, so the pool needs no cleaning
between requests.

heads_per_step packs that many kv heads per grid step (one shared
online-softmax epilogue, hp-head page DMAs — the same d=64 vreg-
filling axis as the training kernel's packing, PR 3) and is owned by
the apex_tpu.tune cache with a deterministic heuristic fallback.  The
kv block size IS the page size: pages are non-contiguous in the pool,
so one page is the natural DMA unit, and `page_size` itself is the
tuner-owned block-size knob (serve.KVCacheConfig consults
`tune.tuned("serve_page", ...)` when unset).

Forward-only: decode is inference — no VJP, no lse output.
"""

from __future__ import annotations

import functools
import math
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import pallas_interpret, use_pallas

_NEG_INF = -1e30

_HP_FALLBACK_WARNED = set()


def _check_shapes(q, k_pages, v_pages, block_table, lengths):
    if q.ndim != 4:
        raise ValueError(f"q must be (n_slots, q_len, n_q_heads, "
                         f"head_dim), got {q.shape}")
    n_slots, q_len, hq, d = q.shape
    if k_pages.ndim != 4 or k_pages.shape != v_pages.shape:
        raise ValueError(
            f"k_pages/v_pages must be equal-(n_kv_heads, n_pages, "
            f"page_size, head_dim), got {k_pages.shape}/{v_pages.shape}")
    hkv = k_pages.shape[0]
    if k_pages.shape[3] != d:
        raise ValueError(f"head_dim mismatch: q {d} vs pages "
                         f"{k_pages.shape[3]}")
    if hq % hkv:
        raise ValueError(
            f"n_q_heads={hq} must be a multiple of n_kv_heads={hkv} "
            "(GQA groups)")
    if block_table.ndim != 2 or block_table.shape[0] != n_slots:
        raise ValueError(
            f"block_table must be (n_slots={n_slots}, max_pages), got "
            f"{block_table.shape}")
    if lengths.shape != (n_slots,):
        raise ValueError(
            f"lengths must be ({n_slots},), got {lengths.shape}")
    max_kv = block_table.shape[1] * k_pages.shape[2]
    if q_len > max_kv:
        raise ValueError(
            f"q_len={q_len} exceeds the table's capacity {max_kv}")


def _resolve_heads_per_step(heads_per_step, hkv, page_size):
    """Validated kv-head packing factor.  None → heuristic: the
    largest power-of-two divisor of n_kv_heads keeping the packed
    (hp · page_size) score lanes within one 1024-wide tile class (the
    same vreg-filling rationale as the training kernel's packing).
    Invalid explicit values warn once and degrade to 1 — a stale tuned
    config must never fail a serving step."""
    if heads_per_step is None:
        hp = 1
        while (hkv % (hp * 2) == 0 and (hp * 2) * page_size <= 1024
               and hp * 2 <= 16):
            hp *= 2
        return hp
    hp = int(heads_per_step)
    if hp == 1:
        return 1
    if hp < 1 or hkv % hp:
        key = ("decode_hp", hp, hkv)
        if key not in _HP_FALLBACK_WARNED:
            _HP_FALLBACK_WARNED.add(key)
            reason = ("is not positive" if hp < 1 else
                      f"does not divide n_kv_heads={hkv}")
            warnings.warn(
                f"flash_decode: heads_per_step={hp} {reason}; running "
                "unpacked", stacklevel=4)
        return 1
    return hp


def _tuned_decode_config(n_slots, q_len, hq, hkv, d, page_size, dtype):
    """Trace-time autotuner lookup (apex_tpu.tune): pure host-side
    dict access, None on a miss so an empty cache keeps the
    heuristics.  A hit is sanity-validated (hand-edited caches degrade,
    never crash a serving step)."""
    try:
        from apex_tpu import tune
    except Exception:  # pragma: no cover — tune must never break decode
        return None
    cfg = tune.tuned("flash_decode",
                     tune.decode_attrs(n_slots, q_len, hq, hkv, d,
                                       page_size, dtype))
    if not cfg:
        return None
    hp = cfg.get("heads_per_step", 1)
    if not (isinstance(hp, int) and 1 <= hp <= 16 and hkv % hp == 0):
        key = ("decode_cfg", hkv, d, page_size)
        if key not in _HP_FALLBACK_WARNED:
            _HP_FALLBACK_WARNED.add(key)
            warnings.warn(
                f"flash_decode: ignoring out-of-range tuned config "
                f"{cfg}; using heuristics", stacklevel=4)
        return None
    return cfg


# --------------------------- reference (jnp) path ---------------------------

def paged_attention_reference(q, k_pages, v_pages, block_table, lengths,
                              *, softmax_scale=None):
    """Dense paged-decode oracle: gather every table page, mask by
    position, plain softmax attention in fp32.

    Deliberately spelled with the SAME op sequence as
    flash_attention.attention_reference (einsum → where-mask →
    jax.nn.softmax → einsum → astype) so that at q_len=1 its output is
    BITWISE equal to the training path — `flash_attention` at a
    1-token query resolves to attention_reference on every backend
    (no block divides seq 1), and tests/test_serve.py pins the two
    paths together bit for bit.  Rows with no visible position return
    exact zeros (module contract)."""
    _check_shapes(q, k_pages, v_pages, block_table, lengths)
    n_slots, q_len, hq, d = q.shape
    hkv = k_pages.shape[0]
    G = hq // hkv
    scale = (softmax_scale if softmax_scale is not None
             else 1.0 / math.sqrt(d))
    # (hkv, slots, maxp, page, d) → (slots, hkv, max_kv, d)
    k = k_pages[:, block_table].transpose(1, 0, 2, 3, 4)
    v = v_pages[:, block_table].transpose(1, 0, 2, 3, 4)
    k = k.reshape(n_slots, hkv, -1, d)
    v = v.reshape(n_slots, hkv, -1, d)
    if G > 1:
        k = jnp.repeat(k, G, axis=1)
        v = jnp.repeat(v, G, axis=1)
    qb = q.transpose(0, 2, 1, 3)  # (slots, hq, q_len, d)
    s = jnp.einsum("bhqd,bhkd->bhqk", qb.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    kvpos = jnp.arange(k.shape[2], dtype=jnp.int32)[None, None, None, :]
    vis = (lengths[:, None, None, None].astype(jnp.int32) - q_len + 1
           + jnp.arange(q_len, dtype=jnp.int32)[None, None, :, None])
    s = jnp.where(kvpos >= vis, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    # rows with zero visible positions are exact zeros, not the
    # softmax-of-all-masked uniform average
    o = jnp.where(vis > 0, o, 0.0).astype(q.dtype)
    return o.transpose(0, 2, 1, 3)


# ------------------------------ Pallas kernel -------------------------------

def _decode_kernel(lens_ref, tbl_ref, q_ref, k_ref, v_ref, o_ref,
                   m_scr, l_scr, acc_scr, *, scale, page, rows, q_len,
                   hp, n_blocks):
    """Grid (slot, kv-head group, table entry).  Scores run (hp, rows,
    page): `page` occupies the lane dim (the wide axis — rows are
    G·q_len, usually < 8), stats (hp, rows) share one epilogue across
    the packed heads.  Page blocks at or beyond `lengths[s]` are
    SKIPPED (their DMA still lands — masked by position, so stale or
    recycled page content is harmless)."""
    s = pl.program_id(0)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    length = lens_ref[s]

    @pl.when(t * page < length)
    def _step():
        # per-head matmuls statically unrolled (≡ the training packed
        # kernel): bit-identical per head whatever hp is
        st = jnp.stack([
            lax.dot_general(q_ref[0, p], k_ref[p, 0],
                            (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
            for p in range(hp)]) * scale            # (hp, rows, page)
        kvpos = t * page + lax.broadcasted_iota(
            jnp.int32, (1, rows, page), 2)
        ridx = lax.broadcasted_iota(jnp.int32, (1, rows, page), 1)
        vis = length - q_len + 1 + (ridx % q_len)   # causal in-block
        st = jnp.where(kvpos >= vis, _NEG_INF, st)
        m_prev = m_scr[...]                         # (hp, rows)
        m_new = jnp.maximum(m_prev, jnp.max(st, axis=2))
        p_exp = jnp.exp(st - m_new[:, :, None])
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p_exp, axis=2)
        acc_scr[...] = acc_scr[...] * alpha[:, :, None] + jnp.stack([
            lax.dot_general(p_exp[p].astype(v_ref.dtype), v_ref[p, 0],
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
            for p in range(hp)])                    # (hp, rows, d)
        m_scr[...] = m_new

    @pl.when(t == n_blocks - 1)
    def _epilogue():
        l = jnp.maximum(l_scr[...], 1e-30)          # (hp, rows)
        o = acc_scr[...] / l[:, :, None]
        # zero-visibility rows (inactive slots; q rows before the
        # sequence start) are exact zeros, per the module contract
        ridx = lax.broadcasted_iota(jnp.int32, (hp, rows), 1)
        rvalid = (length - q_len + 1 + (ridx % q_len)) > 0
        o_ref[...] = jnp.where(rvalid[:, :, None], o,
                               0.0).astype(o_ref.dtype)[None]


def _decode_pallas(q, k_pages, v_pages, block_table, lengths, scale, hp):
    n_slots, q_len, hq, d = q.shape
    hkv, _, page, _ = k_pages.shape
    G = hq // hkv
    rows = G * q_len
    max_pages = block_table.shape[1]
    hg = hkv // hp
    # rows grouped per kv head: row r = g·q_len + i (g = in-group q
    # head, i = q position)
    qr = q.transpose(0, 2, 1, 3).reshape(n_slots, hkv, rows, d)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,      # lengths, block_table (SMEM)
        grid=(n_slots, hg, max_pages),
        in_specs=[
            pl.BlockSpec((1, hp, rows, d),
                         lambda s, g, t, lens, tbl: (s, g, 0, 0)),
            # the paged gather: page block_table[s, t] is DMA'd for
            # grid step t — the block index map IS the gather
            pl.BlockSpec((hp, 1, page, d),
                         lambda s, g, t, lens, tbl: (g, tbl[s, t], 0, 0)),
            pl.BlockSpec((hp, 1, page, d),
                         lambda s, g, t, lens, tbl: (g, tbl[s, t], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hp, rows, d),
                               lambda s, g, t, lens, tbl: (s, g, 0, 0)),
        scratch_shapes=[pltpu.VMEM((hp, rows), jnp.float32),
                        pltpu.VMEM((hp, rows), jnp.float32),
                        pltpu.VMEM((hp, rows, d), jnp.float32)],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, scale=scale, page=page,
                          rows=rows, q_len=q_len, hp=hp,
                          n_blocks=max_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((n_slots, hkv, rows, d), q.dtype),
        # the table axis carries the online-softmax recurrence and must
        # stay sequential; slot and head-group own disjoint outputs
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=pallas_interpret(),
    )(lengths.astype(jnp.int32), block_table.astype(jnp.int32),
      qr, k_pages, v_pages)
    return (out.reshape(n_slots, hkv, G, q_len, d)
            .transpose(0, 3, 1, 2, 4).reshape(n_slots, q_len, hq, d))


# --------------------------------- public API -------------------------------

def flash_decode(q, k_pages, v_pages, block_table, lengths, *,
                 softmax_scale: Optional[float] = None,
                 heads_per_step: Optional[int] = None,
                 use_pallas_override: Optional[bool] = None):
    """Single/few-query attention against a paged KV cache.

    See the module docstring for the layout contract.  heads_per_step
    None consults the apex_tpu.tune cache at trace time (key:
    `tune.decode_attrs`) and falls back to the deterministic heuristic
    on a miss — an empty cache is byte-identical to the un-tuned
    kernel.  Inactive slots (lengths == 0) return exact zeros.

    The Pallas path runs on TPU (or under APEX_TPU_FORCE_PALLAS=1 /
    override=True in interpret mode); elsewhere the dense gathered
    reference runs — at q_len=1 that path is bitwise-identical to
    `flash_attention` over the gathered cache (tests/test_serve.py).
    """
    _check_shapes(q, k_pages, v_pages, block_table, lengths)
    n_slots, q_len, hq, d = q.shape
    hkv, _, page, _ = k_pages.shape
    scale = (softmax_scale if softmax_scale is not None
             else 1.0 / math.sqrt(d))
    if not use_pallas(use_pallas_override):
        return paged_attention_reference(
            q, k_pages, v_pages, block_table, lengths,
            softmax_scale=scale)
    if heads_per_step is None:
        cfg = _tuned_decode_config(n_slots, q_len, hq, hkv, d, page,
                                   q.dtype)
        if cfg:
            heads_per_step = cfg.get("heads_per_step")
    hp = _resolve_heads_per_step(heads_per_step, hkv, page)
    return _decode_pallas(q, k_pages, v_pages, block_table, lengths,
                          scale, hp)
