"""Blockwise flash attention — Pallas fwd + bwd, the core attention kernel.

≡ the reference's largest kernel investments combined:
  * fmhalib — fixed-size flash-style fused MHA, seq ≤ 512, sm80/90
    (apex/contrib/csrc/fmha/, 7.0k LoC CUDA)
  * fast_multihead_attn — fused MHA variants w/ cutlass GEMMs + fused
    softmax (apex/contrib/csrc/multihead_attn/, 7.9k LoC CUDA)
re-designed as ONE blockwise kernel with no sequence-length cap: online
softmax (running max/denominator) tiles (bq × bk) score blocks through
VMEM so the (sq × sk) score matrix never reaches HBM.  The backward
recomputes scores blockwise (flash-attention-2 style: dq in one grid,
dk/dv in another) from the saved logsumexp.

The blockwise structure is deliberately ring-friendly: a context-
parallel extension rotates K/V blocks over ICI between the same
per-block inner steps (SURVEY §2.4 CP note).

Layout: (batch, heads, seq, head_dim); head_dim padded to the 128-lane
tile inside the kernel when needed.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import dropout as _dense_dropout
from apex_tpu.ops._common import pallas_interpret, use_pallas

_NEG_INF = -1e30


def _causal_dispatch(step_fn, j, t, bq, bk, causal):
    """Run step_fn(masked) gated on the causal block structure: skip
    blocks above the diagonal entirely; apply mask arithmetic only on
    diagonal-crossing blocks (interior blocks take the unmasked path —
    the per-score iota/compare/select chain is a large share of VPU
    time)."""
    if not causal:
        step_fn(False)
        return
    on_diag = (t * bk + bk - 1) > (j * bq)
    run = (t * bk) <= (j * bq + bq - 1)
    pl.when(run & on_diag)(lambda: step_fn(True))
    pl.when(run & jnp.logical_not(on_diag))(lambda: step_fn(False))


def _causal_mask(st, j, t, bq, bk):
    """Mask scores above the diagonal on a TRANSPOSED (bk, bq) block."""
    krow = t * bk + lax.broadcasted_iota(jnp.int32, (bk, bq), 0)
    qcol = j * bq + lax.broadcasted_iota(jnp.int32, (bk, bq), 1)
    return jnp.where(krow > qcol, _NEG_INF, st)


def _mask_bias(st, j, t, bq, bk, causal_masked, has_bias, bias_ref,
               has_seg, qseg_ref, kseg_ref):
    """Apply (in order) additive bias, segment mask, causal mask to a
    TRANSPOSED (bk, bq) score block.

    ≡ the reference's additive-mask softmax fusion
    (apex/contrib/csrc/multihead_attn/softmax.cuh:27-200 computes
    x*scale + mask in-kernel) and the fmha varlen packing
    (fmha_api.cpp:18-160's cu_seqlens): segment ids are the TPU-native
    varlen — tokens attend only within equal ids, so packed sequences
    and padding cost no cross-attention."""
    if has_bias:
        st = st + bias_ref[0, 0]                        # (bk, bq)
    if has_seg:
        qs = qseg_ref[0, j]                             # (bq,) lanes
        ks = kseg_ref[0, t].reshape(bk, 1)              # (bk, 1) sublanes
        st = jnp.where(ks != qs, _NEG_INF, st)
    if causal_masked:
        st = _causal_mask(st, j, t, bq, bk)
    return st


def _extras_arrays(b, h, sq, sk, nq, bq, nk, bk, bias, q_seg, kv_seg):
    """Host-side packing of the optional bias / segment-id operands.

    bias: broadcastable (nb in {1,b}, nh in {1,h}, sq, sk) — passed to
    the kernels TRANSPOSED as (nb, nh, sk, sq) so score blocks need no
    per-step transpose.  Segment ids: (b, s) int32, reshaped to
    (b, n_blocks, block) whole-row-resident blocks.  Absent operands
    ride as (1,1,1,1)/(1,1,1) dummies (static has_* flags gate every
    kernel read)."""
    if bias is not None:
        nb, nh = bias.shape[0], bias.shape[1]
        # broadcast-1 sq/sk dims expand HERE (inside fwd/bwd impls, not
        # before the custom_vjp) so the VJP residuals keep the caller's
        # compact bias; batch/head broadcasting stays in the index map.
        # NOTE a (.., 1, sk) pad bias still expands to sq*sk transiently
        # — prefer segment_ids for pure padding (no S^2 anything)
        bias_t = jnp.broadcast_to(
            jnp.swapaxes(bias.astype(jnp.float32), 2, 3),
            (nb, nh, sk, sq))
    else:
        nb = nh = 1
        bias_t = jnp.zeros((1, 1, 1, 1), jnp.float32)
    if q_seg is not None:
        qs = q_seg.astype(jnp.int32).reshape(b, nq, bq)
        ks = kv_seg.astype(jnp.int32).reshape(b, nk, bk)
    else:
        qs = jnp.zeros((1, 1, 1), jnp.int32)
        ks = jnp.zeros((1, 1, 1), jnp.int32)
    return bias_t, qs, ks


def _extras_specs(h, nq, bq, nk, bk, has_bias, nb, nh, has_seg, *,
                  jt_from_args):
    """BlockSpecs for (bias_t, q_seg, kv_seg).  `jt_from_args` maps the
    grid args after i to (j, t) — grids differ in block order."""
    if has_bias:
        def bias_idx(i, *rest):
            j, t = jt_from_args(*rest)
            return (i // h if nb > 1 else 0,
                    i % h if nh > 1 else 0, t, j)
        bspec = pl.BlockSpec((1, 1, bk, bq), bias_idx)
    else:
        bspec = pl.BlockSpec((1, 1, 1, 1), lambda i, *_: (0, 0, 0, 0))
    if has_seg:
        qspec = pl.BlockSpec((1, nq, bq), lambda i, *_: (i // h, 0, 0))
        kspec = pl.BlockSpec((1, nk, bk), lambda i, *_: (i // h, 0, 0))
    else:
        qspec = pl.BlockSpec((1, 1, 1), lambda i, *_: (0, 0, 0))
        kspec = pl.BlockSpec((1, 1, 1), lambda i, *_: (0, 0, 0))
    return bspec, qspec, kspec


def _fmix32(h):
    """murmur3 finalizer: full avalanche over int32 lanes."""
    h = h ^ lax.shift_right_logical(h, 16)
    h = h * jnp.int32(-2048144789)        # 0x85ebca6b
    h = h ^ lax.shift_right_logical(h, 13)
    h = h * jnp.int32(-1028477387)        # 0xc2b2ae35
    h = h ^ lax.shift_right_logical(h, 16)
    return h


def _dropout_keep(seed_ref, i, j, t, shape, rate):
    """Deterministic per-score-block keep mask from a COORDINATE hash.

    ≡ the reference FMHA's philox dropout (apex/contrib/csrc/fmha/src/
    fmha/softmax.h): counter-based bits so the BACKWARD kernels
    regenerate the identical mask without storing sq x sk bytes.  The
    bits are a murmur-style hash of (seed, head, GLOBAL score
    coordinates) — a pure function of the element's identity, so any
    kernel (any grid order, any block size, interpret mode included)
    reproduces it exactly.  The hardware PRNG
    (pltpu.prng_random_bits) is NOT usable here: its stream→element
    mapping follows each kernel's codegen, so forward and backward
    kernels with different structure silently disagree (caught by the
    examples/tpu_kernel_smoke.py dropout gate)."""
    bk, bq = shape
    krow = t * bk + lax.broadcasted_iota(jnp.int32, shape, 0)  # k global
    qcol = j * bq + lax.broadcasted_iota(jnp.int32, shape, 1)  # q global
    h = seed_ref[0, 0] * jnp.int32(1000003) + jnp.int32(i)
    v = (h + krow * jnp.int32(-1640531535)       # 0x9e3779b1
         + qcol * jnp.int32(-2048144777))        # 0x85ebca77
    v = _fmix32(v)
    # integer-only compare (Mosaic has no uint32->f32 cast): clear the
    # sign bit for a uniform int32 in [0, 2^31) and threshold against
    # rate * 2^31
    r = v & jnp.int32(0x7FFFFFFF)
    thresh = jnp.int32(int(rate * 2147483648.0))
    return r >= thresh


# --------------------------- reference (jnp) path ---------------------------

def attention_reference(q, k, v, *, causal=False, softmax_scale=None,
                        bias=None, q_segment_ids=None, kv_segment_ids=None,
                        dropout_rate=0.0, dropout_key=None):
    """Plain softmax attention, fp32 accumulation (the parity oracle,
    ≡ the python fallback paths in apex/contrib/multihead_attn).
    Dropout masks the post-softmax attention weights (bernoulli stream —
    a different stream than the kernel's philox, same distribution)."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if q_segment_ids is not None:
        seg = (q_segment_ids[:, None, :, None]
               != kv_segment_ids[:, None, None, :])  # (b, 1, sq, sk)
        s = jnp.where(seg, _NEG_INF, s)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.triu(jnp.ones((sq, sk), bool), k=1)
        s = jnp.where(mask, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    p = _dense_dropout(dropout_key, dropout_rate, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


# ------------------------------ forward kernel ------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref,
                seed_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, nk,
                dropout_rate, has_bias, has_seg):
    """Scores run TRANSPOSED (bk, bq): the softmax statistics (m, l,
    lse) are then (1, bq) lane-major rows — fully-packed vregs instead
    of 1/128-occupied columns, and the lse/delta HBM arrays are
    (bh, nq, bq) with no minor-dim-1 tile padding (a (bh, sq, 1) fp32
    array tiles to 128x its logical size on TPU)."""
    i = pl.program_id(0)
    j = pl.program_id(1)  # q block
    t = pl.program_id(2)  # k block

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _step(masked):
        # native-dtype operands: MXU wants bf16 x bf16 -> fp32; a
        # pre-upcast to fp32 would push the matmul off the MXU
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, has_bias, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        m_prev = m_scr[...]                                     # (1, bq)
        m_new = jnp.maximum(m_prev, jnp.max(st, axis=0, keepdims=True))
        p = jnp.exp(st - m_new)                                 # (bk, bq)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=0, keepdims=True)
        if dropout_rate > 0.0:
            # dropout is linear in p, so masking before the (deferred)
            # 1/l normalization equals dropout(softmax(s)) exactly; the
            # denominator l stays the raw softmax sum
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            p_acc = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
        else:
            p_acc = p
        # acc is kept transposed (d, bq) so alpha/l rows broadcast along
        # lanes; (bk, d)^T-contract (bk, bq) -> (d, bq)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            v_ref[0], p_acc.astype(v_ref.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _epilogue():
        l = jnp.maximum(l_scr[...], 1e-30)                      # (1, bq)
        o_ref[0] = (acc_scr[...] / l).T.astype(o_ref.dtype)
        # lse rides as (1, nq, bq) per-head block; write q-block row j
        lse_ref[0, j] = (m_scr[...] + jnp.log(l)).reshape(bq)


# ------------------------------ backward kernels ----------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   bias_ref, qseg_ref, kseg_ref,
                   seed_ref, dq_ref, dq_scr, *, scale, causal, bq, bk, nk,
                   dropout_rate, has_bias, has_seg):
    i = pl.program_id(0)
    j = pl.program_id(1)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    def _step(masked):
        # transposed scores (bk, bq): lse/delta are (1, bq) lane rows
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, has_bias, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        p = jnp.exp(st - lse_ref[0, j])                         # (bk, bq)
        dp = jax.lax.dot_general(v_ref[0], do_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout_rate))
        ds = p * (dp - delta_ref[0, j])                         # (bk, bq)
        # (bk, bq)^T-contract (bk, d) -> (bq, d)
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _epilogue():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    bias_ref, qseg_ref, kseg_ref,
                    seed_ref, dk_ref, dv_ref, dk_scr, dv_scr, *, scale,
                    causal, bq, bk, nq, dropout_rate, has_bias, has_seg):
    i = pl.program_id(0)
    t = pl.program_id(1)  # k block
    j = pl.program_id(2)  # q block (sequential inner)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _step(masked):
        # transposed scores (bk, bq): lse/delta are (1, bq) lane rows
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, has_bias, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        p = jnp.exp(st - lse_ref[0, j])                 # (bk, bq)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p, 0.0) * inv
        else:
            p_v = p
        dv_scr[...] += jax.lax.dot_general(
            p_v.astype(do_ref.dtype), do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)
        dp = jax.lax.dot_general(v_ref[0], do_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = jnp.where(keep, dp, 0.0) * inv
        ds = p * (dp - delta_ref[0, j])                 # (bk, bq)
        dk_scr[...] += scale * jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(j == nq - 1)
    def _epilogue():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      bias_ref, qseg_ref, kseg_ref,
                      seed_ref, dq_ref, dk_ref, dv_ref,
                      dq_scr, dk_scr, dv_scr, *, scale, causal, bq, bk,
                      nq, nk, dropout_rate, has_bias, has_seg):
    """Single-pass backward: dq, dk, dv from ONE score/exp recompute.

    The two-kernel split recomputes st/p twice (7 matmuls + 2 exp
    chains); this fused grid (bh, q-block, k-block) does 5 matmuls + 1
    exp chain.  dq accumulates per q block over the inner k loop (the
    usual pattern); dk/dv accumulate across the OUTER q loop in a
    full-(sk, d) VMEM scratch, which caps this path at moderate sk —
    _bwd_impl falls back to the two-kernel path beyond that."""
    i = pl.program_id(0)
    j = pl.program_id(1)  # q block (outer)
    t = pl.program_id(2)  # k block (inner)

    @pl.when(t == 0)
    def _init_dq():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when((j == 0) & (t == 0))
    def _init_dkv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _step(masked):
        rows = (pl.ds(t * bk, bk), slice(None))
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, has_bias, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        p = jnp.exp(st - lse_ref[0, j])                 # (bk, bq)
        dp = jax.lax.dot_general(v_ref[0], do_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p, 0.0) * inv
            dp = jnp.where(keep, dp, 0.0) * inv
        else:
            p_v = p
        dv_scr[rows] += jax.lax.dot_general(
            p_v.astype(do_ref.dtype), do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)
        ds = p * (dp - delta_ref[0, j])                 # (bk, bq)
        dk_scr[rows] += scale * jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, d)

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _write_dq():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)

    # dk/dv blocks are flushed to HBM every t step (their block index
    # advances with t); only the final q pass (j == nq-1) leaves the
    # complete sums behind — earlier writes are overwritten
    dk_ref[0] = dk_scr[pl.ds(t * bk, bk), :].astype(dk_ref.dtype)
    dv_ref[0] = dv_scr[pl.ds(t * bk, bk), :].astype(dv_ref.dtype)


# ----------------------------- host-side plumbing ---------------------------

def _pick_block(seq, cap=512):
    for b in (1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= cap and seq % b == 0:
            return b
    return None


def _resolve_blocks(sq, sk, block_q, block_k, has_bias=False):
    """Default blocks, swept on v5e (docs/PERF.md): single block per
    axis when the sequence fits (<=1024 — grid overhead dominates the
    extra causal-mask work), else (512, 1024) to cap the fp32 score
    tile at 2 MB of VMEM while keeping k-side matmuls wide.  Explicit
    blocks must divide the sequence.  A fused bias adds a same-size
    fp32 block, so the q block is halved to stay inside VMEM."""
    if block_q is not None and sq % block_q:
        raise ValueError(f"block_q={block_q} does not divide sq={sq}")
    if block_k is not None and sk % block_k:
        raise ValueError(f"block_k={block_k} does not divide sk={sk}")
    q_cap = 1024 if (sq <= 1024 and not has_bias) else 512
    bq = block_q or _pick_block(sq, cap=q_cap)
    bk = block_k or _pick_block(sk, cap=1024)
    return bq, bk


def _compiler_params(grid_len):
    # first axes (batch*head and the parallel block axis) are
    # order-independent; the innermost axis carries the online-softmax /
    # accumulator recurrence and must stay sequential
    return pltpu.CompilerParams(
        dimension_semantics=("parallel",) * (grid_len - 1) + ("arbitrary",))


def _flatten_bh(x):
    b, h, s, d = x.shape
    return x.reshape(b * h, s, d)


def _fwd_impl(q, k, v, scale, causal, dropout_rate=0.0, seed=None,
              block_q=None, block_k=None, bias=None, q_seg=None,
              kv_seg=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _resolve_blocks(sq, sk, block_q, block_k,
                              has_bias=bias is not None)
    qf, kf, vf = _flatten_bh(q), _flatten_bh(k), _flatten_bh(v)
    bh = b * h
    nq, nk = sq // bq, sk // bk
    if seed is None:
        seed = jnp.zeros((1, 1), jnp.int32)
    has_bias, has_seg = bias is not None, q_seg is not None
    nb = bias.shape[0] if has_bias else 1
    nh = bias.shape[1] if has_bias else 1
    bias_t, qs, ks = _extras_arrays(b, h, sq, sk, nq, bq, nk, bk,
                                    bias, q_seg, kv_seg)
    bspec, qsspec, ksspec = _extras_specs(
        h, nq, bq, nk, bk, has_bias, nb, nh, has_seg,
        jt_from_args=lambda j, t: (j, t))
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, scale=scale, causal=causal, bq=bq,
                          bk=bk, nk=nk, dropout_rate=dropout_rate,
                          has_bias=has_bias, has_seg=has_seg),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, t: (i, t, 0)),
            pl.BlockSpec((1, bk, d), lambda i, j, t: (i, t, 0)),
            bspec, qsspec, ksspec,
            pl.BlockSpec((1, 1), lambda i, j, t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda i, j, t: (i, j, 0)),
            # lse as (bh, nq, bq): one whole-head block resident per i
            # (a (bh, sq, 1) fp32 array would tile-pad to 128x its
            # size; 2-D (1, bq) blocks violate the (8, 128) tile rule)
            pl.BlockSpec((1, nq, bq), lambda i, j, t: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, bq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, bq), jnp.float32),
            pltpu.VMEM((1, bq), jnp.float32),
            pltpu.VMEM((d, bq), jnp.float32),
        ],
        # the q-block axis must stay sequential here: the whole-head lse
        # block is shared across j, and a Megacore split of a "parallel"
        # j would give each core a private copy with half the rows
        # written (last flush wins)
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=pallas_interpret(),
    )(qf, kf, vf, bias_t, qs, ks, seed)
    return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _head_row_spec(nq, bq):
    """Whole-head (1, nq, bq) block for the lse/delta row stats —
    resident across the block loops (index depends only on i, whatever
    the grid order)."""
    return pl.BlockSpec((1, nq, bq), lambda i, *_: (i, 0, 0))


def _bwd_impl(q, k, v, o, lse, do, scale, causal, dropout_rate=0.0,
              seed=None, block_q=None, block_k=None, bias=None,
              q_seg=None, kv_seg=None):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bq, bk = _resolve_blocks(sq, sk, block_q, block_k,
                              has_bias=bias is not None)
    nq, nk = sq // bq, sk // bk
    bh = b * h
    if seed is None:
        seed = jnp.zeros((1, 1), jnp.int32)
    has_bias, has_seg = bias is not None, q_seg is not None
    nb = bias.shape[0] if has_bias else 1
    nh = bias.shape[1] if has_bias else 1
    bias_t, qsegs, ksegs = _extras_arrays(b, h, sq, sk, nq, bq, nk, bk,
                                          bias, q_seg, kv_seg)
    bspec, qsspec, ksspec = _extras_specs(
        h, nq, bq, nk, bk, has_bias, nb, nh, has_seg,
        jt_from_args=lambda j, t: (j, t))
    static = dict(scale=scale, causal=causal, bq=bq, bk=bk,
                  dropout_rate=dropout_rate, has_bias=has_bias,
                  has_seg=has_seg)
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # (b,h,sq)
    args = [_flatten_bh(q), _flatten_bh(k), _flatten_bh(v),
            _flatten_bh(do), lse.reshape(bh, nq, bq),
            delta.reshape(bh, nq, bq), bias_t, qsegs, ksegs, seed]
    qspec = pl.BlockSpec((1, bq, d), lambda i, j, t: (i, j, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda i, j, t: (i, t, 0))
    r1 = _head_row_spec(nq, bq)
    sspec1 = pl.BlockSpec((1, 1), lambda i, j, t: (0, 0))

    # single-pass fused backward while the full-(sk, d) dk/dv scratch
    # fits VMEM comfortably; two-kernel fallback for long context
    if sk * d <= 256 * 1024:
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, nq=nq, nk=nk, **static),
            grid=(bh, nq, nk),
            in_specs=[qspec, kspec, kspec, qspec, r1, r1,
                      bspec, qsspec, ksspec, sspec1],
            out_specs=[qspec, kspec, kspec],
            out_shape=[jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
                       jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                       jax.ShapeDtypeStruct((bh, sk, d), v.dtype)],
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                            pltpu.VMEM((sk, d), jnp.float32),
                            pltpu.VMEM((sk, d), jnp.float32)],
            # dk/dv accumulate across the q-block axis too, so only the
            # leading batch*head axis is order-independent here
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=pallas_interpret(),
        )(*args)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape))

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, nk=nk, **static),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, r1, r1,
                  bspec, qsspec, ksspec, sspec1],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(3),
        interpret=pallas_interpret(),
    )(*args)
    # dkv grid: k blocks outer, q blocks inner-sequential
    qspec2 = pl.BlockSpec((1, bq, d), lambda i, t, j: (i, j, 0))
    kspec2 = pl.BlockSpec((1, bk, d), lambda i, t, j: (i, t, 0))
    r2 = _head_row_spec(nq, bq)
    sspec2 = pl.BlockSpec((1, 1), lambda i, t, j: (0, 0))
    bspec2, qsspec2, ksspec2 = _extras_specs(
        h, nq, bq, nk, bk, has_bias, nb, nh, has_seg,
        jt_from_args=lambda t, j: (j, t))
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, nq=nq, **static),
        grid=(bh, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, r2, r2,
                  bspec2, qsspec2, ksspec2, sspec2],
        out_specs=[kspec2, kspec2],
        out_shape=[jax.ShapeDtypeStruct((bh, sk, d), k.dtype),
                   jax.ShapeDtypeStruct((bh, sk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, d), jnp.float32),
                        pltpu.VMEM((bk, d), jnp.float32)],
        compiler_params=_compiler_params(3),
        interpret=pallas_interpret(),
    )(*args)
    return (dq.reshape(q.shape), dk.reshape(k.shape), dv.reshape(v.shape))


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10))
def _flash(q, k, v, bias, q_seg, kv_seg, scale, causal, dropout_rate,
           block_q, block_k, seed):
    o, _ = _fwd_impl(q, k, v, scale, causal, dropout_rate, seed,
                     block_q, block_k, bias, q_seg, kv_seg)
    return o


def _flash_fwd(q, k, v, bias, q_seg, kv_seg, scale, causal, dropout_rate,
               block_q, block_k, seed):
    o, lse = _fwd_impl(q, k, v, scale, causal, dropout_rate, seed,
                       block_q, block_k, bias, q_seg, kv_seg)
    return o, (q, k, v, bias, q_seg, kv_seg, o, lse, seed)


def _flash_bwd(scale, causal, dropout_rate, block_q, block_k, res, do):
    q, k, v, bias, q_seg, kv_seg, o, lse, seed = res
    dq, dk, dv = _bwd_impl(q, k, v, o, lse, do, scale, causal,
                           dropout_rate, seed, block_q, block_k,
                           bias, q_seg, kv_seg)
    import numpy as _np

    def _int_zero(x):
        return (None if x is None
                else _np.zeros(x.shape, dtype=jax.dtypes.float0))
    # bias is treated as a CONSTANT (padding masks, fixed position
    # biases): its cotangent is zero by contract — see flash_attention's
    # docstring
    dbias = None if bias is None else jnp.zeros_like(bias)
    return (dq, dk, dv, dbias, _int_zero(q_seg), _int_zero(kv_seg),
            _int_zero(seed))


_flash.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------- public API -------------------------------

def flash_attention(q, k, v, *, causal: bool = False,
                    softmax_scale: Optional[float] = None,
                    bias=None,
                    segment_ids=None,
                    q_segment_ids=None,
                    kv_segment_ids=None,
                    dropout_rate: float = 0.0,
                    dropout_key=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    use_pallas_override: Optional[bool] = None):
    """Flash attention over (batch, heads, seq, head_dim).

    ≡ apex.contrib.fmha.FMHAFun (apex/contrib/fmha/fmha.py:33-72) with
    the seq≤512/head-64 restriction removed, and the core of the
    fast_multihead_attn variants (self/encdec attention cores).
    Attention dropout runs IN-kernel with a counter-based mask
    regenerated in backward (≡ the reference's philox dropout,
    fmha/src/fmha/softmax.h) — no sq x sk mask ever reaches HBM, so
    dropout works at any sequence length.

    bias: additive fp score bias, shape (b|1, h|1, sq, sk), fused into
    the kernel (≡ the additive-mask softmax in
    apex/contrib/csrc/multihead_attn/softmax.cuh:27-200).  It is
    treated as a CONSTANT — its cotangent is defined as zero — which
    covers padding masks, ALiBi slopes, and fixed relative-position
    biases; a *trainable* bias must go through the dense reference
    path.

    segment_ids: (b, s) int — tokens attend only where ids are equal;
    this is the TPU-native form of the reference fmha's cu_seqlens
    varlen packing (fmha_api.cpp:18-160): pack multiple sequences into
    one row with distinct ids and padded tokens cost no attention.
    q_segment_ids/kv_segment_ids set the two sides separately (encdec
    or kv-cache shapes); fully-masked query rows produce a uniform
    attention over kv (like the dense oracle) — mask them in the loss.
    """
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError("dropout_rate > 0 requires dropout_key")
    if segment_ids is not None:
        if q_segment_ids is not None or kv_segment_ids is not None:
            raise ValueError(
                "pass either segment_ids or q_/kv_segment_ids, not both")
        q_segment_ids = kv_segment_ids = segment_ids
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    b, h = q.shape[0], q.shape[1]
    sq, sk = q.shape[2], k.shape[2]
    if bias is not None:
        eb, eh = bias.shape[0], bias.shape[1]
        if (bias.ndim != 4 or eb not in (1, b) or eh not in (1, h)
                or bias.shape[2] not in (1, sq)
                or bias.shape[3] not in (1, sk)):
            raise ValueError(
                f"bias shape {bias.shape} not broadcastable to "
                f"({b}|1, {h}|1, {sq}|1, {sk}|1)")
    if q_segment_ids is not None:
        q_segment_ids = jnp.asarray(q_segment_ids, jnp.int32)
        kv_segment_ids = jnp.asarray(kv_segment_ids, jnp.int32)
        if q_segment_ids.shape != (b, sq) or kv_segment_ids.shape != (b, sk):
            raise ValueError(
                f"segment id shapes {q_segment_ids.shape}/"
                f"{kv_segment_ids.shape} != ({b}, {sq})/({b}, {sk})")
    # in-kernel dropout is a pure coordinate hash — it runs (and gives
    # bit-identical masks) in interpret mode too, so CPU CI covers it
    kernel_ok = (use_pallas(use_pallas_override)
                 and _pick_block(q.shape[2]) and _pick_block(k.shape[2]))
    if kernel_ok:
        if dropout_rate > 0.0:
            seed = jax.random.randint(dropout_key, (1, 1), -2**31, 2**31 - 1,
                                      dtype=jnp.int32)
        else:
            seed = jnp.zeros((1, 1), jnp.int32)
        return _flash(q, k, v, bias, q_segment_ids, kv_segment_ids,
                      scale, causal, float(dropout_rate),
                      block_q, block_k, seed)
    # stop_gradient keeps the zero-dbias contract identical to the
    # kernel path — a trainable bias must call attention_reference
    # directly, on every backend
    return attention_reference(q, k, v, causal=causal, softmax_scale=scale,
                               bias=(None if bias is None
                                     else lax.stop_gradient(bias)),
                               q_segment_ids=q_segment_ids,
                               kv_segment_ids=kv_segment_ids,
                               dropout_rate=dropout_rate,
                               dropout_key=dropout_key)
