"""Blockwise flash attention — Pallas fwd + bwd, the core attention kernel.

≡ the reference's largest kernel investments combined:
  * fmhalib — fixed-size flash-style fused MHA, seq ≤ 512, sm80/90
    (apex/contrib/csrc/fmha/, 7.0k LoC CUDA)
  * fast_multihead_attn — fused MHA variants w/ cutlass GEMMs + fused
    softmax (apex/contrib/csrc/multihead_attn/, 7.9k LoC CUDA)
re-designed as ONE blockwise kernel with no sequence-length cap: online
softmax (running max/denominator) tiles (bq × bk) score blocks through
VMEM so the (sq × sk) score matrix never reaches HBM.  The backward
recomputes scores blockwise (flash-attention-2 style: dq in one grid,
dk/dv in another) from the saved logsumexp.

The blockwise structure is deliberately ring-friendly: a context-
parallel extension rotates K/V blocks over ICI between the same
per-block inner steps (SURVEY §2.4 CP note).

Layout: (batch, heads, seq, head_dim); head_dim padded to the 128-lane
tile inside the kernel when needed.
"""

from __future__ import annotations

import functools
import math
import warnings
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import dropout as _dense_dropout
from apex_tpu.ops._common import pallas_interpret, use_pallas

_NEG_INF = -1e30
# fused single-pass backward cap: full-(sk, d) dk/dv scratch must fit
# VMEM (tests monkeypatch this to force the two-kernel path at small sizes)
_FUSED_BWD_CAP = 256 * 1024
# head-packed fused backward: TOTAL (hp, sk, d) scratch cap — two fp32
# scratches at this size are 4 MB of VMEM; beyond it the backward drops
# to hp=1 (fused or two-kernel as before)
_FUSED_BWD_CAP_PACKED = 512 * 1024


def _causal_dispatch(step_fn, j, t, bq, bk, causal):
    """Run step_fn(masked) gated on the causal block structure: skip
    blocks above the diagonal entirely; apply mask arithmetic only on
    diagonal-crossing blocks (interior blocks take the unmasked path —
    the per-score iota/compare/select chain is a large share of VPU
    time)."""
    if not causal:
        step_fn(False)
        return
    on_diag = (t * bk + bk - 1) > (j * bq)
    run = (t * bk) <= (j * bq + bq - 1)
    pl.when(run & on_diag)(lambda: step_fn(True))
    pl.when(run & jnp.logical_not(on_diag))(lambda: step_fn(False))


def _causal_mask(st, j, t, bq, bk):
    """Mask scores above the diagonal on a TRANSPOSED (bk, bq) block."""
    krow = t * bk + lax.broadcasted_iota(jnp.int32, (bk, bq), 0)
    qcol = j * bq + lax.broadcasted_iota(jnp.int32, (bk, bq), 1)
    return jnp.where(krow > qcol, _NEG_INF, st)


def _mask_bias(st, j, t, bq, bk, causal_masked, bias_kind, bias_ref,
               has_seg, qseg_ref, kseg_ref):
    """Apply (in order) additive bias, segment mask, causal mask to a
    TRANSPOSED (bk, bq) score block.

    ≡ the reference's additive-mask softmax fusion
    (apex/contrib/csrc/multihead_attn/softmax.cuh:27-200 computes
    x*scale + mask in-kernel) and the fmha varlen packing
    (fmha_api.cpp:18-160's cu_seqlens): segment ids are the TPU-native
    varlen — tokens attend only within equal ids, so packed sequences
    and padding cost no cross-attention.

    bias_kind: "none" | "full" (a transposed (bk, bq) block of a
    (.., sq, sk) bias) | "sk" (a (.., 1, sk) key-compact bias riding as
    a (bk,) row — padding masks / ALiBi never expand to S² in HBM)."""
    if bias_kind == "full":
        st = st + bias_ref[0, 0]                        # (bk, bq)
    elif bias_kind == "sk":
        st = st + bias_ref[0, 0, 0].reshape(bk, 1)      # k-varying row
    if has_seg:
        qs = qseg_ref[0, j]                             # (bq,) lanes
        ks = kseg_ref[0, t].reshape(bk, 1)              # (bk, 1) sublanes
        st = jnp.where(ks != qs, _NEG_INF, st)
    if causal_masked:
        st = _causal_mask(st, j, t, bq, bk)
    return st


def _bias_kind(bias, sk):
    """Static bias classification.  "sk" = key-compact (.., 1, sk):
    rides compact through the kernels (no S² expansion in HBM — the
    padding-mask / ALiBi case).  "none" also covers query-compact
    (.., *, 1) biases: a per-query score constant cancels exactly in
    softmax (finite values — whole-row masking must use segment ids),
    so the kernels skip it entirely instead of expanding it to S².
    Everything else is "full" (.., sq, sk)."""
    if bias is None:
        return "none"
    if bias.shape[3] == 1:
        return "none"
    if bias.shape[2] == 1 and bias.shape[3] == sk:
        return "sk"
    return "full"


def _extras_arrays(b, h, sq, sk, nq, bq, nk, bk, bias, q_seg, kv_seg,
                   bias_kind="none"):
    """Host-side packing of the optional bias / segment-id operands.

    bias: broadcastable (nb in {1,b}, nh in {1,h}, sq, sk) — "full"
    biases pass to the kernels TRANSPOSED as (nb, nh, sk, sq) so score
    blocks need no per-step transpose; "sk" key-compact biases stay
    (nb, nh, 1, sk) — never expanded.  Segment ids: (b, s) int32,
    reshaped to (b, n_blocks, block) whole-row-resident blocks.  Absent
    operands ride as (1,1,1,1)/(1,1,1) dummies (static kind flags gate
    every kernel read)."""
    if bias_kind == "sk":
        nb, nh = bias.shape[0], bias.shape[1]
        bias_t = bias.astype(jnp.float32)               # (nb, nh, 1, sk)
    elif bias_kind == "full":
        nb, nh = bias.shape[0], bias.shape[1]
        # broadcast-1 sq dims expand HERE (inside fwd/bwd impls, not
        # before the custom_vjp) so the VJP residuals keep the caller's
        # compact bias; batch/head broadcasting stays in the index map
        bias_t = jnp.broadcast_to(
            jnp.swapaxes(bias.astype(jnp.float32), 2, 3),
            (nb, nh, sk, sq))
    else:
        nb = nh = 1
        bias_t = jnp.zeros((1, 1, 1, 1), jnp.float32)
    if q_seg is not None:
        qs = q_seg.astype(jnp.int32).reshape(b, nq, bq)
        ks = kv_seg.astype(jnp.int32).reshape(b, nk, bk)
    else:
        qs = jnp.zeros((1, 1, 1), jnp.int32)
        ks = jnp.zeros((1, 1, 1), jnp.int32)
    return bias_t, qs, ks


def _extras_specs(h, nq, bq, nk, bk, bias_kind, nb, nh, has_seg, *,
                  jt_from_args, hp=1):
    """BlockSpecs for (bias_t, q_seg, kv_seg).  `jt_from_args` maps the
    grid args after i to (j, t) — grids differ in block order.

    With head packing (hp > 1, hp | h) grid axis 0 indexes GROUPS of hp
    consecutive heads: i = batch * (h/hp) + head_group, so the batch
    index becomes i // (h/hp) and a per-head ("full"/"sk" with nh > 1)
    bias rides as an hp-tall head block.  At hp == 1 every map below is
    exactly the unpacked one."""
    hg = h // hp   # head groups per batch (grid-axis-0 stride)
    if bias_kind == "full":
        def bias_idx(i, *rest):
            j, t = jt_from_args(*rest)
            return (i // hg if nb > 1 else 0,
                    i % hg if nh > 1 else 0, t, j)
        bspec = pl.BlockSpec((1, hp if nh > 1 else 1, bk, bq), bias_idx)
    elif bias_kind == "sk":
        def bias_idx(i, *rest):
            j, t = jt_from_args(*rest)
            return (i // hg if nb > 1 else 0,
                    i % hg if nh > 1 else 0, 0, t)
        bspec = pl.BlockSpec((1, hp if nh > 1 else 1, 1, bk), bias_idx)
    else:
        bspec = pl.BlockSpec((1, 1, 1, 1), lambda i, *_: (0, 0, 0, 0))
    if has_seg:
        qspec = pl.BlockSpec((1, nq, bq), lambda i, *_: (i // hg, 0, 0))
        kspec = pl.BlockSpec((1, nk, bk), lambda i, *_: (i // hg, 0, 0))
    else:
        qspec = pl.BlockSpec((1, 1, 1), lambda i, *_: (0, 0, 0))
        kspec = pl.BlockSpec((1, 1, 1), lambda i, *_: (0, 0, 0))
    return bspec, qspec, kspec


def _fmix32(h):
    """murmur3 finalizer: full avalanche over int32 lanes."""
    h = h ^ lax.shift_right_logical(h, 16)
    h = h * jnp.int32(-2048144789)        # 0x85ebca6b
    h = h ^ lax.shift_right_logical(h, 13)
    h = h * jnp.int32(-1028477387)        # 0xc2b2ae35
    h = h ^ lax.shift_right_logical(h, 16)
    return h


def _dropout_keep(seed_ref, i, j, t, shape, rate):
    """Deterministic per-score-block keep mask from a COORDINATE hash.

    ≡ the reference FMHA's philox dropout (apex/contrib/csrc/fmha/src/
    fmha/softmax.h): counter-based bits so the BACKWARD kernels
    regenerate the identical mask without storing sq x sk bytes.  The
    bits are a murmur-style hash of (seed, head, GLOBAL score
    coordinates) — a pure function of the element's identity, so any
    kernel (any grid order, any block size, interpret mode included)
    reproduces it exactly.  seed_ref rows 1 and 2 carry the chunk's
    global (q, k) sequence offsets: a ring-attention chunk covering
    global rows [q_off, q_off+s) x [k_off, k_off+s) generates the SAME
    bits as single-chip attention over the gathered sequence, so
    dropout composes across ring steps (fwd and bwd see one mask).
    The hardware PRNG (pltpu.prng_random_bits) is NOT usable here: its
    stream→element mapping follows each kernel's codegen, so forward
    and backward kernels with different structure silently disagree
    (caught by the examples/tpu_kernel_smoke.py dropout gate)."""
    bk, bq = shape
    krow = (seed_ref[2, 0] + t * bk
            + lax.broadcasted_iota(jnp.int32, shape, 0))  # k global
    qcol = (seed_ref[1, 0] + j * bq
            + lax.broadcasted_iota(jnp.int32, shape, 1))  # q global
    h = seed_ref[0, 0] * jnp.int32(1000003) + jnp.int32(i)
    v = (h + krow * jnp.int32(-1640531535)       # 0x9e3779b1
         + qcol * jnp.int32(-2048144777))        # 0x85ebca77
    v = _fmix32(v)
    # integer-only compare (Mosaic has no uint32->f32 cast): clear the
    # sign bit for a uniform int32 in [0, 2^31) and threshold against
    # rate * 2^31
    r = v & jnp.int32(0x7FFFFFFF)
    thresh = jnp.int32(int(rate * 2147483648.0))
    return r >= thresh


def _seed3(seed, q_off=0, k_off=0):
    """(3, 1) int32 seed operand: [seed, global q offset, global k
    offset].  Accepts None, scalars, or legacy (1, 1) seed arrays;
    offsets may be traced (ring steps pass rank/src-dependent values)."""
    if seed is None:
        seed = jnp.zeros((), jnp.int32)
    seed = jnp.asarray(seed, jnp.int32).reshape(-1)[:1]
    return jnp.stack([seed[0], jnp.asarray(q_off, jnp.int32),
                      jnp.asarray(k_off, jnp.int32)]).reshape(3, 1)


def dropout_keep_dense(seed, b, h, sq, sk, rate, q_off=0, k_off=0):
    """Dense (b, h, sq, sk) keep mask — the SAME bits as the in-kernel
    hash (i = flattened batch*head index), for the jnp blockwise paths
    and parity tests."""
    seed = jnp.asarray(seed, jnp.int32).reshape(-1)[:1][0]
    i = jnp.arange(b * h, dtype=jnp.int32).reshape(b, h, 1, 1)
    qcol = (jnp.asarray(q_off, jnp.int32)
            + jnp.arange(sq, dtype=jnp.int32)).reshape(1, 1, sq, 1)
    krow = (jnp.asarray(k_off, jnp.int32)
            + jnp.arange(sk, dtype=jnp.int32)).reshape(1, 1, 1, sk)
    v = (seed * jnp.int32(1000003) + i
         + krow * jnp.int32(-1640531535)
         + qcol * jnp.int32(-2048144777))
    v = _fmix32(v)
    r = v & jnp.int32(0x7FFFFFFF)
    thresh = jnp.int32(int(rate * 2147483648.0))
    return r >= thresh


# --------------------------- reference (jnp) path ---------------------------

def attention_reference(q, k, v, *, causal=False, softmax_scale=None,
                        bias=None, q_segment_ids=None, kv_segment_ids=None,
                        dropout_rate=0.0, dropout_key=None):
    """Plain softmax attention, fp32 accumulation (the parity oracle,
    ≡ the python fallback paths in apex/contrib/multihead_attn).
    Dropout masks the post-softmax attention weights (bernoulli stream —
    a different stream than the kernel's philox, same distribution)."""
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if q_segment_ids is not None:
        seg = (q_segment_ids[:, None, :, None]
               != kv_segment_ids[:, None, None, :])  # (b, 1, sq, sk)
        s = jnp.where(seg, _NEG_INF, s)
    if causal:
        sq, sk = s.shape[-2], s.shape[-1]
        mask = jnp.triu(jnp.ones((sq, sk), bool), k=1)
        s = jnp.where(mask, _NEG_INF, s)
    p = jax.nn.softmax(s, axis=-1)
    p = _dense_dropout(dropout_key, dropout_rate, p)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
                      ).astype(q.dtype)


# ------------------------------ forward kernel ------------------------------

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref,
                seed_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, bq, bk, nk,
                dropout_rate, bias_kind, has_seg):
    """Scores run TRANSPOSED (bk, bq): the softmax statistics (m, l,
    lse) are then (1, bq) lane-major rows — fully-packed vregs instead
    of 1/128-occupied columns, and the lse/delta HBM arrays are
    (bh, nq, bq) with no minor-dim-1 tile padding (a (bh, sq, 1) fp32
    array tiles to 128x its logical size on TPU)."""
    i = pl.program_id(0)
    j = pl.program_id(1)  # q block
    t = pl.program_id(2)  # k block

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _step(masked):
        # native-dtype operands: MXU wants bf16 x bf16 -> fp32; a
        # pre-upcast to fp32 would push the matmul off the MXU
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, bias_kind, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        m_prev = m_scr[...]                                     # (1, bq)
        m_new = jnp.maximum(m_prev, jnp.max(st, axis=0, keepdims=True))
        p = jnp.exp(st - m_new)                                 # (bk, bq)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=0, keepdims=True)
        if dropout_rate > 0.0:
            # dropout is linear in p, so masking before the (deferred)
            # 1/l normalization equals dropout(softmax(s)) exactly; the
            # denominator l stays the raw softmax sum
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            p_acc = jnp.where(keep, p, 0.0) * (1.0 / (1.0 - dropout_rate))
        else:
            p_acc = p
        # acc is kept transposed (d, bq) so alpha/l rows broadcast along
        # lanes; (bk, d)^T-contract (bk, bq) -> (d, bq)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            v_ref[0], p_acc.astype(v_ref.dtype), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _epilogue():
        l = jnp.maximum(l_scr[...], 1e-30)                      # (1, bq)
        o_ref[0] = (acc_scr[...] / l).T.astype(o_ref.dtype)
        # lse rides as (1, nq, bq) per-head block; write q-block row j
        lse_ref[0, j] = (m_scr[...] + jnp.log(l)).reshape(bq)


# ----------------------- head-packed forward kernel -------------------------
#
# d=64 heads half-fill the 128-deep MXU contraction port, and the
# per-step softmax/rescale epilogue runs on (1, bq) stat rows that
# occupy one sublane of an 8-sublane fp32 vreg.  Packing hp heads per
# grid step (grid axis 0 over head GROUPS) attacks both overheads: the
# K/V/Q DMAs move hp-head slabs, the grid runs 1/hp the steps, and the
# online-softmax statistics become (hp, bq) blocks whose max/exp/
# rescale chains fill the vregs across heads — one shared epilogue for
# the whole group.  The per-head matmuls stay separate (a d=64
# contraction is a hardware fact no packing changes — docs/PERF.md
# roofline scores against the shape-achievable mix), executed as a
# static unrolled loop so numerics are bit-identical to the unpacked
# kernel per head.


def _mask_bias_packed(st, j, t, bq, bk, hp, causal_masked, bias_kind,
                      bias_ref, bias_per_head, has_seg, qseg_ref,
                      kseg_ref):
    """_mask_bias over an (hp, bk, bq) stacked score block.  Bias blocks
    are (1, hp, bk, bq) when per-head (nh > 1) else (1, 1, bk, bq)
    broadcast; segment ids and the causal mask depend only on (j, t) so
    one (bk, bq) mask broadcasts across the packed heads."""
    if bias_kind == "full":
        st = st + bias_ref[0]                       # (hp|1, bk, bq)
    elif bias_kind == "sk":
        nh_blk = hp if bias_per_head else 1
        st = st + bias_ref[0, :, 0].reshape(nh_blk, bk, 1)
    if has_seg:
        qs = qseg_ref[0, j]                         # (bq,) lanes
        ks = kseg_ref[0, t].reshape(1, bk, 1)
        st = jnp.where(ks != qs, _NEG_INF, st)
    if causal_masked:
        krow = t * bk + lax.broadcasted_iota(jnp.int32, (1, bk, bq), 1)
        qcol = j * bq + lax.broadcasted_iota(jnp.int32, (1, bk, bq), 2)
        st = jnp.where(krow > qcol, _NEG_INF, st)
    return st


def _fwd_kernel_packed(q_ref, k_ref, v_ref, bias_ref, qseg_ref, kseg_ref,
                       seed_ref, o_ref, lse_ref,
                       m_scr, l_scr, acc_scr, *, scale, causal, bq, bk,
                       nk, hp, dropout_rate, bias_kind, bias_per_head,
                       has_seg):
    """_fwd_kernel over hp packed heads: scores stack to (hp, bk, bq),
    stats/lse are (hp, bq) lane-major blocks, the accumulator is
    (hp, d, bq).  Per-head math is identical to the unpacked kernel —
    the packing only batches it."""
    i = pl.program_id(0)  # batch * head-group
    j = pl.program_id(1)  # q block
    t = pl.program_id(2)  # k block

    @pl.when(t == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    def _step(masked):
        st = jnp.stack([
            jax.lax.dot_general(k_ref[p], q_ref[p],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for p in range(hp)]) * scale            # (hp, bk, bq)
        st = _mask_bias_packed(st, j, t, bq, bk, hp, masked, bias_kind,
                               bias_ref, bias_per_head, has_seg,
                               qseg_ref, kseg_ref)
        m_prev = m_scr[...]                         # (hp, bq)
        m_new = jnp.maximum(m_prev, jnp.max(st, axis=1))
        p_exp = jnp.exp(st - m_new[:, None, :])     # (hp, bk, bq)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p_exp, axis=1)
        if dropout_rate > 0.0:
            # per-head coordinate hash with the FLAT batch*head index
            # i*hp + p — bit-identical to the unpacked kernel's mask
            keep = jnp.stack([
                _dropout_keep(seed_ref, i * hp + p, j, t, (bk, bq),
                              dropout_rate) for p in range(hp)])
            p_acc = jnp.where(keep, p_exp, 0.0) * (
                1.0 / (1.0 - dropout_rate))
        else:
            p_acc = p_exp
        acc_scr[...] = acc_scr[...] * alpha[:, None, :] + jnp.stack([
            jax.lax.dot_general(v_ref[p], p_acc[p].astype(v_ref.dtype),
                                (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for p in range(hp)])                    # (hp, d, bq)
        m_scr[...] = m_new

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _epilogue():
        l = jnp.maximum(l_scr[...], 1e-30)          # (hp, bq)
        o_ref[...] = jnp.swapaxes(acc_scr[...] / l[:, None, :],
                                  1, 2).astype(o_ref.dtype)
        lse_ref[:, j] = m_scr[...] + jnp.log(l)


# ------------------------------ backward kernels ----------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   bias_ref, qseg_ref, kseg_ref,
                   seed_ref, dq_ref, *rest, scale, causal, bq, bk, nk,
                   dropout_rate, bias_kind, has_seg, want_dbias=False):
    if want_dbias:          # "full"-bias grad: ds IS the dbias block
        db_ref, dq_scr = rest
    else:
        db_ref, (dq_scr,) = None, rest
    i = pl.program_id(0)
    j = pl.program_id(1)
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if want_dbias:
        # causal-skipped blocks never run _step: zero first, overwrite
        # in-step (same VMEM-resident block, ordered within this step)
        db_ref[0] = jnp.zeros_like(db_ref[0])

    def _step(masked):
        # transposed scores (bk, bq): lse/delta are (1, bq) lane rows
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, bias_kind, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        p = jnp.exp(st - lse_ref[0, j])                         # (bk, bq)
        dp = jax.lax.dot_general(v_ref[0], do_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            dp = jnp.where(keep, dp, 0.0) * (1.0 / (1.0 - dropout_rate))
        ds = p * (dp - delta_ref[0, j])                         # (bk, bq)
        if want_dbias:
            db_ref[0] = ds
        # (bk, bq)^T-contract (bk, d) -> (bq, d)
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _epilogue():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    bias_ref, qseg_ref, kseg_ref,
                    seed_ref, dk_ref, dv_ref, *rest, scale,
                    causal, bq, bk, nq, dropout_rate, bias_kind, has_seg,
                    want_dbias=False):
    if want_dbias:          # "sk"-bias grad: q-summed ds rows
        db_ref, dk_scr, dv_scr, dbr_scr = rest
    else:
        db_ref = dbr_scr = None
        dk_scr, dv_scr = rest
    i = pl.program_id(0)
    t = pl.program_id(1)  # k block
    j = pl.program_id(2)  # q block (sequential inner)

    @pl.when(j == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)
        if want_dbias:
            dbr_scr[...] = jnp.zeros_like(dbr_scr)

    def _step(masked):
        # transposed scores (bk, bq): lse/delta are (1, bq) lane rows
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, bias_kind, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        p = jnp.exp(st - lse_ref[0, j])                 # (bk, bq)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p, 0.0) * inv
        else:
            p_v = p
        dv_scr[...] += jax.lax.dot_general(
            p_v.astype(do_ref.dtype), do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)
        dp = jax.lax.dot_general(v_ref[0], do_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = jnp.where(keep, dp, 0.0) * inv
        ds = p * (dp - delta_ref[0, j])                 # (bk, bq)
        if want_dbias:
            # q-sum of ds as a LANE-major (1, bk) row via the MXU
            # (ones-contract) — no sublane→lane relayout
            dbr_scr[...] += jax.lax.dot_general(
                jnp.ones((1, bq), jnp.float32), ds,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)     # (1, bk)
        dk_scr[...] += scale * jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(j == nq - 1)
    def _epilogue():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)
        if want_dbias:
            # db rides as (1, nk, bk) whole-head rows (≡ the lse layout
            # trick): write k-block row t
            db_ref[0, t] = dbr_scr[...].reshape(bk)


def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      bias_ref, qseg_ref, kseg_ref,
                      seed_ref, dq_ref, dk_ref, dv_ref, *rest,
                      scale, causal, bq, bk,
                      nq, nk, dropout_rate, bias_kind, has_seg,
                      want_dbias=False):
    """Single-pass backward: dq, dk, dv from ONE score/exp recompute.

    The two-kernel split recomputes st/p twice (7 matmuls + 2 exp
    chains); this fused grid (bh, q-block, k-block) does 5 matmuls + 1
    exp chain.  dq accumulates per q block over the inner k loop (the
    usual pattern); dk/dv accumulate across the OUTER q loop in a
    full-(sk, d) VMEM scratch, which caps this path at moderate sk —
    _bwd_impl falls back to the two-kernel path beyond that."""
    if want_dbias:          # "full"-bias grad: ds IS the dbias block
        db_ref, dq_scr, dk_scr, dv_scr = rest
    else:
        db_ref = None
        dq_scr, dk_scr, dv_scr = rest
    i = pl.program_id(0)
    j = pl.program_id(1)  # q block (outer)
    t = pl.program_id(2)  # k block (inner)

    @pl.when(t == 0)
    def _init_dq():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    if want_dbias:
        db_ref[0] = jnp.zeros_like(db_ref[0])

    @pl.when((j == 0) & (t == 0))
    def _init_dkv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _step(masked):
        rows = (pl.ds(t * bk, bk), slice(None))
        st = jax.lax.dot_general(k_ref[0], q_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32) * scale
        st = _mask_bias(st, j, t, bq, bk, masked, bias_kind, bias_ref,
                        has_seg, qseg_ref, kseg_ref)
        p = jnp.exp(st - lse_ref[0, j])                 # (bk, bq)
        dp = jax.lax.dot_general(v_ref[0], do_ref[0],
                                 (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            keep = _dropout_keep(seed_ref, i, j, t, (bk, bq), dropout_rate)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p, 0.0) * inv
            dp = jnp.where(keep, dp, 0.0) * inv
        else:
            p_v = p
        dv_scr[rows] += jax.lax.dot_general(
            p_v.astype(do_ref.dtype), do_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)
        ds = p * (dp - delta_ref[0, j])                 # (bk, bq)
        if want_dbias:
            db_ref[0] = ds
        dk_scr[rows] += scale * jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bk, d)
        dq_scr[...] += scale * jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0], (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)         # (bq, d)

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _write_dq():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)

    # dk/dv blocks are flushed to HBM every t step (their block index
    # advances with t); only the final q pass (j == nq-1) leaves the
    # complete sums behind — earlier writes are overwritten
    dk_ref[0] = dk_scr[pl.ds(t * bk, bk), :].astype(dk_ref.dtype)
    dv_ref[0] = dv_scr[pl.ds(t * bk, bk), :].astype(dv_ref.dtype)


def _bwd_fused_kernel_packed(q_ref, k_ref, v_ref, do_ref, lse_ref,
                             delta_ref, bias_ref, qseg_ref, kseg_ref,
                             seed_ref, dq_ref, dk_ref, dv_ref,
                             dq_scr, dk_scr, dv_scr, *, scale, causal,
                             bq, bk, nq, nk, hp, dropout_rate,
                             bias_kind, bias_per_head, has_seg):
    """_bwd_fused_kernel over hp packed heads (no dbias — _bwd_impl
    drops to the unpacked kernels when a bias gradient is wanted).
    dq accumulates per (group, q block); dk/dv accumulate across the
    outer q loop in (hp, sk, d) VMEM scratch — the packed VMEM cap is
    checked host-side (_FUSED_BWD_CAP_PACKED)."""
    i = pl.program_id(0)  # batch * head-group
    j = pl.program_id(1)  # q block (outer)
    t = pl.program_id(2)  # k block (inner)

    @pl.when(t == 0)
    def _init_dq():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    @pl.when((j == 0) & (t == 0))
    def _init_dkv():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    def _step(masked):
        rows = (slice(None), pl.ds(t * bk, bk), slice(None))
        st = jnp.stack([
            jax.lax.dot_general(k_ref[p], q_ref[p],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for p in range(hp)]) * scale            # (hp, bk, bq)
        st = _mask_bias_packed(st, j, t, bq, bk, hp, masked, bias_kind,
                               bias_ref, bias_per_head, has_seg,
                               qseg_ref, kseg_ref)
        p_exp = jnp.exp(st - lse_ref[:, j][:, None, :])
        dp = jnp.stack([
            jax.lax.dot_general(v_ref[p], do_ref[p],
                                (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for p in range(hp)])                    # (hp, bk, bq)
        if dropout_rate > 0.0:
            keep = jnp.stack([
                _dropout_keep(seed_ref, i * hp + p, j, t, (bk, bq),
                              dropout_rate) for p in range(hp)])
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p_exp, 0.0) * inv
            dp = jnp.where(keep, dp, 0.0) * inv
        else:
            p_v = p_exp
        dv_scr[rows] += jnp.stack([
            jax.lax.dot_general(p_v[p].astype(do_ref.dtype), do_ref[p],
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for p in range(hp)])                    # (hp, bk, d)
        ds = p_exp * (dp - delta_ref[:, j][:, None, :])
        dk_scr[rows] += scale * jnp.stack([
            jax.lax.dot_general(ds[p].astype(q_ref.dtype), q_ref[p],
                                (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for p in range(hp)])                    # (hp, bk, d)
        dq_scr[...] += scale * jnp.stack([
            jax.lax.dot_general(ds[p].astype(k_ref.dtype), k_ref[p],
                                (((0,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
            for p in range(hp)])                    # (hp, bq, d)

    _causal_dispatch(_step, j, t, bq, bk, causal)

    @pl.when(t == nk - 1)
    def _write_dq():
        dq_ref[...] = dq_scr[...].astype(dq_ref.dtype)

    # dk/dv flushed every t step (block index advances with t); only
    # the final q pass leaves the complete sums (≡ _bwd_fused_kernel)
    dk_ref[...] = dk_scr[:, pl.ds(t * bk, bk), :].astype(dk_ref.dtype)
    dv_ref[...] = dv_scr[:, pl.ds(t * bk, bk), :].astype(dv_ref.dtype)


# ----------------------------- host-side plumbing ---------------------------

def _pick_block(seq, cap=512):
    for b in (1024, 512, 256, 128, 64, 32, 16, 8):
        if b <= cap and seq % b == 0:
            return b
    return None


_BLOCK_FALLBACK_WARNED = set()


def _fit_block(blk, seq, name):
    """Largest power-of-two block <= blk that divides seq.  Tuned
    configs are swept at the bench shapes; an off-size sequence (odd
    microbatch remainder, a probe script) must degrade to a dividing
    block instead of hard-failing mid-training (warn once per
    (name, blk, seq))."""
    if blk is None or seq % blk == 0:
        return blk
    fb = _pick_block(seq, cap=blk)
    if fb is None:
        raise ValueError(
            f"{name}={blk} does not divide seq={seq} and no smaller "
            f"power-of-two block divides it either")
    key = (name, blk, seq)
    if key not in _BLOCK_FALLBACK_WARNED:
        _BLOCK_FALLBACK_WARNED.add(key)
        warnings.warn(
            f"flash attention: {name}={blk} does not divide seq={seq}; "
            f"falling back to the largest dividing block {fb}",
            stacklevel=4)
    return fb


def _resolve_blocks(sq, sk, block_q, block_k, full_bias=False):
    """Default blocks, swept on v5e (docs/PERF.md): single block per
    axis when the sequence fits (<=1024 — grid overhead dominates the
    extra causal-mask work), else (512, 1024) to cap the fp32 score
    tile at 2 MB of VMEM while keeping k-side matmuls wide.  Explicit
    blocks that do not divide the sequence fall back to the largest
    dividing power-of-two block (warn once) so tuned configs never
    hard-fail on off-size sequences.  A fused FULL bias adds a
    same-size fp32 block, so the q block is halved to stay inside VMEM
    (a key-compact "sk" bias is only a (bk,) row — no halving)."""
    block_q = _fit_block(block_q, sq, "block_q")
    block_k = _fit_block(block_k, sk, "block_k")
    q_cap = 1024 if (sq <= 1024 and not full_bias) else 512
    bq = block_q or _pick_block(sq, cap=q_cap)
    bk = block_k or _pick_block(sk, cap=1024)
    return bq, bk


def _resolve_heads_per_step(heads_per_step, h, want_dbias=False):
    """Validated packing factor: must divide the (local) head count;
    dbias paths run unpacked.  Invalid explicit values warn once and
    fall back to 1 (the tuned path must degrade, not fail)."""
    hp = int(heads_per_step or 1)
    if hp <= 1:
        return 1
    if want_dbias:
        return 1
    if h % hp:
        key = ("heads_per_step", hp, h)
        if key not in _BLOCK_FALLBACK_WARNED:
            _BLOCK_FALLBACK_WARNED.add(key)
            warnings.warn(
                f"flash attention: heads_per_step={hp} does not divide "
                f"num_heads={h}; running unpacked", stacklevel=4)
        return 1
    return hp


def _compiler_params(grid_len):
    # first axes (batch*head and the parallel block axis) are
    # order-independent; the innermost axis carries the online-softmax /
    # accumulator recurrence and must stay sequential
    return pltpu.CompilerParams(
        dimension_semantics=("parallel",) * (grid_len - 1) + ("arbitrary",))


def _flatten_bh(x):
    b, h, s, d = x.shape
    return x.reshape(b * h, s, d)


def _fwd_impl(q, k, v, scale, causal, dropout_rate=0.0, seed=None,
              block_q=None, block_k=None, bias=None, q_seg=None,
              kv_seg=None, q_off=0, k_off=0, heads_per_step=1):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bias_kind = _bias_kind(bias, sk)
    bq, bk = _resolve_blocks(sq, sk, block_q, block_k,
                              full_bias=bias_kind == "full")
    hp = _resolve_heads_per_step(heads_per_step, h)
    qf, kf, vf = _flatten_bh(q), _flatten_bh(k), _flatten_bh(v)
    bh = b * h
    nq, nk = sq // bq, sk // bk
    seed = _seed3(seed, q_off, k_off)
    has_seg = q_seg is not None
    nb = bias.shape[0] if bias is not None else 1
    nh = bias.shape[1] if bias is not None else 1
    bias_t, qs, ks = _extras_arrays(b, h, sq, sk, nq, bq, nk, bk,
                                    bias, q_seg, kv_seg, bias_kind)
    bspec, qsspec, ksspec = _extras_specs(
        h, nq, bq, nk, bk, bias_kind, nb, nh, has_seg,
        jt_from_args=lambda j, t: (j, t), hp=hp)
    if hp == 1:
        kernel = functools.partial(
            _fwd_kernel, scale=scale, causal=causal, bq=bq, bk=bk,
            nk=nk, dropout_rate=dropout_rate, bias_kind=bias_kind,
            has_seg=has_seg)
        scratch = [pltpu.VMEM((1, bq), jnp.float32),
                   pltpu.VMEM((1, bq), jnp.float32),
                   pltpu.VMEM((d, bq), jnp.float32)]
    else:
        kernel = functools.partial(
            _fwd_kernel_packed, scale=scale, causal=causal, bq=bq,
            bk=bk, nk=nk, hp=hp, dropout_rate=dropout_rate,
            bias_kind=bias_kind, bias_per_head=nh > 1, has_seg=has_seg)
        scratch = [pltpu.VMEM((hp, bq), jnp.float32),
                   pltpu.VMEM((hp, bq), jnp.float32),
                   pltpu.VMEM((hp, d, bq), jnp.float32)]
    o, lse = pl.pallas_call(
        kernel,
        grid=(bh // hp, nq, nk),
        in_specs=[
            pl.BlockSpec((hp, bq, d), lambda i, j, t: (i, j, 0)),
            pl.BlockSpec((hp, bk, d), lambda i, j, t: (i, t, 0)),
            pl.BlockSpec((hp, bk, d), lambda i, j, t: (i, t, 0)),
            bspec, qsspec, ksspec,
            pl.BlockSpec((3, 1), lambda i, j, t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((hp, bq, d), lambda i, j, t: (i, j, 0)),
            # lse as (bh, nq, bq): one whole-head(-group) block resident
            # per i (a (bh, sq, 1) fp32 array would tile-pad to 128x its
            # size; 2-D (1, bq) blocks violate the (8, 128) tile rule)
            pl.BlockSpec((hp, nq, bq), lambda i, j, t: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, nq, bq), jnp.float32),
        ],
        scratch_shapes=scratch,
        # the q-block axis must stay sequential here: the whole-head lse
        # block is shared across j, and a Megacore split of a "parallel"
        # j would give each core a private copy with half the rows
        # written (last flush wins)
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary")),
        interpret=pallas_interpret(),
    )(qf, kf, vf, bias_t, qs, ks, seed)
    return o.reshape(b, h, sq, d), lse.reshape(b, h, sq)


def _head_row_spec(nq, bq):
    """Whole-head (1, nq, bq) block for the lse/delta row stats —
    resident across the block loops (index depends only on i, whatever
    the grid order)."""
    return pl.BlockSpec((1, nq, bq), lambda i, *_: (i, 0, 0))


def _bwd_impl(q, k, v, o, lse, do, scale, causal, dropout_rate=0.0,
              seed=None, block_q=None, block_k=None, bias=None,
              q_seg=None, kv_seg=None, want_dbias=False,
              grad_dtype=None, q_off=0, k_off=0, heads_per_step=1):
    """Returns (dq, dk, dv, dbias) — dbias is None unless want_dbias.

    grad_dtype overrides the dq/dk/dv output dtype (default: the input
    dtypes).  The ring-attention backward passes fp32 so per-ring-step
    partials accumulate at full precision instead of being rounded to
    bf16 once per ring hop (the kernels accumulate in fp32 scratch
    either way; this only moves the final rounding)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bias_kind = _bias_kind(bias, sk)
    bq, bk = _resolve_blocks(sq, sk, block_q, block_k,
                              full_bias=bias_kind == "full")
    hp = _resolve_heads_per_step(heads_per_step, h,
                                 want_dbias=want_dbias)
    nq, nk = sq // bq, sk // bk
    bh = b * h
    seed = _seed3(seed, q_off, k_off)
    has_seg = q_seg is not None
    nb = bias.shape[0] if bias is not None else 1
    nh = bias.shape[1] if bias is not None else 1
    bias_t, qsegs, ksegs = _extras_arrays(b, h, sq, sk, nq, bq, nk, bk,
                                          bias, q_seg, kv_seg, bias_kind)
    bspec, qsspec, ksspec = _extras_specs(
        h, nq, bq, nk, bk, bias_kind, nb, nh, has_seg,
        jt_from_args=lambda j, t: (j, t))
    static = dict(scale=scale, causal=causal, bq=bq, bk=bk,
                  dropout_rate=dropout_rate, bias_kind=bias_kind,
                  has_seg=has_seg)
    dq_dt = grad_dtype or q.dtype
    dk_dt = grad_dtype or k.dtype
    dv_dt = grad_dtype or v.dtype
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)  # (b,h,sq)
    args = [_flatten_bh(q), _flatten_bh(k), _flatten_bh(v),
            _flatten_bh(do), lse.reshape(bh, nq, bq),
            delta.reshape(bh, nq, bq), bias_t, qsegs, ksegs, seed]
    qspec = pl.BlockSpec((1, bq, d), lambda i, j, t: (i, j, 0))
    kspec = pl.BlockSpec((1, bk, d), lambda i, j, t: (i, t, 0))
    r1 = _head_row_spec(nq, bq)
    sspec1 = pl.BlockSpec((3, 1), lambda i, j, t: (0, 0))

    def _reduce_db(db_full):
        """(b, h, ...) per-head dbias partials → the caller's broadcast
        shape (nb, nh, ...)."""
        if nb == 1:
            db_full = jnp.sum(db_full, axis=0, keepdims=True)
        if nh == 1:
            db_full = jnp.sum(db_full, axis=1, keepdims=True)
        return db_full

    # dbias("full") comes from the fused/dq kernels (ds written per
    # (j, t) block); dbias("sk") needs the dkv grid (q-sum accumulates
    # over the inner j axis), so it forces the two-kernel path
    dbias_full = want_dbias and bias_kind == "full"
    dbias_sk = want_dbias and bias_kind == "sk"

    # head-packed single-pass backward: only when the fused path is
    # live anyway, no bias gradient is wanted (dbias writes are
    # per-head), and the (hp, sk, d) dk/dv scratch pair fits VMEM
    if (hp > 1 and sk * d <= _FUSED_BWD_CAP and not want_dbias
            and hp * sk * d <= _FUSED_BWD_CAP_PACKED):
        bspec_p, qsspec_p, ksspec_p = _extras_specs(
            h, nq, bq, nk, bk, bias_kind, nb, nh, has_seg,
            jt_from_args=lambda j, t: (j, t), hp=hp)
        qspec_p = pl.BlockSpec((hp, bq, d), lambda i, j, t: (i, j, 0))
        kspec_p = pl.BlockSpec((hp, bk, d), lambda i, j, t: (i, t, 0))
        rp = pl.BlockSpec((hp, nq, bq), lambda i, j, t: (i, 0, 0))
        dq, dk, dv = pl.pallas_call(
            functools.partial(_bwd_fused_kernel_packed, nq=nq, nk=nk,
                              hp=hp, bias_per_head=nh > 1, **static),
            grid=(bh // hp, nq, nk),
            in_specs=[qspec_p, kspec_p, kspec_p, qspec_p, rp, rp,
                      bspec_p, qsspec_p, ksspec_p,
                      pl.BlockSpec((3, 1), lambda i, j, t: (0, 0))],
            out_specs=[qspec_p, kspec_p, kspec_p],
            out_shape=[jax.ShapeDtypeStruct((bh, sq, d), dq_dt),
                       jax.ShapeDtypeStruct((bh, sk, d), dk_dt),
                       jax.ShapeDtypeStruct((bh, sk, d), dv_dt)],
            scratch_shapes=[pltpu.VMEM((hp, bq, d), jnp.float32),
                            pltpu.VMEM((hp, sk, d), jnp.float32),
                            pltpu.VMEM((hp, sk, d), jnp.float32)],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary",
                                     "arbitrary")),
            interpret=pallas_interpret(),
        )(*args)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape), None)

    # single-pass fused backward while the full-(sk, d) dk/dv scratch
    # fits VMEM comfortably; two-kernel fallback for long context
    if sk * d <= _FUSED_BWD_CAP and not dbias_sk:
        out_specs = [qspec, kspec, kspec]
        out_shape = [jax.ShapeDtypeStruct((bh, sq, d), dq_dt),
                     jax.ShapeDtypeStruct((bh, sk, d), dk_dt),
                     jax.ShapeDtypeStruct((bh, sk, d), dv_dt)]
        if dbias_full:
            out_specs.append(pl.BlockSpec((1, bk, bq),
                                          lambda i, j, t: (i, t, j)))
            out_shape.append(
                jax.ShapeDtypeStruct((bh, sk, sq), jnp.float32))
        outs = pl.pallas_call(
            functools.partial(_bwd_fused_kernel, nq=nq, nk=nk,
                              want_dbias=dbias_full, **static),
            grid=(bh, nq, nk),
            in_specs=[qspec, kspec, kspec, qspec, r1, r1,
                      bspec, qsspec, ksspec, sspec1],
            out_specs=out_specs,
            out_shape=out_shape,
            scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32),
                            pltpu.VMEM((sk, d), jnp.float32),
                            pltpu.VMEM((sk, d), jnp.float32)],
            # dk/dv accumulate across the q-block axis too, so only the
            # leading batch*head axis is order-independent here
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary", "arbitrary")),
            interpret=pallas_interpret(),
        )(*args)
        dq, dk, dv = outs[:3]
        dbias = None
        if dbias_full:
            db = _reduce_db(outs[3].reshape(b, h, sk, sq))
            dbias = jnp.swapaxes(db, 2, 3)
        return (dq.reshape(q.shape), dk.reshape(k.shape),
                dv.reshape(v.shape), dbias)

    dq_specs = [qspec]
    dq_shape = [jax.ShapeDtypeStruct((bh, sq, d), dq_dt)]
    if dbias_full:
        dq_specs.append(pl.BlockSpec((1, bk, bq),
                                     lambda i, j, t: (i, t, j)))
        dq_shape.append(jax.ShapeDtypeStruct((bh, sk, sq), jnp.float32))
    dq_out = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, nk=nk, want_dbias=dbias_full,
                          **static),
        grid=(bh, nq, nk),
        in_specs=[qspec, kspec, kspec, qspec, r1, r1,
                  bspec, qsspec, ksspec, sspec1],
        out_specs=dq_specs if dbias_full else dq_specs[0],
        out_shape=dq_shape if dbias_full else dq_shape[0],
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        compiler_params=_compiler_params(3),
        interpret=pallas_interpret(),
    )(*args)
    dbias = None
    if dbias_full:
        dq, db_t = dq_out
        dbias = jnp.swapaxes(_reduce_db(db_t.reshape(b, h, sk, sq)), 2, 3)
    else:
        dq = dq_out
    # dkv grid: k blocks outer, q blocks inner-sequential
    qspec2 = pl.BlockSpec((1, bq, d), lambda i, t, j: (i, j, 0))
    kspec2 = pl.BlockSpec((1, bk, d), lambda i, t, j: (i, t, 0))
    r2 = _head_row_spec(nq, bq)
    sspec2 = pl.BlockSpec((3, 1), lambda i, t, j: (0, 0))
    bspec2, qsspec2, ksspec2 = _extras_specs(
        h, nq, bq, nk, bk, bias_kind, nb, nh, has_seg,
        jt_from_args=lambda t, j: (j, t))
    dkv_specs = [kspec2, kspec2]
    dkv_shape = [jax.ShapeDtypeStruct((bh, sk, d), dk_dt),
                 jax.ShapeDtypeStruct((bh, sk, d), dv_dt)]
    dkv_scratch = [pltpu.VMEM((bk, d), jnp.float32),
                   pltpu.VMEM((bk, d), jnp.float32)]
    if dbias_sk:
        # db rides as (bh, nk, bk) whole-head rows (the lse layout);
        # shared across both block axes → t must not Megacore-split
        dkv_specs.append(pl.BlockSpec((1, nk, bk),
                                      lambda i, t, j: (i, 0, 0)))
        dkv_shape.append(jax.ShapeDtypeStruct((bh, nk, bk), jnp.float32))
        dkv_scratch.append(pltpu.VMEM((1, bk), jnp.float32))
        dkv_params = pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary", "arbitrary"))
    else:
        dkv_params = _compiler_params(3)
    outs = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, nq=nq, want_dbias=dbias_sk,
                          **static),
        grid=(bh, nk, nq),
        in_specs=[qspec2, kspec2, kspec2, qspec2, r2, r2,
                  bspec2, qsspec2, ksspec2, sspec2],
        out_specs=dkv_specs,
        out_shape=dkv_shape,
        scratch_shapes=dkv_scratch,
        compiler_params=dkv_params,
        interpret=pallas_interpret(),
    )(*args)
    dk, dv = outs[:2]
    if dbias_sk:
        db = _reduce_db(outs[2].reshape(b, h, sk))       # (nb, nh, sk)
        dbias = db[:, :, None, :]                        # (nb, nh, 1, sk)
    return (dq.reshape(q.shape), dk.reshape(k.shape),
            dv.reshape(v.shape), dbias)


@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(6, 7, 8, 9, 10, 11, 12))
def _flash(q, k, v, bias, q_seg, kv_seg, scale, causal, dropout_rate,
           block_q, block_k, heads_per_step, bias_grad, seed):
    o, _ = _fwd_impl(q, k, v, scale, causal, dropout_rate, seed,
                     block_q, block_k, bias, q_seg, kv_seg,
                     heads_per_step=heads_per_step)
    return o


def _flash_fwd(q, k, v, bias, q_seg, kv_seg, scale, causal, dropout_rate,
               block_q, block_k, heads_per_step, bias_grad, seed):
    o, lse = _fwd_impl(q, k, v, scale, causal, dropout_rate, seed,
                       block_q, block_k, bias, q_seg, kv_seg,
                       heads_per_step=heads_per_step)
    return o, (q, k, v, bias, q_seg, kv_seg, o, lse, seed)


def _flash_bwd(scale, causal, dropout_rate, block_q, block_k,
               heads_per_step, bias_grad, res, do):
    q, k, v, bias, q_seg, kv_seg, o, lse, seed = res
    # a key-broadcast (.., *, 1) bias adds a per-query constant to the
    # scores — softmax cancels it, so its gradient is EXACTLY zero (no
    # kernel work); bias_grad=False opts constant biases (padding
    # masks, fixed ALiBi) out of the dbias computation entirely
    want_dbias = (bias_grad and bias is not None and bias.shape[3] != 1)
    dq, dk, dv, dbias = _bwd_impl(q, k, v, o, lse, do, scale, causal,
                                  dropout_rate, seed, block_q, block_k,
                                  bias, q_seg, kv_seg,
                                  want_dbias=want_dbias,
                                  heads_per_step=heads_per_step)
    import numpy as _np

    def _int_zero(x):
        return (None if x is None
                else _np.zeros(x.shape, dtype=jax.dtypes.float0))
    if bias is not None:
        dbias = (dbias.astype(bias.dtype) if want_dbias
                 else jnp.zeros_like(bias))
    return (dq, dk, dv, dbias, _int_zero(q_seg), _int_zero(kv_seg),
            _int_zero(seed))


_flash.defvjp(_flash_fwd, _flash_bwd)


# --------------------------------- public API -------------------------------

# cache-sourced score-tile guard: hp·bq·bk fp32 elements must stay
# within ~4 MB of VMEM (the sweep's own candidate cap is half this)
_TUNED_SCORE_ELEMS_CAP = 1024 * 1024


def _tuned_flash_config(b, h, sq, sk, d, dtype, causal, bias_kind,
                        has_seg):
    """Trace-time autotuner lookup (apex_tpu.tune): a pure host-side
    dict access — zero collectives, no host syncs.  None on a miss, so
    an empty cache leaves every call on today's heuristics.

    A hit is SANITY-VALIDATED before use (a hand-edited or
    cross-version cache must degrade to heuristics, never crash a run):
    blocks and packing must be ints in range and the packed fp32 score
    tile must fit VMEM; anything off warns once and is ignored
    (divisibility fixups happen later in _resolve_blocks /
    _resolve_heads_per_step)."""
    try:
        from apex_tpu import tune
    except Exception:  # pragma: no cover — tune must never break attn
        return None
    if sq != sk:
        return None   # tuned entries are swept at self-attention shapes
    cfg = tune.tuned("flash_sdpa",
                     tune.flash_attrs(b, h, sq, sk, d, dtype, causal,
                                      bias=bias_kind, seg=has_seg))
    if not cfg:
        return None
    bq = cfg.get("block_q")
    bk = cfg.get("block_k")
    hp = cfg.get("heads_per_step", 1)
    ok = (all(v is None or (isinstance(v, int) and 8 <= v <= 4096)
              for v in (bq, bk))
          and isinstance(hp, int) and 1 <= hp <= 16
          and hp * (bq or 1024) * (bk or 1024) <= _TUNED_SCORE_ELEMS_CAP)
    if not ok:
        key = ("tuned_cfg", sq, sk, d)
        if key not in _BLOCK_FALLBACK_WARNED:
            _BLOCK_FALLBACK_WARNED.add(key)
            warnings.warn(
                f"flash attention: ignoring out-of-range tuned config "
                f"{cfg} at (sq={sq}, sk={sk}, d={d}); using heuristics",
                stacklevel=3)
        return None
    return cfg


def flash_attention(q, k, v, *, causal: bool = False,
                    softmax_scale: Optional[float] = None,
                    bias=None,
                    segment_ids=None,
                    q_segment_ids=None,
                    kv_segment_ids=None,
                    dropout_rate: float = 0.0,
                    dropout_key=None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    heads_per_step: Optional[int] = None,
                    # True by default DELIBERATELY: a trainable bias
                    # silently freezing (the round-3 contract) is wrong
                    # training with no error; the full-bias dbias
                    # buffer this costs is a loud, debuggable OOM whose
                    # opt-out (bias_grad=False) is documented below.
                    bias_grad: bool = True,
                    use_pallas_override: Optional[bool] = None):
    """Flash attention over (batch, heads, seq, head_dim).

    ≡ apex.contrib.fmha.FMHAFun (apex/contrib/fmha/fmha.py:33-72) with
    the seq≤512/head-64 restriction removed, and the core of the
    fast_multihead_attn variants (self/encdec attention cores).
    Attention dropout runs IN-kernel with a counter-based mask
    regenerated in backward (≡ the reference's philox dropout,
    fmha/src/fmha/softmax.h) — no sq x sk mask ever reaches HBM, so
    dropout works at any sequence length.

    bias: additive fp score bias, shape (b|1, h|1, sq|1, sk), fused
    into the kernel (≡ the additive-mask softmax in
    apex/contrib/csrc/multihead_attn/softmax.cuh:27-200).  A
    key-compact (.., 1, sk) bias — the padding-mask / ALiBi shape —
    rides compact through the kernels (never expanded to sq × sk in
    HBM).  TRAINABLE biases are first-class (≡ the
    self_multihead_attn_bias CUDA variants): the backward emits the
    real dbias, reduced over broadcast dims — full (sq, sk) biases
    from per-block ds writes, key-compact ones from an in-kernel
    q-sum.  COST NOTE: a differentiated call with a full (sq, sk)
    bias materializes a per-(b, h) fp32 dbias partial (b·h·sq·sk
    bytes ×4 transient) before the broadcast reduction — pass
    bias_grad=False for constant biases (padding masks, fixed slopes)
    to skip all dbias work, as the in-repo mask paths do.  A
    (.., *, 1) query-compact bias is a per-query score constant:
    softmax cancels it exactly (finite values; whole-row masking must
    use segment ids), so it is skipped in the kernels and its gradient
    is exactly zero.

    block_q / block_k / heads_per_step: the kernel-shape knobs.
    heads_per_step > 1 packs that many d-minor heads into each grid
    step (shared online-softmax epilogue, hp-head K/V slabs per DMA —
    the d=64 packing axis; see _fwd_kernel_packed).  When ALL THREE are
    None the apex_tpu.tune cache is consulted at trace time for a
    config tuned at this exact (shape, dtype, device-kind) key — a
    cache miss (or APEX_TPU_TUNE=0) keeps the built-in heuristics, so
    an empty cache is byte-identical to explicit None everywhere.
    Explicit blocks that do not divide the sequence fall back to the
    largest dividing block (warn once) instead of failing.

    segment_ids: (b, s) int — tokens attend only where ids are equal;
    this is the TPU-native form of the reference fmha's cu_seqlens
    varlen packing (fmha_api.cpp:18-160): pack multiple sequences into
    one row with distinct ids and padded tokens cost no attention.
    q_segment_ids/kv_segment_ids set the two sides separately (encdec
    or kv-cache shapes); fully-masked query rows produce a uniform
    attention over kv (like the dense oracle) — mask them in the loss.
    """
    d = q.shape[-1]
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)
    if not 0.0 <= dropout_rate < 1.0:
        raise ValueError(f"dropout_rate must be in [0, 1), got {dropout_rate}")
    if dropout_rate > 0.0 and dropout_key is None:
        raise ValueError("dropout_rate > 0 requires dropout_key")
    if segment_ids is not None:
        if q_segment_ids is not None or kv_segment_ids is not None:
            raise ValueError(
                "pass either segment_ids or q_/kv_segment_ids, not both")
        q_segment_ids = kv_segment_ids = segment_ids
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    b, h = q.shape[0], q.shape[1]
    sq, sk = q.shape[2], k.shape[2]
    if bias is not None:
        eb, eh = bias.shape[0], bias.shape[1]
        if (bias.ndim != 4 or eb not in (1, b) or eh not in (1, h)
                or bias.shape[2] not in (1, sq)
                or bias.shape[3] not in (1, sk)):
            raise ValueError(
                f"bias shape {bias.shape} not broadcastable to "
                f"({b}|1, {h}|1, {sq}|1, {sk}|1)")
    if q_segment_ids is not None:
        q_segment_ids = jnp.asarray(q_segment_ids, jnp.int32)
        kv_segment_ids = jnp.asarray(kv_segment_ids, jnp.int32)
        if q_segment_ids.shape != (b, sq) or kv_segment_ids.shape != (b, sk):
            raise ValueError(
                f"segment id shapes {q_segment_ids.shape}/"
                f"{kv_segment_ids.shape} != ({b}, {sq})/({b}, {sk})")
    # in-kernel dropout is a pure coordinate hash — it runs (and gives
    # bit-identical masks) in interpret mode too, so CPU CI covers it
    kernel_ok = (use_pallas(use_pallas_override)
                 and _pick_block(q.shape[2]) and _pick_block(k.shape[2]))
    if kernel_ok:
        if block_q is None and block_k is None and heads_per_step is None:
            # fully-unspecified config → consult the autotuner cache
            # (explicit knobs always win; a miss keeps the heuristics)
            cfg = _tuned_flash_config(
                b, h, sq, sk, q.shape[3], q.dtype, causal,
                _bias_kind(bias, sk), q_segment_ids is not None)
            if cfg:
                block_q = cfg.get("block_q")
                block_k = cfg.get("block_k")
                heads_per_step = cfg.get("heads_per_step")
        if dropout_rate > 0.0:
            seed = jax.random.randint(dropout_key, (1, 1), -2**31, 2**31 - 1,
                                      dtype=jnp.int32)
        else:
            seed = jnp.zeros((1, 1), jnp.int32)
        return _flash(q, k, v, bias, q_segment_ids, kv_segment_ids,
                      scale, causal, float(dropout_rate),
                      block_q, block_k, int(heads_per_step or 1),
                      bool(bias_grad), seed)
    # fallback keeps the same dbias semantics: AD through the dense
    # path yields the (broadcast-reduced) dbias when bias_grad, and a
    # stop_gradient reproduces the constant-bias contract otherwise
    return attention_reference(q, k, v, causal=causal, softmax_scale=scale,
                               bias=(bias if bias is None or bias_grad
                                     else lax.stop_gradient(bias)),
                               q_segment_ids=q_segment_ids,
                               kv_segment_ids=kv_segment_ids,
                               dropout_rate=dropout_rate,
                               dropout_key=dropout_key)
