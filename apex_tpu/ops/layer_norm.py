"""Fused LayerNorm / RMSNorm — Pallas fwd+bwd with custom_vjp.

≡ the reference's `fused_layer_norm_cuda` extension
(csrc/layer_norm_cuda.cpp:429-441, kernels csrc/layer_norm_cuda_kernel.cu:411-678)
and its Python wrappers (apex/normalization/fused_layer_norm.py:32-165):
fwd/bwd × {affine, plain} × {LayerNorm, RMSNorm}, computing statistics in
fp32 regardless of input dtype (the "mixed dtype" Megatron variants fall
out for free — stats are always fp32 here) and saving (mean, rstd) for
backward.  Also subsumes `apex.contrib.layer_norm.FastLayerNorm`
(apex/contrib/layer_norm/layer_norm.py:40) — on TPU one blocked kernel
covers all hidden sizes instead of per-size tuned CUDA kernels.

Layout: leading dims are flattened to rows; the kernel grids over row
blocks with the full hidden dim resident in VMEM (hidden ≤ ~64k fp32).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from apex_tpu.ops._common import (pallas_interpret, tuned_row_block,
                                  use_pallas_fusable)


# --------------------------- reference (jnp) path ---------------------------

def layer_norm_reference(x, weight=None, bias=None, eps=1e-5):
    """Pure-jnp LayerNorm over the last dim, fp32 stats (the CPU fallback,
    ≡ apex/normalization/fused_layer_norm.py:288-294)."""
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * lax.rsqrt(var + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(x.dtype)


def rms_norm_reference(x, weight=None, eps=1e-5):
    x32 = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * lax.rsqrt(ms + eps)
    if weight is not None:
        y = y * weight.astype(jnp.float32)
    return y.astype(x.dtype)


# ------------------------------ pallas kernels ------------------------------

def _fwd_kernel(x_ref, w_ref, b_ref, y_ref, mean_ref, rstd_ref, *, eps, rms,
                affine, has_bias):
    x = x_ref[...].astype(jnp.float32)
    if rms:
        mean = jnp.zeros((x.shape[0], 1), jnp.float32)
        var = jnp.mean(x * x, axis=1, keepdims=True)
    else:
        mean = jnp.mean(x, axis=1, keepdims=True)
        xc = x - mean
        var = jnp.mean(xc * xc, axis=1, keepdims=True)
    rstd = lax.rsqrt(var + eps)
    y = (x - mean) * rstd
    if affine:
        y = y * w_ref[...].astype(jnp.float32)
        if has_bias:
            y = y + b_ref[...].astype(jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    mean_ref[...] = mean
    rstd_ref[...] = rstd


def _bwd_kernel(g_ref, x_ref, mean_ref, rstd_ref, w_ref,
                dx_ref, dw_ref, db_ref, *, rms, affine):
    g = g_ref[...].astype(jnp.float32)
    x = x_ref[...].astype(jnp.float32)
    mean = mean_ref[...]
    rstd = rstd_ref[...]
    xhat = (x - mean) * rstd
    if affine:
        wg = g * w_ref[...].astype(jnp.float32)
    else:
        wg = g
    # dx = rstd * (wg - mean(wg)[LN only] - xhat * mean(wg * xhat))
    c2 = jnp.mean(wg * xhat, axis=1, keepdims=True)
    if rms:
        dx = rstd * (wg - xhat * c2)
    else:
        c1 = jnp.mean(wg, axis=1, keepdims=True)
        dx = rstd * (wg - c1 - xhat * c2)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    if affine:
        # accumulate dw/db across the sequential grid (single (1, hidden)
        # output revisited every step — TPU grids are sequential)
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _init():
            dw_ref[...] = jnp.zeros_like(dw_ref)
            db_ref[...] = jnp.zeros_like(db_ref)

        dw_ref[...] += jnp.sum(g * xhat, axis=0, keepdims=True)
        db_ref[...] += jnp.sum(g, axis=0, keepdims=True)


def _pad_rows(x2, block):
    rows = x2.shape[0]
    pad = (-rows) % block
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    return x2, rows


def _fwd_pallas(x2, weight, bias, eps, rms):
    rows, hidden = x2.shape
    affine = weight is not None
    has_bias = bias is not None
    blk = tuned_row_block("layer_norm_fwd", rows, hidden)
    x2p, _ = _pad_rows(x2, blk)
    prows = x2p.shape[0]
    grid = prows // blk
    w = weight if affine else jnp.zeros((hidden,), x2.dtype)
    b = bias if has_bias else jnp.zeros((hidden,), x2.dtype)
    kernel = functools.partial(_fwd_kernel, eps=eps, rms=rms, affine=affine,
                               has_bias=has_bias)
    y, mean, rstd = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((blk, hidden), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((blk, hidden), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((prows, hidden), x2.dtype),
            jax.ShapeDtypeStruct((prows, 1), jnp.float32),
            jax.ShapeDtypeStruct((prows, 1), jnp.float32),
        ],
        interpret=pallas_interpret(),
    )(x2p, w, b)
    return y[:rows], mean[:rows], rstd[:rows]


def _bwd_pallas(g2, x2, mean, rstd, weight, rms):
    rows, hidden = x2.shape
    affine = weight is not None
    blk = tuned_row_block("layer_norm_bwd", rows, hidden)
    g2p, _ = _pad_rows(g2, blk)
    x2p, _ = _pad_rows(x2, blk)
    meanp, _ = _pad_rows(mean, blk)
    rstdp, _ = _pad_rows(rstd, blk)
    prows = x2p.shape[0]
    grid = prows // blk
    w = weight if affine else jnp.zeros((hidden,), x2.dtype)
    kernel = functools.partial(_bwd_kernel, rms=rms, affine=affine)
    dx, dwp, dbp = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((blk, hidden), lambda i: (i, 0)),
            pl.BlockSpec((blk, hidden), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((blk, 1), lambda i: (i, 0)),
            pl.BlockSpec((hidden,), lambda i: (0,)),
        ],
        out_specs=[
            pl.BlockSpec((blk, hidden), lambda i: (i, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
            pl.BlockSpec((1, hidden), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((prows, hidden), x2.dtype),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
            jax.ShapeDtypeStruct((1, hidden), jnp.float32),
        ],
        interpret=pallas_interpret(),
    )(g2p, x2p, meanp, rstdp, w)
    dw = dwp[0] if affine else None
    db = dbp[0] if affine else None
    return dx[:rows], dw, db


# ----------------------------- custom_vjp plumbing --------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _norm(x, weight, bias, eps, rms):
    y, _, _ = _norm_fwd_impl(x, weight, bias, eps, rms)
    return y


def _norm_fwd_impl(x, weight, bias, eps, rms):
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    y2, mean, rstd = _fwd_pallas(x2, weight, bias, eps, rms)
    return y2.reshape(shape), mean, rstd


def _norm_fwd(x, weight, bias, eps, rms):
    y, mean, rstd = _norm_fwd_impl(x, weight, bias, eps, rms)
    return y, (x, weight, bias, mean, rstd)


def _norm_bwd(eps, rms, res, g):
    x, weight, bias, mean, rstd = res
    shape = x.shape
    g2 = g.reshape(-1, shape[-1])
    x2 = x.reshape(-1, shape[-1])
    dx, dw, db = _bwd_pallas(g2, x2, mean, rstd, weight, rms)
    dx = dx.reshape(shape)
    dw = None if weight is None else dw.astype(weight.dtype)
    db = None if bias is None else (db.astype(bias.dtype) if db is not None else None)
    return (dx, dw, db)


_norm.defvjp(_norm_fwd, _norm_bwd)


# --------------------------------- public API -------------------------------

def fused_layer_norm(x, weight=None, bias=None, eps: float = 1e-5,
                     use_pallas_override: Optional[bool] = None):
    """Fused affine/plain LayerNorm ≡ fused_layer_norm_affine /
    fused_layer_norm (apex/normalization/fused_layer_norm.py:168-201)."""
    if use_pallas_fusable(use_pallas_override):
        return _norm(x, weight, bias, eps, False)
    return layer_norm_reference(x, weight, bias, eps)


def fused_rms_norm(x, weight=None, eps: float = 1e-5,
                   use_pallas_override: Optional[bool] = None):
    """Fused RMSNorm ≡ fused_rms_norm_affine / fused_rms_norm
    (apex/normalization/fused_layer_norm.py:189-201)."""
    if use_pallas_fusable(use_pallas_override):
        return _norm(x, weight, None, eps, True)
    return rms_norm_reference(x, weight, eps)


class FusedLayerNorm:
    """Module facade ≡ apex.normalization.FusedLayerNorm
    (apex/normalization/fused_layer_norm.py:204-297)."""

    def __init__(self, normalized_shape, eps=1e-5, elementwise_affine=True):
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        if len(normalized_shape) != 1:
            raise NotImplementedError("only last-dim LayerNorm is supported")
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine

    def init(self, key=None, dtype=jnp.float32):
        if not self.elementwise_affine:
            return {}
        h = self.normalized_shape[0]
        return {"weight": jnp.ones((h,), dtype), "bias": jnp.zeros((h,), dtype)}

    def apply(self, params, x, use_pallas_override=None):
        w = params.get("weight") if self.elementwise_affine else None
        b = params.get("bias") if self.elementwise_affine else None
        return fused_layer_norm(x, w, b, self.eps, use_pallas_override)


class FusedRMSNorm:
    """≡ apex.normalization.FusedRMSNorm (fused_layer_norm.py:300-397)."""

    def __init__(self, normalized_shape, eps=1e-5, elementwise_affine=True):
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.eps = eps
        self.elementwise_affine = elementwise_affine

    def init(self, key=None, dtype=jnp.float32):
        if not self.elementwise_affine:
            return {}
        return {"weight": jnp.ones(self.normalized_shape, dtype)}

    def apply(self, params, x, use_pallas_override=None):
        w = params.get("weight") if self.elementwise_affine else None
        return fused_rms_norm(x, w, self.eps, use_pallas_override)
