"""Shared kernel-layer plumbing.

≡ the reference's shared native infrastructure (csrc/type_shim.h dtype
dispatch, csrc/compat.h): here it is backend dispatch — every fused op
has a Pallas TPU kernel and a pure-jnp reference implementation; on
non-TPU backends (CPU tests, interpret mode) the jnp path is used, the
same way the reference falls back to pure PyTorch when the extension is
absent (apex/normalization/fused_layer_norm.py:288-294).
"""

from __future__ import annotations

import contextlib
import os
import threading

import jax
import jax.numpy as jnp

_FORCE = os.environ.get("APEX_TPU_FORCE_PALLAS", "")


def use_pallas(override=None) -> bool:
    """Decide kernel path: Pallas on TPU, jnp reference elsewhere.

    `override`: True → pallas (interpret-mode off-TPU), False → jnp.
    Env APEX_TPU_FORCE_PALLAS=1/0 wins over the backend default.
    """
    if override is not None:
        return override
    if _FORCE == "1":
        return True
    if _FORCE == "0":
        return False
    return jax.default_backend() == "tpu"


def use_pallas_fusable(override=None) -> bool:
    """use_pallas for ops where XLA's automatic fusion usually wins.

    Memory-bound elementwise ops (LayerNorm/RMSNorm) fuse into their
    neighboring producers/consumers under XLA; a standalone Pallas
    kernel puts a custom_vjp/custom-call boundary in the way and costs
    a full extra HBM round trip (measured on v5e: GPT-350M step 41.9k
    -> 44.5k tok/s from letting XLA fuse the 49 LayerNorms).  The
    Pallas kernel remains available via override=True or
    APEX_TPU_FORCE_PALLAS=1 (and is what interpret-mode parity tests
    pin).
    """
    if override is not None:
        return override
    return _FORCE == "1"


def pallas_interpret() -> bool:
    """Pallas kernels run in interpret mode off-TPU (for CPU CI parity)."""
    return jax.default_backend() != "tpu"


def round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def row_block(rows: int, hidden: int, bytes_per_elt: int = 4,
              vmem_budget: int = 2 * 1024 * 1024, align: int = 8,
              cap: int = 1024) -> int:
    """Pick a row-block size so a (block, hidden) fp32 tile fits the VMEM
    budget; aligned to the fp32 sublane (8)."""
    b = max(align, vmem_budget // max(1, hidden * bytes_per_elt))
    b = min(b, cap, round_up(rows, align))
    return round_up(b, align) if b % align else b


def tuned_row_block(op: str, rows: int, hidden: int, **kw) -> int:
    """row_block with an autotuner override: consult apex_tpu.tune for
    (op, pow2-bucketed rows, hidden) on this device kind; a hit whose
    block_rows is a sane sublane multiple wins, anything else falls
    back to the deterministic heuristic.  Trace-time host-side lookup
    only — no device work (tune package docstring)."""
    base = row_block(rows, hidden, **kw)
    try:
        from apex_tpu import tune
        cfg = tune.tuned(op, dict(rows=tune.pow2_bucket(rows),
                                  hidden=hidden))
    except Exception:  # pragma: no cover — tuner must never break ops
        return base
    if cfg:
        blk = cfg.get("block_rows")
        if (isinstance(blk, int) and 8 <= blk <= 4096 and blk % 8 == 0):
            return blk
    return base


# --------------------------- numerics taps ---------------------------
#
# The flight-recorder tap op (monitor/trace, ISSUE 4).  It lives here —
# not in monitor/ — because the models call `tap()` on their hot path
# and must not import the monitor package (which pulls sinks/logger);
# ops._common is already in their import closure (dropout above).
#
# Contract: `tap(x, name)` is a BYTE-IDENTICAL identity when no
# TapContext is active (the default) — it returns `x` itself before
# tracing ever sees a new op, so untapped programs compile unchanged.
# Under an active context every tap draws a zeros (2, 4) row from the
# context's `probes` array and BOTH stat planes flow out through that
# row's *gradient*: `grad_tap`'s custom_vjp saves `tap_stats(x)` as a
# residual and returns it stacked with `tap_stats(cotangent)` as the
# probe's cotangent.  Differentiating the loss w.r.t. `probes` then
# yields (n_taps, 2, 4) = per-tap [fwd, grad] stats with no side
# channels, no host callbacks, and no collectives — and because no
# traced value ever lands in Python state, taps are safe inside
# jax.checkpoint/remat regions and lax control flow.

TAP_STAT_FIELDS = ("absmax", "mean", "rms", "nonfinite")
TAP_STAT_DIM = len(TAP_STAT_FIELDS)
TAP_PLANES = ("fwd", "grad")


def tap_stats(x) -> jnp.ndarray:
    """f32[4] = [absmax, mean, rms, nonfinite-element count] of x.

    Computed in f32; when x holds non-finite values the first three
    lanes are themselves non-finite (NaN propagates through max/mean)
    while lane 3 — the count — is always finite and is what provenance
    keys on."""
    xf = x.astype(jnp.float32)
    return jnp.stack([
        jnp.max(jnp.abs(xf)),
        jnp.mean(xf),
        jnp.sqrt(jnp.mean(jnp.square(xf))),
        jnp.sum(~jnp.isfinite(xf)).astype(jnp.float32),
    ])


@jax.custom_vjp
def grad_tap(x, probe):
    """Identity on x whose backward writes stacked
    [tap_stats(x), tap_stats(cotangent)] into `probe`'s gradient
    (probe: f32[2, 4] zeros drawn from TapContext)."""
    del probe
    return x


def _grad_tap_fwd(x, probe):
    del probe
    return x, tap_stats(x)


def _grad_tap_bwd(fwd_stats, g):
    return g, jnp.stack([fwd_stats, tap_stats(g)])


grad_tap.defvjp(_grad_tap_fwd, _grad_tap_bwd)


class TapContext:
    """Assigns probe rows to tap points for one trace.

    probes: f32[max_taps, 2, 4] zeros — an ARGUMENT of the caller's
    jax.grad so each tap's [fwd, grad] stats land in its row (see
    grad_tap).  Rows are assigned in forward trace order; `names[i]`
    labels row i (host-side strings, read after jax.grad returns).
    `discover=True` records names only (no probe draw) for shape-free
    tap enumeration."""

    def __init__(self, probes=None, discover: bool = False):
        self.probes = probes
        self.discover = discover
        self.names = []

    @property
    def max_taps(self) -> int:
        return 0 if self.probes is None else int(self.probes.shape[0])


_ACTIVE_TAPS = threading.local()


def active_tap_context():
    return getattr(_ACTIVE_TAPS, "ctx", None)


@contextlib.contextmanager
def tap_context(ctx: TapContext):
    prev = active_tap_context()
    _ACTIVE_TAPS.ctx = ctx
    try:
        yield ctx
    finally:
        _ACTIVE_TAPS.ctx = prev


def tap(x, name: str):
    """Named numerics tap point.  No active TapContext (the default):
    returns x itself — zero cost, compiled out.  Active: arms the
    [fwd, grad] stats probe for this point."""
    ctx = active_tap_context()
    if ctx is None:
        return x
    i = len(ctx.names)
    ctx.names.append(str(name))
    if ctx.discover:
        return x
    if i >= ctx.max_taps:
        raise ValueError(
            f"tap {name!r} is tap #{i + 1} but the TapContext probes "
            f"array holds {ctx.max_taps} rows; raise "
            "TraceConfig.max_taps")
    return grad_tap(x, ctx.probes[i])


def dropout(key, rate: float, x):
    """Inverted-bernoulli dropout: zero with probability `rate`, scale
    survivors by 1/(1-rate).  The ONE implementation shared by the dense
    attention oracle, the models, and contrib modules so their dropout
    semantics can never diverge (the flash kernel's in-kernel
    counter-based mask is its hardware-PRNG counterpart)."""
    if rate == 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    import jax.numpy as jnp
    return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))
