"""Shared kernel-layer plumbing.

≡ the reference's shared native infrastructure (csrc/type_shim.h dtype
dispatch, csrc/compat.h): here it is backend dispatch — every fused op
has a Pallas TPU kernel and a pure-jnp reference implementation; on
non-TPU backends (CPU tests, interpret mode) the jnp path is used, the
same way the reference falls back to pure PyTorch when the extension is
absent (apex/normalization/fused_layer_norm.py:288-294).
"""

from __future__ import annotations

import os

import jax

_FORCE = os.environ.get("APEX_TPU_FORCE_PALLAS", "")


def use_pallas(override=None) -> bool:
    """Decide kernel path: Pallas on TPU, jnp reference elsewhere.

    `override`: True → pallas (interpret-mode off-TPU), False → jnp.
    Env APEX_TPU_FORCE_PALLAS=1/0 wins over the backend default.
    """
    if override is not None:
        return override
    if _FORCE == "1":
        return True
    if _FORCE == "0":
        return False
    return jax.default_backend() == "tpu"


def use_pallas_fusable(override=None) -> bool:
    """use_pallas for ops where XLA's automatic fusion usually wins.

    Memory-bound elementwise ops (LayerNorm/RMSNorm) fuse into their
    neighboring producers/consumers under XLA; a standalone Pallas
    kernel puts a custom_vjp/custom-call boundary in the way and costs
    a full extra HBM round trip (measured on v5e: GPT-350M step 41.9k
    -> 44.5k tok/s from letting XLA fuse the 49 LayerNorms).  The
    Pallas kernel remains available via override=True or
    APEX_TPU_FORCE_PALLAS=1 (and is what interpret-mode parity tests
    pin).
    """
    if override is not None:
        return override
    return _FORCE == "1"


def pallas_interpret() -> bool:
    """Pallas kernels run in interpret mode off-TPU (for CPU CI parity)."""
    return jax.default_backend() != "tpu"


def round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def row_block(rows: int, hidden: int, bytes_per_elt: int = 4,
              vmem_budget: int = 2 * 1024 * 1024, align: int = 8,
              cap: int = 1024) -> int:
    """Pick a row-block size so a (block, hidden) fp32 tile fits the VMEM
    budget; aligned to the fp32 sublane (8)."""
    b = max(align, vmem_budget // max(1, hidden * bytes_per_elt))
    b = min(b, cap, round_up(rows, align))
    return round_up(b, align) if b % align else b


def tuned_row_block(op: str, rows: int, hidden: int, **kw) -> int:
    """row_block with an autotuner override: consult apex_tpu.tune for
    (op, pow2-bucketed rows, hidden) on this device kind; a hit whose
    block_rows is a sane sublane multiple wins, anything else falls
    back to the deterministic heuristic.  Trace-time host-side lookup
    only — no device work (tune package docstring)."""
    base = row_block(rows, hidden, **kw)
    try:
        from apex_tpu import tune
        cfg = tune.tuned(op, dict(rows=tune.pow2_bucket(rows),
                                  hidden=hidden))
    except Exception:  # pragma: no cover — tuner must never break ops
        return base
    if cfg:
        blk = cfg.get("block_rows")
        if (isinstance(blk, int) and 8 <= blk <= 4096 and blk % 8 == 0):
            return blk
    return base


def dropout(key, rate: float, x):
    """Inverted-bernoulli dropout: zero with probability `rate`, scale
    survivors by 1/(1-rate).  The ONE implementation shared by the dense
    attention oracle, the models, and contrib modules so their dropout
    semantics can never diverge (the flash kernel's in-kernel
    counter-based mask is its hardware-PRNG counterpart)."""
    if rate == 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    import jax.numpy as jnp
    return jnp.where(mask, x / keep, jnp.zeros((), x.dtype))
