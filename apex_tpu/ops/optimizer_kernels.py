"""Fused optimizer kernels over flat 1-D parameter buffers — Pallas.

≡ the reference's `amp_C` extension (csrc/amp_C_frontend.cpp:175-204):
multi_tensor_{adam,sgd,adagrad,novograd,lamb,l2norm,scale,axpby} built on
the chunked multi_tensor_apply launcher (csrc/multi_tensor_apply.cuh:19-100).
The TPU re-design replaces "hundreds of tensors, chunked kernel launches"
with ONE flat fp32 buffer per state (see optimizers/flat.py for the
pytree<->buffer mapping ≡ apex_C.flatten/unflatten): a single Pallas
pass reads grad and state, applies decay/moments/bias-correction/update,
and writes params+state in place (input_output_aliases ≡ in-place CUDA
functors).  Grad unscaling and the overflow-skip are fused into the same
pass (≡ the capturable CUDA-graph Adam, apex/optimizers/fused_adam.py:199-263:
`inv_scale` multiply + `found_inf` masked update, no host sync).

Per-tensor reductions (LAMB trust ratios, NovoGrad per-tensor norms) are
computed as XLA segmented reductions over the flat buffer and passed in
as per-element vectors — the analogue of the reference's two-phase
l2norm→lamb launch pair (apex/optimizers/fused_lamb.py:124-199).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._common import pallas_interpret, use_pallas

_LANES = 128
_BLOCK_ROWS = 512  # (512, 128) fp32 tile = 256 KiB per operand

# Flat buffers created at optimizer init should be padded to this length
# multiple (optimizers/flat.py flatten(pad_to=...)); _to2d is then a free
# bitcast and the kernels run fully in place via input_output_aliases.
FLAT_TILE = _BLOCK_ROWS * _LANES


def _to2d(flat):
    n = flat.shape[0]
    pad = (-n) % (_BLOCK_ROWS * _LANES)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, _LANES), n


def _from2d(x2, n):
    return x2.reshape(-1)[:n]


def _elementwise_call(kernel, arrays, n_out, interpret_override=None):
    """Run an elementwise kernel over equally-shaped flat buffers.

    The first `n_out` arrays are updated in place (aliased), mirroring
    multi_tensor_apply's in-place tensor-list updates.
    """
    two_d = [_to2d(a)[0] for a in arrays]
    n = arrays[0].shape[0]
    rows = two_d[0].shape[0]
    grid = rows // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    interp = pallas_interpret() if interpret_override is None else interpret_override
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec] * len(two_d),
        out_specs=[spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct(two_d[0].shape, two_d[i].dtype)
                   for i in range(n_out)],
        input_output_aliases={i: i for i in range(n_out)},
        interpret=interp,
    )(*two_d)
    if n_out == 1:
        outs = [outs] if not isinstance(outs, (list, tuple)) else outs
    return [_from2d(o, n) for o in outs]


# ------------------------------- Adam ---------------------------------------

def _adam_kernel(p_ref, m_ref, v_ref, g_ref, sc_ref,
                 p_out, m_out, v_out, *,
                 beta1, beta2, eps, weight_decay, adam_w_mode,
                 bias_correction):
    """sc_ref rows: [lr, inv_scale, found_inf, bc1, bc2] broadcast scalars."""
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    lr = sc_ref[0, 0]
    inv_scale = sc_ref[1, 0]
    found_inf = sc_ref[2, 0]
    bc1 = sc_ref[3, 0]
    bc2 = sc_ref[4, 0]
    g = g * inv_scale
    if not adam_w_mode and weight_decay != 0.0:
        g = g + weight_decay * p  # L2 mode ≡ ADAM_MODE_1 (multi_tensor_adam.cu)
    m_new = beta1 * m + (1.0 - beta1) * g
    v_new = beta2 * v + (1.0 - beta2) * g * g
    if bias_correction:
        mhat = m_new / bc1
        vhat = v_new / bc2
    else:
        mhat, vhat = m_new, v_new
    update = mhat / (jnp.sqrt(vhat) + eps)
    if adam_w_mode and weight_decay != 0.0:
        update = update + weight_decay * p  # AdamW ≡ ADAM_MODE_0
    p_new = p - lr * update
    keep = found_inf > 0.5
    p_out[...] = jnp.where(keep, p, p_new).astype(p_out.dtype)
    m_out[...] = jnp.where(keep, m, m_new).astype(m_out.dtype)
    v_out[...] = jnp.where(keep, v, v_new).astype(v_out.dtype)


def adam_flat(p, m, v, g, lr, step, *, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=0.0, adam_w_mode=True, bias_correction=True,
              inv_scale=1.0, found_inf=False, use_pallas_override=None):
    """One fused Adam/AdamW step on flat buffers.

    ≡ amp_C.multi_tensor_adam / multi_tensor_adam_capturable
    (csrc/multi_tensor_adam.cu).  `step` may be traced (on-device step
    count, ≡ capturable mode's GPU-side `step` tensor).
    Returns (p, m, v) new buffers (donate inputs under jit).
    """
    step = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - jnp.power(jnp.float32(beta1), step)
    bc2 = 1.0 - jnp.power(jnp.float32(beta2), step)
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(inv_scale, jnp.float32),
        jnp.asarray(found_inf, jnp.float32),
        bc1, bc2,
    ]).reshape(5, 1)
    if not use_pallas(use_pallas_override):
        return _adam_reference(p, m, v, g, scalars, beta1, beta2, eps,
                               weight_decay, adam_w_mode, bias_correction)
    kernel = functools.partial(
        _adam_kernel, beta1=beta1, beta2=beta2, eps=eps,
        weight_decay=weight_decay, adam_w_mode=adam_w_mode,
        bias_correction=bias_correction)
    p2, np_ = _to2d(p)
    m2, _ = _to2d(m)
    v2, _ = _to2d(v)
    g2, _ = _to2d(g)
    rows = p2.shape[0]
    grid = rows // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((5, 1), lambda i: (0, 0))
    pn, mn, vn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, spec, sspec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype)],
        input_output_aliases={0: 0, 1: 1, 2: 2},
        interpret=pallas_interpret(),
    )(p2, m2, v2, g2, scalars)
    return _from2d(pn, np_), _from2d(mn, np_), _from2d(vn, np_)


def _adam_reference(p, m, v, g, scalars, beta1, beta2, eps, weight_decay,
                    adam_w_mode, bias_correction):
    lr, inv_scale, found_inf, bc1, bc2 = [scalars[i, 0] for i in range(5)]
    g = g.astype(jnp.float32) * inv_scale
    p32 = p.astype(jnp.float32)
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p32
    m32 = m.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    m_new = beta1 * m32 + (1 - beta1) * g
    v_new = beta2 * v32 + (1 - beta2) * g * g
    mhat = m_new / bc1 if bias_correction else m_new
    vhat = v_new / bc2 if bias_correction else v_new
    update = mhat / (jnp.sqrt(vhat) + eps)
    if adam_w_mode and weight_decay:
        update = update + weight_decay * p32
    p_new = p32 - lr * update
    keep = found_inf > 0.5
    return (jnp.where(keep, p32, p_new).astype(p.dtype),
            jnp.where(keep, m32, m_new).astype(m.dtype),
            jnp.where(keep, v32, v_new).astype(v.dtype))


# ------------------------------- SGD ----------------------------------------

def _sgd_kernel(p_ref, b_ref, g_ref, sc_ref, p_out, b_out, *,
                momentum, dampening, nesterov, weight_decay,
                wd_after_momentum, first_run):
    """sc rows: [lr, inv_scale, found_inf, first].  `first` selects the
    buf:=g initialization (torch's buf-is-None branch) IN-kernel so one
    aliased pass covers step 0 and steady state — a host-side where on
    the buffer would materialize a copy and break in-place aliasing.
    `first_run=True` forces the init branch statically."""
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    lr = sc_ref[0, 0]
    inv_scale = sc_ref[1, 0]
    found_inf = sc_ref[2, 0]
    first = sc_ref[3, 0] > 0.5
    g = g * inv_scale
    if weight_decay != 0.0 and not wd_after_momentum:
        g = g + weight_decay * p
    if momentum != 0.0:
        if first_run:
            b_new = g
        else:
            b_steady = momentum * b + (1.0 - dampening) * g
            b_new = jnp.where(first, g, b_steady)
        upd = g + momentum * b_new if nesterov else b_new
    else:
        b_new = b
        upd = g
    if weight_decay != 0.0 and wd_after_momentum:
        upd = upd + weight_decay * p
    p_new = p - lr * upd
    keep = found_inf > 0.5
    p_out[...] = jnp.where(keep, p, p_new).astype(p_out.dtype)
    b_out[...] = jnp.where(keep, b, b_new).astype(b_out.dtype)


def sgd_flat(p, buf, g, lr, *, momentum=0.0, dampening=0.0, nesterov=False,
             weight_decay=0.0, wd_after_momentum=False, first_run=False,
             first=False, inv_scale=1.0, found_inf=False,
             use_pallas_override=None):
    """≡ amp_C.multi_tensor_sgd (csrc/multi_tensor_sgd_kernel.cu).
    Returns (p, momentum_buffer).  `first` (traced bool) selects the
    buf:=g first-step branch in-kernel; `first_run` is its static form."""
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(inv_scale, jnp.float32),
        jnp.asarray(found_inf, jnp.float32),
        jnp.asarray(first, jnp.float32),
    ]).reshape(4, 1)
    if not use_pallas(use_pallas_override):
        # jnp fallback mirrors the kernel exactly
        g32 = g.astype(jnp.float32) * scalars[1, 0]
        p32 = p.astype(jnp.float32)
        if weight_decay and not wd_after_momentum:
            g32 = g32 + weight_decay * p32
        if momentum != 0.0:
            if first_run:
                b_new = g32
            else:
                b_new = jnp.where(scalars[3, 0] > 0.5, g32,
                                  momentum * buf.astype(jnp.float32)
                                  + (1 - dampening) * g32)
            upd = g32 + momentum * b_new if nesterov else b_new
        else:
            b_new, upd = buf, g32
        if weight_decay and wd_after_momentum:
            upd = upd + weight_decay * p32
        p_new = p32 - scalars[0, 0] * upd
        keep = scalars[2, 0] > 0.5
        b32 = buf.astype(jnp.float32)
        b_new = b_new.astype(jnp.float32)
        return (jnp.where(keep, p32, p_new).astype(p.dtype),
                jnp.where(keep, b32, b_new).astype(buf.dtype))
    kernel = functools.partial(
        _sgd_kernel, momentum=momentum, dampening=dampening,
        nesterov=nesterov, weight_decay=weight_decay,
        wd_after_momentum=wd_after_momentum, first_run=first_run)
    p2, n = _to2d(p)
    b2, _ = _to2d(buf)
    g2, _ = _to2d(g)
    grid = p2.shape[0] // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((4, 1), lambda i: (0, 0))
    pn, bn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, sspec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(b2.shape, b2.dtype)],
        input_output_aliases={0: 0, 1: 1},
        interpret=pallas_interpret(),
    )(p2, b2, g2, scalars)
    return _from2d(pn, n), _from2d(bn, n)


# ----------------------------- Adagrad --------------------------------------

def _adagrad_kernel(p_ref, h_ref, g_ref, sc_ref, p_out, h_out, *,
                    eps, weight_decay, adagrad_w_mode):
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    h = h_ref[...]
    lr = sc_ref[0, 0]
    if not adagrad_w_mode and weight_decay != 0.0:
        g = g + weight_decay * p
    h_new = h + g * g
    upd = g / (jnp.sqrt(h_new) + eps)
    if adagrad_w_mode and weight_decay != 0.0:
        upd = upd + weight_decay * p
    p_out[...] = (p - lr * upd).astype(p_out.dtype)
    h_out[...] = h_new


def adagrad_flat(p, h, g, lr, *, eps=1e-10, weight_decay=0.0,
                 adagrad_w_mode=False, use_pallas_override=None):
    """≡ amp_C.multi_tensor_adagrad (csrc/multi_tensor_adagrad.cu)."""
    scalars = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    if not use_pallas(use_pallas_override):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if not adagrad_w_mode and weight_decay:
            g32 = g32 + weight_decay * p32
        h_new = h + g32 * g32
        upd = g32 / (jnp.sqrt(h_new) + eps)
        if adagrad_w_mode and weight_decay:
            upd = upd + weight_decay * p32
        return (p32 - scalars[0, 0] * upd).astype(p.dtype), h_new
    kernel = functools.partial(_adagrad_kernel, eps=eps,
                               weight_decay=weight_decay,
                               adagrad_w_mode=adagrad_w_mode)
    p2, n = _to2d(p)
    h2, _ = _to2d(h)
    g2, _ = _to2d(g)
    grid = p2.shape[0] // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    pn, hn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, sspec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(h2.shape, jnp.float32)],
        input_output_aliases={0: 0, 1: 1},
        interpret=pallas_interpret(),
    )(p2, h2, g2, scalars)
    return _from2d(pn, n), _from2d(hn, n)


# ------------------------- LAMB (two-phase) ---------------------------------

def _lamb_phase1_kernel(m_ref, v_ref, g_ref, p_ref, sc_ref,
                        m_out, v_out, u_out, *,
                        beta1, beta2, beta3, eps, weight_decay,
                        bias_correction):
    """Phase 1 ≡ amp_C.multi_tensor_lamb_stage1 / lamb stage computing the
    raw update u = mhat/(sqrt(vhat)+eps) + wd*p with global-grad-norm
    clipping fused (sc rows: [clip_ratio, bc1, bc2]).  beta3 is the grad
    coefficient of the m update: 1-beta1 under grad averaging, else 1
    (≡ the reference's beta3 in multi_tensor_lamb.cu)."""
    g = g_ref[...].astype(jnp.float32) * sc_ref[0, 0]
    p = p_ref[...].astype(jnp.float32)
    m_new = beta1 * m_ref[...] + beta3 * g
    v_new = beta2 * v_ref[...] + (1.0 - beta2) * g * g
    mhat = m_new / sc_ref[1, 0] if bias_correction else m_new
    vhat = v_new / sc_ref[2, 0] if bias_correction else v_new
    u = mhat / (jnp.sqrt(vhat) + eps)
    if weight_decay != 0.0:
        u = u + weight_decay * p
    m_out[...] = m_new
    v_out[...] = v_new
    u_out[...] = u


def _lamb_phase2_kernel(p_ref, u_ref, r_ref, sc_ref, p_out):
    """Phase 2 ≡ multi_tensor_lamb_stage2: p -= lr * trust_ratio * u, with
    the per-element trust-ratio vector r."""
    lr = sc_ref[0, 0]
    p = p_ref[...].astype(jnp.float32)
    p_out[...] = (p - lr * r_ref[...] * u_ref[...]).astype(p_out.dtype)


def lamb_phase1_flat(m, v, g, p, clip_ratio, step, *, beta1, beta2, eps,
                     weight_decay, bias_correction=True,
                     grad_averaging=True, use_pallas_override=None):
    beta3 = (1.0 - beta1) if grad_averaging else 1.0
    step = jnp.asarray(step, jnp.float32)
    bc1 = 1.0 - jnp.power(jnp.float32(beta1), step)
    bc2 = 1.0 - jnp.power(jnp.float32(beta2), step)
    scalars = jnp.stack([jnp.asarray(clip_ratio, jnp.float32), bc1,
                         bc2]).reshape(3, 1)
    if not use_pallas(use_pallas_override):
        g32 = g.astype(jnp.float32) * scalars[0, 0]
        p32 = p.astype(jnp.float32)
        m_new = beta1 * m + beta3 * g32
        v_new = beta2 * v + (1 - beta2) * g32 * g32
        mhat = m_new / bc1 if bias_correction else m_new
        vhat = v_new / bc2 if bias_correction else v_new
        u = mhat / (jnp.sqrt(vhat) + eps)
        if weight_decay:
            u = u + weight_decay * p32
        return m_new, v_new, u
    kernel = functools.partial(
        _lamb_phase1_kernel, beta1=beta1, beta2=beta2, beta3=beta3, eps=eps,
        weight_decay=weight_decay, bias_correction=bias_correction)
    m2, n = _to2d(m)
    v2, _ = _to2d(v)
    g2, _ = _to2d(g)
    p2, _ = _to2d(p)
    grid = m2.shape[0] // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((3, 1), lambda i: (0, 0))
    mn, vn, u = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, spec, sspec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct(m2.shape, jnp.float32)] * 3,
        input_output_aliases={0: 0, 1: 1},
        interpret=pallas_interpret(),
    )(m2, v2, g2, p2, scalars)
    return _from2d(mn, n), _from2d(vn, n), _from2d(u, n)


def lamb_phase2_flat(p, u, ratio_elem, lr, use_pallas_override=None):
    scalars = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    if not use_pallas(use_pallas_override):
        return (p.astype(jnp.float32) - scalars[0, 0] * ratio_elem * u
                ).astype(p.dtype)
    p2, n = _to2d(p)
    u2, _ = _to2d(u)
    r2, _ = _to2d(ratio_elem)
    grid = p2.shape[0] // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    pn = pl.pallas_call(
        _lamb_phase2_kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, sspec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(p2.shape, p2.dtype),
        input_output_aliases={0: 0},
        interpret=pallas_interpret(),
    )(p2, u2, r2, scalars)
    return _from2d(pn, n)


# --------------------------- reductions / utilities -------------------------

def l2norm_flat(flat):
    """Global L2 norm ≡ amp_C.multi_tensor_l2norm (csrc/multi_tensor_l2norm_kernel.cu).
    XLA lowers this to an optimal tree reduction; no Pallas needed."""
    return jnp.sqrt(jnp.sum(jnp.square(flat.astype(jnp.float32))))


def per_tensor_l2norm(flat, sizes):
    """Per-tensor norms over a flat buffer ≡ multi_tensor_l2norm
    per_tensor=True mode.  `sizes` is the static segment-length list."""
    norms = []
    off = 0
    for s in sizes:
        seg = jax.lax.dynamic_slice(flat, (off,), (s,))
        norms.append(jnp.sqrt(jnp.sum(jnp.square(seg.astype(jnp.float32)))))
        off += s
    return jnp.stack(norms)


def expand_per_tensor(values, sizes, total):
    """Broadcast per-tensor scalars to per-element vector (static sizes)."""
    return jnp.repeat(values, jnp.asarray(sizes), total_repeat_length=total)


def scale_flat(flat, scale):
    """≡ amp_C.multi_tensor_scale: scaled copy; overflow check is fused by
    XLA into the same pass when consumed with jnp.isfinite."""
    return flat.astype(jnp.float32) * scale


def axpby_flat(a, x, b, y):
    """≡ amp_C.multi_tensor_axpby: a*x + b*y."""
    return a * x.astype(jnp.float32) + b * y.astype(jnp.float32)


# --- row-aligned per-tensor reductions (128-lane-aligned FlatSpec) ----------
#
# With FlatSpec(align=_LANES) every tensor's segment spans whole rows of
# the (rows, 128) 2-D view (zero-filled tails), so multi_tensor_l2norm's
# per_tensor mode becomes: one squared-row-sum pass + one static
# segment-sum — instead of one dynamic_slice+reduction per tensor (which
# at BERT/GPT scale is ~600 serialized slices over the whole buffer).

def _row_segment_ids(spec):
    import numpy as _np
    # segment extents come straight from the spec's (aligned) offsets so
    # this can never drift from make_spec's padding rule
    bounds = list(spec.offsets) + [spec.total]
    rows = [(bounds[i + 1] - bounds[i]) // _LANES
            for i in range(len(spec.offsets))]
    return _np.repeat(_np.arange(len(rows), dtype=_np.int32), rows)


def per_tensor_l2norm_aligned(flat, spec):
    """Per-tensor L2 norms over a lane-aligned flat buffer; `spec.align`
    must be a multiple of the 128-lane width."""
    assert spec.align % _LANES == 0, "spec must be lane-aligned"
    x2 = flat[: spec.total].reshape(-1, _LANES).astype(jnp.float32)
    rowsq = jnp.sum(x2 * x2, axis=1)                      # (rows,)
    seg = jnp.asarray(_row_segment_ids(spec))             # static constant
    sums = jax.ops.segment_sum(rowsq, seg,
                               num_segments=len(spec.sizes))
    return jnp.sqrt(sums)


def expand_per_tensor_aligned(values, spec, total):
    """Broadcast per-tensor scalars to a per-element vector of `total`
    length (>= spec.total; the tail repeats the last value, harmless on
    zero padding)."""
    assert spec.align % _LANES == 0
    seg = jnp.asarray(_row_segment_ids(spec))
    per_row = values[seg]                                  # (rows,)
    elem = jnp.broadcast_to(per_row[:, None],
                            (per_row.shape[0], _LANES)).reshape(-1)
    if total > elem.shape[0]:
        elem = jnp.concatenate(
            [elem, jnp.broadcast_to(values[-1], (total - elem.shape[0],))])
    return elem


def _row_segment_ids_padded(spec, rows_total):
    """Row→tensor map over the PADDED buffer (rows_total >= spec rows):
    tail padding rows get the dummy id len(spec.sizes)."""
    import numpy as _np
    base = _row_segment_ids(spec)
    pad = rows_total - base.shape[0]
    return _np.concatenate(
        [base, _np.full((pad,), len(spec.sizes), _np.int32)])


def shard_segment_ids(spec, rank, rows_shard, padded_total):
    """This rank's slice of the padded row→tensor map (tail padding rows
    get the dummy id len(spec.sizes)).  The shard is a contiguous flat
    slice [rank*S, (rank+1)*S) with S a multiple of FLAT_TILE, so its
    rows are a contiguous run of the global row map — a dynamic slice at
    a traced `rank` is all it takes.  Compute ONCE per step and pass to
    the per-tensor helpers below (the full row map is O(params/128))."""
    assert spec.align % _LANES == 0
    seg_full = jnp.asarray(
        _row_segment_ids_padded(spec, padded_total // _LANES))
    return jax.lax.dynamic_slice(seg_full, (rank * rows_shard,),
                                 (rows_shard,))


def per_tensor_sumsq_shard(shard, spec, seg):
    """Per-tensor PARTIAL sums of squares over ONE rank's contiguous
    flat shard (`seg` from shard_segment_ids).  A psum over the shard
    axis yields the exact full-buffer per-tensor sums — no rank ever
    materializes the full buffer (≡ the reference's pipelined
    block-reduction L2 norms, distributed_fused_lamb.py:728-987, which
    exist for the same reason).  Returns (n_tensors,) fp32 partial sums;
    the dummy tail segment (zero padding) is dropped."""
    x2 = shard.reshape(-1, _LANES).astype(jnp.float32)
    rowsq = jnp.sum(x2 * x2, axis=1)                      # (rows,)
    sums = jax.ops.segment_sum(rowsq, seg,
                               num_segments=len(spec.sizes) + 1)
    return sums[: len(spec.sizes)]


def expand_per_tensor_shard(values, seg):
    """Broadcast per-tensor scalars to ONE rank's shard elements —
    the shard-local counterpart of expand_per_tensor_aligned (padding
    rows broadcast 1.0, harmless on zero-padded updates)."""
    rows_shard = seg.shape[0]
    vals = jnp.concatenate(
        [values.astype(jnp.float32), jnp.ones((1,), jnp.float32)])
    per_row = vals[seg]                                    # (rows,)
    return jnp.broadcast_to(per_row[:, None],
                            (rows_shard, _LANES)).reshape(-1)
