"""Fused optimizer kernels over flat 1-D parameter buffers — Pallas.

≡ the reference's `amp_C` extension (csrc/amp_C_frontend.cpp:175-204):
multi_tensor_{adam,sgd,adagrad,novograd,lamb,l2norm,scale,axpby} built on
the chunked multi_tensor_apply launcher (csrc/multi_tensor_apply.cuh:19-100).
The TPU re-design replaces "hundreds of tensors, chunked kernel launches"
with ONE flat fp32 buffer per state (see optimizers/flat.py for the
pytree<->buffer mapping ≡ apex_C.flatten/unflatten): a single Pallas
pass reads grad and state, applies decay/moments/bias-correction/update,
and writes params+state in place (input_output_aliases ≡ in-place CUDA
functors).  Grad unscaling and the overflow-skip are fused into the same
pass (≡ the capturable CUDA-graph Adam, apex/optimizers/fused_adam.py:199-263:
`inv_scale` multiply + `found_inf` masked update, no host sync).

Per-tensor reductions (LAMB trust ratios, NovoGrad per-tensor norms) are
computed as XLA segmented reductions over the flat buffer and passed in
as per-element vectors — the analogue of the reference's two-phase
l2norm→lamb launch pair (apex/optimizers/fused_lamb.py:124-199).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import pallas_interpret, use_pallas

_LANES = 128
_BLOCK_ROWS = 512  # (512, 128) fp32 tile = 256 KiB per operand

# Flat buffers created at optimizer init should be padded to this length
# multiple (optimizers/flat.py flatten(pad_to=...)); _to2d is then a free
# bitcast and the kernels run fully in place via input_output_aliases.
FLAT_TILE = _BLOCK_ROWS * _LANES


def _to2d(flat):
    n = flat.shape[0]
    pad = (-n) % (_BLOCK_ROWS * _LANES)
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(-1, _LANES), n


def _block_rows(rows: int, kernel: str) -> int:
    """Rows-per-block for a flat kernel's grid: the autotuned value for
    (kernel, pow2-bucketed rows) when it divides the (FLAT_TILE-padded)
    row count, else the swept default _BLOCK_ROWS.  Trace-time lookup
    only (apex_tpu.tune) — an empty cache is byte-identical to the
    constant."""
    try:
        from apex_tpu import tune
        cfg = tune.tuned("opt_flat", dict(kernel=kernel,
                                          rows=tune.pow2_bucket(rows)))
    except Exception:  # pragma: no cover — tuner must never break opts
        return _BLOCK_ROWS
    if cfg:
        br = cfg.get("block_rows")
        if isinstance(br, int) and 8 <= br <= 4096 and rows % br == 0:
            return br
    return _BLOCK_ROWS


def _from2d(x2, n):
    return x2.reshape(-1)[:n]


def _elementwise_call(kernel, arrays, n_out, interpret_override=None):
    """Run an elementwise kernel over equally-shaped flat buffers.

    The first `n_out` arrays are updated in place (aliased), mirroring
    multi_tensor_apply's in-place tensor-list updates.
    """
    two_d = [_to2d(a)[0] for a in arrays]
    n = arrays[0].shape[0]
    rows = two_d[0].shape[0]
    R = _block_rows(rows, "elementwise")
    grid = rows // R
    spec = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    interp = pallas_interpret() if interpret_override is None else interpret_override
    outs = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec] * len(two_d),
        out_specs=[spec] * n_out,
        out_shape=[jax.ShapeDtypeStruct(two_d[0].shape, two_d[i].dtype)
                   for i in range(n_out)],
        input_output_aliases={i: i for i in range(n_out)},
        interpret=interp,
    )(*two_d)
    if n_out == 1:
        outs = [outs] if not isinstance(outs, (list, tuple)) else outs
    return [_from2d(o, n) for o in outs]


# ------------------------------- Adam ---------------------------------------

def _adam_kernel(p_ref, m_ref, v_ref, g_ref, sc_ref,
                 p_out, m_out, v_out, *,
                 eps, weight_decay, adam_w_mode):
    """sc_ref rows: [lr_eff, inv_scale, b1e, c1, b2e, c2, rbc1, rbc2,
    found].

    The overflow-skip and bias correction are FOLDED INTO THE SCALARS on
    the host (adam_flat): found_inf sets lr_eff=0, b*e=1, c*=0 and the
    single g select below zeroes the (inf/nan) grad stream, so the
    elementwise pass needs one select instead of three and the 1/bc
    divides become rbc multiplies — the VPU (not HBM) is the bound for
    bf16 state, so per-element op count is what this kernel optimizes."""
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    lr_eff = sc_ref[0, 0]
    inv_scale = sc_ref[1, 0]
    b1e, c1 = sc_ref[2, 0], sc_ref[3, 0]
    b2e, c2 = sc_ref[4, 0], sc_ref[5, 0]
    rbc1, rbc2 = sc_ref[6, 0], sc_ref[7, 0]
    # the one per-element select: inf/nan grads would otherwise poison
    # m/v through 0*inf=nan even with c1=c2=0
    g = jnp.where(sc_ref[8, 0] > 0.5, 0.0, g * inv_scale)
    if not adam_w_mode and weight_decay != 0.0:
        g = g + weight_decay * p  # L2 mode ≡ ADAM_MODE_1 (multi_tensor_adam.cu)
    m_new = b1e * m + c1 * g
    v_new = b2e * v + c2 * (g * g)
    update = (m_new * rbc1) / (jnp.sqrt(v_new * rbc2) + eps)
    if adam_w_mode and weight_decay != 0.0:
        update = update + weight_decay * p  # AdamW ≡ ADAM_MODE_0
    p_out[...] = (p - lr_eff * update).astype(p_out.dtype)
    m_out[...] = m_new.astype(m_out.dtype)
    v_out[...] = v_new.astype(v_out.dtype)


def _adam_fold_scalars(lr, step, beta1, beta2, bias_correction,
                       inv_scale, found_inf):
    """The ONE definition of the Adam folded-scalar rows (shared by the
    uniform and per-tensor-seg variants, which must stay numerically
    identical).  clamp: at step 0 (reachable only when found_inf skips
    the very first update, so m=v=0) bc would be 0 and 1/bc inf —
    inf*0=nan would poison the select-free kernel."""
    step = jnp.asarray(step, jnp.float32)
    bc1 = jnp.maximum(1.0 - jnp.power(jnp.float32(beta1), step), 1e-20)
    bc2 = jnp.maximum(1.0 - jnp.power(jnp.float32(beta2), step), 1e-20)
    one = jnp.float32(1.0)
    keep = jnp.asarray(found_inf).astype(jnp.bool_)
    # fold overflow-skip + bias correction into broadcast scalars: the
    # kernel then runs select-free and divide-free (one vector divide
    # left) — see _adam_kernel
    return jnp.stack([
        jnp.where(keep, 0.0, jnp.asarray(lr, jnp.float32)),   # lr_eff
        jnp.asarray(inv_scale, jnp.float32),
        jnp.where(keep, one, jnp.float32(beta1)),             # b1e
        jnp.where(keep, 0.0, 1.0 - jnp.float32(beta1)),       # c1
        jnp.where(keep, one, jnp.float32(beta2)),             # b2e
        jnp.where(keep, 0.0, 1.0 - jnp.float32(beta2)),       # c2
        one / bc1 if bias_correction else one,                # rbc1
        one / bc2 if bias_correction else one,                # rbc2
        keep.astype(jnp.float32),                             # found
    ]).reshape(9, 1)


def adam_flat(p, m, v, g, lr, step, *, beta1=0.9, beta2=0.999, eps=1e-8,
              weight_decay=0.0, adam_w_mode=True, bias_correction=True,
              inv_scale=1.0, found_inf=False, use_pallas_override=None):
    """One fused Adam/AdamW step on flat buffers.

    ≡ amp_C.multi_tensor_adam / multi_tensor_adam_capturable
    (csrc/multi_tensor_adam.cu).  `step` may be traced (on-device step
    count, ≡ capturable mode's GPU-side `step` tensor).
    Returns (p, m, v) new buffers (donate inputs under jit).
    """
    scalars = _adam_fold_scalars(lr, step, beta1, beta2, bias_correction,
                                 inv_scale, found_inf)
    if not use_pallas(use_pallas_override):
        return _adam_reference(p, m, v, g, scalars, eps,
                               weight_decay, adam_w_mode)
    kernel = functools.partial(
        _adam_kernel, eps=eps,
        weight_decay=weight_decay, adam_w_mode=adam_w_mode)
    p2, np_ = _to2d(p)
    m2, _ = _to2d(m)
    v2, _ = _to2d(v)
    g2, _ = _to2d(g)
    rows = p2.shape[0]
    R = _block_rows(rows, "adam")
    grid = rows // R
    spec = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((9, 1), lambda i: (0, 0))
    pn, mn, vn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, spec, sspec],
        out_specs=[spec, spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype)],
        input_output_aliases={0: 0, 1: 1, 2: 2},
        interpret=pallas_interpret(),
    )(p2, m2, v2, g2, scalars)
    return _from2d(pn, np_), _from2d(mn, np_), _from2d(vn, np_)


def _adam_reference(p, m, v, g, scalars, eps, weight_decay, adam_w_mode):
    """Same folded-scalar contract as _adam_kernel (the CPU oracle)."""
    (lr_eff, inv_scale, b1e, c1, b2e, c2, rbc1, rbc2, found) = [
        scalars[i, 0] for i in range(9)]
    g = jnp.where(found > 0.5, 0.0, g.astype(jnp.float32) * inv_scale)
    p32 = p.astype(jnp.float32)
    if not adam_w_mode and weight_decay:
        g = g + weight_decay * p32
    m32 = m.astype(jnp.float32)
    v32 = v.astype(jnp.float32)
    m_new = b1e * m32 + c1 * g
    v_new = b2e * v32 + c2 * (g * g)
    update = (m_new * rbc1) / (jnp.sqrt(v_new * rbc2) + eps)
    if adam_w_mode and weight_decay:
        update = update + weight_decay * p32
    p_new = p32 - lr_eff * update
    return (p_new.astype(p.dtype), m_new.astype(m.dtype),
            v_new.astype(v.dtype))


def _adam_seg_kernel(p_ref, m_ref, v_ref, g_ref, sc_ref, lo_ref, hi_ref,
                     vals_ref, off_ref, p_out, m_out, v_out, *,
                     eps, adam_w_mode, npad, R):
    """_adam_kernel with PER-TENSOR weight decay and lr scale: vals_ref
    row 0 holds each tensor's weight decay, row 1 its lr multiplier;
    the per-row pair is rebuilt per block from the static segment row
    bounds via one one-hot matmul (the lamb_phase2_seg trick) — the
    (total,) per-element vectors never exist in HBM.

    ≡ the reference's param_groups loop (apex/optimizers/fused_adam.py:
    156-303), which launches multi_tensor_adam once per group with that
    group's lr/weight_decay — here one pass covers every group.
    Padding rows fall outside every bound → wd=0 AND lr scale 0, so the
    zero-filled tails never move."""
    i = pl.program_id(0)
    oh = _block_onehot(lo_ref, hi_ref, off_ref, i, R, npad)
    # one select-matmul yields both per-row values; HIGHEST keeps the
    # fp32 hyperparameters exact (default MXU path rounds to bf16)
    wl = jax.lax.dot_general(oh, vals_ref[0:2, :],
                             (((1,), (1,)), ((), ())),
                             precision=jax.lax.Precision.HIGHEST)
    wd_row, lrs_row = wl[:, 0:1], wl[:, 1:2]
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    m = m_ref[...].astype(jnp.float32)
    v = v_ref[...].astype(jnp.float32)
    lr_eff = sc_ref[0, 0]
    inv_scale = sc_ref[1, 0]
    b1e, c1 = sc_ref[2, 0], sc_ref[3, 0]
    b2e, c2 = sc_ref[4, 0], sc_ref[5, 0]
    rbc1, rbc2 = sc_ref[6, 0], sc_ref[7, 0]
    g = jnp.where(sc_ref[8, 0] > 0.5, 0.0, g * inv_scale)
    if not adam_w_mode:
        g = g + wd_row * p
    m_new = b1e * m + c1 * g
    v_new = b2e * v + c2 * (g * g)
    update = (m_new * rbc1) / (jnp.sqrt(v_new * rbc2) + eps)
    if adam_w_mode:
        update = update + wd_row * p
    p_out[...] = (p - lr_eff * lrs_row * update).astype(p_out.dtype)
    m_out[...] = m_new.astype(m_out.dtype)
    v_out[...] = v_new.astype(v_out.dtype)


def _seg_vals2(wd_values, lr_scale_values, npad):
    n_seg = wd_values.shape[0]
    vals = jnp.zeros((8, npad), jnp.float32)
    vals = vals.at[0, :n_seg].set(wd_values.astype(jnp.float32))
    vals = vals.at[1, :n_seg].set(lr_scale_values.astype(jnp.float32))
    return vals


def adam_flat_seg(p, m, v, g, lr, step, *, wd_values, lr_scale_values,
                  spec, row_offset=0, padded_total=None,
                  beta1=0.9, beta2=0.999, eps=1e-8, adam_w_mode=True,
                  bias_correction=True, inv_scale=1.0, found_inf=False,
                  use_pallas_override=None):
    """adam_flat with per-tensor (weight_decay, lr_scale) vectors — the
    consumer of get_params_for_weight_decay_optimization's mask.  `spec`
    must be lane-aligned (FlatSpec(align=128)); `row_offset` is p's
    global starting row for ZeRO shards (may be traced; `padded_total`
    is then required for the jnp fallback's segment map)."""
    scalars = _adam_fold_scalars(lr, step, beta1, beta2, bias_correction,
                                 inv_scale, found_inf)
    wd_values = jnp.asarray(wd_values, jnp.float32)
    lr_scale_values = jnp.asarray(lr_scale_values, jnp.float32)
    n_seg = wd_values.shape[0]
    npad = _seg_pad(n_seg)
    if not (use_pallas(use_pallas_override) and n_seg + 1 < _SEG_CAP
            and p.shape[0] % FLAT_TILE == 0):
        rows = p.shape[0] // _LANES
        total = padded_total if padded_total is not None else p.shape[0]
        rank = jnp.asarray(row_offset, jnp.int32) // rows
        seg = shard_segment_ids(spec, rank, rows, total)
        wd_elem = expand_per_tensor_shard(wd_values, seg)
        lrs_elem = expand_per_tensor_shard(lr_scale_values, seg)
        return _adam_seg_reference(p, m, v, g, scalars, eps, adam_w_mode,
                                   wd_elem, lrs_elem)
    p2, np_ = _to2d(p)
    m2, _ = _to2d(m)
    v2, _ = _to2d(v)
    g2, _ = _to2d(g)
    R = _block_rows(p2.shape[0], "adam_seg")
    grid = p2.shape[0] // R
    lo, hi = _seg_row_bounds(spec, npad)
    vals = _seg_vals2(wd_values, lr_scale_values, npad)
    off = jnp.asarray(row_offset, jnp.int32).reshape(1, 1)
    bspec = pl.BlockSpec((8, npad), lambda i: (0, 0))
    spec_b = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    pn, mn, vn = pl.pallas_call(
        functools.partial(_adam_seg_kernel, eps=eps,
                          adam_w_mode=adam_w_mode, npad=npad, R=R),
        grid=(grid,),
        in_specs=[spec_b, spec_b, spec_b, spec_b,
                  pl.BlockSpec((9, 1), lambda i: (0, 0)),
                  bspec, bspec, bspec,
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=[spec_b, spec_b, spec_b],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype)],
        input_output_aliases={0: 0, 1: 1, 2: 2},
        interpret=pallas_interpret(),
    )(p2, m2, v2, g2, scalars, lo, hi, vals, off)
    return _from2d(pn, np_), _from2d(mn, np_), _from2d(vn, np_)


def _adam_seg_reference(p, m, v, g, scalars, eps, adam_w_mode, wd_elem,
                        lrs_elem):
    """Per-element-vector oracle with the same folded-scalar contract."""
    (lr_eff, inv_scale, b1e, c1, b2e, c2, rbc1, rbc2, found) = [
        scalars[i, 0] for i in range(9)]
    g = jnp.where(found > 0.5, 0.0, g.astype(jnp.float32) * inv_scale)
    p32 = p.astype(jnp.float32)
    if not adam_w_mode:
        g = g + wd_elem * p32
    m_new = b1e * m.astype(jnp.float32) + c1 * g
    v_new = b2e * v.astype(jnp.float32) + c2 * (g * g)
    update = (m_new * rbc1) / (jnp.sqrt(v_new * rbc2) + eps)
    if adam_w_mode:
        update = update + wd_elem * p32
    p_new = p32 - lr_eff * lrs_elem * update
    return (p_new.astype(p.dtype), m_new.astype(m.dtype),
            v_new.astype(v.dtype))


# ------------------------------- SGD ----------------------------------------

def _sgd_kernel(p_ref, b_ref, g_ref, sc_ref, p_out, b_out, *,
                momentum, dampening, nesterov, weight_decay,
                wd_after_momentum, first_run):
    """sc rows: [lr, inv_scale, found_inf, first].  `first` selects the
    buf:=g initialization (torch's buf-is-None branch) IN-kernel so one
    aliased pass covers step 0 and steady state — a host-side where on
    the buffer would materialize a copy and break in-place aliasing.
    `first_run=True` forces the init branch statically."""
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    b = b_ref[...].astype(jnp.float32)
    lr = sc_ref[0, 0]
    inv_scale = sc_ref[1, 0]
    found_inf = sc_ref[2, 0]
    first = sc_ref[3, 0] > 0.5
    g = g * inv_scale
    if weight_decay != 0.0 and not wd_after_momentum:
        g = g + weight_decay * p
    if momentum != 0.0:
        if first_run:
            b_new = g
        else:
            b_steady = momentum * b + (1.0 - dampening) * g
            b_new = jnp.where(first, g, b_steady)
        upd = g + momentum * b_new if nesterov else b_new
    else:
        b_new = b
        upd = g
    if weight_decay != 0.0 and wd_after_momentum:
        upd = upd + weight_decay * p
    p_new = p - lr * upd
    keep = found_inf > 0.5
    p_out[...] = jnp.where(keep, p, p_new).astype(p_out.dtype)
    b_out[...] = jnp.where(keep, b, b_new).astype(b_out.dtype)


def sgd_flat(p, buf, g, lr, *, momentum=0.0, dampening=0.0, nesterov=False,
             weight_decay=0.0, wd_after_momentum=False, first_run=False,
             first=False, inv_scale=1.0, found_inf=False,
             use_pallas_override=None):
    """≡ amp_C.multi_tensor_sgd (csrc/multi_tensor_sgd_kernel.cu).
    Returns (p, momentum_buffer).  `first` (traced bool) selects the
    buf:=g first-step branch in-kernel; `first_run` is its static form."""
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(inv_scale, jnp.float32),
        jnp.asarray(found_inf, jnp.float32),
        jnp.asarray(first, jnp.float32),
    ]).reshape(4, 1)
    if not use_pallas(use_pallas_override):
        # jnp fallback mirrors the kernel exactly
        g32 = g.astype(jnp.float32) * scalars[1, 0]
        p32 = p.astype(jnp.float32)
        if weight_decay and not wd_after_momentum:
            g32 = g32 + weight_decay * p32
        if momentum != 0.0:
            if first_run:
                b_new = g32
            else:
                b_new = jnp.where(scalars[3, 0] > 0.5, g32,
                                  momentum * buf.astype(jnp.float32)
                                  + (1 - dampening) * g32)
            upd = g32 + momentum * b_new if nesterov else b_new
        else:
            b_new, upd = buf, g32
        if weight_decay and wd_after_momentum:
            upd = upd + weight_decay * p32
        p_new = p32 - scalars[0, 0] * upd
        keep = scalars[2, 0] > 0.5
        b32 = buf.astype(jnp.float32)
        b_new = b_new.astype(jnp.float32)
        return (jnp.where(keep, p32, p_new).astype(p.dtype),
                jnp.where(keep, b32, b_new).astype(buf.dtype))
    kernel = functools.partial(
        _sgd_kernel, momentum=momentum, dampening=dampening,
        nesterov=nesterov, weight_decay=weight_decay,
        wd_after_momentum=wd_after_momentum, first_run=first_run)
    p2, n = _to2d(p)
    b2, _ = _to2d(buf)
    g2, _ = _to2d(g)
    R = _block_rows(p2.shape[0], "sgd")
    grid = p2.shape[0] // R
    spec = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((4, 1), lambda i: (0, 0))
    pn, bn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, sspec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(b2.shape, b2.dtype)],
        input_output_aliases={0: 0, 1: 1},
        interpret=pallas_interpret(),
    )(p2, b2, g2, scalars)
    return _from2d(pn, n), _from2d(bn, n)


# ----------------------------- Adagrad --------------------------------------

def _adagrad_kernel(p_ref, h_ref, g_ref, sc_ref, p_out, h_out, *,
                    eps, weight_decay, adagrad_w_mode):
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    h = h_ref[...]
    lr = sc_ref[0, 0]
    if not adagrad_w_mode and weight_decay != 0.0:
        g = g + weight_decay * p
    h_new = h + g * g
    upd = g / (jnp.sqrt(h_new) + eps)
    if adagrad_w_mode and weight_decay != 0.0:
        upd = upd + weight_decay * p
    p_out[...] = (p - lr * upd).astype(p_out.dtype)
    h_out[...] = h_new


def adagrad_flat(p, h, g, lr, *, eps=1e-10, weight_decay=0.0,
                 adagrad_w_mode=False, use_pallas_override=None):
    """≡ amp_C.multi_tensor_adagrad (csrc/multi_tensor_adagrad.cu)."""
    scalars = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    if not use_pallas(use_pallas_override):
        g32 = g.astype(jnp.float32)
        p32 = p.astype(jnp.float32)
        if not adagrad_w_mode and weight_decay:
            g32 = g32 + weight_decay * p32
        h_new = h + g32 * g32
        upd = g32 / (jnp.sqrt(h_new) + eps)
        if adagrad_w_mode and weight_decay:
            upd = upd + weight_decay * p32
        return (p32 - scalars[0, 0] * upd).astype(p.dtype), h_new
    kernel = functools.partial(_adagrad_kernel, eps=eps,
                               weight_decay=weight_decay,
                               adagrad_w_mode=adagrad_w_mode)
    p2, n = _to2d(p)
    h2, _ = _to2d(h)
    g2, _ = _to2d(g)
    R = _block_rows(p2.shape[0], "adagrad")
    grid = p2.shape[0] // R
    spec = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    pn, hn = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, sspec],
        out_specs=[spec, spec],
        out_shape=[jax.ShapeDtypeStruct(p2.shape, p2.dtype),
                   jax.ShapeDtypeStruct(h2.shape, jnp.float32)],
        input_output_aliases={0: 0, 1: 1},
        interpret=pallas_interpret(),
    )(p2, h2, g2, scalars)
    return _from2d(pn, n), _from2d(hn, n)


# ------------------------- LAMB (two-phase) ---------------------------------

def _lamb_phase1_kernel(m_ref, v_ref, g_ref, p_ref, sc_ref,
                        m_out, v_out, u_out, *, eps, weight_decay):
    """Phase 1 ≡ amp_C.multi_tensor_lamb_stage1 / lamb stage computing the
    raw update u = mhat/(sqrt(vhat)+eps) + wd*p with global-grad-norm
    clipping fused.  sc rows: [g_scale, b1e, c1, b2e, c2, rbc1, rbc2,
    found] — overflow skip + bias correction folded into scalars like
    _adam_kernel (one g select; reciprocal-multiply bias correction)."""
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    g = jnp.where(sc_ref[7, 0] > 0.5, 0.0, g * sc_ref[0, 0])
    m_new = sc_ref[1, 0] * m_ref[...] + sc_ref[2, 0] * g
    v_new = sc_ref[3, 0] * v_ref[...] + sc_ref[4, 0] * (g * g)
    u = (m_new * sc_ref[5, 0]) / (jnp.sqrt(v_new * sc_ref[6, 0]) + eps)
    if weight_decay != 0.0:
        u = u + weight_decay * p
    m_out[...] = m_new.astype(m_out.dtype)
    v_out[...] = v_new.astype(v_out.dtype)
    u_out[...] = u.astype(u_out.dtype)


def _lamb_phase1_seg_kernel(m_ref, v_ref, g_ref, p_ref, sc_ref, lo_ref,
                            hi_ref, vals_ref, off_ref, m_out, v_out,
                            u_out, *, eps, npad, R):
    """Phase 1 with PER-TENSOR weight decay (vals row 0), rebuilt per
    block from segment row bounds — the LAMB consumer of
    get_params_for_weight_decay_optimization's no-decay mask (lr scale
    rides in phase 2's per-tensor ratio, zero extra work)."""
    i = pl.program_id(0)
    oh = _block_onehot(lo_ref, hi_ref, off_ref, i, R, npad)
    wd_row = jax.lax.dot_general(oh, vals_ref[0:1, :],
                                 (((1,), (1,)), ((), ())),
                                 precision=jax.lax.Precision.HIGHEST)
    g = g_ref[...].astype(jnp.float32)
    p = p_ref[...].astype(jnp.float32)
    g = jnp.where(sc_ref[7, 0] > 0.5, 0.0, g * sc_ref[0, 0])
    m_new = sc_ref[1, 0] * m_ref[...] + sc_ref[2, 0] * g
    v_new = sc_ref[3, 0] * v_ref[...] + sc_ref[4, 0] * (g * g)
    u = (m_new * sc_ref[5, 0]) / (jnp.sqrt(v_new * sc_ref[6, 0]) + eps)
    u = u + wd_row * p
    m_out[...] = m_new.astype(m_out.dtype)
    v_out[...] = v_new.astype(v_out.dtype)
    u_out[...] = u.astype(u_out.dtype)


def _lamb_phase2_kernel(p_ref, u_ref, r_ref, sc_ref, p_out):
    """Phase 2 ≡ multi_tensor_lamb_stage2: p -= lr * trust_ratio * u, with
    the per-element trust-ratio vector r."""
    lr = sc_ref[0, 0]
    p = p_ref[...].astype(jnp.float32)
    p_out[...] = (p - lr * r_ref[...] * u_ref[...]).astype(p_out.dtype)


def _lamb_fold_scalars(clip_ratio, step, beta1, beta2, bias_correction,
                       grad_averaging, inv_scale, found_inf):
    """The ONE definition of the LAMB phase-1 folded-scalar rows
    (shared by the uniform and per-tensor-seg variants)."""
    beta3 = (1.0 - beta1) if grad_averaging else 1.0
    step = jnp.asarray(step, jnp.float32)
    bc1 = jnp.maximum(1.0 - jnp.power(jnp.float32(beta1), step), 1e-20)
    bc2 = jnp.maximum(1.0 - jnp.power(jnp.float32(beta2), step), 1e-20)
    one = jnp.float32(1.0)
    keep = jnp.asarray(found_inf).astype(jnp.bool_)
    g_scale = (jnp.asarray(clip_ratio, jnp.float32)
               * jnp.asarray(inv_scale, jnp.float32))
    return jnp.stack([
        g_scale,
        jnp.where(keep, one, jnp.float32(beta1)),          # b1e
        jnp.where(keep, 0.0, jnp.float32(beta3)),          # c1
        jnp.where(keep, one, jnp.float32(beta2)),          # b2e
        jnp.where(keep, 0.0, 1.0 - jnp.float32(beta2)),    # c2
        one / bc1 if bias_correction else one,             # rbc1
        one / bc2 if bias_correction else one,             # rbc2
        keep.astype(jnp.float32),                          # found
    ]).reshape(8, 1)


def lamb_phase1_flat(m, v, g, p, clip_ratio, step, *, beta1, beta2, eps,
                     weight_decay, bias_correction=True,
                     grad_averaging=True, inv_scale=1.0, found_inf=False,
                     use_pallas_override=None):
    """`g` may ride in its native (bf16) dtype — the kernel upcasts per
    block.  inv_scale and the overflow skip are folded into the scalar
    rows (≡ the capturable CUDA-graph LAMB), so callers need no extra
    whole-buffer passes for unscale or skip-masking."""
    scalars = _lamb_fold_scalars(clip_ratio, step, beta1, beta2,
                                 bias_correction, grad_averaging,
                                 inv_scale, found_inf)
    if not use_pallas(use_pallas_override):
        g32 = jnp.where(scalars[7, 0] > 0.5, 0.0,
                        g.astype(jnp.float32) * scalars[0, 0])
        p32 = p.astype(jnp.float32)
        m_new = scalars[1, 0] * m + scalars[2, 0] * g32
        v_new = scalars[3, 0] * v + scalars[4, 0] * (g32 * g32)
        u = (m_new * scalars[5, 0]) / (
            jnp.sqrt(v_new * scalars[6, 0]) + eps)
        if weight_decay:
            u = u + weight_decay * p32
        return (m_new.astype(m.dtype), v_new.astype(v.dtype),
                u.astype(p.dtype))
    kernel = functools.partial(
        _lamb_phase1_kernel, eps=eps, weight_decay=weight_decay)
    m2, n = _to2d(m)
    v2, _ = _to2d(v)
    g2, _ = _to2d(g)
    p2, _ = _to2d(p)
    R = _block_rows(m2.shape[0], "lamb1")
    grid = m2.shape[0] // R
    spec = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((8, 1), lambda i: (0, 0))
    mn, vn, u = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, spec, sspec],
        out_specs=[spec, spec, spec],
        # m/v aliased in place (dtypes preserved); u rides in the
        # master dtype so a bf16-state LAMB halves the u write + the
        # norm-pass and phase-2 reads (≡ the 1.3B Adam bf16-state point)
        out_shape=[jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype),
                   jax.ShapeDtypeStruct(p2.shape, p2.dtype)],
        input_output_aliases={0: 0, 1: 1},
        interpret=pallas_interpret(),
    )(m2, v2, g2, p2, scalars)
    return _from2d(mn, n), _from2d(vn, n), _from2d(u, n)


def lamb_phase1_seg(m, v, g, p, clip_ratio, step, *, wd_values, spec,
                    row_offset=0, padded_total=None, beta1, beta2, eps,
                    bias_correction=True, grad_averaging=True,
                    inv_scale=1.0, found_inf=False,
                    use_pallas_override=None):
    """lamb_phase1_flat with a per-tensor weight-decay vector expanded
    in-kernel from the (lane-aligned) spec's row bounds."""
    scalars = _lamb_fold_scalars(clip_ratio, step, beta1, beta2,
                                 bias_correction, grad_averaging,
                                 inv_scale, found_inf)
    wd_values = jnp.asarray(wd_values, jnp.float32)
    n_seg = wd_values.shape[0]
    npad = _seg_pad(n_seg)
    if not (use_pallas(use_pallas_override) and n_seg + 1 < _SEG_CAP
            and p.shape[0] % FLAT_TILE == 0):
        rows = p.shape[0] // _LANES
        total = padded_total if padded_total is not None else p.shape[0]
        rank = jnp.asarray(row_offset, jnp.int32) // rows
        seg = shard_segment_ids(spec, rank, rows, total)
        wd_elem = expand_per_tensor_shard(wd_values, seg)
        g32 = jnp.where(scalars[7, 0] > 0.5, 0.0,
                        g.astype(jnp.float32) * scalars[0, 0])
        p32 = p.astype(jnp.float32)
        m_new = scalars[1, 0] * m + scalars[2, 0] * g32
        v_new = scalars[3, 0] * v + scalars[4, 0] * (g32 * g32)
        u = (m_new * scalars[5, 0]) / (
            jnp.sqrt(v_new * scalars[6, 0]) + eps)
        u = u + wd_elem * p32
        return (m_new.astype(m.dtype), v_new.astype(v.dtype),
                u.astype(p.dtype))
    m2, n = _to2d(m)
    v2, _ = _to2d(v)
    g2, _ = _to2d(g)
    p2, _ = _to2d(p)
    R = _block_rows(m2.shape[0], "lamb1_seg")
    grid = m2.shape[0] // R
    lo, hi = _seg_row_bounds(spec, npad)
    vals8 = jnp.zeros((8, npad), jnp.float32).at[0, :n_seg].set(wd_values)
    off = jnp.asarray(row_offset, jnp.int32).reshape(1, 1)
    bspec = pl.BlockSpec((8, npad), lambda i: (0, 0))
    spec_b = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    mn, vn, u = pl.pallas_call(
        functools.partial(_lamb_phase1_seg_kernel, eps=eps, npad=npad,
                          R=R),
        grid=(grid,),
        in_specs=[spec_b, spec_b, spec_b, spec_b,
                  pl.BlockSpec((8, 1), lambda i: (0, 0)),
                  bspec, bspec, bspec,
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=[spec_b, spec_b, spec_b],
        out_shape=[jax.ShapeDtypeStruct(m2.shape, m2.dtype),
                   jax.ShapeDtypeStruct(v2.shape, v2.dtype),
                   jax.ShapeDtypeStruct(p2.shape, p2.dtype)],
        input_output_aliases={0: 0, 1: 1},
        interpret=pallas_interpret(),
    )(m2, v2, g2, p2, scalars, lo, hi, vals8, off)
    return _from2d(mn, n), _from2d(vn, n), _from2d(u, n)


def _lamb_phase2_seg_kernel(p_ref, u_ref, lo_ref, hi_ref, vals_ref,
                            sc_ref, off_ref, p_out, *, npad, R):
    """Phase 2 with IN-KERNEL trust-ratio expansion: the per-tensor
    ratio row vector is rebuilt per block via the bounds one-hot matmul
    (same trick as _rows_sumsq_seg_kernel, transposed) — the (total,)
    per-element ratio vector never exists in HBM."""
    i = pl.program_id(0)
    lr = sc_ref[0, 0]
    oh = _block_onehot(lo_ref, hi_ref, off_ref, i, R, npad)
    # exactly one 1 per row → this dot is a SELECT of vals; HIGHEST
    # keeps the selected fp32 ratio exact (default = bf16 rounding)
    ratio_row = jax.lax.dot_general(
        oh, vals_ref[0:1, :], (((1,), (1,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)              # (R, 1)
    p = p_ref[...].astype(jnp.float32)
    p_out[...] = (p - lr * ratio_row * u_ref[...]).astype(p_out.dtype)


def lamb_phase2_seg(p, u, ratio_values, spec, lr, *, row_offset=0,
                    padded_total=None, use_pallas_override=None):
    """p -= lr * trust_ratio[tensor] * u with per-tensor `ratio_values`
    ((n_seg,)) expanded in-kernel from the spec's static row bounds.
    `row_offset` is p's global starting row (rank*shard_rows for a
    shard; may be traced — `padded_total` must then be given for the
    fallback's segment map).  Rows outside every tensor (tail padding)
    get ratio 0, leaving them untouched."""
    n_seg = ratio_values.shape[0]
    npad = _seg_pad(n_seg)
    if not (use_pallas(use_pallas_override) and n_seg + 1 < _SEG_CAP
            and p.shape[0] % FLAT_TILE == 0):
        rows = p.shape[0] // _LANES
        total = padded_total if padded_total is not None else p.shape[0]
        rank = jnp.asarray(row_offset, jnp.int32) // rows
        seg = shard_segment_ids(spec, rank, rows, total)
        vals = jnp.concatenate(
            [ratio_values.astype(jnp.float32),
             jnp.zeros((1,), jnp.float32)])  # dummy tail ratio 0
        per_row = vals[seg]
        ratio_elem = jnp.broadcast_to(
            per_row[:, None], (per_row.shape[0], _LANES)).reshape(-1)
        return lamb_phase2_flat(p, u, ratio_elem, lr,
                                use_pallas_override=use_pallas_override)
    p2, n = _to2d(p)
    u2, _ = _to2d(u)
    R = _block_rows(p2.shape[0], "lamb2_seg")
    nb = p2.shape[0] // R
    lo, hi = _seg_row_bounds(spec, npad)
    vals8 = jnp.broadcast_to(
        jnp.pad(ratio_values.astype(jnp.float32),
                (0, npad - n_seg))[None, :], (8, npad))
    scalars = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    off = jnp.asarray(row_offset, jnp.int32).reshape(1, 1)
    bspec = pl.BlockSpec((8, npad), lambda i: (0, 0))
    sspec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    pn = pl.pallas_call(
        functools.partial(_lamb_phase2_seg_kernel, npad=npad, R=R),
        grid=(nb,),
        in_specs=[pl.BlockSpec((R, _LANES), lambda i: (i, 0)),
                  pl.BlockSpec((R, _LANES), lambda i: (i, 0)),
                  bspec, bspec, bspec, sspec, sspec],
        out_specs=pl.BlockSpec((R, _LANES), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(p2.shape, p2.dtype),
        input_output_aliases={0: 0},
        interpret=pallas_interpret(),
    )(p2, u2, lo, hi, vals8, scalars, off)
    return _from2d(pn, n)


def lamb_phase2_flat(p, u, ratio_elem, lr, use_pallas_override=None):
    scalars = jnp.asarray(lr, jnp.float32).reshape(1, 1)
    if not use_pallas(use_pallas_override):
        return (p.astype(jnp.float32) - scalars[0, 0] * ratio_elem * u
                ).astype(p.dtype)
    p2, n = _to2d(p)
    u2, _ = _to2d(u)
    r2, _ = _to2d(ratio_elem)
    R = _block_rows(p2.shape[0], "lamb2")
    grid = p2.shape[0] // R
    spec = pl.BlockSpec((R, _LANES), lambda i: (i, 0))
    sspec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    pn = pl.pallas_call(
        _lamb_phase2_kernel,
        grid=(grid,),
        in_specs=[spec, spec, spec, sspec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct(p2.shape, p2.dtype),
        input_output_aliases={0: 0},
        interpret=pallas_interpret(),
    )(p2, u2, r2, scalars)
    return _from2d(pn, n)


# --------------------------- reductions / utilities -------------------------

def l2norm_flat(flat):
    """Global L2 norm ≡ amp_C.multi_tensor_l2norm (csrc/multi_tensor_l2norm_kernel.cu).
    XLA lowers this to an optimal tree reduction; no Pallas needed."""
    return jnp.sqrt(jnp.sum(jnp.square(flat.astype(jnp.float32))))


def per_tensor_l2norm(flat, sizes):
    """Per-tensor norms over a flat buffer ≡ multi_tensor_l2norm
    per_tensor=True mode.  `sizes` is the static segment-length list."""
    norms = []
    off = 0
    for s in sizes:
        seg = jax.lax.dynamic_slice(flat, (off,), (s,))
        norms.append(jnp.sqrt(jnp.sum(jnp.square(seg.astype(jnp.float32)))))
        off += s
    return jnp.stack(norms)


def expand_per_tensor(values, sizes, total):
    """Broadcast per-tensor scalars to per-element vector (static sizes)."""
    return jnp.repeat(values, jnp.asarray(sizes), total_repeat_length=total)


def scale_flat(flat, scale):
    """≡ amp_C.multi_tensor_scale: scaled copy; overflow check is fused by
    XLA into the same pass when consumed with jnp.isfinite."""
    return flat.astype(jnp.float32) * scale


def axpby_flat(a, x, b, y):
    """≡ amp_C.multi_tensor_axpby: a*x + b*y."""
    return a * x.astype(jnp.float32) + b * y.astype(jnp.float32)


# --- row-aligned per-tensor reductions (128-lane-aligned FlatSpec) ----------
#
# With FlatSpec(align=_LANES) every tensor's segment spans whole rows of
# the (rows, 128) 2-D view (zero-filled tails), so multi_tensor_l2norm's
# per_tensor mode becomes: one squared-row-sum pass + one static
# segment-sum — instead of one dynamic_slice+reduction per tensor (which
# at BERT/GPT scale is ~600 serialized slices over the whole buffer).

def _row_segment_ids(spec):
    import numpy as _np
    # segment extents come straight from the spec's (aligned) offsets so
    # this can never drift from make_spec's padding rule
    bounds = list(spec.offsets) + [spec.total]
    rows = [(bounds[i + 1] - bounds[i]) // _LANES
            for i in range(len(spec.offsets))]
    return _np.repeat(_np.arange(len(rows), dtype=_np.int32), rows)


# Per-tensor segment reductions over the flat buffer.  TPU scatter (the
# jax.ops.segment_sum lowering) and big gathers are VPU-serial — at
# BERT-Large scale one segment_sum over 2.6M rows measured 36 ms and the
# values[seg] expand gather 35 ms, dwarfing the optimizer math itself.
# Segments are CONTIGUOUS row runs, so each (rows, 128) block can turn
# its row sums into per-tensor partials with ONE one-hot matmul on the
# MXU ((R, 1)^T-dot-(R, n_seg) from an iota==seg compare); a VMEM
# accumulator carries partials across the sequential grid.  ≡ the
# two-phase multi_tensor_l2norm reduction (csrc/multi_tensor_l2norm.cu)
# re-shaped for the MXU.

_SEG_CAP = 2048  # one-hot width cap; fall back to segment_sum beyond


def _seg_pad(n_seg):
    return max(_LANES, -(-(n_seg + 1) // _LANES) * _LANES)


def _seg_row_bounds(spec, npad):
    """Per-tensor [start, end) ROW bounds as (8, npad) int32 blocks (row
    0 is real; broadcast to the fp32 min-tile height).  The contiguous
    layout means segment membership is two compares against these
    bounds — no per-row segment-id array, no gather.  Unused columns get
    a sentinel past any row index."""
    import numpy as _np
    assert spec.align % _LANES == 0, "spec must be lane-aligned"
    n_seg = len(spec.sizes)
    starts = _np.full((npad,), 2 ** 30, _np.int32)
    ends = _np.full((npad,), 2 ** 30, _np.int32)
    bounds = list(spec.offsets) + [spec.total]
    for s in range(n_seg):
        starts[s] = bounds[s] // _LANES
        ends[s] = bounds[s + 1] // _LANES
    lo = jnp.broadcast_to(jnp.asarray(starts)[None, :], (8, npad))
    hi = jnp.broadcast_to(jnp.asarray(ends)[None, :], (8, npad))
    return lo, hi


def _block_onehot(lo_ref, hi_ref, off_ref, i, R, npad):
    """(R, npad) one-hot of global-row-in-segment for grid block i."""
    rowg = (off_ref[0, 0] + i * R
            + lax.broadcasted_iota(jnp.int32, (R, 1), 0))
    return ((rowg >= lo_ref[0:1, :]) & (rowg < hi_ref[0:1, :])
            ).astype(jnp.float32)


def _rows_sumsq_seg_kernel(x_ref, lo_ref, hi_ref, off_ref, out_ref, acc,
                           *, nb, npad, R):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    xb = x_ref[...].astype(jnp.float32)
    rq = jnp.sum(xb * xb, axis=1, keepdims=True)            # (R, 1)
    oh = _block_onehot(lo_ref, hi_ref, off_ref, i, R, npad)
    # HIGHEST: the default MXU fp32 path is a single bf16 pass, which
    # rounds the row sums to ~8 mantissa bits — trust ratios then drift
    # ~4e-4 vs the jnp oracle
    acc[0:1, :] += jax.lax.dot_general(
        rq, oh, (((0,), (0,)), ((), ())),
        precision=jax.lax.Precision.HIGHEST)

    @pl.when(i == nb - 1)
    def _done():
        out_ref[...] = acc[...]


def _per_tensor_sumsq_2d(x2, spec, n_seg, row_offset):
    """(rows, 128) buffer → (n_seg,) sums of squares via per-block
    one-hot matmuls.  `row_offset` is this buffer's global starting row
    (0 for a full buffer; rank*shard_rows for a shard — may be traced)."""
    rows = x2.shape[0]
    R = _block_rows(rows, "sumsq_seg")
    nb = rows // R
    npad = _seg_pad(n_seg)
    lo, hi = _seg_row_bounds(spec, npad)
    off = jnp.asarray(row_offset, jnp.int32).reshape(1, 1)
    bspec = pl.BlockSpec((8, npad), lambda i: (0, 0))
    out = pl.pallas_call(
        functools.partial(_rows_sumsq_seg_kernel, nb=nb, npad=npad, R=R),
        grid=(nb,),
        in_specs=[pl.BlockSpec((R, _LANES), lambda i: (i, 0)),
                  bspec, bspec,
                  pl.BlockSpec((1, 1), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((8, npad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, npad), jnp.float32),
        scratch_shapes=[pltpu.VMEM((8, npad), jnp.float32)],
        interpret=pallas_interpret(),
    )(x2, lo, hi, off)
    return out[0, :n_seg]


def per_tensor_l2norm_aligned(flat, spec, use_pallas_override=None):
    """Per-tensor L2 norms over a lane-aligned flat buffer; `spec.align`
    must be a multiple of the 128-lane width."""
    assert spec.align % _LANES == 0, "spec must be lane-aligned"
    n_seg = len(spec.sizes)
    if (use_pallas(use_pallas_override) and n_seg < _SEG_CAP
            and flat.shape[0] % FLAT_TILE == 0):
        x2 = flat.reshape(-1, _LANES)
        return jnp.sqrt(_per_tensor_sumsq_2d(x2, spec, n_seg, 0))
    x2 = flat[: spec.total].reshape(-1, _LANES).astype(jnp.float32)
    rowsq = jnp.sum(x2 * x2, axis=1)                      # (rows,)
    seg = jnp.asarray(_row_segment_ids(spec))             # static constant
    sums = jax.ops.segment_sum(rowsq, seg,
                               num_segments=len(spec.sizes))
    return jnp.sqrt(sums)


def expand_per_tensor_aligned(values, spec, total):
    """Broadcast per-tensor scalars to a per-element vector of `total`
    length (>= spec.total; the tail repeats the last value, harmless on
    zero padding)."""
    assert spec.align % _LANES == 0
    seg = jnp.asarray(_row_segment_ids(spec))
    per_row = values[seg]                                  # (rows,)
    elem = jnp.broadcast_to(per_row[:, None],
                            (per_row.shape[0], _LANES)).reshape(-1)
    if total > elem.shape[0]:
        elem = jnp.concatenate(
            [elem, jnp.broadcast_to(values[-1], (total - elem.shape[0],))])
    return elem


def _row_segment_ids_padded(spec, rows_total):
    """Row→tensor map over the PADDED buffer (rows_total >= spec rows):
    tail padding rows get the dummy id len(spec.sizes)."""
    import numpy as _np
    base = _row_segment_ids(spec)
    pad = rows_total - base.shape[0]
    return _np.concatenate(
        [base, _np.full((pad,), len(spec.sizes), _np.int32)])


def shard_segment_ids(spec, rank, rows_shard, padded_total):
    """This rank's slice of the padded row→tensor map (tail padding rows
    get the dummy id len(spec.sizes)).  The shard is a contiguous flat
    slice [rank*S, (rank+1)*S) with S a multiple of FLAT_TILE, so its
    rows are a contiguous run of the global row map — a dynamic slice at
    a traced `rank` is all it takes.  Compute ONCE per step and pass to
    the per-tensor helpers below (the full row map is O(params/128))."""
    assert spec.align % _LANES == 0
    seg_full = jnp.asarray(
        _row_segment_ids_padded(spec, padded_total // _LANES))
    return jax.lax.dynamic_slice(seg_full, (rank * rows_shard,),
                                 (rows_shard,))


def per_tensor_sumsq_shard(shard, spec, rank, padded_total,
                           use_pallas_override=None):
    """Per-tensor PARTIAL sums of squares over ONE rank's contiguous
    flat shard (shards partition the `padded_total`-long buffer evenly;
    `rank` may be traced).  A psum over the shard axis yields the exact
    full-buffer per-tensor sums — no rank ever materializes the full
    buffer (≡ the reference's pipelined block-reduction L2 norms,
    distributed_fused_lamb.py:728-987, which exist for the same reason).
    Returns (n_tensors,) fp32 partial sums; tail-padding rows fall
    outside every bound and contribute nothing."""
    n_seg = len(spec.sizes)
    rows_shard = shard.shape[0] // _LANES
    if (use_pallas(use_pallas_override) and n_seg + 1 < _SEG_CAP
            and shard.shape[0] % FLAT_TILE == 0):
        x2 = shard.reshape(-1, _LANES)
        return _per_tensor_sumsq_2d(x2, spec, n_seg, rank * rows_shard)
    seg = shard_segment_ids(spec, rank, rows_shard, padded_total)
    x2 = shard.reshape(-1, _LANES).astype(jnp.float32)
    rowsq = jnp.sum(x2 * x2, axis=1)                      # (rows,)
    sums = jax.ops.segment_sum(rowsq, seg,
                               num_segments=len(spec.sizes) + 1)
    return sums[: len(spec.sizes)]


def expand_per_tensor_shard(values, seg):
    """Broadcast per-tensor scalars to ONE rank's shard elements —
    the shard-local counterpart of expand_per_tensor_aligned (padding
    rows broadcast 0.0, matching the lamb_phase2_seg / one-hot kernel
    convention for the padding segment).  Prefer lamb_phase2_seg, which
    folds the expansion into the update kernel and never materializes
    the per-element vector."""
    rows_shard = seg.shape[0]
    vals = jnp.concatenate(
        [values.astype(jnp.float32), jnp.zeros((1,), jnp.float32)])
    per_row = vals[seg]                                    # (rows,)
    return jnp.broadcast_to(per_row[:, None],
                            (rows_shard, _LANES)).reshape(-1)
