"""Max pooling with a dense routed backward — no SelectAndScatter.

≡ torch.nn.MaxPool2d as used by the reference's canonical ResNet
(examples/imagenet/main_amp.py via torchvision resnet50).  XLA lowers
the AD transpose of `reduce_window(max)` to SelectAndScatter, which is
VPU-serial on TPU: at the RN50 bench point (256x112x112x64, 3x3/s2) the
fwd+bwd pair measured 15.1 ms — ~13% of the whole training step.

For stride-2 pools the routed backward is a PARITY DECOMPOSITION: with
s=2, an input position's candidate windows sit at *static* shifts of
the window grid determined only by the position's (row, col) parity,
so routing dy needs nothing but static slices and fused elementwise
selects — no scatter, no gather.  Phase 1 finds, first-wins in
row-major offset order (exactly SelectAndScatter's GE-select tie
semantics), WHICH window offset holds each max; phase 2 lets every
input parity plane claim dy from its (≤2 per dim) candidate windows.

MEASURED OUTCOME (v5e, RN50 b256 full train step): SelectAndScatter
118.7 ms/step; parity-routed 125.3; interior-pad scatter 159.0;
repeat-upsampled views 173.1.  In isolation SelectAndScatter's
fwd+bwd pair is slow (15.1 ms), but in the full program XLA overlaps
it with surrounding work better than any of the dense reformulations,
whose extra elementwise passes and the final parity-interleave
relayout cost more than they save.  The routed backward is therefore
OPT-IN (`routed_backward=True`) and the default is reduce_window +
XLA AD — kept as the measured record and for backends/shapes where
SelectAndScatter degrades further.

Non-stride-2 configs always use reduce_window + XLA AD.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _same_pads(size, k, s):
    out = -(-size // s)
    total = max((out - 1) * s + k - size, 0)
    return total // 2, total - total // 2


def _pool_dims(x_shape, window, strides, padding):
    h, w = x_shape[1], x_shape[2]
    kh, kw = window
    sh, sw = strides
    if padding == "SAME":
        ph = _same_pads(h, kh, sh)
        pw = _same_pads(w, kw, sw)
    else:  # VALID
        ph = pw = (0, 0)
    oh = (h + ph[0] + ph[1] - kh) // sh + 1
    ow = (w + pw[0] + pw[1] - kw) // sw + 1
    return ph, pw, oh, ow


def _reduce_max(x, window, strides, padding):
    kh, kw = window
    sh, sw = strides
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, kh, kw, 1),
                             (1, sh, sw, 1), padding)


def max_pool2d(x, window=(3, 3), strides=(2, 2), padding="SAME",
               routed_backward=False):
    """NHWC max pool.  Forward ≡ lax.reduce_window(max).

    routed_backward=True (stride-2 only) swaps XLA's SelectAndScatter
    AD for the dense parity-routed transpose — the gradient is
    identical (incl. first-wins tie order) but on v5e the default
    measured FASTER in full-model context (see module docstring)."""
    if routed_backward and strides == (2, 2):
        return _mp2(x, window, padding)
    return _reduce_max(x, window, strides, padding)


@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _mp2(x, window, padding):
    return _reduce_max(x, window, (2, 2), padding)


def _mp2_fwd(x, window, padding):
    y = _reduce_max(x, window, (2, 2), padding)
    return y, (x, y)


def _shifted(g, sh_h, sh_w, len_h, len_w, fill):
    """g[(b, oh, ow, c)] viewed at static shift: out[i, j] =
    g[i + sh_h, j + sh_w] for i < len_h, j < len_w (fill outside)."""
    oh, ow = g.shape[1], g.shape[2]
    pad_h = (max(0, -sh_h), max(0, len_h + sh_h - oh))
    pad_w = (max(0, -sh_w), max(0, len_w + sh_w - ow))
    gp = jnp.pad(g, ((0, 0), pad_h, pad_w, (0, 0)),
                 constant_values=fill)
    return lax.slice(gp, (0, sh_h + pad_h[0], sh_w + pad_w[0], 0),
                     (g.shape[0], sh_h + pad_h[0] + len_h,
                      sh_w + pad_w[0] + len_w, g.shape[3]))


def _mp2_bwd(window, padding, res, dy):
    x, y = res
    kh, kw = window
    b, h, w, c = x.shape
    (ph_lo, _), (pw_lo, _), oh, ow = _pool_dims(x.shape, window, (2, 2),
                                                padding)
    xp = jnp.pad(x, ((0, 0), (ph_lo, _same_pads(h, kh, 2)[1]),
                     (pw_lo, _same_pads(w, kw, 2)[1]), (0, 0)),
                 constant_values=-jnp.inf) if padding == "SAME" else x
    # phase 1: winning offset per window, row-major first-wins
    idx = jnp.full(y.shape, -1, jnp.int32)
    for di in range(kh):
        for dj in range(kw):
            xs = lax.slice(xp, (0, di, dj, 0),
                           (b, di + 2 * (oh - 1) + 1,
                            dj + 2 * (ow - 1) + 1, c),
                           (1, 2, 2, 1))
            hit = (xs == y) & (idx < 0)
            idx = jnp.where(hit, di * kw + dj, idx)
    dyf = dy.astype(jnp.float32)

    # phase 2: parity planes.  Input p = 2*p2 + u (parity u): candidate
    # windows w = p2 + cu - a with cu = (u+padlo)//2, at offset
    # di = (u+padlo)%2 + 2a — all STATIC per (u, a).
    def plane_1d(u, padlo, k):
        """[(shift, di)] candidate windows for parity u."""
        cu = (u + padlo) // 2
        par = (u + padlo) % 2
        return [(cu - a, par + 2 * a) for a in range(-(-k // 2))
                if par + 2 * a < k]

    h2 = (h + 1) // 2
    w2 = (w + 1) // 2
    planes = []
    for u in (0, 1):
        ch = plane_1d(u, ph_lo, kh)
        row = []
        for v in (0, 1):
            cw = plane_1d(v, pw_lo, kw)
            acc = jnp.zeros((b, h2, w2, c), jnp.float32)
            for sh_h, di in ch:
                for sh_w, dj in cw:
                    idx_s = _shifted(idx, sh_h, sh_w, h2, w2, -1)
                    dy_s = _shifted(dyf, sh_h, sh_w, h2, w2, 0.0)
                    acc = acc + jnp.where(idx_s == di * kw + dj, dy_s,
                                          0.0)
            row.append(acc)
        planes.append(row)
    # interleave parity planes back to the input grid:
    # (b, h2, 2, w2, 2, c) -> (b, 2*h2, 2*w2, c) -> crop to (h, w)
    grid = jnp.stack([jnp.stack(r, axis=3) for r in planes], axis=2)
    dx = grid.reshape(b, 2 * h2, 2 * w2, c)[:, :h, :w, :]
    return (dx.astype(x.dtype),)


_mp2.defvjp(_mp2_fwd, _mp2_bwd)
