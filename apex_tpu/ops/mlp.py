"""Fused MLP — chain of linear(+bias)+activation layers.

≡ the reference's `mlp_cuda` extension (csrc/mlp.cpp:163-164, epilogue
kernels csrc/mlp_cuda.cu:437-950) and apex.mlp.MLP (apex/mlp/mlp.py:11-33):
a cublas-GEMM chain with fused bias/ReLU/sigmoid epilogues.  On TPU the
chain is the Pallas fused-dense kernel per layer (ops/fused_dense.py);
XLA fuses the rest.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.ops.fused_dense import linear_bias


def mlp_forward(x, weights, biases, activation: str = "relu",
                use_pallas_override: Optional[bool] = None):
    """Apply the MLP chain; activation on all layers but the last
    (≡ mlp_cuda.forward semantics: MLP applies activation between
    layers, none after the final one)."""
    n = len(weights)
    for i, (w, b) in enumerate(zip(weights, biases)):
        act = activation if i < n - 1 else None
        x = linear_bias(x, w, b, act, use_pallas_override)
    return x


class MLP:
    """≡ apex.mlp.MLP (apex/mlp/mlp.py:33): mlp_sizes = [in, h1, ..., out].

    activation: 'none' | 'relu' | 'sigmoid' (mlp.py:41-47).
    """

    def __init__(self, mlp_sizes: Sequence[int], bias: bool = True,
                 activation: str = "relu"):
        if activation not in ("none", "relu", "sigmoid", "gelu"):
            raise TypeError(f"activation '{activation}' not supported")
        self.mlp_sizes = tuple(mlp_sizes)
        self.use_bias = bias
        self.activation = activation

    def init(self, key, dtype=jnp.float32):
        params = {"weights": [], "biases": []}
        for i in range(len(self.mlp_sizes) - 1)            :
            key, k1, k2 = jax.random.split(key, 3)
            fan_in, fan_out = self.mlp_sizes[i], self.mlp_sizes[i + 1]
            # ≡ MLP.reset_parameters (mlp.py:63-70): kaiming-uniform-ish
            bound = 1.0 / jnp.sqrt(fan_in)
            params["weights"].append(
                jax.random.uniform(k1, (fan_in, fan_out), dtype, -bound,
                                   bound))
            params["biases"].append(
                jax.random.uniform(k2, (fan_out,), dtype, -bound, bound)
                if self.use_bias else None)
        return params

    def apply(self, params, x, use_pallas_override=None):
        act = self.activation if self.activation != "none" else None
        return mlp_forward(x, params["weights"], params["biases"],
                           act or "none", use_pallas_override)
