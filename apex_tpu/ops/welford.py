"""Per-channel batch statistics kernel — the SyncBatchNorm compute core.

≡ the reference's `syncbn` extension (csrc/syncbn.cpp:99-108, Welford
kernels csrc/welford.cu:259-702).  The CUDA design computes local
Welford mean/var, all-gathers (mean, var, count) and merges with
welford_parallel; the TPU design computes local per-channel (sum, sumsq,
count) in one Pallas pass — fp32 accumulation makes plain moments as
stable as Welford at BN's scale — and merges across the process group
with a single `lax.psum` (see parallel/sync_batchnorm.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from apex_tpu.ops._common import (
    pallas_interpret,
    row_block,
    use_pallas_fusable,
)


def _stats_kernel(x_ref, sum_ref, sq_ref):
    x = x_ref[...].astype(jnp.float32)
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sum_ref[...] = jnp.zeros_like(sum_ref)
        sq_ref[...] = jnp.zeros_like(sq_ref)

    sum_ref[...] += jnp.sum(x, axis=0, keepdims=True)
    sq_ref[...] += jnp.sum(x * x, axis=0, keepdims=True)


@jax.custom_vjp
def channel_sums(x2):
    """(sum, sumsq) over rows of a (rows, C) array, fp32."""
    return _channel_sums_impl(x2)


def _channel_sums_impl(x2):
    # fusable-op rule (≡ LayerNorm, ops/_common.use_pallas_fusable):
    # XLA fuses the (sum, sumsq) multi-output reduction into the
    # producing conv's consumer; the standalone Pallas kernel costs a
    # custom-call boundary + an extra HBM pass.  Measured on v5e at
    # the RN50 bench point (b256): BN stack fwd+bwd 55.1 ms (Pallas)
    # vs 21.6 ms (XLA), full model fwd(train) 66.4 -> 27.6 ms
    # (scripts/resnet_profile.py) — the 4-round ResNet plateau was
    # mostly THIS kernel.
    if not use_pallas_fusable(None):
        x32 = x2.astype(jnp.float32)
        return jnp.sum(x32, axis=0), jnp.sum(x32 * x32, axis=0)
    rows, c = x2.shape
    blk = row_block(rows, c)
    pad = (-rows) % blk
    xp = jnp.pad(x2, ((0, pad), (0, 0))) if pad else x2
    s, q = pl.pallas_call(
        _stats_kernel,
        grid=(xp.shape[0] // blk,),
        in_specs=[pl.BlockSpec((blk, c), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((1, c), lambda i: (0, 0)),
                   pl.BlockSpec((1, c), lambda i: (0, 0))],
        out_shape=[jax.ShapeDtypeStruct((1, c), jnp.float32),
                   jax.ShapeDtypeStruct((1, c), jnp.float32)],
        interpret=pallas_interpret(),
    )(xp)
    return s[0], q[0]


def _channel_sums_fwd(x2):
    return _channel_sums_impl(x2), x2


def _channel_sums_bwd(x2, g):
    ds, dq = g
    dx = jnp.broadcast_to(ds, x2.shape) + 2.0 * x2.astype(jnp.float32) * dq
    return (dx.astype(x2.dtype),)


channel_sums.defvjp(_channel_sums_fwd, _channel_sums_bwd)


def batch_stats(x, reduce_axes):
    """Per-channel (mean, var, count) reducing over `reduce_axes`.

    ≡ syncbn.welford_mean_var (csrc/welford.cu:259).  Channel dim = the
    one axis not in reduce_axes.
    """
    ndim = x.ndim
    reduce_axes = tuple(a % ndim for a in reduce_axes)
    (chan,) = [a for a in range(ndim) if a not in reduce_axes]
    perm = list(reduce_axes) + [chan]
    x2 = jnp.transpose(x, perm).reshape(-1, x.shape[chan])
    count = x2.shape[0]
    s, q = channel_sums(x2)
    mean = s / count
    var = jnp.maximum(q / count - mean * mean, 0.0)
    return mean, var, count


def merge_stats(mean, var, count, axis_name):
    """Merge per-device (mean, var, count) over a mesh axis.

    ≡ the all_gather + syncbn.welford_parallel merge
    (apex/parallel/optimized_sync_batchnorm_kernel.py:36-43,
    csrc/welford.cu:569) — here one psum of (count, count*mean,
    count*(var+mean²)) using the parallel-variance identity.
    """
    n = jnp.asarray(count, jnp.float32)
    tn = jax.lax.psum(n, axis_name)
    tmean = jax.lax.psum(n * mean, axis_name) / tn
    tsq = jax.lax.psum(n * (var + mean * mean), axis_name) / tn
    return tmean, jnp.maximum(tsq - tmean * tmean, 0.0), tn
