"""apex_tpu.ops — fused kernels (Pallas on TPU, jnp reference off-TPU).

≡ the reference's native kernel layer (csrc/, apex/contrib/csrc/) plus
its Python autograd wrappers (apex.normalization, apex.mlp,
apex.fused_dense, apex.transformer.functional.fused_softmax,
apex.contrib.{xentropy,focal_loss,index_mul_2d,...}).
"""

_LAZY = {
    "layer_norm": "apex_tpu.ops.layer_norm",
    "softmax": "apex_tpu.ops.softmax",
    "xentropy": "apex_tpu.ops.xentropy",
    "focal_loss": "apex_tpu.ops.focal_loss",
    "mlp": "apex_tpu.ops.mlp",
    "fused_dense": "apex_tpu.ops.fused_dense",
    "multi_tensor": "apex_tpu.ops.multi_tensor",
    "welford": "apex_tpu.ops.welford",
    "flash_attention": "apex_tpu.ops.flash_attention",
    "index_mul_2d": "apex_tpu.ops.index_mul_2d",
    "optimizer_kernels": "apex_tpu.ops.optimizer_kernels",
}

_SYMBOLS = {
    "fused_layer_norm": ("apex_tpu.ops.layer_norm", "fused_layer_norm"),
    "fused_rms_norm": ("apex_tpu.ops.layer_norm", "fused_rms_norm"),
    "FusedLayerNorm": ("apex_tpu.ops.layer_norm", "FusedLayerNorm"),
    "FusedRMSNorm": ("apex_tpu.ops.layer_norm", "FusedRMSNorm"),
}


def __getattr__(name):
    import importlib
    if name in _LAZY:
        return importlib.import_module(_LAZY[name])
    if name in _SYMBOLS:
        mod, sym = _SYMBOLS[name]
        return getattr(importlib.import_module(mod), sym)
    raise AttributeError(name)
