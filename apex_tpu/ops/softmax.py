"""Fused scaled-(masked)-softmax family — Pallas fwd+bwd.

≡ the reference's four Megatron softmax extensions:
  scaled_upper_triang_masked_softmax_cuda (csrc/megatron/scaled_upper_triang_masked_softmax.cpp)
  scaled_masked_softmax_cuda              (csrc/megatron/scaled_masked_softmax.cpp)
  generic_scaled_masked_softmax_cuda      (csrc/megatron/generic_scaled_masked_softmax.cpp)
  scaled_softmax_cuda                     (csrc/megatron/scaled_softmax.cpp)
and their autograd wrappers (apex/transformer/functional/fused_softmax.py:21-276).

One blocked Pallas kernel covers all variants (the CUDA split into
warp-tuned vs "generic" shapes is a GPU artifact; on TPU a single
row-blocked kernel serves every sequence length).  Mask semantics match
the reference: masked positions receive -10000 before the softmax
(masked_fill_, scaled_masked_softmax.h), so fully-masked rows produce a
uniform distribution, not NaN.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from apex_tpu.ops._common import (pallas_interpret, row_block,
                                  tuned_row_block, use_pallas)

_MASK_VALUE = -10000.0


# --------------------------- reference (jnp) path ---------------------------

def scaled_softmax_reference(x, scale=1.0):
    x32 = x.astype(jnp.float32) * scale
    return jax.nn.softmax(x32, axis=-1).astype(x.dtype)


def scaled_masked_softmax_reference(x, mask, scale=1.0):
    """mask: bool, True = masked out (≡ reference mask semantics)."""
    x32 = x.astype(jnp.float32) * scale
    x32 = jnp.where(mask, _MASK_VALUE, x32)
    return jax.nn.softmax(x32, axis=-1).astype(x.dtype)


def scaled_upper_triang_masked_softmax_reference(x, scale=1.0):
    """Causal mask over the last two dims (sq, sk), sq == sk."""
    sq, sk = x.shape[-2], x.shape[-1]
    causal = jnp.triu(jnp.ones((sq, sk), bool), k=1)
    return scaled_masked_softmax_reference(x, causal, scale)


# ------------------------------ pallas kernels ------------------------------

def _fwd_kernel(x_ref, m_ref, y_ref, *, scale, causal, has_mask, sq, blk):
    x = x_ref[...].astype(jnp.float32) * scale
    if has_mask:
        x = jnp.where(m_ref[...], _MASK_VALUE, x)
    if causal:
        i = pl.program_id(0)
        rows = i * blk + lax.broadcasted_iota(jnp.int32, x.shape, 0)
        pos = rows % sq
        cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
        x = jnp.where(cols > pos, _MASK_VALUE, x)
    x = x - jnp.max(x, axis=1, keepdims=True)
    e = jnp.exp(x)
    y = e / jnp.sum(e, axis=1, keepdims=True)
    y_ref[...] = y.astype(y_ref.dtype)


def _bwd_kernel(g_ref, y_ref, dx_ref, *, scale):
    g = g_ref[...].astype(jnp.float32)
    y = y_ref[...].astype(jnp.float32)
    dot = jnp.sum(g * y, axis=1, keepdims=True)
    dx_ref[...] = (scale * y * (g - dot)).astype(dx_ref.dtype)


def _pad_rows(a, blk):
    pad = (-a.shape[0]) % blk
    if pad:
        a = jnp.pad(a, ((0, pad), (0, 0)))
    return a


def _fwd_pallas(x2, mask2, scale, causal, sq):
    rows, sk = x2.shape
    has_mask = mask2 is not None
    blk = tuned_row_block("softmax_fwd", rows, sk)
    xp = _pad_rows(x2, blk)
    prows = xp.shape[0]
    grid = prows // blk
    inputs = [xp]
    in_specs = [pl.BlockSpec((blk, sk), lambda i: (i, 0))]
    if has_mask:
        inputs.append(_pad_rows(mask2, blk))
        in_specs.append(pl.BlockSpec((blk, sk), lambda i: (i, 0)))
    kernel = functools.partial(_fwd_kernel, scale=scale, causal=causal,
                               has_mask=has_mask, sq=sq, blk=blk)

    def wrapped(x_ref, *rest):
        if has_mask:
            m_ref, y_ref = rest
        else:
            (y_ref,) = rest
            m_ref = None
        kernel(x_ref, m_ref, y_ref)

    y = pl.pallas_call(
        wrapped,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((blk, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((prows, sk), x2.dtype),
        interpret=pallas_interpret(),
    )(*inputs)
    return y[:rows]


def _bwd_pallas(g2, y2, scale):
    rows, sk = g2.shape
    blk = tuned_row_block("softmax_bwd", rows, sk)
    gp, yp = _pad_rows(g2, blk), _pad_rows(y2, blk)
    prows = gp.shape[0]
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, scale=scale),
        grid=(prows // blk,),
        in_specs=[pl.BlockSpec((blk, sk), lambda i: (i, 0)),
                  pl.BlockSpec((blk, sk), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((blk, sk), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((prows, sk), g2.dtype),
        interpret=pallas_interpret(),
    )(gp, yp)
    return dx[:rows]


# ----------------------------- custom_vjp plumbing --------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _softmax(x, mask, scale, causal):
    return _softmax_impl(x, mask, scale, causal)


def _softmax_impl(x, mask, scale, causal):
    shape = x.shape
    sk = shape[-1]
    sq = shape[-2] if len(shape) >= 2 else 1
    x2 = x.reshape(-1, sk)
    mask2 = None
    if mask is not None:
        mask2 = jnp.broadcast_to(mask, shape).reshape(-1, sk)
    return _fwd_pallas(x2, mask2, scale, causal, sq).reshape(shape)


def _softmax_fwd(x, mask, scale, causal):
    y = _softmax_impl(x, mask, scale, causal)
    return y, y


def _softmax_bwd(scale, causal, y, g):
    shape = y.shape
    dx = _bwd_pallas(g.reshape(-1, shape[-1]), y.reshape(-1, shape[-1]), scale)
    return (dx.reshape(shape), None)


_softmax.defvjp(_softmax_fwd, _softmax_bwd)


# --------------------------------- public API -------------------------------

def scaled_softmax(x, scale: float = 1.0,
                   use_pallas_override: Optional[bool] = None):
    """≡ ScaledSoftmax (fused_softmax.py:180-216)."""
    if use_pallas(use_pallas_override):
        return _softmax(x, None, float(scale), False)
    return scaled_softmax_reference(x, scale)


def scaled_masked_softmax(x, mask, scale: float = 1.0,
                          use_pallas_override: Optional[bool] = None):
    """≡ ScaledMaskedSoftmax (fused_softmax.py:94-130); also covers the
    GenericScaledMaskedSoftmax arbitrary-shape variant (132-163)."""
    if mask is None:
        return scaled_softmax(x, scale, use_pallas_override)
    if use_pallas(use_pallas_override):
        return _softmax(x, mask, float(scale), False)
    return scaled_masked_softmax_reference(x, mask, scale)


def scaled_upper_triang_masked_softmax(x, scale: float = 1.0,
                                       use_pallas_override: Optional[bool] = None):
    """≡ ScaledUpperTriangMaskedSoftmax (fused_softmax.py:21-56)."""
    if x.shape[-2] != x.shape[-1]:
        raise ValueError("causal softmax requires sq == sk")
    if use_pallas(use_pallas_override):
        return _softmax(x, None, float(scale), True)
    return scaled_upper_triang_masked_softmax_reference(x, scale)


def get_batch_per_block(sq: int, sk: int, batches: int, attn_heads: int) -> int:
    """Scheduling hint ≡ scaled_masked_softmax_cuda.get_batch_per_block
    (csrc/megatron/scaled_masked_softmax.cpp): how many (batch, head)
    rows one kernel block covers.  The Pallas kernel tiles rows in
    row-block groups over the flattened (batches*heads*sq) dimension,
    so the answer is rows-per-block / sq (at least 1)."""
    rows = batches * attn_heads * sq
    return max(1, row_block(rows, sk) // max(sq, 1))
