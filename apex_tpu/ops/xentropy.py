"""Fused label-smoothed softmax cross-entropy — Pallas fwd+bwd.

≡ the reference's `xentropy_cuda` extension
(apex/contrib/csrc/xentropy/xentropy_kernel.cu, 718 LoC) and its wrapper
`apex.contrib.xentropy.SoftmaxCrossEntropyLoss` (apex/contrib/xentropy/__init__.py:1):
one pass computes per-sample loss = lse(x) - (1-eps)*x[label] - eps*mean(x)
saving only the log-sum-exp for backward; the backward pass reconstructs
softmax(x) - q where q = (1-eps)*onehot + eps/V.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from apex_tpu.ops._common import pallas_interpret, row_block, use_pallas


# --------------------------- reference (jnp) path ---------------------------

def softmax_cross_entropy_reference(logits, labels, smoothing=0.0):
    """Per-sample loss, fp32; labels int (rows,)."""
    x = logits.astype(jnp.float32)
    lse = jax.nn.logsumexp(x, axis=-1)
    xl = jnp.take_along_axis(x, labels[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    if smoothing:
        return lse - (1.0 - smoothing) * xl - smoothing * jnp.mean(x, axis=-1)
    return lse - xl


# ------------------------------ pallas kernels ------------------------------

def _fwd_kernel(x_ref, lbl_ref, loss_ref, lse_ref, *, smoothing):
    x = x_ref[...].astype(jnp.float32)
    m = jnp.max(x, axis=1, keepdims=True)
    lse = jnp.log(jnp.sum(jnp.exp(x - m), axis=1, keepdims=True)) + m
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == lbl_ref[...]).astype(jnp.float32)
    xl = jnp.sum(x * onehot, axis=1, keepdims=True)
    loss = lse - (1.0 - smoothing) * xl
    if smoothing:
        loss = loss - smoothing * jnp.mean(x, axis=1, keepdims=True)
    loss_ref[...] = loss
    lse_ref[...] = lse


def _bwd_kernel(g_ref, x_ref, lbl_ref, lse_ref, dx_ref, *, smoothing):
    x = x_ref[...].astype(jnp.float32)
    p = jnp.exp(x - lse_ref[...])
    cols = lax.broadcasted_iota(jnp.int32, x.shape, 1)
    onehot = (cols == lbl_ref[...]).astype(jnp.float32)
    q = (1.0 - smoothing) * onehot
    if smoothing:
        q = q + smoothing / x.shape[1]
    dx_ref[...] = (g_ref[...] * (p - q)).astype(dx_ref.dtype)


def _pad(a, blk, fill=0):
    pad = (-a.shape[0]) % blk
    if pad:
        a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1),
                    constant_values=fill)
    return a


def _fwd_pallas(x2, labels, smoothing):
    rows, v = x2.shape
    blk = row_block(rows, v)
    xp = _pad(x2, blk)
    lbl = _pad(labels.astype(jnp.int32).reshape(-1, 1), blk)
    prows = xp.shape[0]
    loss, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, smoothing=smoothing),
        grid=(prows // blk,),
        in_specs=[pl.BlockSpec((blk, v), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_specs=[pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                   pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((prows, 1), jnp.float32),
                   jax.ShapeDtypeStruct((prows, 1), jnp.float32)],
        interpret=pallas_interpret(),
    )(xp, lbl)
    return loss[:rows, 0], lse[:rows]


def _bwd_pallas(g, x2, labels, lse, smoothing):
    rows, v = x2.shape
    blk = row_block(rows, v)
    gp = _pad(g.reshape(-1, 1).astype(jnp.float32), blk)
    xp = _pad(x2, blk)
    lbl = _pad(labels.astype(jnp.int32).reshape(-1, 1), blk)
    lsep = _pad(lse, blk)
    prows = xp.shape[0]
    dx = pl.pallas_call(
        functools.partial(_bwd_kernel, smoothing=smoothing),
        grid=(prows // blk,),
        in_specs=[pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                  pl.BlockSpec((blk, v), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0)),
                  pl.BlockSpec((blk, 1), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((blk, v), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((prows, v), x2.dtype),
        interpret=pallas_interpret(),
    )(gp, xp, lbl, lsep)
    return dx[:rows]


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _xent(logits2, labels, smoothing):
    loss, _ = _fwd_pallas(logits2, labels, smoothing)
    return loss


def _xent_fwd(logits2, labels, smoothing):
    loss, lse = _fwd_pallas(logits2, labels, smoothing)
    return loss, (logits2, labels, lse)


def _xent_bwd(smoothing, res, g):
    logits2, labels, lse = res
    return (_bwd_pallas(g, logits2, labels, lse, smoothing), None)


_xent.defvjp(_xent_fwd, _xent_bwd)


# --------------------------------- public API -------------------------------

def softmax_cross_entropy_loss(logits, labels, smoothing: float = 0.0,
                               use_pallas_override: Optional[bool] = None):
    """Per-sample label-smoothed cross entropy.

    ≡ apex.contrib.xentropy.SoftmaxCrossEntropyLoss.apply(logits, labels,
    smoothing, padding_idx=0, half_to_float).  Leading dims are batch;
    last dim is the vocab.
    """
    shape = logits.shape
    if use_pallas(use_pallas_override):
        loss = _xent(logits.reshape(-1, shape[-1]), labels.reshape(-1),
                     float(smoothing))
        return loss.reshape(shape[:-1])
    return softmax_cross_entropy_reference(logits, labels, smoothing)


SoftmaxCrossEntropyLoss = softmax_cross_entropy_loss
