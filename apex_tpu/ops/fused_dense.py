"""Fused dense (matmul + bias + activation) kernels.

≡ the reference's `fused_dense_cuda` extension (csrc/fused_dense.cpp:188-191,
cublasLt epilogue kernels csrc/fused_dense_cuda.cu) and its wrappers
apex.fused_dense.{FusedDense,FusedDenseGeluDense}
(apex/fused_dense/fused_dense.py:7-99), plus
`fused_weight_gradient_mlp_cuda` (csrc/megatron/fused_weight_gradient_dense.cpp:19-20)
— the wgrad GEMM that accumulates directly into a persistent fp32
main_grad buffer.

TPU design: a Pallas MXU matmul kernel with the bias+activation epilogue
fused into the final K-step (≡ cublasLt epilogues), fp32 accumulation
scratch, custom_vjp whose backward runs plain XLA matmuls (dgrad/wgrad
are bare GEMMs — XLA is already optimal there).  Off-TPU (and under
`use_pallas=False`) the forward is a jnp chain that XLA fuses to the
same schedule.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from apex_tpu.ops._common import pallas_interpret, round_up, use_pallas


def _act(y, activation):
    if activation == "relu":
        return jnp.maximum(y, 0.0)
    if activation == "gelu":
        return jax.nn.gelu(y, approximate=True)
    if activation == "sigmoid":
        return jax.nn.sigmoid(y)
    if activation in (None, "none"):
        return y
    raise ValueError(f"unknown activation {activation!r}")


# --------------------------- reference (jnp) path ---------------------------

def linear_bias_reference(x, w, b=None, activation=None):
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    if b is not None:
        y = y + b.astype(jnp.float32)
    return _act(y, activation).astype(x.dtype)


# ------------------------------ pallas kernel -------------------------------

def _matmul_kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *, activation,
                   has_bias, k_steps):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == k_steps - 1)
    def _epilogue():
        y = acc_ref[...]
        if has_bias:
            y = y + b_ref[0, :].astype(jnp.float32)
        o_ref[...] = _act(y, activation).astype(o_ref.dtype)


def _matmul_pallas(x2, w, b, activation, bm=256, bn=256, bk=512):
    m, kdim = x2.shape
    _, n = w.shape
    bm = min(bm, round_up(m, 8))
    bn = min(bn, round_up(n, 128))
    bk = min(bk, round_up(kdim, 128))
    mp, np_, kp = round_up(m, bm), round_up(n, bn), round_up(kdim, bk)
    xp = jnp.pad(x2, ((0, mp - m), (0, kp - kdim))) if (mp, kp) != (m, kdim) else x2
    wp = jnp.pad(w, ((0, kp - kdim), (0, np_ - n))) if (kp, np_) != (kdim, n) else w
    has_bias = b is not None
    # bias rides as a (1, n) row: TPU Mosaic requires >=2-D blocks with a
    # 128-lane minor dim (a 1-D spec compiles in interpret mode only)
    bp = jnp.pad(b, (0, np_ - n)) if has_bias and np_ != n else (
        b if has_bias else jnp.zeros((np_,), x2.dtype))
    bp = bp.reshape(1, np_)
    k_steps = kp // bk
    out = pl.pallas_call(
        functools.partial(_matmul_kernel, activation=activation,
                          has_bias=has_bias, k_steps=k_steps),
        grid=(mp // bm, np_ // bn, k_steps),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, k: (i, k)),
            pl.BlockSpec((bk, bn), lambda i, j, k: (k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), x2.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=pallas_interpret(),
    )(xp, wp, bp)
    return out[:m, :n]


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _fused_linear(x2, w, b, activation):
    return _matmul_pallas(x2, w, b, activation)


def _fused_linear_fwd(x2, w, b, activation):
    # save pre-activation only when the activation needs it
    if activation in (None, "none"):
        y = _matmul_pallas(x2, w, b, activation)
        return y, (x2, w, b, None)
    pre = _matmul_pallas(x2, w, b, None)
    return _act(pre.astype(jnp.float32), activation).astype(x2.dtype), (
        x2, w, b, pre)


def _fused_linear_bwd(activation, res, g):
    x2, w, b, pre = res
    g32 = g.astype(jnp.float32)
    if activation == "relu":
        g32 = jnp.where(pre > 0, g32, 0.0)
    elif activation == "gelu":
        _, vjp = jax.vjp(lambda p: jax.nn.gelu(p.astype(jnp.float32),
                                               approximate=True), pre)
        (g32,) = vjp(g32)
    elif activation == "sigmoid":
        s = jax.nn.sigmoid(pre.astype(jnp.float32))
        g32 = g32 * s * (1.0 - s)
    g_cast = g32.astype(x2.dtype)
    dx = jnp.dot(g_cast, w.T, preferred_element_type=jnp.float32).astype(x2.dtype)
    dw = jnp.dot(x2.T, g_cast, preferred_element_type=jnp.float32).astype(w.dtype)
    db = None if b is None else jnp.sum(g32, axis=0).astype(b.dtype)
    return dx, dw, db


_fused_linear.defvjp(_fused_linear_fwd, _fused_linear_bwd)


# --------------------------------- public API -------------------------------

def linear_bias(x, w, b=None, activation: Optional[str] = None,
                use_pallas_override: Optional[bool] = None):
    """y = act(x @ w + b) with the epilogue fused.

    ≡ fused_dense_cuda.linear_bias_forward (csrc/fused_dense.cpp:188).
    x: (..., K), w: (K, N), b: (N,).
    """
    shape = x.shape
    x2 = x.reshape(-1, shape[-1])
    if use_pallas(use_pallas_override):
        y = _fused_linear(x2, w, b, activation)
    else:
        y = linear_bias_reference(x2, w, b, activation)
    return y.reshape(shape[:-1] + (w.shape[-1],))


def linear_gelu_linear(x, w1, b1, w2, b2,
                       use_pallas_override: Optional[bool] = None):
    """y = (gelu(x@w1+b1))@w2+b2 ≡ fused_dense_cuda.linear_gelu_linear_forward
    (csrc/fused_dense.cpp:190)."""
    h = linear_bias(x, w1, b1, "gelu", use_pallas_override)
    return linear_bias(h, w2, b2, None, use_pallas_override)


def qkv_split_heads(qkv, num_heads, head_dim):
    """Packed-QKV head split: (S, B, 3·nh·d) → three (B, nh, S, d).

    The QKV projection is already ONE GEMM (a single (H, 3H)
    ColumnParallelLinear ≡ the reference's fused QKV,
    standalone_transformer_lm.py attention).  What the round-6 per-GEMM
    roofline flagged was the glue AFTER it: slicing q/k/v out of the
    middle of the packed reshape and transposing each slice separately
    costs three strided (S, B, nh, d) copies.  This helper transposes
    the PACKED tensor once — (3, B, nh, S, d), one fused relayout whose
    minor dim stays the lane-aligned head_dim — and hands out q/k/v as
    leading-dim views (no further copy).  Gradient is the mirrored
    single transpose (AD of transpose+concat).
    """
    s, b = qkv.shape[:2]
    qkv = qkv.reshape(s, b, 3, num_heads, head_dim)
    qkv = qkv.transpose(2, 1, 3, 0, 4)  # (3, B, nh, S, d)
    return qkv[0], qkv[1], qkv[2]


def wgrad_accum(main_grad, x, g):
    """main_grad += x^T @ g with fp32 accumulation.

    ≡ fused_weight_gradient_mlp_cuda.wgrad_gemm_accum_fp32
    (csrc/megatron/fused_weight_gradient_dense.cpp:19) — the Megatron
    linear's weight-grad GEMM that accumulates into a persistent fp32
    buffer.  Under jit with donation the accumulate is in-place.
    """
    x2 = x.reshape(-1, x.shape[-1])
    g2 = g.reshape(-1, g.shape[-1])
    return main_grad + jnp.dot(x2.T, g2, preferred_element_type=jnp.float32)


class FusedDense:
    """≡ apex.fused_dense.FusedDense (apex/fused_dense/fused_dense.py:64)."""

    def __init__(self, in_features, out_features, bias=True):
        self.in_features = in_features
        self.out_features = out_features
        self.use_bias = bias

    def init(self, key, dtype=jnp.float32):
        k1, k2 = jax.random.split(key)
        bound = 1.0 / jnp.sqrt(self.in_features)
        p = {"weight": jax.random.uniform(
            k1, (self.in_features, self.out_features), dtype, -bound, bound)}
        if self.use_bias:
            p["bias"] = jax.random.uniform(k2, (self.out_features,), dtype,
                                           -bound, bound)
        return p

    def apply(self, params, x, use_pallas_override=None):
        return linear_bias(x, params["weight"], params.get("bias"),
                           None, use_pallas_override)


class FusedDenseGeluDense:
    """≡ apex.fused_dense.FusedDenseGeluDense (fused_dense.py:82)."""

    def __init__(self, in_features, intermediate_features, out_features,
                 bias=True):
        self.sizes = (in_features, intermediate_features, out_features)
        self.use_bias = bias

    def init(self, key, dtype=jnp.float32):
        i, h, o = self.sizes
        k1, k2, k3, k4 = jax.random.split(key, 4)
        b1 = 1.0 / jnp.sqrt(i)
        b2 = 1.0 / jnp.sqrt(h)
        return {
            "weight1": jax.random.uniform(k1, (i, h), dtype, -b1, b1),
            "bias1": jax.random.uniform(k2, (h,), dtype, -b1, b1),
            "weight2": jax.random.uniform(k3, (h, o), dtype, -b2, b2),
            "bias2": jax.random.uniform(k4, (o,), dtype, -b2, b2),
        }

    def apply(self, params, x, use_pallas_override=None):
        return linear_gelu_linear(x, params["weight1"], params["bias1"],
                                  params["weight2"], params["bias2"],
                                  use_pallas_override)
