"""apex_tpu — a TPU-native training-systems toolkit.

A ground-up JAX/XLA/Pallas re-design of the capability surface of NVIDIA
Apex (reference: apex/__init__.py): mixed precision, fused kernels
(normalization, softmax, attention, losses, optimizers), data-parallel
training utilities, and a Megatron-style tensor/pipeline/sequence
parallelism library — all expressed as functional transforms over a
`jax.sharding.Mesh` instead of CUDA streams + NCCL process groups.

Subpackages (lazily importable):
  amp          — precision policies + dynamic loss scaling (≡ apex.amp)
  ops          — Pallas/XLA fused kernels (≡ csrc/ + apex.normalization,
                 apex.mlp, apex.fused_dense, apex.contrib kernels)
  optimizers   — fused optimizers over flat buffers (≡ apex.optimizers)
  parallel     — mesh/collectives/DP/SyncBN/LARC (≡ apex.parallel)
  transformer  — TP/SP/PP library (≡ apex.transformer)
  models       — flagship end-to-end models (ResNet, GPT, MoE-GPT, BERT)
  moe          — expert-parallel Mixture-of-Experts (router/dispatch/layer)
  monitor      — on-device metrics pytree + host sinks + profiler capture
"""

import logging as _logging

from apex_tpu import _compat as _compat  # installs jax version shims

__version__ = "0.1.0"


class RankInfoFormatter(_logging.Formatter):
    """Log formatter prefixing (dp, tp, pp) rank info when a mesh is live.

    TPU-native analogue of apex/__init__.py:31-43: instead of torch
    process-group ranks we report jax process_index and, when a global
    mesh has been initialized, the mesh axis coordinates of this host.
    """

    def format(self, record):
        from apex_tpu.parallel import mesh as _mesh

        try:
            info = _mesh.get_rank_info()
        except Exception:
            info = "uninit"
        record.rank_info = info
        return super().format(record)


_logger = _logging.getLogger(__name__)
_logger.addHandler(_logging.NullHandler())


def deprecated_warning(msg: str) -> None:
    """≡ apex.deprecated_warning (apex/__init__.py:45-56): emit a
    deprecation notice once, only from process 0."""
    import warnings

    try:
        import jax

        if jax.process_index() != 0:
            return
    except Exception:
        pass
    warnings.warn(msg, FutureWarning, stacklevel=2)


def _get_logger(name=None):
    return _logging.getLogger(name or __name__)


# Eager, cheap imports only; heavy subpackages import on attribute access.
from apex_tpu import parallel  # noqa: E402,F401
from apex_tpu import ops  # noqa: E402,F401
from apex_tpu import optimizers  # noqa: E402,F401
from apex_tpu import amp  # noqa: E402,F401
from apex_tpu import transformer  # noqa: E402,F401


_LAZY_SUBMODULES = {
    # reference name parity (apex/__init__.py lazy subpackages)
    "contrib", "fp16_utils", "models", "monitor", "normalization", "mlp",
    "fused_dense", "multi_tensor_apply", "checkpoint", "rnn",
    # TPU-native additions
    "moe", "serve", "lint", "tune",
}


def __getattr__(name):
    import importlib

    if name in _LAZY_SUBMODULES:
        return importlib.import_module(f"apex_tpu.{name}")
    if name == "RNN":  # ≡ apex.RNN (apex/RNN/__init__.py)
        return importlib.import_module("apex_tpu.rnn")
    raise AttributeError(f"module 'apex_tpu' has no attribute {name!r}")
