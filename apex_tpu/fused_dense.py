"""apex_tpu.fused_dense — fused linear(+bias)(+GELU) (≡ apex.fused_dense,
apex/fused_dense/fused_dense.py:7-99).

Parity shim re-exporting the fused dense kernels from the ops layer.
"""

from apex_tpu.ops.fused_dense import (  # noqa: F401
    FusedDense,
    FusedDenseGeluDense,
    linear_bias,
    linear_gelu_linear,
    wgrad_accum,
)

__all__ = ["FusedDense", "FusedDenseGeluDense", "linear_bias",
           "linear_gelu_linear", "wgrad_accum"]
