"""ResNet (18/34/50/101/152) — the flagship CNN, NHWC, TPU-first.

≡ the reference's canonical end-to-end model: torchvision resnet50
driven by examples/imagenet/main_amp.py (AMP + DDP + SyncBN), plus the
fused bottleneck block of apex.contrib.bottleneck
(apex/contrib/bottleneck/bottleneck.py:134) — on TPU the conv+BN+ReLU
chains are XLA-fused; the block structure here mirrors the contrib
Bottleneck so the SpatialBottleneck halo variant (parallel/collectives
halo_exchange_1d) drops in.

Layout: NHWC (TPU-native conv layout).  BatchNorm is SyncBatchNorm with
an optional dp axis name — pass axis_name=None for local BN.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.ops.pooling import max_pool2d
from apex_tpu.parallel.sync_batchnorm import sync_batch_norm


def conv2d(x, w, stride=1, padding="SAME"):
    """NHWC conv; weights HWIO.  bf16 inputs hit the MXU directly."""
    return lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def space_to_depth_2x2(x):
    """(B, H, W, C) → (B, H/2, W/2, 4C), channel order (u, v, c)."""
    b, h, w, c = x.shape
    x = x.reshape(b, h // 2, 2, w // 2, 2, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // 2, w // 2, 4 * c)


def _stem_s2d_weights(w7):
    """Exact rewrite of the (7,7,3,cout) stride-2 stem kernel as a
    (4,4,12,cout) stride-1 kernel over the 2x2 space-to-depth input.

    With SAME padding (pad_lo=2 at k7 s2; pad_lo=1 at k4 s1):
      out[i] = Σ_di x[2i + di - 2] · w[di]
             = Σ_{ka,u} z[i + ka - 1]⟨u⟩ · w[2·ka + u]
    so w'[ka, kb, (u,v,c), o] = w_pad[2ka+u, 2kb+v, c, o] with w zero-
    padded from 7 to 8 taps.  The same TPU stem transform as the MLPerf
    ResNet submissions — the (3-channel, stride-2) conv maps terribly
    onto the MXU's 128-lane tiles; the s2d form is stride-1 with 4x the
    channels and identical math.
    """
    k, _, cin, cout = w7.shape
    w_pad = jnp.zeros((8, 8, cin, cout), w7.dtype).at[:k, :k].set(w7)
    w_pad = w_pad.reshape(4, 2, 4, 2, cin, cout)       # (ka,u,kb,v,c,o)
    return w_pad.transpose(0, 2, 1, 3, 4, 5).reshape(4, 4, 4 * cin, cout)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    std = jnp.sqrt(2.0 / fan_in)  # kaiming normal ≡ torchvision init
    return jax.random.normal(key, (kh, kw, cin, cout), dtype) * std


def _bn_init(c):
    return ({"scale": jnp.ones((c,)), "bias": jnp.zeros((c,))},
            {"running_mean": jnp.zeros((c,)), "running_var": jnp.ones((c,))})


def _bn_apply(params, state, x, training, axis_name, eps=1e-5,
              momentum=0.1):
    y, rm, rv = sync_batch_norm(
        x, params["scale"], params["bias"], state["running_mean"],
        state["running_var"], training=training, momentum=momentum,
        eps=eps, axis_name=axis_name)
    return y, {"running_mean": rm, "running_var": rv}


class Bottleneck:
    """1x1 → 3x3 → 1x1 with residual ≡ torchvision Bottleneck /
    apex.contrib.bottleneck.Bottleneck (bottleneck.py:134)."""

    expansion = 4

    def __init__(self, cin, width, stride=1, downsample=False):
        self.cin = cin
        self.width = width
        self.stride = stride
        self.downsample = downsample
        self.cout = width * self.expansion

    def init(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, 4)
        params, state = {}, {}
        params["conv1"] = _conv_init(ks[0], 1, 1, self.cin, self.width, dtype)
        params["bn1"], state["bn1"] = _bn_init(self.width)
        params["conv2"] = _conv_init(ks[1], 3, 3, self.width, self.width, dtype)
        params["bn2"], state["bn2"] = _bn_init(self.width)
        params["conv3"] = _conv_init(ks[2], 1, 1, self.width, self.cout, dtype)
        params["bn3"], state["bn3"] = _bn_init(self.cout)
        # zero-init last BN scale ≡ torchvision zero_init_residual /
        # main_amp.py training recipe
        params["bn3"]["scale"] = jnp.zeros_like(params["bn3"]["scale"])
        if self.downsample:
            params["conv_ds"] = _conv_init(ks[3], 1, 1, self.cin, self.cout,
                                           dtype)
            params["bn_ds"], state["bn_ds"] = _bn_init(self.cout)
        return params, state

    def apply(self, params, state, x, training, axis_name):
        new_state = {}
        out = conv2d(x, params["conv1"])
        out, new_state["bn1"] = _bn_apply(params["bn1"], state["bn1"], out,
                                          training, axis_name)
        out = jnp.maximum(out, 0)
        out = conv2d(out, params["conv2"], stride=self.stride)
        out, new_state["bn2"] = _bn_apply(params["bn2"], state["bn2"], out,
                                          training, axis_name)
        out = jnp.maximum(out, 0)
        out = conv2d(out, params["conv3"])
        out, new_state["bn3"] = _bn_apply(params["bn3"], state["bn3"], out,
                                          training, axis_name)
        if self.downsample:
            sc = conv2d(x, params["conv_ds"], stride=self.stride)
            sc, new_state["bn_ds"] = _bn_apply(params["bn_ds"],
                                               state["bn_ds"], sc,
                                               training, axis_name)
        else:
            sc = x
        return jnp.maximum(out + sc, 0), new_state


class BasicBlock:
    expansion = 1

    def __init__(self, cin, width, stride=1, downsample=False):
        self.cin = cin
        self.width = width
        self.stride = stride
        self.downsample = downsample
        self.cout = width

    def init(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, 3)
        params, state = {}, {}
        params["conv1"] = _conv_init(ks[0], 3, 3, self.cin, self.width, dtype)
        params["bn1"], state["bn1"] = _bn_init(self.width)
        params["conv2"] = _conv_init(ks[1], 3, 3, self.width, self.width, dtype)
        params["bn2"], state["bn2"] = _bn_init(self.width)
        params["bn2"]["scale"] = jnp.zeros_like(params["bn2"]["scale"])
        if self.downsample:
            params["conv_ds"] = _conv_init(ks[2], 1, 1, self.cin, self.cout,
                                           dtype)
            params["bn_ds"], state["bn_ds"] = _bn_init(self.cout)
        return params, state

    def apply(self, params, state, x, training, axis_name):
        new_state = {}
        out = conv2d(x, params["conv1"], stride=self.stride)
        out, new_state["bn1"] = _bn_apply(params["bn1"], state["bn1"], out,
                                          training, axis_name)
        out = jnp.maximum(out, 0)
        out = conv2d(out, params["conv2"])
        out, new_state["bn2"] = _bn_apply(params["bn2"], state["bn2"], out,
                                          training, axis_name)
        if self.downsample:
            sc = conv2d(x, params["conv_ds"], stride=self.stride)
            sc, new_state["bn_ds"] = _bn_apply(params["bn_ds"],
                                               state["bn_ds"], sc,
                                               training, axis_name)
        else:
            sc = x
        return jnp.maximum(out + sc, 0), new_state


_CONFIGS = {
    "resnet10": (BasicBlock, (1, 1, 1, 1)),  # test/CI stand-in
    "resnet18": (BasicBlock, (2, 2, 2, 2)),
    "resnet34": (BasicBlock, (3, 4, 6, 3)),
    "resnet50": (Bottleneck, (3, 4, 6, 3)),
    "resnet101": (Bottleneck, (3, 4, 23, 3)),
    "resnet152": (Bottleneck, (3, 8, 36, 3)),
}


class ResNet:
    def __init__(self, arch: str = "resnet50", num_classes: int = 1000,
                 axis_name: Optional[str] = None, small_input: bool = False,
                 stem: str = "conv7"):
        """stem="space_to_depth" computes the SAME function as the
        default 7x7/s2 stem via a 2x2 space-to-depth input + 4x4/s1
        conv (see _stem_s2d_weights) — params stay (7,7,3,64), so
        checkpoints are interchangeable between the two settings."""
        if stem not in ("conv7", "space_to_depth"):
            raise ValueError(f"unknown stem {stem!r}")
        if stem == "space_to_depth" and small_input:
            raise ValueError(
                "stem='space_to_depth' rewrites the 7x7/s2 ImageNet "
                "stem; the small_input (CIFAR) 3x3/s1 stem has no "
                "stride to fold — use the default stem")
        block_cls, layers = _CONFIGS[arch]
        self.arch = arch
        self.num_classes = num_classes
        self.axis_name = axis_name
        self.small_input = small_input  # CIFAR stand-in: 3x3 stem, no pool
        self.stem = stem
        self.blocks = []
        cin = 64
        for stage, n in enumerate(layers):
            width = 64 * (2 ** stage)
            for i in range(n):
                stride = 2 if (stage > 0 and i == 0) else 1
                downsample = (i == 0 and (stride != 1 or
                                          cin != width * block_cls.expansion))
                blk = block_cls(cin, width, stride, downsample)
                self.blocks.append(blk)
                cin = blk.cout
        self.feat_dim = cin

    def init(self, key, dtype=jnp.float32):
        ks = jax.random.split(key, len(self.blocks) + 2)
        params, state = {}, {}
        stem_k = 3 if self.small_input else 7
        params["conv_stem"] = _conv_init(ks[0], stem_k, stem_k, 3, 64, dtype)
        params["bn_stem"], state["bn_stem"] = _bn_init(64)
        for i, blk in enumerate(self.blocks):
            params[f"block{i}"], state[f"block{i}"] = blk.init(ks[i + 1],
                                                               dtype)
        params["fc_w"] = jax.random.normal(
            ks[-1], (self.feat_dim, self.num_classes), dtype) * 0.01
        params["fc_b"] = jnp.zeros((self.num_classes,), dtype)
        return params, state

    def apply(self, params, state, x, training: bool = True,
              axis_name="__unset__"):
        ax = self.axis_name if axis_name == "__unset__" else axis_name
        new_state = {}
        stride = 1 if self.small_input else 2
        if self.stem == "space_to_depth" and not self.small_input:
            if x.shape[1] % 2 or x.shape[2] % 2:
                raise ValueError(
                    f"stem='space_to_depth' needs even spatial dims, "
                    f"got {x.shape[1]}x{x.shape[2]} — pad the input or "
                    "use the default stem (same function)")
            h = conv2d(space_to_depth_2x2(x),
                       _stem_s2d_weights(params["conv_stem"]), stride=1)
        else:
            h = conv2d(x, params["conv_stem"], stride=stride)
        h, new_state["bn_stem"] = _bn_apply(params["bn_stem"],
                                            state["bn_stem"], h, training, ax)
        h = jnp.maximum(h, 0)
        if not self.small_input:
            # default (SelectAndScatter) backward: measured faster than
            # every dense routed reformulation in full-model context on
            # v5e (ops/pooling.py docstring has the numbers)
            h = max_pool2d(h, (3, 3), (2, 2), "SAME")
        for i, blk in enumerate(self.blocks):
            h, new_state[f"block{i}"] = blk.apply(
                params[f"block{i}"], state[f"block{i}"], h, training, ax)
        h = jnp.mean(h, axis=(1, 2))  # global average pool
        logits = h @ params["fc_w"] + params["fc_b"]
        return logits, new_state


def resnet50(**kw):
    return ResNet("resnet50", **kw)


def resnet18(**kw):
    return ResNet("resnet18", **kw)
