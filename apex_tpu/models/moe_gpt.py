"""MoE-GPT — the expert-parallel flagship (ISSUE 13, ROADMAP item 5).

GPT with every block's dense MLP swapped for `apex_tpu.moe.MoEMLP`:
fp32 top-k routing, capacity-factor dropping into a static (E, C, H)
dispatch buffer, ONE all_to_all over the `ep` mesh axis each way, and
raw-gate-weighted combine.  Everything else — embedding, attention,
layer norms, vocab-parallel head — is literally the GPT code (this
class only overrides init / partition_specs / the block's MLP half),
which is what makes the acceptance anchor provable: at n_experts=1 /
top_k=1 / capacity_factor=inf / aux_coef=z_coef=0 the whole train
step is BITWISE the dense GPT step's (tests/test_moe.py).

Training wiring (the `build_moe_train_step` builder, shared by
bench.py, scripts/lint_step.py, scripts/comms_probe.py and the
tests): the batch shards over the COMBINED ("dp", "ep") axes — expert
parallelism lives inside the data-parallel world — and the ZeRO-2
`DistributedFusedAdam` shards its fp32/bf16 master state over the
same combined axes (`num_shards=dp*ep`, `axis_name=("dp","ep")`,
`ep_shards=ep` so the checkpoint layout records the expert sharding
and `restore_sharded` can refuse an ep re-shard BY NAME).  Gradients
need no expert-special handling: the combine all_to_all's AD
transpose already sums each expert's partial grads across its ep
group, so the step's uniform mean over ("dp", "ep") is exact
(docs/moe.md, "Why one pmean is enough").

Not supported in this round (loud errors, not silent wrongness):
sequence_parallel (the token-locality assumption of dispatch breaks)
and remat (per-block aux stats cross the checkpoint boundary);
tensor-parallel expert GEMMs are future work — experts replicate
over tp.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from apex_tpu.models.gpt import GPT, GPTConfig
from apex_tpu.moe.layer import MoEMLP, mean_aux
from apex_tpu.ops._common import tap as _tap
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.random import (
    model_parallel_fold_in,
)


@dataclasses.dataclass(frozen=True)
class MoEGPTConfig(GPTConfig):
    n_experts: int = 8
    top_k: int = 2
    # slots per expert per source shard = ceil(T*k*cf/E) (router.
    # expert_capacity); inf = never drop (capacity == token count)
    capacity_factor: float = 1.25
    # ep size the model computes at (experts sliced by lax.axis_index
    # ("ep") when > 1); must divide n_experts and match the mesh
    expert_parallel: int = 1
    aux_coef: float = 1e-2           # load-balancing loss weight
    z_coef: float = 1e-3             # router z-loss weight
    router_block_rows: int = 0       # 0 = tuner/heuristic (moe_router op)

    def __post_init__(self):
        if self.sequence_parallel:
            raise ValueError(
                "MoEGPT does not support sequence_parallel: dispatch "
                "assumes every local token row is a whole token, and a "
                "seq-sharded activation is not (route-then-gather is "
                "future work)")
        if self.remat:
            raise ValueError(
                "MoEGPT does not support remat yet: the per-block MoE "
                "aux stats cross the jax.checkpoint boundary; run the "
                "smoke/bench shapes without it")
        if self.n_experts % max(1, self.expert_parallel):
            raise ValueError(
                f"n_experts={self.n_experts} must divide by "
                f"expert_parallel={self.expert_parallel}")


class MoEGPT(GPT):
    def __init__(self, config: MoEGPTConfig):
        super().__init__(config)
        c = config
        self.moe = [
            MoEMLP(c.hidden, c.ffn_mult * c.hidden, c.n_experts,
                   top_k=c.top_k, capacity_factor=c.capacity_factor,
                   ep_size=c.expert_parallel, init_std=0.02,
                   proj_init_std=0.02 / float(jnp.sqrt(
                       2.0 * c.num_layers)),
                   router_block_rows=c.router_block_rows or None,
                   tp_axis=c.axis_name,
                   overlap_chunks=c.overlap_chunks)
            for _ in range(c.num_layers)]

    # ------------------------------ params --------------------------------

    def init(self, key):
        params = super().init(key)
        c = self.c
        moe_key = jax.random.fold_in(key, c.num_layers + 7)
        for i in range(c.num_layers):
            bp = params[f"block{i}"]
            bp.pop("fc1")
            bp.pop("fc2")
            bp["moe"] = self.moe[i].init(
                jax.random.fold_in(moe_key, i), c.dtype)
        return params

    def partition_specs(self):
        specs = super().partition_specs()
        for i in range(self.c.num_layers):
            bs = specs[f"block{i}"]
            bs.pop("fc1")
            bs.pop("fc2")
            bs["moe"] = self.moe[i].partition_specs()
        return specs

    # ------------------------------ forward -------------------------------

    def _block(self, i, params, x, key):
        """GPT's block with the MLP half replaced; returns (x, MoEAux)."""
        qkv_mod, proj_mod, _, _ = self.blocks[i]
        bp = params
        k1 = k2 = k3 = None
        if key is not None:
            k1, k2, k3 = jax.random.split(key, 3)
        h = _tap(self._ln(bp["ln1"], x), f"block{i}/ln1")
        attn = self._attention(bp, qkv_mod, proj_mod, h, k1)
        attn = _tap(attn, f"block{i}/attn")
        x = x + self._dropout(k2, attn)
        h = _tap(self._ln(bp["ln2"], x), f"block{i}/ln2")
        m, aux = self.moe[i].apply(bp["moe"], h,
                                   tap_prefix=f"block{i}/moe",
                                   cn=("ffn1", "ffn_out"))
        m = _tap(m, f"block{i}/mlp")
        x = x + self._dropout(k3, m)
        return x, aux

    def apply_with_stats(self, params, tokens, key=None):
        """GPT.apply with per-block MoE aux collection: returns
        (hidden (S, B, H), MoEAux averaged over blocks)."""
        c = self.c
        ids = tokens.T
        h = self.embed.apply(params["embed"], ids)
        pos = params["pos_embed"][: tokens.shape[1]][:, None, :]
        h = h + pos.astype(h.dtype)
        if key is not None:
            key = model_parallel_fold_in(key, c.axis_name)
        auxes = []
        for i in range(c.num_layers):
            bk = None if key is None else jax.random.fold_in(key, i)
            h, aux = self._block(i, params[f"block{i}"], h, bk)
            auxes.append(aux)
        return self._ln_final(params, h), mean_aux(auxes)

    def apply(self, params, tokens, key=None):
        return self.apply_with_stats(params, tokens, key)[0]

    def loss_with_stats(self, params, tokens, labels, key=None):
        """(total loss, flat fp32 stats dict).  total = CE +
        aux_coef * load-balance + z_coef * z-loss; a coefficient of
        exactly 0.0 adds NOTHING to the trace (the bitwise dense-
        parity anchor needs total == CE to the bit, and x + 0.0 is
        not an identity for -0.0).  Stats are shard-local values —
        under the train step's P() out-spec the logger sees one
        shard's numbers (document-grade, not a collective)."""
        c = self.c
        h, aux = self.apply_with_stats(params, tokens, key)
        logits = self.logits_local(params, h)
        ce = jnp.mean(vocab_parallel_cross_entropy(
            logits, labels.T, axis_name=c.axis_name, fused=c.fused_xent))
        total = ce
        if c.aux_coef:
            total = total + jnp.asarray(c.aux_coef, ce.dtype) \
                * aux.aux_loss.astype(ce.dtype)
        if c.z_coef:
            total = total + jnp.asarray(c.z_coef, ce.dtype) \
                * aux.z_loss.astype(ce.dtype)
        stats = {"ce_loss": ce.astype(jnp.float32),
                 "moe_aux_loss": aux.aux_loss,
                 "moe_z_loss": aux.z_loss,
                 "moe_drop_fraction": aux.drop_fraction,
                 "moe_gate_entropy": aux.gate_entropy}
        return total, stats

    def loss(self, params, tokens, labels, key=None):
        return self.loss_with_stats(params, tokens, labels, key)[0]


# preset ≡ the GPT-350M bench point with 8 experts (params grow ~4x,
# per-token FLOPs stay ~dense + router)
MOE_GPT_350M_8E = dict(hidden=1024, num_layers=24, num_heads=16,
                       n_experts=8, top_k=2)


def moe_smoke_config(ep: int = 1, **overrides) -> MoEGPTConfig:
    """The CPU smoke shape every gate/test builds (mirrors the dense
    smoke configs of bench/lint/comms): tiny GPT dims, 4 experts."""
    cfg = dict(vocab_size=512, seq_len=64, hidden=64, num_layers=2,
               num_heads=4, dropout=0.0, n_experts=4, top_k=2,
               capacity_factor=2.0, expert_parallel=ep)
    cfg.update(overrides)
    return MoEGPTConfig(**cfg)


def build_moe_train_step(on_tpu: bool = False, *, batch=None,
                         n_buckets: int = 2, metrics=None, trace=None,
                         devices=None):
    """The flagship MoE-GPT training step — ONE builder shared by
    bench.py, `lint_step.py moe`, `comms_probe.py moe`, and the tests
    (the no-drift rule of the other flagship builders).

    Meshes over ALL visible devices: ep = 2 whenever the device count
    is even (the dp=2 x ep=2 acceptance grid on a 4-device mesh; dp=4
    x ep=2 on the 8-way test mesh), else ep = 1.  The batch is rounded
    up to a dp*ep multiple.  ZeRO-2 `DistributedFusedAdam` shards the
    master state over the combined data axes.

    Returns (model, step, args, info): `args` is
    (opt_state, None, (tokens_sds, labels_sds)) — real sharded state,
    ShapeDtypeStruct batch (lint traces / comms AOT-compiles it;
    callers that EXECUTE substitute real int32 arrays of the same
    shape, see info["batch"]/info["seq"]).
    """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from apex_tpu.optimizers.distributed_fused_adam import (
        DistributedFusedAdam,
    )
    from apex_tpu.parallel import ddp
    from apex_tpu.parallel import mesh as M

    if devices is None:
        devices = jax.devices()
    n_dev = len(devices)
    ep = 2 if n_dev % 2 == 0 else 1
    M.destroy_model_parallel()
    mesh = M.initialize_model_parallel(expert_model_parallel_size=ep,
                                       devices=devices)
    dp = M.get_data_parallel_world_size()
    data_axes = M.get_data_parallel_axis_names()
    axis_name = data_axes if len(data_axes) > 1 else data_axes[0]

    if on_tpu:
        batch = batch or 8
        seq = 1024
        cfg = MoEGPTConfig(vocab_size=50304, seq_len=seq, dropout=0.0,
                           dtype=jnp.bfloat16, logits_dtype=jnp.bfloat16,
                           use_flash_attention=True, expert_parallel=ep,
                           capacity_factor=1.25,
                           **{k: v for k, v in MOE_GPT_350M_8E.items()
                              if k != "num_layers"}, num_layers=12)
    else:
        batch = batch or 4
        seq = 64
        cfg = moe_smoke_config(ep=ep)
    world = dp * ep
    batch = -(-batch // world) * world

    model = MoEGPT(cfg)
    params = model.init(jax.random.PRNGKey(0))
    opt = DistributedFusedAdam(
        num_shards=world, lr=1e-4, n_buckets=n_buckets,
        axis_name=axis_name, ep_shards=ep,
        use_pallas=on_tpu or None,
        master_dtype=jnp.bfloat16 if on_tpu else jnp.float32)
    sspec = opt.state_partition_specs()
    state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                              out_specs=sspec, check_vma=False))(params)

    def loss_fn(p, b):
        return model.loss_with_stats(p, b[0], b[1])

    step = ddp.make_train_step(
        loss_fn, opt, mesh, axis_name=axis_name,
        batch_spec=(P(axis_name), P(axis_name)), has_aux=True,
        metrics=metrics, trace=trace)
    del params
    tokens = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    labels = jax.ShapeDtypeStruct((batch, seq), jnp.int32)
    info = {"batch": batch, "seq": seq, "dp": dp, "ep": ep,
            "vocab_size": cfg.vocab_size, "config": cfg, "mesh": mesh}
    return model, step, (state, None, (tokens, labels)), info
