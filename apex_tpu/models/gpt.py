"""GPT — tensor/sequence-parallel transformer LM, the flagship model.

≡ the reference's standalone Megatron GPT
(apex/transformer/testing/standalone_transformer_lm.py, 1574 LoC;
standalone_gpt.py:33-50) re-designed TPU-first:

* layout (S, B, H) so sequence-parallel collectives act on dim 0 (same
  choice as Megatron, and contiguous for TPU lane tiling);
* attention QKV via ColumnParallelLinear (heads sharded over tp),
  causal Pallas softmax (or flash attention, ops/flash_attention.py),
  output via RowParallelLinear;
* MLP = ColumnParallel → gelu → RowParallel (4x hidden);
* vocab-parallel embedding + tied-weight LM head + vocab-parallel
  cross entropy;
* runs shard-local inside `shard_map` over the (pp, dp, tp) mesh —
  partition_specs() gives every param its PartitionSpec.

Dropout uses functional keys (fold_in per layer and per tp rank ≡ the
CudaRNGStatesTracker contract, tensor_parallel/random.py:204-235).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.ops.layer_norm import fused_layer_norm
from apex_tpu.ops.softmax import scaled_upper_triang_masked_softmax
from apex_tpu.parallel.collectives import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
)
from apex_tpu.parallel.mesh import TP_AXIS
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.random import (
    model_parallel_fold_in,
)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    seq_len: int = 1024
    hidden: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    ffn_mult: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.float32
    sequence_parallel: bool = False
    use_flash_attention: bool = False
    remat: bool = False            # activation checkpointing per block
    axis_name: str = TP_AXIS

    @property
    def head_dim(self):
        return self.hidden // self.num_heads


# preset sizes ≡ gpt_scaling_test.py sweep points
GPT2_350M = dict(hidden=1024, num_layers=24, num_heads=16)
GPT2_1p3B = dict(hidden=2048, num_layers=24, num_heads=32)


class GPT:
    def __init__(self, config: GPTConfig):
        self.c = config
        c = config
        self.embed = VocabParallelEmbedding(
            c.vocab_size, c.hidden, axis_name=c.axis_name,
            sequence_parallel=c.sequence_parallel)
        self.blocks = []
        for _ in range(c.num_layers):
            qkv = ColumnParallelLinear(
                c.hidden, 3 * c.hidden, gather_output=False,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name, init_std=0.02)
            proj = RowParallelLinear(
                c.hidden, c.hidden, input_is_parallel=True,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name,
                init_std=0.02 / jnp.sqrt(2.0 * c.num_layers))
            fc1 = ColumnParallelLinear(
                c.hidden, c.ffn_mult * c.hidden, gather_output=False,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name, init_std=0.02)
            fc2 = RowParallelLinear(
                c.ffn_mult * c.hidden, c.hidden, input_is_parallel=True,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name,
                init_std=0.02 / jnp.sqrt(2.0 * c.num_layers))
            self.blocks.append((qkv, proj, fc1, fc2))

    # ------------------------------ params --------------------------------
    def init(self, key):
        c = self.c
        keys = jax.random.split(key, 2 + 4 * c.num_layers)
        params = {
            "embed": self.embed.init(keys[0], c.dtype),
            "pos_embed": jax.random.normal(
                keys[1], (c.seq_len, c.hidden), c.dtype) * 0.02,
            "final_ln": {"weight": jnp.ones((c.hidden,), c.dtype),
                         "bias": jnp.zeros((c.hidden,), c.dtype)},
        }
        for i, (qkv, proj, fc1, fc2) in enumerate(self.blocks):
            k = keys[2 + 4 * i: 6 + 4 * i]
            params[f"block{i}"] = {
                "ln1": {"weight": jnp.ones((c.hidden,), c.dtype),
                        "bias": jnp.zeros((c.hidden,), c.dtype)},
                "qkv": qkv.init(k[0], c.dtype),
                "proj": proj.init(k[1], c.dtype),
                "ln2": {"weight": jnp.ones((c.hidden,), c.dtype),
                        "bias": jnp.zeros((c.hidden,), c.dtype)},
                "fc1": fc1.init(k[2], c.dtype),
                "fc2": fc2.init(k[3], c.dtype),
            }
        return params

    def partition_specs(self):
        """PartitionSpec pytree matching init() — the TP sharding map
        (≡ the tensor_model_parallel param attributes, layers.py:70-107)."""
        c = self.c
        specs = {
            "embed": {"weight": P(c.axis_name, None)},
            "pos_embed": P(),
            "final_ln": {"weight": P(), "bias": P()},
        }
        col = {"weight": P(None, c.axis_name), "bias": P(c.axis_name)}
        row = {"weight": P(c.axis_name, None), "bias": P()}
        for i in range(c.num_layers):
            specs[f"block{i}"] = {
                "ln1": {"weight": P(), "bias": P()},
                "qkv": dict(col), "proj": dict(row),
                "ln2": {"weight": P(), "bias": P()},
                "fc1": dict(col), "fc2": dict(row),
            }
        return specs

    # ------------------------------ forward -------------------------------
    def _ln(self, p, x):
        w, b = p["weight"], p["bias"]
        if self.c.sequence_parallel:
            w = copy_to_tensor_model_parallel_region(w, self.c.axis_name)
            b = copy_to_tensor_model_parallel_region(b, self.c.axis_name)
        return fused_layer_norm(x, w, b)

    def _dropout(self, key, x):
        if self.c.dropout == 0.0 or key is None:
            return x
        keep = 1.0 - self.c.dropout
        mask = jax.random.bernoulli(key, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0)

    def _attention(self, block_params, qkv_mod, proj_mod, x, key):
        """x: (S[, /tp], B, H) local.  Heads sharded over tp."""
        c = self.c
        qkv = qkv_mod.apply(block_params["qkv"], x)  # (S, B, 3H/tp)
        s, b, _ = qkv.shape
        nh_local = qkv.shape[-1] // (3 * c.head_dim)
        qkv = qkv.reshape(s, b, 3, nh_local, c.head_dim)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # (b, nh, s, hd)
        q = q.transpose(1, 2, 0, 3)
        k = k.transpose(1, 2, 0, 3)
        v = v.transpose(1, 2, 0, 3)
        if c.use_flash_attention:
            from apex_tpu.ops.flash_attention import flash_attention
            ctx = flash_attention(q, k, v, causal=True,
                                  softmax_scale=1.0 / math.sqrt(c.head_dim))
        else:
            scores = jnp.einsum("bnsh,bnth->bnst", q, k,
                                preferred_element_type=jnp.float32
                                ).astype(x.dtype)
            probs = scaled_upper_triang_masked_softmax(
                scores.reshape(-1, s, s),
                1.0 / math.sqrt(c.head_dim)).reshape(scores.shape)
            probs = self._dropout(key, probs)
            ctx = jnp.einsum("bnst,bnth->bnsh", probs, v,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)  # (S,B,H/tp)
        return proj_mod.apply(block_params["proj"], ctx)

    def _block(self, i, params, x, key):
        qkv_mod, proj_mod, fc1, fc2 = self.blocks[i]
        bp = params
        k1 = k2 = k3 = None
        if key is not None:
            k1, k2, k3 = jax.random.split(key, 3)
        h = self._ln(bp["ln1"], x)
        attn = self._attention(bp, qkv_mod, proj_mod, h, k1)
        x = x + self._dropout(k2, attn)
        h = self._ln(bp["ln2"], x)
        m = fc1.apply(bp["fc1"], h)
        m = jax.nn.gelu(m, approximate=True)
        m = fc2.apply(bp["fc2"], m)
        x = x + self._dropout(k3, m)
        return x

    def apply(self, params, tokens, key=None):
        """tokens: (B, S) global int ids (replicated over tp).
        Returns hidden states (S[, /tp], B, H) local and a closure-free
        path to logits/loss below.  Shard-local: call inside shard_map.
        """
        c = self.c
        ids = tokens.T  # (S, B)
        h = self.embed.apply(params["embed"], ids)  # (S,B,H) or (S/tp,B,H)
        pos = params["pos_embed"][: tokens.shape[1]][:, None, :]
        if c.sequence_parallel:
            pos = scatter_to_sequence_parallel_region(pos, c.axis_name)
        h = h + pos.astype(h.dtype)
        if key is not None:
            key = model_parallel_fold_in(key, c.axis_name)
        for i in range(c.num_layers):
            bk = None if key is None else jax.random.fold_in(key, i)
            blk = lambda p, x: self._block(i, p, x, bk)
            if c.remat:
                blk = jax.checkpoint(blk)
            h = blk(params[f"block{i}"], h)
        h = self._ln_final(params, h)
        return h

    def _ln_final(self, params, h):
        p = params["final_ln"]
        w, b = p["weight"], p["bias"]
        if self.c.sequence_parallel:
            w = copy_to_tensor_model_parallel_region(w, self.c.axis_name)
            b = copy_to_tensor_model_parallel_region(b, self.c.axis_name)
        return fused_layer_norm(h, w, b)

    def logits_local(self, params, h):
        """LM head with tied embedding weight → vocab-sharded logits
        (S, B, V/tp).  With SP the hidden is re-gathered first."""
        c = self.c
        if c.sequence_parallel:
            h = gather_from_sequence_parallel_region(h, c.axis_name)
        w = params["embed"]["weight"]  # local (V/tp, H)
        x = copy_to_tensor_model_parallel_region(h, c.axis_name)
        return jnp.einsum("sbh,vh->sbv", x, w,
                          preferred_element_type=jnp.float32)

    def loss(self, params, tokens, labels, key=None):
        """Mean LM loss.  tokens/labels: (B, S) global."""
        h = self.apply(params, tokens, key)
        logits = self.logits_local(params, h)  # (S,B,V/tp)
        loss = vocab_parallel_cross_entropy(
            logits, labels.T, axis_name=self.c.axis_name)
        return jnp.mean(loss)


def gpt_350m(**overrides) -> GPT:
    cfg = {**GPT2_350M, **overrides}
    return GPT(GPTConfig(**cfg))


def gpt_1p3b(**overrides) -> GPT:
    cfg = {**GPT2_1p3B, **overrides}
    return GPT(GPTConfig(**cfg))
