"""GPT — tensor/sequence-parallel transformer LM, the flagship model.

≡ the reference's standalone Megatron GPT
(apex/transformer/testing/standalone_transformer_lm.py, 1574 LoC;
standalone_gpt.py:33-50) re-designed TPU-first:

* layout (S, B, H) so sequence-parallel collectives act on dim 0 (same
  choice as Megatron, and contiguous for TPU lane tiling);
* attention QKV via ColumnParallelLinear (heads sharded over tp),
  causal Pallas softmax (or flash attention, ops/flash_attention.py),
  output via RowParallelLinear;
* MLP = ColumnParallel → gelu → RowParallel (4x hidden);
* vocab-parallel embedding + tied-weight LM head + vocab-parallel
  cross entropy;
* runs shard-local inside `shard_map` over the (pp, dp, tp) mesh —
  partition_specs() gives every param its PartitionSpec.

Dropout uses functional keys (fold_in per layer and per tp rank ≡ the
CudaRNGStatesTracker contract, tensor_parallel/random.py:204-235).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from apex_tpu.ops._common import tap as _tap
from apex_tpu.ops.layer_norm import fused_layer_norm
from apex_tpu.ops.softmax import scaled_upper_triang_masked_softmax
from apex_tpu.parallel.collectives import (
    copy_to_tensor_model_parallel_region,
    gather_from_sequence_parallel_region,
    reduce_scatter_to_sequence_parallel_region,
    scatter_to_sequence_parallel_region,
)
from apex_tpu.parallel.mesh import TP_AXIS
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)
from apex_tpu.transformer.tensor_parallel.random import (
    model_parallel_fold_in,
)

# The checkpoint_name tags _block emits — the single source of truth
# shared by the block (via _cn below) and remat_policy validation.
REMAT_TAGS = frozenset({"qkv", "attn_ctx", "attn_out", "ffn1", "ffn_out"})


def _cn(x, name):
    assert name in REMAT_TAGS, name  # keep REMAT_TAGS in sync with _block
    return checkpoint_name(x, name)


@dataclasses.dataclass(frozen=True)
class GPTConfig:
    vocab_size: int = 50304
    seq_len: int = 1024
    hidden: int = 1024
    num_layers: int = 24
    num_heads: int = 16
    ffn_mult: int = 4
    dropout: float = 0.0
    dtype: Any = jnp.float32
    # LM-head logits dtype: None keeps fp32 logits.  bf16 halves the
    # (S, B, V) HBM traffic in fwd and bwd; the cross entropy upcasts to
    # fp32 internally either way (≡ the reference xentropy_cuda, which
    # consumes fp16 logits with fp32 internal math).  Opt-in so existing
    # bf16 configs keep their fp32-logits numerics.
    logits_dtype: Any = None
    sequence_parallel: bool = False
    use_flash_attention: bool = False
    # Explicit flash kernel-shape overrides for A/B sweeps.  None (the
    # default) lets the flash kernel consult the apex_tpu.tune cache at
    # trace time for a config tuned at this exact (shape, dtype,
    # device-kind) key, falling back to the built-in heuristics on a
    # miss — so an untuned machine runs exactly the pre-tuner kernels.
    attn_block_q: Any = None
    attn_block_k: Any = None
    attn_heads_per_step: Any = None
    # Chunked compute/collective overlap depth for the TP layers
    # (parallel/overlap.py) and the MoE micro-chunk exchange.  None =
    # tuner-owned (`overlap_chunks` op, heuristic 1 — the monolithic
    # pre-overlap program, byte-identical on untuned machines); an int
    # forces the pipeline depth for A/B sweeps (non-dividing requests
    # fall back to the largest dividing count, warn once).
    overlap_chunks: Any = None
    remat: bool = False            # activation checkpointing per block
    # What the per-block checkpoint may keep (≡ the reference's partial /
    # selective activation checkpointing, fwd_bwd_pipelining_without_
    # interleaving.py:351-362 + tensor_parallel/random.py:237-306):
    #   None    — save nothing, recompute the whole block (full remat)
    #   "dots"  — save matmul (MXU) outputs, recompute elementwise only
    #   "names:a,b" — save only the listed checkpoint_name'd tensors
    #     (qkv, attn_ctx, attn_out, ffn1, ffn_out — see _block); the
    #     memory/recompute dial between full remat and "dots"
    remat_policy: Any = None
    # Vocab-parallel cross-entropy backward strategy: None = auto (the
    # fused custom_vjp when logits are sub-fp32, saving compute-dtype
    # residuals instead of the fp32 (S, B, V) upcast — cross_entropy.py
    # module docstring); True/False force it for A/B sweeps.
    fused_xent: Any = None
    axis_name: str = TP_AXIS

    @property
    def head_dim(self):
        return self.hidden // self.num_heads


# preset sizes ≡ gpt_scaling_test.py sweep points
GPT2_350M = dict(hidden=1024, num_layers=24, num_heads=16)
GPT2_1p3B = dict(hidden=2048, num_layers=24, num_heads=32)


class GPT:
    def __init__(self, config: GPTConfig):
        self.c = config
        c = config
        self.embed = VocabParallelEmbedding(
            c.vocab_size, c.hidden, axis_name=c.axis_name,
            sequence_parallel=c.sequence_parallel)
        self.blocks = []
        for _ in range(c.num_layers):
            qkv = ColumnParallelLinear(
                c.hidden, 3 * c.hidden, gather_output=False,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name, init_std=0.02,
                overlap_chunks=c.overlap_chunks)
            proj = RowParallelLinear(
                c.hidden, c.hidden, input_is_parallel=True,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name,
                init_std=0.02 / jnp.sqrt(2.0 * c.num_layers),
                overlap_chunks=c.overlap_chunks)
            fc1 = ColumnParallelLinear(
                c.hidden, c.ffn_mult * c.hidden, gather_output=False,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name, init_std=0.02,
                overlap_chunks=c.overlap_chunks)
            fc2 = RowParallelLinear(
                c.ffn_mult * c.hidden, c.hidden, input_is_parallel=True,
                sequence_parallel=c.sequence_parallel,
                axis_name=c.axis_name,
                init_std=0.02 / jnp.sqrt(2.0 * c.num_layers),
                overlap_chunks=c.overlap_chunks)
            self.blocks.append((qkv, proj, fc1, fc2))

    # ------------------------------ params --------------------------------
    def init(self, key):
        c = self.c
        keys = jax.random.split(key, 2 + 4 * c.num_layers)
        params = {
            "embed": self.embed.init(keys[0], c.dtype),
            "pos_embed": jax.random.normal(
                keys[1], (c.seq_len, c.hidden), c.dtype) * 0.02,
            "final_ln": {"weight": jnp.ones((c.hidden,), c.dtype),
                         "bias": jnp.zeros((c.hidden,), c.dtype)},
        }
        for i, (qkv, proj, fc1, fc2) in enumerate(self.blocks):
            k = keys[2 + 4 * i: 6 + 4 * i]
            params[f"block{i}"] = {
                "ln1": {"weight": jnp.ones((c.hidden,), c.dtype),
                        "bias": jnp.zeros((c.hidden,), c.dtype)},
                "qkv": qkv.init(k[0], c.dtype),
                "proj": proj.init(k[1], c.dtype),
                "ln2": {"weight": jnp.ones((c.hidden,), c.dtype),
                        "bias": jnp.zeros((c.hidden,), c.dtype)},
                "fc1": fc1.init(k[2], c.dtype),
                "fc2": fc2.init(k[3], c.dtype),
            }
        return params

    def partition_specs(self):
        """PartitionSpec pytree matching init() — the TP sharding map
        (≡ the tensor_model_parallel param attributes, layers.py:70-107)."""
        c = self.c
        specs = {
            "embed": {"weight": P(c.axis_name, None)},
            "pos_embed": P(),
            "final_ln": {"weight": P(), "bias": P()},
        }
        col = {"weight": P(None, c.axis_name), "bias": P(c.axis_name)}
        row = {"weight": P(c.axis_name, None), "bias": P()}
        for i in range(c.num_layers):
            specs[f"block{i}"] = {
                "ln1": {"weight": P(), "bias": P()},
                "qkv": dict(col), "proj": dict(row),
                "ln2": {"weight": P(), "bias": P()},
                "fc1": dict(col), "fc2": dict(row),
            }
        return specs

    # ------------------------------ forward -------------------------------
    def _ln(self, p, x):
        w, b = p["weight"], p["bias"]
        if self.c.sequence_parallel:
            w = copy_to_tensor_model_parallel_region(w, self.c.axis_name)
            b = copy_to_tensor_model_parallel_region(b, self.c.axis_name)
        return fused_layer_norm(x, w, b)

    def _dropout(self, key, x):
        from apex_tpu.ops._common import dropout
        return dropout(key, self.c.dropout, x)

    def _attention(self, block_params, qkv_mod, proj_mod, x, key):
        """x: (S[, /tp], B, H) local.  Heads sharded over tp."""
        c = self.c
        qkv = qkv_mod.apply(block_params["qkv"], x)  # (S, B, 3H/tp)
        qkv = _cn(qkv, "qkv")
        s, b, _ = qkv.shape
        nh_local = qkv.shape[-1] // (3 * c.head_dim)
        # one transpose of the PACKED tensor instead of three strided
        # slice+transpose copies (ops/fused_dense.qkv_split_heads)
        from apex_tpu.ops.fused_dense import qkv_split_heads
        q, k, v = qkv_split_heads(qkv, nh_local, c.head_dim)
        if c.use_flash_attention:
            from apex_tpu.ops.flash_attention import flash_attention
            rate = c.dropout if key is not None else 0.0
            ctx = flash_attention(q, k, v, causal=True,
                                  softmax_scale=1.0 / math.sqrt(c.head_dim),
                                  dropout_rate=rate,
                                  dropout_key=key if rate > 0 else None,
                                  block_q=c.attn_block_q,
                                  block_k=c.attn_block_k,
                                  heads_per_step=c.attn_heads_per_step)
        else:
            scores = jnp.einsum("bnsh,bnth->bnst", q, k,
                                preferred_element_type=jnp.float32
                                ).astype(x.dtype)
            probs = scaled_upper_triang_masked_softmax(
                scores.reshape(-1, s, s),
                1.0 / math.sqrt(c.head_dim)).reshape(scores.shape)
            probs = self._dropout(key, probs)
            ctx = jnp.einsum("bnst,bnth->bnsh", probs, v,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)  # (S,B,H/tp)
        ctx = _cn(ctx, "attn_ctx")
        return proj_mod.apply(block_params["proj"], ctx)

    def _block(self, i, params, x, key):
        # `_tap` points (flight-recorder stat taps, monitor.trace): the
        # per-block ln/attn/mlp outputs, identity no-ops unless a
        # TapContext is active (ops._common.tap) — untapped programs
        # compile byte-identical
        qkv_mod, proj_mod, fc1, fc2 = self.blocks[i]
        bp = params
        k1 = k2 = k3 = None
        if key is not None:
            k1, k2, k3 = jax.random.split(key, 3)
        h = _tap(self._ln(bp["ln1"], x), f"block{i}/ln1")
        attn = self._attention(bp, qkv_mod, proj_mod, h, k1)
        attn = _cn(attn, "attn_out")
        attn = _tap(attn, f"block{i}/attn")
        x = x + self._dropout(k2, attn)
        h = _tap(self._ln(bp["ln2"], x), f"block{i}/ln2")
        m = fc1.apply(bp["fc1"], h)
        m = _cn(m, "ffn1")
        m = jax.nn.gelu(m, approximate=True)
        m = fc2.apply(bp["fc2"], m)
        m = _cn(m, "ffn_out")
        m = _tap(m, f"block{i}/mlp")
        x = x + self._dropout(k3, m)
        return x

    def apply(self, params, tokens, key=None):
        """tokens: (B, S) global int ids (replicated over tp).
        Returns hidden states (S[, /tp], B, H) local and a closure-free
        path to logits/loss below.  Shard-local: call inside shard_map.
        """
        c = self.c
        ids = tokens.T  # (S, B)
        h = self.embed.apply(params["embed"], ids)  # (S,B,H) or (S/tp,B,H)
        pos = params["pos_embed"][: tokens.shape[1]][:, None, :]
        if c.sequence_parallel:
            pos = scatter_to_sequence_parallel_region(pos, c.axis_name)
        h = h + pos.astype(h.dtype)
        if key is not None:
            key = model_parallel_fold_in(key, c.axis_name)
        for i in range(c.num_layers):
            bk = None if key is None else jax.random.fold_in(key, i)
            blk = lambda p, x: self._block(i, p, x, bk)
            if c.remat:
                if c.remat_policy == "dots":
                    pol = jax.checkpoint_policies.checkpoint_dots
                    blk = jax.checkpoint(blk, policy=pol)
                elif (isinstance(c.remat_policy, str)
                      and c.remat_policy.startswith("names:")):
                    names = tuple(
                        n for n in c.remat_policy[6:].split(",") if n)
                    bad = [n for n in names if n not in REMAT_TAGS]
                    if bad:
                        raise ValueError(
                            f"remat_policy names {bad} do not match any "
                            f"checkpoint_name tag in _block; known tags: "
                            f"{sorted(REMAT_TAGS)}")
                    pol = jax.checkpoint_policies.save_only_these_names(
                        *names)
                    blk = jax.checkpoint(blk, policy=pol)
                elif c.remat_policy is None:
                    blk = jax.checkpoint(blk)
                else:
                    raise ValueError(
                        f"unknown remat_policy {c.remat_policy!r}; "
                        "expected None, 'dots', or 'names:...'")
            h = blk(params[f"block{i}"], h)
        h = self._ln_final(params, h)
        return h

    def _ln_final(self, params, h):
        p = params["final_ln"]
        w, b = p["weight"], p["bias"]
        if self.c.sequence_parallel:
            w = copy_to_tensor_model_parallel_region(w, self.c.axis_name)
            b = copy_to_tensor_model_parallel_region(b, self.c.axis_name)
        return fused_layer_norm(h, w, b)

    def logits_local(self, params, h):
        """LM head with tied embedding weight → vocab-sharded logits
        (S, B, V/tp).  With SP the hidden is re-gathered first."""
        c = self.c
        if c.sequence_parallel:
            h = gather_from_sequence_parallel_region(h, c.axis_name)
        w = params["embed"]["weight"]  # local (V/tp, H)
        x = copy_to_tensor_model_parallel_region(h, c.axis_name)
        out_dtype = c.logits_dtype or jnp.float32
        return jnp.einsum("sbh,vh->sbv", x, w,
                          preferred_element_type=jnp.float32
                          ).astype(out_dtype)

    def loss(self, params, tokens, labels, key=None):
        """Mean LM loss.  tokens/labels: (B, S) global."""
        h = self.apply(params, tokens, key)
        logits = self.logits_local(params, h)  # (S,B,V/tp)
        loss = vocab_parallel_cross_entropy(
            logits, labels.T, axis_name=self.c.axis_name,
            fused=self.c.fused_xent)
        return jnp.mean(loss)


class GPTPipelined(GPT):
    """GPT over a (pp, dp, tp) mesh: blocks stacked per layer and
    sharded over pp; embedding / LM head replicated across stages (the
    reference places them on first/last stage with an embedding group
    allreduce, parallel_state.py:319-407 — here the tie is exact because
    every stage holds the same embed weight and grads mix via the
    pipeline's AD).  Microbatched via the SPMD clocked pipeline
    (pipeline_parallel/schedules.spmd_pipeline).
    """

    def __init__(self, config: GPTConfig, num_microbatches: int,
                 pipeline_parallel_size: int,
                 num_model_chunks: int = 1, remat_stage: bool = False,
                 checkpoint_window=None):
        super().__init__(config)
        c = config
        self.num_microbatches = num_microbatches
        self.pp = pipeline_parallel_size
        self.chunks = num_model_chunks
        self.remat_stage = remat_stage
        # 1F1B memory dial: jax.checkpoint window over pipeline clocks
        # (schedules.spmd_pipeline docstring); pp is the 1F1B-bound pick
        self.checkpoint_window = checkpoint_window
        assert c.num_layers % (self.pp * self.chunks) == 0, (
            "num_layers must divide pp * num_model_chunks")
        self.layers_per_stage = c.num_layers // (self.pp * self.chunks)

    def init(self, key):
        flat_params = super().init(key)
        c = self.c
        # stack per-layer block params: leaves (L, ...)
        blocks = [flat_params.pop(f"block{i}") for i in range(c.num_layers)]
        stacked = jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *blocks)
        # reorder (L, ...) → (pp, chunks, layers_per_stage, ...):
        # global layer g = ((c_idx*pp + s) * lps + j)
        def reorder(l):
            return l.reshape(self.chunks, self.pp, self.layers_per_stage,
                             *l.shape[1:]).swapaxes(0, 1)
        flat_params["blocks"] = jax.tree_util.tree_map(reorder, stacked)
        return flat_params

    def partition_specs(self):
        base = super().partition_specs()
        c = self.c
        block_spec = base.pop("block0")
        for i in range(1, c.num_layers):
            base.pop(f"block{i}")
        # blocks leaves gained (pp, chunks, lps) leading dims; pp sharded
        def add_dims(spec):
            return P("pp", None, None, *spec)
        base["blocks"] = jax.tree_util.tree_map(
            add_dims, block_spec,
            is_leaf=lambda s: isinstance(s, P))
        return base

    def _stage_fn(self, stage_blocks, h, chunk):
        """Apply this stage's layers_per_stage blocks (scanned)."""
        def body(x, layer_params):
            return self._block_shared(layer_params, x, None), None
        h, _ = jax.lax.scan(body, h, stage_blocks)
        return h

    def _block_shared(self, bp, x, key):
        """_block with the (shared-config) layer modules of block 0."""
        qkv_mod, proj_mod, fc1, fc2 = self.blocks[0]
        h = self._ln(bp["ln1"], x)
        attn = self._attention(bp, qkv_mod, proj_mod, h, key)
        x = x + attn
        h = self._ln(bp["ln2"], x)
        m = fc1.apply(bp["fc1"], h)
        m = jax.nn.gelu(m, approximate=True)
        m = fc2.apply(bp["fc2"], m)
        return x + m

    def loss(self, params, tokens, labels, key=None):
        """tokens/labels: (B, S); B = num_microbatches × microbatch size.
        Shard-local (call inside shard_map over the full mesh)."""
        from apex_tpu.transformer.pipeline_parallel.schedules import (
            spmd_pipeline)
        c = self.c
        m = self.num_microbatches
        B, S = tokens.shape
        assert B % m == 0
        mb = B // m
        ids = tokens.reshape(m, mb, S).transpose(0, 2, 1)  # (m, S, mb)

        def embed_one(ids_mb):
            h = self.embed.apply(params["embed"], ids_mb)
            pos = params["pos_embed"][:S][:, None, :]
            if c.sequence_parallel:
                pos = scatter_to_sequence_parallel_region(pos, c.axis_name)
            return h + pos.astype(h.dtype)

        h_mbs = jax.vmap(embed_one)(ids)  # (m, S[, /tp], mb, H)

        # local stage params: drop the sharded pp dim (local size 1)
        stage_blocks = jax.tree_util.tree_map(lambda l: l[0],
                                              params["blocks"])

        def stage_fn(chunk_blocks, x, chunk):
            return self._stage_fn(chunk_blocks, x, chunk)

        def head_one(h_mb, labels_mb):
            h_f = self._ln_final(params, h_mb)
            logits = self.logits_local(params, h_f)  # (S, mb, V/tp)
            return jnp.mean(vocab_parallel_cross_entropy(
                logits, labels_mb, axis_name=c.axis_name,
                fused=c.fused_xent))

        lbl = labels.reshape(m, mb, S).transpose(0, 2, 1)  # (m, S, mb)
        # head + loss run on the LAST STAGE inside the clocked scan and
        # only a scalar crosses the pp axis (the old path psum'd the
        # whole (m, S, mb, H) stacked output every step)
        total = spmd_pipeline(stage_fn, stage_blocks, h_mbs,
                              num_model_chunks=self.chunks,
                              remat_stage=self.remat_stage,
                              checkpoint_window=self.checkpoint_window,
                              loss_fn=head_one, loss_args=lbl)
        return total / m


def gpt_350m(**overrides) -> GPT:
    cfg = {**GPT2_350M, **overrides}
    return GPT(GPTConfig(**cfg))


def gpt_1p3b(**overrides) -> GPT:
    cfg = {**GPT2_1p3B, **overrides}
    return GPT(GPTConfig(**cfg))
