"""apex_tpu.models — flagship end-to-end models (≡ the reference's
examples/ + apex/transformer/testing standalone models)."""


def __getattr__(name):
    import importlib
    if name in ("resnet", "gpt", "bert", "moe_gpt"):
        return importlib.import_module(f"apex_tpu.models.{name}")
    if name in ("MoEGPT", "MoEGPTConfig", "build_moe_train_step"):
        return getattr(importlib.import_module("apex_tpu.models.moe_gpt"),
                       name)
    if name in ("ResNet", "resnet50", "resnet18"):
        return getattr(importlib.import_module("apex_tpu.models.resnet"),
                       name)
    raise AttributeError(name)
