"""BERT — bidirectional tensor-parallel encoder (MLM + NSP).

≡ the reference's standalone BERT
(apex/transformer/testing/standalone_bert.py over
standalone_transformer_lm.py): token+position+tokentype embeddings,
padding-masked attention (FusedScaleMaskSoftmax padding variant), TP
transformer blocks, pooler, tied-weight MLM head and binary NSP head.
Pairs with FusedLAMB for the BERT-Large pretraining baseline config
(BASELINE.md).

Layout (S, B, H) like the GPT flagship; shard-local inside shard_map.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from apex_tpu.ops._common import tap as _tap
from apex_tpu.ops.layer_norm import fused_layer_norm
from apex_tpu.ops.softmax import scaled_masked_softmax
from apex_tpu.parallel.mesh import TP_AXIS
from apex_tpu.transformer.tensor_parallel.cross_entropy import (
    vocab_parallel_cross_entropy,
)
from apex_tpu.transformer.tensor_parallel.layers import (
    ColumnParallelLinear,
    RowParallelLinear,
    VocabParallelEmbedding,
)


@dataclasses.dataclass(frozen=True)
class BertConfig:
    vocab_size: int = 30528
    seq_len: int = 512
    hidden: int = 1024          # BERT-Large defaults
    num_layers: int = 24
    num_heads: int = 16
    ffn_mult: int = 4
    num_tokentypes: int = 2
    dtype: Any = jnp.float32
    # MLM logits dtype: None keeps fp32 (S, B, V) logits; bf16 halves
    # their fwd+bwd HBM traffic (the xent upcasts internally either
    # way) — same contract as GPTConfig.logits_dtype
    logits_dtype: Any = None
    # padding-masked FLASH attention (segment-id masked Pallas kernel)
    # instead of the dense FusedScaleMaskSoftmax path: no S^2 score
    # matrix, so BERT trains at seq 4k+ on one chip (VERDICT r1 #3)
    use_flash_attention: bool = False
    # explicit flash kernel-shape overrides; None → autotuner lookup
    # then heuristics (same contract as GPTConfig.attn_*)
    attn_block_q: Any = None
    attn_block_k: Any = None
    attn_heads_per_step: Any = None
    axis_name: str = TP_AXIS

    @property
    def head_dim(self):
        return self.hidden // self.num_heads


class Bert:
    def __init__(self, config: BertConfig):
        self.c = c = config
        self.embed = VocabParallelEmbedding(c.vocab_size, c.hidden,
                                            axis_name=c.axis_name)
        self.blocks = []
        for _ in range(c.num_layers):
            self.blocks.append((
                ColumnParallelLinear(c.hidden, 3 * c.hidden,
                                     gather_output=False,
                                     axis_name=c.axis_name, init_std=0.02),
                RowParallelLinear(c.hidden, c.hidden, input_is_parallel=True,
                                  axis_name=c.axis_name,
                                  init_std=0.02 / math.sqrt(2 * c.num_layers)),
                ColumnParallelLinear(c.hidden, c.ffn_mult * c.hidden,
                                     gather_output=False,
                                     axis_name=c.axis_name, init_std=0.02),
                RowParallelLinear(c.ffn_mult * c.hidden, c.hidden,
                                  input_is_parallel=True,
                                  axis_name=c.axis_name,
                                  init_std=0.02 / math.sqrt(2 * c.num_layers)),
            ))

    def init(self, key):
        c = self.c
        ks = jax.random.split(key, 6 + 4 * c.num_layers)
        params = {
            "embed": self.embed.init(ks[0], c.dtype),
            "pos_embed": jax.random.normal(ks[1], (c.seq_len, c.hidden),
                                           c.dtype) * 0.02,
            "tokentype_embed": jax.random.normal(
                ks[2], (c.num_tokentypes, c.hidden), c.dtype) * 0.02,
            "embed_ln": {"weight": jnp.ones((c.hidden,), c.dtype),
                         "bias": jnp.zeros((c.hidden,), c.dtype)},
            "pooler_w": jax.random.normal(ks[3], (c.hidden, c.hidden),
                                          c.dtype) * 0.02,
            "pooler_b": jnp.zeros((c.hidden,), c.dtype),
            "lm_head_ln": {"weight": jnp.ones((c.hidden,), c.dtype),
                           "bias": jnp.zeros((c.hidden,), c.dtype)},
            "lm_head_dense_w": jax.random.normal(
                ks[4], (c.hidden, c.hidden), c.dtype) * 0.02,
            "lm_head_dense_b": jnp.zeros((c.hidden,), c.dtype),
            "nsp_w": jax.random.normal(ks[5], (c.hidden, 2), c.dtype) * 0.02,
            "nsp_b": jnp.zeros((2,), c.dtype),
        }
        for i, mods in enumerate(self.blocks):
            k = jax.random.split(ks[5], 4 * c.num_layers)[4 * i: 4 * i + 4]
            params[f"block{i}"] = {
                "ln1": {"weight": jnp.ones((c.hidden,), c.dtype),
                        "bias": jnp.zeros((c.hidden,), c.dtype)},
                "qkv": mods[0].init(k[0], c.dtype),
                "proj": mods[1].init(k[1], c.dtype),
                "ln2": {"weight": jnp.ones((c.hidden,), c.dtype),
                        "bias": jnp.zeros((c.hidden,), c.dtype)},
                "fc1": mods[2].init(k[2], c.dtype),
                "fc2": mods[3].init(k[3], c.dtype),
            }
        return params

    def partition_specs(self):
        c = self.c
        col = {"weight": P(None, c.axis_name), "bias": P(c.axis_name)}
        row = {"weight": P(c.axis_name, None), "bias": P()}
        ln = {"weight": P(), "bias": P()}
        specs = {
            "embed": {"weight": P(c.axis_name, None)},
            "pos_embed": P(), "tokentype_embed": P(), "embed_ln": dict(ln),
            "pooler_w": P(), "pooler_b": P(),
            "lm_head_ln": dict(ln), "lm_head_dense_w": P(),
            "lm_head_dense_b": P(), "nsp_w": P(), "nsp_b": P(),
        }
        for i in range(c.num_layers):
            specs[f"block{i}"] = {"ln1": dict(ln), "qkv": dict(col),
                                  "proj": dict(row), "ln2": dict(ln),
                                  "fc1": dict(col), "fc2": dict(row)}
        return specs

    def _attention(self, bp, qkv_mod, proj_mod, x, pad_mask):
        c = self.c
        qkv = qkv_mod.apply(bp["qkv"], x)   # (S, B, 3H/tp)
        s, b, _ = qkv.shape
        nh_local = qkv.shape[-1] // (3 * c.head_dim)
        qkv = qkv.reshape(s, b, 3, nh_local, c.head_dim)
        q, k, v = (qkv[:, :, i].transpose(1, 2, 0, 3) for i in range(3))
        if c.use_flash_attention:
            # pad_mask (B, S) True = padded → segment ids: real tokens
            # share one id, pads another, so cross attention is masked
            # without ever materializing the S^2 scores
            from apex_tpu.ops.flash_attention import flash_attention
            seg = jnp.logical_not(pad_mask).astype(jnp.int32)
            ctx = flash_attention(q, k, v,
                                  softmax_scale=1.0 / math.sqrt(c.head_dim),
                                  segment_ids=seg,
                                  block_q=c.attn_block_q,
                                  block_k=c.attn_block_k,
                                  heads_per_step=c.attn_heads_per_step
                                  ).astype(x.dtype)
        else:
            scores = jnp.einsum("bnsh,bnth->bnst", q, k,
                                preferred_element_type=jnp.float32
                                ).astype(x.dtype)
            # pad_mask: (B, S) True = padded → mask (B, 1, S, S)
            mask = pad_mask[:, None, None, :]
            probs = scaled_masked_softmax(scores, mask,
                                          1.0 / math.sqrt(c.head_dim))
            ctx = jnp.einsum("bnst,bnth->bnsh", probs, v,
                             preferred_element_type=jnp.float32
                             ).astype(x.dtype)
        ctx = ctx.transpose(2, 0, 1, 3).reshape(s, b, -1)
        return proj_mod.apply(bp["proj"], ctx)

    def encode(self, params, tokens, tokentype_ids=None, pad_mask=None):
        """tokens: (B, S) → hidden (S, B, H)."""
        c = self.c
        ids = tokens.T
        h = self.embed.apply(params["embed"], ids)
        h = h + params["pos_embed"][: ids.shape[0]][:, None, :].astype(h.dtype)
        if tokentype_ids is not None:
            tt = jnp.take(params["tokentype_embed"], tokentype_ids.T, axis=0)
            h = h + tt.astype(h.dtype)
        h = fused_layer_norm(h, params["embed_ln"]["weight"],
                             params["embed_ln"]["bias"])
        if pad_mask is None:
            pad_mask = jnp.zeros(tokens.shape, bool)
        # `_tap` points mirror GPT._block (flight-recorder stat taps):
        # identity no-ops unless a TapContext is active
        for i, mods in enumerate(self.blocks):
            bp = params[f"block{i}"]
            hn = fused_layer_norm(h, bp["ln1"]["weight"], bp["ln1"]["bias"])
            hn = _tap(hn, f"block{i}/ln1")
            h = h + _tap(self._attention(bp, mods[0], mods[1], hn, pad_mask),
                         f"block{i}/attn")
            hn = fused_layer_norm(h, bp["ln2"]["weight"], bp["ln2"]["bias"])
            hn = _tap(hn, f"block{i}/ln2")
            m = mods[2].apply(bp["fc1"], hn)
            m = jax.nn.gelu(m, approximate=True)
            h = h + _tap(mods[3].apply(bp["fc2"], m), f"block{i}/mlp")
        return h

    def loss(self, params, tokens, mlm_labels, loss_mask,
             nsp_labels=None, tokentype_ids=None, pad_mask=None):
        """Masked-LM loss (+ NSP when labels given) ≡ standalone BERT's
        pretraining loss (standalone_bert.py forward)."""
        c = self.c
        h = self.encode(params, tokens, tokentype_ids, pad_mask)
        # MLM head: dense+gelu+LN then tied-embedding projection
        lm = h @ params["lm_head_dense_w"].astype(h.dtype) + \
            params["lm_head_dense_b"].astype(h.dtype)
        lm = jax.nn.gelu(lm, approximate=True)
        lm = fused_layer_norm(lm, params["lm_head_ln"]["weight"],
                              params["lm_head_ln"]["bias"])
        from apex_tpu.parallel.collectives import (
            copy_to_tensor_model_parallel_region)
        lm = copy_to_tensor_model_parallel_region(lm, c.axis_name)
        logits = jnp.einsum("sbh,vh->sbv", lm,
                            params["embed"]["weight"],
                            preferred_element_type=jnp.float32
                            ).astype(c.logits_dtype or jnp.float32)
        per_tok = vocab_parallel_cross_entropy(logits, mlm_labels.T,
                                               axis_name=c.axis_name)
        lm_mask = loss_mask.T.astype(jnp.float32)
        mlm_loss = jnp.sum(per_tok * lm_mask) / jnp.maximum(
            jnp.sum(lm_mask), 1.0)
        if nsp_labels is None:
            return mlm_loss
        pooled = jnp.tanh(h[0] @ params["pooler_w"].astype(h.dtype)
                          + params["pooler_b"].astype(h.dtype))  # (B, H)
        nsp_logits = pooled @ params["nsp_w"].astype(h.dtype) + \
            params["nsp_b"].astype(h.dtype)
        nsp = jnp.mean(
            -jax.nn.log_softmax(nsp_logits.astype(jnp.float32))[
                jnp.arange(nsp_logits.shape[0]), nsp_labels])
        return mlm_loss + nsp


def bert_large(**overrides) -> Bert:
    return Bert(BertConfig(**overrides))
