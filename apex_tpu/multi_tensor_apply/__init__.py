"""apex_tpu.multi_tensor_apply — chunked multi-tensor functor dispatch.

≡ apex.multi_tensor_apply (apex/multi_tensor_apply/multi_tensor_apply.py:3-30)
and the native chunking template it dispatches to
(csrc/multi_tensor_apply.cuh:19-100).

On TPU the launch-granularity problem the reference solves (hundreds of
small tensors -> a handful of CUDA kernel launches, <=110 tensors / 320
blocks per launch) does not exist: XLA compiles the whole update into one
program.  What remains useful is the *interface* — "apply this functor to
parallel lists of tensors in one fused pass" — which we express by
flattening each tensor list into a single 1-D buffer
(apex_tpu.optimizers.flat), applying the functor once, and scattering the
results back.  The C++ host runtime (apex_tpu/csrc) supplies the same
chunk-planning arithmetic for the native data path.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp

from apex_tpu.optimizers import flat as _flat

__all__ = ["MultiTensorApply", "multi_tensor_applier"]


class MultiTensorApply:
    """Callable dispatcher ≡ MultiTensorApply
    (apex/multi_tensor_apply/multi_tensor_apply.py:24-30).

    `chunk_size` is kept for signature parity; it has no performance
    meaning under XLA (there is exactly one fused "launch") but is used
    by the C++ host planner when staging buffers
    (apex_tpu/csrc/__init__.py:82 chunk_plan).
    """

    available = True

    def __init__(self, chunk_size: int = 2048 * 32):
        self.chunk_size = int(chunk_size)

    def __call__(self, op: Callable, noop_flag,
                 tensor_lists: Sequence[Sequence[jax.Array]], *args):
        """Apply `op` elementwise across parallel tensor lists.

        op(noop_flag, flat_buffers, *args) -> tuple of updated flat
        buffers (one per input list) — mirroring the reference call
        `multi_tensor_applier(op, overflow_buf, [g, p, m, v], ...)`
        (apex/optimizers/fused_adam.py:265-303).  Returns the updated
        tensor lists (functional: no in-place mutation in JAX).
        """
        if not tensor_lists or not tensor_lists[0]:
            return tuple(list(tl) for tl in tensor_lists)
        n = len(tensor_lists[0])
        for tl in tensor_lists:
            if len(tl) != n:
                raise ValueError("tensor lists must have equal length "
                                 "(≡ multi_tensor_apply.cuh size check)")
        for tl in tensor_lists:
            if any(t.dtype != tl[0].dtype for t in tl):
                raise ValueError(
                    "all tensors in one list must share a dtype "
                    "(≡ multi_tensor_apply.cuh per-list dtype assert)")
        specs = [_flat.make_spec(list(tl)) for tl in tensor_lists]
        flats = [_flat.flatten(list(tl), dtype=tl[0].dtype)
                 for tl in tensor_lists]
        outs = op(noop_flag, flats, *args)
        if isinstance(outs, jax.Array):
            outs = (outs,)
        rebuilt = []
        for out, spec, tl in zip(outs, specs, tensor_lists):
            if out is None:
                rebuilt.append(list(tl))
            else:
                rebuilt.append(_flat.unflatten(out, spec))
        return tuple(rebuilt)


multi_tensor_applier = MultiTensorApply(2048 * 32)
