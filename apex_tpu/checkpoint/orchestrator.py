"""Elastic-resume orchestration loop (ISSUE 11 tentpole, layer 2).

PR 9 promoted a lost dp rank from "the next collective hangs forever"
to "`LostRankWatchdog` raises `RankLostError`".  This module promotes
the raise into RECOVERY: a supervised train-loop driver that closes the
loop the veScale posture (arXiv 2509.07003) describes —

    detect lost rank
      → flight-recorder dump naming the last committed step
      → rebuild the mesh at the surviving dp topology
      → `restore_sharded` re-shard restore at dp=N→M
      → resume training

with retry/backoff around session builds (transient coordinator
errors: a restarting host refuses connections for a few seconds) and a
HARD escalation path — `EscalationError` — when recovery is
impossible: no committed checkpoint exists, the resume budget is
exhausted, or the build keeps failing past the retry policy.

The orchestrator owns the SUPERVISION; the caller owns the training
specifics through one callback::

    def build(dp, resume_step, attempt):
        # construct mesh/model/optimizer/manager at `dp` ranks,
        # restore from `resume_step` (None = from scratch), configure
        # the CheckpointManager with `attempt` (multi-host saves must
        # bump the attempt token across retries of the same step),
        # and return a zero-arg callable that runs the segment.
        return run_segment

    orch = ElasticOrchestrator(ckpt_dir, build, initial_dp=4,
                               recorder=recorder, watchdog=watchdog)
    result = orch.run()

`run_segment()` returns the finished result, or raises `RankLostError`
(usually from the `LostRankWatchdog` the caller drives inside its
loop) to trigger a resume cycle.  `stats()` exposes the `fleet_*`
telemetry scalars `MetricsLogger(fleet=orch)` stamps (schema v8).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from apex_tpu.checkpoint.chaos import RankLostError
from apex_tpu.checkpoint.sharded import latest_committed_step


class EscalationError(RuntimeError):
    """The orchestrator cannot recover on its own: no committed
    checkpoint to resume from, the resume budget is exhausted, or the
    session build kept failing past the retry policy.  A human (or a
    higher-level scheduler) must intervene — this is the HARD
    escalation path, deliberately not retried."""


class RetryPolicy:
    """Exponential backoff for transient build failures.  `attempts`
    counts TOTAL tries (first one included); `delay(i)` is the sleep
    before retry i (1-based)."""

    def __init__(self, attempts: int = 3, backoff_s: float = 0.05,
                 multiplier: float = 2.0):
        if attempts < 1:
            raise ValueError(f"attempts must be >= 1, got {attempts}")
        if backoff_s < 0:
            raise ValueError(f"backoff_s must be >= 0, got {backoff_s}")
        self.attempts = attempts
        self.backoff_s = backoff_s
        self.multiplier = multiplier

    def delay(self, retry_index: int) -> float:
        return self.backoff_s * (self.multiplier ** (retry_index - 1))


class ElasticOrchestrator:
    """Supervised elastic training: run → lost rank → dump → rebuild at
    the surviving topology → re-shard restore → resume.

    directory: the fleet's shared checkpoint root (the resume point is
    ALWAYS re-read from disk — the dying session's opinion is never
    trusted).  build: the session factory described in the module
    docstring.  initial_dp / min_dp: topology bounds; choose_dp
    overrides the default shrink rule ``max(min_dp, dp - 1)`` and
    receives ``(dp, exc)`` — `RankLostError.rank` names the dead rank
    when a smarter placement wants it.  recorder: an optional
    `FlightRecorder`; every lost-rank event dumps a crash report whose
    reason names the last committed step BEFORE any rebuild starts.
    watchdog: optional `LostRankWatchdog`, `reset()` on every rebuild
    (rank counts legitimately change at dp=N→M).  max_resumes bounds
    the recovery budget; transient names the exception types worth
    retrying at the SAME topology (coordinator hiccups), everything
    else propagates."""

    def __init__(self, directory: str, build: Callable[..., Callable], *,
                 initial_dp: int, min_dp: int = 1,
                 choose_dp: Optional[Callable[[int, BaseException],
                                              int]] = None,
                 recorder=None, watchdog=None, max_resumes: int = 4,
                 retry: Optional[RetryPolicy] = None,
                 transient: Tuple[type, ...] = (ConnectionError,
                                                TimeoutError),
                 sleep: Callable[[float], None] = time.sleep):
        if initial_dp < 1 or min_dp < 1 or min_dp > initial_dp:
            raise ValueError(
                f"need 1 <= min_dp <= initial_dp, got min_dp={min_dp} "
                f"initial_dp={initial_dp}")
        if max_resumes < 0:
            raise ValueError(f"max_resumes must be >= 0, got {max_resumes}")
        self.directory = directory
        self.build = build
        self.initial_dp = initial_dp
        self.min_dp = min_dp
        self.choose_dp = choose_dp
        self.recorder = recorder
        self.watchdog = watchdog
        self.max_resumes = max_resumes
        self.retry = retry or RetryPolicy()
        self.transient = tuple(transient)
        self.sleep = sleep
        self.dp = initial_dp
        self.resumes = 0
        self.events: List[dict] = []

    # ------------------------------------------------------------------

    def stats(self) -> Dict[str, Any]:
        """The `fleet_*` telemetry scalars (schema v8):
        `fleet_resumes` — completed lost-rank recovery cycles,
        `fleet_dp` — the topology currently training."""
        return {"fleet_resumes": int(self.resumes),
                "fleet_dp": int(self.dp)}

    def _dump(self, reason: str, exc: BaseException) -> None:
        if self.recorder is None:
            return
        try:
            import apex_tpu.monitor.compile.watermarks as wm
            self.recorder.dump(reason=reason, oom=wm.is_oom(exc))
        except Exception:  # the dump is forensics, never the failure
            pass

    def _build_session(self, dp: int, resume_step: Optional[int],
                       attempt: int) -> Callable:
        """`build` under the retry policy: transient errors back off
        and retry at the SAME topology; exhaustion escalates."""
        last_exc: Optional[BaseException] = None
        for i in range(1, self.retry.attempts + 1):
            try:
                return self.build(dp, resume_step, attempt)
            except self.transient as e:
                last_exc = e
                self.events.append({
                    "kind": "transient_build_failure", "dp": dp,
                    "try": i, "error": repr(e)})
                if i < self.retry.attempts:
                    self.sleep(self.retry.delay(i))
        raise EscalationError(
            f"session build at dp={dp} failed {self.retry.attempts} "
            f"times on transient errors (last: {last_exc!r}) — "
            "escalating to the operator") from last_exc

    # ------------------------------------------------------------------

    def run(self) -> Any:
        """Drive sessions until one finishes.  Returns its result."""
        while True:
            resume_step = latest_committed_step(self.directory)
            attempt = self.resumes
            session = self._build_session(self.dp, resume_step, attempt)
            try:
                result = session()
            except RankLostError as e:
                last = latest_committed_step(self.directory)
                where = (f"step {last}" if last is not None
                         else "NONE COMMITTED")
                self._dump(
                    f"rank lost at dp={self.dp}: {e}; last committed "
                    f"checkpoint: {where}; orchestrator rebuilding at "
                    "the surviving topology", e)
                if last is None:
                    raise EscalationError(
                        "a rank was lost and NO committed checkpoint "
                        f"exists under {self.directory} — nothing to "
                        "resume from; restart from scratch (lost-rank "
                        f"cause: {e})") from e
                if self.resumes >= self.max_resumes:
                    raise EscalationError(
                        f"resume budget exhausted: {self.resumes} "
                        f"recoveries already spent (max_resumes="
                        f"{self.max_resumes}); the fleet is flapping — "
                        "escalating to the operator") from e
                new_dp = (self.choose_dp(self.dp, e) if self.choose_dp
                          else max(self.min_dp, self.dp - 1))
                if not self.min_dp <= new_dp:
                    raise EscalationError(
                        f"surviving topology dp={new_dp} is below "
                        f"min_dp={self.min_dp} — not enough healthy "
                        "ranks to continue") from e
                self.events.append({
                    "kind": "rank_lost", "rank": getattr(e, "rank", None),
                    "dp_from": self.dp, "dp_to": new_dp,
                    "resume_step": last})
                self.resumes += 1
                self.dp = new_dp
                if self.watchdog is not None:
                    self.watchdog.reset()
                continue
            return result
