"""Shard-native checkpoint format + elastic re-layout (ISSUE 9 tentpole).

A checkpoint is a directory ``step_{k}/`` holding one raw little-endian
binary file per (field, rank) plus ``manifest.json``.  Three contracts:

* **Atomic commit.**  Shard files are written first; the manifest is
  written LAST through a tmp file + ``os.replace``.  The manifest IS
  the commit — a directory without one (kill mid-save) is never a
  loadable checkpoint, and `latest_committed_step` never returns it.
* **Shard-native.**  Each dp rank's ZeRO-2 flat-buffer shard is
  persisted as written by `ddp`'s sharded optimizers
  (`state_partition_specs()` is the source of truth for which fields
  shard); nothing is gathered at save time.  The manifest records the
  optimizer's `shard_layout()` fingerprint (align / total / n_tensors /
  bucket boundaries / num_shards), the amp scaler scalars, and the
  kernel-autotuner fingerprint.
* **Elastic restore.**  `restore_sharded` re-lays a checkpoint written
  at dp=N out for a target optimizer at dp=M (including M=1, the full
  gather): per bucket, the N rank chunks are concatenated, trimmed of
  tail padding to the CANONICAL align-padded flat content (which is
  bucket-count independent — bucket flats concatenate to the global
  aligned layout), then re-padded and re-sliced for the target's
  (num_shards, n_buckets).  Values are raw-copied: equal-topology
  restore is BITWISE, and cross-topology restore moves only zero
  padding (trajectory differences come from fp reduction order alone —
  see docs/checkpointing.md's resume matrix).

Shard completeness is validated against the manifest BEFORE any
deserialization: a missing or truncated shard raises
`IncompleteCheckpointError` naming the missing ranks (a partial
directory used to surface as an opaque deserialization traceback).
"""

from __future__ import annotations

import json
import os
import shutil
import time
import warnings
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

CKPT_SCHEMA_VERSION = 1
MANIFEST = "manifest.json"
# model state outside the optimizer (RNG key, BN running stats) rides
# the SAME manifest as replicated rank-0 fields under this name prefix
# — one commit covers the whole run.  `restore_sharded` never feeds
# them to the optimizer state; `load_model_state` returns them.
MODEL_PREFIX = "model."


class CheckpointError(RuntimeError):
    """Base class for checkpoint-format errors."""


class IncompleteCheckpointError(CheckpointError):
    """A committed manifest's shard files are missing or truncated.
    `missing` lists human-readable "field rank file (why)" entries."""

    def __init__(self, msg: str, missing: Sequence[str] = ()):
        super().__init__(msg)
        self.missing = list(missing)


class LayoutMismatchError(CheckpointError):
    """Source and target flat layouts cannot be re-laid into each other
    (different leaf population / align / dtype)."""


def _dtype(name: str) -> np.dtype:
    """Resolve a manifest dtype name, including the ml_dtypes extension
    types numpy's own registry doesn't know by string ("bfloat16")."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except AttributeError:
            raise CheckpointError(
                f"unknown checkpoint dtype {name!r}") from None


def _crc(raw: bytes) -> int:
    return zlib.crc32(raw) & 0xFFFFFFFF


def step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{int(step)}")


def write_rank_file(d: str, name: str, kind: str, rank: int, value, *,
                    expect_dtype: Optional[str] = None) -> Tuple[dict, list]:
    """Write ONE (field, rank) shard file and return its manifest file
    entry + shape.  The single definition of the on-disk format — file
    naming, contiguity, byte count, crc32 — shared by the single-host
    writer and the multi-host per-host writer so the two can never
    silently diverge."""
    a = np.asarray(value)
    shape = a.shape  # before ascontiguousarray: it promotes 0-d
    a = np.ascontiguousarray(a)
    if expect_dtype is not None and str(a.dtype) != expect_dtype:
        raise ValueError(
            f"field {name!r}: rank {rank} dtype {a.dtype} != "
            f"{expect_dtype}")
    fn = (f"{name}.rank{rank:03d}.bin" if kind == "sharded"
          else f"{name}.bin")
    raw = a.tobytes()
    with open(os.path.join(d, fn), "wb") as f:
        f.write(raw)
    return ({"rank": rank, "file": fn, "bytes": len(raw),
             "crc32": _crc(raw)}, list(shape))


# ---------------------------------------------------------------------------
# write side
# ---------------------------------------------------------------------------

def save_sharded(directory: str, step: int, fields: Dict[str, tuple], *,
                 flat_layout: Optional[dict] = None,
                 scaler: Optional[dict] = None,
                 tuner_fingerprint: Optional[str] = None,
                 extra: Optional[dict] = None,
                 overwrite: bool = False) -> str:
    """Write one committed checkpoint under ``directory/step_{step}``.

    fields: ``{name: (kind, value)}`` — kind ``"sharded"`` with value a
    rank-ordered list of per-rank 1-D host arrays, or ``"replicated"``
    with a single host array.  Returns the committed step directory.

    Commit protocol (kill-anywhere safe): leftover files of an aborted
    earlier attempt are cleared, shard files land one by one, and the
    manifest — which records every file's byte count and crc32 — is
    renamed into place LAST.  Overwriting an ALREADY-COMMITTED step
    writes the whole new attempt into a staging directory and swaps it
    in only after ITS manifest committed — the existing commit is never
    de-committed by a write in progress, so a kill mid-overwrite still
    leaves a loadable step (the only unguarded window is the two
    directory renames of the final swap).  The named `chaos.check`
    points let the fault-injection harness kill this writer mid-save in
    tests.
    """
    from apex_tpu.checkpoint import chaos

    final = step_dir(directory, step)
    committed = os.path.exists(os.path.join(final, MANIFEST))
    if committed and not overwrite:
        raise CheckpointError(
            f"{final} already holds a COMMITTED checkpoint; pass "
            "overwrite=True to replace it")
    # committed target: stage the new attempt next to it (the ".tmp"
    # suffix keeps it invisible to latest_committed_step's step_N scan)
    d = final + ".tmp" if committed else final
    if os.path.isdir(d):
        # an aborted save's partials — clear so a stale shard of a
        # different size can never survive next to a fresh manifest.
        # ONLY this format's artifacts: a legacy save_checkpoint
        # (state.pkl / orbax state/) sharing the step directory must
        # be refused, not silently destroyed
        for f in os.listdir(d):
            p = os.path.join(d, f)
            if os.path.isdir(p) or not (
                    f == MANIFEST or f.endswith((".bin", ".tmp"))
                    or (f.startswith("manifest.host")
                        and f.endswith(".json"))):
                raise CheckpointError(
                    f"{d} holds {f!r}, which is not a sharded-"
                    "checkpoint artifact — refusing to clear a "
                    "directory written by another format (legacy "
                    "save_checkpoint?); use a separate checkpoint root")
            os.remove(p)
    os.makedirs(d, exist_ok=True)

    manifest = {
        "ckpt_schema_version": CKPT_SCHEMA_VERSION,
        "step": int(step),
        "created_unix": time.time(),
        "fields": {},
        "flat_layout": flat_layout,
        "scaler": scaler,
        "tuner_fingerprint": tuner_fingerprint,
        "extra": extra or {},
    }
    chaos.check("ckpt.before_shards")
    total = 0
    for name, (kind, value) in fields.items():
        if kind not in ("sharded", "replicated"):
            raise ValueError(f"field {name!r}: kind must be 'sharded' or "
                             f"'replicated', got {kind!r}")
        arrs = list(value) if kind == "sharded" else [value]
        entry = {"kind": kind, "dtype": str(np.asarray(arrs[0]).dtype),
                 "num_shards": len(arrs) if kind == "sharded" else 1,
                 "shapes": [], "files": []}
        for r, a in enumerate(arrs):
            fe, shape = write_rank_file(d, name, kind, r, a,
                                        expect_dtype=entry["dtype"])
            entry["shapes"].append(shape)
            entry["files"].append(fe)
            total += fe["bytes"]
            chaos.check("ckpt.mid_shards")
        manifest["fields"][name] = entry
    manifest["total_bytes"] = total
    chaos.check("ckpt.before_manifest")
    tmp = os.path.join(d, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, os.path.join(d, MANIFEST))  # <-- the commit
    if d != final:
        # swap the fully-committed staging dir over the old commit;
        # the old one stays intact on disk until the swap completes
        old = final + ".old"
        shutil.rmtree(old, ignore_errors=True)
        os.rename(final, old)
        os.rename(d, final)
        shutil.rmtree(old, ignore_errors=True)
    return final


# ---------------------------------------------------------------------------
# validation / discovery
# ---------------------------------------------------------------------------

def validate_manifest(m: dict) -> None:
    """Schema check (raises CheckpointError) — the fixture-drift half of
    ``scripts/resume_probe.py --selftest``."""
    if not isinstance(m, dict):
        raise CheckpointError(f"manifest is {type(m).__name__}, not a dict")
    ver = m.get("ckpt_schema_version")
    if ver != CKPT_SCHEMA_VERSION:
        raise CheckpointError(
            f"ckpt_schema_version {ver!r} != {CKPT_SCHEMA_VERSION}")
    if not isinstance(m.get("step"), int) or m["step"] < 0:
        raise CheckpointError(f"bad step {m.get('step')!r}")
    flds = m.get("fields")
    if not isinstance(flds, dict) or not flds:
        raise CheckpointError("manifest has no fields")
    for name, e in flds.items():
        for key in ("kind", "dtype", "num_shards", "shapes", "files"):
            if key not in e:
                raise CheckpointError(f"field {name!r} missing {key!r}")
        if e["kind"] not in ("sharded", "replicated"):
            raise CheckpointError(f"field {name!r} bad kind {e['kind']!r}")
        _dtype(e["dtype"])  # resolvable
        n = len(e["files"])
        if n != (e["num_shards"] if e["kind"] == "sharded" else 1):
            raise CheckpointError(
                f"field {name!r}: {n} files for num_shards "
                f"{e['num_shards']}")
        for f in e["files"]:
            for key in ("rank", "file", "bytes", "crc32"):
                if key not in f:
                    raise CheckpointError(
                        f"field {name!r} file entry missing {key!r}")


def read_manifest(path: str) -> dict:
    """Read+validate the manifest of one step directory.  A missing
    manifest means the directory was never committed (kill mid-save);
    an unparseable one means the commit itself was corrupted."""
    mf = os.path.join(path, MANIFEST)
    if not os.path.exists(mf):
        raise CheckpointError(
            f"{path} has no {MANIFEST} — not a committed checkpoint "
            "(a save was killed before its atomic manifest rename); use "
            "latest_committed_step() to find the newest loadable step")
    try:
        with open(mf) as f:
            m = json.load(f)
    except ValueError as e:
        raise CheckpointError(
            f"{mf} is not valid JSON ({e}) — corrupted commit") from e
    validate_manifest(m)
    return m


def verify_shards(path: str, manifest: Optional[dict] = None, *,
                  crc: bool = True) -> None:
    """Validate every shard file named by the manifest BEFORE anything
    deserializes: existence, exact byte count, and (crc=True) checksum.
    Raises IncompleteCheckpointError listing the missing/short ranks."""
    m = manifest if manifest is not None else read_manifest(path)
    missing: List[str] = []
    for name, e in m["fields"].items():
        for f in e["files"]:
            fp = os.path.join(path, f["file"])
            if not os.path.exists(fp):
                missing.append(f"{name} rank {f['rank']} ({f['file']}: "
                               "missing)")
                continue
            sz = os.path.getsize(fp)
            if sz != f["bytes"]:
                missing.append(
                    f"{name} rank {f['rank']} ({f['file']}: {sz} bytes, "
                    f"manifest says {f['bytes']} — truncated)")
                continue
            if crc:
                with open(fp, "rb") as fh:
                    if _crc(fh.read()) != f["crc32"]:
                        missing.append(
                            f"{name} rank {f['rank']} ({f['file']}: "
                            "crc32 mismatch — corrupted)")
    if missing:
        raise IncompleteCheckpointError(
            f"checkpoint {path} is incomplete — {len(missing)} shard "
            f"file(s) failed validation: " + "; ".join(missing),
            missing=missing)


def _recover_swaps(directory: str) -> None:
    """Heal a kill between the two renames of an overwrite swap: a
    fully-committed ``step_N.tmp`` (new attempt) or ``step_N.old``
    (displaced original) whose final directory is missing is renamed
    back into place — .tmp preferred (it only commits after the new
    save finished).  Without this, the swap's microsecond window could
    strand the only loadable copy under a name the step scan skips."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return
    for suffix in (".tmp", ".old"):     # .tmp = the newer attempt, wins
        for d in entries:
            if not (d.startswith("step_") and d.endswith(suffix)):
                continue
            p = os.path.join(directory, d)
            final = p[: -len(suffix)]
            if os.path.exists(os.path.join(final, MANIFEST)):
                continue                 # final is committed; leave it
            try:
                verify_shards(p, crc=False)
            except CheckpointError:
                continue                 # not a committed copy
            if os.path.isdir(final):     # uncommitted partial: clear
                shutil.rmtree(final, ignore_errors=True)
            try:
                os.rename(p, final)
            except OSError:  # pragma: no cover — racing writer wins
                pass


def _committed_steps(directory: str) -> List[int]:
    """Steps whose manifest exists and whose shard files match their
    manifested sizes (the cheap sweep; crc happens at restore).
    Interrupted overwrite swaps are healed first."""
    if not os.path.isdir(directory):
        return []
    _recover_swaps(directory)
    out = []
    for d in os.listdir(directory):
        if not d.startswith("step_"):
            continue
        try:
            s = int(d[5:])
        except ValueError:
            continue
        try:
            verify_shards(os.path.join(directory, d), crc=False)
        except CheckpointError:
            continue
        out.append(s)
    return sorted(out)


def latest_committed_step(directory: str) -> Optional[int]:
    """Newest step under `directory` whose manifest parses AND whose
    shard files all exist at their manifested sizes (a cheap size-only
    sweep — crc validation happens at restore).  Uncommitted partials
    never count, so 'resume from the latest checkpoint' is always
    'resume from the latest checkpoint that will actually load' (and
    `restore_sharded(step=None)` additionally falls back past
    size-preserving corruption its crc sweep uncovers)."""
    steps = _committed_steps(directory)
    return steps[-1] if steps else None


def prune(directory: str, keep: int) -> List[int]:
    """Delete all but the newest `keep` COMMITTED steps, plus any
    uncommitted partial directories older than the newest committed
    step (aborted-save garbage).  Returns the deleted step numbers."""
    if not os.path.isdir(directory) or keep < 1:
        return []
    committed, partial = [], []
    for d in os.listdir(directory):
        if not d.startswith("step_"):
            continue
        try:
            s = int(d[5:])
        except ValueError:
            continue
        p = os.path.join(directory, d)
        (committed if os.path.exists(os.path.join(p, MANIFEST))
         else partial).append(s)
    committed.sort()
    newest = committed[-1] if committed else None
    doomed = committed[:-keep] if len(committed) > keep else []
    doomed += [s for s in partial if newest is not None and s < newest]
    for s in doomed:
        shutil.rmtree(os.path.join(directory, f"step_{s}"),
                      ignore_errors=True)
    # aborted overwrite staging dirs (killed before their swap) — only
    # when the FINAL directory is committed: a .tmp/.old that is the
    # sole surviving copy of its step belongs to _recover_swaps, not
    # the trash
    for d in os.listdir(directory):
        p = os.path.join(directory, d)
        for suffix in (".tmp", ".old"):
            if (d.startswith("step_") and d.endswith(suffix)
                    and os.path.exists(os.path.join(
                        p[: -len(suffix)], MANIFEST))):
                shutil.rmtree(p, ignore_errors=True)
    return sorted(doomed)


# ---------------------------------------------------------------------------
# read side: host loading + elastic re-layout
# ---------------------------------------------------------------------------

def load_field_host(path: str, manifest: dict, name: str, *,
                    check_crc: bool = False):
    """Read one field's raw bytes back into host arrays (rank-ordered
    list for sharded fields, a single array for replicated ones).
    Callers run `verify_shards` (at least the size sweep) first.
    check_crc=True checksums the SAME read that deserializes — the
    restore path's way to validate content without paying a second
    full pass over a multi-GB payload (verify_shards(crc=True) exists
    for standalone validation)."""
    e = manifest["fields"][name]
    dt = _dtype(e["dtype"])
    out = []
    for f, shape in zip(e["files"], e["shapes"]):
        with open(os.path.join(path, f["file"]), "rb") as fh:
            raw = fh.read()
        if check_crc and _crc(raw) != f["crc32"]:
            raise IncompleteCheckpointError(
                f"checkpoint {path} is incomplete — shard file failed "
                f"validation: {name} rank {f['rank']} ({f['file']}: "
                "crc32 mismatch — corrupted)",
                missing=[f"{name} rank {f['rank']} ({f['file']}: "
                         "crc32 mismatch — corrupted)"])
        out.append(np.frombuffer(raw, dtype=dt).reshape(shape).copy())
    return out if e["kind"] == "sharded" else out[0]


def pack_model_state(tree: dict) -> Dict[str, tuple]:
    """Flatten a (possibly nested) dict of model-state arrays — RNG
    keys, BN running stats, anything outside the optimizer — into
    replicated manifest fields named ``model.<dotted.path>``.  Keys are
    joined with ``"."`` so they must not themselves contain ``"."``
    (or ``"/"``, which cannot appear in a shard file name)."""
    out: Dict[str, tuple] = {}

    def _walk(prefix, node):
        if isinstance(node, dict):
            if not node:
                raise ValueError(
                    f"model state {prefix or '<root>'!r} is an empty dict")
            for k, v in node.items():
                k = str(k)
                if "." in k or "/" in k:
                    raise ValueError(
                        f"model state key {k!r} contains '.'/'/' — the "
                        "manifest joins nested keys with '.'")
                _walk(f"{prefix}.{k}" if prefix else k, v)
        else:
            out[MODEL_PREFIX + prefix] = ("replicated", np.asarray(node))

    _walk("", dict(tree))
    return out


def unpack_model_state(fields: Dict[str, np.ndarray]) -> dict:
    """Inverse of `pack_model_state`: ``model.a.b`` names back into a
    nested dict (prefix-less input keys are accepted too)."""
    root: dict = {}
    for name, value in fields.items():
        path = name[len(MODEL_PREFIX):] if name.startswith(MODEL_PREFIX) \
            else name
        parts = path.split(".")
        node = root
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = value
    return root


def load_model_state(directory: str, step: Optional[int] = None, *,
                     verify_crc: bool = True) -> dict:
    """The ``model.*`` replicated fields of one committed step as a
    nested host-array dict ({} when the checkpoint carries none).
    step=None reads the latest committed step."""
    directory = os.path.abspath(directory)
    if step is None:
        step = latest_committed_step(directory)
        if step is None:
            raise CheckpointError(
                f"no committed checkpoint under {directory}")
    p = step_dir(directory, step)
    m = read_manifest(p)
    names = [n for n in m["fields"] if n.startswith(MODEL_PREFIX)]
    return unpack_model_state(
        {n: load_field_host(p, m, n, check_crc=verify_crc)
         for n in names})


def _check_layouts(src: dict, dst: dict) -> None:
    src_ep = int(src.get("ep_shards", 1))
    dst_ep = int(dst.get("ep_shards", 1))
    if src_ep != dst_ep:
        # refuse BY NAME, never silently concat: an expert-sharded
        # layout's rank enumeration is (dp-major, ep-minor) over the
        # combined data axes, and the elastic re-shard contract is
        # dp-elasticity ONLY — re-laying across the ep axis would
        # reassign which mesh coordinate holds which expert state
        # under a contract nothing has validated (ISSUE 13 satellite;
        # docs/moe.md "Checkpointing expert-sharded state")
        raise LayoutMismatchError(
            f"checkpoint flat layout is expert-sharded over "
            f"ep={src_ep} but the target optimizer's layout carries "
            f"ep={dst_ep} — re-sharding is elastic in dp only; the "
            "'ep' axis cannot be re-laid (restore at the original "
            "expert-parallel size, or gather_state_dict the source "
            "run into a layout-independent checkpoint first)")
    for key in ("align", "total", "n_tensors", "master_dtype"):
        if src.get(key) != dst.get(key):
            raise LayoutMismatchError(
                f"checkpoint flat layout {key}={src.get(key)!r} does not "
                f"match the target optimizer's {dst.get(key)!r} — "
                "re-sharding can re-lay (num_shards, n_buckets), not a "
                "different leaf population / alignment / master dtype")
    if sum(src["bucket_totals"]) != src["total"]:
        raise LayoutMismatchError(
            f"inconsistent source layout: bucket totals "
            f"{src['bucket_totals']} do not sum to total {src['total']}")


def canonical_flat(shards: Sequence[np.ndarray], layout: dict) -> np.ndarray:
    """Reassemble the CANONICAL flat content — the align-padded leaf
    concatenation, tail padding trimmed — from per-rank shard buffers
    written under `layout`.  Bucket-count independent: per-bucket flats
    trimmed to their spec totals concatenate to exactly the global
    aligned layout (offsets are cumulative aligned leaf sizes)."""
    n = int(layout["num_shards"])
    if len(shards) != n:
        raise LayoutMismatchError(
            f"{len(shards)} shard buffers for num_shards {n}")
    buckets = []
    off = 0  # per-rank offset of this bucket's chunk inside the shard
    for padded, tot in zip(layout["bucket_padded"],
                           layout["bucket_totals"]):
        per = padded // n
        full = np.concatenate([sh[off:off + per] for sh in shards])
        if full.shape[0] != padded:
            raise LayoutMismatchError(
                f"bucket reassembly got {full.shape[0]} elements, layout "
                f"says {padded} — shard buffers do not match the layout")
        buckets.append(full[:tot])
        off += per
    return np.concatenate(buckets) if buckets else np.zeros(
        (0,), _dtype(layout["master_dtype"]))


def relayout_flat(canonical: np.ndarray, layout: dict) -> np.ndarray:
    """Slice the canonical flat content into the GLOBAL buffer of a
    target layout: bucket-major re-padding, then rank-major shard
    concatenation — exactly the global array a ``P(dp)``-sharded
    optimizer state leaf holds, ready for one `device_put`."""
    m = int(layout["num_shards"])
    bucket_flats = []
    off = 0
    for padded, tot in zip(layout["bucket_padded"],
                           layout["bucket_totals"]):
        b = canonical[off:off + tot]
        if b.shape[0] != tot:
            raise LayoutMismatchError(
                f"canonical buffer has {canonical.shape[0]} elements, "
                f"target layout wants {sum(layout['bucket_totals'])}")
        bucket_flats.append(np.pad(b, (0, padded - tot)))
        off += tot
    ranks = []
    for r in range(m):
        parts = []
        for bf in bucket_flats:
            per = bf.shape[0] // m
            parts.append(bf[r * per:(r + 1) * per])
        ranks.append(np.concatenate(parts) if parts
                     else canonical[:0])
    return np.concatenate(ranks) if ranks else canonical[:0]


def reshard(shards: Sequence[np.ndarray], src_layout: dict,
            dst_layout: dict) -> np.ndarray:
    """dp=N shard buffers → the global buffer for a dp=M layout.  The
    equal-layout fast path is a bare concatenation (trivially bitwise);
    the general path moves only zero padding around the same values."""
    _check_layouts(src_layout, dst_layout)
    same = all(src_layout.get(k) == dst_layout.get(k)
               for k in ("num_shards", "n_buckets", "bucket_padded",
                         "bucket_totals"))
    if same:
        return np.concatenate(list(shards))
    return relayout_flat(canonical_flat(shards, src_layout), dst_layout)


def restore_sharded(directory: str, optimizer, *, mesh=None,
                    step: Optional[int] = None,
                    axis_name: Optional[str] = None,
                    verify_crc: bool = True):
    """Restore an optimizer-state checkpoint for `optimizer`'s CURRENT
    layout/topology (init() must have run so the layout is fixed).

    Returns ``(state, scaler_state, manifest)`` — `state` is the
    optimizer's ``_STATE`` NamedTuple with sharded leaves placed as
    ``P(axis_name)`` global arrays on `mesh` (plain host-backed arrays
    when mesh is None), `scaler_state` an ``amp.scaler``
    LossScalerState or None.

    step=None resumes from the latest COMMITTED step; if that step's
    crc sweep then finds size-preserving corruption (the one failure
    mode the cheap commit scan can't see), restore falls back — with a
    loud warning — to the next older intact commit rather than abort a
    resume an older checkpoint could serve.  An EXPLICIT step never
    falls back.  Shard completeness (+crc) is verified before any
    bytes deserialize.  A tuner-fingerprint mismatch warns: the run
    will resume correct but under different tuned kernels, so bitwise
    trajectory claims lapse.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    directory = os.path.abspath(directory)
    explicit = step is not None
    if not explicit:
        step = latest_committed_step(directory)
        if step is None:
            raise CheckpointError(
                f"no committed checkpoint under {directory}")

    def _load_step(s):
        """(manifest, host values) of one step — completeness swept
        cheaply first, content checksummed on the SAME read that
        deserializes (one pass over a multi-GB payload, not two)."""
        p = step_dir(directory, s)
        m = read_manifest(p)
        verify_shards(p, m, crc=False)
        # model.* fields never reach the optimizer state — reading
        # them here would double the restore I/O the moment
        # restore_model_state reads them for real
        return m, {n: load_field_host(p, m, n, check_crc=verify_crc)
                   for n in m["fields"]
                   if not n.startswith(MODEL_PREFIX)}

    try:
        manifest, host_values = _load_step(step)
    except IncompleteCheckpointError:
        if explicit:
            raise
        fallback = None
        for s in sorted((x for x in _committed_steps(directory)
                         if x < step), reverse=True):
            try:
                fallback = (s,) + _load_step(s)
            except CheckpointError:
                continue
            break
        if fallback is None:
            raise
        warnings.warn(
            f"restore_sharded: newest committed step {step} failed its "
            f"checksum sweep — falling back to the next intact commit, "
            f"step {fallback[0]} (training since then is lost; "
            "investigate the damaged directory before pruning claims "
            "it)", stacklevel=2)
        step, manifest, host_values = fallback

    sharded_fields = [n for n, e in manifest["fields"].items()
                     if e["kind"] == "sharded"
                     and not n.startswith(MODEL_PREFIX)]
    dst_layout = None
    if sharded_fields:
        if not hasattr(optimizer, "shard_layout"):
            raise CheckpointError(
                f"checkpoint step {step} carries sharded fields "
                f"{sharded_fields} but {type(optimizer).__name__} has no "
                "shard_layout() — restore needs a ZeRO optimizer "
                "(init() first)")
        dst_layout = optimizer.shard_layout()
    src_layout = manifest.get("flat_layout")
    if sharded_fields and not src_layout:
        raise CheckpointError(
            f"checkpoint step {step} has sharded fields but no "
            "flat_layout record — cannot re-shard")

    if axis_name is None:
        axis_name = getattr(optimizer, "axis_name", None) or "dp"

    def put(host, spec):
        if mesh is None:
            return jnp.asarray(host)
        return jax.device_put(host, NamedSharding(mesh, spec))

    values = {}
    for name, e in manifest["fields"].items():
        if name.startswith(MODEL_PREFIX):
            continue  # model state: fetched via load_model_state, never
            # mistaken for a missing optimizer-state field
        host = host_values[name]
        if e["kind"] == "sharded":
            global_host = reshard(host, src_layout, dst_layout)
            values[name] = put(global_host, P(axis_name))
        else:
            values[name] = put(host, P())

    state_cls = getattr(optimizer, "_STATE", None)
    if state_cls is not None and set(state_cls._fields) == set(values):
        state = state_cls(**values)
    else:
        state = values

    scaler_state = None
    if manifest.get("scaler"):
        from apex_tpu.amp import scaler as scaler_lib
        scaler_state = scaler_lib.load_state_dict(manifest["scaler"])

    fp = manifest.get("tuner_fingerprint")
    if fp:
        try:
            from apex_tpu import tune
            cur = tune.fingerprint()
        except Exception:  # pragma: no cover — tuner is advisory here
            cur = None
        if cur is not None and cur != fp:
            warnings.warn(
                f"restore_sharded: checkpoint was written under tuner "
                f"fingerprint {fp} but the active one is {cur} — the "
                "resumed run uses different tuned kernels, so bitwise "
                "trajectory equality with the original run is not "
                "guaranteed (allclose still holds)", stacklevel=2)
    return state, scaler_state, manifest
