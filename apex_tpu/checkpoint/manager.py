"""`CheckpointManager` — async ZeRO-sharded snapshots off the hot path.

The training loop calls ``manager.maybe_save(step, opt_state, scaler)``
between steps.  On a cadence hit the manager

1. waits for the PREVIOUS write to commit (double-buffered: at most one
   write is ever in flight, so step N+1 never waits on the write of
   step N — only a save landing while the previous one is STILL
   writing blocks, and that wait is priced in `ckpt_blocking_s`);
2. snapshots the state device→host (`copy_to_host_async` fans the DMA
   out over all leaves before the first blocking fetch), splitting each
   leaf by the optimizer's ``state_partition_specs()`` — the source of
   truth for which flat buffers shard over dp — into per-rank shard
   buffers or one replicated array;
3. hands the host snapshot to a background writer thread that runs the
   `sharded.save_sharded` commit protocol (shards first, manifest
   rename last) and prunes old steps.

`ckpt_blocking_s` (what the hot path paid) and `ckpt_save_s` (what the
writer thread paid) land in `stats()`, which
``MetricsLogger(ckpt=manager)`` stamps into every telemetry record —
the bench JSON prices the cadence with the same two numbers.

Multi-host (ISSUE 11): pass ``process_id``/``num_processes`` and every
controller process writes only its LOCAL ranks' shard files plus a
per-host sub-manifest; process 0 commits the global manifest only
after every host's sub-manifest is present and crc-verified
(`checkpoint.multihost` owns the barrier protocol).  A kill of any
host at any point never yields a loadable partial.  Process 0
additionally stamps `ckpt_commit_barrier_s` — how long the commit
barrier waited on the slowest host — into `stats()`.

Model state outside the optimizer (RNG key, BN running stats) rides
the same commit: pass ``model_state={"rng_key": key, ...}`` to
`save`/`maybe_save` and read it back with `restore_model_state()` —
one manifest covers the whole run (rank-0 replicated fields, never fed
to the optimizer state).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from apex_tpu.checkpoint import sharded as S


class CheckpointManager:
    """directory: checkpoint root (``step_{k}/`` subdirs).  optimizer:
    the live optimizer instance — a ZeRO variant's
    ``state_partition_specs()``/``shard_layout()`` drive the shard
    split; a plain flat optimizer checkpoints replicated.  keep: how
    many committed steps survive pruning.  async_write=False runs the
    writer inline (the chaos tests' deterministic mode)."""

    def __init__(self, directory: str, optimizer=None, *,
                 every_n_steps: int = 100, keep: int = 2,
                 axis_name: Optional[str] = None,
                 async_write: bool = True,
                 process_id: Optional[int] = None,
                 num_processes: Optional[int] = None,
                 local_ranks=None, attempt: Optional[int] = None,
                 barrier_timeout_s: float = 120.0):
        if every_n_steps < 1:
            raise ValueError(
                f"every_n_steps must be >= 1, got {every_n_steps}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = os.path.abspath(directory)
        self.optimizer = optimizer
        self.every_n_steps = every_n_steps
        self.keep = keep
        self.axis_name = axis_name or getattr(optimizer, "axis_name",
                                              None) or "dp"
        self.async_write = async_write
        # multi-host commit (checkpoint.multihost): each id falls back
        # to the launcher's env INDEPENDENTLY — a caller passing only
        # num_processes=N must still pick up its per-process id, or
        # every host would believe it is process 0
        if num_processes is None:
            num_processes = int(os.environ.get(
                "APEX_TPU_NUM_PROCESSES", "1") or 1)
        if process_id is None:
            process_id = int(os.environ.get(
                "APEX_TPU_PROCESS_ID", "0") or 0)
        self.num_processes = int(num_processes)
        self.process_id = int(process_id)
        if not 0 <= self.process_id < self.num_processes:
            raise ValueError(
                f"process_id {self.process_id} outside "
                f"[0, {self.num_processes})")
        self.local_ranks = (None if local_ranks is None
                            else sorted(int(r) for r in local_ranks))
        self.attempt = attempt  # None: resolved from APEX_TPU_ATTEMPT
        self.barrier_timeout_s = barrier_timeout_s
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last_requested: Optional[int] = None
        self._stats: Dict[str, Any] = {}

    @property
    def multihost(self) -> bool:
        return self.num_processes > 1

    def _resolve_attempt(self) -> int:
        if self.attempt is not None:
            return int(self.attempt)
        return int(os.environ.get("APEX_TPU_ATTEMPT", "0") or 0)

    def _resolve_local_ranks(self, num_shards: int):
        from apex_tpu.checkpoint import multihost as MH
        if self.local_ranks is not None:
            return self.local_ranks
        return MH.local_ranks(self.process_id, self.num_processes,
                              num_shards)

    # ------------------------------------------------------------------
    # save path
    # ------------------------------------------------------------------

    def maybe_save(self, step: int, opt_state, scaler_state=None,
                   extra: Optional[dict] = None,
                   model_state: Optional[dict] = None) -> bool:
        """Save iff `step` is on the cadence (and not already saved).
        Returns whether a save was started — commit is asynchronous;
        `wait()` blocks until it lands."""
        step = int(step)
        if step == self._last_requested or step % self.every_n_steps:
            return False
        self.save(step, opt_state, scaler_state, extra=extra,
                  model_state=model_state)
        return True

    def save(self, step: int, opt_state, scaler_state=None, *,
             extra: Optional[dict] = None,
             model_state: Optional[dict] = None) -> None:
        """Unconditional save of `step`.  Blocking cost = wait for the
        previous in-flight write + the device→host snapshot; the file
        I/O runs on the writer thread.  `model_state`: a (nested) dict
        of replicated rank-0 arrays (RNG key, BN stats) committed in
        the SAME manifest (multi-host: only process 0 writes them)."""
        t0 = time.perf_counter()
        self.wait()  # double buffer: at most one write in flight
        fields = self._snapshot(opt_state)
        if model_state:
            if not self.multihost or self.process_id == 0:
                packed = S.pack_model_state(model_state)
                clash = set(packed) & set(fields)
                if clash:
                    raise S.CheckpointError(
                        f"model state collides with optimizer fields: "
                        f"{sorted(clash)}")
                fields.update(packed)
        scaler = None
        if scaler_state is not None:
            from apex_tpu.amp import scaler as scaler_lib
            scaler = scaler_lib.state_dict(scaler_state)
        layout = None
        if self.optimizer is not None and hasattr(self.optimizer,
                                                  "shard_layout"):
            layout = self.optimizer.shard_layout()
        try:
            from apex_tpu import tune
            fingerprint = tune.fingerprint()
        except Exception:  # pragma: no cover — tuner stamp is advisory
            fingerprint = None
        blocking = time.perf_counter() - t0
        self._last_requested = int(step)
        total = sum(
            sum(int(np.asarray(a).nbytes) for a in
                (v.values() if isinstance(v, dict) else v))
            if kind == "sharded" else int(np.asarray(v).nbytes)
            for kind, v in fields.values())

        def _write():
            t1 = time.perf_counter()
            try:
                if self.multihost:
                    from apex_tpu.checkpoint import multihost as MH
                    _, barrier_s = MH.save_sharded_multihost(
                        self.directory, step, fields,
                        process_id=self.process_id,
                        num_processes=self.num_processes,
                        attempt=self._resolve_attempt(),
                        flat_layout=layout, scaler=scaler,
                        tuner_fingerprint=fingerprint, extra=extra,
                        timeout_s=self.barrier_timeout_s)
                else:
                    S.save_sharded(
                        self.directory, step, fields, flat_layout=layout,
                        scaler=scaler, tuner_fingerprint=fingerprint,
                        extra=extra, overwrite=True)
                # ONE atomic update at commit time: every ckpt_* stat
                # describes the SAME save (a logger reading between a
                # save() call and its commit must never see this
                # save's blocking next to the previous save's clock)
                stats = dict(
                    ckpt_blocking_s=round(blocking, 6),
                    ckpt_save_s=round(time.perf_counter() - t1, 6),
                    ckpt_last_step=int(step),
                    ckpt_bytes=int(total))
                if self.multihost:
                    if self.process_id == 0:
                        # how long the commit barrier waited on the
                        # slowest host's sub-manifest (schema v8)
                        stats["ckpt_commit_barrier_s"] = round(
                            barrier_s, 6)
                    else:
                        # a non-zero host never observes the commit —
                        # its resume point is whatever disk says
                        lc = S.latest_committed_step(self.directory)
                        if lc is None:
                            stats.pop("ckpt_last_step")
                        else:
                            stats["ckpt_last_step"] = int(lc)
                self._stats.update(stats)
                # prune on process 0 only: N hosts racing rmtree over a
                # shared store would tear each other's sweeps apart
                # (and partials NEWER than the newest commit — another
                # host's in-flight staging — are never pruned anyway)
                if not self.multihost or self.process_id == 0:
                    S.prune(self.directory, self.keep)
            except BaseException as e:
                self._error = e
                raise

        if self.async_write:
            # the writer swallows its own re-raise: the failure is
            # surfaced on the TRAINING thread at the next wait()/save()
            # (the default threading excepthook would only stderr-spam)
            def _quiet():
                try:
                    _write()
                except BaseException:
                    pass  # kept in self._error, re-raised by wait()

            self._thread = threading.Thread(
                target=_quiet, name=f"ckpt-write-step{step}", daemon=True)
            self._thread.start()
        else:
            try:
                _write()
            except BaseException:
                # surfaced HERE, synchronously — clearing the deferred
                # copy keeps the next save()'s wait() from re-raising a
                # stale error and silently skipping ITS write (a fleet
                # that recovers after one refused commit must not lose
                # its next resume point)
                self._error = None
                raise

    def wait(self) -> None:
        """Block until the in-flight write (if any) committed; re-raise
        a writer-thread failure HERE, on the training thread — a save
        that silently failed is a resume point that doesn't exist."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _host_shards(self, name: str, v, num: int) -> Dict[int, Any]:
        """{global_rank: host array} for a ``P(dp)``-sharded 1-D leaf.
        Multi-controller arrays (not fully addressable) are assembled
        from `addressable_shards` — the only shards this process CAN
        fetch; single-controller arrays split the full host copy."""
        shards = getattr(v, "addressable_shards", None)
        if (shards and not getattr(v, "is_fully_addressable", True)):
            glen = int(v.shape[0])
            if glen % num:
                raise S.CheckpointError(
                    f"field {name!r}: global length {glen} not "
                    f"divisible by num_shards {num}")
            per = glen // num
            out: Dict[int, Any] = {}
            for sh in shards:
                idx = sh.index[0] if sh.index else slice(0, glen)
                start = int(idx.start or 0)
                if start % per:
                    raise S.CheckpointError(
                        f"field {name!r}: device shard at offset "
                        f"{start} does not align with the {num}-way "
                        "rank split — is the leaf sharded over a "
                        "different axis?")
                out.setdefault(start // per, np.asarray(sh.data))
            return out
        host = np.asarray(v)
        if host.shape[0] % num:
            raise S.CheckpointError(
                f"field {name!r}: global length {host.shape[0]} "
                f"not divisible by num_shards {num}")
        return dict(enumerate(np.split(host, num)))

    def _snapshot(self, opt_state) -> Dict[str, tuple]:
        """Device→host copy, split per `state_partition_specs()`.
        Multi-host mode keeps only this process's `local_ranks` for
        sharded fields and drops replicated fields on non-zero hosts
        (they are rank-0 state — `multihost.write_host_shards`
        enforces it)."""
        d = (opt_state._asdict() if hasattr(opt_state, "_asdict")
             else dict(opt_state))
        specs = None
        if self.optimizer is not None and hasattr(
                self.optimizer, "state_partition_specs"):
            specs = self.optimizer.state_partition_specs()
            specs = (specs._asdict() if hasattr(specs, "_asdict")
                     else dict(specs))
        num = int(getattr(self.optimizer, "num_shards", 1) or 1)
        # fan the DMAs out before the first blocking fetch
        for v in d.values():
            if hasattr(v, "copy_to_host_async"):
                try:
                    v.copy_to_host_async()
                except Exception:  # pragma: no cover — fetch still works
                    pass
        local = (set(self._resolve_local_ranks(num)) if self.multihost
                 else None)
        fields: Dict[str, tuple] = {}
        for name, v in d.items():
            spec = specs.get(name) if specs else None
            is_sharded = bool(spec) and self.axis_name in tuple(spec)
            if is_sharded:
                by_rank = self._host_shards(name, v, num) if num > 1 \
                    else {0: np.asarray(v)}
                if local is None:
                    fields[name] = ("sharded",
                                    [by_rank[r] for r in sorted(by_rank)])
                else:
                    mine = {r: a for r, a in by_rank.items()
                            if r in local}
                    missing = local - set(mine)
                    if missing:
                        raise S.CheckpointError(
                            f"field {name!r}: local ranks "
                            f"{sorted(missing)} are not addressable "
                            "from this process — local_ranks does not "
                            "match the device placement")
                    if mine:  # a host with zero ranks skips the field
                        fields[name] = ("sharded", mine)
            elif not self.multihost or self.process_id == 0:
                fields[name] = ("replicated", np.asarray(v))
        return fields

    # ------------------------------------------------------------------
    # restore / introspection
    # ------------------------------------------------------------------

    @property
    def last_committed_step(self) -> Optional[int]:
        """Ground truth from disk (a fresh manager after a crash reads
        the same answer the dying one would have)."""
        return S.latest_committed_step(self.directory)

    def restore(self, mesh=None, step: Optional[int] = None,
                verify_crc: bool = True):
        """`sharded.restore_sharded` against this manager's optimizer.
        Returns (state, scaler_state, manifest)."""
        if self.optimizer is None:
            raise S.CheckpointError(
                "CheckpointManager.restore needs the optimizer the "
                "state is being restored FOR (its init() fixes the "
                "target layout)")
        return S.restore_sharded(
            self.directory, self.optimizer, mesh=mesh, step=step,
            axis_name=self.axis_name, verify_crc=verify_crc)

    def restore_model_state(self, step: Optional[int] = None, *,
                            verify_crc: bool = True) -> dict:
        """The ``model.*`` fields (RNG key, BN stats, …) of one
        committed step as a nested host-array dict — {} when that step
        carries none.  Pair with `restore()` at the SAME step."""
        return S.load_model_state(self.directory, step,
                                  verify_crc=verify_crc)

    def stats(self) -> Dict[str, Any]:
        """The `ckpt_*` telemetry scalars of the newest save (empty
        before the first) — what ``MetricsLogger(ckpt=manager)`` stamps
        and the bench JSON prices the cadence with."""
        return dict(self._stats)

    def close(self) -> None:
        self.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # don't mask an in-flight exception with a writer error
        try:
            self.wait()
        except BaseException:
            if exc == (None, None, None):
                raise
