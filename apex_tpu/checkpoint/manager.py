"""`CheckpointManager` — async ZeRO-sharded snapshots off the hot path.

The training loop calls ``manager.maybe_save(step, opt_state, scaler)``
between steps.  On a cadence hit the manager

1. waits for the PREVIOUS write to commit (double-buffered: at most one
   write is ever in flight, so step N+1 never waits on the write of
   step N — only a save landing while the previous one is STILL
   writing blocks, and that wait is priced in `ckpt_blocking_s`);
2. snapshots the state device→host (`copy_to_host_async` fans the DMA
   out over all leaves before the first blocking fetch), splitting each
   leaf by the optimizer's ``state_partition_specs()`` — the source of
   truth for which flat buffers shard over dp — into per-rank shard
   buffers or one replicated array;
3. hands the host snapshot to a background writer thread that runs the
   `sharded.save_sharded` commit protocol (shards first, manifest
   rename last) and prunes old steps.

`ckpt_blocking_s` (what the hot path paid) and `ckpt_save_s` (what the
writer thread paid) land in `stats()`, which
``MetricsLogger(ckpt=manager)`` stamps into every telemetry record —
the bench JSON prices the cadence with the same two numbers.

Single-controller: the manager assumes every shard is addressable from
this process (the repo's virtual CPU mesh and the single-controller TPU
runtime both are).  A multi-host deployment writes per-host shard
subsets with rank-0 committing the manifest — the named extension in
docs/checkpointing.md.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Dict, Optional

import numpy as np

from apex_tpu.checkpoint import sharded as S


class CheckpointManager:
    """directory: checkpoint root (``step_{k}/`` subdirs).  optimizer:
    the live optimizer instance — a ZeRO variant's
    ``state_partition_specs()``/``shard_layout()`` drive the shard
    split; a plain flat optimizer checkpoints replicated.  keep: how
    many committed steps survive pruning.  async_write=False runs the
    writer inline (the chaos tests' deterministic mode)."""

    def __init__(self, directory: str, optimizer=None, *,
                 every_n_steps: int = 100, keep: int = 2,
                 axis_name: Optional[str] = None,
                 async_write: bool = True):
        if every_n_steps < 1:
            raise ValueError(
                f"every_n_steps must be >= 1, got {every_n_steps}")
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self.directory = os.path.abspath(directory)
        self.optimizer = optimizer
        self.every_n_steps = every_n_steps
        self.keep = keep
        self.axis_name = axis_name or getattr(optimizer, "axis_name",
                                              None) or "dp"
        self.async_write = async_write
        self._thread: Optional[threading.Thread] = None
        self._error: Optional[BaseException] = None
        self._last_requested: Optional[int] = None
        self._stats: Dict[str, Any] = {}

    # ------------------------------------------------------------------
    # save path
    # ------------------------------------------------------------------

    def maybe_save(self, step: int, opt_state, scaler_state=None,
                   extra: Optional[dict] = None) -> bool:
        """Save iff `step` is on the cadence (and not already saved).
        Returns whether a save was started — commit is asynchronous;
        `wait()` blocks until it lands."""
        step = int(step)
        if step == self._last_requested or step % self.every_n_steps:
            return False
        self.save(step, opt_state, scaler_state, extra=extra)
        return True

    def save(self, step: int, opt_state, scaler_state=None, *,
             extra: Optional[dict] = None) -> None:
        """Unconditional save of `step`.  Blocking cost = wait for the
        previous in-flight write + the device→host snapshot; the file
        I/O runs on the writer thread."""
        t0 = time.perf_counter()
        self.wait()  # double buffer: at most one write in flight
        fields = self._snapshot(opt_state)
        scaler = None
        if scaler_state is not None:
            from apex_tpu.amp import scaler as scaler_lib
            scaler = scaler_lib.state_dict(scaler_state)
        layout = None
        if self.optimizer is not None and hasattr(self.optimizer,
                                                  "shard_layout"):
            layout = self.optimizer.shard_layout()
        try:
            from apex_tpu import tune
            fingerprint = tune.fingerprint()
        except Exception:  # pragma: no cover — tuner stamp is advisory
            fingerprint = None
        blocking = time.perf_counter() - t0
        self._last_requested = int(step)
        total = sum(
            sum(int(np.asarray(a).nbytes) for a in v)
            if kind == "sharded" else int(np.asarray(v).nbytes)
            for kind, v in fields.values())

        def _write():
            t1 = time.perf_counter()
            try:
                S.save_sharded(
                    self.directory, step, fields, flat_layout=layout,
                    scaler=scaler, tuner_fingerprint=fingerprint,
                    extra=extra, overwrite=True)
                # ONE atomic update at commit time: every ckpt_* stat
                # describes the SAME save (a logger reading between a
                # save() call and its commit must never see this
                # save's blocking next to the previous save's clock)
                self._stats.update(
                    ckpt_blocking_s=round(blocking, 6),
                    ckpt_save_s=round(time.perf_counter() - t1, 6),
                    ckpt_last_step=int(step),
                    ckpt_bytes=int(total))
                S.prune(self.directory, self.keep)
            except BaseException as e:
                self._error = e
                raise

        if self.async_write:
            # the writer swallows its own re-raise: the failure is
            # surfaced on the TRAINING thread at the next wait()/save()
            # (the default threading excepthook would only stderr-spam)
            def _quiet():
                try:
                    _write()
                except BaseException:
                    pass  # kept in self._error, re-raised by wait()

            self._thread = threading.Thread(
                target=_quiet, name=f"ckpt-write-step{step}", daemon=True)
            self._thread.start()
        else:
            _write()

    def wait(self) -> None:
        """Block until the in-flight write (if any) committed; re-raise
        a writer-thread failure HERE, on the training thread — a save
        that silently failed is a resume point that doesn't exist."""
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None
        if self._error is not None:
            err, self._error = self._error, None
            raise err

    def _snapshot(self, opt_state) -> Dict[str, tuple]:
        """Device→host copy, split per `state_partition_specs()`."""
        d = (opt_state._asdict() if hasattr(opt_state, "_asdict")
             else dict(opt_state))
        specs = None
        if self.optimizer is not None and hasattr(
                self.optimizer, "state_partition_specs"):
            specs = self.optimizer.state_partition_specs()
            specs = (specs._asdict() if hasattr(specs, "_asdict")
                     else dict(specs))
        num = int(getattr(self.optimizer, "num_shards", 1) or 1)
        # fan the DMAs out before the first blocking fetch
        for v in d.values():
            if hasattr(v, "copy_to_host_async"):
                try:
                    v.copy_to_host_async()
                except Exception:  # pragma: no cover — fetch still works
                    pass
        fields: Dict[str, tuple] = {}
        for name, v in d.items():
            spec = specs.get(name) if specs else None
            is_sharded = bool(spec) and self.axis_name in tuple(spec)
            host = np.asarray(v)
            if is_sharded and num > 1:
                if host.shape[0] % num:
                    raise S.CheckpointError(
                        f"field {name!r}: global length {host.shape[0]} "
                        f"not divisible by num_shards {num}")
                fields[name] = ("sharded", list(np.split(host, num)))
            elif is_sharded:
                fields[name] = ("sharded", [host])
            else:
                fields[name] = ("replicated", host)
        return fields

    # ------------------------------------------------------------------
    # restore / introspection
    # ------------------------------------------------------------------

    @property
    def last_committed_step(self) -> Optional[int]:
        """Ground truth from disk (a fresh manager after a crash reads
        the same answer the dying one would have)."""
        return S.latest_committed_step(self.directory)

    def restore(self, mesh=None, step: Optional[int] = None,
                verify_crc: bool = True):
        """`sharded.restore_sharded` against this manager's optimizer.
        Returns (state, scaler_state, manifest)."""
        if self.optimizer is None:
            raise S.CheckpointError(
                "CheckpointManager.restore needs the optimizer the "
                "state is being restored FOR (its init() fixes the "
                "target layout)")
        return S.restore_sharded(
            self.directory, self.optimizer, mesh=mesh, step=step,
            axis_name=self.axis_name, verify_crc=verify_crc)

    def stats(self) -> Dict[str, Any]:
        """The `ckpt_*` telemetry scalars of the newest save (empty
        before the first) — what ``MetricsLogger(ckpt=manager)`` stamps
        and the bench JSON prices the cadence with."""
        return dict(self._stats)

    def close(self) -> None:
        self.wait()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        # don't mask an in-flight exception with a writer error
        try:
            self.wait()
        except BaseException:
            if exc == (None, None, None):
                raise
