"""Preemption-proof checkpointing (ISSUE 9, ROADMAP item 4).

Three layers (docs/checkpointing.md):

* `legacy` — the original whole-pytree save/load surface
  (`save_checkpoint` / `load_checkpoint` / `latest_step`, orbax-backed
  with a pickle fallback) for model weights and small state.
* `sharded` + `manager` — the shard-native format: each dp rank's
  ZeRO-2 flat-buffer shard persists as raw bytes under an atomically
  committed manifest; `CheckpointManager` takes the device→host copy
  off the hot path (double-buffered background writer) and
  `restore_sharded` re-lays a dp=N checkpoint out for dp=M (elastic
  resume; equal topology is bitwise).
* `chaos` — the fault-injection harness: fail points inside the
  writer, host-side corruption helpers, the flight-recorder
  `resume_guard`, and the `LostRankWatchdog` that turns a lost rank
  into a crash dump naming the last committed step instead of a hang.

`scripts/resume_probe.py` is the standing CI gate over the whole
stack: save → kill → restore → trajectory-match.
"""

from apex_tpu.checkpoint import chaos  # noqa: F401
from apex_tpu.checkpoint import multihost  # noqa: F401
from apex_tpu.checkpoint.legacy import (  # noqa: F401
    latest_step,
    load_checkpoint,
    save_checkpoint,
)
from apex_tpu.checkpoint.manager import CheckpointManager  # noqa: F401
from apex_tpu.checkpoint.multihost import (  # noqa: F401
    MultihostCommitError,
    save_sharded_multihost,
)
from apex_tpu.checkpoint.orchestrator import (  # noqa: F401
    ElasticOrchestrator,
    EscalationError,
    RetryPolicy,
)
from apex_tpu.checkpoint.sharded import (  # noqa: F401
    CKPT_SCHEMA_VERSION,
    CheckpointError,
    IncompleteCheckpointError,
    LayoutMismatchError,
    latest_committed_step,
    load_model_state,
    pack_model_state,
    read_manifest,
    restore_sharded,
    save_sharded,
    unpack_model_state,
    validate_manifest,
    verify_shards,
)

__all__ = [
    "CKPT_SCHEMA_VERSION",
    "CheckpointError",
    "CheckpointManager",
    "ElasticOrchestrator",
    "EscalationError",
    "IncompleteCheckpointError",
    "LayoutMismatchError",
    "MultihostCommitError",
    "RetryPolicy",
    "chaos",
    "latest_committed_step",
    "latest_step",
    "load_checkpoint",
    "load_model_state",
    "multihost",
    "pack_model_state",
    "read_manifest",
    "restore_sharded",
    "save_checkpoint",
    "save_sharded",
    "save_sharded_multihost",
    "unpack_model_state",
    "validate_manifest",
    "verify_shards",
]
