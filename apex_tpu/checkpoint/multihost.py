"""Multi-host distributed checkpoint commit (ISSUE 11 tentpole, layer 1).

A multi-controller fleet cannot funnel every shard through one process:
each host can address (and therefore snapshot) only its own ranks'
state.  This module distributes the WRITE side of the shard-native
format while keeping the single-host commit semantics intact — the
veScale posture (arXiv 2509.07003): single-controller *consistency*
with multi-host *execution*.

Protocol (all hosts share one checkpoint directory, e.g. NFS/GCS-fuse):

1. **Every host** writes only its LOCAL ranks' shard files into
   ``step_{k}/`` (the format is already rank-keyed — file names embed
   the global rank, so hosts never collide) and then publishes a
   per-host sub-manifest ``manifest.host{h:03d}.json`` via tmp +
   ``os.replace``.  The sub-manifest records byte counts + crc32 of
   exactly the files that host wrote, the step, and the caller's
   `attempt` token.
2. **Process 0** additionally writes the replicated (rank-0) fields,
   then runs the COMMIT BARRIER: it polls until every host's
   sub-manifest is present, matches (step, attempt), and every file it
   names crc-verifies on disk.  Only then does it merge the
   sub-manifests into the ordinary global ``manifest.json`` —
   committed through the same tmp + ``os.replace`` rename the
   single-host writer uses.

The global manifest is byte-for-byte the single-host schema, so
`verify_shards` / `latest_committed_step` / `restore_sharded` need no
multi-host awareness: **the rank-0 manifest is the single source of
truth**.  A kill of ANY host at ANY point leaves either the previous
commit or nothing — a straggler host's stale ``step_{k}`` directory
without a global manifest is invisible to the step scan, and a stale
sub-manifest next to a committed OLDER global manifest resolves to the
older step on every host.

Attempt tokens: if a commit of step k fails (a host died) and the
orchestrator re-drives the fleet to save step k again, the retry MUST
carry a bumped `attempt` — the barrier refuses to mix a surviving
host's fresh files with a dead attempt's stale sub-manifest (the crc
sweep alone cannot distinguish two internally-consistent attempts).

CPU-emulation note: jax 0.4.x cannot run cross-process collectives on
the CPU backend, so `scripts/fleet_probe.py` exercises this protocol
with per-process deterministic replicas of the compute and genuinely
distributed writes + real process kills — the commit/barrier layer
under test here is exactly the code path a real TPU pod runs.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from apex_tpu.checkpoint.sharded import (
    CKPT_SCHEMA_VERSION,
    MANIFEST,
    CheckpointError,
    _crc,
    step_dir,
    write_rank_file,
)

SUBMANIFEST_FMT = "manifest.host{:03d}.json"
SUBMANIFEST_PREFIX = "manifest.host"


class MultihostCommitError(CheckpointError):
    """The commit barrier refused: one or more hosts never produced a
    consistent sub-manifest (died, stale attempt, crc mismatch).
    `unready` maps host id -> human-readable reason."""

    def __init__(self, msg: str, unready: Optional[Dict[int, str]] = None):
        super().__init__(msg)
        self.unready = dict(unready or {})


def submanifest_path(directory_or_step_dir: str, host: int) -> str:
    return os.path.join(directory_or_step_dir, SUBMANIFEST_FMT.format(host))


def local_ranks(process_id: int, num_processes: int,
                num_shards: int) -> List[int]:
    """The contiguous block of global dp ranks host `process_id` owns
    (the placement `jax.distributed` gives a homogeneous fleet).  When
    num_shards doesn't divide evenly the first hosts take the extras."""
    if not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} outside [0, {num_processes})")
    base, extra = divmod(num_shards, num_processes)
    counts = [base + (1 if p < extra else 0)
              for p in range(num_processes)]
    start = sum(counts[:process_id])
    return list(range(start, start + counts[process_id]))


# ---------------------------------------------------------------------------
# per-host write side
# ---------------------------------------------------------------------------

def write_host_shards(d: str, step: int, fields: Dict[str, tuple], *,
                      host: int, num_processes: int, attempt: int = 0,
                      flat_layout: Optional[dict] = None) -> dict:
    """Write this host's shard files under `d` and return its
    sub-manifest dict (NOT yet published).

    fields: ``{name: (kind, value)}`` — kind ``"sharded"`` with value a
    ``{global_rank: 1-D host array}`` dict holding only THIS host's
    ranks, or ``"replicated"`` with a single host array (only host 0
    may carry replicated fields — they are rank-0 state).  Reuses the
    single-host chaos points (``ckpt.before_shards`` /
    ``ckpt.mid_shards``) so the kill matrix covers mid-shard-write
    deaths on any host.
    """
    from apex_tpu.checkpoint import chaos

    os.makedirs(d, exist_ok=True)
    sub = {
        "ckpt_schema_version": CKPT_SCHEMA_VERSION,
        "step": int(step),
        "host": int(host),
        "num_processes": int(num_processes),
        "attempt": int(attempt),
        "created_unix": time.time(),
        "fields": {},
        "flat_layout": flat_layout,
    }
    chaos.check("ckpt.before_shards")
    for name, (kind, value) in fields.items():
        if kind not in ("sharded", "replicated"):
            raise ValueError(f"field {name!r}: kind must be 'sharded' or "
                             f"'replicated', got {kind!r}")
        if kind == "replicated" and host != 0:
            raise ValueError(
                f"field {name!r}: replicated fields are rank-0 state and "
                f"may only be written by host 0, not host {host}")
        if kind == "sharded":
            items = sorted((int(r), np.asarray(a))
                           for r, a in dict(value).items())
        else:
            items = [(0, np.asarray(value))]
        if not items:
            raise ValueError(f"field {name!r}: host {host} has no ranks "
                             "to write (empty shard dict)")
        entry = {"kind": kind, "dtype": str(items[0][1].dtype),
                 "shapes": [], "files": []}
        for r, a in items:
            fe, shape = write_rank_file(d, name, kind, r, a,
                                        expect_dtype=entry["dtype"])
            entry["shapes"].append(shape)
            entry["files"].append(fe)
            chaos.check("ckpt.mid_shards")
        sub["fields"][name] = entry
    return sub


def publish_submanifest(d: str, sub: dict) -> str:
    """Atomically publish a host's sub-manifest (tmp + ``os.replace``) —
    the per-host half-commit the barrier waits on.  A host killed
    before this point contributes nothing but overwritable orphan
    files."""
    from apex_tpu.checkpoint import chaos

    chaos.check("host.before_submanifest")
    path = submanifest_path(d, sub["host"])
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(sub, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)
    return path


# ---------------------------------------------------------------------------
# process-0 commit barrier
# ---------------------------------------------------------------------------

def _read_submanifest(d: str, host: int) -> Optional[dict]:
    p = submanifest_path(d, host)
    if not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except ValueError:
        return None  # mid-replace on a non-atomic store: poll again


def _check_sub(d: str, sub: Optional[dict], *, step: int,
               attempt: int) -> Optional[str]:
    """None when `sub` is consistent and fully on disk; otherwise the
    human-readable not-ready reason the barrier reports."""
    if sub is None:
        return "no sub-manifest published"
    if sub.get("step") != int(step):
        return f"sub-manifest is for step {sub.get('step')}, not {step}"
    if sub.get("attempt") != int(attempt):
        return (f"sub-manifest attempt {sub.get('attempt')} != {attempt} "
                "(stale attempt — bump the attempt token on retries)")
    for name, e in sub.get("fields", {}).items():
        for fe in e["files"]:
            fp = os.path.join(d, fe["file"])
            if not os.path.exists(fp):
                return f"{fe['file']} missing"
            if os.path.getsize(fp) != fe["bytes"]:
                return f"{fe['file']} size mismatch (write in flight?)"
            with open(fp, "rb") as fh:
                if _crc(fh.read()) != fe["crc32"]:
                    return f"{fe['file']} crc mismatch"
    return None


def gather_submanifests(d: str, num_processes: int, *, step: int,
                        attempt: int = 0, timeout_s: float = 120.0,
                        poll_s: float = 0.05) -> List[dict]:
    """Process 0's barrier wait: poll until EVERY host's sub-manifest is
    present, matches (step, attempt), and crc-verifies — or raise
    `MultihostCommitError` naming each unready host after `timeout_s`.
    A crc/size mismatch is 'not ready yet' (the host may still be
    writing), never an instant failure; only the deadline turns it into
    a refusal.  A host verified once stays verified — the poll loop
    never re-reads an already-checksummed host's payload, so waiting on
    one slow host doesn't turn the barrier into an O(polls × fleet
    bytes) read storm over the shared store."""
    deadline = time.monotonic() + timeout_s
    ready: Dict[int, dict] = {}
    while True:
        unready = {}
        for h in range(num_processes):
            if h in ready:
                continue
            sub = _read_submanifest(d, h)
            why = _check_sub(d, sub, step=step, attempt=attempt)
            if why is None:
                ready[h] = sub
            else:
                unready[h] = why
        if not unready:
            return [ready[h] for h in range(num_processes)]
        if time.monotonic() >= deadline:
            raise MultihostCommitError(
                f"commit barrier for step {step} (attempt {attempt}) "
                f"timed out after {timeout_s:.1f}s — refusing to commit; "
                "unready hosts: " + "; ".join(
                    f"host {h}: {why}" for h, why in sorted(unready.items())),
                unready=unready)
        time.sleep(poll_s)


def merge_submanifests(subs: Sequence[dict], *, step: int,
                       num_shards: Optional[int] = None,
                       flat_layout: Optional[dict] = None,
                       scaler: Optional[dict] = None,
                       tuner_fingerprint: Optional[str] = None,
                       extra: Optional[dict] = None) -> dict:
    """Merge per-host sub-manifests into the ordinary GLOBAL manifest
    (single-host schema — `validate_manifest`-clean).  Validates exact
    rank coverage: every sharded field must assemble ranks
    ``0..num_shards-1`` with no gap and no duplicate claim."""
    if not subs:
        raise MultihostCommitError("no sub-manifests to merge")
    if flat_layout is None:
        for s in subs:
            if s.get("flat_layout"):
                flat_layout = s["flat_layout"]
                break
    for s in subs:
        sl = s.get("flat_layout")
        if sl and flat_layout and sl != flat_layout:
            raise MultihostCommitError(
                f"host {s.get('host')} recorded a different flat_layout "
                "than host 0 — the fleet is not running one optimizer "
                "configuration; refusing to commit")
    if num_shards is None and flat_layout:
        num_shards = int(flat_layout.get("num_shards", 0)) or None

    fields: Dict[str, dict] = {}
    total = 0
    for s in sorted(subs, key=lambda x: x.get("host", 0)):
        for name, e in s["fields"].items():
            tgt = fields.setdefault(
                name, {"kind": e["kind"], "dtype": e["dtype"],
                       "by_rank": {}})
            if tgt["kind"] != e["kind"] or tgt["dtype"] != e["dtype"]:
                raise MultihostCommitError(
                    f"field {name!r}: host {s.get('host')} wrote kind/"
                    f"dtype {e['kind']}/{e['dtype']}, another host wrote "
                    f"{tgt['kind']}/{tgt['dtype']} — refusing to commit")
            for fe, shape in zip(e["files"], e["shapes"]):
                r = int(fe["rank"])
                if r in tgt["by_rank"]:
                    raise MultihostCommitError(
                        f"field {name!r}: rank {r} written by two hosts "
                        "— overlapping local_ranks; refusing to commit")
                tgt["by_rank"][r] = (fe, shape)
                total += int(fe["bytes"])

    out_fields: Dict[str, dict] = {}
    for name, tgt in fields.items():
        ranks = sorted(tgt["by_rank"])
        if tgt["kind"] == "sharded":
            if not num_shards:
                # guessing n from the highest rank seen would commit a
                # missing-TAIL-rank torn fleet as "complete" — refuse
                raise MultihostCommitError(
                    f"field {name!r}: cannot validate rank coverage "
                    "without the expected shard count — pass "
                    "num_shards or a flat_layout; refusing to commit")
            n = num_shards
            missing = sorted(set(range(n)) - set(ranks))
            if missing or ranks != list(range(n)):
                raise MultihostCommitError(
                    f"field {name!r}: rank coverage {ranks} does not "
                    f"assemble 0..{n - 1}"
                    + (f" (missing {missing})" if missing else "")
                    + " — refusing to commit")
            n_files = n
        else:
            if ranks != [0]:
                raise MultihostCommitError(
                    f"replicated field {name!r} has rank entries {ranks}")
            n_files = 1
        out_fields[name] = {
            "kind": tgt["kind"], "dtype": tgt["dtype"],
            "num_shards": n_files,
            "shapes": [tgt["by_rank"][r][1] for r in ranks],
            "files": [tgt["by_rank"][r][0] for r in ranks],
        }

    return {
        "ckpt_schema_version": CKPT_SCHEMA_VERSION,
        "step": int(step),
        "created_unix": time.time(),
        "fields": out_fields,
        "flat_layout": flat_layout,
        "scaler": scaler,
        "tuner_fingerprint": tuner_fingerprint,
        "extra": extra or {},
        "total_bytes": total,
        "multihost": {"num_processes": len(subs),
                      "hosts": sorted(int(s.get("host", 0)) for s in subs)},
    }


def commit_global_manifest(d: str, manifest: dict) -> str:
    """The global atomic barrier: rename the merged manifest into place.
    ``host.before_barrier`` armed here kills process 0 with every
    host's data on disk but NO commit — the step must stay invisible."""
    from apex_tpu.checkpoint import chaos
    from apex_tpu.checkpoint.sharded import validate_manifest

    validate_manifest(manifest)
    chaos.check("host.before_barrier")
    tmp = os.path.join(d, MANIFEST + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, os.path.join(d, MANIFEST))  # <-- the commit
    return os.path.join(d, MANIFEST)


# ---------------------------------------------------------------------------
# the one-call surface the manager uses
# ---------------------------------------------------------------------------

def save_sharded_multihost(
        directory: str, step: int, fields: Dict[str, tuple], *,
        process_id: int, num_processes: int, attempt: int = 0,
        flat_layout: Optional[dict] = None, scaler: Optional[dict] = None,
        tuner_fingerprint: Optional[str] = None, extra: Optional[dict] = None,
        timeout_s: float = 120.0, poll_s: float = 0.05,
) -> Tuple[Optional[str], float]:
    """This host's half of one multi-host commit.

    Every process calls this with its LOCAL fields (sharded values as
    ``{global_rank: array}`` dicts; replicated fields only on process
    0).  Non-zero processes write shards + sub-manifest and return
    immediately with ``(None, 0.0)`` — they never wait on the barrier.
    Process 0 writes its own files, waits for every host, merges, and
    commits; it returns ``(committed_step_dir, barrier_wait_seconds)``.
    The barrier wait is the `ckpt_commit_barrier_s` telemetry stamp.

    Overwriting an already-committed step is refused: the single-host
    staged-swap overwrite cannot be made kill-anywhere-safe when N
    uncoordinated hosts would each need to observe the swap atomically.
    Fleet orchestration numbers saves past the restored step instead
    (the PR 9 `train_with_monitor --resume` rule).
    """
    if not 0 <= process_id < num_processes:
        raise ValueError(
            f"process_id {process_id} outside [0, {num_processes})")
    d = step_dir(directory, step)
    if os.path.exists(os.path.join(d, MANIFEST)):
        raise CheckpointError(
            f"{d} already holds a COMMITTED checkpoint; multi-host "
            "overwrite is unsupported — number saves past the restored "
            "step (or prune) instead")
    sub = write_host_shards(
        d, step, fields, host=process_id, num_processes=num_processes,
        attempt=attempt, flat_layout=flat_layout)
    publish_submanifest(d, sub)
    if process_id != 0:
        return None, 0.0
    t0 = time.monotonic()
    subs = gather_submanifests(d, num_processes, step=step,
                               attempt=attempt, timeout_s=timeout_s,
                               poll_s=poll_s)
    barrier_s = time.monotonic() - t0
    manifest = merge_submanifests(
        subs, step=step, flat_layout=flat_layout, scaler=scaler,
        tuner_fingerprint=tuner_fingerprint, extra=extra)
    commit_global_manifest(d, manifest)
    return d, barrier_s
