"""Fault injection for the checkpoint/restore path (ISSUE 9 layer 3).

Production preemptions are not polite: the SIGKILL lands mid-shard-file,
mid-manifest, or mid-step, and a flaky host makes one dp rank silently
slow instead of dead.  This module makes every one of those a
REPRODUCIBLE test:

* **Fail points** — `arm("ckpt.mid_shards", count=2)` makes the writer
  raise `SimulatedPreemption` at its 2nd named checkpoint inside
  `sharded.save_sharded` (points: ``ckpt.before_shards``,
  ``ckpt.mid_shards`` — checked after EVERY shard file,
  ``ckpt.before_manifest``).  Because the manifest rename is the commit,
  any of these leaves the directory unloadable and the PREVIOUS commit
  the resume point — which is exactly what the chaos tests assert.
* **Host-side corruption** — `truncate_shard` / `delete_shard` /
  `corrupt_manifest` damage an already-committed checkpoint the way a
  dying disk or a half-synced object store does; `verify_shards` must
  then refuse it with the missing ranks named.
* **`resume_guard`** — `FlightRecorder.guard()` with the resume point in
  the story: any exception dumps a crash report whose reason names the
  LAST COMMITTED step (no recorder schema change — the resume point
  rides in the reason string the renderer already prints).
* **`LostRankWatchdog`** — the PR-4 straggler detector's persistent
  flag, escalated: a rank past `deadline` consecutive outlier steps
  raises `RankLostError` (naming the rank, its skew, and the last
  committed step) instead of letting the next collective hang forever.
  Run the loop under `resume_guard` and a lost rank produces a crash
  dump + a clean resume point, the veScale fault-tolerance posture.
"""

from __future__ import annotations

import contextlib
import json
import os
from typing import Dict, Optional

from apex_tpu.checkpoint.sharded import MANIFEST


class SimulatedPreemption(RuntimeError):
    """Raised by an armed fail point — stands in for the SIGKILL."""


class RankLostError(RuntimeError):
    """A dp rank is declared lost/stalled by the watchdog.  Carries the
    structured fields the elastic orchestrator needs to pick the
    surviving topology: `rank` (which one died) and `last_committed`
    (the resume point, None when nothing ever committed)."""

    def __init__(self, msg: str, rank: Optional[int] = None,
                 last_committed: Optional[int] = None):
        super().__init__(msg)
        self.rank = rank
        self.last_committed = last_committed


_ARMED: Dict[str, int] = {}

# the single-host writer's checked points — every one of these fires
# inside `sharded.save_sharded` (the kill-matrix loops iterate them)
CKPT_POINTS = ("ckpt.before_shards", "ckpt.mid_shards",
               "ckpt.before_manifest")
# multi-host fail points (ISSUE 11): a host dying before it publishes
# its per-host sub-manifest, process 0 dying before the global
# manifest barrier, and a rank dying mid-training-step.  Checked by
# `multihost.py`'s writer and the fleet workers' step loops (the
# ckpt.* shard-write points fire inside the multi-host writer too).
HOST_POINTS = ("host.before_submanifest", "host.before_barrier",
               "rank.lost_at_step")
# serving-plane fail points (ISSUE 14).  `serve.kill_mid_drain` is a
# classic raise-style kill (checked by `DecodeEngine.drain`'s loop);
# the other two are INJECTION points consumed via `fire()` — their
# failure mode is not a process death but a wedged device
# (`serve.stall_step`: the engine stops making retire-poll progress,
# the EngineWatchdog's prey) or corrupted decode output
# (`serve.poison_logits`: garbage token ids the retire poll's validity
# guard must catch).  scripts/serve_chaos_probe.py iterates them.
SERVE_POINTS = ("serve.stall_step", "serve.poison_logits",
                "serve.kill_mid_drain")
POINTS = CKPT_POINTS + HOST_POINTS + SERVE_POINTS  # all arm() accepts

# Cross-process arming (the fleet probe's kill switch): the LAUNCHER
# can't call arm() inside a child, so children read these env vars.
# APEX_TPU_CHAOS        "point:count[,point:count...]"
# APEX_TPU_CHAOS_PROC   arm only in the child whose
#                       APEX_TPU_PROCESS_ID matches (absent = all)
ENV_VAR = "APEX_TPU_CHAOS"
ENV_PROC_VAR = "APEX_TPU_CHAOS_PROC"


def arm_from_env(environ=None, var: str = ENV_VAR) -> list:
    """Arm fail points named by ``APEX_TPU_CHAOS`` (workers call this
    once at startup).  Honors ``APEX_TPU_CHAOS_PROC``: when set, only
    the child whose ``APEX_TPU_PROCESS_ID`` matches arms anything — the
    fleet probe's way of killing ONE specific host.  `var` reads the
    spec from a different variable (the probe stages save-time kills
    under ``APEX_TPU_CHAOS_SAVE`` so the commit of an EARLIER step
    isn't the one that fires).  Returns the (point, count) list
    actually armed."""
    env = os.environ if environ is None else environ
    spec = env.get(var, "").strip()
    if not spec:
        return []
    target = env.get(ENV_PROC_VAR, "").strip()
    if target and env.get("APEX_TPU_PROCESS_ID", "").strip() != target:
        return []
    armed = []
    for item in spec.split(","):
        item = item.strip()
        if not item:
            continue
        point, sep, count = item.partition(":")
        n = int(count) if sep else 1
        arm(point, n)
        armed.append((point, n))
    return armed


def arm(point: str, count: int = 1) -> None:
    """Arm `point` to fire on its `count`-th check (count=1: the next)."""
    if point not in POINTS:
        raise ValueError(f"unknown fail point {point!r}; choices: {POINTS}")
    if count < 1:
        raise ValueError(f"count must be >= 1, got {count}")
    _ARMED[point] = count


def disarm_all() -> None:
    _ARMED.clear()


def check(point: str) -> None:
    """Called by the checkpoint writer at its named points; raises when
    the countdown armed for `point` reaches zero.  A no-op (one dict
    lookup) when nothing is armed — the production path pays nothing."""
    n = _ARMED.get(point)
    if n is None:
        return
    if n <= 1:
        _ARMED.pop(point, None)
        raise SimulatedPreemption(f"simulated preemption at {point}")
    _ARMED[point] = n - 1


def fire(point: str) -> bool:
    """Like `check()` but RETURNS True instead of raising — for fail
    points whose effect is an injected corruption or stall rather than
    a process death (the `serve.stall_step` / `serve.poison_logits`
    points: the injection site flips its own behavior when the
    countdown lands, and the failure is then DETECTED downstream by
    the watchdog / validity guard under test).  Same countdown
    semantics as `check()`; a no-op dict lookup when nothing is armed."""
    n = _ARMED.get(point)
    if n is None:
        return False
    if n <= 1:
        _ARMED.pop(point, None)
        return True
    _ARMED[point] = n - 1
    return False


@contextlib.contextmanager
def preempt_at(point: str, count: int = 1):
    """Scoped arming: the fail point is disarmed on exit even when the
    body died somewhere else first."""
    arm(point, count)
    try:
        yield
    finally:
        _ARMED.pop(point, None)


# ---------------------------------------------------------------------------
# host-side corruption of a COMMITTED checkpoint
# ---------------------------------------------------------------------------

def _shard_file(path: str, field: str, rank: int) -> str:
    m_path = os.path.join(path, MANIFEST)
    with open(m_path) as f:
        m = json.load(f)
    entry = m["fields"][field]
    for fe in entry["files"]:
        if fe["rank"] == rank:
            return os.path.join(path, fe["file"])
    raise ValueError(f"field {field!r} has no rank {rank}")


def truncate_shard(path: str, field: str, rank: int = 0,
                   keep_bytes: int = 7) -> str:
    """Chop a committed shard file down to `keep_bytes` — the
    half-synced-disk failure.  Returns the damaged file's path."""
    fp = _shard_file(path, field, rank)
    with open(fp, "rb") as f:
        head = f.read(keep_bytes)
    with open(fp, "wb") as f:
        f.write(head)
    return fp


def delete_shard(path: str, field: str, rank: int = 0) -> str:
    fp = _shard_file(path, field, rank)
    os.remove(fp)
    return fp


def corrupt_manifest(path: str, mode: str = "truncate") -> str:
    """Damage the manifest itself: ``truncate`` chops its JSON mid-byte
    (an interrupted overwrite), ``stale`` rewrites it to reference a
    shard file that no longer exists (manifest and data out of sync).
    Either way `read_manifest`/`verify_shards` must refuse loudly."""
    mf = os.path.join(path, MANIFEST)
    if mode == "truncate":
        with open(mf, "rb") as f:
            raw = f.read()
        with open(mf, "wb") as f:
            f.write(raw[: max(1, len(raw) // 2)])
    elif mode == "stale":
        with open(mf) as f:
            m = json.load(f)
        first = next(iter(m["fields"]))
        m["fields"][first]["files"][0]["file"] = "gone.rank000.bin"
        with open(mf, "w") as f:
            json.dump(m, f)
    else:
        raise ValueError(f"mode must be 'truncate' or 'stale', got {mode!r}")
    return mf


# ---------------------------------------------------------------------------
# crash-dump wiring (PR-4 flight recorder + straggler detector)
# ---------------------------------------------------------------------------

@contextlib.contextmanager
def resume_guard(recorder, manager):
    """`FlightRecorder.guard()` that names the resume point: on ANY
    exception (a real crash, a `SimulatedPreemption`, a
    `RankLostError`) the flight report's reason carries the last
    COMMITTED checkpoint step, so the operator reading the dump knows
    where `restore()` will land WITHOUT trusting the dying process.
    No recorder schema change — the resume point rides in the reason
    string `scripts/flight_report.py` already renders."""
    import apex_tpu.monitor.compile.watermarks as wm

    try:
        yield recorder
    except BaseException as e:
        last = manager.last_committed_step if manager is not None else None
        where = (f"step {last}" if last is not None
                 else "NONE COMMITTED — restart from scratch")
        recorder.dump(
            reason=f"exception: {e!r}; last committed checkpoint: {where}",
            oom=wm.is_oom(e))
        raise


class LostRankWatchdog:
    """Escalates the PR-4 `StragglerDetector`'s persistent flag into a
    loud, dump-carrying failure instead of a collective hang.

    Feed it each step's gathered (n_ranks, k) timing matrix (or call
    `check()` after updating a shared detector yourself).  Once any
    rank has been an outlier for `deadline` CONSECUTIVE steps it raises
    `RankLostError` naming the rank, its skew, and — when a manager is
    attached — the last committed checkpoint step.  Under
    `resume_guard` that exception becomes a crash dump whose reason IS
    the resume runbook.

    Flap recovery: a rank that RECOVERS (its skew drops back below the
    detector's threshold for a step) resets to zero consecutive flags —
    it is never left one slow step away from a spurious
    `RankLostError`.  `check()` only judges each detector summary ONCE
    (keyed on its step index): re-checking between updates — a loop
    that polls the watchdog more often than it folds timings — can
    neither re-raise on stale data nor double-count.  `reset()` clears
    the detector for an elastic topology change (the orchestrator calls
    it on rebuild: rank counts legitimately change at dp=N→M and the
    detector otherwise refuses a mid-run rank-count change)."""

    def __init__(self, straggler, manager=None, deadline: int = 10):
        if deadline < 1:
            raise ValueError(f"deadline must be >= 1, got {deadline}")
        self.straggler = straggler
        self.manager = manager
        self.deadline = deadline
        self._judged_step: Optional[int] = None

    def reset(self) -> None:
        """Forget all flap history — the elastic-resume rebuild hook."""
        self._judged_step = None
        if hasattr(self.straggler, "reset"):
            self.straggler.reset()

    def check(self, timings=None) -> Optional[dict]:
        """Fold `timings` (when given) and raise if any rank crossed the
        deadline; returns the straggler's last summary otherwise."""
        if timings is not None:
            self.straggler.update(timings)
        last = self.straggler.last
        if not last:
            return None
        if last.get("step_index") == self._judged_step:
            return last  # already judged this summary — stale re-check
        self._judged_step = last.get("step_index")
        for f in last["flagged"]:
            if f["consecutive"] >= self.deadline:
                lc = (self.manager.last_committed_step
                      if self.manager is not None else None)
                where = (f"step {lc}" if lc is not None
                         else "none committed")
                raise RankLostError(
                    f"rank {f['rank']} lost/stalled: {f['consecutive']} "
                    f"consecutive steps beyond "
                    f"{self.straggler.threshold}x the median (skew "
                    f"{f['skew']:.2f}); resume from last committed "
                    f"checkpoint: {where}",
                    rank=int(f["rank"]), last_committed=lc)
        return last
