"""Checkpoint / resume.

≡ the reference's checkpoint surface (SURVEY §5.4): amp.state_dict
(apex/amp/frontend.py:365-404 — apex_tpu.amp.state_dict),
FP16_Optimizer.state_dict (fp16_utils/fp16_optimizer.py —
amp/fp16_optimizer.py), and model/optimizer persistence which the
reference leaves to user scripts (examples/imagenet/main_amp.py save
path).  Here it is first-class: orbax-backed sharded save/restore of
arbitrary pytrees (params, optimizer flat buffers, scaler state), with
a numpy fallback when orbax is unavailable.

ISSUE 9 moved this surface into the `apex_tpu.checkpoint` package
(imports are unchanged); the shard-native async format lives in
`checkpoint.sharded` / `checkpoint.manager`.  `load_checkpoint` now
recognizes that format too: a manifest directory is validated for
shard COMPLETENESS (existence, sizes, checksums) before anything
deserializes, so a truncated or missing shard raises
`IncompleteCheckpointError` naming the missing ranks — and a short
pickle raises a named CheckpointError — instead of the opaque
deserialization tracebacks both used to surface as.
"""

from __future__ import annotations

import os
import pickle
import warnings
from typing import Any, Optional

import jax
import numpy as np

_ORBAX_HINT = ("orbax is not installed — install the checkpoint extra "
               "(`pip install orbax-checkpoint`) for sharded "
               "checkpoints")


def _to_host(tree):
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def save_checkpoint(path: str, tree, step: Optional[int] = None,
                    use_orbax: bool = True) -> str:
    """Save a pytree; returns the directory written.

    With orbax available the state is written through
    `PyTreeCheckpointer`; a MISSING orbax degrades to the pickle
    fallback with a one-time warning naming the extra (it used to
    degrade silently — an operator who thought they had sharded
    checkpoints found out at restore time).  A real orbax save error
    (disk full, bad tree) raises — it must not be laundered into a
    silent format downgrade."""
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    os.makedirs(path, exist_ok=True)
    if use_orbax:
        try:
            import orbax.checkpoint as ocp
        except ImportError:
            warnings.warn(
                f"save_checkpoint: {_ORBAX_HINT}; falling back to the "
                "single-file pickle format", stacklevel=2)
        else:
            ckpt = ocp.PyTreeCheckpointer()
            ckpt.save(os.path.join(path, "state"), _to_host(tree),
                      force=True)
            return path
    with open(os.path.join(path, "state.pkl"), "wb") as f:
        pickle.dump(_to_host(tree), f)
    return path


def load_checkpoint(path: str, step: Optional[int] = None,
                    target: Any = None):
    """Restore a pytree saved by save_checkpoint.

    A checkpoint written in the orbax layout NEEDS orbax to read —
    there is no pickle to fall back to, so a missing install raises an
    ImportError that names the extra instead of the bare module-level
    one.

    A directory in the `checkpoint.sharded` manifest layout is
    validated for shard completeness FIRST (`verify_shards` — a
    missing/truncated shard raises IncompleteCheckpointError listing
    the missing ranks) and returns the host-side field dict
    ({name: array | [per-rank arrays]}); optimizer-state re-layout
    goes through `checkpoint.restore_sharded` instead."""
    path = os.path.abspath(path)
    if step is not None:
        path = os.path.join(path, f"step_{step}")
    from apex_tpu.checkpoint import sharded as _sh
    if os.path.exists(os.path.join(path, _sh.MANIFEST)):
        if target is not None:
            raise ValueError(
                "load_checkpoint(target=...) is not supported for a "
                "sharded-manifest checkpoint — the field dict has no "
                "single pytree structure to unflatten into; restore "
                "optimizer state through checkpoint.restore_sharded "
                "(which re-lays shards for the target optimizer)")
        manifest = _sh.read_manifest(path)
        # completeness swept cheaply; content crc rides the SAME read
        # that deserializes (no second pass over the payload)
        _sh.verify_shards(path, manifest, crc=False)
        return {name: _sh.load_field_host(path, manifest, name,
                                          check_crc=True)
                for name in manifest["fields"]}
    orbax_path = os.path.join(path, "state")
    if os.path.exists(orbax_path):
        try:
            import orbax.checkpoint as ocp
        except ImportError as e:
            raise ImportError(
                f"load_checkpoint: {orbax_path} is an orbax-format "
                f"checkpoint but {_ORBAX_HINT}") from e
        ckpt = ocp.PyTreeCheckpointer()
        restored = ckpt.restore(orbax_path)
        if target is not None:
            restored = jax.tree_util.tree_unflatten(
                jax.tree_util.tree_structure(target),
                jax.tree_util.tree_leaves(restored))
        return restored
    pkl = os.path.join(path, "state.pkl")
    try:
        with open(pkl, "rb") as f:
            return pickle.load(f)
    except (EOFError, pickle.UnpicklingError) as e:
        from apex_tpu.checkpoint.sharded import CheckpointError
        raise CheckpointError(
            f"{pkl} is truncated or corrupt "
            f"({os.path.getsize(pkl)} bytes): {e!r} — the save was "
            "likely killed mid-write; the sharded format "
            "(checkpoint.CheckpointManager) commits atomically and "
            "names damaged shards instead") from e


def latest_step(path: str) -> Optional[int]:
    """Find the newest step_N under path (auto-resume helper ≡ the
    reference's get_autoresume hook, pipeline_parallel/utils.py:142)."""
    if not os.path.isdir(path):
        return None
    steps = []
    for d in os.listdir(path):
        if d.startswith("step_"):
            try:
                steps.append(int(d[5:]))
            except ValueError:
                pass
    return max(steps) if steps else None
