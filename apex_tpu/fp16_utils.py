"""apex_tpu.fp16_utils — manual fp16/bf16 master-weight tooling.

≡ apex.fp16_utils (apex/fp16_utils/__init__.py): the older, explicit
mixed-precision workflow — convert a network to half keeping norms fp32,
keep fp32 master params, copy grads/params between the two, wrap the
optimizer, scale losses.  In apex_tpu the mechanisms live in
`apex_tpu.amp` (pure-functional policies and scaler states); this module
re-exports them under the reference names so reference users find the
same surface:

  network_to_half / convert_network  ≡ fp16util.py:35-72
  prep_param_lists                   ≡ fp16util.py:92
  model_grads_to_master_grads        ≡ fp16util.py:138
  master_params_to_model_params      ≡ fp16util.py:160
  FP16_Optimizer                     ≡ fp16_optimizer.py:13
  LossScaler / DynamicLossScaler     ≡ loss_scaler.py:10,49
"""

from __future__ import annotations

import jax.numpy as jnp

from apex_tpu.amp import scaler as _scaler
from apex_tpu.amp.fp16_optimizer import FP16_Optimizer
from apex_tpu.amp.policy import (
    convert_network,
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
)

__all__ = [
    "network_to_half", "convert_network", "prep_param_lists",
    "model_grads_to_master_grads", "master_params_to_model_params",
    "FP16_Optimizer", "LossScaler", "DynamicLossScaler", "to_python_float",
]


def network_to_half(params, dtype=jnp.float16):
    """≡ network_to_half (apex/fp16_utils/fp16util.py:35-44): cast every
    floating leaf to half, keeping norm/BN params fp32 (the reference
    wraps BN modules in `tofp16`-exempt shells; here norm leaves are
    recognized by name in convert_network)."""
    return convert_network(params, dtype)


def to_python_float(x):
    """≡ to_python_float (apex/fp16_utils/fp16util.py): host scalar."""
    try:
        return float(x)
    except TypeError:
        return float(jnp.asarray(x).reshape(()))


class LossScaler:
    """Static loss scaler ≡ apex/fp16_utils/loss_scaler.py:10-46, as a
    thin OO facade over the functional apex_tpu.amp.scaler state."""

    dynamic = False

    def __init__(self, scale=1.0):
        self.state = _scaler.init(float(scale))

    @property
    def loss_scale(self):
        return float(self.state.scale)

    def scale_loss(self, loss):
        return _scaler.scale_loss(self.state, loss)

    def unscale(self, grads):
        return _scaler.unscale(self.state, grads)

    def update_scale(self, overflow):
        self.state = _scaler.update(self.state, overflow,
                                    dynamic=self.dynamic)


class DynamicLossScaler(LossScaler):
    """≡ apex/fp16_utils/loss_scaler.py:49-118: grow scale on a run of
    finite steps, halve on overflow."""

    dynamic = True

    def __init__(self, init_scale=2.0 ** 32, scale_factor=2.0,
                 scale_window=1000):
        self.state = _scaler.init("dynamic", init_scale=float(init_scale))
        self.scale_factor = scale_factor
        self.scale_window = scale_window

    def update_scale(self, overflow):
        self.state = _scaler.update(
            self.state, overflow, dynamic=True,
            growth_interval=self.scale_window,
            growth_factor=self.scale_factor,
            backoff_factor=1.0 / self.scale_factor)
