"""apex_tpu.rnn — low-precision-friendly RNN/LSTM/GRU/mLSTM.

≡ apex.RNN (apex/RNN/models.py:21-49, RNNBackend.py:25-232): a pure
re-implementation of the cuDNN RNN zoo whose point was fp16 safety
(explicit cell math instead of opaque cuDNN calls).  TPU version: cells
as `lax.scan` bodies — XLA fuses the gate math and the scan keeps
everything on-device; bf16-safe by construction (fp32 cell state).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def _linear_init(key, shape, dtype):
    bound = 1.0 / math.sqrt(shape[0])
    return jax.random.uniform(key, shape, dtype, -bound, bound)


class _RNNBase:
    """Common init/apply over a cell ≡ RNNBackend.RNNCell/stackedRNN."""

    gate_mult = 1

    def __init__(self, input_size, hidden_size, num_layers=1,
                 bidirectional=False):
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.bidirectional = bidirectional

    def init(self, key, dtype=jnp.float32):
        params = []
        n_dir = 2 if self.bidirectional else 1
        for layer in range(self.num_layers):
            for d in range(n_dir):
                key, k1, k2, k3, k4 = jax.random.split(key, 5)
                in_dim = self.input_size if layer == 0 \
                    else self.hidden_size * n_dir
                g = self.gate_mult * self.hidden_size
                params.append({
                    "w_ih": _linear_init(k1, (in_dim, g), dtype),
                    "w_hh": _linear_init(k2, (self.hidden_size, g), dtype),
                    "b_ih": jnp.zeros((g,), dtype),
                    "b_hh": jnp.zeros((g,), dtype),
                })
        return params

    def _cell(self, p, x_t, state):
        raise NotImplementedError

    def _init_state(self, batch):
        return jnp.zeros((batch, self.hidden_size), jnp.float32)

    def _run_dir(self, p, xs, reverse=False):
        batch = xs.shape[1]
        state0 = self._init_state(batch)

        def step(state, x_t):
            new_state, out = self._cell(p, x_t, state)
            return new_state, out

        _, outs = lax.scan(step, state0, xs, reverse=reverse)
        return outs

    def apply(self, params, x):
        """x: (S, B, input_size) → (S, B, H * n_dir)."""
        n_dir = 2 if self.bidirectional else 1
        h = x
        for layer in range(self.num_layers):
            outs = []
            for d in range(n_dir):
                p = params[layer * n_dir + d]
                outs.append(self._run_dir(p, h, reverse=(d == 1)))
            h = jnp.concatenate(outs, axis=-1) if n_dir == 2 else outs[0]
        return h


class RNNReLU(_RNNBase):
    """≡ apex.RNN.ReLU (models.py)."""

    def _cell(self, p, x_t, h):
        g = x_t @ p["w_ih"] + p["b_ih"] + h.astype(x_t.dtype) @ p["w_hh"] \
            + p["b_hh"]
        h_new = jnp.maximum(g.astype(jnp.float32), 0)
        return h_new, h_new.astype(x_t.dtype)


class RNNTanh(_RNNBase):
    """≡ apex.RNN.Tanh."""

    def _cell(self, p, x_t, h):
        g = x_t @ p["w_ih"] + p["b_ih"] + h.astype(x_t.dtype) @ p["w_hh"] \
            + p["b_hh"]
        h_new = jnp.tanh(g.astype(jnp.float32))
        return h_new, h_new.astype(x_t.dtype)


class LSTM(_RNNBase):
    """≡ apex.RNN.LSTM (models.py:21)."""

    gate_mult = 4

    def _init_state(self, batch):
        z = jnp.zeros((batch, self.hidden_size), jnp.float32)
        return (z, z)

    def _cell(self, p, x_t, state):
        h, c = state
        g = (x_t @ p["w_ih"] + p["b_ih"]
             + h.astype(x_t.dtype) @ p["w_hh"] + p["b_hh"]
             ).astype(jnp.float32)
        i, f, gg, o = jnp.split(g, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(gg)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new.astype(x_t.dtype)


class GRU(_RNNBase):
    """≡ apex.RNN.GRU."""

    gate_mult = 3

    def _cell(self, p, x_t, h):
        gi = (x_t @ p["w_ih"] + p["b_ih"]).astype(jnp.float32)
        gh = (h.astype(x_t.dtype) @ p["w_hh"] + p["b_hh"]
              ).astype(jnp.float32)
        ir, iz, in_ = jnp.split(gi, 3, axis=-1)
        hr, hz, hn = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        n = jnp.tanh(in_ + r * hn)
        h_new = (1 - z) * n + z * h
        return h_new, h_new.astype(x_t.dtype)


class mLSTM(_RNNBase):
    """≡ apex.RNN.mLSTM (multiplicative LSTM, models.py:49 +
    RNNBackend.mLSTMRNNCell): m = (x W_mx) * (h W_mh) modulates the
    hidden state fed to the gates."""

    gate_mult = 4

    def init(self, key, dtype=jnp.float32):
        params = super().init(key, dtype)
        for layer, p in enumerate(params):
            in_dim = self.input_size if layer == 0 else self.hidden_size
            key, k1, k2 = jax.random.split(key, 3)
            p["w_mx"] = _linear_init(k1, (in_dim, self.hidden_size), dtype)
            p["w_mh"] = _linear_init(k2, (self.hidden_size,
                                          self.hidden_size), dtype)
        return params

    def _init_state(self, batch):
        z = jnp.zeros((batch, self.hidden_size), jnp.float32)
        return (z, z)

    def _cell(self, p, x_t, state):
        h, c = state
        m = (x_t @ p["w_mx"]) * (h.astype(x_t.dtype) @ p["w_mh"])
        g = (x_t @ p["w_ih"] + p["b_ih"] + m @ p["w_hh"] + p["b_hh"]
             ).astype(jnp.float32)
        i, f, gg, o = jnp.split(g, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        c_new = f * c + i * jnp.tanh(gg)
        h_new = o * jnp.tanh(c_new)
        return (h_new, c_new), h_new.astype(x_t.dtype)
