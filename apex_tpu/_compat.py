"""Runtime version shims.

The framework targets current jax — `jax.shard_map` at the top level
with the `check_vma` kwarg.  Older runtimes (jax ≤ 0.4.x, e.g. a
CPU-only CI image) still ship shard_map under `jax.experimental` with
the kwarg named `check_rep`.  install() bridges that delta once, at
import time, so every `from jax import shard_map` call site runs
unchanged on both; it is a no-op on current jax.
"""

from __future__ import annotations

import jax


def install():
    from jax import lax

    try:
        from jax.experimental.pallas import tpu as pltpu
        if (not hasattr(pltpu, "CompilerParams")
                and hasattr(pltpu, "TPUCompilerParams")):
            # renamed upstream: TPUCompilerParams (≤ 0.4.x) →
            # CompilerParams; same kwargs (dimension_semantics etc.)
            pltpu.CompilerParams = pltpu.TPUCompilerParams
    except ImportError:  # pallas not available at all — kernels will
        pass             # take their jnp fallback paths anyway

    if not hasattr(lax, "axis_size"):
        from jax._src import core as _core

        def axis_size(axis_name):
            frame = _core.axis_frame(axis_name)
            if isinstance(frame, int):  # 0.4.x returns the size directly
                return frame
            return frame.size  # raise HERE if neither shape fits,
            # not as a confusing type error at the caller

        lax.axis_size = axis_size

    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=True, **kw):
        kw.setdefault("check_rep", check_vma)
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **kw)

    jax.shard_map = shard_map


install()
