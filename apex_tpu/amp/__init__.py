"""apex_tpu.amp — mixed precision: policies + dynamic loss scaling.

≡ apex.amp (apex/amp/frontend.py) + apex.fp16_utils, re-designed for
XLA: no op monkey-patching; an explicit `Policy` applied at call sites,
a pure-functional `LossScaler` state, and master-weight helpers.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp

from apex_tpu.amp import scaler
from apex_tpu.amp.policy import (
    FP32_CLASS_OPS,
    MATMUL_CLASS_OPS,
    Policy,
    convert_network,
    get_policy,
    master_params_to_model_params,
    model_grads_to_master_grads,
    prep_param_lists,
)
from apex_tpu.amp.scaler import LossScalerState

__all__ = [
    "Policy", "get_policy", "initialize", "AmpState", "scaler",
    "LossScalerState", "convert_network", "prep_param_lists",
    "model_grads_to_master_grads", "master_params_to_model_params",
    "MATMUL_CLASS_OPS", "FP32_CLASS_OPS", "state_dict", "load_state_dict",
]


@dataclasses.dataclass
class AmpState:
    """Bundle of policy + per-loss scaler states ≡ _amp_state
    (apex/amp/_amp_state.py:16) minus the global mutability."""

    policy: Policy
    loss_scalers: list  # one LossScalerState per loss (frontend.py:229-233)

    @property
    def dynamic(self) -> bool:
        return self.policy.loss_scale == "dynamic"


def initialize(params=None, opt_level: str = "O1", num_losses: int = 1,
               low_dtype=jnp.bfloat16, **overrides):
    """≡ apex.amp.initialize (apex/amp/frontend.py:197-404).

    Returns (cast_params, AmpState).  O2/O3 cast the param pytree
    (keeping norm params fp32 under O2, ≡ _initialize.py:178-184); O0/O1
    leave params fp32.  `num_losses` scalers are created
    (≡ _initialize.py:229-233).
    """
    policy = get_policy(opt_level, low_dtype=low_dtype, **overrides)
    if params is not None and policy.param_dtype != jnp.float32:
        if policy.keep_norm_fp32:
            params = convert_network(params, policy.param_dtype)
        else:
            params = policy.cast_to_param(params)
    scalers = [scaler.init(policy.loss_scale) for _ in range(num_losses)]
    state = AmpState(policy=policy, loss_scalers=scalers)
    if params is None:
        return state
    return params, state


def scale_loss(state: AmpState, loss, loss_id: int = 0):
    """≡ the `with amp.scale_loss(...)` entry (apex/amp/handle.py:16-113)."""
    return scaler.scale_loss(state.loss_scalers[loss_id], loss)


def unscale_and_update(state: AmpState, grads, loss_id: int = 0):
    """Unscale grads, check overflow, update the scaler state.

    ≡ ctx-manager exit: LossScaler.unscale + update_scale
    (apex/amp/handle.py:118-154, scaler.py:105-217).  Returns
    (unscaled_grads, found_inf, new_state); the caller masks the
    optimizer update with found_inf.
    """
    s = state.loss_scalers[loss_id]
    grads, found_inf = scaler.unscale(s, grads)
    new_s = scaler.update(s, found_inf, dynamic=state.dynamic)
    scalers = list(state.loss_scalers)
    scalers[loss_id] = new_s
    return grads, found_inf, AmpState(policy=state.policy, loss_scalers=scalers)


def state_dict(state: AmpState) -> dict:
    """≡ apex.amp.state_dict (frontend.py:365-384)."""
    return {f"loss_scaler{i}": scaler.state_dict(s)
            for i, s in enumerate(state.loss_scalers)}


def load_state_dict(state: AmpState, d: dict) -> AmpState:
    """≡ apex.amp.load_state_dict (frontend.py:387-404)."""
    scalers = [scaler.load_state_dict(d[f"loss_scaler{i}"])
               for i in range(len(state.loss_scalers))]
    return AmpState(policy=state.policy, loss_scalers=scalers)
