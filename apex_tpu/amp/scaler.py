"""Dynamic loss scaling as a pure function-of-state.

≡ apex.amp.scaler.LossScaler (apex/amp/scaler.py:33-217) and
apex.fp16_utils.loss_scaler.{LossScaler,DynamicLossScaler}
(apex/fp16_utils/loss_scaler.py:10,49).  The reference mutates a Python
object and patches `optimizer.step` to skip on overflow
(apex/amp/handle.py:128-154); under jit that data-dependent skip becomes
a `lax.cond`-free masked update: `update()` runs every step and the
optimizer applies `jnp.where(found_inf, old, new)` (see
optimizers/fused_adam.py), keeping the whole step on-device with no host
sync — the TPU analogue of the reference's "capturable" CUDA-graph mode
(apex/optimizers/fused_adam.py:199-263).

State is a small pytree so it jits, shards, and checkpoints trivially
(state_dict parity: apex/amp/frontend.py:365-404).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


class LossScalerState(NamedTuple):
    scale: jnp.ndarray            # f32 scalar, current loss scale
    growth_tracker: jnp.ndarray   # i32 scalar ≡ _unskipped (scaler.py:44)
    found_inf: jnp.ndarray        # bool scalar, last-step overflow flag


def init(loss_scale="dynamic", init_scale=2.0 ** 16) -> LossScalerState:
    """≡ LossScaler.__init__ (apex/amp/scaler.py:33-60).  A static float
    disables growth/backoff; "dynamic" starts at 2**16."""
    if loss_scale == "dynamic":
        scale = init_scale
    else:
        scale = float(loss_scale) if loss_scale is not None else 1.0
    return LossScalerState(
        scale=jnp.asarray(scale, jnp.float32),
        growth_tracker=jnp.zeros((), jnp.int32),
        found_inf=jnp.zeros((), bool),
    )


def scale_loss(state: LossScalerState, loss):
    """≡ amp.scale_loss ctx manager entry (apex/amp/handle.py:113):
    loss.float() * loss_scale."""
    return loss.astype(jnp.float32) * state.scale


def check_finite(grads) -> jnp.ndarray:
    """Global finite check over a grad pytree ≡ the overflow buffer the
    multi-tensor unscale kernel sets (apex/amp/scaler.py:105-117).  XLA
    fuses this reduction into the surrounding step."""
    leaves = jax.tree_util.tree_leaves(grads)
    if not leaves:
        return jnp.zeros((), bool)
    flags = [~jnp.all(jnp.isfinite(g)) for g in leaves]
    return jnp.stack(flags).any()


def unscale(state: LossScalerState, grads):
    """(grads / scale, found_inf) ≡ LossScaler.unscale (scaler.py:105-145)."""
    inv = 1.0 / state.scale
    unscaled = jax.tree_util.tree_map(lambda g: g * inv.astype(g.dtype), grads)
    return unscaled, check_finite(grads)


def update(state: LossScalerState, found_inf, dynamic: bool = True,
           growth_interval: int = 2000, growth_factor: float = 2.0,
           backoff_factor: float = 0.5, min_scale: float = 1.0,
           max_scale: float = 2.0 ** 24) -> LossScalerState:
    """≡ LossScaler.update_scale (apex/amp/scaler.py:197-217), branch-free:
    on overflow scale *= backoff and tracker resets; after
    `growth_interval` clean steps scale *= growth."""
    if not dynamic:
        return state._replace(found_inf=found_inf)
    tracker = jnp.where(found_inf, 0, state.growth_tracker + 1)
    grow = tracker >= growth_interval
    scale = jnp.where(
        found_inf,
        jnp.maximum(state.scale * backoff_factor, min_scale),
        jnp.where(grow, jnp.minimum(state.scale * growth_factor, max_scale),
                  state.scale),
    )
    tracker = jnp.where(grow, 0, tracker)
    return LossScalerState(scale=scale, growth_tracker=tracker,
                           found_inf=found_inf)


def state_dict(state: LossScalerState) -> dict:
    """≡ apex.amp.state_dict (apex/amp/frontend.py:365-384)."""
    return {
        "loss_scale": jax.device_get(state.scale).item(),
        "unskipped": jax.device_get(state.growth_tracker).item(),
    }


def load_state_dict(d: dict) -> LossScalerState:
    """≡ apex.amp.load_state_dict (apex/amp/frontend.py:387-404)."""
    return LossScalerState(
        scale=jnp.asarray(d["loss_scale"], jnp.float32),
        growth_tracker=jnp.asarray(d["unskipped"], jnp.int32),
        found_inf=jnp.zeros((), bool),
    )
