"""Mixed-precision policies — TPU-native `apex.amp` opt levels.

The reference's AMP is an op-patching engine: O1 monkey-patches torch
namespaces with cast wrappers driven by allow/deny lists
(apex/amp/frontend.py:104-193, apex/amp/lists/torch_overrides.py:7-115),
O2/O3 cast the whole model (apex/amp/_initialize.py:178-203).  JAX traces
pure functions, so op interception is impossible and unnecessary: a
*policy* object states param/compute/output dtypes and is applied
explicitly at module call sites.  The cast lists become the behavioral
contract encoded in `MATMUL_CLASS_OPS` / `FP32_CLASS_OPS` below: under
O1 only matmul-class compute runs in low precision, while
reduction/loss/norm-class ops stay fp32 — the same split as the
reference's allow list (conv/mm/addmm…) vs promote list
(softmax/norm/loss, functional_overrides.py:16-80).

On TPU the low-precision dtype defaults to bfloat16: its fp32-sized
exponent makes loss scaling unnecessary (scaler retained for fp16-parity
mode, see scaler.py).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp

# Behavioral contract of the reference cast lists (apex/amp/lists/):
# ops that benefit from low precision (MXU-bound)...
MATMUL_CLASS_OPS = ("conv", "matmul", "dense", "attention", "mlp", "einsum")
# ...and ops that must run fp32 (reference "promote"/fp32 lists).
FP32_CLASS_OPS = (
    "softmax", "log_softmax", "layer_norm", "batch_norm", "group_norm",
    "cross_entropy", "mse_loss", "l1_loss", "exp", "log", "pow", "sum",
    "cumsum", "var", "std", "norm",
)


def _cast_floating(tree, dtype):
    def cast(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(cast, tree)


@dataclasses.dataclass(frozen=True)
class Policy:
    """(param, compute, output) dtype triple.

    ≡ the Properties object produced by apex.amp.frontend.initialize
    (frontend.py:9-101) — `cast_model_type` → param_dtype, the O1 patched
    casts → compute_dtype, `cast_model_outputs` → output_dtype.
    `keep_norm_fp32` ≡ keep_batchnorm_fp32 (frontend.py:129).
    `master_weights` ≡ master_weights (frontend.py:135).
    `loss_scale` is "dynamic", None, or a float (frontend.py:139).
    """

    opt_level: str = "O1"
    param_dtype: Any = jnp.float32
    compute_dtype: Any = jnp.float32
    output_dtype: Any = jnp.float32
    keep_norm_fp32: bool = True
    master_weights: bool = False
    loss_scale: Optional[Any] = None   # None | float | "dynamic"

    # -- casting helpers applied at module call sites ----------------------
    def cast_to_compute(self, *trees):
        out = tuple(_cast_floating(t, self.compute_dtype) for t in trees)
        return out[0] if len(out) == 1 else out

    def cast_to_param(self, *trees):
        out = tuple(_cast_floating(t, self.param_dtype) for t in trees)
        return out[0] if len(out) == 1 else out

    def cast_to_output(self, *trees):
        out = tuple(_cast_floating(t, self.output_dtype) for t in trees)
        return out[0] if len(out) == 1 else out

    def compute_for(self, op_name: str):
        """Compute dtype for a named op class, honoring the fp32 list.

        The allow (matmul) list wins over the fp32 list on compound
        names — "einsum" is matmul-class even though it contains "sum"
        (≡ the reference patches exact function objects, so its lists
        can never collide; substring classification needs the
        precedence).  Under O3 (keep_norm_fp32=False, the reference's
        "pure half" mode with no patched casts, frontend.py:168-193)
        fp32-class ops run in the compute dtype too."""
        if any(k in op_name for k in MATMUL_CLASS_OPS):
            return self.compute_dtype
        if any(k in op_name for k in FP32_CLASS_OPS):
            return jnp.float32 if self.keep_norm_fp32 else self.compute_dtype
        return self.compute_dtype


def _mk(opt_level, low=jnp.bfloat16, **kw):
    presets = {
        # ≡ apex/amp/frontend.py:104-193 opt_levels table
        "O0": dict(param_dtype=jnp.float32, compute_dtype=jnp.float32,
                   output_dtype=jnp.float32, master_weights=False,
                   loss_scale=1.0),
        "O1": dict(param_dtype=jnp.float32, compute_dtype=low,
                   output_dtype=jnp.float32, master_weights=False,
                   loss_scale="dynamic"),
        "O2": dict(param_dtype=low, compute_dtype=low,
                   output_dtype=jnp.float32, master_weights=True,
                   keep_norm_fp32=True, loss_scale="dynamic"),
        "O3": dict(param_dtype=low, compute_dtype=low, output_dtype=low,
                   master_weights=False, keep_norm_fp32=False,
                   loss_scale=1.0),
    }
    cfg = presets[opt_level]
    cfg.update(kw)
    return Policy(opt_level=opt_level, **cfg)


def get_policy(opt_level: str = "O1", low_dtype=jnp.bfloat16, **overrides) -> Policy:
    """Look up an O0-O3 preset with keyword overrides.

    ≡ apex.amp.frontend.initialize's opt_level + explicit-override handling
    (frontend.py:343-356).  On TPU `low_dtype` defaults to bfloat16; pass
    jnp.float16 for fp16-parity experiments (with dynamic loss scaling).
    """
    if opt_level not in ("O0", "O1", "O2", "O3"):
        raise ValueError(f"Unexpected optimization level {opt_level}")
    return _mk(opt_level, low=low_dtype, **overrides)


# --- fp16_utils equivalents ------------------------------------------------

def convert_network(params, dtype, is_norm_param=None):
    """Cast a param pytree to `dtype`, keeping norm-layer params fp32.

    ≡ apex.fp16_utils.convert_network / convert_module
    (apex/fp16_utils/fp16util.py:35-72).  `is_norm_param(path)` decides
    which leaves stay fp32; the default matches keys containing norm/bn
    (the reference keys on isinstance(module, _BatchNorm)).
    """
    if is_norm_param is None:
        def is_norm_param(path):
            p = "/".join(str(k) for k in path).lower()
            return ("norm" in p) or ("bn" in p) or ("batchstats" in p)

    def cast(path, x):
        if not (hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating)):
            return x
        if is_norm_param(path):
            return x.astype(jnp.float32)
        return x.astype(dtype)

    return jax.tree_util.tree_map_with_path(cast, params)


def prep_param_lists(params):
    """(model_params, fp32 master copies) ≡ apex.fp16_utils.prep_param_lists
    (fp16util.py:92-135) — flat lists become pytrees."""
    master = _cast_floating(params, jnp.float32)
    return params, master


def model_grads_to_master_grads(model_grads):
    """≡ fp16util.model_grads_to_master_grads (fp16util.py:138)."""
    return _cast_floating(model_grads, jnp.float32)


def master_params_to_model_params(master_params, model_params):
    """≡ fp16util.master_params_to_model_params (fp16util.py:160-177)."""
    return jax.tree_util.tree_map(
        lambda m, p: m.astype(p.dtype) if hasattr(p, "dtype") else m,
        master_params, model_params)
