"""FP16_Optimizer — manual master-weight optimizer facade.

≡ apex.fp16_utils.FP16_Optimizer (apex/fp16_utils/fp16_optimizer.py:13)
and the deprecated apex.contrib.optimizers.FP16_Optimizer: wraps an
inner optimizer with fp32 master weights, (dynamic) loss scaling, and
overflow-skipping.  In this framework the fused optimizers already keep
fp32 flat masters, so this class is the *workflow* facade: scale →
backward (caller) → clip/unscale → masked step → scaler update,
with state_dict parity.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.amp import scaler as scaler_lib
from apex_tpu.parallel.clip_grad import clip_grad_norm


class FP16_Optimizer:
    def __init__(self, init_optimizer, static_loss_scale: float = 1.0,
                 dynamic_loss_scale: bool = False,
                 dynamic_loss_args: Optional[dict] = None,
                 verbose: bool = False):
        self.optimizer = init_optimizer
        self.dynamic = dynamic_loss_scale
        self.scaler_state = scaler_lib.init(
            "dynamic" if dynamic_loss_scale else static_loss_scale)
        self.clip_grad_norm_value = None
        # flight-recorder provenance (ISSUE 4): the last step's tap
        # snapshot, so an overflow skip can say WHICH tap tripped
        self._last_tap_state = None
        self._last_tap_names = None

    @property
    def loss_scale(self):
        return float(self.scaler_state.scale)

    def init(self, params):
        return self.optimizer.init(params)

    def scale_loss(self, loss):
        """≡ FP16_Optimizer.backward's loss*scale (the backward itself is
        the caller's jax.grad)."""
        return scaler_lib.scale_loss(self.scaler_state, loss)

    def step(self, state, grads, lr=None, max_grad_norm=None,
             metrics=None, metrics_count_step: bool = True,
             tap_state=None, tap_names=None):
        """Unscale, (optionally clip), masked step, update scaler.
        Returns (params, state) — or (params, state, new_metrics) when
        a `monitor.MetricsState` is passed: loss scale, the unscaled
        PRE-clip grad norm, overflow/skip counts, and master
        param/update norms fold in on-device (this facade holds no
        loss, so that field carries over).  Pass
        metrics_count_step=False when another hook (e.g.
        forward_backward_no_pipelining) already counts this iteration's
        step — otherwise each iteration advances `step` twice and every
        derived rate halves.

        tap_state / tap_names: the iteration's `monitor.trace.TapState`
        + tap labels (from the tapped backward that produced `grads`).
        The facade keeps them so an overflow skip is attributable:
        `overflow_provenance()` names the tap that tripped instead of
        only the global found_inf flag.  Device arrays are held as-is —
        no sync unless provenance is actually asked for."""
        self._last_tap_state = tap_state
        if tap_names is not None:
            self._last_tap_names = tuple(tap_names)
        scale_used = self.scaler_state.scale
        grads, found_inf = scaler_lib.unscale(self.scaler_state, grads)
        # telemetry wants the PRE-clip norm: a clipped norm pins at the
        # threshold and can never show the spike clipping exists to tame
        grads_preclip = grads
        if max_grad_norm:
            grads, _ = clip_grad_norm(grads, max_grad_norm)
        params, new_state = self.optimizer.step(
            state, grads, lr=lr, found_inf=found_inf)
        self.scaler_state = scaler_lib.update(
            self.scaler_state, found_inf, dynamic=self.dynamic)
        if metrics is None:
            return params, new_state
        from apex_tpu.monitor import metrics as _mon
        new_metrics = _mon.update_metrics(
            metrics, grads=grads_preclip,  # unscaled, pre-clip
            params_flat=getattr(state, "params", None),
            new_params_flat=getattr(new_state, "params", None),
            loss_scale=scale_used, found_inf=found_inf,
            count_step=metrics_count_step)
        return params, new_state, new_metrics

    def overflow_provenance(self):
        """Which tap tripped on the last step (None when the last step
        carried no tap state or both planes were clean).  One
        device_get; returns `monitor.trace.provenance`'s dict:
        {"plane", "tap", "index", "stats"} — for a loss-scaling
        overflow the gradient plane names the tap nearest the loss
        where the non-finite values entered backward."""
        if self._last_tap_state is None:
            return None
        from apex_tpu.monitor.trace import taps as _trc
        return _trc.provenance(self._last_tap_state,
                               self._last_tap_names or ())

    # -- checkpoint parity (fp16_optimizer.py state_dict incl. masters) --
    def state_dict(self, state):
        return {"optimizer": self.optimizer.state_dict(state),
                "loss_scaler": scaler_lib.state_dict(self.scaler_state)}

    def load_state_dict(self, d):
        self.scaler_state = scaler_lib.load_state_dict(d["loss_scaler"])
        return self.optimizer.load_state_dict(d["optimizer"])
