"""Dense dispatch/combine + the ep all-to-all exchange.

The sparse-accumulation lesson of arXiv 1905.04035 applied to expert
parallelism: **densify before the collective, never ship ragged sparse
payloads**.  Tokens scatter into a fixed `(n_experts, capacity,
d_model)` buffer (dropped tokens go to a trash row that stays local,
so the exchanged payload's shape depends on NOTHING the router
decided), and the whole cross-expert exchange is ONE tiled
`all_to_all` over the `ep` mesh axis each way:

    dispatch:  (E, C, H) --all_to_all(split 0, concat 1)--> (E/ep, ep*C, H)
    combine:   (E/ep, ep*C, H) --all_to_all(split 1, concat 0)--> (E, C, H)

Each shard dispatches its LOCAL tokens into slots for ALL E global
experts; the exchange hands every ep peer the block for the experts it
owns and returns the computed outputs the same way.  The payload is
E*C*H * itemsize bytes per direction, priced by the ICI roofline's
ring all-to-all formula ((n-1)/n * D / bw, monitor/comms/roofline.py)
and inventoried by the comms gate (`comms_probe.py moe`).

Scatter/gather discipline: every non-trash destination row is unique
by construction (positions within an expert are distinct across all
(token, slot) assignments), so the scatter is exact — a kept token's
row is its activation bit-for-bit, which is what makes the
capacity_factor=inf round trip and the n_experts=1 dense-GPT parity
BITWISE, not just close.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax


def dispatch(x, dest, n_experts: int, capacity: int) -> jnp.ndarray:
    """Scatter token rows x (T, H) to their destination slots.

    dest: (T, k) flat rows from `router.capacity_destinations`.
    Returns the dense (E*C + 1, H) buffer in x's dtype — row E*C is
    the trash row (dropped tokens pile up there and are never read).
    Non-trash rows are unique, so `.set` writes each kept token's
    activation exactly; unfilled slots stay zero and contribute
    nothing downstream (zero rows through the expert MLP produce
    bias-only outputs that combine never reads)."""
    t, h = x.shape
    k = dest.shape[1]
    buf = jnp.zeros((n_experts * capacity + 1, h), x.dtype)
    for j in range(k):
        buf = buf.at[dest[:, j]].set(x)
    return buf


def combine(ybuf, dest, gate) -> jnp.ndarray:
    """Gather expert outputs back to token order, weighted by gates.

    ybuf: (E*C + 1, H) with the trash row ZEROED (exchange_combine
    rebuilds it that way), dest: (T, k), gate: (T, k) fp32 raw gate
    probs.  Dropped assignments index the trash row and contribute
    exactly 0 — a fully dropped token passes through on the residual
    alone.  The weight multiply casts the GATE to the activation
    dtype (not the activations to fp32): at gate == 1.0 the product
    is the expert output bit-for-bit, the dense-parity anchor."""
    k = dest.shape[1]
    out = ybuf[dest[:, 0]] * gate[:, 0, None].astype(ybuf.dtype)
    for j in range(1, k):
        out = out + ybuf[dest[:, j]] * gate[:, j, None].astype(ybuf.dtype)
    return out


def exchange_dispatch(buf, ep_axis, ep_size: int, n_experts: int,
                      capacity: int) -> jnp.ndarray:
    """(E*C+1, H) local dispatch buffer -> (E/ep, ep*C, H) rows for
    THIS shard's experts, gathered from every ep peer.  The trash row
    is sliced off first — it is local-only garbage and shipping it
    would waste ICI bytes for values nobody reads.  ep_size == 1 is
    the degenerate reshape (no collective traced at all)."""
    h = buf.shape[1]
    ebuf = buf[:n_experts * capacity].reshape(n_experts, capacity, h)
    if ep_size == 1:
        return ebuf
    # tiled all_to_all: expert-group chunk g of dim 0 ships to ep peer
    # g; the ep received chunks concatenate along the slot dim
    return lax.all_to_all(ebuf, ep_axis, split_axis=0, concat_axis=1,
                          tiled=True)


def exchange_combine(y, ep_axis, ep_size: int, n_experts: int,
                     capacity: int) -> jnp.ndarray:
    """Inverse exchange + trash-row rebuild: expert outputs
    (E_loc, ep*C, H) -> the (E*C + 1, H) combine buffer in original
    (expert, slot) order with a fresh zero trash row."""
    h = y.shape[-1]
    if ep_size > 1:
        y = lax.all_to_all(y, ep_axis, split_axis=1, concat_axis=0,
                           tiled=True)
    flat = y.reshape(n_experts * capacity, h)
    return jnp.concatenate([flat, jnp.zeros((1, h), flat.dtype)], axis=0)


def chunked_expert_exchange(buf, ffn, ep_axis, ep_size: int,
                            n_experts: int, capacity: int,
                            chunks: int = 1) -> jnp.ndarray:
    """dispatch-exchange -> expert FFN -> combine-exchange, micro-
    chunked along the capacity dim (ISSUE 18): the dispatch
    all_to_all of chunk k+1 and the combine all_to_all of chunk k-1
    both ride ICI while the expert FFN chews chunk k.

    `ffn(xe)` maps (E_loc, rows, H) -> (E_loc, rows, H) and must be
    ROW-INDEPENDENT along the slot dim (MoEMLP._expert_ffn is: the
    einsum contracts hidden dims only) — that is what makes each
    chunk's rows bitwise the rows of the monolithic exchange, and the
    concatenation an exact reassembly.  Slot chunk j of every expert
    travels together, so each chunk's exchange is the same tiled
    all_to_all pattern at capacity/chunks rows — chunk-count-many
    smaller collectives, same total bytes (the comms-fixture pin).

    chunks == 1 is EXACTLY the monolithic exchange_dispatch -> ffn ->
    exchange_combine sequence (byte-identical trace, the
    RecompileSentry anchor).  AD needs no custom_vjp: all_to_all
    transposes to its inverse per chunk, and the ffn's parameter
    grads sum across the chunk calls automatically."""
    if chunks <= 1:
        xe = exchange_dispatch(buf, ep_axis, ep_size, n_experts, capacity)
        ye = ffn(xe)
        return exchange_combine(ye, ep_axis, ep_size, n_experts, capacity)
    h = buf.shape[1]
    ebuf = buf[:n_experts * capacity].reshape(n_experts, capacity, h)
    cc = capacity // chunks
    outs = []
    for j in range(chunks):
        piece = lax.slice_in_dim(ebuf, j * cc, (j + 1) * cc, axis=1)
        if ep_size > 1:
            piece = lax.all_to_all(piece, ep_axis, split_axis=0,
                                   concat_axis=1, tiled=True)
        ye = ffn(piece)  # (E_loc, ep*cc, H), rows independent
        if ep_size > 1:
            ye = lax.all_to_all(ye, ep_axis, split_axis=1,
                                concat_axis=0, tiled=True)
        outs.append(ye)
    y = jnp.concatenate(outs, axis=1)  # (E, capacity, H), slot order
    flat = y.reshape(n_experts * capacity, h)
    return jnp.concatenate([flat, jnp.zeros((1, h), flat.dtype)], axis=0)
