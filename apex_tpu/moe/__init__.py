"""apex_tpu.moe — expert-parallel Mixture-of-Experts (ISSUE 13).

The "harsher second customer" of ROADMAP item 5: a top-k router with
fp32 gates and capacity-factor token dropping (router.py), dense
dispatch/combine whose cross-expert exchange is ONE all_to_all over
the `ep` mesh axis each way (dispatch.py — the densify-before-the-
collective rule of arXiv 1905.04035), and `MoEMLP` (layer.py), the
drop-in for a transformer block's MLP that `models/moe_gpt.py` trains
under the unmodified `ddp.make_train_step` with the existing ZeRO
machinery (flat master state sharded over the combined ("dp", "ep")
axes).

Host-side telemetry bridge: `MoERecorder` holds the newest step's
MoE aux scalars so `MetricsLogger(moe=recorder)` stamps the schema-v9
`moe_*` fields into every record — the same attachment pattern as the
serve/fleet planes, zero added device syncs (the step already returns
the aux pytree; `update` is fed the host copy the logger fetch pays
for anyway).
"""

from __future__ import annotations

from apex_tpu.moe.layer import MoEAux, MoEMLP, mean_aux  # noqa: F401
from apex_tpu.moe.router import (  # noqa: F401
    RouterOutput,
    capacity_destinations,
    expert_capacity,
    topk_gates,
    topk_gates_blocked,
    topk_gates_dense,
)

__all__ = [
    "MoEAux", "MoEMLP", "mean_aux", "MoERecorder",
    "RouterOutput", "capacity_destinations", "expert_capacity",
    "topk_gates", "topk_gates_blocked", "topk_gates_dense",
]


class MoERecorder:
    """Host-side holder of the newest MoE step aux for the logger.

    Feed it the step's aux output (a `MoEAux`, or any mapping/
    NamedTuple carrying aux_loss / drop_fraction fields — device
    arrays are fine, they are floated here) once per logging window;
    `MetricsLogger(moe=recorder)` then stamps `moe_aux_loss` /
    `moe_drop_fraction` (+ `moe_gate_entropy` when present) into each
    record.  Before the first update nothing is stamped — the
    OPTIONAL-never-null schema rule.
    """

    def __init__(self):
        self._last = None

    def update(self, aux) -> None:
        if hasattr(aux, "_asdict"):
            aux = aux._asdict()
        # accept BOTH spellings: a raw MoEAux (field names) and the
        # model's stats dict (already moe_-prefixed, what the train
        # step's aux output carries) — normalize to field names
        self._last = {
            (k[4:] if k.startswith("moe_") else k): float(v)
            for k, v in dict(aux).items()}

    def moe_record(self) -> dict:
        if not self._last:
            return {}
        out = {}
        for src, dst in (("aux_loss", "moe_aux_loss"),
                         ("drop_fraction", "moe_drop_fraction"),
                         ("gate_entropy", "moe_gate_entropy"),
                         ("z_loss", "moe_z_loss")):
            if src in self._last:
                out[dst] = self._last[src]
        return out
