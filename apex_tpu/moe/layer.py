"""`MoEMLP` — the expert-parallel drop-in for a transformer block's MLP.

Composition of the subsystem's three pieces (router.py, dispatch.py)
into one shard-local layer that runs inside `shard_map` over the
(pp, dp[, ep], tp) mesh:

    route (fp32 gates) -> dispatch into (E, C, H) -> all_to_all over ep
    -> per-expert FFN (bf16-friendly, fp32 MXU accumulation)
    -> all_to_all back -> combine weighted by raw gate probs

Parameter layout: every shard holds the FULL (E, ...) expert tensors —
the ZeRO-2 posture: compute-time replicated, master state sharded over
the combined (dp, ep) axes by `DistributedFusedAdam(num_shards=dp*ep,
axis_name=("dp","ep"), ep_shards=ep)` — and slices its own E/ep
experts by `lax.axis_index("ep")` at compute time.  Gradient
correctness needs NO expert-special sync: the combine all_to_all's AD
transpose routes each shard's loss cotangents back to the shard that
computed the expert, so after backward every shard already holds
d(sum of its ep group's losses)/d(its expert slice) — a uniform pmean
over ("dp", "ep") is then exact for expert and non-expert params
alike (docs/moe.md derives this).

Telemetry: when a flight-recorder TapContext is armed, the layer taps
`{prefix}/load` (per-expert assignment fractions — absmax = hottest
expert), `{prefix}/drop` (per-expert dropped fractions — mean = drop
fraction) and `{prefix}/gate_entropy` (per-token gate entropy — mean
falling toward 0 = router collapse) through the existing TapState
plane: zero host syncs, zero collectives, and the untapped program is
byte-identical because the whole hook is trace-time gated on
`active_tap_context()`.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.ad_checkpoint import checkpoint_name
from jax.sharding import PartitionSpec as P

from apex_tpu.moe import dispatch as D
from apex_tpu.moe import router as R
from apex_tpu.ops._common import active_tap_context, tap as _tap
from apex_tpu.parallel.collectives import (
    copy_to_tensor_model_parallel_region,
    reduce_from_tensor_model_parallel_region,
)
from apex_tpu.parallel.mesh import EP_AXIS


class MoEAux(NamedTuple):
    """Per-layer fp32 scalars the model folds into its loss/stats."""

    aux_loss: jnp.ndarray        # load-balancing loss (1.0 = balanced)
    z_loss: jnp.ndarray          # router z-loss
    drop_fraction: jnp.ndarray   # dropped assignments / (T * k)
    gate_entropy: jnp.ndarray    # mean per-token gate entropy


class MoEMLP:
    """Expert MLP bank: E experts of (H -> ffn_mult*H -> H), gelu.

    Drop-in for the GPT block's ColumnParallel->gelu->RowParallel MLP:
    at n_experts=1 / top_k=1 / capacity_factor=inf the output is
    BITWISE the dense MLP's (same GEMM contractions row-for-row, gate
    exactly 1.0) — the acceptance anchor tests/test_moe.py pins.
    """

    def __init__(self, hidden: int, ffn_hidden: int, n_experts: int, *,
                 top_k: int = 1, capacity_factor: float = 1.25,
                 ep_size: int = 1, ep_axis: str = EP_AXIS,
                 init_std: float = 0.02,
                 proj_init_std: Optional[float] = None,
                 router_block_rows: Optional[int] = None,
                 tp_axis: Optional[str] = None,
                 overlap_chunks=None):
        if n_experts % max(1, ep_size):
            raise ValueError(
                f"n_experts={n_experts} must divide by ep_size={ep_size}")
        if top_k > n_experts:
            raise ValueError(f"top_k={top_k} > n_experts={n_experts}")
        self.hidden = hidden
        self.ffn_hidden = ffn_hidden
        self.n_experts = n_experts
        self.top_k = top_k
        self.capacity_factor = float(capacity_factor)
        self.ep_size = ep_size
        self.ep_axis = ep_axis
        self.init_std = init_std
        self.proj_init_std = proj_init_std or init_std
        self.router_block_rows = router_block_rows
        # tp_axis: the dense GPT block's tensor-parallel region markers
        # (ColumnParallel's copy_to on entry, RowParallel's reduce_from
        # before the output bias), mirrored here so the drop-in keeps
        # the identical op sequence.  MoE experts REPLICATE over tp —
        # only tp == 1 is supported (the markers are then identities;
        # at tp > 1 the duplicate-compute reduce would scale outputs
        # by tp, so apply() raises at trace time when the bound tp
        # axis has size > 1).
        self.tp_axis = tp_axis
        # micro-chunk depth of the dispatch/combine exchange
        # (dispatch.chunked_expert_exchange): None = tuner-owned
        # (`overlap_chunks` op, heuristic 1 = the monolithic exchange,
        # byte-identical); an int forces it for A/B sweeps.
        self.overlap_chunks = overlap_chunks

    # ------------------------------ params --------------------------------

    def init(self, key, dtype=jnp.float32) -> dict:
        kg, k1, k2 = jax.random.split(key, 3)
        e, h, f = self.n_experts, self.hidden, self.ffn_hidden
        return {
            "wg": jax.random.normal(kg, (h, e), dtype) * self.init_std,
            "w1": jax.random.normal(k1, (e, h, f), dtype) * self.init_std,
            "b1": jnp.zeros((e, f), dtype),
            "w2": jax.random.normal(k2, (e, f, h), dtype)
            * self.proj_init_std,
            "b2": jnp.zeros((e, h), dtype),
        }

    def partition_specs(self) -> dict:
        """Everything REPLICATED — the compute-time contract: every
        shard holds the full (E, ...) expert tensors (the ZeRO-2
        posture; `_local_experts` slices this shard's E/ep experts by
        axis_index at compute time, which requires the full tensor as
        input).  Ep-RESIDENT expert params — P(ep, ...) leaves with no
        gather — are the ZeRO-3 rung of ROADMAP item 1, and would
        change `_local_experts` in the same commit as this spec."""
        return {"wg": P(), "w1": P(), "b1": P(), "w2": P(), "b2": P()}

    # ------------------------------ forward -------------------------------

    def _local_experts(self, params):
        """This shard's E/ep slice of each expert tensor (the whole
        tensor when ep_size == 1 — no axis_index traced)."""
        if self.ep_size == 1:
            return (params["w1"], params["b1"], params["w2"], params["b2"])
        e_loc = self.n_experts // self.ep_size
        start = lax.axis_index(self.ep_axis) * e_loc

        def sl(a):
            return lax.dynamic_slice_in_dim(a, start, e_loc, axis=0)

        return (sl(params["w1"]), sl(params["b1"]),
                sl(params["w2"]), sl(params["b2"]))

    def _expert_ffn(self, params, xe, cn=None):
        """Per-expert FFN on the exchanged buffer (E_loc, rows, H):
        the same dot/astype/bias/gelu sequence as the dense
        ColumnParallel -> gelu -> RowParallel pair, batched over the
        expert dim with fp32 MXU accumulation.  cn: optional pair of
        checkpoint_name tags applied where the dense GPT block tags
        its MLP (after each projection+bias) — the model passes
        ("ffn1", "ffn_out") so remat policies keep addressing the
        same points."""
        w1, b1, w2, b2 = self._local_experts(params)
        h = jnp.einsum("ech,ehf->ecf", xe, w1,
                       preferred_element_type=jnp.float32).astype(xe.dtype)
        h = h + b1[:, None, :].astype(h.dtype)
        if cn:
            h = checkpoint_name(h, cn[0])
        h = jax.nn.gelu(h, approximate=True)
        y = jnp.einsum("ecf,efh->ech", h, w2,
                       preferred_element_type=jnp.float32).astype(h.dtype)
        if self.tp_axis is not None:
            y = reduce_from_tensor_model_parallel_region(y, self.tp_axis)
        y = y + b2[:, None, :].astype(y.dtype)
        if cn:
            y = checkpoint_name(y, cn[1])
        return y

    def _exchange_chunks(self, capacity: int, dtype) -> int:
        """Trace-time micro-chunk count for the ep exchange: explicit
        override, else the `overlap_chunks` tuner op (heuristic 1 on a
        miss).  Non-dividing requests fall back to the largest divisor
        of the capacity, warn once (the flash-attention block rule)."""
        from apex_tpu.parallel import overlap as OV
        req = self.overlap_chunks
        if req is None:
            from apex_tpu import tune
            cfg = tune.tuned("overlap_chunks", tune.overlap_attrs(
                "moe", capacity, self.hidden, self.ep_size, dtype))
            req = int(cfg["chunks"]) if cfg else 1
        req = int(req)
        if req <= 1:
            return 1
        return OV.resolve_chunks(req, capacity, site="moe")

    def apply(self, params, x, tap_prefix: Optional[str] = None,
              cn=None):
        """x: (..., H) shard-local activations (any leading dims —
        (S, B, H) from a GPT block).  Returns (y, MoEAux) with y in
        x's shape and dtype.  Call inside shard_map when ep_size > 1
        (the all_to_all needs the bound ep axis).  cn: checkpoint_name
        tag pair, see _expert_ffn."""
        lead_shape = x.shape[:-1]
        if self.tp_axis is not None:
            try:
                tp = int(lax.axis_size(self.tp_axis))
            except NameError:  # axis unbound (outside shard_map)
                tp = 1
            if tp > 1:
                # loud error, not silent wrongness: experts REPLICATE
                # over tp, so the duplicate-compute reduce_from below
                # would scale every MoE output by tp
                raise NotImplementedError(
                    f"MoEMLP does not support tensor parallelism yet "
                    f"(tp axis {self.tp_axis!r} has size {tp}): experts "
                    "replicate over tp and the RowParallel-style "
                    "reduction would multiply outputs by tp — build "
                    "the MoE mesh with tensor_model_parallel_size=1")
            # the dense ColumnParallel entry marker (identity forward,
            # grad psum over tp in backward) — see __init__
            x = copy_to_tensor_model_parallel_region(x, self.tp_axis)
        xt = x.reshape(-1, self.hidden)
        t = xt.shape[0]
        e, k = self.n_experts, self.top_k
        cap = R.expert_capacity(t, e, k, self.capacity_factor)

        out = R.topk_gates(xt, params["wg"], k,
                           block_rows=self.router_block_rows)
        if e == 1 and k == 1 and cap >= t and self.ep_size == 1:
            # Degenerate routing (the n_experts=1 limit): every token
            # goes to expert 0 with gate exactly 1.0 and the dispatch
            # permutation is the identity, so the scatter/exchange/
            # gather collapses away and the expert FFN runs on the
            # ORIGINAL activation shape — a real optimization (no
            # buffers, no scatter) that also makes this limit BITWISE
            # the dense MLP: same op shapes means XLA fuses the bias-
            # grad reductions identically (the general (E, C, H) path
            # is bitwise in VALUES but fuses those reduces in a
            # different loop order).  Dispatch itself is covered by
            # the round-trip and dp x ep grid tests.
            dropped = jnp.zeros((1,), jnp.float32)
            y1 = jnp.dot(x, params["w1"][0],
                         preferred_element_type=jnp.float32
                         ).astype(x.dtype)
            y1 = y1 + params["b1"][0].astype(y1.dtype)
            if cn:
                y1 = checkpoint_name(y1, cn[0])
            y1 = jax.nn.gelu(y1, approximate=True)
            y2 = jnp.dot(y1, params["w2"][0],
                         preferred_element_type=jnp.float32
                         ).astype(y1.dtype)
            if self.tp_axis is not None:
                y2 = reduce_from_tensor_model_parallel_region(
                    y2, self.tp_axis)
            # softmax over ONE logit is identically the constant 1.0,
            # so the gate weighting is the identity FUNCTION (value
            # and derivative) — skipping the multiply is exact, and
            # keeps the router's ops out of the MLP's forward/backward
            # fusion neighborhoods (an extra *1.0 changes nothing in
            # values but re-tiles the layernorm-backward reduce, an
            # accumulation-order wobble that would break the bitwise
            # anchor).  The router still runs for gates/aux stats.
            y2 = y2 + params["b2"][0].astype(y2.dtype)
            if cn:
                y2 = checkpoint_name(y2, cn[1])
            y = y2.reshape(-1, self.hidden)
        else:
            dest, dropped = R.capacity_destinations(out.idx, e, cap)
            buf = D.dispatch(xt, dest, e, cap)
            # micro-chunked exchange (ISSUE 18): chunk k+1's dispatch
            # all_to_all overlaps chunk k's expert FFN; chunks == 1 is
            # the monolithic sequence, byte-identical
            chunks = self._exchange_chunks(cap, xt.dtype)
            ybuf = D.chunked_expert_exchange(
                buf, lambda xe: self._expert_ffn(params, xe, cn=cn),
                self.ep_axis, self.ep_size, e, cap, chunks)
            y = D.combine(ybuf, dest, out.gate)

        aux_loss, load, _ = R.load_balancing_aux(out.probs, out.idx, e)
        drop_per_expert = dropped / jnp.asarray(t * k, jnp.float32)
        ent = R.gate_entropy(out.probs)
        aux = MoEAux(aux_loss=aux_loss,
                     z_loss=R.router_z_loss(out.logits),
                     drop_fraction=jnp.sum(drop_per_expert),
                     gate_entropy=jnp.mean(ent))

        if tap_prefix is not None and active_tap_context() is not None:
            # flight-recorder hook, armed at TRACE time only: the
            # tapped stat tensors ride into the loss through a 0.0 *
            # sum so AD's probe-cotangent path runs for them (the fwd
            # stats plane is a residual — a zero cotangent still emits
            # it); untapped traces skip this block entirely, keeping
            # the byte-identical contract of ops._common.tap
            s = (_tap(load, f"{tap_prefix}/load").sum()
                 + _tap(drop_per_expert, f"{tap_prefix}/drop").sum()
                 + _tap(ent, f"{tap_prefix}/gate_entropy").sum())
            y = y + (0.0 * s).astype(y.dtype)

        return y.reshape(*lead_shape, self.hidden), aux


def mean_aux(auxes) -> MoEAux:
    """Average a list of per-layer MoEAux into one (fp32 scalars)."""
    n = jnp.asarray(len(auxes), jnp.float32)
    return MoEAux(*[
        sum(getattr(a, f) for a in auxes) / n for f in MoEAux._fields])
