"""Top-k expert router — fp32 gates, capacity-aware destinations.

The routing contract (docs/moe.md):

* **fp32 gate logits regardless of compute dtype.**  The gate GEMM
  runs the activations in their compute dtype but accumulates into
  fp32 (`preferred_element_type`) — the logits, softmax, and top-k
  selection are all fp32.  A bf16 softmax loses ties and the tiny
  probability gaps the selection keys on (lint rule DP105 makes a
  low-precision selection a finding).
* **Ties pinned by index.**  `lax.top_k` is stable: equal
  probabilities resolve to the LOWER expert index, so routing is a
  pure function of the logits with no backend-dependent tie noise.
* **Byte-identical blocked path.**  Softmax and top-k are
  row-independent, so chunking the token rows changes scheduling
  only, never values.  `topk_gates` consults the `moe_router` tuner
  op (apex_tpu.tune) for a `block_rows` config; on a miss — every
  untuned machine — the dense single-shot reference runs, which is
  the pre-tuner kernel exactly (the tune/ contract).

The capacity math (`expert_capacity`) and the position-within-expert
assignment (`capacity_destinations`) live here too: together they make
routing emit a STATIC-shaped destination map — tokens beyond an
expert's capacity route to the trash row (index `n_experts *
capacity`), mirroring the KV trash-page trick of apex_tpu.serve, so
compiled shapes never depend on where tokens actually went.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax


def expert_capacity(tokens: int, n_experts: int, top_k: int,
                    capacity_factor: float) -> int:
    """Per-expert, per-source-shard slot count (static).

    ceil(tokens * top_k * capacity_factor / n_experts), rounded up to
    the fp32 sublane (8) and clamped to `tokens` (one expert can never
    receive more than every token once — top-k picks DISTINCT
    experts).  capacity_factor=inf is the no-drop setting: exactly
    `tokens` slots per expert.  Under expert parallelism each expert's
    total capacity is ep * this value (one block per source shard);
    the drop decision stays LOCAL to the source shard, the GShard
    per-group capacity rule.
    """
    if tokens < 1:
        raise ValueError(f"tokens must be >= 1, got {tokens}")
    if math.isinf(capacity_factor):
        return tokens
    if capacity_factor <= 0:
        raise ValueError(
            f"capacity_factor must be > 0 (or inf), got {capacity_factor}")
    c = math.ceil(tokens * top_k * capacity_factor / n_experts)
    c = ((c + 7) // 8) * 8
    return min(c, tokens)


def gate_logits(x, wg) -> jnp.ndarray:
    """fp32 gate logits (T, E) for activations x (T, H) in ANY compute
    dtype: the GEMM keeps low-precision operands (full MXU rate, no
    DP101 upcast) and accumulates fp32 — the output IS fp32, never a
    downcast-then-upcast round trip."""
    return jnp.dot(x, wg.astype(x.dtype),
                   preferred_element_type=jnp.float32)


def _softmax_topk(logits, top_k: int):
    probs = jax.nn.softmax(logits, axis=-1)          # fp32
    gate, idx = lax.top_k(probs, top_k)              # ties -> low index
    return probs, gate, idx


class RouterOutput(NamedTuple):
    """Everything downstream dispatch/combine and the aux losses need.

    probs: (T, E) fp32 full softmax; gate: (T, k) fp32 selected probs
    (RAW, not renormalized — Switch-style, so the router receives main
    -loss gradient at any k; at k=1/E=1 the gate is exactly 1.0, the
    dense-parity anchor); idx: (T, k) int32 expert ids; logits: (T, E)
    fp32 (the z-loss reads these)."""

    probs: jnp.ndarray
    gate: jnp.ndarray
    idx: jnp.ndarray
    logits: jnp.ndarray


def topk_gates_dense(x, wg, top_k: int) -> RouterOutput:
    """The dense reference: one softmax + top_k over all token rows."""
    logits = gate_logits(x, wg)
    probs, gate, idx = _softmax_topk(logits, top_k)
    return RouterOutput(probs=probs, gate=gate, idx=idx, logits=logits)


def topk_gates_blocked(x, wg, top_k: int, block_rows: int) -> RouterOutput:
    """Row-blocked path: the same softmax + top_k over `block_rows`-row
    chunks via lax.map.  Byte-identical to the dense reference (both
    ops are row-independent); the block size only moves the
    VMEM-residency / grid-overhead point on TPU."""
    logits = gate_logits(x, wg)
    t = logits.shape[0]
    pad = (-t) % block_rows
    padded = jnp.pad(logits, ((0, pad), (0, 0)))
    blocks = padded.reshape(-1, block_rows, logits.shape[1])
    probs_b, gate_b, idx_b = lax.map(
        lambda b: _softmax_topk(b, top_k), blocks)
    e = logits.shape[1]
    return RouterOutput(
        probs=probs_b.reshape(-1, e)[:t],
        gate=gate_b.reshape(-1, top_k)[:t],
        idx=idx_b.reshape(-1, top_k)[:t],
        logits=logits)


def topk_gates(x, wg, top_k: int,
               block_rows: Optional[int] = None) -> RouterOutput:
    """Route x (T, H) through gate weight wg (H, E): the `moe_router`
    tuner op.  An explicit `block_rows` wins; otherwise the tune cache
    is consulted at trace time (host-side dict access, zero device
    work) and a miss falls back to the dense reference — byte-identical
    on every path, per the tune/ contract."""
    if block_rows is None:
        try:
            from apex_tpu import tune
            cfg = tune.tuned("moe_router", tune.moe_router_attrs(
                x.shape[0], wg.shape[1], top_k, x.dtype))
        except Exception:  # pragma: no cover — tuner must never break ops
            cfg = None
        if cfg:
            blk = cfg.get("block_rows")
            if isinstance(blk, int) and 8 <= blk <= 1 << 16 \
                    and blk % 8 == 0:
                block_rows = blk
    if block_rows is None:
        return topk_gates_dense(x, wg, top_k)
    return topk_gates_blocked(x, wg, top_k, block_rows)


def capacity_destinations(idx, n_experts: int, capacity: int):
    """Flat destination rows for each (token, slot) assignment.

    idx: (T, k) int32 expert choices.  Returns (dest, n_dropped):
    dest (T, k) int32 into a flat (n_experts * capacity + 1)-row
    buffer — assignment j of token t lands at `expert * capacity +
    position` where position counts earlier assignments of the same
    expert (slot-major priority: all slot-0 choices outrank slot-1),
    or at the TRASH row (`n_experts * capacity`) once the expert's
    local capacity is full.  n_dropped is the per-expert (E,) fp32
    dropped-assignment count.  Shapes are static — routing can never
    cause a recompile."""
    t, k = idx.shape
    dests = []
    counts = jnp.zeros((n_experts,), jnp.int32)
    dropped = jnp.zeros((n_experts,), jnp.float32)
    for j in range(k):
        oh = jax.nn.one_hot(idx[:, j], n_experts, dtype=jnp.int32)
        pos_table = counts[None, :] + jnp.cumsum(oh, axis=0) - oh
        pos = jnp.sum(oh * pos_table, axis=1)            # (T,)
        keep = pos < capacity
        dests.append(jnp.where(keep, idx[:, j] * capacity + pos,
                               n_experts * capacity))
        counts = counts + jnp.sum(oh, axis=0)
        dropped = dropped + jnp.sum(
            jnp.where(keep[:, None], 0, oh).astype(jnp.float32), axis=0)
    return jnp.stack(dests, axis=1), dropped


def load_balancing_aux(probs, idx, n_experts: int):
    """The Switch/GShard load-balancing auxiliary loss and its stats.

    f_e = fraction of (token, slot) assignments routed to expert e
    (hard counts, piecewise-constant — gradient flows through P_e
    only); P_e = mean gate probability of e.  aux = E * sum(f * P):
    1.0 at perfect balance, larger when load concentrates.  Returns
    (aux fp32 scalar, f (E,) fp32, P (E,) fp32)."""
    t, k = idx.shape
    assign = jnp.zeros((n_experts,), jnp.float32)
    for j in range(k):
        assign = assign + jnp.sum(
            jax.nn.one_hot(idx[:, j], n_experts, dtype=jnp.float32),
            axis=0)
    f = assign / jnp.asarray(t * k, jnp.float32)
    p_mean = jnp.mean(probs, axis=0)
    aux = jnp.asarray(n_experts, jnp.float32) * jnp.sum(f * p_mean)
    return aux, f, p_mean


def router_z_loss(logits):
    """mean(logsumexp(logits)^2) — keeps gate logits from drifting to
    magnitudes where the fp32 softmax itself saturates (ST-MoE)."""
    return jnp.mean(jnp.square(
        jax.scipy.special.logsumexp(logits, axis=-1)))


def gate_entropy(probs):
    """Per-token gate entropy (T,) fp32 — the collapse detector the
    `block{i}/moe/gate_entropy` tap carries (mean -> average entropy;
    near-zero mean means the router collapsed to single experts)."""
    plogp = jnp.where(probs > 0,
                      probs * jnp.log(jnp.maximum(probs, 1e-30)), 0.0)
    return -jnp.sum(plogp, axis=-1)
