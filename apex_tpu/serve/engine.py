"""Continuous-batching decode engine (ISSUE 8 tentpole, layer 3).

The scheduler problem: requests arrive and finish at their own pace,
but XLA compiles one program per argument-shape signature — a naive
server that batches "whatever is live right now" recompiles on every
admission, and at production churn that is a compile per step.  This
engine holds the compiled program's shapes FIXED forever and moves
only VALUES underneath it:

  * decoding runs over a fixed grid of ``n_slots`` request slots; a
    slot is active when its ``lengths`` entry is nonzero and its
    ``done`` flag is clear — admission and retirement flip values in
    these arrays, never shapes;
  * the paged KV pool and the block table are fixed-shape
    (serve/kv_cache.py); admission points a slot's table row at
    freshly reserved pages, retirement returns them;
  * per-slot decode state (position, current token, generated count,
    done flag, output ring) lives ON DEVICE, so the jitted decode
    step reads and writes it without a single host sync (HS4xx-clean:
    the step contains no .item()/host branch on traced values);
  * inactive slots ride through the step as exact no-ops: the decode
    kernel returns zeros for length-0 slots and their K/V writes are
    routed to the trash page, so a half-empty server pays the fixed
    grid, never a recompile.

The shape contract is ENFORCED, not hoped for: the decode step is
wrapped in a `RecompileSentry` (monitor.compile) which the engine
marks steady after warmup — any later retrace raises in the churn
test and fails bench.py's `serve_recompile_ok` stamp.

The ONLY host/device traffic in steady state is the scheduler's
retirement poll (one (n_slots,) bool + one (n_slots,) int32 fetched
between steps) and the output rows of slots that finished — both
outside the jitted step, both O(n_slots), both independent of
sequence length.

Failure semantics (ISSUE 14 — docs/serving.md "Overload & failure
semantics" is the operator story):

  * per-request DEADLINES — `submit(deadline_ms=)`; expired requests
    are evicted from the queue at admit time and from live slots at
    the retire poll (pages released, ledger terminal `expired`), so a
    stuck client never strands pool pages;
  * CANCELLATION — `cancel(rid)` removes a queued request outright and
    ends a mid-generation one through the existing `done` mask (a
    host-side value edit: no new compiled shapes, the RecompileSentry
    stays green);
  * OVERLOAD CONTROL — a bounded admission queue
    (`ServeConfig.max_queue_depth`) with a shed policy (`shed-newest`
    / `shed-lowest-deadline`), plus an SLO-driven proactive shed: with
    a `ServeSLO(max_queue_wait_ms=)` attached the engine sheds when
    the PROJECTED queue wait of a new arrival would breach — before
    the queue-wait plane breaches, not after.  Backpressure surfaces
    through `submit()` (`last_shed_rid`), the `overloaded` property,
    and `gauges()['queue_saturation']`;
  * WATCHDOG + DRAIN — `serve.watchdog.EngineWatchdog` detects a
    stalled decode loop (no retire-poll progress within a timeout) and
    restarts from `state_dict()` with bitwise mid-generation resume;
    `drain()` stops admission, finishes live slots, and returns a
    restorable snapshot for deploys.  The retire poll validates
    retiring token ids (`PoisonedOutputError` on garbage — the
    `serve.poison_logits` chaos point makes it reachable), and
    `scripts/serve_chaos_probe.py` is the standing kill/overload gate.

Model: the engine decodes `apex_tpu.models.gpt.GPT` weight pytrees
(the flagship LM) on a single device — the forward here mirrors
GPT._block op-for-op (same LayerNorm, same packed-QKV split order as
ops.fused_dense.qkv_split_heads, same fp32-accumulated GEMMs) so a
checkpoint trained by the training stack serves unchanged.  Prefill
runs the prompt densely at a fixed padded length (one compile,
reused for every admission); decode runs the paged flash-decode
kernel (ops/flash_decode.py).  Sampling is greedy argmax — the
deterministic baseline the parity and churn tests pin.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import time
from typing import Any, Dict, List, NamedTuple, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from apex_tpu.checkpoint import chaos as _chaos
from apex_tpu.ops.flash_decode import flash_decode
from apex_tpu.ops.layer_norm import fused_layer_norm
from apex_tpu.serve.kv_cache import (TRASH_PAGE, KVCacheConfig,
                                     PagedKVCache, default_page_size)
from apex_tpu.serve.telemetry import (ServeTelemetry,
                                      step_latency_percentiles)

_NEG_INF = -1e30

# decode-step warmup allowance before the sentry is force-marked
# steady: the legitimate compiles are the first call (+ a possible
# donated-layout second); a step that retraced EVERY call would
# otherwise never leave warmup and the recompile gate would fail OPEN
_STEADY_WARMUP_CAP = 6

# admission/shed policies for the bounded queue (ISSUE 14)
SHED_POLICIES = ("shed-newest", "shed-lowest-deadline")


class PoisonedOutputError(RuntimeError):
    """The retire poll fetched token ids outside [0, vocab) for a
    finishing slot — the decode plane emitted garbage (a poisoned
    logits path; the `serve.poison_logits` chaos point injects it).
    Recovery is a restart from the last good snapshot (the
    EngineWatchdog's contract)."""

    def __init__(self, msg: str, slot: Optional[int] = None,
                 request_id: Optional[int] = None,
                 step: Optional[int] = None):
        super().__init__(msg)
        self.slot = slot
        self.request_id = request_id
        self.step = step


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Static serving-side knobs.  The shape-bearing fields bake into
    the compiled step — change one and you have a NEW deployment,
    which is the point: nothing a request carries can retrace the
    step.  The overload-control fields (`max_queue_depth`,
    `shed_policy`) are HOST scheduler policy only — they never touch
    a compiled shape and are deliberately absent from the deployment
    fingerprint (a snapshot restores across a policy change).

    n_pages None sizes the pool so `pool_fraction` of the worst case
    (every slot at max_prompt_len + max_new_cap) fits — the paged
    saving shows up as pool_fraction < 1.  eos_id None disables EOS
    termination (requests run to their max_new_tokens).
    max_queue_depth None keeps the legacy unbounded queue; a bound
    arms the shed path (docs/serving.md, ISSUE 14)."""

    n_slots: int = 64
    max_prompt_len: int = 128
    max_new_cap: int = 128
    eos_id: Optional[int] = None
    page_size: Optional[int] = None
    n_pages: Optional[int] = None
    pool_fraction: float = 0.5
    cache_dtype: Any = None          # None → the model compute dtype
    emit_logits: bool = False        # decode also returns (slots, V) logits
    max_queue_depth: Optional[int] = None
    shed_policy: str = "shed-newest"


@dataclasses.dataclass
class FinishedRequest:
    """One ended request: the host-side result `poll()` hands back.
    `status` is the terminal state (serve/telemetry.py): "ok" carries
    the full generation; "expired"/"cancelled" carry the partial
    tokens generated before eviction (informational — the client
    already stopped caring); "shed" carries none."""

    request_id: int
    prompt: List[int]
    tokens: List[int]                # generated ids (greedy), EOS included
    n_prompt: int = 0
    status: str = "ok"

    def __post_init__(self):
        self.n_prompt = len(self.prompt)


@dataclasses.dataclass
class _Request:
    """Host scheduler bookkeeping for one queued or live request.
    `deadline_t`/`submit_t` are perf_counter-absolute; the snapshot
    serializes them as AGES so they survive a cross-process restore."""

    rid: int
    prompt: List[int]
    max_new: int
    submit_t: float
    deadline_t: Optional[float] = None
    deadline_ms: Optional[float] = None

    def expired(self, now: float) -> bool:
        return self.deadline_t is not None and now >= self.deadline_t


class DecodeState(NamedTuple):
    """Per-slot device state — every leaf is (n_slots, ...) and fixed
    shape; the decode step donates and returns it."""

    block_table: jnp.ndarray     # (n_slots, pages_per_slot_max) i32
    lengths: jnp.ndarray         # (n_slots,) i32 — tokens IN the cache
    cur_tokens: jnp.ndarray      # (n_slots,) i32 — next token to decode
    n_generated: jnp.ndarray     # (n_slots,) i32
    max_new: jnp.ndarray         # (n_slots,) i32 — per-request budget
    done: jnp.ndarray            # (n_slots,) bool
    out_tokens: jnp.ndarray      # (n_slots, max_new_cap) i32


class _Step:
    """A jitted step with the audit metadata the observatory readers
    expect (`.lower`/`.jitted`/`.arg_names`/`.donate_argnums` — the
    same attachment contract as ddp.make_train_step), so
    `analyze_step`, `lint_step` and the RecompileSentry all see the
    EXACT serving program."""

    def __init__(self, fn, arg_names, donate_argnums):
        self.jitted = jax.jit(fn, donate_argnums=donate_argnums)
        self.lower = self.jitted.lower
        self.arg_names = tuple(arg_names)
        self.donate_argnums = tuple(donate_argnums)

    def __call__(self, *args):
        return self.jitted(*args)


def choose_shed_victim(candidates, policy: str):
    """The ONE shed-policy spelling (serve_chaos_probe's selftest
    replays it engine-free).  `candidates` are queued requests in FIFO
    order with the INCOMING request last; each carries `.rid` and
    `.deadline_t` (None = no deadline).  Returns the victim:

    * `shed-newest` — the incoming request: the queue's FIFO promise
      to earlier arrivals holds, the late arrival absorbs the overload;
    * `shed-lowest-deadline` — the EARLIEST-deadline candidate: it has
      the least slack and is the likeliest to expire in the queue
      anyway, so shedding it wastes the least feasible work.
      Deadline-less requests (infinite slack) are shed last; ties
      break toward the newest (highest rid) — the FIFO tilt again."""
    if policy == "shed-newest":
        return candidates[-1]
    if policy != "shed-lowest-deadline":
        raise ValueError(f"unknown shed policy {policy!r}; choices: "
                         f"{SHED_POLICIES}")
    return min(candidates,
               key=lambda r: (r.deadline_t if r.deadline_t is not None
                              else math.inf, -r.rid))


def _dot(x, w, b=None):
    """The TP layers' GEMM spelling (fp32 accumulate, cast back, bias
    in compute dtype) so served logits match trained logits."""
    y = jnp.dot(x, w, preferred_element_type=jnp.float32).astype(x.dtype)
    if b is not None:
        y = y + b.astype(y.dtype)
    return y


class DecodeEngine:
    """Continuous-batching server over a GPT weight pytree.

    >>> eng = DecodeEngine(model_cfg, params, ServeConfig(n_slots=64))
    >>> rid = eng.submit([1, 2, 3], max_new_tokens=16)
    >>> while eng.pending:
    ...     eng.step()
    ...     for fin in eng.poll(): ...

    `step()` = retire finished slots → admit queued requests (prefill)
    → one decode step for ALL slots.  The decode step is sentry-wrapped
    and auto-marked steady after its first stable call;
    `recompile_ok` is False the moment a steady-state retrace happens.
    """

    def __init__(self, model_cfg, params, serve_cfg: ServeConfig,
                 recorder=None, telemetry=True, slo=None):
        c, s = model_cfg, serve_cfg
        if c.hidden % c.num_heads:
            raise ValueError(
                f"num_heads={c.num_heads} must divide hidden={c.hidden} "
                "(head_dim = hidden // num_heads)")
        if s.shed_policy not in SHED_POLICIES:
            raise ValueError(
                f"shed_policy {s.shed_policy!r} not in {SHED_POLICIES}")
        if s.max_queue_depth is not None and s.max_queue_depth < 1:
            raise ValueError(
                f"max_queue_depth must be >= 1 (or None for unbounded), "
                f"got {s.max_queue_depth}")
        self.model_cfg = c
        self.serve_cfg = s
        self.params = params
        max_len = s.max_prompt_len + s.max_new_cap
        if max_len > c.seq_len:
            raise ValueError(
                f"max_prompt_len + max_new_cap = {max_len} exceeds the "
                f"model's seq_len {c.seq_len} (no positions for it)")
        cache_dtype = s.cache_dtype if s.cache_dtype is not None else c.dtype
        # page size first (tuner-owned) — the pool is sized in pages
        page = (s.page_size if s.page_size is not None else
                default_page_size(c.num_heads, c.head_dim, cache_dtype))
        per_slot = -(-max_len // page)
        n_pages = s.n_pages
        if n_pages is None:
            worst = s.n_slots * per_slot
            n_pages = 1 + max(per_slot, int(math.ceil(
                worst * s.pool_fraction)))
        self.kv_config = KVCacheConfig(
            n_layers=c.num_layers, n_kv_heads=c.num_heads,
            head_dim=c.head_dim, n_slots=s.n_slots, n_pages=n_pages,
            pages_per_slot_max=per_slot, page_size=page,
            dtype=cache_dtype)
        self.cache = PagedKVCache(self.kv_config)
        k_pages, v_pages = self.cache.init_pages()
        self.kv = {"k_pages": k_pages, "v_pages": v_pages}
        ns = s.n_slots
        zi = lambda *sh: jnp.zeros(sh, jnp.int32)  # noqa: E731
        self.state = DecodeState(
            block_table=self.cache.device_table(),
            lengths=zi(ns), cur_tokens=zi(ns), n_generated=zi(ns),
            max_new=zi(ns), done=jnp.zeros((ns,), bool),
            out_tokens=zi(ns, s.max_new_cap))

        self.decode_step = _Step(self._decode_fn,
                                 ("params", "kv_cache", "state"), (1, 2))
        self._prefill = _Step(
            self._prefill_fn,
            ("params", "kv_cache", "state", "slot", "tokens", "length",
             "req_max_new"), (1, 2))
        from apex_tpu.monitor.compile import RecompileSentry
        # retained so a watchdog restart can rebuild the replacement
        # engine with the SAME flight recorder (post-incident
        # observability must survive the incident)
        self.recorder = recorder
        self.sentry = RecompileSentry(self.decode_step,
                                      name="serve_decode",
                                      recorder=recorder, warn=True)
        self._steady = False
        self.last_logits = None

        self._next_rid = 0
        self._pending = collections.deque()    # _Request, FIFO
        self._free_slots = list(range(ns - 1, -1, -1))
        self._live: Dict[int, _Request] = {}   # slot -> _Request
        self._finished: List[FinishedRequest] = []
        # resilience plane (ISSUE 14)
        self._draining = False
        self._stalled = False
        self._evict_status: Dict[int, str] = {}   # slot -> "cancelled"
        self.steps_completed = 0     # retire-poll progress counter (the
        #                              EngineWatchdog's heartbeat: a
        #                              stalled step never bumps it)
        self.last_shed_rid: Optional[int] = None  # per-submit signal
        self.watchdog = None         # set by EngineWatchdog.__init__

        # serving observatory (ISSUE 10): the request-lifecycle ledger
        # + gauges.  Pure host bookkeeping — the compiled decode step
        # and its outputs are bitwise identical telemetry on vs off
        # (slo_probe enforces it).  telemetry= accepts True (default
        # ServeTelemetry), a ServeTelemetry instance (custom caps), or
        # False/None (off).  slo= is an optional ServeSLO whose
        # verdict `serve_record()` stamps as `serve_slo_ok`.
        if telemetry is True:
            telemetry = ServeTelemetry()
        self.telemetry = telemetry or None
        self.slo = slo
        # requests admitted since the last retire poll: their prefill/
        # decode is bounded by the NEXT poll's device fetch, which is
        # where their first-token stamp is taken (telemetry module
        # docstring — the zero-extra-syncs timestamp discipline)
        self._awaiting_first: List[int] = []
        if (recorder is not None and self.telemetry is not None
                and hasattr(recorder, "attach_serve")):
            recorder.attach_serve(self)

    # ------------------------------------------------------------------
    # model forward pieces (mirror models.gpt.GPT._block op-for-op)
    # ------------------------------------------------------------------

    def _split_qkv(self, qkv):
        """(rows, 3H) → three (rows, nh, d), SAME packing order as
        ops.fused_dense.qkv_split_heads ((..., 3, nh, d) major-to-
        minor), so trained checkpoints serve unchanged."""
        c = self.model_cfg
        rows = qkv.shape[0]
        qkv = qkv.reshape(rows, 3, c.num_heads, c.head_dim)
        return qkv[:, 0], qkv[:, 1], qkv[:, 2]

    def _mlp(self, bp, x):
        h = fused_layer_norm(x, bp["ln2"]["weight"], bp["ln2"]["bias"])
        m = _dot(h, bp["fc1"]["weight"], bp["fc1"]["bias"])
        m = jax.nn.gelu(m, approximate=True)
        return _dot(m, bp["fc2"]["weight"], bp["fc2"]["bias"])

    def _logits(self, params, h):
        """Tied-embedding LM head, fp32 logits (≡ GPT.logits_local)."""
        w = params["embed"]["weight"]
        return jnp.dot(h, w.T, preferred_element_type=jnp.float32)

    def _write_layer(self, kv, layer, pos_flat, k_new, v_new):
        """Scatter one layer's new K/V rows into the paged pool.
        pos_flat: (rows,) flattened page*page_size + offset positions
        (trash-page routed where masked); k_new/v_new: (rows, hkv, d).
        """
        cfg = self.kv_config
        hkv, npg, page, d = (cfg.n_kv_heads, cfg.n_pages, cfg.page_size,
                             cfg.head_dim)
        out = {}
        for name, new in (("k_pages", k_new), ("v_pages", v_new)):
            flat = kv[name][layer].reshape(hkv, npg * page, d)
            flat = flat.at[:, pos_flat, :].set(
                new.swapaxes(0, 1).astype(flat.dtype))
            out[name] = kv[name].at[layer].set(
                flat.reshape(hkv, npg, page, d))
        return out

    # ------------------------------------------------------------------
    # decode step (jitted; fixed shapes forever)
    # ------------------------------------------------------------------

    def _decode_fn(self, params, kv, state):
        c, s = self.model_cfg, self.serve_cfg
        cfg = self.kv_config
        page = cfg.page_size
        ns = s.n_slots
        scale = 1.0 / math.sqrt(c.head_dim)
        active = (~state.done) & (state.lengths > 0)

        pos = jnp.clip(state.lengths, 0, c.seq_len - 1)
        x = (jnp.take(params["embed"]["weight"], state.cur_tokens, axis=0)
             + jnp.take(params["pos_embed"], pos, axis=0)).astype(c.dtype)

        # the current token's cache position; inactive slots write the
        # trash page (read-harmless, module contract in kv_cache.py)
        page_ids = jnp.take_along_axis(
            state.block_table, (state.lengths // page)[:, None],
            axis=1)[:, 0]
        page_ids = jnp.where(active, page_ids, TRASH_PAGE)
        pos_flat = page_ids * page + state.lengths % page
        # lengths INCLUDING the token being decoded (flash_decode
        # contract); 0 parks inactive slots on the zero-output path
        vis = jnp.where(active, state.lengths + 1, 0)

        for i in range(c.num_layers):
            bp = params[f"block{i}"]
            h = fused_layer_norm(x, bp["ln1"]["weight"],
                                 bp["ln1"]["bias"])
            qkv = _dot(h, bp["qkv"]["weight"], bp["qkv"]["bias"])
            q, k_new, v_new = self._split_qkv(qkv)   # (ns, nh, d)
            kv = self._write_layer(kv, i, pos_flat, k_new, v_new)
            ctx = flash_decode(
                q[:, None], kv["k_pages"][i], kv["v_pages"][i],
                state.block_table, vis, softmax_scale=scale)
            ctx = ctx.reshape(ns, c.hidden).astype(c.dtype)
            x = x + _dot(ctx, bp["proj"]["weight"], bp["proj"]["bias"])
            x = x + self._mlp(bp, x)

        h = fused_layer_norm(x, params["final_ln"]["weight"],
                             params["final_ln"]["bias"])
        logits = self._logits(params, h)             # (ns, V) f32
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)

        n_gen = state.n_generated
        idx = jnp.clip(n_gen, 0, s.max_new_cap - 1)
        arange = jnp.arange(ns)
        prev = state.out_tokens[arange, idx]
        out_tokens = state.out_tokens.at[arange, idx].set(
            jnp.where(active, nxt, prev))
        hit_eos = ((nxt == s.eos_id) if s.eos_id is not None
                   else jnp.zeros((ns,), bool))
        newly_done = active & (hit_eos | (n_gen + 1 >= state.max_new))
        new_state = DecodeState(
            block_table=state.block_table,
            lengths=state.lengths + active.astype(jnp.int32),
            cur_tokens=jnp.where(active, nxt, state.cur_tokens),
            n_generated=n_gen + active.astype(jnp.int32),
            max_new=state.max_new,
            done=state.done | newly_done,
            out_tokens=out_tokens)
        if s.emit_logits:
            return kv, new_state, logits
        return kv, new_state

    # ------------------------------------------------------------------
    # prefill step (jitted once; padded to max_prompt_len)
    # ------------------------------------------------------------------

    def _prefill_fn(self, params, kv, state, slot, tokens, length,
                    req_max_new):
        c, s = self.model_cfg, self.serve_cfg
        cfg = self.kv_config
        page = cfg.page_size
        P = s.max_prompt_len
        scale = 1.0 / math.sqrt(c.head_dim)

        kpos = jnp.arange(P, dtype=jnp.int32)
        x = (jnp.take(params["embed"]["weight"], tokens, axis=0)
             + params["pos_embed"][:P]).astype(c.dtype)

        valid = kpos < length
        table_row = state.block_table[slot]          # (pages_per_slot,)
        page_ids = jnp.take(table_row, kpos // page)
        page_ids = jnp.where(valid, page_ids, TRASH_PAGE)
        pos_flat = page_ids * page + kpos % page
        # padding beyond `length` (and the causal future) is masked by
        # POSITION; its garbage K/V rows land on the trash page
        mask = ((kpos[None, None, :] > kpos[None, :, None])
                | (kpos[None, None, :] >= length))

        for i in range(c.num_layers):
            bp = params[f"block{i}"]
            h = fused_layer_norm(x, bp["ln1"]["weight"],
                                 bp["ln1"]["bias"])
            qkv = _dot(h, bp["qkv"]["weight"], bp["qkv"]["bias"])
            q, k_new, v_new = self._split_qkv(qkv)   # (P, nh, d)
            kv = self._write_layer(kv, i, pos_flat, k_new, v_new)
            st = jnp.einsum("qnd,knd->nqk", q.astype(jnp.float32),
                            k_new.astype(jnp.float32)) * scale
            st = jnp.where(mask, _NEG_INF, st)
            p = jax.nn.softmax(st, axis=-1)
            ctx = jnp.einsum("nqk,knd->qnd", p,
                             v_new.astype(jnp.float32)).astype(c.dtype)
            ctx = ctx.reshape(P, c.hidden)
            x = x + _dot(ctx, bp["proj"]["weight"], bp["proj"]["bias"])
            x = x + self._mlp(bp, x)

        h = fused_layer_norm(x, params["final_ln"]["weight"],
                             params["final_ln"]["bias"])
        h_last = jnp.take(h, jnp.clip(length - 1, 0, P - 1), axis=0)
        logits = self._logits(params, h_last[None])[0]      # (V,) f32
        first = jnp.argmax(logits).astype(jnp.int32)

        done0 = (req_max_new <= 1)
        if s.eos_id is not None:
            done0 = done0 | (first == s.eos_id)
        out_row = jnp.zeros((s.max_new_cap,), jnp.int32).at[0].set(first)
        new_state = DecodeState(
            block_table=state.block_table,
            lengths=state.lengths.at[slot].set(length),
            cur_tokens=state.cur_tokens.at[slot].set(first),
            n_generated=state.n_generated.at[slot].set(1),
            max_new=state.max_new.at[slot].set(req_max_new),
            done=state.done.at[slot].set(done0),
            out_tokens=state.out_tokens.at[slot].set(out_row))
        return kv, new_state

    # ------------------------------------------------------------------
    # host-side scheduler
    # ------------------------------------------------------------------

    @property
    def pending(self) -> int:
        """Requests not yet fully retired (queued + live)."""
        return len(self._pending) + len(self._live)

    @property
    def recompile_ok(self) -> bool:
        return self.sentry.steady_recompiles == 0

    @property
    def draining(self) -> bool:
        return self._draining

    @property
    def stalled(self) -> bool:
        return self._stalled

    # ------------------------------------------------------------------
    # overload control (ISSUE 14)
    # ------------------------------------------------------------------

    def projected_queue_wait_s(self) -> Optional[float]:
        """The queue wait a NEWLY queued request is projected to see:
        queue_depth × mean per-request service time / n_slots (the
        M/M/c head approximation over the ledger's admit→retire
        `service_s` estimator).  None until a request has retired —
        the projection never guesses without data."""
        if self.telemetry is None:
            return None
        svc = self.telemetry.ledger.service.mean
        if svc is None:
            return None
        return len(self._pending) * svc / max(1, self.serve_cfg.n_slots)

    @property
    def overloaded(self) -> bool:
        """The backpressure signal: True when the bounded queue is at
        capacity, or when the SLO projection says a new arrival's
        queue wait would breach `slo.max_queue_wait_ms` — the
        shed-BEFORE-the-breach discipline.  Callers that can defer
        work check this before `submit()`."""
        s = self.serve_cfg
        if (s.max_queue_depth is not None
                and len(self._pending) >= s.max_queue_depth):
            return True
        if self.slo is not None and self.slo.max_queue_wait_ms is not None:
            proj = self.projected_queue_wait_s()
            if proj is not None and 1e3 * proj > self.slo.max_queue_wait_ms:
                return True
        return False

    def _shed_victim(self, incoming: _Request) -> _Request:
        """Pick the request overload control sheds
        (`choose_shed_victim` is the one policy spelling — the chaos
        probe's selftest replays it engine-free).  The victim is
        removed from the queue here when it is a queued one."""
        victim = choose_shed_victim(list(self._pending) + [incoming],
                                    self.serve_cfg.shed_policy)
        if victim is not incoming:
            self._pending.remove(victim)
        return victim

    def _shed(self, req: _Request, now: float) -> None:
        if self.telemetry is not None:
            self.telemetry.ledger.on_shed(req.rid, now)
        self._finished.append(FinishedRequest(
            request_id=req.rid, prompt=req.prompt, tokens=[],
            status="shed"))
        self.last_shed_rid = req.rid

    def _expire_queued(self, req: _Request, now: float) -> None:
        if self.telemetry is not None:
            self.telemetry.ledger.on_expire(req.rid, now, n_tokens=0,
                                            where="queue")
        self._finished.append(FinishedRequest(
            request_id=req.rid, prompt=req.prompt, tokens=[],
            status="expired"))

    def _sweep_expired_queue(self, now: float) -> int:
        """Evict every queued request whose deadline has passed (the
        admit-time half of the TTL contract — no pages were ever
        reserved for these, so eviction is pure host bookkeeping)."""
        if not any(r.deadline_t is not None for r in self._pending):
            return 0
        keep, dropped = [], 0
        for req in self._pending:
            if req.expired(now):
                self._expire_queued(req, now)
                dropped += 1
            else:
                keep.append(req)
        if dropped:
            self._pending = collections.deque(keep)
        return dropped

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               deadline_ms: Optional[float] = None) -> int:
        """Queue a request; returns its request id.  `deadline_ms` is
        a TTL from NOW: a request still queued past it is evicted at
        the admit sweep, a live one at the retire poll (terminal state
        `expired`, pages released either way).

        Backpressure: with a bounded queue (`max_queue_depth`) at
        capacity — or an attached SLO whose queue-wait projection says
        a new arrival would breach — the shed policy picks a victim
        (possibly this request).  The victim ends `shed`: it surfaces
        through `poll()` with that status, and `last_shed_rid` is set
        for the duration of this call (None when nothing was shed) so
        the submitter sees the signal synchronously."""
        s = self.serve_cfg
        if self._draining:
            raise RuntimeError("submit() during drain(): admission is "
                               "stopped — this engine is shutting down")
        prompt = [int(t) for t in prompt]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > s.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} > max_prompt_len "
                f"{s.max_prompt_len}")
        if not 1 <= max_new_tokens <= s.max_new_cap:
            raise ValueError(
                f"max_new_tokens {max_new_tokens} not in "
                f"[1, {s.max_new_cap}]")
        if deadline_ms is not None and deadline_ms <= 0:
            raise ValueError(
                f"deadline_ms must be > 0 (or None), got {deadline_ms}")
        # reject requests NO future state can admit (an explicit small
        # n_pages can undercut the per-slot worst case) — queueing one
        # would spin the engine forever behind a head-of-line request
        # that never fits
        need = self.kv_config.pages_for(len(prompt) + max_new_tokens)
        ceiling = min(self.kv_config.pages_per_slot_max,
                      self.kv_config.usable_pages)
        if need > ceiling:
            raise ValueError(
                f"request needs {need} pages (prompt {len(prompt)} + "
                f"max_new {max_new_tokens} at page_size "
                f"{self.kv_config.page_size}) but this deployment can "
                f"ever serve at most {ceiling} per request")
        now = time.perf_counter()
        rid = self._next_rid
        self._next_rid += 1
        req = _Request(
            rid=rid, prompt=prompt, max_new=int(max_new_tokens),
            submit_t=now,
            deadline_t=(now + deadline_ms / 1e3
                        if deadline_ms is not None else None),
            deadline_ms=deadline_ms)
        if self.telemetry is not None:
            self.telemetry.ledger.on_submit(
                rid, len(prompt), int(max_new_tokens), now,
                deadline_ms=deadline_ms)
        self.last_shed_rid = None
        # expired queue entries are dead weight — drop them BEFORE
        # judging capacity, so a full-of-corpses queue doesn't shed a
        # viable request
        self._sweep_expired_queue(now)
        if self.overloaded:
            victim = self._shed_victim(req)
            self._shed(victim, now)
            if victim is req:
                return rid
        self._pending.append(req)
        return rid

    def cancel(self, request_id: int) -> bool:
        """Cancel a request by id.  In-queue: removed outright
        (terminal `cancelled`, surfaced through `poll()`).
        Mid-generation: the slot's `done` flag is set host-side — a
        VALUE edit on the existing mask, so the compiled step never
        changes — and the next retire poll retires it with the tokens
        generated so far, releasing its pages.  Returns True when the
        request was found live or queued; False for an unknown or
        already-terminal id (cancelling twice is a no-op, not an
        error).  A cancel that races natural completion still reports
        `cancelled` — the client had already stopped listening."""
        for req in self._pending:
            if req.rid == request_id:
                self._pending.remove(req)
                if self.telemetry is not None:
                    self.telemetry.ledger.on_cancel(
                        request_id, time.perf_counter(), n_tokens=0,
                        where="queue")
                self._finished.append(FinishedRequest(
                    request_id=request_id, prompt=req.prompt, tokens=[],
                    status="cancelled"))
                return True
        for slot, req in self._live.items():
            if req.rid == request_id:
                if self._evict_status.get(slot) == "cancelled":
                    return False           # already cancelled, in flight
                self._evict_status[slot] = "cancelled"
                self.state = self.state._replace(
                    done=self.state.done.at[slot].set(True))
                return True
        return False

    def _try_admit(self) -> int:
        """Admit queued requests into free slots while pages last.
        FIFO head-of-line: a request that doesn't fit blocks the queue
        (no starvation of big requests).  Deadline-expired entries are
        swept first — the admit-time half of the TTL contract."""
        admitted = 0
        self._sweep_expired_queue(time.perf_counter())
        while self._pending and self._free_slots:
            req = self._pending[0]
            slot = self._free_slots[-1]
            row = self.cache.allocate_slot(
                slot, len(req.prompt) + req.max_new)
            if row is None:
                break                      # pool exhausted — retry later
            self._pending.popleft()
            self._free_slots.pop()
            self._live[slot] = req
            # admit stamp = the scheduler's decision moment, BEFORE
            # the prefill dispatch: queue wait measures time in the
            # queue, not the admitting prefill's (possibly compiling)
            # dispatch — that cost lands in TTFT, where it belongs
            if self.telemetry is not None:
                self.telemetry.ledger.on_admit(req.rid, slot,
                                               time.perf_counter())
                self._awaiting_first.append(req.rid)
            self.state = self.state._replace(
                block_table=self.cache.device_table())
            padded = np.zeros((self.serve_cfg.max_prompt_len,), np.int32)
            padded[:len(req.prompt)] = req.prompt
            self.kv, self.state = self._prefill(
                self.params, self.kv, self.state, np.int32(slot),
                jnp.asarray(padded), np.int32(len(req.prompt)),
                np.int32(req.max_new))
            admitted += 1
        return admitted

    def _retire_finished(self) -> int:
        """The scheduler's ONLY steady-state device reads: the done
        flags and generated counts (two (n_slots,) fetches), plus the
        output rows of slots that actually finished.  Returns the
        number of slots vacated — normal retirements PLUS deadline
        evictions and cancellations, all of which exit here (one poll,
        one page-release path: the pool can only reconcile one way).

        Before any slot is mutated, finishing tokens are validated
        against the vocab — garbage ids raise `PoisonedOutputError`
        naming the slot/request/step with the engine untouched, so a
        watchdog restart recovers from the last good snapshot."""
        if not self._live:
            return 0
        done = np.asarray(self.state.done)
        # ^ that fetch is the engine's steady-state sync point: it
        # blocks until every previously dispatched step (the admitting
        # prefills and their decode included) has materialized — so
        # the host clock NOW bounds the device-side truth, and the
        # lifecycle stamps below cost no extra sync (ISSUE 10).
        now = time.perf_counter()
        if self.telemetry is not None:
            if self._awaiting_first:
                self.telemetry.ledger.on_first_token(
                    self._awaiting_first, now)
                self._awaiting_first = []
        # the retire-poll half of the TTL contract: live slots whose
        # deadline passed are evicted NOW — their pages go back to the
        # pool instead of decoding for a client that stopped waiting
        expired = [s for s, req in self._live.items()
                   if not done[s] and req.expired(now)]
        if not done.any() and not expired:
            return 0
        n_gen = np.asarray(self.state.n_generated)
        # one wholesale fetch for the wave — per-slot slicing would
        # cost a device round-trip per finished request
        out_tok = np.asarray(self.state.out_tokens)
        leaving = [s for s in sorted(self._live)
                   if done[s] or s in expired]
        # poison guard FIRST, before any bookkeeping mutates: all-or-
        # nothing, the restart path needs a consistent engine to dump.
        # EVERY leaving slot is validated — an expired eviction still
        # delivers its partial tokens, and a corrupted decode plane
        # whose victims all expire (mass client timeout) must trip the
        # guard, not keep serving
        vocab = self.model_cfg.vocab_size
        for slot in leaving:
            toks = out_tok[slot, :int(n_gen[slot])]
            if toks.size and (int(toks.min()) < 0
                              or int(toks.max()) >= vocab):
                rid = self._live[slot].rid
                raise PoisonedOutputError(
                    f"slot {slot} (request {rid}) finished with token "
                    f"ids outside [0, {vocab}) at step "
                    f"{self.steps_completed} — the decode plane "
                    "emitted garbage; restart from the last good "
                    "snapshot", slot=slot, request_id=rid,
                    step=self.steps_completed)
        for slot in leaving:
            req = self._live.pop(slot)
            n = int(n_gen[slot])
            toks = out_tok[slot, :n].tolist()
            if done[slot]:
                status = self._evict_status.pop(slot, "ok")
            else:
                status = "expired"
                self._evict_status.pop(slot, None)
            self._finished.append(
                FinishedRequest(request_id=req.rid, prompt=req.prompt,
                                tokens=toks, status=status))
            if self.telemetry is not None:
                led = self.telemetry.ledger
                if status == "ok":
                    led.on_retire(req.rid, n, now)
                elif status == "cancelled":
                    led.on_cancel(req.rid, now, n_tokens=n, where="live")
                else:
                    led.on_expire(req.rid, now, n_tokens=n, where="live")
            self.cache.release_slot(slot)
            self._free_slots.append(slot)
        idx = jnp.asarray(leaving, jnp.int32)
        self.state = self.state._replace(
            lengths=self.state.lengths.at[idx].set(0),
            n_generated=self.state.n_generated.at[idx].set(0),
            done=self.state.done.at[idx].set(False))
        return len(leaving)

    def step(self):
        """One engine iteration: retire → admit → decode-all-slots.
        Returns (admitted, retired) counts so callers can tell churn
        steps (which carry prefill/cleanup work) from pure decode
        steps — the bench's steady-state latency percentiles exclude
        the former.  `retired` counts every vacated slot: normal
        completions plus deadline evictions and cancellations (the
        ledger splits them by terminal state).

        A step that made retire-poll progress bumps `steps_completed`
        — the EngineWatchdog's heartbeat.  The `serve.stall_step`
        chaos point wedges the engine (no poll, no progress, forever —
        a hung device, not a crash); the watchdog is what notices."""
        if self._stalled or _chaos.fire("serve.stall_step"):
            self._stalled = True
            return 0, 0
        retired = self._retire_finished()
        admitted = 0 if self._draining else self._try_admit()
        if not self._live:
            # fully drained (a non-empty queue always admits into an
            # empty grid — submit() rejected anything that can't):
            # skip the all-inactive decode forward the final retire
            # would otherwise pay for nothing
            self.steps_completed += 1
            if self.telemetry is not None:
                self.telemetry.note_step(admitted, retired, self.gauges())
            return admitted, retired
        out = self.sentry(self.params, self.kv, self.state)
        if self.serve_cfg.emit_logits:
            self.kv, self.state, self.last_logits = out
        else:
            self.kv, self.state = out
        if _chaos.fire("serve.poison_logits"):
            # inject the corruption the retire poll's validity guard
            # exists for: every live slot's output ring turns to
            # garbage ids, detected (by name) when one finishes
            live = jnp.asarray(sorted(self._live), jnp.int32)
            self.state = self.state._replace(
                out_tokens=self.state.out_tokens.at[live].set(-1))
        self.steps_completed += 1
        # first call that did NOT compile = warmup over; from here any
        # retrace is a steady-state recompile (the correctness gate).
        # The warmup cap closes the fail-open hole: a step retracing
        # on every call never has a compile-free call, so it must be
        # forced steady to have its retraces COUNTED, not laundered
        # as perpetual warmup.
        if not self._steady:
            just_compiled = (
                self.sentry.events
                and self.sentry.events[-1]["call"] == self.sentry.calls)
            if (not just_compiled
                    or self.sentry.calls >= _STEADY_WARMUP_CAP):
                self.sentry.mark_steady()
                self._steady = True
        if self.telemetry is not None:
            self.telemetry.note_step(admitted, retired, self.gauges())
        return admitted, retired

    def run(self, max_steps: int = 10_000) -> List[FinishedRequest]:
        """Drive until every submitted request retired; returns them
        in completion order."""
        steps = 0
        while self.pending:
            if steps >= max_steps:
                raise RuntimeError(
                    f"run(): {self.pending} request(s) still live after "
                    f"{max_steps} steps")
            self.step()
            steps += 1
        self._retire_finished()
        return self.poll()

    def poll(self) -> List[FinishedRequest]:
        out, self._finished = self._finished, []
        return out

    def drain(self, max_steps: int = 10_000) -> dict:
        """Graceful shutdown for deploys: STOP admission, run the live
        slots to completion (deadlines and cancellations still apply),
        and return a restorable `state_dict()` snapshot — the
        still-queued requests ride in it, so the replacement engine
        (same deployment, new weights rolled back, new host...) picks
        them up with `load_state_dict` and nothing a client submitted
        is lost.  Finished results remain available via `poll()`.

        The `serve.kill_mid_drain` chaos point kills the loop partway
        (a deploy's own preemption); the PR 9 snapshot contract is the
        recovery — `scripts/serve_chaos_probe.py` drives the matrix."""
        self._draining = True
        try:
            steps = 0
            while self._live:
                _chaos.check("serve.kill_mid_drain")
                if steps >= max_steps:
                    raise RuntimeError(
                        f"drain(): {len(self._live)} slot(s) still live "
                        f"after {max_steps} steps")
                self.step()
                steps += 1
            return self.state_dict()
        finally:
            self._draining = False

    def stats(self) -> dict:
        return {
            "n_slots": self.serve_cfg.n_slots,
            "live": len(self._live),
            "queued": len(self._pending),
            "free_pages": self.cache.free_pages,
            "pool_bytes": self.kv_config.pool_bytes(),
            "recompile_ok": self.recompile_ok,
            "sentry": self.sentry.summary(),
            "draining": self._draining,
            "stalled": self._stalled,
            "steps_completed": self.steps_completed,
        }

    # ------------------------------------------------------------------
    # serving observatory readers (ISSUE 10)
    # ------------------------------------------------------------------

    def gauges(self) -> dict:
        """Instantaneous scheduler/pool gauges — all host-side values
        the scheduler already owns, zero device traffic."""
        cfg = self.kv_config
        used = cfg.usable_pages - self.cache.free_pages
        mqd = self.serve_cfg.max_queue_depth
        return {
            "slots_live": len(self._live),
            "slots_free": len(self._free_slots),
            "queue_depth": len(self._pending),
            "pages_free": self.cache.free_pages,
            "pages_used": used,
            "pool_util": used / max(1, cfg.usable_pages),
            # the backpressure gauge (ISSUE 14): how full the bounded
            # admission queue is; 0.0 under the legacy unbounded queue
            # (there is no capacity to saturate)
            "queue_saturation": (len(self._pending) / mqd
                                 if mqd else 0.0),
        }

    def serve_record(self) -> dict:
        """Flat `serve_*` JSON scalars for `MetricsLogger(serve=eng)`
        (SCHEMA v7): live gauges always, ledger percentiles once
        samples exist, `serve_slo_ok` when an SLO is attached."""
        if self.telemetry is None:
            return {}
        rec = self.telemetry.serve_record()
        if self.watchdog is not None:
            rec["serve_watchdog_stalls"] = int(self.watchdog.stalls)
            rec["serve_watchdog_restarts"] = int(self.watchdog.restarts)
        if self.slo is not None:
            v = self.slo_verdict()
            # only GROUNDED verdicts stamp: a breach always does; a
            # green does only once every configured axis has samples.
            # A fresh/idle engine's all-skipped "ok" is unmeasured,
            # and stamping it would paint an outage window green.
            if v.grounded:
                rec["serve_slo_ok"] = bool(v.ok)
        return rec

    def slo_verdict(self, slo=None):
        """Evaluate `slo` (default: the engine's attached ServeSLO)
        against the live telemetry — the breach report names the
        violated axis and the offending percentile."""
        slo = slo if slo is not None else self.slo
        if slo is None:
            raise ValueError("slo_verdict: no ServeSLO attached or given")
        if self.telemetry is None:
            raise ValueError("slo_verdict: engine built telemetry=False")
        return slo.evaluate(self.telemetry)

    def telemetry_report(self) -> Optional[dict]:
        """The full JSON-safe observatory dict (ledger summary + tail,
        gauges/peaks, step counters, engine stats, SLO verdict when
        attached) — what `FlightRecorder.attach_serve` pulls into a
        crash dump and what `scripts/slo_probe.py` validates."""
        if self.telemetry is None:
            return None
        rep = self.telemetry.report()
        rep["stats"] = self.stats()
        if self.slo is not None:
            rep["slo"] = self.slo.to_dict()
            rep["slo_verdict"] = self.slo_verdict().to_dict()
        return rep

    # ------------------------------------------------------------------
    # checkpoint / preemption resume (ISSUE 9)
    # ------------------------------------------------------------------

    # v2 (ISSUE 14): scheduler entries carry submit AGE and REMAINING
    # deadline (perf_counter absolutes are process-relative, so the
    # snapshot stores deltas and load re-absolutizes them) plus the
    # finished list's terminal statuses — restored in-flight requests
    # keep their original submit stamps and a deadline keeps counting
    # down across the restore.  v1 snapshots are refused by version.
    _SERVE_STATE_VERSION = 2

    def _deployment_fingerprint(self) -> dict:
        """The static knobs that bake into the compiled step — a
        snapshot only restores into the SAME deployment (shapes never
        change; a mismatch would mean silently different programs)."""
        c, s, k = self.model_cfg, self.serve_cfg, self.kv_config
        return {"n_slots": s.n_slots, "max_prompt_len": s.max_prompt_len,
                "max_new_cap": s.max_new_cap, "eos_id": s.eos_id,
                "page_size": k.page_size, "n_pages": k.n_pages,
                "n_layers": c.num_layers, "hidden": c.hidden,
                "num_heads": c.num_heads, "vocab_size": c.vocab_size,
                # dtypes are part of the deployment: a cross-dtype
                # restore would silently cast the KV pool and break
                # the bitwise resume contract without an error
                "cache_dtype": str(jnp.dtype(k.dtype)),
                "model_dtype": str(jnp.dtype(c.dtype))}

    def state_dict(self) -> dict:
        """Host snapshot of EVERYTHING a preempted serving node needs
        to resume mid-generation: the paged KV pool, the per-slot
        DecodeState, the allocator, and the scheduler queues.  The
        weight pytree is deliberately NOT included — weights are the
        deployment artifact, checkpointed separately (the serve-weights
        round-trip test).  Round-trips through
        `checkpoint.save_checkpoint`; restore into a FRESH engine of
        the same deployment via `load_state_dict` and decoding
        continues bitwise where it left off (tests/test_checkpoint.py
        pins the resumed tokens to the unpreempted run's)."""
        jax.block_until_ready((self.kv, self.state))
        snap_t = time.perf_counter()

        def pack(req: _Request) -> list:
            # submit age + remaining deadline: deltas survive the
            # process boundary that perf_counter absolutes do not.  A
            # remaining deadline may be NEGATIVE (already expired at
            # snapshot time) — preserved, so it expires immediately on
            # resume instead of being granted a fresh TTL.
            return [req.rid, list(req.prompt), req.max_new,
                    snap_t - req.submit_t,
                    (req.deadline_t - snap_t
                     if req.deadline_t is not None else None),
                    req.deadline_ms]

        return {
            "serve_state_version": self._SERVE_STATE_VERSION,
            "deployment": self._deployment_fingerprint(),
            "kv": {k: np.asarray(v) for k, v in self.kv.items()},
            "decode_state": {k: np.asarray(v)
                             for k, v in self.state._asdict().items()},
            "cache": self.cache.state_dict(),
            "scheduler": {
                "next_rid": self._next_rid,
                "pending": [pack(r) for r in self._pending],
                "free_slots": list(self._free_slots),
                "live": {int(s): pack(r)
                         for s, r in self._live.items()},
                "evict_status": {int(s): st for s, st
                                 in self._evict_status.items()},
                "finished": [[f.request_id, list(f.prompt),
                              list(f.tokens), f.status]
                             for f in self._finished],
            },
        }

    def load_state_dict(self, d: dict) -> None:
        """Inverse of state_dict into a fresh engine of the SAME
        deployment (the fingerprint is validated field by field — the
        compiled step's shapes depend on every one of them).  The
        restored engine recompiles its decode step on first use (a
        fresh process has an empty jit cache); after that warmup the
        zero-steady-recompile contract holds as before."""
        ver = d.get("serve_state_version")
        if ver != self._SERVE_STATE_VERSION:
            raise ValueError(
                f"serve_state_version {ver!r} != "
                f"{self._SERVE_STATE_VERSION}")
        want = self._deployment_fingerprint()
        got = d.get("deployment") or {}
        bad = [k for k in want if got.get(k) != want[k]]
        if bad:
            raise ValueError(
                "DecodeEngine.load_state_dict: snapshot is from a "
                "different deployment — mismatched " + ", ".join(
                    f"{k} (snapshot {got.get(k)!r} != engine "
                    f"{want[k]!r})" for k in bad))
        cfg = self.kv_config
        self.kv = {k: jnp.asarray(v).astype(cfg.dtype)
                   for k, v in d["kv"].items()}
        ds = {k: jnp.asarray(v) for k, v in d["decode_state"].items()}
        self.state = DecodeState(**ds)
        self.cache.load_state_dict(d["cache"])
        sch = d["scheduler"]
        now = time.perf_counter()

        def unpack(entry) -> _Request:
            rid, p, mn, age, remaining, dl_ms = entry
            return _Request(
                rid=int(rid), prompt=[int(t) for t in p],
                max_new=int(mn), submit_t=now - float(age),
                deadline_t=(now + float(remaining)
                            if remaining is not None else None),
                deadline_ms=(float(dl_ms) if dl_ms is not None
                             else None))

        self._next_rid = int(sch["next_rid"])
        self._pending = collections.deque(
            unpack(e) for e in sch["pending"])
        self._free_slots = [int(s) for s in sch["free_slots"]]
        self._live = {int(s): unpack(e) for s, e in sch["live"].items()}
        self._evict_status = {int(s): str(st) for s, st
                              in sch.get("evict_status", {}).items()}
        self._finished = [
            FinishedRequest(request_id=int(rid), prompt=[int(t) for t in p],
                            tokens=[int(t) for t in toks],
                            status=str(status))
            for rid, p, toks, status in sch["finished"]]
        self._draining = False
        self._stalled = False
        # the ledger is RESTORE-scoped (monotonic stamps die with the
        # process; it is deliberately not in the snapshot): the
        # telemetry is rebuilt FRESH — an in-place rollback on a
        # non-fresh engine would otherwise double-count rids already
        # submitted and strand open records of requests absent from
        # the snapshot, breaking the submitted==admitted==retired
        # reconciliation forever — and the restored requests are then
        # re-registered so retire events keep reconciling: queued ones
        # as fresh submissions (queue wait from the restore point is
        # real), in-flight ones marked `restored` so they count in
        # totals without feeding resume-relative deltas into the
        # latency estimators
        self._awaiting_first = []
        if self.telemetry is not None:
            old = self.telemetry
            self.telemetry = ServeTelemetry(
                tail_cap=old.ledger.tail.maxlen,
                estimator_capacity=old.ledger.ttft.capacity,
                step_time_warmup=old._step_time_warmup)
            led = self.telemetry.ledger
            for req in self._pending:
                led.reopen_restored(req.rid, len(req.prompt),
                                    req.max_new, now,
                                    submit_t=req.submit_t,
                                    deadline_ms=req.deadline_ms)
            for slot, req in self._live.items():
                led.reopen_restored(req.rid, len(req.prompt),
                                    req.max_new, now, slot=slot,
                                    submit_t=req.submit_t,
                                    deadline_ms=req.deadline_ms)


def measure_decode(eng: DecodeEngine, *, warm: int = 2,
                   max_steps: Optional[int] = None,
                   stop=None) -> dict:
    """Drive a loaded engine to completion and measure it — the ONE
    timing convention bench.py's `serve_*` stamps and
    examples/serve_gpt.py both quote (two hand-rolled loops already
    disagreed once; a drift here skews published trajectories).

    Per-step wall time `block_until_ready`s the new state INSIDE the
    timed region: JAX dispatch is async, so an unsynced timer records
    ~0.1 ms of host dispatch while the real decode runs under the NEXT
    step's first device fetch (the same reason the other bench timers
    materialize outputs in-window).  Blocking on any output of the
    step's single executable bounds the whole computation.

    Returns a dict:
      finished        every FinishedRequest, completion order
      per_step_s      raw per-step seconds (head includes compiles)
      steps / churn_steps / pure_decode_steps
      tokens_per_sec  tokens ACTUALLY emitted post-warmup / window
                      seconds (queued or retired slots credit nothing)
      p50_ms / p99_ms per-token latency over PURE decode steps —
                      admission/retirement steps carry prefill/cleanup
                      work and are excluded (`step()` reports churn);
                      pure_decode_steps == 0 marks the degenerate
                      all-churn window where they fall back, with a
                      warning, to every post-warmup step
      admitted / retired  summed step() accounting (what slo_probe
                      reconciles the ledger against)
      ledger          the engine's ledger summary (None when the
                      engine was built telemetry=False)
      recompile_ok    the sentry verdict
      stopped         True when `stop` ended the drive early

    `stop=` (ISSUE 14) is a zero-arg callable polled BETWEEN steps
    once at least one step has been measured; returning True ends the
    drive with work still pending — the graceful-shutdown hook
    (examples/serve_gpt.py's SIGTERM handler sets a flag this reads,
    then hands the remainder to `drain()`).  The returned stats cover
    the steps that actually ran.

    ISSUE 10 re-expressed the percentile math over the ledger's
    module: `telemetry.step_latency_percentiles` is the ONE
    implementation (live telemetry's `step_lat` estimator applies the
    same exclusions), and each synced per-step duration is recorded
    into the engine's telemetry so a live reader sees the same
    convention this function returns (the regression test pins new
    p50/p99 == old on identical recorded durations).
    """
    if not eng.pending:
        raise ValueError("measure_decode: engine has no pending "
                         "requests — submit before measuring")
    per_step, churn, cum_tokens = [], [], []
    finished: List[FinishedRequest] = []
    polled_tokens = 0
    n_admitted = n_retired = 0
    stopped = False
    while eng.pending:
        if stop is not None and per_step and stop():
            stopped = True           # graceful early exit, between steps
            break
        if max_steps is not None and len(per_step) >= max_steps:
            raise RuntimeError(
                f"measure_decode: {eng.pending} request(s) still live "
                f"after {max_steps} steps")
        t0 = time.perf_counter()
        admitted, retired = eng.step()
        jax.block_until_ready(eng.state)
        dt = time.perf_counter() - t0
        per_step.append(dt)
        churned = bool(admitted or retired)
        churn.append(churned)
        n_admitted += admitted
        n_retired += retired
        if eng.telemetry is not None:
            eng.telemetry.record_step_time(dt, churned, warmup=warm)
        fins = eng.poll()
        finished.extend(fins)
        polled_tokens += sum(len(f.tokens) for f in fins)
        cum_tokens.append(
            polled_tokens + int(np.asarray(eng.state.n_generated).sum()))
    # the last step retires the final cohort at ITS start; drain any
    # stragglers the loop exit left unpolled
    n_retired += eng._retire_finished()
    finished.extend(eng.poll())
    w = min(warm, len(per_step) - 1)        # w <= len-1: never empty
    window = per_step[w:]
    win_tokens = int(np.diff([0] + cum_tokens)[w:].sum())
    pct = step_latency_percentiles(per_step, churn, warm=warm)
    if not pct["pure_decode_steps"]:
        # every post-warmup step churned — the percentiles below are
        # churn-contaminated, LOUDLY (pure_decode_steps == 0 marks the
        # record; a silent fallback would stamp prefill bursts as
        # decode latency)
        import warnings
        warnings.warn(
            "measure_decode: no pure decode step in the measurement "
            "window; p50/p99 include admission/retirement work",
            stacklevel=2)
    return {
        "finished": finished,
        "per_step_s": per_step,
        "churn": churn,
        "steps": len(per_step),
        "churn_steps": int(sum(churn)),
        "pure_decode_steps": pct["pure_decode_steps"],
        "tokens_per_sec": win_tokens / sum(window),
        "p50_ms": pct["p50_ms"],
        "p99_ms": pct["p99_ms"],
        "admitted": n_admitted,
        "retired": n_retired,
        "ledger": (eng.telemetry.ledger.summary()
                   if eng.telemetry is not None else None),
        "recompile_ok": eng.recompile_ok,
        "stopped": stopped,
    }


def flagship_n_slots(on_tpu: bool) -> int:
    """The flagship slot-count policy — 64 on TPU, 8 on the CPU smoke
    backend.  Exposed so callers that need the default BEFORE building
    (bench's overload leg sizes its queue bound off it) don't pay a
    throwaway engine construction for one integer."""
    return 64 if on_tpu else 8


def build_flagship_engine(on_tpu: bool, n_slots: Optional[int] = None,
                          seed: int = 0, recorder=None,
                          params=None,
                          serve_overrides: Optional[dict] = None,
                          ) -> DecodeEngine:
    """The ONE serving setup bench.py and the standing gates
    (scripts/lint_step.py serve, scripts/comms_probe.py serve) build —
    one copy, not a drift-prone re-spelling (the lint_step
    `_build_bench_step` convention).  On TPU: GPT-350M-class weights in
    bf16 with the bench prompt/new-token budgets; on a CPU backend a
    smoke config substitutes through the same build path.

    The returned engine's `decode_step` carries the audit metadata
    (`.lower`/`.arg_names`/`.donate_argnums`), so
    `analyze_step(eng.decode_step, (eng.params, eng.kv, eng.state))`
    prices the pool in the budget table's `kv_cache` row.

    `params=` reuses an already-initialized flagship weight pytree
    (bench's concurrency sweep builds one engine per n_slots — the
    seed-identical 350M init would otherwise be paid per level).
    `n_slots=None` takes the flagship default, 64 on TPU / 8 on the
    CPU smoke backend — the ONE place the policy lives (the lint and
    comms gates must probe the same program bench measures).

    `serve_overrides=` replaces ServeConfig fields on top of the
    flagship defaults (bench's overload leg and the chaos probe bound
    the queue this way: `{"max_queue_depth": 16, "shed_policy":
    "shed-lowest-deadline"}`) — shape-bearing overrides make a new
    deployment, scheduler-policy ones don't."""
    from apex_tpu.models.gpt import GPTConfig

    if n_slots is None:
        n_slots = flagship_n_slots(on_tpu)
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, seq_len=1024, hidden=1024,
                        num_layers=24, num_heads=16, dropout=0.0,
                        dtype=jnp.bfloat16)
        sc = ServeConfig(n_slots=n_slots, max_prompt_len=128,
                         max_new_cap=128)
    else:
        cfg = GPTConfig(vocab_size=512, seq_len=64, hidden=64,
                        num_layers=2, num_heads=4, dropout=0.0)
        sc = ServeConfig(n_slots=n_slots, max_prompt_len=16,
                         max_new_cap=16, page_size=8)
    if serve_overrides:
        sc = dataclasses.replace(sc, **serve_overrides)
    if params is None:
        params = _init_gpt_params(cfg, seed)
    return DecodeEngine(cfg, params, sc, recorder=recorder)


def _init_gpt_params(cfg, seed: int):
    from apex_tpu.models.gpt import GPT

    return GPT(cfg).init(jax.random.PRNGKey(seed))
