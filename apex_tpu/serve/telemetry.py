"""Serving observatory (ISSUE 10 tentpole): the request-lifecycle
ledger, streaming SLO percentiles, and queue/pool gauges that make a
RUNNING `DecodeEngine` answer "what is my TTFT p99 right now" — not
only a finished `measure_decode` run after the fact.

Design constraints, in the order they bind:

  * **Zero device syncs on the decode hot path.**  Every number here
    is HOST-side: lifecycle timestamps are `time.perf_counter` stamps
    of scheduler events, per-token counts come from the retire wave's
    already-fetched `n_generated`/`out_tokens`, and the gauges read
    the host-side allocator/queue state the scheduler already owns.
    The decode step's compiled program — and its outputs — are
    bitwise identical telemetry-on vs telemetry-off (the slo_probe
    acceptance check).

  * **Honest timestamps under async dispatch.**  JAX dispatch returns
    before the device finishes, so a stamp taken right after a
    dispatch call would measure host overhead, not decode.  The one
    moment the engine is KNOWN to be caught up is the retire poll at
    the top of each `step()`: `np.asarray(state.done)` blocks until
    every previously dispatched step (the admitting prefill and its
    decode included) has materialized.  So first-token and retire
    stamps are taken at that post-fetch moment — a request admitted
    in step N gets its first-token stamp when step N+1's poll
    completes, which bounds the device-side truth at the engine's own
    one-step granularity without adding a single sync.

  * **Bounded memory at production churn.**  Percentiles stream
    through a fixed-size reservoir (`StreamingPercentiles`: exact
    below capacity, Vitter's algorithm R above it, deterministic
    seeding — tested against the NumPy oracle), and the completed-
    request ledger keeps a bounded tail (the newest `tail_cap`
    records) plus exact lifetime counters; a week-long serving run
    holds the same few hundred KiB as a smoke test.

Per-request derivations (`RequestRecord`):

    queue_wait = admit_t - submit_t          (head-of-line time)
    ttft       = first_token_t - submit_t    (submission -> first token
                                              observable on host)
    decode_s   = retire_t - first_token_t
    per-token  = decode_s / (n_tokens - 1)   (None for 1-token requests:
                                              both stamps ride the same
                                              poll, there is no
                                              per-token signal in them)

`ServeSLO` turns the live estimators into a deployment gate: a
breach report names the violated axis AND the offending percentile
(`scripts/slo_probe.py` is the standing CI gate; its `--selftest`
carries a seeded breach as the negative control).

`step_latency_percentiles` is the ONE implementation of the
per-token-latency-over-pure-decode-steps convention `measure_decode`
has always quoted (bench + examples/serve_gpt.py); re-expressing it
here means live telemetry, bench, and the example cannot drift apart
(the regression test pins the new math to the old on identical
recorded step durations).
"""

from __future__ import annotations

import collections
import dataclasses
import math
import random
from typing import Dict, List, Optional, Sequence

# v2 (ISSUE 14): the ledger grew TERMINAL STATES — before, every
# submitted request was assumed to retire normally; now a request ends
# in exactly one of `ok` / `expired` (deadline passed in queue or in a
# live slot) / `cancelled` (client abandoned, in queue or
# mid-generation) / `shed` (overload control refused it), and the
# lifetime counters balance EXACTLY: n_submitted == n_retired +
# n_expired + n_cancelled + n_shed + n_open (`RequestLedger.balance()`
# is the one spelling, probe- and test-enforced).  Records carry
# `status` + `deadline_ms`, the summary carries the terminal counters
# and the new `service_s` estimator (admit→retire span of OK requests
# — what the engine's proactive-shed projection quotes).
SERVE_TELEMETRY_VERSION = 2

# a request's terminal states (RequestRecord.status; "open" until then)
TERMINAL_STATES = ("ok", "expired", "cancelled", "shed")

# reservoir size: exact percentiles for every CI-scale run (and any
# sane bench sweep), ~32 KiB of floats at production churn
_DEFAULT_ESTIMATOR_CAPACITY = 4096
# completed-request records kept for the crash-dump tail
_DEFAULT_TAIL_CAP = 1024


# ---------------------------------------------------------------------------
# streaming percentiles
# ---------------------------------------------------------------------------


class StreamingPercentiles:
    """Bounded-memory percentile estimator: exact until `capacity`
    samples, then a uniform reservoir (Vitter's algorithm R — each of
    the n seen samples survives with probability capacity/n).

    Deterministic: replacement draws come from a private
    `random.Random(seed)`, so two runs over the same sample stream
    produce the same estimate (the slo_probe fixture depends on it).
    Lifetime `n` / `mean` / `min` / `max` are exact regardless of
    eviction.  `percentile(q)` matches `np.percentile`'s linear
    interpolation over the retained sample, so below capacity the
    estimate IS the oracle (the tiny-sample tests pin equality, the
    beyond-capacity tests pin tolerance)."""

    def __init__(self, capacity: int = _DEFAULT_ESTIMATOR_CAPACITY,
                 seed: int = 0):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self._rng = random.Random(seed)
        self._buf: List[float] = []
        self._sorted: Optional[List[float]] = None
        self.n = 0                       # lifetime count (exact)
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def add(self, x: float) -> None:
        x = float(x)
        if not math.isfinite(x):
            raise ValueError(f"non-finite sample {x!r}")
        self.n += 1
        self._sum += x
        self._min = min(self._min, x)
        self._max = max(self._max, x)
        if len(self._buf) < self.capacity:
            self._buf.append(x)
            self._sorted = None
        else:
            j = self._rng.randrange(self.n)
            if j < self.capacity:
                self._buf[j] = x
                self._sorted = None

    def extend(self, xs: Sequence[float]) -> None:
        for x in xs:
            self.add(x)

    @property
    def mean(self) -> Optional[float]:
        return self._sum / self.n if self.n else None

    @property
    def min(self) -> Optional[float]:
        return self._min if self.n else None

    @property
    def max(self) -> Optional[float]:
        return self._max if self.n else None

    def percentile(self, q: float) -> Optional[float]:
        """np.percentile(..., q) over the retained sample (linear
        interpolation); None when no samples have been seen."""
        if not self._buf:
            return None
        if not 0.0 <= q <= 100.0:
            raise ValueError(f"percentile q={q} not in [0, 100]")
        if self._sorted is None:
            self._sorted = sorted(self._buf)
        s = self._sorted
        if len(s) == 1:
            return s[0]
        pos = (len(s) - 1) * (q / 100.0)
        lo = int(math.floor(pos))
        hi = min(lo + 1, len(s) - 1)
        frac = pos - lo
        return s[lo] * (1.0 - frac) + s[hi] * frac

    def summary(self) -> dict:
        """JSON-safe digest: exact counters + p50/p95/p99 estimates
        (all None when empty — a never-stamped axis, not a zero)."""
        return {
            "n": self.n,
            "retained": len(self._buf),
            "mean": self.mean,
            "min": self.min,
            "max": self.max,
            "p50": self.percentile(50.0),
            "p95": self.percentile(95.0),
            "p99": self.percentile(99.0),
        }


# ---------------------------------------------------------------------------
# the request-lifecycle ledger
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class RequestRecord:
    """One request's lifecycle, host-stamped (monotonic seconds from
    `time.perf_counter` — deltas are meaningful, absolutes are not)."""

    request_id: int
    n_prompt: int
    max_new: int
    submit_t: float
    admit_t: Optional[float] = None
    first_token_t: Optional[float] = None
    retire_t: Optional[float] = None
    n_tokens: int = 0
    slot: Optional[int] = None
    # a request re-registered after a preemption resume: its in-flight
    # stamps are resume-relative, so it counts in the ledger's totals
    # but never feeds the latency estimators.  (Since ISSUE 14 the
    # SUBMIT stamp of a restored request IS its original one — the
    # snapshot preserves submit age — only the admit/first-token
    # re-stamps are resume artifacts.)
    restored: bool = False
    # terminal state (ISSUE 14): "open" until the request ends, then
    # exactly one of TERMINAL_STATES.  `where` records which side of
    # the scheduler a non-ok terminal hit ("queue" | "live").
    status: str = "open"
    where: Optional[str] = None
    deadline_ms: Optional[float] = None

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.admit_t is None:
            return None
        return self.admit_t - self.submit_t

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def decode_s(self) -> Optional[float]:
        if self.retire_t is None or self.first_token_t is None:
            return None
        return self.retire_t - self.first_token_t

    @property
    def per_token_s(self) -> Optional[float]:
        """Decode seconds per generated token AFTER the first; None
        when there is no per-token signal: below 2 tokens, and
        whenever the first-token and retire stamps rode the SAME poll
        (a request that finished within its admitting step has a zero
        decode span — feeding 0.0 would deflate the latency
        estimator, not measure it)."""
        d = self.decode_s
        if d is None or d <= 0.0 or self.n_tokens < 2:
            return None
        return d / (self.n_tokens - 1)

    def to_dict(self) -> dict:
        return {
            "request_id": self.request_id,
            "n_prompt": self.n_prompt,
            "max_new": self.max_new,
            "n_tokens": self.n_tokens,
            "slot": self.slot,
            "submit_t": self.submit_t,
            "admit_t": self.admit_t,
            "first_token_t": self.first_token_t,
            "retire_t": self.retire_t,
            "queue_wait_s": self.queue_wait_s,
            "ttft_s": self.ttft_s,
            "per_token_s": self.per_token_s,
            "restored": self.restored,
            "status": self.status,
            "where": self.where,
            "deadline_ms": self.deadline_ms,
        }


class RequestLedger:
    """submit -> admit -> first-token -> retire, for every request.

    Open records (submitted, not yet retired) live in a dict keyed by
    request id; retiring a request derives its queue-wait / TTFT /
    per-token latency, feeds the streaming estimators, and moves the
    record to the bounded `tail` (newest `tail_cap` — the crash-dump
    attachment).  Lifetime counters are exact and are the numbers the
    slo_probe reconciles against the engine's own `(admitted,
    retired)` step accounting."""

    def __init__(self, tail_cap: int = _DEFAULT_TAIL_CAP,
                 estimator_capacity: int = _DEFAULT_ESTIMATOR_CAPACITY):
        self._open: Dict[int, RequestRecord] = {}
        self.tail = collections.deque(maxlen=tail_cap)
        self.n_submitted = 0
        self.n_admitted = 0
        self.n_retired = 0
        self.tokens_emitted = 0
        # terminal-state counters (ISSUE 14).  The `_queue`/`_live`
        # split records WHERE a request died — the reconciliation
        # teeth: queue-side terminals never touched a slot, live-side
        # ones exited through the retire poll like a normal retire.
        self.n_expired_queue = 0
        self.n_expired_live = 0
        self.n_cancelled_queue = 0
        self.n_cancelled_live = 0
        self.n_shed = 0
        # distinct seeds: identical sample streams into two estimators
        # must not share an eviction pattern
        self.queue_wait = StreamingPercentiles(estimator_capacity, seed=1)
        self.ttft = StreamingPercentiles(estimator_capacity, seed=2)
        self.token_lat = StreamingPercentiles(estimator_capacity, seed=3)
        # admit→retire span of OK requests: the per-request service
        # time the engine's proactive-shed projection quotes
        self.service = StreamingPercentiles(estimator_capacity, seed=5)

    # ----------------------------- hooks -----------------------------

    def on_submit(self, request_id: int, n_prompt: int, max_new: int,
                  t: float, deadline_ms: Optional[float] = None) -> None:
        self._open[request_id] = RequestRecord(
            request_id=request_id, n_prompt=n_prompt, max_new=max_new,
            submit_t=t, deadline_ms=deadline_ms)
        self.n_submitted += 1

    def on_admit(self, request_id: int, slot: int, t: float) -> None:
        rec = self._open.get(request_id)
        if rec is None or rec.admit_t is not None:
            raise ValueError(
                f"ledger: admit of request {request_id} that is "
                f"{'already admitted' if rec else 'not open'}")
        rec.admit_t = t
        rec.slot = slot
        self.n_admitted += 1

    def on_first_token(self, request_ids: Sequence[int], t: float) -> None:
        """Stamp first-token for requests whose admitting step's work
        is now bounded (the engine calls this right after the retire
        poll's device fetch — see the module docstring)."""
        for rid in request_ids:
            rec = self._open.get(rid)
            if rec is not None and rec.first_token_t is None:
                rec.first_token_t = t

    def on_retire(self, request_id: int, n_tokens: int, t: float) -> None:
        rec = self._open.pop(request_id, None)
        if rec is None:
            raise ValueError(f"ledger: retire of request {request_id} "
                             "that is not open")
        rec.retire_t = t
        rec.n_tokens = int(n_tokens)
        rec.status = "ok"
        self.n_retired += 1
        self.tokens_emitted += rec.n_tokens
        if rec.restored:
            # totals yes, latency no: the stamps are resume-relative
            self.tail.append(rec)
            return
        if rec.queue_wait_s is not None:
            self.queue_wait.add(rec.queue_wait_s)
        if rec.ttft_s is not None:
            self.ttft.add(rec.ttft_s)
        if rec.per_token_s is not None:
            self.token_lat.add(rec.per_token_s)
        if rec.admit_t is not None:
            self.service.add(t - rec.admit_t)
        self.tail.append(rec)

    def _close_terminal(self, request_id: int, t: float, status: str,
                        where: str, n_tokens: int) -> RequestRecord:
        rec = self._open.pop(request_id, None)
        if rec is None:
            raise ValueError(
                f"ledger: {status} of request {request_id} that is "
                "not open")
        rec.retire_t = t
        rec.n_tokens = int(n_tokens)
        rec.status = status
        rec.where = where
        # non-ok terminals count in the totals and ride the tail but
        # NEVER feed the latency estimators: the SLO percentiles judge
        # the latency of requests the engine actually served — a shed
        # request's zero-length "service" or an expired request's
        # deadline-capped wait would deflate/skew them, not measure
        # them (tokens_emitted likewise counts only delivered output)
        self.tail.append(rec)
        return rec

    def on_expire(self, request_id: int, t: float, n_tokens: int = 0,
                  where: str = "queue") -> None:
        """Terminal `expired`: the request's deadline passed — in the
        queue (never admitted; evicted at the admit sweep) or in a
        live slot (evicted at the retire poll, partial tokens noted
        but not delivered)."""
        self._close_terminal(request_id, t, "expired", where, n_tokens)
        if where == "queue":
            self.n_expired_queue += 1
        else:
            self.n_expired_live += 1

    def on_cancel(self, request_id: int, t: float, n_tokens: int = 0,
                  where: str = "queue") -> None:
        """Terminal `cancelled`: the client abandoned the request —
        removed from the queue, or retired mid-generation through the
        `done` mask at the next retire poll."""
        self._close_terminal(request_id, t, "cancelled", where, n_tokens)
        if where == "queue":
            self.n_cancelled_queue += 1
        else:
            self.n_cancelled_live += 1

    def on_shed(self, request_id: int, t: float) -> None:
        """Terminal `shed`: overload control refused the request at
        admission (bounded queue full, or the SLO projection said a
        new arrival would breach the queue-wait contract)."""
        self._close_terminal(request_id, t, "shed", "queue", 0)
        self.n_shed += 1

    def reopen_restored(self, request_id: int, n_prompt: int,
                        max_new: int, t: float,
                        slot: Optional[int] = None,
                        submit_t: Optional[float] = None,
                        deadline_ms: Optional[float] = None) -> None:
        """Re-register a request restored from a preemption snapshot
        (`DecodeEngine.load_state_dict`).  Since ISSUE 14 the snapshot
        preserves each request's submit AGE, so restored requests keep
        their ORIGINAL submit stamps (`submit_t=`, already
        re-absolutized by the engine) — a restored queued request's
        queue wait includes the time it already spent waiting before
        the preemption.  In-flight requests additionally stamp
        admit/first-token at the restore moment and are marked
        `restored`, so they reconcile in the counters without feeding
        resume-relative admit deltas into the latency estimators."""
        self.on_submit(request_id, n_prompt, max_new,
                       t if submit_t is None else submit_t,
                       deadline_ms=deadline_ms)
        if slot is not None:
            self.on_admit(request_id, slot, t)
            self.on_first_token([request_id], t)
            self._open[request_id].restored = True

    # --------------------------- readers -----------------------------

    @property
    def n_open(self) -> int:
        return len(self._open)

    @property
    def n_expired(self) -> int:
        return self.n_expired_queue + self.n_expired_live

    @property
    def n_cancelled(self) -> int:
        return self.n_cancelled_queue + self.n_cancelled_live

    def balance(self) -> dict:
        """The exact-reconciliation identity (ISSUE 14): every
        submitted request is in exactly one terminal state or still
        open, and every admitted request either retired normally or
        was evicted from a live slot.  Returns the two residuals
        (both MUST be zero) plus the terms — the probe and the tests
        assert `ok`."""
        submitted_residual = self.n_submitted - (
            self.n_retired + self.n_expired + self.n_cancelled
            + self.n_shed + self.n_open)
        admitted_residual = self.n_admitted - (
            self.n_retired + self.n_expired_live + self.n_cancelled_live
            + sum(1 for r in self._open.values()
                  if r.admit_t is not None))
        return {
            "ok": submitted_residual == 0 and admitted_residual == 0,
            "submitted_residual": submitted_residual,
            "admitted_residual": admitted_residual,
            "n_submitted": self.n_submitted,
            "n_admitted": self.n_admitted,
            "n_retired": self.n_retired,
            "n_expired": self.n_expired,
            "n_cancelled": self.n_cancelled,
            "n_shed": self.n_shed,
            "n_open": self.n_open,
        }

    def summary(self) -> dict:
        """JSON-safe digest: exact counters + the estimator summaries
        (seconds; the serve_record stamps convert to ms)."""
        return {
            "n_submitted": self.n_submitted,
            "n_admitted": self.n_admitted,
            "n_retired": self.n_retired,
            "n_expired": self.n_expired,
            "n_expired_queue": self.n_expired_queue,
            "n_expired_live": self.n_expired_live,
            "n_cancelled": self.n_cancelled,
            "n_cancelled_queue": self.n_cancelled_queue,
            "n_cancelled_live": self.n_cancelled_live,
            "n_shed": self.n_shed,
            "n_open": self.n_open,
            "balance_ok": self.balance()["ok"],
            "tokens_emitted": self.tokens_emitted,
            "queue_wait_s": self.queue_wait.summary(),
            "ttft_s": self.ttft.summary(),
            "per_token_s": self.token_lat.summary(),
            "service_s": self.service.summary(),
        }

    def tail_dicts(self) -> List[dict]:
        return [r.to_dict() for r in self.tail]


# ---------------------------------------------------------------------------
# per-step gauges + the aggregate telemetry object
# ---------------------------------------------------------------------------


class ServeTelemetry:
    """Everything the engine's observability plane holds: the ledger,
    per-step churn counters, the newest gauge snapshot + lifetime
    peaks, and the pure-decode step-time estimator fed by synced
    drivers (`measure_decode`).  Owned by `DecodeEngine` (constructed
    with `telemetry=True`, the default); pure host state."""

    def __init__(self, tail_cap: int = _DEFAULT_TAIL_CAP,
                 estimator_capacity: int = _DEFAULT_ESTIMATOR_CAPACITY,
                 step_time_warmup: int = 2):
        self.ledger = RequestLedger(tail_cap=tail_cap,
                                    estimator_capacity=estimator_capacity)
        self.n_steps = 0
        self.churn_steps = 0
        self.gauges: dict = {}
        self.peaks = {"queue_depth": 0, "slots_live": 0, "pool_util": 0.0,
                      "pages_used": 0, "queue_saturation": 0.0}
        # per-token latency over PURE decode steps, the measure_decode
        # convention — fed by drivers that sync per step; the first
        # `step_time_warmup` recorded steps carry compiles and are
        # dropped (reset_step_times() after an explicit warmup also
        # works)
        self.step_lat = StreamingPercentiles(estimator_capacity, seed=4)
        self._step_time_warmup = step_time_warmup
        self._step_times_seen = 0

    # ----------------------------- hooks -----------------------------

    def note_step(self, admitted: int, retired: int, gauges: dict) -> None:
        """One engine `step()`: churn accounting + gauge snapshot.
        Called by the engine on every step, decode or drained."""
        self.n_steps += 1
        if admitted or retired:
            self.churn_steps += 1
        self.gauges = dict(gauges)
        for k in self.peaks:
            v = gauges.get(k)
            if v is not None and v > self.peaks[k]:
                self.peaks[k] = v

    def record_step_time(self, seconds: float, churned: bool,
                         warmup: Optional[int] = None) -> None:
        """A device-synced per-step wall time from a driver that
        blocks per step (measure_decode / slo_probe).  Only pure
        decode steps past the warmup feed the estimator — the same
        exclusions `step_latency_percentiles` applies post-hoc
        (`measure_decode` passes its own `warm=` through so the two
        views cannot disagree; the one residual difference is the
        post-hoc `min(warm, len - 1)` clamp on runs shorter than the
        warmup, which a streaming feed cannot know upfront)."""
        w = self._step_time_warmup if warmup is None else warmup
        self._step_times_seen += 1
        if self._step_times_seen <= w or churned:
            return
        self.step_lat.add(seconds)

    def reset_step_times(self) -> None:
        self.step_lat = StreamingPercentiles(self.step_lat.capacity,
                                             seed=4)
        self._step_times_seen = self._step_time_warmup

    # --------------------------- readers -----------------------------

    def slo_summary(self) -> dict:
        """The axes `ServeSLO.evaluate` judges, in ms.  Missing
        samples are None (an axis with no data is SKIPPED by the
        verdict, never vacuously passed as 0)."""
        def ms(v):
            return None if v is None else 1e3 * v
        return {
            "ttft_p99_ms": ms(self.ledger.ttft.percentile(99.0)),
            "per_token_p99_ms": ms(self.ledger.token_lat.percentile(99.0)),
            "queue_wait_max_ms": ms(self.ledger.queue_wait.max),
            "n_retired": self.ledger.n_retired,
        }

    def serve_record(self) -> dict:
        """Flat `serve_*` JSON scalars for `MetricsLogger(serve=...)`
        (SCHEMA v7).  Gauges stamp always (a serving engine always has
        a queue depth); percentile fields stamp only once samples
        exist — optional-never-null, the v4 rule."""
        g = self.gauges
        rec = {
            "serve_queue_depth": int(g.get("queue_depth", 0)),
            "serve_slots_live": int(g.get("slots_live", 0)),
            "serve_pages_free": int(g.get("pages_free", 0)),
            "serve_pool_util": float(g.get("pool_util", 0.0)),
            "serve_requests_retired": int(self.ledger.n_retired),
            "serve_tokens_emitted": int(self.ledger.tokens_emitted),
        }
        # v10 (ISSUE 14): terminal-state counters — real lifetime
        # counts like requests_retired, stamped always (0 is a real
        # count for a healthy engine, not a missing sample)
        rec["serve_shed_total"] = int(self.ledger.n_shed)
        rec["serve_expired_total"] = int(self.ledger.n_expired)
        rec["serve_cancelled_total"] = int(self.ledger.n_cancelled)
        led = self.ledger
        if led.ttft.n:
            rec["serve_ttft_p50_ms"] = 1e3 * led.ttft.percentile(50.0)
            rec["serve_ttft_p99_ms"] = 1e3 * led.ttft.percentile(99.0)
        if led.token_lat.n:
            rec["serve_token_p50_ms"] = 1e3 * led.token_lat.percentile(50.0)
            rec["serve_token_p99_ms"] = 1e3 * led.token_lat.percentile(99.0)
        if led.queue_wait.n:
            rec["serve_queue_wait_p99_ms"] = (
                1e3 * led.queue_wait.percentile(99.0))
            rec["serve_queue_wait_max_ms"] = 1e3 * led.queue_wait.max
        return rec

    def report(self) -> dict:
        """The full JSON-safe observatory dict — what
        `FlightRecorder.attach_serve` rides into the crash dump and
        what `validate_serve_report` schema-checks."""
        return {
            "serve_telemetry_version": SERVE_TELEMETRY_VERSION,
            "steps": {"n_steps": self.n_steps,
                      "churn_steps": self.churn_steps,
                      "pure_decode_step_s": self.step_lat.summary()},
            "gauges": dict(self.gauges),
            "peaks": dict(self.peaks),
            "ledger": self.ledger.summary(),
            "ledger_tail": self.ledger.tail_dicts(),
        }


_REQUIRED_REPORT = ("serve_telemetry_version", "steps", "gauges", "peaks",
                    "ledger", "ledger_tail")
_REQUIRED_LEDGER = ("n_submitted", "n_admitted", "n_retired", "n_open",
                    "n_expired", "n_cancelled", "n_shed", "balance_ok",
                    "tokens_emitted", "queue_wait_s", "ttft_s",
                    "per_token_s", "service_s")
_REQUIRED_EST = ("n", "retained", "mean", "min", "max", "p50", "p95", "p99")


def validate_serve_report(report: dict) -> None:
    """Raise ValueError unless `report` matches the current serve-
    telemetry schema — the slo_probe `--selftest` fixture-drift gate
    (exact version pin, the flight-report convention: a drifted
    fixture must fail loudly, not render garbage)."""
    if not isinstance(report, dict):
        raise ValueError(f"report is {type(report).__name__}, want dict")
    for k in _REQUIRED_REPORT:
        if k not in report:
            raise ValueError(f"missing serve report field {k!r}")
    if report["serve_telemetry_version"] != SERVE_TELEMETRY_VERSION:
        raise ValueError(
            f"serve_telemetry_version "
            f"{report['serve_telemetry_version']!r} != "
            f"{SERVE_TELEMETRY_VERSION}")
    led = report["ledger"]
    if not isinstance(led, dict):
        raise ValueError("ledger is not a dict")
    for k in _REQUIRED_LEDGER:
        if k not in led:
            raise ValueError(f"missing ledger field {k!r}")
    for axis in ("queue_wait_s", "ttft_s", "per_token_s", "service_s"):
        est = led[axis]
        if not isinstance(est, dict):
            raise ValueError(f"ledger estimator {axis!r} is not a dict")
        for k in _REQUIRED_EST:
            if k not in est:
                raise ValueError(
                    f"ledger estimator {axis!r} missing field {k!r}")
    for k in ("n_submitted", "n_admitted", "n_retired", "n_open",
              "n_expired", "n_cancelled", "n_shed", "tokens_emitted"):
        if not isinstance(led[k], int) or isinstance(led[k], bool):
            raise ValueError(f"ledger counter {k!r} is not an int")
    if not isinstance(led["balance_ok"], bool):
        raise ValueError("ledger balance_ok is not a bool")
    if not isinstance(report["ledger_tail"], list):
        raise ValueError("ledger_tail is not a list")
    for i, rec in enumerate(report["ledger_tail"]):
        for k in ("request_id", "n_tokens", "submit_t", "retire_t"):
            if k not in rec:
                raise ValueError(f"ledger_tail[{i}] missing field {k!r}")


# ---------------------------------------------------------------------------
# SLO config + verdict
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class SLOBreach:
    """One violated axis: which, at which percentile, by how much."""

    axis: str            # "ttft" | "per_token" | "queue_wait"
    percentile: str      # "p99" | "max"
    observed_ms: float
    limit_ms: float

    def describe(self) -> str:
        return (f"{self.axis} {self.percentile} "
                f"{self.observed_ms:.3f} ms > SLO {self.limit_ms:.3f} ms")


@dataclasses.dataclass
class SLOVerdict:
    """`ok` is the gate; `breaches` name every violated axis;
    `skipped` lists configured axes that had NO samples (a fresh
    engine can't pass or fail — slo_probe treats a skipped axis it
    expected to measure as its own failure); `n_judged` counts the
    axes that were actually compared — an all-skipped verdict has
    `ok=True, n_judged=0`, which readers (the `serve_slo_ok` stamp)
    must treat as unmeasured, not green."""

    ok: bool
    breaches: List[SLOBreach]
    skipped: List[str]
    summary: dict
    n_judged: int = 0

    @property
    def grounded(self) -> bool:
        """True when this verdict carries real information: a breach
        (always real), or every configured axis measured.  A green
        with skipped axes is vacuous and must not be stamped."""
        return (not self.ok) or (self.n_judged > 0 and not self.skipped)

    def describe(self) -> str:
        if self.ok:
            parts = ["serve SLO: OK"]
            if self.skipped:
                parts.append(f"(no samples for: {', '.join(self.skipped)})")
            return " ".join(parts)
        return ("serve SLO: BREACH — "
                + "; ".join(b.describe() for b in self.breaches))

    def to_dict(self) -> dict:
        return {"ok": self.ok,
                "breaches": [dataclasses.asdict(b) for b in self.breaches],
                "skipped": list(self.skipped),
                "n_judged": self.n_judged,
                "grounded": self.grounded,
                "summary": dict(self.summary)}


@dataclasses.dataclass(frozen=True)
class ServeSLO:
    """A deployment's latency contract.  None disables an axis.

    * `ttft_p99_ms` — p99 time-to-first-token (submission to the first
      token being host-observable).
    * `per_token_p99_ms` — p99 per-request decode latency per token
      after the first.
    * `max_queue_wait_ms` — the WORST observed queue wait (a max, not
      a percentile: one starved request is an incident, and a p99
      would launder it at low request counts)."""

    ttft_p99_ms: Optional[float] = None
    per_token_p99_ms: Optional[float] = None
    max_queue_wait_ms: Optional[float] = None

    def evaluate_summary(self, summary: dict) -> SLOVerdict:
        """Judge a `ServeTelemetry.slo_summary()`-shaped dict (the
        fixture path: the slo_probe selftest replays a committed
        summary through the same verdict code the live path uses)."""
        breaches: List[SLOBreach] = []
        skipped: List[str] = []
        n_judged = 0
        axes = (
            ("ttft", "p99", self.ttft_p99_ms,
             summary.get("ttft_p99_ms")),
            ("per_token", "p99", self.per_token_p99_ms,
             summary.get("per_token_p99_ms")),
            ("queue_wait", "max", self.max_queue_wait_ms,
             summary.get("queue_wait_max_ms")),
        )
        for axis, pct, limit, observed in axes:
            if limit is None:
                continue
            if observed is None:
                skipped.append(axis)
                continue
            n_judged += 1
            if observed > limit:
                breaches.append(SLOBreach(
                    axis=axis, percentile=pct,
                    observed_ms=float(observed), limit_ms=float(limit)))
        return SLOVerdict(ok=not breaches, breaches=breaches,
                          skipped=skipped, summary=dict(summary),
                          n_judged=n_judged)

    def evaluate(self, telemetry: "ServeTelemetry") -> SLOVerdict:
        return self.evaluate_summary(telemetry.slo_summary())

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# the one step-latency convention (measure_decode re-expressed)
# ---------------------------------------------------------------------------


def step_latency_percentiles(per_step_s: Sequence[float],
                             churn: Sequence[bool],
                             warm: int = 2) -> dict:
    """Per-token latency percentiles over PURE decode steps — the ONE
    timing convention (previously inlined in `measure_decode`; bench,
    examples/serve_gpt.py, and the live `ServeTelemetry.step_lat`
    estimator all quote it from here now).

    Exclusions, exactly as before: the first `min(warm, len - 1)`
    steps (compiles), then any step that admitted or retired (prefill/
    cleanup work rides in it).  An all-churn window falls back to
    every post-warmup step and marks itself with
    `pure_decode_steps == 0` (callers warn — a silent fallback would
    stamp prefill bursts as decode latency)."""
    import numpy as np

    per_step_s = list(per_step_s)
    churn = list(churn)
    if not per_step_s:
        raise ValueError("step_latency_percentiles: no steps recorded")
    if len(churn) != len(per_step_s):
        raise ValueError(
            f"step_latency_percentiles: {len(per_step_s)} step times vs "
            f"{len(churn)} churn flags")
    w = min(warm, len(per_step_s) - 1)        # never an empty window
    window = per_step_s[w:]
    pure = [t for t, c in zip(window, churn[w:]) if not c]
    decode_only = pure or window
    return {
        "p50_ms": 1e3 * float(np.percentile(decode_only, 50)),
        "p99_ms": 1e3 * float(np.percentile(decode_only, 99)),
        "pure_decode_steps": len(pure),
        "window_steps": len(window),
    }
