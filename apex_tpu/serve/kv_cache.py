"""Paged KV cache — a fixed pool of HBM pages shared by every live
sequence (ISSUE 8 tentpole, layer 2).

The serving memory problem: a dense per-slot cache is
``(n_slots, max_model_len)`` whether a user sent 10 tokens or 10k —
worst-case HBM is pinned per CONCURRENT USER, which caps concurrency
at the longest request anyone might send.  Paging breaks that link:
the cache is one pool of ``(page_size, head_dim)`` pages, a sequence
owns only the pages its actual length needs, and a per-slot BLOCK
TABLE names which pool pages hold its tokens (the vLLM design,
re-aimed at XLA's static-shape constraint).

Static-shape contract (the "why shapes never change" half that lives
here; serve/engine.py holds the scheduler half):

  * the pool arrays ``k_pages``/``v_pages`` are allocated ONCE at
    engine construction — ``(n_layers, n_kv_heads, n_pages,
    page_size, head_dim)`` — and never reshaped;
  * the block table is ``(n_slots, pages_per_slot_max)`` int32 and
    never reshaped; admission/retirement edit VALUES only;
  * page 0 is the TRASH page: it is never allocated to a sequence,
    and every masked-out write (inactive slots, prompt padding) is
    routed to it so the scatter that writes new K/V needs no dynamic
    shape or host branch;
  * stale table entries and partial last pages are masked BY POSITION
    in the decode kernel (ops/flash_decode.py), never by data — a
    recycled page needs no cleaning between requests.

Allocation is HOST-side (a free list of page ids) and happens only at
admission/retirement — never inside the jitted decode step, which
sees the table as a plain int32 argument.  Pages for a request are
reserved at admission for its worst case (prompt + max_new_tokens),
so the step can run to completion without the device ever asking the
host for memory; the saving vs a dense cache is that the reservation
is per-REQUEST worst case, not per-SLOT model-length worst case.

``page_size`` is owned by the apex_tpu.tune cache (op ``serve_page``,
key ``tune.serve_page_attrs``) with a deterministic heuristic
fallback, because the page is the decode kernel's kv block: one page
= one DMA per grid step, so the same knob sets the gather granularity
and the pool's internal fragmentation (≤ page_size - 1 tokens wasted
per sequence).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

# the reserved trash page (module contract above)
TRASH_PAGE = 0


class PageAccountingError(ValueError):
    """The free-list accounting was about to be corrupted: a release of
    a slot that holds no pages (double release, or a slot that was
    never allocated).  Raised BY NAME instead of silently extending the
    free list — the resilience eviction paths (deadline expiry,
    cancellation, watchdog restart; ISSUE 14) made the double-release
    reachable for the first time, and a silent one would hand the same
    page to two sequences later."""


def default_page_size(n_kv_heads: int, head_dim: int, dtype=None) -> int:
    """Tuner-owned page size with a deterministic heuristic fallback.

    Consults ``tune.tuned("serve_page", ...)`` for this cache layout on
    this device kind; a miss (or a nonsense cached value) falls back to
    the heuristic: 128 — the TPU lane width, so the per-page score tile
    of the decode kernel fills whole vregs, and at d=64/bf16 a page is
    16 KiB per kv head, a comfortable DMA unit.  Pure host-side lookup,
    safe at trace time (tune package docstring)."""
    try:
        from apex_tpu import tune
        cfg = tune.tuned("serve_page",
                         tune.serve_page_attrs(n_kv_heads, head_dim,
                                               dtype))
    except Exception:  # pragma: no cover — tune must never break serve
        cfg = None
    if cfg:
        ps = cfg.get("page_size")
        if isinstance(ps, int) and 8 <= ps <= 2048 and ps % 8 == 0:
            return ps
    return 128


@dataclasses.dataclass(frozen=True)
class KVCacheConfig:
    """Static layout of the paged pool.

    page_size None consults the autotuner (``serve_page``) and falls
    back to the 128-lane heuristic — an empty cache is deterministic.
    n_pages includes the trash page; ``usable_pages`` is what requests
    can actually own.  pages_per_slot_max bounds one sequence's table
    row (its max length is pages_per_slot_max * page_size)."""

    n_layers: int
    n_kv_heads: int
    head_dim: int
    n_slots: int
    n_pages: int
    pages_per_slot_max: int
    page_size: Optional[int] = None
    dtype: Any = jnp.bfloat16

    def __post_init__(self):
        if self.page_size is None:
            object.__setattr__(
                self, "page_size",
                default_page_size(self.n_kv_heads, self.head_dim,
                                  self.dtype))
        if self.n_pages < 2:
            raise ValueError(
                f"n_pages={self.n_pages}: need at least the trash page "
                "+ one usable page")
        if self.n_slots < 1 or self.pages_per_slot_max < 1:
            raise ValueError("n_slots and pages_per_slot_max must be >= 1")

    @property
    def usable_pages(self) -> int:
        return self.n_pages - 1          # page 0 is the trash page

    @property
    def max_seq_len(self) -> int:
        """Longest sequence one table row can address."""
        return self.pages_per_slot_max * self.page_size

    def pages_for(self, n_tokens: int) -> int:
        """Pages a sequence of n_tokens tokens occupies."""
        return -(-max(0, int(n_tokens)) // self.page_size)

    # ------------------------------ pricing ------------------------------
    # the numbers `analyze_step`'s budget table and docs/serving.md
    # quote: what the pool costs, and what one concurrent user costs

    def page_bytes(self) -> int:
        """HBM bytes of ONE page across all layers, K and V."""
        return (2 * self.n_layers * self.n_kv_heads * self.page_size
                * self.head_dim * jnp.dtype(self.dtype).itemsize)

    def pool_bytes(self) -> int:
        """Total HBM of the page pool (the `kv_cache` budget row)."""
        return self.n_pages * self.page_bytes()

    def bytes_per_token(self) -> int:
        """Cache bytes one token costs (all layers, K+V)."""
        return (2 * self.n_layers * self.n_kv_heads * self.head_dim
                * jnp.dtype(self.dtype).itemsize)

    def bytes_per_user(self, seq_len: int) -> int:
        """Cache bytes one concurrent user at seq_len costs — page
        granularity included (the partial last page is paid in full)."""
        return self.pages_for(seq_len) * self.page_bytes()


class PagedKVCache:
    """The pool + the host-side free-list allocator.

    Device side: ``k_pages``/``v_pages`` jnp arrays in the kernel's
    layout and a ``block_table`` int32 array (all static shapes —
    module contract).  The ENGINE owns the device arrays once decoding
    starts (they ride inside its donated state); this object keeps the
    authoritative host mirror of the table and the free list, and
    hands out fresh device tables after admission edits.

    Host side: ``allocate(n)`` pops page ids from the free list (None
    when the pool can't serve n — the scheduler's admission-control
    signal), ``release(ids)`` returns them.  Page 0 (TRASH_PAGE) is
    never handed out.
    """

    def __init__(self, config: KVCacheConfig):
        self.config = config
        c = config
        self._free: List[int] = list(range(1, c.n_pages))
        # host mirror of the block table; unassigned entries point at
        # the trash page (read-harmless: masked by position)
        self._table = np.full((c.n_slots, c.pages_per_slot_max),
                              TRASH_PAGE, np.int32)
        self._slot_pages: Dict[int, List[int]] = {}

    # ------------------------- device arrays -------------------------

    def init_pages(self):
        """Fresh zeroed (k_pages, v_pages) pool arrays in the decode
        kernel's layout.  Zeros are a convenience, not a correctness
        requirement — the position masking contract means garbage
        would serve equally."""
        c = self.config
        shape = (c.n_layers, c.n_kv_heads, c.n_pages, c.page_size,
                 c.head_dim)
        return jnp.zeros(shape, c.dtype), jnp.zeros(shape, c.dtype)

    def device_table(self):
        """The current block table as a device array (push after
        admission edits; shape never changes)."""
        return jnp.asarray(self._table)

    # ------------------------- allocation ----------------------------

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def can_admit(self, n_tokens: int) -> bool:
        n = self.config.pages_for(n_tokens)
        return n <= len(self._free) and n <= self.config.pages_per_slot_max

    def allocate_slot(self, slot: int, n_tokens: int) -> Optional[np.ndarray]:
        """Reserve pages for a sequence of up to n_tokens tokens in
        `slot` and point the slot's table row at them.  Returns the
        row (int32, pages_per_slot_max) or None when the pool or the
        table row cannot serve it — the caller queues the request."""
        c = self.config
        if slot in self._slot_pages:
            raise ValueError(f"slot {slot} already holds pages; "
                             "release_slot first")
        n = c.pages_for(n_tokens)
        if n > c.pages_per_slot_max or n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        self._slot_pages[slot] = pages
        row = np.full((c.pages_per_slot_max,), TRASH_PAGE, np.int32)
        row[:n] = pages
        self._table[slot] = row
        return row

    def release_slot(self, slot: int) -> None:
        """Return a retired slot's pages to the pool.  The table row
        keeps its (now stale) entries until reassignment — stale ids
        are read-harmless by the position-masking contract.

        A release of a slot holding no pages raises
        `PageAccountingError` BY NAME: it is either a double release or
        a never-allocated slot, and silently ignoring it (the pre-
        ISSUE-14 behavior) masks exactly the scheduler bug that later
        double-allocates a page to two live sequences."""
        if slot not in self._slot_pages:
            raise PageAccountingError(
                f"release_slot({slot}): slot holds no pages — double "
                "release, or a slot that was never allocated")
        self._free.extend(self._slot_pages.pop(slot))

    def slot_pages(self, slot: int) -> List[int]:
        return list(self._slot_pages.get(slot, ()))

    # ------------------------- checkpoint (ISSUE 9) ------------------

    def state_dict(self) -> dict:
        """Host snapshot of the allocator: free list, table mirror,
        slot→pages assignments.  Together with the engine's device
        planes this is everything a preempted serving node needs to
        resume mid-generation (DecodeEngine.state_dict carries it)."""
        return {"free": list(self._free),
                "table": self._table.copy(),
                "slot_pages": {int(s): list(p)
                               for s, p in self._slot_pages.items()}}

    def load_state_dict(self, d: dict) -> None:
        """Inverse of state_dict under THIS config.  Validates the page
        accounting (every page trash-or-accounted exactly once) so a
        snapshot from a different deployment fails loudly instead of
        double-allocating pages later."""
        c = self.config
        free = [int(p) for p in d["free"]]
        slot_pages = {int(s): [int(p) for p in pp]
                      for s, pp in d["slot_pages"].items()}
        held = [p for pp in slot_pages.values() for p in pp]
        accounted = sorted(free + held)
        if accounted != list(range(1, c.n_pages)):
            raise ValueError(
                f"PagedKVCache.load_state_dict: snapshot accounts for "
                f"{len(accounted)} pages, this deployment has "
                f"{c.n_pages - 1} usable ones (n_pages={c.n_pages}) — "
                "snapshot is from a different deployment or corrupt")
        table = np.asarray(d["table"], np.int32)
        if table.shape != self._table.shape:
            raise ValueError(
                f"PagedKVCache.load_state_dict: table shape "
                f"{table.shape} != configured {self._table.shape}")
        self._free = free
        self._table = table.copy()
        self._slot_pages = slot_pages


def gather_slot(k_pages, v_pages, table_row, length: int, layer: int = 0):
    """Host/test helper: the contiguous (length, n_kv_heads, head_dim)
    K and V of one slot, gathered through its table row — the dense
    view the parity tests compare the kernel against."""
    c_page = k_pages.shape[3]
    n = -(-length // c_page)
    k = k_pages[layer][:, np.asarray(table_row[:n])]   # (hkv, n, page, d)
    v = v_pages[layer][:, np.asarray(table_row[:n])]
    k = k.reshape(k.shape[0], -1, k.shape[-1])[:, :length]
    v = v.reshape(v.shape[0], -1, v.shape[-1])[:, :length]
    return k.transpose(1, 0, 2), v.transpose(1, 0, 2)
