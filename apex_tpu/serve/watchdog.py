"""Engine watchdog — the serving analogue of `LostRankWatchdog`
(ISSUE 14 tentpole, layer d).

A serving node's worst failure is not a crash — crashes raise, and the
PR 9 snapshot contract already covers them.  The worst failure is a
WEDGE: the decode loop stops making progress (a hung DMA, a deadlocked
runtime, a driver that never completes a dispatch) while the process
looks alive, every live client's tokens stop, and nothing raises.  The
training plane escalates that shape of failure through
`checkpoint.chaos.LostRankWatchdog` (persistent straggler flag →
`RankLostError` instead of a collective hang); this module is the same
posture re-aimed at `DecodeEngine`:

* the engine bumps `steps_completed` at every step that completed its
  retire poll — the ONE heartbeat (a stalled step, real or injected by
  the `serve.stall_step` chaos point, never bumps it);
* `EngineWatchdog.check()` — called by the drive loop between steps —
  raises `EngineStalledError` naming the stuck step once the engine
  has had live work but no heartbeat for `stall_timeout_s`, after
  dumping a flight report whose reason names the step and the restart
  point (no recorder schema change: the story rides the reason string,
  the `resume_guard` convention);
* `restart()` builds a FRESH engine of the same deployment and
  restores the newest periodic snapshot (`snapshot_every=`), so
  decoding resumes MID-GENERATION bitwise (`DecodeEngine.state_dict`,
  the PR 9 contract) — replayed steps are free because greedy decode
  is deterministic.  The snapshot is taken on the watchdog's side of
  the heartbeat because a wedged device cannot be asked for its state
  AFTER the wedge.

`scripts/serve_chaos_probe.py` drives the stall → trip → restart →
bitwise matrix; `MetricsLogger(serve=engine)` stamps
`serve_watchdog_stalls` / `serve_watchdog_restarts` (SCHEMA v10).
"""

from __future__ import annotations

import time
from typing import Callable, Optional

from apex_tpu.serve.engine import DecodeEngine


class EngineStalledError(RuntimeError):
    """The engine made no retire-poll progress within the stall
    timeout while holding live work.  Carries the structured fields
    the restart path needs: `step` (the heartbeat it stuck at),
    `stalled_for_s`, and `snapshot_step` (the restart point, None when
    no snapshot was ever taken)."""

    def __init__(self, msg: str, step: Optional[int] = None,
                 stalled_for_s: Optional[float] = None,
                 snapshot_step: Optional[int] = None):
        super().__init__(msg)
        self.step = step
        self.stalled_for_s = stalled_for_s
        self.snapshot_step = snapshot_step


class EngineWatchdog:
    """Host-side stall detector + restart orchestration for one
    `DecodeEngine`.

    >>> dog = EngineWatchdog(eng, stall_timeout_s=5.0, snapshot_every=8)
    >>> while eng.pending:
    ...     eng.step()
    ...     try:
    ...         dog.check()
    ...     except EngineStalledError:
    ...         eng = dog.restart()      # fresh engine, bitwise resume

    `clock=` is injectable so the trip threshold is testable without
    real waiting; `snapshot_every=N` snapshots `state_dict()` every N
    progressing steps (0 disables — `restart()` then needs a snapshot
    handed in).  Snapshotting costs a device sync + a host copy of the
    KV pool, so production picks a cadence the same way checkpoint
    cadence is priced (docs/serving.md); the chaos probe runs
    `snapshot_every=1` because its proof is bitwise, not cheap."""

    def __init__(self, engine: DecodeEngine, stall_timeout_s: float = 30.0,
                 recorder=None, snapshot_every: int = 0,
                 clock: Callable[[], float] = time.perf_counter):
        if stall_timeout_s <= 0:
            raise ValueError(
                f"stall_timeout_s must be > 0, got {stall_timeout_s}")
        if snapshot_every < 0:
            raise ValueError(
                f"snapshot_every must be >= 0, got {snapshot_every}")
        self.engine = engine
        self.stall_timeout_s = stall_timeout_s
        # default to the engine's own flight recorder: a restart must
        # not silently drop crash-dump wiring the deployment attached
        self.recorder = (recorder if recorder is not None
                         else getattr(engine, "recorder", None))
        self.snapshot_every = snapshot_every
        self.clock = clock
        self.stalls = 0
        self.restarts = 0
        self.snapshot: Optional[dict] = None
        self.snapshot_step: Optional[int] = None
        self._last_heartbeat = engine.steps_completed
        self._last_progress_t = clock()
        self._since_snapshot = 0
        engine.watchdog = self

    def check(self) -> None:
        """Judge the heartbeat.  Progress (or an idle engine) resets
        the stall clock; live work without progress past the timeout
        raises `EngineStalledError` naming the stuck step, after
        dumping a flight report when a recorder is attached."""
        now = self.clock()
        hb = self.engine.steps_completed
        if hb != self._last_heartbeat:
            self._last_heartbeat = hb
            self._last_progress_t = now
            if self.snapshot_every:
                self._since_snapshot += 1
                if self._since_snapshot >= self.snapshot_every:
                    self.take_snapshot()
            return
        if not self.engine.pending:
            # no work is not a stall — an idle engine has nothing to
            # make progress ON; the clock re-arms at the next submit
            self._last_progress_t = now
            return
        stalled = now - self._last_progress_t
        if stalled <= self.stall_timeout_s:
            return
        self.stalls += 1
        live = len(self.engine._live)
        queued = len(self.engine._pending)
        where = (f"snapshot at step {self.snapshot_step}"
                 if self.snapshot_step is not None
                 else "NO SNAPSHOT — restart loses in-flight work")
        msg = (f"serve engine stalled: no retire-poll progress for "
               f"{stalled:.2f}s (timeout {self.stall_timeout_s:.2f}s) "
               f"stuck at step {hb} with {live} live / {queued} queued "
               f"request(s); restart point: {where}")
        if self.recorder is not None:
            self.recorder.dump(reason=f"engine watchdog: {msg}")
        raise EngineStalledError(msg, step=hb, stalled_for_s=stalled,
                                 snapshot_step=self.snapshot_step)

    def take_snapshot(self) -> Optional[dict]:
        """Snapshot the engine NOW (device-synced `state_dict()`) —
        the restart point.  Never call this on a suspected-stalled
        engine: the sync would hang on the wedge; the periodic cadence
        exists so a snapshot from BEFORE the wedge is always at hand.

        The snapshot is LAST KNOWN-GOOD, not merely last: the
        candidate's output rings are validated against the vocab
        before it replaces the held one.  Poison is detected at
        RETIRE time (`PoisonedOutputError`), possibly steps after the
        injection — if the watchdog blindly kept the newest state, the
        poison would be inside every later snapshot and restart would
        reload the corruption forever.  A poisoned candidate is
        refused (returns None, the previous snapshot stays) so the
        restart always lands before the injection."""
        snap = self.engine.state_dict()
        ds = snap["decode_state"]
        vocab = self.engine.model_cfg.vocab_size
        n_gen = ds["n_generated"]
        out = ds["out_tokens"]
        for slot in range(out.shape[0]):
            toks = out[slot, :int(n_gen[slot])]
            if toks.size and (int(toks.min()) < 0
                              or int(toks.max()) >= vocab):
                return None            # poisoned — keep the good one
        self.snapshot = snap
        self.snapshot_step = self.engine.steps_completed
        self._since_snapshot = 0
        return self.snapshot

    def restart(self, snapshot: Optional[dict] = None,
                params=None) -> DecodeEngine:
        """Build a FRESH engine of the same deployment, restore
        `snapshot` (default: the newest periodic one), and re-arm the
        watchdog on it.  The restored engine recompiles its decode
        step on first use (fresh jit cache) and then holds the
        zero-steady-recompile contract as before; resumed decoding is
        BITWISE the unstalled run's (greedy decode is deterministic,
        so replaying the steps since the snapshot reproduces them)."""
        snap = snapshot if snapshot is not None else self.snapshot
        if snap is None:
            raise ValueError(
                "EngineWatchdog.restart: no snapshot to restore "
                "(snapshot_every=0 and none handed in)")
        old = self.engine
        eng = DecodeEngine(
            old.model_cfg, params if params is not None else old.params,
            old.serve_cfg, recorder=self.recorder,
            telemetry=old.telemetry is not None, slo=old.slo)
        eng.load_state_dict(snap)
        self.restarts += 1
        self.engine = eng
        old.watchdog = None
        eng.watchdog = self
        self._last_heartbeat = eng.steps_completed
        self._last_progress_t = self.clock()
        self._since_snapshot = 0
        return eng
