"""apex_tpu.serve — the inference subsystem (ISSUE 8 tentpole).

Three layers, bottom-up:

  * ops/flash_decode.py — single/few-query flash attention against a
    PAGED KV cache: the kernel gathers pages through a per-slot block
    table at DMA time (scalar-prefetch index map), so the compiled
    shapes never depend on sequence length or concurrency.
  * serve/kv_cache.py — the page pool + block-table allocator:
    thousands of ragged sequences share one fixed pool of HBM pages;
    partial pages and stale table entries are masked BY POSITION,
    never cleaned.
  * serve/engine.py — continuous batching: a host-side scheduler that
    admits and retires requests into a fixed slot grid every step;
    per-slot state lives on device, the decode step is sync-free, and
    a RecompileSentry enforces that steady-state churn never
    retraces.

  * serve/telemetry.py — the serving observatory (ISSUE 10): a
    request-lifecycle ledger (submit → admit → first-token → retire,
    host-stamped, zero extra device syncs), bounded-memory streaming
    percentiles for live TTFT / queue-wait / per-token latency,
    queue/pool gauges, and the `ServeSLO` verdict that
    `scripts/slo_probe.py` gates in CI.

  * serve/watchdog.py + the engine's resilience plane (ISSUE 14):
    per-request deadlines/TTL, cancellation, a bounded admission
    queue with shed policies and SLO-driven proactive shedding,
    terminal ledger states (`expired`/`cancelled`/`shed`), the
    `EngineWatchdog` stall detector with bitwise snapshot restart,
    and `drain()` for deploys — chaos-gated by
    `scripts/serve_chaos_probe.py` over the `SERVE_POINTS` fail
    points (checkpoint/chaos.py).

docs/serving.md is the operator guide; examples/serve_gpt.py the
runnable entry point; bench.py stamps `serve_*` decode-throughput and
latency axes; docs/observability.md § "Reading the serving plane"
documents the live stamps.
"""

from apex_tpu.ops.flash_decode import (  # noqa: F401
    flash_decode,
    paged_attention_reference,
)
from apex_tpu.serve.engine import (  # noqa: F401
    SHED_POLICIES,
    DecodeEngine,
    DecodeState,
    FinishedRequest,
    PoisonedOutputError,
    ServeConfig,
    build_flagship_engine,
    choose_shed_victim,
    measure_decode,
)
from apex_tpu.serve.kv_cache import (  # noqa: F401
    TRASH_PAGE,
    KVCacheConfig,
    PageAccountingError,
    PagedKVCache,
    default_page_size,
    gather_slot,
)
from apex_tpu.serve.telemetry import (  # noqa: F401
    SERVE_TELEMETRY_VERSION,
    TERMINAL_STATES,
    RequestLedger,
    RequestRecord,
    ServeSLO,
    ServeTelemetry,
    SLOBreach,
    SLOVerdict,
    StreamingPercentiles,
    step_latency_percentiles,
    validate_serve_report,
)
from apex_tpu.serve.watchdog import (  # noqa: F401
    EngineStalledError,
    EngineWatchdog,
)
