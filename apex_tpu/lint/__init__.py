"""apex_tpu.lint — static analysis of the traced/lowered train step.

The standing correctness gate in front of execution (ISSUE 6): where
the monitor stack *observes* the running program (telemetry, flight
recorder, compile observatory), this package *verifies* the program
before it runs — the veScale single-controller posture applied to the
closed jaxpr.  Four passes:

  dtype_policy — the static form of Apex's AMP cast lists: fp32 GEMMs
                 in low-precision regions, lossy convert round trips,
                 low-precision accumulation, non-fp32 master updates
                 (DP1xx)
  collectives  — unbound/mismatched mesh axes, psum-of-psum,
                 loop-invariant collectives in scan bodies, fp16 psum
                 overflow hazards, dead collectives (CL2xx)
  donation     — donate_argnums coverage of the state arguments plus
                 the runtime cross-check against
                 `monitor.analyze_step`'s donation_ok (DN3xx)
  hostsync     — the Python-AST retrace/host-sync pass: .item(),
                 float(tracer), np.asarray, traced branching,
                 jit-in-loop, loop-carried scalar closures inside
                 jitted regions (HS4xx — the static complement of
                 RecompileSentry)

Entry points: `lint_step(step, args)` for built train steps (reads the
builder-attached arg_names/donate_argnums/mesh_axis_names and traces
the exact program), `lint_program(fn, args)` for bare jittables,
`lint_paths([dirs])` for the source pass.  `scripts/lint_step.py` is
the CI gate (nonzero exit on findings outside the committed allowlist,
`scripts/lint_allowlist.txt`); findings also attach to
`monitor.analyze_step(..., lint=True)` reports and ride into the
flight-recorder crash dump with them.  See docs/lint.md for the rule
catalog and the allowlist workflow.
"""

from apex_tpu.lint.engine import (  # noqa: F401
    COLLECTIVE_PRIMS,
    LOW_PRECISION,
    LintConfig,
    collect_views,
    lint_program,
    lint_step,
    trace_jaxpr,
)
from apex_tpu.lint.findings import (  # noqa: F401
    LINT_SCHEMA_VERSION,
    RULES,
    SEVERITIES,
    Finding,
    LintReport,
    apply_allowlist,
    load_allowlist,
    make_finding,
    parse_allowlist,
    render_findings,
    validate_findings,
)
from apex_tpu.lint.hostsync import (  # noqa: F401
    lint_paths,
    lint_source,
    lint_source_text,
)
