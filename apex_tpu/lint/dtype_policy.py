"""Dtype-policy lint — the static form of Apex's AMP cast lists.

The reference enforces mixed precision dynamically (op wrappers driven
by allow/deny lists, apex/amp/lists/); under JAX the traced program
makes the same properties *checkable before execution*:

  DP101  a `dot_general`/conv executing in fp32 inside a region whose
         policy is low-precision — the silent upcast that costs 8x MXU
         throughput and the exact inverse of the cast-list contract.
  DP102  a lossy convert round trip (f32 -> bf16 -> f32 with nothing
         in between) on a tensor big enough to matter: mantissa
         silently discarded, the downcast buys nothing.  The upcast
         must be the downcast's ONLY consumer — a bf16 copy that also
         feeds a GEMM is the normal mixed-precision shape.  Small
         per-channel vectors are exempt — an active amp policy
         downcasts norm scale/bias with the whole tree and the norm op
         re-promotes them internally (the FP32_CLASS_OPS contract),
         which is by-design, not a hazard.
  DP103  low-precision ACCUMULATION in a large reduction: a
         `reduce_sum`-class op summing >= threshold elements with a
         bf16/fp16 accumulator.  jnp.sum ALWAYS upcasts to f32
         internally (even with dtype=jnp.bfloat16 — the jaxpr is
         convert->f32 reduce->downcast), so a low-precision reduce_sum
         in the jaxpr can only come from a raw lax-level reduction.
         dot_generals are NOT checked here: jnp sets
         preferred_element_type to the input dtype by default, so the
         param carries no user intent — and the TPU MXU accumulates
         bf16 products in f32 regardless.
  DP104  master-weight update math not in fp32: a large f32 program
         output produced DIRECTLY by an upcast from a low-precision
         value of the same shape — the whole update was computed in
         low precision and the f32 master buffer only stores the
         rounded result (the Apex master-weights guarantee, statically).
  DP105  a top-k / sort selection over LOW-PRECISION operands — the
         MoE router contract (apex_tpu.moe): gate logits and their
         softmax must be fp32 regardless of compute dtype, because
         bf16's 8-bit mantissa collapses the tiny probability gaps
         (and the ties) the selection keys on, silently changing
         which experts train.  The conforming shape keeps the gate
         GEMM's operands in the compute dtype but accumulates fp32
         (`preferred_element_type`), so DP101 and DP105 are
         satisfiable together.
"""

from __future__ import annotations

from typing import List

from apex_tpu.lint import engine as E
from apex_tpu.lint.findings import Finding, make_finding

# GEMM-class primitives (the conv covers the ResNet path)
_GEMM_PRIMS = ("dot_general", "conv_general_dilated")

# reductions whose accumulator dtype matters (max/min need no
# accumulation precision; cumsum's output size makes the
# reduction-length heuristic meaningless)
_ACCUM_REDUCTIONS = ("reduce_sum", "reduce_prod")

# selection primitives the DP105 router-gate check covers (jnp.argsort
# and lax.top_k both surface as these; approx_top_k is the TPU-native
# variant)
_SELECTION_PRIMS = ("top_k", "approx_top_k", "sort")


def _gemm_in_dtypes(eqn):
    return [E.dtype_name(v) for v in eqn.invars[:2]]


def _use_counts(jaxpr) -> dict:
    """var -> number of consuming sites (eqn inputs + jaxpr outputs)."""
    out: dict = {}
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not isinstance(v, E._Literal):
                out[v] = out.get(v, 0) + 1
    for v in jaxpr.outvars:
        if not isinstance(v, E._Literal):
            out[v] = out.get(v, 0) + 1
    return out


def _infer_low_region(views) -> bool:
    """With no declared compute dtype: the program is a low-precision
    region when at least half its GEMMs run low-precision operands."""
    low = total = 0
    for view in views:
        for eqn in view.jaxpr.eqns:
            if eqn.primitive.name in _GEMM_PRIMS:
                dts = _gemm_in_dtypes(eqn)
                if not any(E.is_float(d) for d in dts):
                    continue  # integer/bool dots are not policy-bound
                total += 1
                if any(E.is_low_precision(d) for d in dts):
                    low += 1
    return total > 0 and low * 2 >= total


def run(views, *, program: str, config: E.LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    declared = config.compute_dtype
    low_region = (declared in E.LOW_PRECISION if declared is not None
                  else _infer_low_region(views))

    for view in views:
        prods = E.producers(view.jaxpr)
        use_counts = _use_counts(view.jaxpr)
        convert_eqns = [e for e in view.jaxpr.eqns
                        if e.primitive.name == "convert_element_type"]
        counts: dict = {}
        for eqn in view.jaxpr.eqns:
            prim = eqn.primitive.name
            idx = counts.get(prim, 0)
            counts[prim] = idx + 1
            loc = view.eqn_location(program, eqn, idx)

            # ---- DP101: fp32 GEMM inside a low-precision region ----
            if low_region and prim in _GEMM_PRIMS:
                dts = _gemm_in_dtypes(eqn)
                if any(d == "float32" for d in dts) \
                        and not any(E.is_low_precision(d) for d in dts):
                    findings.append(make_finding(
                        "DP101", loc,
                        f"{prim} runs float32 operands inside a "
                        f"{declared or 'low-precision'} policy region "
                        "(8x MXU throughput left on the table)",
                        hint="cast the operands to the compute dtype at "
                             "the call site (policy.cast_to_compute), or "
                             "allowlist if this GEMM is deliberately "
                             "fp32-class"))

            # ---- DP102: lossy convert round trip ----
            if prim == "convert_element_type":
                src = eqn.invars[0]
                mid_eqn = (None if isinstance(src, E._Literal)
                           else prods.get(src))
                if (mid_eqn is not None
                        and mid_eqn.primitive.name
                        == "convert_element_type"):
                    d0 = E.dtype_name(mid_eqn.invars[0])
                    d1 = E.dtype_name(src)
                    d2 = E.dtype_name(eqn.outvars[0])
                    # the upcast must be the downcast's ONLY consumer:
                    # a bf16 copy that ALSO feeds a GEMM is the normal
                    # mixed-precision shape, not a wasted round trip
                    if (d0 == d2 and d0 == "float32"
                            and E.is_low_precision(d1)
                            and use_counts.get(src, 0) == 1
                            and E.num_elements(eqn.outvars[0])
                            >= config.min_roundtrip_elems):
                        findings.append(make_finding(
                            "DP102", loc,
                            f"value round-trips {d0} -> {d1} -> {d2} "
                            "with no compute in between — the mantissa "
                            "is discarded for nothing",
                            hint="drop both casts, or keep the value in "
                                 f"{d1} if the downcast was the intent"))

            # ---- DP105: low-precision top-k / sort selection ----
            if prim in _SELECTION_PRIMS:
                sel_dt = next((E.dtype_name(v) for v in eqn.invars
                               if E.is_float(E.dtype_name(v))), None)
                if E.is_low_precision(sel_dt):
                    findings.append(make_finding(
                        "DP105", loc,
                        f"{prim} selects over {sel_dt} operands — a "
                        "router gate softmax/selection in low "
                        "precision loses ties and the probability "
                        "gaps the top-k keys on, silently changing "
                        "which experts train",
                        hint="compute gate logits with preferred_"
                             "element_type=float32 and keep the "
                             "softmax + selection in fp32 (the "
                             "apex_tpu.moe router contract)"))

            # ---- DP103a: low-precision large reduce_sum ----
            if prim in _ACCUM_REDUCTIONS:
                in_dt = E.dtype_name(eqn.invars[0])
                out_dt = E.dtype_name(eqn.outvars[0])
                n_in = E.num_elements(eqn.invars[0])
                n_out = max(1, E.num_elements(eqn.outvars[0]))
                reduced = n_in // n_out
                if (E.is_low_precision(in_dt)
                        and E.is_low_precision(out_dt)
                        and reduced >= config.reduction_threshold):
                    findings.append(make_finding(
                        "DP103", loc,
                        f"{prim} accumulates {reduced} {in_dt} elements "
                        f"in {out_dt} — error grows with the reduction "
                        "size",
                        hint="accumulate in float32 (jnp.sum(x, "
                             "dtype=jnp.float32)) and downcast the "
                             "result if needed"))


        # ---- DP104: master update math not in fp32 ----
        # only program-boundary outputs are master buffers (the
        # outermost jaxpr and its jit/shard_map bodies — NOT scan
        # carries or remat bodies, whose outputs legitimately change
        # dtype); a large f32 output whose producing eqn is an upcast
        # from a low-precision SAME-SHAPE value means the whole update
        # was computed low-precision and merely stored f32
        boundary = view.scan_num_consts is None and all(
            part in ("", "pjit", "shard_map", "closed_call", "jit")
            for part in view.path.split("/"))
        if boundary:
            seen = set()
            for ov in view.jaxpr.outvars:
                if isinstance(ov, E._Literal) or ov in seen:
                    continue
                seen.add(ov)
                if E.dtype_name(ov) != "float32":
                    continue
                if E.num_elements(ov) < config.large_output_elems:
                    continue
                p = prods.get(ov)
                if p is None or p.primitive.name != "convert_element_type":
                    continue
                src_dt = E.dtype_name(p.invars[0])
                if (E.is_low_precision(src_dt)
                        and E.num_elements(p.invars[0])
                        == E.num_elements(ov)):
                    idx = convert_eqns.index(p)
                    findings.append(make_finding(
                        "DP104", view.eqn_location(program, p, idx),
                        f"a {E.num_elements(ov)}-element float32 state "
                        f"output is a bare upcast of a {src_dt} value — "
                        "the master-weight update math ran in "
                        f"{src_dt}, the f32 buffer only stores the "
                        "rounded result",
                        hint="compute the update in float32 (cast the "
                             "grads up BEFORE the optimizer math), the "
                             "Apex master-weights contract"))
    return findings
