"""Donation lint — statically prove the step donates its state.

An undonated state argument is a second full copy of the largest
buffers in the program (the "three fp32 state copies per step" failure
bench.py's baseline works around by hand).  The builders already carry
everything needed to check this without compiling: `step.arg_names`
labels the arguments, `step.donate_argnums` says which are donated,
and the (possibly abstract) call args give the bytes.  When the caller
also has an AOT `CompileReport` (monitor.analyze_step), DN302
cross-checks the static claim against the runtime truth — XLA can
refuse a donation the signature promised (layout mismatch), and
`donation_ok=False` is exactly that refusal.

  DN301  an argument that names itself state (`opt_state`,
         `model_state`, ...) and is big enough to matter is not
         covered by donate_argnums.
  DN302  the runtime donation check failed: `CompileReport.donation_ok`
         is False — donated bytes did not alias into the outputs.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from apex_tpu.lint import engine as E  # noqa: F401 — config type
from apex_tpu.lint.findings import Finding, make_finding


def _tree_bytes(tree) -> int:
    from apex_tpu.monitor.compile.report import tree_bytes
    return tree_bytes(tree)


def _is_state_name(name: str) -> bool:
    n = name.lower()
    return n == "state" or n.endswith("_state") or n == "opt_state"


def run(step, args, *, program: str, config,
        arg_names: Optional[Sequence[str]] = None,
        donate_argnums: Optional[Sequence[int]] = None,
        compile_report=None) -> List[Finding]:
    findings: List[Finding] = []
    if donate_argnums is None:
        donate_argnums = getattr(step, "donate_argnums", None)
    if arg_names is None:
        arg_names = getattr(step, "arg_names", None)
    names = list(arg_names or [])
    names += [f"arg{i}" for i in range(len(names), len(args))]
    donated = set(donate_argnums or ())

    for i, (name, arg) in enumerate(zip(names, args)):
        if not _is_state_name(name) or i in donated:
            continue
        b = _tree_bytes(arg)
        if b < config.state_bytes_floor:
            continue  # a scaler/metrics pytree of scalars is noise
        findings.append(make_finding(
            "DN301", f"{program}:args[{i}]:{name}",
            f"state argument {name!r} ({b / 2**20:.1f} MiB) is not in "
            f"donate_argnums={sorted(donated)} — a second full copy of "
            "it stays alive across the step",
            hint="add the argument to donate_argnums (the builders' "
                 "donate=True path) or shrink it out of the state"))

    if compile_report is not None:
        rep = (compile_report.to_dict()
               if hasattr(compile_report, "to_dict")
               else dict(compile_report))
        if rep.get("donation_ok") is False:
            und = rep.get("undonated_bytes")
            don = rep.get("donated_bytes")
            findings.append(make_finding(
                "DN302", f"{program}:compile_report",
                f"runtime donation FAILED: {und} of {don} donated "
                "bytes did not alias into the outputs — XLA kept a "
                "second state copy alive despite the donation "
                "annotation",
                hint="check for dtype/layout changes between the "
                     "donated input and its output (analyze_step's "
                     "budget table shows where the bytes went)"))
    return findings
