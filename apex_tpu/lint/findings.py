"""`Finding` records, the rule catalog, and the allowlist engine.

Every lint pass (dtype_policy / collectives / donation / hostsync)
emits `Finding`s through this module so the CLI, the CompileReport
attachment, and the flight-recorder crash dump all speak one schema.
A finding is (rule id, severity, location, message, fix hint); the
committed allowlist (`scripts/lint_allowlist.txt`) maps known,
accepted findings out of the gate — `apply_allowlist` splits a run's
findings into `new` (gate-failing) and `allowlisted`.

Schema stability is CI-gated the same way the flight recorder's is:
`validate_findings` raises on drift, and `scripts/lint_step.py
--selftest` renders the committed fixture (`scripts/lint_fixture.json`)
and exits nonzero when the schema or the rendering's load-bearing
markers are lost.  Bump LINT_SCHEMA_VERSION on any field
add/rename/re-semantics.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Iterable, List, Optional, Sequence, Tuple

LINT_SCHEMA_VERSION = 1

SEVERITIES = ("error", "warning", "info")

# The rule catalog: id -> (default severity, one-line summary).  Rule
# ids are namespaced by pass (DP1xx dtype-policy, CL2xx collectives,
# DN3xx donation, HS4xx retrace/host-sync) so an allowlist line reads
# at a glance which analysis it silences.  docs/lint.md carries the
# long-form catalog with examples and fixes.
RULES = {
    # dtype-policy (the static form of Apex's cast lists)
    "DP101": ("warning", "fp32 GEMM inside a low-precision policy region"),
    "DP102": ("warning", "lossy convert_element_type round trip"),
    "DP103": ("warning", "low-precision accumulation in a large reduction"),
    "DP104": ("warning", "master-weight update math not in fp32"),
    "DP105": ("warning", "router top-k selection over low-precision gates"),
    # collectives
    "CL201": ("error", "collective over an unbound/mismatched mesh axis"),
    "CL202": ("warning", "psum-of-psum redundancy"),
    "CL203": ("warning", "loop-invariant collective inside a scan body"),
    "CL204": ("warning", "fp16 psum operand can overflow under loss scaling"),
    "CL205": ("warning", "dead collective (result unused)"),
    "CL206": ("error", "all_to_all over an unbound/mismatched ep axis"),
    "CL207": ("error", "non-bijective ppermute perm (silent zero-fill)"),
    # donation
    "DN301": ("warning", "state argument not covered by donate_argnums"),
    "DN302": ("error", "runtime donation failed (CompileReport.donation_ok)"),
    # retrace / host-sync hazards (AST pass)
    "HS401": ("error", ".item() on a traced value inside a jitted region"),
    "HS402": ("error", "float()/int()/bool() on a traced value in jit"),
    "HS403": ("error", "np.asarray/device_get on a traced value in jit"),
    "HS404": ("warning", "branching on a traced value inside jit"),
    "HS405": ("warning", "jax.jit constructed inside a loop (retrace/call)"),
    "HS406": ("warning", "jitted closure over a loop-carried Python scalar"),
}


@dataclasses.dataclass
class Finding:
    """One lint finding.  `location` is a jaxpr path
    (`program:shard_map/scan:dot_general[3]`) or a source location
    (`examples/foo.py:42`).  Allowlist entries match the rule id
    EXACTLY and the location by fnmatch glob (see apply_allowlist)."""

    rule: str
    severity: str
    location: str
    message: str
    hint: str = ""

    def __post_init__(self):
        if self.rule not in RULES:
            raise ValueError(f"unknown lint rule id {self.rule!r}")
        if self.severity not in SEVERITIES:
            raise ValueError(f"unknown severity {self.severity!r}")

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


def make_finding(rule: str, location: str, message: str,
                 hint: str = "", severity: Optional[str] = None) -> Finding:
    """Construct a finding with the rule's default severity."""
    sev = severity or RULES[rule][0]
    return Finding(rule=rule, severity=sev, location=location,
                   message=message, hint=hint)


# ------------------------------ allowlist ------------------------------

def parse_allowlist(text: str) -> List[Tuple[str, str]]:
    """Parse allowlist lines into (rule, location-glob) pairs.

    Format, one entry per line:

        RULE location-glob   # optional comment

    Blank lines and full-line `#` comments are skipped.  The glob
    matches the finding's location with fnmatch (so `HS401
    examples/*.py:*` silences a rule across a tree).  A bare `RULE`
    with no glob matches every location — reserve that for rules that
    are wrong for this repo wholesale.
    """
    entries = []
    for ln, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        parts = line.split(None, 1)
        rule = parts[0]
        if rule not in RULES:
            raise ValueError(
                f"allowlist line {ln}: unknown rule id {rule!r}")
        glob = parts[1].strip() if len(parts) > 1 else "*"
        entries.append((rule, glob))
    return entries


def load_allowlist(path) -> List[Tuple[str, str]]:
    with open(path) as f:
        return parse_allowlist(f.read())


def apply_allowlist(findings: Iterable[Finding],
                    allowlist: Sequence[Tuple[str, str]]):
    """Split findings into (new, allowlisted) against the entries."""
    new, allowed = [], []
    for f in findings:
        if any(f.rule == rule and fnmatch.fnmatch(f.location, glob)
               for rule, glob in allowlist):
            allowed.append(f)
        else:
            new.append(f)
    return new, allowed


# ------------------------------ report ------------------------------

@dataclasses.dataclass
class LintReport:
    """One lint run's outcome: the program/tree linted, the findings
    that gate (`new`), and the ones the committed allowlist accepted.
    `ok` is the CI bit — no new findings."""

    target: str
    new: List[Finding]
    allowlisted: List[Finding] = dataclasses.field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.new

    def to_dict(self) -> dict:
        return {
            "lint_schema_version": LINT_SCHEMA_VERSION,
            "target": self.target,
            "ok": self.ok,
            "new": [f.to_dict() for f in self.new],
            "allowlisted": [f.to_dict() for f in self.allowlisted],
        }


def validate_findings(obj: dict) -> None:
    """Raise ValueError unless `obj` is a LintReport.to_dict() of the
    current schema — the `lint_step.py --selftest` drift gate (mirrors
    `trace.report.validate_report`)."""
    if not isinstance(obj, dict):
        raise ValueError(f"lint report is {type(obj).__name__}, want dict")
    for k in ("lint_schema_version", "target", "ok", "new", "allowlisted"):
        if k not in obj:
            raise ValueError(f"missing lint report field {k!r}")
    if obj["lint_schema_version"] != LINT_SCHEMA_VERSION:
        raise ValueError(
            f"lint_schema_version {obj['lint_schema_version']!r} != "
            f"{LINT_SCHEMA_VERSION}")
    for group in ("new", "allowlisted"):
        if not isinstance(obj[group], list):
            raise ValueError(f"{group} is not a list")
        for i, f in enumerate(obj[group]):
            for k in ("rule", "severity", "location", "message", "hint"):
                if k not in f:
                    raise ValueError(f"{group}[{i}] missing field {k!r}")
            if f["rule"] not in RULES:
                raise ValueError(
                    f"{group}[{i}] unknown rule {f['rule']!r}")
            if f["severity"] not in SEVERITIES:
                raise ValueError(
                    f"{group}[{i}] unknown severity {f['severity']!r}")
    if bool(obj["ok"]) != (len(obj["new"]) == 0):
        raise ValueError("ok bit inconsistent with new findings")


_SEV_ORDER = {"error": 0, "warning": 1, "info": 2}


def render_findings(report) -> str:
    """Human-readable rendering (the CLI output).  Accepts a LintReport
    or its to_dict() form (what the crash dump / fixture carries)."""
    r = report.to_dict() if hasattr(report, "to_dict") else dict(report)
    lines = [f"=== lint: {r.get('target')} ==="]
    new = sorted(r.get("new") or [],
                 key=lambda f: (_SEV_ORDER.get(f["severity"], 9),
                                f["rule"], f["location"]))
    for f in new:
        lines.append(f"{f['severity'].upper():<7} {f['rule']} "
                     f"{f['location']}")
        lines.append(f"        {f['message']}")
        if f.get("hint"):
            lines.append(f"        fix: {f['hint']}")
    allowed = r.get("allowlisted") or []
    if allowed:
        lines.append(f"({len(allowed)} allowlisted finding(s) accepted)")
    if not new:
        lines.append("clean: no new findings")
    else:
        n_err = sum(1 for f in new if f["severity"] == "error")
        lines.append(f"{len(new)} new finding(s), {n_err} error(s)")
    return "\n".join(lines)
