"""Retrace / host-sync hazard lint — the Python-AST pass.

The jaxpr passes see the program that traced; this pass sees the
source that WOULD fail or silently retrace at trace time — the static
complement of `monitor.compile.RecompileSentry`.  It identifies
*traced regions* (functions decorated with / passed to jit, pmap,
vmap, grad, shard_map, scan, cond, while_loop, ...; nested defs
inherit) and flags, inside them:

  HS401  `.item()` on a traced value — a forced device sync that
         raises under jit and serializes dispatch outside it.
  HS402  `float(x)` / `int(x)` / `bool(x)` on a traced value —
         ConcretizationTypeError at trace time (shape/dtype reads are
         exempt: they are static under jit).
  HS403  `np.asarray` / `np.array` / `jax.device_get` on a traced
         value — host materialization inside the program.
  HS404  `if`/`while` on a traced value — either a trace error or,
         with static args, a retrace per Python branch taken (`is
         None` checks and shape/dtype tests are exempt: static).
  HS405  `jax.jit(...)` constructed inside a loop — a fresh cache
         entry (and a full retrace+compile) every iteration.
  HS406  a traced function closing over a name assigned in an
         enclosing LOOP — the closed-over Python scalar is baked in as
         a constant, so each iteration's new value silently retraces
         (the weak-typed scalar closure RecompileSentry catches at
         runtime).

The analysis is deliberately conservative: a value is "traced" only
when it provably derives from a traced function's parameters, so the
pass stays clean on host-side driver code (warmup loops may sync — the
hazard is syncing inside the program).
"""

from __future__ import annotations

import ast
import builtins
import dataclasses
import os
from typing import List, Optional, Set

from apex_tpu.lint.findings import Finding, make_finding

# calls/decorators that trace their function argument(s)
TRANSFORMS = frozenset({
    "jit", "pmap", "vmap", "xmap", "grad", "value_and_grad", "jacfwd",
    "jacrev", "hessian", "vjp", "jvp", "linearize", "shard_map",
    "checkpoint", "remat", "scan", "while_loop", "fori_loop", "cond",
    "switch", "associative_scan", "custom_vjp", "custom_jvp",
    "eval_shape", "make_jaxpr", "named_call",
})
# the jit-family subset whose CONSTRUCTION in a loop is itself a hazard
_JIT_MAKERS = frozenset({"jit", "pmap"})

# attribute reads that are static under tracing
STATIC_ATTRS = frozenset({
    "shape", "ndim", "dtype", "size", "itemsize", "weak_type",
    "sharding", "aval", "_fields", "nbytes",
})
# calls whose result is static regardless of argument tracedness
STATIC_CALLS = frozenset({
    "len", "isinstance", "issubclass", "getattr", "hasattr", "type",
    "range", "id", "repr", "str", "format", "callable",
})
# host-materialization callables: (object name, attr) pairs + bare names
_HOST_FUNCS = frozenset({"asarray", "array", "copyto"})
_HOST_MODULES = frozenset({"np", "numpy", "onp"})


def _call_target(func) -> Optional[str]:
    """The trailing name of a call target: `jax.jit` -> 'jit',
    `jit` -> 'jit', `jax.lax.scan` -> 'scan'."""
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return None


def _is_transform_call(call: ast.Call) -> bool:
    return _call_target(call.func) in TRANSFORMS


def _partial_transform(call: ast.Call) -> bool:
    """functools.partial(jax.jit, ...) used as a decorator/wrapper."""
    if _call_target(call.func) != "partial" or not call.args:
        return False
    return _call_target(call.args[0]) in TRANSFORMS \
        if isinstance(call.args[0], (ast.Name, ast.Attribute)) else False


@dataclasses.dataclass(eq=False)  # identity hashing — scopes are nodes
class _Func:
    node: ast.AST                  # FunctionDef / Lambda / Module
    name: str
    parent: Optional["_Func"]
    params: Set[str] = dataclasses.field(default_factory=set)
    assigned: Set[str] = dataclasses.field(default_factory=set)
    loop_assigned: Set[str] = dataclasses.field(default_factory=set)
    traced: bool = False
    # def lexically inside a loop of the parent scope: a fresh function
    # (and a fresh trace) per iteration BY CONSTRUCTION, so
    # loop-rebound closures are per-iteration values, not stale bakes
    defined_in_loop: bool = False
    children: list = dataclasses.field(default_factory=list)


class _ScopeBuilder(ast.NodeVisitor):
    """First pass: the function-scope tree with per-scope assignment
    and loop-assignment sets, plus traced marks from decorators and
    transform-call references."""

    def __init__(self):
        self.module = _Func(node=None, name="<module>", parent=None)
        self.current = self.module
        self.loop_depth = 0
        self.by_node = {}
        # (scope, name) -> _Func for resolving `jax.jit(f)` references
        self.defs = {}
        self.jit_in_loop: list = []  # (lineno, target) for HS405

    # -- scopes --
    def _enter(self, node, name, params):
        fn = _Func(node=node, name=name, parent=self.current,
                   params=set(params),
                   defined_in_loop=self.loop_depth > 0)
        fn.assigned |= fn.params
        self.current.children.append(fn)
        self.by_node[node] = fn
        self.defs[(self.current, name)] = fn
        outer_loop = self.loop_depth
        self.loop_depth = 0
        prev, self.current = self.current, fn
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.current = prev
        self.loop_depth = outer_loop
        return fn

    @staticmethod
    def _params_of(args: ast.arguments):
        names = [a.arg for a in args.posonlyargs + args.args
                 + args.kwonlyargs]
        if args.vararg:
            names.append(args.vararg.arg)
        if args.kwarg:
            names.append(args.kwarg.arg)
        return names

    def visit_FunctionDef(self, node):
        fn = self._enter(node, node.name, self._params_of(node.args))
        self.current.assigned.add(node.name)
        if self.loop_depth:
            self.current.loop_assigned.add(node.name)
        for dec in node.decorator_list:
            tgt = None
            if isinstance(dec, (ast.Name, ast.Attribute)):
                tgt = _call_target(dec)
            elif isinstance(dec, ast.Call):
                if _partial_transform(dec):
                    tgt = "jit"
                else:
                    tgt = _call_target(dec.func)
            if tgt in TRANSFORMS:
                fn.traced = True
            # a jit DECORATOR on a def inside a loop is the same
            # fresh-cache-entry-per-iteration hazard as jit(...) called
            # in the loop (the decorator runs each iteration)
            if tgt in _JIT_MAKERS and self.loop_depth:
                self.jit_in_loop.append((node.lineno, tgt))

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        self._enter(node, "<lambda>", self._params_of(node.args))

    # -- loops --
    def visit_For(self, node):
        for tname in ast.walk(node.target):
            if isinstance(tname, ast.Name):
                self.current.assigned.add(tname.id)
                self.current.loop_assigned.add(tname.id)
        self.loop_depth += 1
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.loop_depth -= 1

    visit_AsyncFor = visit_For

    def visit_While(self, node):
        self.loop_depth += 1
        for child in ast.iter_child_nodes(node):
            self.visit(child)
        self.loop_depth -= 1

    # -- assignments --
    def _note_assign(self, name):
        self.current.assigned.add(name)
        if self.loop_depth:
            self.current.loop_assigned.add(name)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Store):
            self._note_assign(node.id)
        self.generic_visit(node)

    def visit_Import(self, node):
        for alias in node.names:
            self.current.assigned.add(
                (alias.asname or alias.name).split(".")[0])

    visit_ImportFrom = visit_Import

    # -- transform-call references + jit-in-loop --
    def visit_Call(self, node):
        tgt = _call_target(node.func)
        if tgt in TRANSFORMS or _partial_transform(node):
            fn_args = list(node.args)
            # scan/while/cond take the callee as leading arg(s); jit
            # and friends too — mark every function-valued argument
            for arg in fn_args:
                if isinstance(arg, ast.Lambda):
                    pass  # marked below once scope exists
                elif isinstance(arg, ast.Name):
                    self._mark_name_traced(arg.id)
            if tgt in _JIT_MAKERS and self.loop_depth:
                self.jit_in_loop.append((node.lineno, tgt))
        self.generic_visit(node)
        # lambdas appear as children after generic_visit built them
        if tgt in TRANSFORMS or _partial_transform(node):
            for arg in node.args:
                if isinstance(arg, ast.Lambda):
                    fn = self.by_node.get(arg)
                    if fn is not None:
                        fn.traced = True

    def _mark_name_traced(self, name):
        scope = self.current
        while scope is not None:
            fn = self.defs.get((scope, name))
            if fn is not None:
                fn.traced = True
                return
            scope = scope.parent


def _propagate_traced(fn: _Func):
    for child in fn.children:
        if fn.traced:
            child.traced = True
        _propagate_traced(child)


_BUILTINS = frozenset(dir(builtins))


class _Refs:
    """Dynamic-reference collector with static exemptions: a name
    counts only when it is reachable OUTSIDE shape/dtype reads,
    `is None` tests, isinstance/len-class calls."""

    def __init__(self, traced_names: Set[str]):
        self.traced = traced_names
        self.hits: Set[str] = set()

    def collect(self, node) -> Set[str]:
        self._walk(node)
        return self.hits

    def _walk(self, node):
        if node is None:
            return
        if isinstance(node, ast.Name):
            if node.id in self.traced:
                self.hits.add(node.id)
            return
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return  # x.shape / x.dtype are static under jit
            self._walk(node.value)
            return
        if isinstance(node, ast.Call):
            tgt = _call_target(node.func)
            if tgt in STATIC_CALLS:
                return
            self._walk(node.func)
            for a in node.args:
                self._walk(a)
            for kw in node.keywords:
                self._walk(kw.value)
            return
        if isinstance(node, ast.Compare):
            if node.ops and all(isinstance(op, (ast.Is, ast.IsNot))
                                for op in node.ops):
                return  # `x is None` is a static identity test
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            return  # nested scopes are checked on their own
        for child in ast.iter_child_nodes(node):
            self._walk(child)


def _walk_own_scope(node):
    """ast.walk, but pruning nested function/lambda subtrees — an
    inner helper's assignments belong to ITS scope, and letting them
    leak into the enclosing fixpoint marks host-side names traced
    (false HS402/HS404 positives on plain Python values)."""
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.Lambda)):
        return  # a nested def at the top is itself another scope
    stack = [node]
    while stack:
        n = stack.pop()
        yield n
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            stack.append(child)


def _traced_names_fixpoint(fn: _Func, body) -> Set[str]:
    """Parameters of this traced function (and traced enclosing ones)
    plus locals provably derived from them (bounded fixpoint over the
    straight-line assignments of THIS scope only)."""
    traced = set(fn.params)
    scope = fn.parent
    while scope is not None:
        if scope.traced:
            traced |= scope.params
        scope = scope.parent
    for _ in range(4):
        grew = False
        for node in body:
            for stmt in _walk_own_scope(node):
                targets, value = None, None
                if isinstance(stmt, ast.Assign):
                    targets, value = stmt.targets, stmt.value
                elif isinstance(stmt, ast.AugAssign):
                    targets, value = [stmt.target], stmt.value
                elif isinstance(stmt, ast.AnnAssign) and stmt.value:
                    targets, value = [stmt.target], stmt.value
                if value is None:
                    continue
                if _Refs(traced).collect(value):
                    for t in targets:
                        for nm in ast.walk(t):
                            if isinstance(nm, ast.Name) \
                                    and nm.id not in traced:
                                traced.add(nm.id)
                                grew = True
        if not grew:
            break
    return traced


class _HazardFinder(ast.NodeVisitor):
    """Second pass over ONE traced function's body."""

    def __init__(self, relpath: str, fn: _Func, findings: list):
        self.relpath = relpath
        self.fn = fn
        self.findings = findings
        body = getattr(fn.node, "body", [])
        if isinstance(fn.node, ast.Lambda):
            body = [fn.node.body]
        self.body = body if isinstance(body, list) else [body]
        self.traced_names = _traced_names_fixpoint(fn, self.body)

    def run(self):
        for node in self.body:
            self.visit(node)

    def _loc(self, node) -> str:
        return f"{self.relpath}:{node.lineno}"

    def _refs(self, node) -> Set[str]:
        return _Refs(self.traced_names).collect(node)

    # nested scopes are visited as their own traced functions
    def visit_FunctionDef(self, node):
        return

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):
        return

    def visit_Call(self, node):
        tgt = _call_target(node.func)
        # HS401 — .item()
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == "item"
                and self._refs(node.func.value)):
            self.findings.append(make_finding(
                "HS401", self._loc(node),
                f"`.item()` on a traced value inside jitted function "
                f"{self.fn.name!r} — a forced host sync "
                "(ConcretizationTypeError under jit)",
                hint="return the array and sync on the host side, or "
                     "keep the value on device"))
        # HS402 — float()/int()/bool() on traced values
        elif tgt in ("float", "int", "bool") \
                and isinstance(node.func, ast.Name) and node.args:
            refs = set()
            for a in node.args:
                refs |= self._refs(a)
            if refs:
                self.findings.append(make_finding(
                    "HS402", self._loc(node),
                    f"{tgt}() on traced value(s) {sorted(refs)} inside "
                    f"jitted function {self.fn.name!r} — "
                    "ConcretizationTypeError at trace time",
                    hint="use jnp ops on the traced value, or hoist "
                         "the conversion out of the jitted region"))
        # HS403 — host materialization
        elif self._is_host_call(node):
            refs = set()
            for a in node.args:
                refs |= self._refs(a)
            if refs:
                self.findings.append(make_finding(
                    "HS403", self._loc(node),
                    f"host materialization ({ast.unparse(node.func)}) "
                    f"of traced value(s) {sorted(refs)} inside jitted "
                    f"function {self.fn.name!r}",
                    hint="keep the value in jnp; np.asarray/device_get "
                         "belong on the host side of the step"))
        self.generic_visit(node)

    @staticmethod
    def _is_host_call(node: ast.Call) -> bool:
        f = node.func
        if isinstance(f, ast.Attribute):
            base = f.value
            if f.attr in _HOST_FUNCS and isinstance(base, ast.Name) \
                    and base.id in _HOST_MODULES:
                return True
            if f.attr == "device_get":
                return True
        return False

    def _check_branch(self, node, kind):
        refs = self._refs(node.test)
        if refs:
            self.findings.append(make_finding(
                "HS404", self._loc(node),
                f"`{kind}` branches on traced value(s) {sorted(refs)} "
                f"inside jitted function {self.fn.name!r} — a trace "
                "error (or a retrace per branch with static args)",
                hint="use lax.cond / jnp.where for data-dependent "
                     "control flow"))

    def visit_If(self, node):
        self._check_branch(node, "if")
        self.generic_visit(node)

    def visit_While(self, node):
        self._check_branch(node, "while")
        self.generic_visit(node)


def _closure_findings(fn: _Func, relpath: str, findings: list):
    """HS406: traced fn closing over a loop-assigned enclosing name."""
    if not fn.traced or fn.node is None:
        return
    loads = set()
    body = getattr(fn.node, "body", None) or [fn.node.body]
    for node in body if isinstance(body, list) else [body]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name) and isinstance(sub.ctx,
                                                        ast.Load):
                loads.add(sub.id)
    free = loads - fn.assigned - _BUILTINS
    chain, scope = fn, fn.parent
    while scope is not None and scope.parent is not None:
        # module-level loop rebinds are script drivers; only function
        # scopes bake closures into a single trace.  A def that itself
        # sits inside the rebinding loop is a FRESH function (and
        # trace) per iteration — per-iteration capture by construction,
        # not a stale bake — so the chain crossing a loop-defined
        # function exempts that scope.
        hits = (sorted(free & scope.loop_assigned - scope.params)
                if not chain.defined_in_loop else [])
        for name in hits:
            findings.append(make_finding(
                "HS406", f"{relpath}:{fn.node.lineno}",
                f"jitted function {fn.name!r} closes over {name!r}, "
                f"which {scope.name!r} rebinds inside a loop — each "
                "new value is baked in as a fresh constant and "
                "silently retraces",
                hint="pass the value as a (weak-typed array) argument "
                     "to the jitted function instead of closing over "
                     "it"))
        free -= scope.assigned
        chain, scope = scope, scope.parent


_DISABLE_RE = None  # compiled lazily (keep the module import light)


def _suppressions(text: str) -> dict:
    """lineno -> set of rule ids (or {"*"}) disabled by an inline
    `# lint: disable=HS405[,HS406]` (or bare `# lint: disable`)
    comment — the mechanism for sites where the flagged pattern is the
    point (an autotuner's deliberate jit-per-candidate sweep), so the
    committed allowlist can stay empty.  flake8 `# noqa` comments are
    deliberately NOT honored: their rule namespace is not ours."""
    global _DISABLE_RE
    import re
    if _DISABLE_RE is None:
        _DISABLE_RE = re.compile(
            r"#\s*lint:\s*disable\s*(?:=\s*"
            r"([A-Z]{2}\d{3}(?:\s*,\s*[A-Z]{2}\d{3})*))?")
    out = {}
    for i, line in enumerate(text.splitlines(), 1):
        if "#" not in line:
            continue
        m = _DISABLE_RE.search(line)
        if not m:
            continue
        rules = m.group(1)
        out[i] = (set(r.strip() for r in rules.split(","))
                  if rules else {"*"})
    return out


def lint_source_text(text: str, path: str,
                     relpath: Optional[str] = None) -> List[Finding]:
    """AST-lint one Python source string.  `relpath` is the location
    prefix findings carry (defaults to `path`).  Findings on lines
    carrying a `# lint: disable=RULE` comment are dropped."""
    rel = relpath or path
    try:
        tree = ast.parse(text, filename=path)
    except SyntaxError as e:
        return [make_finding(
            "HS404", f"{rel}:{e.lineno or 0}",
            f"file does not parse: {e.msg}", severity="error",
            hint="fix the syntax error; the lint pass cannot see "
                 "inside")]
    builder = _ScopeBuilder()
    builder.visit(tree)
    _propagate_traced(builder.module)
    findings: List[Finding] = []
    for lineno, tgt in builder.jit_in_loop:
        findings.append(make_finding(
            "HS405", f"{rel}:{lineno}",
            f"jax.{tgt}(...) constructed inside a loop — every "
            "iteration builds a fresh cache entry and pays a full "
            "retrace + compile",
            hint="hoist the jit construction above the loop and call "
                 "the one jitted function inside it"))

    def walk_funcs(fn: _Func):
        if fn.traced and fn.node is not None:
            _HazardFinder(rel, fn, findings).run()
            _closure_findings(fn, rel, findings)
        for child in fn.children:
            walk_funcs(child)

    walk_funcs(builder.module)

    disabled = _suppressions(text)
    if disabled:
        def _suppressed(f):
            line = f.location.rpartition(":")[2]
            rules = disabled.get(int(line) if line.isdigit() else -1)
            return bool(rules) and ("*" in rules or f.rule in rules)
        findings = [f for f in findings if not _suppressed(f)]

    def _line_key(f):
        path, _, line = f.location.rpartition(":")
        return (path, int(line) if line.isdigit() else 0, f.rule)

    findings.sort(key=_line_key)
    return findings


def lint_source(path, root=None) -> List[Finding]:
    """AST-lint one file; locations are relative to `root` when
    given."""
    with open(path) as f:
        text = f.read()
    rel = os.path.relpath(path, root) if root else os.fspath(path)
    return lint_source_text(text, str(path), relpath=rel)


def lint_paths(paths, root=None) -> List[Finding]:
    """AST-lint every .py file under each path (files or directories),
    sorted for deterministic output."""
    files = []
    for p in paths:
        if os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                files += [os.path.join(dirpath, fn) for fn in filenames
                          if fn.endswith(".py")]
        else:
            files.append(os.fspath(p))
    findings: List[Finding] = []
    for fp in sorted(set(files)):
        findings += lint_source(fp, root=root)
    return findings
