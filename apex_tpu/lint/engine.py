"""The shared jaxpr-walking engine behind the program-level lint passes.

veScale's single-controller posture (arxiv 2509.07003) argues the SPMD
program should be *verified before execution*; under JAX the closed
jaxpr of the train step IS that program, available without a device or
a compile.  This module traces a step (ShapeDtypeStructs suffice — the
same contract as `monitor.analyze_step`), flattens every sub-jaxpr
(pjit / shard_map / scan / while / cond / custom-vjp / remat) into
`JaxprView`s carrying the context the passes need — the jaxpr path,
the mesh axes bound by enclosing shard_maps, whether the jaxpr is a
scan body and which of its invars are loop-invariant — and runs the
registered passes over them.

`lint_step` is the high-level entry: it reads the builder-attached
metadata (`step.arg_names`, `step.donate_argnums`,
`step.mesh_axis_names` — `ddp.make_train_step` and
`make_tp_dp_train_step` attach all three), traces the exact program the
step would run, and returns the combined findings of the dtype-policy,
collective, and donation passes.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Sequence

import jax

try:  # jax >= 0.5 moves the core IR types to jax.extend.core
    from jax.extend.core import Literal as _Literal
except ImportError:  # pragma: no cover — 0.4.x
    _Literal = jax.core.Literal

from apex_tpu.lint.findings import Finding

# collective primitives (by jaxpr name) the collective pass reasons
# about.  pmean does not appear: it traces to psum + div.
COLLECTIVE_PRIMS = frozenset({
    "psum", "pmax", "pmin", "all_gather", "all_to_all", "ppermute",
    "pbroadcast", "reduce_scatter", "psum_scatter",
})

# low-precision float dtypes (by numpy name) for the dtype passes
LOW_PRECISION = frozenset({"bfloat16", "float16", "float8_e4m3fn",
                           "float8_e5m2"})


@dataclasses.dataclass
class JaxprView:
    """One (sub-)jaxpr plus the traversal context the passes need."""

    jaxpr: Any                     # the OPEN jaxpr (has .eqns/.invars)
    path: str                      # e.g. "pjit/shard_map/scan"
    axes: frozenset                # mesh axes bound by enclosing scopes
    scan_num_consts: Optional[int]  # set when this jaxpr is a scan body
    depth: int

    def eqn_location(self, program: str, eqn, index: int) -> str:
        """Stable-ish allowlist location: program, jaxpr path, primitive
        name and its ordinal AMONG SAME-PRIMITIVE eqns in this jaxpr
        (an unrelated edit inserting eqns of other primitives does not
        shift it)."""
        return f"{program}:{self.path}:{eqn.primitive.name}[{index}]"


def _is_jaxpr(obj) -> bool:
    return hasattr(obj, "eqns") and hasattr(obj, "invars")


def _open(obj):
    """ClosedJaxpr -> its open jaxpr; open jaxprs pass through."""
    inner = getattr(obj, "jaxpr", None)
    return inner if inner is not None and _is_jaxpr(inner) else obj


def _sub_jaxprs(eqn):
    """Yield (tag, jaxpr-like) for every sub-jaxpr riding in the eqn's
    params — generic over primitive (pjit 'jaxpr', scan 'jaxpr', cond
    'branches', while 'cond_jaxpr'/'body_jaxpr', custom-vjp
    'call_jaxpr'/'fun_jaxpr', shard_map 'jaxpr', remat 'jaxpr')."""
    for key, val in eqn.params.items():
        if _is_jaxpr(val) or _is_jaxpr(getattr(val, "jaxpr", None)):
            yield key, val
        elif isinstance(val, (tuple, list)):
            for i, item in enumerate(val):
                if _is_jaxpr(item) or _is_jaxpr(getattr(item, "jaxpr",
                                                        None)):
                    yield f"{key}[{i}]", item


def _eqn_axes(eqn) -> frozenset:
    """Mesh axes an eqn's scope binds (shard_map's mesh / pmap's
    axis_name), collected defensively across jax versions."""
    axes = set()
    mesh = eqn.params.get("mesh")
    names = getattr(mesh, "axis_names", None)
    if names:
        axes.update(str(n) for n in names)
    for key in ("axis_name", "axis"):
        v = eqn.params.get(key)
        if isinstance(v, str):
            axes.add(v)
        elif isinstance(v, (tuple, list)):
            axes.update(str(n) for n in v)
    for key in ("in_names", "out_names"):
        v = eqn.params.get(key)
        if isinstance(v, (tuple, list)):
            for d in v:
                if isinstance(d, dict):
                    for nm in d.values():
                        if isinstance(nm, (tuple, list)):
                            axes.update(str(n) for n in nm)
                        else:
                            axes.add(str(nm))
    return frozenset(axes)


def collect_views(closed_jaxpr, *, base_axes=frozenset(),
                  max_depth: int = 32) -> List[JaxprView]:
    """Flatten a (closed) jaxpr and every sub-jaxpr into JaxprViews,
    outermost first."""
    views: List[JaxprView] = []

    def walk(jx, path, axes, scan_consts, depth):
        jx = _open(jx)
        views.append(JaxprView(jaxpr=jx, path=path, axes=axes,
                               scan_num_consts=scan_consts, depth=depth))
        if depth >= max_depth:
            return
        for eqn in jx.eqns:
            prim = eqn.primitive.name
            child_axes = axes | _eqn_axes(eqn)
            for tag, sub in _sub_jaxprs(eqn):
                child_consts = None
                if prim == "scan" and tag == "jaxpr":
                    child_consts = int(eqn.params.get("num_consts", 0))
                walk(sub, f"{path}/{prim}" if path else prim,
                     child_axes, child_consts, depth + 1)

    walk(closed_jaxpr, "", frozenset(base_axes), None, 0)
    return views


def used_vars(jaxpr) -> set:
    """Vars of `jaxpr` that feed an eqn or the jaxpr outputs (dead-code
    detection; make_jaxpr keeps dead eqns — DCE is a lowering pass)."""
    used = set()
    for eqn in jaxpr.eqns:
        for v in eqn.invars:
            if not isinstance(v, _Literal):
                used.add(v)
    for v in jaxpr.outvars:
        if not isinstance(v, _Literal):
            used.add(v)
    return used


def producers(jaxpr) -> dict:
    """var -> producing eqn map for one jaxpr level."""
    out = {}
    for eqn in jaxpr.eqns:
        for v in eqn.outvars:
            out[v] = eqn
    return out


def invariant_vars(view: JaxprView) -> set:
    """For a scan-body view: the vars that are loop-invariant (derive
    only from scan consts, jaxpr constvars, and literals).  Empty set
    for non-scan views."""
    if view.scan_num_consts is None:
        return set()
    jx = view.jaxpr
    inv = set(jx.invars[:view.scan_num_consts]) | set(jx.constvars)
    for eqn in jx.eqns:
        if all(isinstance(v, _Literal) or v in inv
               for v in eqn.invars):
            inv.update(eqn.outvars)
    return inv


def aval_of(var):
    return getattr(var, "aval", None)


def dtype_name(var) -> Optional[str]:
    aval = aval_of(var)
    dt = getattr(aval, "dtype", None)
    return None if dt is None else str(dt)


def num_elements(var) -> int:
    aval = aval_of(var)
    shape = getattr(aval, "shape", None)
    if shape is None:
        return 0
    n = 1
    for d in shape:
        n *= int(d)
    return n


def is_low_precision(name: Optional[str]) -> bool:
    return name in LOW_PRECISION


def is_float(name: Optional[str]) -> bool:
    return name is not None and (name.startswith("float")
                                 or name in LOW_PRECISION)


# ------------------------------ config ------------------------------

@dataclasses.dataclass
class LintConfig:
    """Pass thresholds.  Defaults are deliberately permissive — the
    linter gates on violations a reviewer would flag, not on style."""

    # declared policy compute dtype ("bfloat16"/"float16"/None=infer
    # from the GEMM population: >=50% low-precision dots => low region)
    compute_dtype: Optional[str] = None
    # mesh axes the program may legally reduce over (None = trust the
    # axes bound by the traced shard_maps alone)
    expected_axes: Optional[Sequence[str]] = None
    # DP103: reductions of at least this many summed elements must not
    # accumulate in a low-precision dtype
    reduction_threshold: int = 1 << 16
    # DP102: round trips on tensors below this size are the amp
    # policy's own norm scale/bias re-promotions (FP32_CLASS_OPS
    # contract) — by-design, not a hazard
    min_roundtrip_elems: int = 4096
    # DP104: outputs at least this large are treated as state buffers
    large_output_elems: int = 1 << 14
    # DN301: state args below this many bytes are too small to matter
    state_bytes_floor: int = 1 << 16


# ------------------------------ entry points ------------------------------

def trace_jaxpr(fn, args, *, axis_env=None):
    """The closed jaxpr of `fn(*args)` — args may be arrays or
    ShapeDtypeStructs; tracing never touches a device buffer."""
    return jax.make_jaxpr(fn, axis_env=list(axis_env or []))(*args)


def lint_program(fn=None, args=(), *, jaxpr=None, program: str = "program",
                 config: Optional[LintConfig] = None,
                 axis_env=None) -> List[Finding]:
    """Run the jaxpr passes (dtype-policy + collectives) over
    `fn(*args)` (or a pre-traced `jaxpr=`) and return the findings."""
    from apex_tpu.lint import collectives as _cl
    from apex_tpu.lint import dtype_policy as _dp

    cfg = config or LintConfig()
    if jaxpr is None:
        if fn is None:
            raise TypeError("lint_program needs fn+args or jaxpr=")
        jaxpr = trace_jaxpr(fn, args, axis_env=axis_env)
    base_axes = frozenset(str(a) for a, _ in (axis_env or []))
    views = collect_views(jaxpr, base_axes=base_axes)
    findings: List[Finding] = []
    findings += _dp.run(views, program=program, config=cfg)
    findings += _cl.run(views, program=program, config=cfg)
    return findings


def lint_step(step, args, *, program: str = "step",
              config: Optional[LintConfig] = None,
              arg_names: Optional[Sequence[str]] = None,
              donate_argnums: Optional[Sequence[int]] = None,
              compile_report=None) -> List[Finding]:
    """Lint a built train step: the jaxpr passes over the EXACT program
    the step runs, plus the donation pass over the builder metadata
    (`step.arg_names` / `step.donate_argnums` — `ddp.make_train_step`
    and `make_tp_dp_train_step` attach them) and, when a
    `CompileReport` (or its dict) is given, the static-vs-runtime
    donation cross-check."""
    from apex_tpu.lint import donation as _dn

    cfg = config or LintConfig()
    if cfg.expected_axes is None:
        mesh_axes = getattr(step, "mesh_axis_names", None)
        if mesh_axes:
            cfg = dataclasses.replace(
                cfg, expected_axes=tuple(str(a) for a in mesh_axes))
    # trace the step UNDERNEATH host-side wrappers (RecompileSentry
    # exposes `wrapped`): tracing a wrapper would run its bookkeeping
    # on tracer args — bumping call counts and pre-registering the
    # argument signature the sentry's compile-proxy relies on
    target = getattr(step, "wrapped", step)
    findings = lint_program(target, args, program=program, config=cfg)
    findings += _dn.run(
        step, args, program=program, config=cfg,
        arg_names=arg_names, donate_argnums=donate_argnums,
        compile_report=compile_report)
    return findings
