"""Collective lint — mesh-axis and collective-placement analysis.

The SPMD program's collectives are fully visible in the jaxpr (psum /
all_gather / reduce_scatter eqns with their axis names as params), so
the properties veScale-style pre-execution verification wants are
plain graph checks:

  CL201  a collective over an axis the surrounding program does not
         bind (or that the declared mesh does not carry) — the
         mismatch that otherwise surfaces as an opaque trace error or,
         worse, a silently wrong reduction on a renamed mesh.
  CL202  psum-of-psum over the same axis: the second reduction
         multiplies by the axis size (a pmean of pre-summed grads
         keeps the SUM — the exact hazard ddp.sync_gradients documents)
         or is pure redundant traffic.
  CL203  a loop-invariant collective inside a `scan` body: every
         iteration pays ICI latency for bytes that never change —
         hoist it above the scan.
  CL204  a float16 psum/reduce_scatter operand: under loss scaling the
         summands are scaled by up to 2^15 and fp16 saturates at
         65504; the overflow happens INSIDE the collective where no
         finite-check sees it.  bf16 carries fp32's exponent and is
         exempt.
  CL205  a dead collective (no consumer, not a program output): XLA
         may DCE it, but its presence in the traced program means the
         source builds a reduction it never uses — usually a stale
         metrics line still paying a trace.
  CL206  an `all_to_all` over an unbound or mismatched expert-parallel
         axis.  Two shapes: (a) an all_to_all over an axis nothing
         binds/declares — the CL201 hazard with token-routing stakes
         (both rules fire deliberately: CL201 is the generic check,
         CL206 carries the dispatch-contract hint); (b) an all_to_all
         riding the DATA-parallel axis while the mesh carries `ep` —
         the classic dp/ep transposition typo, which scrambles tokens
         across data-parallel replicas instead of expert peers and
         trains — silently — on the wrong experts.  (b) is scoped to
         dp-riding exchanges only: all_to_alls over other axes (the
         Ulysses context-parallel head-scatter) are legitimate
         non-expert traffic even on an ep-carrying mesh.
  CL207  a `ppermute` whose permutation is not a bijection on its
         participant set.  `lax.ppermute` fills ranks that RECEIVE
         from nobody with ZEROS — no error, no warning — so a perm
         with duplicate sources/destinations or with
         set(srcs) != set(dsts) silently zeroes shards on the
         non-receiving ranks.  The chunked ring-overlap pipelines
         (parallel/overlap.py, ISSUE 18) spell chunk-count-many
         ppermutes per ring hop; one malformed hop zero-fills a
         chunk of activations and the loss still goes down.  The
         check is intra-perm only (LintConfig carries axis NAMES,
         not sizes, so a symmetric proper-subset ring over fewer
         ranks than the axis holds is out of reach here — the comms
         observatory's replica-group crosscheck covers that plane).
"""

from __future__ import annotations

from typing import List

from apex_tpu.lint import engine as E
from apex_tpu.lint.findings import Finding, make_finding

# collectives that SUM their operand (the overflow-under-scaling class)
_SUMMING = ("psum", "reduce_scatter", "psum_scatter")


def _coll_axes(eqn):
    """The axis names one collective eqn reduces over."""
    axes = eqn.params.get("axes")
    if axes is None:
        axes = eqn.params.get("axis_name")
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list, frozenset, set)):
        return tuple(str(a) for a in axes)
    return (str(axes),)


def _producer_through_scaling(prods, var, hops: int = 2):
    """Walk back through elementwise scaling (div/mul by a scalar —
    what pmean lowers to) to the producing eqn, so
    psum(pmean(x)) is recognized as psum-of-psum."""
    for _ in range(hops + 1):
        if isinstance(var, E._Literal):
            return None
        eqn = prods.get(var)
        if eqn is None:
            return None
        if eqn.primitive.name in ("div", "mul"):
            var = eqn.invars[0]
            continue
        return eqn
    return None


def run(views, *, program: str, config: E.LintConfig) -> List[Finding]:
    findings: List[Finding] = []
    expected = (frozenset(str(a) for a in config.expected_axes)
                if config.expected_axes is not None else None)

    for view in views:
        prods = E.producers(view.jaxpr)
        used = E.used_vars(view.jaxpr)
        inv = E.invariant_vars(view)
        counts: dict = {}
        for eqn in view.jaxpr.eqns:
            prim = eqn.primitive.name
            idx = counts.get(prim, 0)
            counts[prim] = idx + 1
            if prim not in E.COLLECTIVE_PRIMS:
                continue
            loc = view.eqn_location(program, eqn, idx)
            axes = _coll_axes(eqn)

            # ---- CL201: unbound / mismatched axis ----
            # the axis must be bound by an enclosing shard_map/pmap
            # scope (view.axes) when any is known, AND be carried by
            # the declared mesh when the caller named one
            for a in axes:
                bound_ok = not view.axes or a in view.axes
                declared_ok = expected is None or a in expected
                if not bound_ok or not declared_ok:
                    known = (sorted(view.axes) if not bound_ok
                             else sorted(expected))
                    findings.append(make_finding(
                        "CL201", loc,
                        f"{prim} reduces over axis {a!r} but the "
                        f"{'program binds' if not bound_ok else 'declared mesh carries'}"
                        f" only {known}",
                        hint="bind the axis in the mesh/shard_map (or "
                             "fix the axis_name typo); a collective "
                             "over the wrong axis reduces the wrong "
                             "ranks"))

            # ---- CL206: all_to_all off the expert-parallel axis ----
            # the ep axis is special-cased because a wrong-axis
            # all_to_all is not redundant traffic like CL202 — it is a
            # silently wrong token exchange.  Two shapes: (a) the
            # all_to_all names an axis nothing binds/declares; (b) an
            # ep axis EXISTS (bound or declared) but the exchange
            # rides a different one.
            if prim == "all_to_all":
                known = frozenset(view.axes) | (expected or frozenset())
                bad = next(
                    (a for a in axes
                     if (view.axes and a not in view.axes)
                     or (expected is not None and a not in expected)),
                    None)
                if bad is not None:
                    # name the axis set of the CHECK that failed (the
                    # CL201 convention): the bound axes when the
                    # program doesn't bind it, else the declared mesh
                    if view.axes and bad not in view.axes:
                        what, have = "program binds", sorted(view.axes)
                    else:
                        what, have = ("declared mesh carries",
                                      sorted(expected))
                    findings.append(make_finding(
                        "CL206", loc,
                        f"all_to_all exchanges over axis {bad!r} but "
                        f"the {what} only {have} — the expert "
                        "dispatch/combine would trade tokens with "
                        "nonexistent peers",
                        hint="bind the ep axis in the mesh "
                             "(initialize_model_parallel(expert_model_"
                             "parallel_size=...)) or fix the axis "
                             "name passed to the exchange"))
                elif ("ep" in known and "ep" not in axes
                        and "dp" in axes):
                    # scoped to DP-riding exchanges: an all_to_all
                    # over cp/tp (Ulysses head-scatter) is legitimate
                    # non-expert traffic even on an ep-carrying mesh
                    findings.append(make_finding(
                        "CL206", loc,
                        f"all_to_all rides {sorted(axes)} while the "
                        "mesh carries an expert-parallel 'ep' axis — "
                        "expert dispatch/combine must exchange over "
                        "ep; a dp/ep transposition scrambles tokens "
                        "across data-parallel replicas instead of "
                        "expert peers",
                        hint="pass the ep axis (mesh.EP_AXIS) to the "
                             "exchange, or allowlist if this "
                             "all_to_all is deliberately non-expert "
                             "traffic"))

            # ---- CL207: non-bijective ppermute (silent zero-fill) ----
            # lax.ppermute zero-fills every rank the perm does not
            # name as a destination — so anything short of a bijection
            # on the participant set loses data without an error.
            if prim == "ppermute":
                perm = tuple(eqn.params.get("perm") or ())
                srcs = [s for s, _ in perm]
                dsts = [d for _, d in perm]
                dup_src = len(srcs) != len(set(srcs))
                dup_dst = len(dsts) != len(set(dsts))
                if dup_src or dup_dst:
                    findings.append(make_finding(
                        "CL207", loc,
                        "ppermute perm has duplicate "
                        f"{'sources' if dup_src else 'destinations'} — "
                        "the permutation is not a bijection and the "
                        "exchange is ill-defined",
                        hint="each rank may appear at most once as "
                             "source and once as destination; a ring "
                             "hop is [(i, (i+shift) % n) for i in "
                             "range(n)]"))
                elif set(srcs) != set(dsts):
                    missing = sorted(set(srcs) - set(dsts))
                    findings.append(make_finding(
                        "CL207", loc,
                        f"ppermute perm sends from ranks {missing} that "
                        "receive from nobody — lax.ppermute fills "
                        "non-receiving ranks with ZEROS, silently "
                        "dropping their shard from the exchange",
                        hint="close the ring (every sender must also "
                             "receive) or allowlist if the zero-fill "
                             "is deliberate (one-directional halo "
                             "edge)"))

            # ---- CL202: psum-of-psum ----
            if prim == "psum":
                src = _producer_through_scaling(prods, eqn.invars[0])
                if src is not None and src.primitive.name == "psum":
                    src_axes = _coll_axes(src)
                    overlap = set(axes) & set(src_axes)
                    if overlap:
                        findings.append(make_finding(
                            "CL202", loc,
                            f"psum over {sorted(overlap)} of a value "
                            "already psum'd over the same axis — the "
                            "second reduction multiplies by the axis "
                            "size (or is pure redundant ICI traffic)",
                            hint="drop one reduction; if the first was "
                                 "a pmean keep ONLY it (see "
                                 "ddp.sync_gradients' vma note)"))

            # ---- CL203: loop-invariant collective in a scan body ----
            if view.scan_num_consts is not None and all(
                    isinstance(v, E._Literal) or v in inv
                    for v in eqn.invars):
                findings.append(make_finding(
                    "CL203", loc,
                    f"{prim} inside a scan body has loop-invariant "
                    "operands — every iteration pays the collective "
                    "for bytes that never change",
                    hint="hoist the collective above the lax.scan and "
                         "close over its result"))

            # ---- CL204: fp16 summing collective ----
            if prim in _SUMMING:
                in_dt = E.dtype_name(eqn.invars[0])
                if in_dt == "float16":
                    findings.append(make_finding(
                        "CL204", loc,
                        f"{prim} sums float16 operands — under loss "
                        "scaling the summands approach fp16's 65504 "
                        "max and the overflow happens inside the "
                        "collective, invisible to the finite-check",
                        hint="unscale or upcast to float32/bfloat16 "
                             "before the collective (bf16 carries "
                             "fp32's exponent range)"))

            # ---- CL205: dead collective ----
            if eqn.outvars and not any(v in used for v in eqn.outvars):
                findings.append(make_finding(
                    "CL205", loc,
                    f"{prim} result is never used (not a consumer, not "
                    "a program output) — the source still builds and "
                    "traces a reduction it throws away",
                    hint="delete the call (XLA would DCE it, but the "
                         "dead code misleads readers and slows "
                         "tracing)"))
    return findings
