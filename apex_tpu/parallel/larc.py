"""LARC — layer-wise adaptive rate control.

≡ apex.parallel.LARC (apex/parallel/LARC.py:5,78): wraps an inner
optimizer; before each step it rescales every parameter tensor's grad by
local_lr = trust_coefficient * ||p|| / (||g|| + wd*||p||), clipped to the
base lr in `clip` mode.  Weight decay is folded into the scaled grad
exactly like the reference (LARC.py:97-105).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def larc_adjust_grads(params, grads, lr, *, trust_coefficient=0.02,
                      clip=True, eps=1e-8, weight_decay=0.0,
                      use_pallas=None):
    """Return LARC-adjusted grads (per-tensor adaptive scaling).

    All per-tensor norms come from ONE row-aligned segment-sum pass over
    a lane-aligned flat view of params and grads — the same mechanism as
    LAMB's trust-ratio pass (ops/optimizer_kernels.py
    per_tensor_l2norm_aligned) — instead of a separate reduction per
    leaf (dozens of tiny XLA reductions at ResNet scale)."""
    from apex_tpu.ops import optimizer_kernels as K
    from apex_tpu.optimizers import flat as F

    spec = F.make_spec(params, align=K._LANES)
    pn = K.per_tensor_l2norm_aligned(
        F.flatten(params, jnp.float32, align=K._LANES,
                  pad_to=K.FLAT_TILE), spec, use_pallas_override=use_pallas)
    gn = K.per_tensor_l2norm_aligned(
        F.flatten(grads, jnp.float32, align=K._LANES,
                  pad_to=K.FLAT_TILE), spec, use_pallas_override=use_pallas)
    local_lr = trust_coefficient * pn / (gn + weight_decay * pn + eps)
    # skip adaptation when either norm is 0 (LARC.py:92-96)
    local_lr = jnp.where((pn > 0) & (gn > 0), local_lr, 1.0)
    if clip:
        scale = jnp.minimum(local_lr / lr, 1.0)
    else:
        scale = local_lr / lr  # eta mode: lr_total = base_lr * local_lr

    leaves_p, treedef = jax.tree_util.tree_flatten(params)
    leaves_g = jax.tree_util.tree_leaves(grads)
    out = []
    for i, (p, g) in enumerate(zip(leaves_p, leaves_g)):
        g32 = g.astype(jnp.float32) + weight_decay * p.astype(jnp.float32)
        out.append((g32 * scale[i]).astype(g.dtype))
    return jax.tree_util.tree_unflatten(treedef, out)


class LARC:
    """Optimizer wrapper ≡ apex.parallel.LARC.

    larc = LARC(FusedSGD(lr=...)); state = larc.init(params);
    params, state = larc.step(state, grads).
    """

    def __init__(self, optimizer, trust_coefficient=0.02, clip=True,
                 eps=1e-8):
        self.optim = optimizer
        self.trust_coefficient = trust_coefficient
        self.clip = clip
        self.eps = eps

    @property
    def spec(self):
        return self.optim.spec

    def init(self, params):
        return self.optim.init(params)

    def step(self, state, grads, lr=None, **kw):
        from apex_tpu.optimizers import flat as F
        lr_val = lr if lr is not None else self.optim.lr
        params = F.unflatten(state.params, self.optim.spec)
        wd = getattr(self.optim, "weight_decay", 0.0)
        adjusted = larc_adjust_grads(
            params, grads, lr_val,
            trust_coefficient=self.trust_coefficient, clip=self.clip,
            eps=self.eps, weight_decay=wd,
            use_pallas=getattr(self.optim, "use_pallas", None))
        # weight decay already applied to grads (reference zeroes it in
        # the wrapped optimizer during step, LARC.py:87-106)
        saved_wd = getattr(self.optim, "weight_decay", None)
        if saved_wd is not None:
            self.optim.weight_decay = 0.0
        try:
            out = self.optim.step(state, adjusted, lr=lr, **kw)
        finally:
            if saved_wd is not None:
                self.optim.weight_decay = saved_wd
        return out
