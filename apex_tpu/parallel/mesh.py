"""Global device-mesh bookkeeping — TPU-native `parallel_state`.

The reference (apex/transformer/parallel_state.py:36-419) maintains a
registry of torch.distributed process groups for data/tensor/pipeline/
virtual-pipeline/model/embedding parallelism.  On TPU there are no
process-group objects: parallel dimensions are *named axes of one
`jax.sharding.Mesh`*, collectives are emitted by the compiler against
those axis names, and "groups" become sub-axes.  This module is the
single place that builds and queries that mesh.

Axis layout follows Megatron rank ordering (tensor-parallel innermost so
TP collectives ride the fastest ICI links, then data-parallel, pipeline
outermost):  mesh shape = (pp, dp, tp) over `jax.devices()` in row-major
order — the same rank→group mapping as parallel_state.py:266-346.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names.  (dp, pp, tp) mirrors the reference's
# data-/pipeline-/tensor-parallel groups; "sp" is not a separate axis —
# Megatron sequence parallelism shards the sequence dim over the tp axis.
# "ep" is the expert-parallel axis (apex_tpu.moe): present in the mesh
# ONLY when initialize_model_parallel is asked for
# expert_model_parallel_size > 1, so dense programs trace over the
# identical 3-axis mesh they always did.
DP_AXIS = "dp"
PP_AXIS = "pp"
TP_AXIS = "tp"
EP_AXIS = "ep"

_GLOBAL_STATE = None


@dataclasses.dataclass
class _MeshState:
    mesh: Mesh
    tensor_model_parallel_size: int
    pipeline_model_parallel_size: int
    data_parallel_size: int
    expert_model_parallel_size: int = 1
    virtual_pipeline_model_parallel_size: Optional[int] = None
    # Mutable "current rank" cursors used by host-driven pipeline code,
    # mirroring the reference's get/set_virtual_pipeline_model_parallel_rank
    # (parallel_state.py:700-712).
    virtual_pipeline_model_parallel_rank: int = 0
    pipeline_model_parallel_split_rank: Optional[int] = None
    use_fp8: bool = False


class MeshNotInitializedError(RuntimeError):
    pass


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    expert_model_parallel_size: int = 1,
    use_fp8: bool = False,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global (pp, dp[, ep], tp) mesh.

    ≡ parallel_state.initialize_model_parallel (parallel_state.py:155-419),
    with process groups replaced by named mesh axes.  The data-parallel
    size is inferred as n_devices // (tp * pp * ep), exactly like the
    reference's `data_parallel_size = world_size // (tp*pp)`
    (parallel_state.py:242-244).

    expert_model_parallel_size > 1 inserts the expert-parallel axis
    between dp and tp — inner to dp so the MoE dispatch/combine
    all-to-alls (apex_tpu.moe) ride faster ICI links than the dp grad
    sync, outer to tp so each expert's GEMMs can still shard over tp.
    With the default (1) the mesh is the exact 3-axis (pp, dp, tp)
    layout every dense program has always traced over — no ep axis
    appears, so compiled programs, comms fixtures, and lint traces of
    dense steps are byte-identical to the pre-MoE framework.
    """
    global _GLOBAL_STATE
    if devices is None:
        devices = jax.devices()
    world_size = len(devices)
    tp, pp = tensor_model_parallel_size, pipeline_model_parallel_size
    ep = expert_model_parallel_size
    if ep < 1:
        raise ValueError(f"expert_model_parallel_size must be >= 1, got {ep}")
    if world_size % (tp * pp * ep) != 0:
        raise ValueError(
            f"world size {world_size} is not divisible by tp({tp}) x pp({pp})"
            f" x ep({ep})"
        )
    dp = world_size // (tp * pp * ep)
    if virtual_pipeline_model_parallel_size is not None and pp < 2:
        raise ValueError(
            "virtual pipeline parallelism requires pipeline_model_parallel_size >= 2"
        )
    if ep > 1:
        dev_array = np.asarray(devices).reshape(pp, dp, ep, tp)
        mesh = Mesh(dev_array, (PP_AXIS, DP_AXIS, EP_AXIS, TP_AXIS))
    else:
        dev_array = np.asarray(devices).reshape(pp, dp, tp)
        mesh = Mesh(dev_array, (PP_AXIS, DP_AXIS, TP_AXIS))
    _GLOBAL_STATE = _MeshState(
        mesh=mesh,
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        data_parallel_size=dp,
        expert_model_parallel_size=ep,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size,
        pipeline_model_parallel_split_rank=pipeline_model_parallel_split_rank,
        use_fp8=use_fp8,
    )
    return mesh


def model_parallel_is_initialized() -> bool:
    """≡ parallel_state.model_parallel_is_initialized (parallel_state.py:424)."""
    return _GLOBAL_STATE is not None


def destroy_model_parallel() -> None:
    """≡ parallel_state.destroy_model_parallel (parallel_state.py:761-792)."""
    global _GLOBAL_STATE
    _GLOBAL_STATE = None


def _state() -> _MeshState:
    if _GLOBAL_STATE is None:
        raise MeshNotInitializedError(
            "mesh is not initialized; call apex_tpu.parallel.initialize_model_parallel first"
        )
    return _GLOBAL_STATE


def get_mesh() -> Mesh:
    return _state().mesh


def get_tensor_model_parallel_world_size() -> int:
    return _state().tensor_model_parallel_size


def get_pipeline_model_parallel_world_size() -> int:
    return _state().pipeline_model_parallel_size


def get_data_parallel_world_size() -> int:
    return _state().data_parallel_size


def get_expert_model_parallel_world_size() -> int:
    return _state().expert_model_parallel_size


def get_data_parallel_axis_names() -> tuple:
    """The mesh axes a data batch (and its grad sync) spans.

    Without expert parallelism this is ("dp",).  With an ep axis the
    batch shards over BOTH ("dp", "ep") — expert parallelism rides
    inside the data-parallel world: each ep shard routes its own
    tokens and the all-to-all exchanges them with its ep peers, so for
    every non-expert parameter the ep axis is just more data
    parallelism (docs/moe.md, the routing contract).  Feed the tuple
    to `ddp.make_train_step(axis_name=...)` / `lax.pmean` — collective
    primitives take the tuple directly.
    """
    if _state().expert_model_parallel_size > 1:
        return (DP_AXIS, EP_AXIS)
    return (DP_AXIS,)


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _state().virtual_pipeline_model_parallel_size


def get_virtual_pipeline_model_parallel_rank() -> int:
    return _state().virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    _state().virtual_pipeline_model_parallel_rank = rank


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _state().pipeline_model_parallel_split_rank


# --- axis_index helpers: valid inside shard_map/pjit over the global mesh ---

def get_tensor_model_parallel_rank():
    """Per-shard tp coordinate; use inside shard_map (≡ get_tensor_model_parallel_rank)."""
    return jax.lax.axis_index(TP_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DP_AXIS)


def get_expert_model_parallel_rank():
    """Per-shard ep coordinate; use inside shard_map.  Only valid when
    the mesh was built with expert_model_parallel_size > 1 (the ep
    axis does not exist otherwise)."""
    return jax.lax.axis_index(EP_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PP_AXIS)


def is_pipeline_first_stage(stage: int) -> bool:
    """Host-side check for a host-driven pipeline stage index.

    ≡ parallel_state.is_pipeline_first_stage (parallel_state.py:590) for the
    non-virtual case; virtual chunks are handled by the schedule driver.
    """
    return stage == 0


def is_pipeline_last_stage(stage: int) -> bool:
    return stage == _state().pipeline_model_parallel_size - 1


def get_rank_info() -> str:
    """(dp, tp, pp) info string for log prefixes ≡ parallel_state.get_rank_info
    (parallel_state.py:421-430).  Host-level: reports process index and mesh
    shape (per-device coordinates are a compile-time notion under SPMD)."""
    if _GLOBAL_STATE is None:
        return f"proc{jax.process_index()}"
    s = _GLOBAL_STATE
    ep = (f"/ep{s.expert_model_parallel_size}"
          if s.expert_model_parallel_size > 1 else "")
    return (
        f"proc{jax.process_index()} dp{s.data_parallel_size}"
        f"/tp{s.tensor_model_parallel_size}"
        f"/pp{s.pipeline_model_parallel_size}{ep}"
    )


# --- sharding constructors -------------------------------------------------

def named_sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh from PartitionSpec entries."""
    return NamedSharding(get_mesh(), P(*spec))


def data_parallel_sharding(ndim: int) -> NamedSharding:
    """Batch-dim sharding over dp (and pp folded in when pp==1 is absent)."""
    spec = [DP_AXIS] + [None] * (ndim - 1)
    return named_sharding(*spec)


# --- group membership (pipeline-stage sets replacing process groups) -------
#
# The reference builds dedicated process groups for tied-embedding /
# position-embedding / relative-position-embedding gradient exchange
# (parallel_state.py:321-407) and fp8 amax reduction (280-292).  Under one
# SPMD mesh those become *sets of pipeline stages* (every (dp, tp)
# coordinate participates alike) plus the mesh axes to reduce over.

def _split(s: _MeshState) -> Optional[int]:
    return s.pipeline_model_parallel_split_rank


def get_embedding_group_stages() -> list:
    """Pipeline stages that hold tied input/output embeddings.

    ≡ embedding_ranks construction (parallel_state.py:352-370): [first,
    last], with the encoder/decoder split stage inserted when set.
    """
    s = _state()
    pp = s.pipeline_model_parallel_size
    if pp == 1:
        return [0]
    stages = [0, pp - 1]
    sp = _split(s)
    if sp is not None and sp not in stages:
        stages = [0, sp, pp - 1]
    return stages


def get_position_embedding_group_stages() -> list:
    """≡ position_embedding_ranks (parallel_state.py:355,367-370)."""
    s = _state()
    if s.pipeline_model_parallel_size == 1:
        return [0]
    sp = _split(s)
    return [0] if sp in (None, 0) else [0, sp]


def get_encoder_relative_position_embedding_group_stages() -> list:
    """≡ encoder_relative_position_embedding_ranks (parallel_state.py:356-363)."""
    s = _state()
    pp = s.pipeline_model_parallel_size
    if pp == 1:
        return [0]
    sp = _split(s)
    return [0] if sp is None else list(range(sp))


def get_decoder_relative_position_embedding_group_stages() -> list:
    """≡ decoder_relative_position_embedding_ranks (parallel_state.py:356-365)."""
    s = _state()
    pp = s.pipeline_model_parallel_size
    if pp == 1:
        return [0]
    sp = _split(s)
    return [0] if sp is None else list(range(sp, pp))


def is_rank_in_embedding_group(stage: int) -> bool:
    """≡ parallel_state.is_rank_in_embedding_group for a host-driven stage."""
    return stage in get_embedding_group_stages()


def is_rank_in_position_embedding_group(stage: int) -> bool:
    return stage in get_position_embedding_group_stages()


def is_pipeline_stage_before_split(stage: Optional[int] = None) -> bool:
    """≡ parallel_state.is_pipeline_stage_before_split: True when the stage
    executes encoder layers (always True without an encoder/decoder split)."""
    s = _state()
    sp = _split(s)
    if sp is None:
        return True
    if stage is None:
        raise ValueError("stage index required under SPMD (no implicit rank)")
    return stage < sp


def is_pipeline_stage_after_split(stage: Optional[int] = None) -> bool:
    s = _state()
    sp = _split(s)
    if sp is None:
        return True
    if stage is None:
        raise ValueError("stage index required under SPMD (no implicit rank)")
    return stage >= sp


def is_pipeline_stage_at_split(stage: int) -> bool:
    """True when `stage` runs the last encoder block and `stage+1` the first
    decoder block (≡ parallel_state.is_pipeline_stage_at_split)."""
    return is_pipeline_stage_before_split(stage) and is_pipeline_stage_after_split(
        stage + 1
    )


def set_pipeline_model_parallel_split_rank(rank: Optional[int]) -> None:
    _state().pipeline_model_parallel_split_rank = rank


# --- pipeline rank math ----------------------------------------------------

def get_pipeline_model_parallel_next_rank(stage: int) -> int:
    """Next stage index, wrapping — the ppermute source/dest math that
    replaces _PIPELINE_GLOBAL_RANKS lookups (parallel_state.py:737-752)."""
    return (stage + 1) % _state().pipeline_model_parallel_size


def get_pipeline_model_parallel_prev_rank(stage: int) -> int:
    return (stage - 1) % _state().pipeline_model_parallel_size


def get_pipeline_model_parallel_first_rank() -> int:
    return 0


def get_pipeline_model_parallel_last_rank() -> int:
    return _state().pipeline_model_parallel_size - 1


def get_pipeline_global_device_ranks(dp_index: int = 0, tp_index: int = 0) -> list:
    """Flat device indices of one pipeline group — range(i, world,
    world//pp) in the reference's rank ordering (parallel_state.py:345-348).
    With the (pp, dp, tp) row-major mesh this is stage*dp*tp + dp_index*tp
    + tp_index for each stage."""
    s = _state()
    stride = s.data_parallel_size * s.tensor_model_parallel_size
    base = dp_index * s.tensor_model_parallel_size + tp_index
    return [base + stage * stride for stage in
            range(s.pipeline_model_parallel_size)]


def get_tensor_model_parallel_src_rank(device_rank: int) -> int:
    """First flat device index of `device_rank`'s TP group
    (≡ parallel_state.get_tensor_model_parallel_src_rank:713-718)."""
    tp = _state().tensor_model_parallel_size
    return (device_rank // tp) * tp


def get_data_parallel_src_rank(device_rank: int) -> int:
    """First flat device index of `device_rank`'s DP group.

    ≡ parallel_state.get_data_parallel_src_rank:721-726 in intent.  The
    reference computes ``rank % num_dp_groups``, which only names the
    group's first member when pp == 1; here the first member is derived
    from the (pp, dp, tp) coordinates directly so it is correct for any
    pipeline depth: same stage, dp index 0, same tp index.
    """
    s = _state()
    stage_size = s.data_parallel_size * s.tensor_model_parallel_size
    stage_base = (device_rank // stage_size) * stage_size
    return stage_base + device_rank % s.tensor_model_parallel_size


# --- fp8 amax reduction ----------------------------------------------------

def fp8_is_enabled() -> bool:
    return _state().use_fp8


def get_amax_reduction_axes() -> tuple:
    """Mesh axes spanning one amax-reduction group.

    The reference's amax group is tp*dp contiguous ranks — exactly one
    pipeline stage's (dp, tp) plane under this mesh layout
    (parallel_state.py:280-292).  Reduce over these axes inside
    shard_map, e.g. ``lax.pmax(amax, get_amax_reduction_axes())``.
    """
    if not _state().use_fp8:
        raise MeshNotInitializedError(
            "AMAX reduction group is not initialized; pass use_fp8=True to "
            "initialize_model_parallel"
        )
    return (DP_AXIS, TP_AXIS)


def reduce_amax(x):
    """pmax of a per-shard amax over the amax-reduction group; call inside
    shard_map over the global mesh."""
    return jax.lax.pmax(x, get_amax_reduction_axes())


def get_model_parallel_axes() -> tuple:
    """Axes of the model-parallel group (pp × tp plane) — e.g. for the
    MP-aware GradScaler's found_inf reduction (amp/grad_scaler.py:44-55)."""
    return (PP_AXIS, TP_AXIS)


def new_process_group(axes) -> tuple:
    """≡ parallel_state.new_process_group (parallel_state.py:108-153).

    The reference creates a torch.distributed group from a rank list,
    choosing NCCL-vs-UCC and IB/socket transports.  Under one SPMD mesh a
    "group" is just a validated tuple of mesh axis names to hand to a
    collective; transport selection is XLA's (ICI within a slice, DCN
    across).  Accepts a single axis name or an iterable of them.
    """
    if isinstance(axes, str):
        axes = (axes,)
    axes = tuple(axes)
    valid = set(get_mesh().axis_names)
    unknown = [a for a in axes if a not in valid]
    if unknown:
        raise ValueError(f"unknown mesh axes {unknown}; have {sorted(valid)}")
    return axes
