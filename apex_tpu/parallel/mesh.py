"""Global device-mesh bookkeeping — TPU-native `parallel_state`.

The reference (apex/transformer/parallel_state.py:36-419) maintains a
registry of torch.distributed process groups for data/tensor/pipeline/
virtual-pipeline/model/embedding parallelism.  On TPU there are no
process-group objects: parallel dimensions are *named axes of one
`jax.sharding.Mesh`*, collectives are emitted by the compiler against
those axis names, and "groups" become sub-axes.  This module is the
single place that builds and queries that mesh.

Axis layout follows Megatron rank ordering (tensor-parallel innermost so
TP collectives ride the fastest ICI links, then data-parallel, pipeline
outermost):  mesh shape = (pp, dp, tp) over `jax.devices()` in row-major
order — the same rank→group mapping as parallel_state.py:266-346.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical axis names.  (dp, pp, tp) mirrors the reference's
# data-/pipeline-/tensor-parallel groups; "sp" is not a separate axis —
# Megatron sequence parallelism shards the sequence dim over the tp axis.
DP_AXIS = "dp"
PP_AXIS = "pp"
TP_AXIS = "tp"

_GLOBAL_STATE = None


@dataclasses.dataclass
class _MeshState:
    mesh: Mesh
    tensor_model_parallel_size: int
    pipeline_model_parallel_size: int
    data_parallel_size: int
    virtual_pipeline_model_parallel_size: Optional[int] = None
    # Mutable "current rank" cursors used by host-driven pipeline code,
    # mirroring the reference's get/set_virtual_pipeline_model_parallel_rank
    # (parallel_state.py:700-712).
    virtual_pipeline_model_parallel_rank: int = 0
    pipeline_model_parallel_split_rank: Optional[int] = None


class MeshNotInitializedError(RuntimeError):
    pass


def initialize_model_parallel(
    tensor_model_parallel_size: int = 1,
    pipeline_model_parallel_size: int = 1,
    virtual_pipeline_model_parallel_size: Optional[int] = None,
    pipeline_model_parallel_split_rank: Optional[int] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the global (pp, dp, tp) mesh.

    ≡ parallel_state.initialize_model_parallel (parallel_state.py:155-419),
    with process groups replaced by named mesh axes.  The data-parallel
    size is inferred as n_devices // (tp * pp), exactly like the
    reference's `data_parallel_size = world_size // (tp*pp)`
    (parallel_state.py:242-244).
    """
    global _GLOBAL_STATE
    if devices is None:
        devices = jax.devices()
    world_size = len(devices)
    tp, pp = tensor_model_parallel_size, pipeline_model_parallel_size
    if world_size % (tp * pp) != 0:
        raise ValueError(
            f"world size {world_size} is not divisible by tp({tp}) x pp({pp})"
        )
    dp = world_size // (tp * pp)
    if virtual_pipeline_model_parallel_size is not None and pp < 2:
        raise ValueError(
            "virtual pipeline parallelism requires pipeline_model_parallel_size >= 2"
        )
    dev_array = np.asarray(devices).reshape(pp, dp, tp)
    mesh = Mesh(dev_array, (PP_AXIS, DP_AXIS, TP_AXIS))
    _GLOBAL_STATE = _MeshState(
        mesh=mesh,
        tensor_model_parallel_size=tp,
        pipeline_model_parallel_size=pp,
        data_parallel_size=dp,
        virtual_pipeline_model_parallel_size=virtual_pipeline_model_parallel_size,
        pipeline_model_parallel_split_rank=pipeline_model_parallel_split_rank,
    )
    return mesh


def model_parallel_is_initialized() -> bool:
    """≡ parallel_state.model_parallel_is_initialized (parallel_state.py:424)."""
    return _GLOBAL_STATE is not None


def destroy_model_parallel() -> None:
    """≡ parallel_state.destroy_model_parallel (parallel_state.py:761-792)."""
    global _GLOBAL_STATE
    _GLOBAL_STATE = None


def _state() -> _MeshState:
    if _GLOBAL_STATE is None:
        raise MeshNotInitializedError(
            "mesh is not initialized; call apex_tpu.parallel.initialize_model_parallel first"
        )
    return _GLOBAL_STATE


def get_mesh() -> Mesh:
    return _state().mesh


def get_tensor_model_parallel_world_size() -> int:
    return _state().tensor_model_parallel_size


def get_pipeline_model_parallel_world_size() -> int:
    return _state().pipeline_model_parallel_size


def get_data_parallel_world_size() -> int:
    return _state().data_parallel_size


def get_virtual_pipeline_model_parallel_world_size() -> Optional[int]:
    return _state().virtual_pipeline_model_parallel_size


def get_virtual_pipeline_model_parallel_rank() -> int:
    return _state().virtual_pipeline_model_parallel_rank


def set_virtual_pipeline_model_parallel_rank(rank: int) -> None:
    _state().virtual_pipeline_model_parallel_rank = rank


def get_pipeline_model_parallel_split_rank() -> Optional[int]:
    return _state().pipeline_model_parallel_split_rank


# --- axis_index helpers: valid inside shard_map/pjit over the global mesh ---

def get_tensor_model_parallel_rank():
    """Per-shard tp coordinate; use inside shard_map (≡ get_tensor_model_parallel_rank)."""
    return jax.lax.axis_index(TP_AXIS)


def get_data_parallel_rank():
    return jax.lax.axis_index(DP_AXIS)


def get_pipeline_model_parallel_rank():
    return jax.lax.axis_index(PP_AXIS)


def is_pipeline_first_stage(stage: int) -> bool:
    """Host-side check for a host-driven pipeline stage index.

    ≡ parallel_state.is_pipeline_first_stage (parallel_state.py:590) for the
    non-virtual case; virtual chunks are handled by the schedule driver.
    """
    return stage == 0


def is_pipeline_last_stage(stage: int) -> bool:
    return stage == _state().pipeline_model_parallel_size - 1


def get_rank_info() -> str:
    """(dp, tp, pp) info string for log prefixes ≡ parallel_state.get_rank_info
    (parallel_state.py:421-430).  Host-level: reports process index and mesh
    shape (per-device coordinates are a compile-time notion under SPMD)."""
    if _GLOBAL_STATE is None:
        return f"proc{jax.process_index()}"
    s = _GLOBAL_STATE
    return (
        f"proc{jax.process_index()} dp{s.data_parallel_size}"
        f"/tp{s.tensor_model_parallel_size}/pp{s.pipeline_model_parallel_size}"
    )


# --- sharding constructors -------------------------------------------------

def named_sharding(*spec) -> NamedSharding:
    """NamedSharding over the global mesh from PartitionSpec entries."""
    return NamedSharding(get_mesh(), P(*spec))


def data_parallel_sharding(ndim: int) -> NamedSharding:
    """Batch-dim sharding over dp (and pp folded in when pp==1 is absent)."""
    spec = [DP_AXIS] + [None] * (ndim - 1)
    return named_sharding(*spec)
