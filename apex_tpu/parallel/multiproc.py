"""Multi-process launcher for multi-host (multi-controller) JAX runs.

≡ apex.parallel.multiproc (apex/parallel/multiproc.py): the reference
spawns `nproc_per_node` copies of a training script with RANK/WORLD_SIZE
env vars for `torch.distributed`.  The TPU-native analogue launches N
controller processes wired to a `jax.distributed` coordinator; on CPU it
additionally forces the emulated-device platform so sharding code paths
run without TPU hardware (the harness gap called out in SURVEY.md §4).

Usage:
    python -m apex_tpu.parallel.multiproc --nproc 4 train.py --arg ...

Each child gets:
    APEX_TPU_COORDINATOR   host:port of the jax.distributed coordinator
    APEX_TPU_NUM_PROCESSES total process count
    APEX_TPU_PROCESS_ID    this process's id
and (CPU emulation) JAX_PLATFORMS=cpu plus
--xla_force_host_platform_device_count so every process sees
`devices_per_proc` local devices.  `init_from_env()` is the child-side
hook that calls `jax.distributed.initialize` from those variables.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys

__all__ = ["main", "init_from_env"]


def init_from_env():
    """Child-side: initialize jax.distributed from launcher env vars.

    ≡ the `torch.distributed.init_process_group(init_method='env://')`
    call the reference's spawned scripts perform.  No-op when the
    launcher variables are absent (single-process run).
    """
    coord = os.environ.get("APEX_TPU_COORDINATOR")
    if not coord:
        return False
    import jax

    devs = int(os.environ.get("APEX_TPU_DEVICES_PER_PROC", "0"))
    if devs > 0:
        # CPU emulation must be forced through jax.config: plugin
        # platforms (e.g. a TPU tunnel) can take priority over the
        # JAX_PLATFORMS env var set by the launcher.
        jax.config.update("jax_platforms", "cpu")
        jax.config.update("jax_num_cpu_devices", devs)
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["APEX_TPU_NUM_PROCESSES"]),
        process_id=int(os.environ["APEX_TPU_PROCESS_ID"]),
    )
    return True


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="apex_tpu multi-process launcher "
                    "(≡ apex/parallel/multiproc.py)")
    parser.add_argument("--nproc", type=int, default=2,
                        help="number of controller processes to spawn")
    parser.add_argument("--coordinator", default="127.0.0.1:12355",
                        help="jax.distributed coordinator host:port")
    parser.add_argument("--devices-per-proc", type=int, default=0,
                        help=">0: force CPU emulation with this many "
                             "virtual devices per process")
    parser.add_argument("script", help="training script to run")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    procs = []
    for pid in range(args.nproc):
        env = dict(os.environ)
        env["APEX_TPU_COORDINATOR"] = args.coordinator
        env["APEX_TPU_NUM_PROCESSES"] = str(args.nproc)
        env["APEX_TPU_PROCESS_ID"] = str(pid)
        if args.devices_per_proc > 0:
            env["APEX_TPU_DEVICES_PER_PROC"] = str(args.devices_per_proc)
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.devices_per_proc}").strip()
        cmd = [sys.executable, args.script] + args.script_args
        procs.append(subprocess.Popen(cmd, env=env))

    rc = 0
    for p in procs:
        p.wait()
        rc = rc or p.returncode
    # Mirror the reference's behavior of surfacing a child failure.
    return rc


if __name__ == "__main__":
    sys.exit(main())
