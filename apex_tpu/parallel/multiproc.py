"""Multi-process launcher for multi-host (multi-controller) JAX runs.

≡ apex.parallel.multiproc (apex/parallel/multiproc.py): the reference
spawns `nproc_per_node` copies of a training script with RANK/WORLD_SIZE
env vars for `torch.distributed`.  The TPU-native analogue launches N
controller processes wired to a `jax.distributed` coordinator; on CPU it
additionally forces the emulated-device platform so sharding code paths
run without TPU hardware (the harness gap called out in SURVEY.md §4).

Usage:
    python -m apex_tpu.parallel.multiproc --nproc 4 train.py --arg ...

Each child gets:
    APEX_TPU_COORDINATOR   host:port of the jax.distributed coordinator
    APEX_TPU_NUM_PROCESSES total process count
    APEX_TPU_PROCESS_ID    this process's id
and (CPU emulation) JAX_PLATFORMS=cpu plus
--xla_force_host_platform_device_count so every process sees
`devices_per_proc` local devices.  `init_from_env()` is the child-side
hook that calls `jax.distributed.initialize` from those variables.

Failure semantics (ISSUE 11): children are POLLED concurrently — a
child that dies first no longer leaves its siblings hung on a
collective until some outer CI timeout eats the budget.  The first
nonzero exit is propagated as the launcher's return code; surviving
children get `--grace` seconds to finish on their own (the fleet
probe's survivors must be OBSERVABLE committing-or-refusing — grace 0,
the default, terminates them immediately), then SIGTERM → SIGKILL.
`--timeout` bounds the whole fleet: a hung run fails loudly instead of
hanging CI.
"""

from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

__all__ = ["main", "init_from_env", "wait_fleet"]


def init_from_env():
    """Child-side: initialize jax.distributed from launcher env vars.

    ≡ the `torch.distributed.init_process_group(init_method='env://')`
    call the reference's spawned scripts perform.  No-op when the
    launcher variables are absent (single-process run).
    """
    coord = os.environ.get("APEX_TPU_COORDINATOR")
    if not coord:
        return False
    import jax

    devs = int(os.environ.get("APEX_TPU_DEVICES_PER_PROC", "0"))
    if devs > 0:
        # CPU emulation must be forced through jax.config: plugin
        # platforms (e.g. a TPU tunnel) can take priority over the
        # JAX_PLATFORMS env var set by the launcher.
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", devs)
        except AttributeError:
            # jax < 0.5 has no jax_num_cpu_devices; the XLA_FLAGS
            # --xla_force_host_platform_device_count the launcher set
            # (before any jax import in the child) provides the devices
            pass
    jax.distributed.initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["APEX_TPU_NUM_PROCESSES"]),
        process_id=int(os.environ["APEX_TPU_PROCESS_ID"]),
    )
    return True


def wait_fleet(procs, *, timeout=None, grace=0.0, poll=0.05,
               term_wait=5.0):
    """Poll `procs` (subprocess.Popen) until all exit, any one fails,
    or `timeout` elapses.  Returns the fleet's return code: 0 when
    every child exited 0; the FIRST nonzero exit otherwise; 124 on
    timeout (the `timeout(1)` convention).

    On first failure the survivors get `grace` seconds to finish on
    their own — a checkpoint barrier refusing cleanly IS the behavior
    under test when a sibling dies — then are terminated (SIGTERM,
    escalating to SIGKILL after `term_wait`).  On timeout everything
    is terminated immediately.
    """
    deadline = None if timeout is None else time.monotonic() + timeout

    def _alive():
        return [p for p in procs if p.poll() is None]

    def _terminate(alive):
        for p in alive:
            try:
                p.terminate()
            except OSError:  # pragma: no cover — already gone
                pass
        t_kill = time.monotonic() + term_wait
        for p in alive:
            while p.poll() is None and time.monotonic() < t_kill:
                time.sleep(poll)
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:  # pragma: no cover
                    pass
                p.wait()

    rc = 0
    grace_deadline = None
    while True:
        alive = _alive()
        if rc == 0:
            for p in procs:
                r = p.poll()
                if r:  # first failure wins; record + start the grace
                    rc = r
                    grace_deadline = time.monotonic() + grace
                    break
        if not alive:
            return rc
        now = time.monotonic()
        if deadline is not None and now >= deadline:
            sys.stderr.write(
                f"multiproc: fleet timeout after {timeout}s — "
                f"terminating {len(alive)} hung child(ren)\n")
            _terminate(alive)
            return rc or 124
        if grace_deadline is not None and now >= grace_deadline:
            _terminate(_alive())
            return rc
        time.sleep(poll)


def main(argv=None):
    parser = argparse.ArgumentParser(
        description="apex_tpu multi-process launcher "
                    "(≡ apex/parallel/multiproc.py)")
    parser.add_argument("--nproc", type=int, default=2,
                        help="number of controller processes to spawn")
    parser.add_argument("--coordinator", default="127.0.0.1:12355",
                        help="jax.distributed coordinator host:port")
    parser.add_argument("--devices-per-proc", type=int, default=0,
                        help=">0: force CPU emulation with this many "
                             "virtual devices per process")
    parser.add_argument("--timeout", type=float, default=None,
                        help="kill the whole fleet after this many "
                             "seconds (exit 124) — a hung fleet fails "
                             "CI instead of eating its budget")
    parser.add_argument("--grace", type=float, default=0.0,
                        help="after the first child failure, let "
                             "survivors run this many seconds before "
                             "terminating them (default 0: immediate)")
    parser.add_argument("script", help="training script to run")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    procs = []
    for pid in range(args.nproc):
        env = dict(os.environ)
        env["APEX_TPU_COORDINATOR"] = args.coordinator
        env["APEX_TPU_NUM_PROCESSES"] = str(args.nproc)
        env["APEX_TPU_PROCESS_ID"] = str(pid)
        if args.devices_per_proc > 0:
            env["APEX_TPU_DEVICES_PER_PROC"] = str(args.devices_per_proc)
            env["JAX_PLATFORMS"] = "cpu"
            flags = env.get("XLA_FLAGS", "")
            env["XLA_FLAGS"] = (
                f"{flags} --xla_force_host_platform_device_count="
                f"{args.devices_per_proc}").strip()
        cmd = [sys.executable, args.script] + args.script_args
        procs.append(subprocess.Popen(cmd, env=env))

    # Mirror the reference's behavior of surfacing a child failure —
    # but poll ALL children: the old in-order wait left siblings hung
    # on a dead rank's collective until an outer timeout fired.
    return wait_fleet(procs, timeout=args.timeout, grace=args.grace)


if __name__ == "__main__":
    sys.exit(main())
