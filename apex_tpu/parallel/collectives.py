"""Autograd-visible collectives — TPU-native `tensor_parallel.mappings`.

The reference defines seven torch.autograd.Functions giving Megatron's
copy/reduce/scatter/gather semantics around tensor-parallel regions
(apex/transformer/tensor_parallel/mappings.py:141-268).  Here each is a
`jax.custom_vjp` over `jax.lax` collectives, to be used **inside
`shard_map`** over the global mesh where the named axis (default "tp")
is unmapped (manual).  Under plain pjit, XLA's partitioner makes these
unnecessary; they exist for the explicit-collective (shard_map) code
path, where JAX's default transpose rules for psum/all_gather do NOT
reproduce Megatron's conjugate f/g pairs.

Forward/backward pairs (mappings.py:141-268):
  copy_to_tensor_model_parallel_region        id      / psum
  reduce_from_tensor_model_parallel_region    psum    / id
  scatter_to_tensor_model_parallel_region     split-1 / gather-1
  gather_from_tensor_model_parallel_region    gather-1/ split-1
  scatter_to_sequence_parallel_region         split0  / gather0
  gather_from_sequence_parallel_region        gather0 / reduce_scatter0
  reduce_scatter_to_sequence_parallel_region  rs0     / gather0
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.mesh import TP_AXIS


def _psum(x, axis_name):
    return lax.psum(x, axis_name)


def _all_gather(x, axis_name, dim):
    """Concatenate shards along `dim` ≡ mappings._gather_along_*_dim."""
    return lax.all_gather(x, axis_name, axis=dim, tiled=True)


def _split(x, axis_name, dim):
    """Keep this rank's slice along `dim` ≡ mappings._split_along_*_dim."""
    n = lax.axis_size(axis_name)
    local = x.shape[dim] // n
    idx = lax.axis_index(axis_name)
    return lax.dynamic_slice_in_dim(x, idx * local, local, axis=dim)


def _reduce_scatter(x, axis_name, dim):
    """Sum across the axis, each rank keeps its slice along `dim`
    ≡ mappings._reduce_scatter_along_first_dim."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=dim, tiled=True)


def _make_pair(name, fwd_fn, bwd_fn):
    """Build a custom_vjp collective with independent fwd/bwd collectives."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
    def fn(x, axis_name=TP_AXIS):
        return fwd_fn(x, axis_name)

    def fn_fwd(x, axis_name):
        return fwd_fn(x, axis_name), None

    def fn_bwd(axis_name, _, g):
        return (bwd_fn(g, axis_name),)

    fn.defvjp(fn_fwd, fn_bwd)
    fn.__name__ = name
    return fn


_last = -1

copy_to_tensor_model_parallel_region = _make_pair(
    "copy_to_tensor_model_parallel_region",
    lambda x, ax: x,
    lambda g, ax: _psum(g, ax),
)

reduce_from_tensor_model_parallel_region = _make_pair(
    "reduce_from_tensor_model_parallel_region",
    lambda x, ax: _psum(x, ax),
    lambda g, ax: g,
)

scatter_to_tensor_model_parallel_region = _make_pair(
    "scatter_to_tensor_model_parallel_region",
    lambda x, ax: _split(x, ax, _last),
    lambda g, ax: _all_gather(g, ax, _last),
)

gather_from_tensor_model_parallel_region = _make_pair(
    "gather_from_tensor_model_parallel_region",
    lambda x, ax: _all_gather(x, ax, _last),
    lambda g, ax: _split(g, ax, _last),
)

scatter_to_sequence_parallel_region = _make_pair(
    "scatter_to_sequence_parallel_region",
    lambda x, ax: _split(x, ax, 0),
    lambda g, ax: _all_gather(g, ax, 0),
)

# tensor_parallel_output_grad=True variant (mappings.py:232-247): backward
# is a reduce-scatter because the downstream TP region produces
# partial-sum gradients on every rank.
gather_from_sequence_parallel_region = _make_pair(
    "gather_from_sequence_parallel_region",
    lambda x, ax: _all_gather(x, ax, 0),
    lambda g, ax: _reduce_scatter(g, ax, 0),
)

# tensor_parallel_output_grad=False variant: backward is a plain split.
gather_from_sequence_parallel_region_no_tp_grad = _make_pair(
    "gather_from_sequence_parallel_region_no_tp_grad",
    lambda x, ax: _all_gather(x, ax, 0),
    lambda g, ax: _split(g, ax, 0),
)

reduce_scatter_to_sequence_parallel_region = _make_pair(
    "reduce_scatter_to_sequence_parallel_region",
    lambda x, ax: _reduce_scatter(x, ax, 0),
    lambda g, ax: _all_gather(g, ax, 0),
)


def ring_exchange(x, axis_name, shift=1):
    """Neighbour exchange over a ring ≡ the reference's halo-exchange NCCL
    p2p (apex/contrib/csrc/nccl_p2p/nccl_p2p.cpp:20-24) — on TPU a single
    `ppermute` riding ICI."""
    n = lax.axis_size(axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def halo_exchange_1d(x, axis_name, halo: int, dim: int = 0):
    """Exchange `halo`-wide boundary slabs with both ring neighbours along
    `dim` ≡ PeerHaloExchanger1d (apex/contrib/peer_memory/peer_halo_exchanger_1d.py:5)
    and HaloExchangerSendRecv (apex/contrib/bottleneck/halo_exchangers.py:60).

    Returns (left_halo, right_halo): the slabs received from the previous /
    next rank, to be concatenated by the caller (spatial-parallel conv).
    """
    n = lax.axis_size(axis_name)
    top = lax.slice_in_dim(x, 0, halo, axis=dim)
    bot = lax.slice_in_dim(x, x.shape[dim] - halo, x.shape[dim], axis=dim)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    left = lax.ppermute(bot, axis_name, fwd)   # from prev rank
    right = lax.ppermute(top, axis_name, bwd)  # from next rank
    return left, right
