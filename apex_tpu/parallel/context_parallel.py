"""Context parallelism for long sequences — ring attention + Ulysses.

The reference's only long-sequence mechanism is Megatron SP + fixed-size
FMHA kernels (SURVEY §5.7: no ring attention, no Ulysses).  For the TPU
framework long context is first-class: the flash kernel's blockwise
structure extends across chips —

* `ring_attention` — sequence (and KV) sharded over a mesh axis; KV
  chunks rotate around the ICI ring with `ppermute` while each device
  accumulates its queries' online-softmax state (running max / denom /
  output).  Peak memory per device: O(s_local²) scores, O(s_local·d)
  KV — sequence length scales linearly with the ring size.

* `ulysses_attention` — all-to-all head scatter: convert seq-sharding
  to head-sharding with `lax.all_to_all`, run dense (flash) attention
  on full sequences of the local heads, convert back.  One collective
  pair per attention instead of n ring hops; needs heads % axis == 0.

Both are differentiable (AD through scan/ppermute/all_to_all emits the
reverse rotation) and compose with the TP layers (use a separate mesh
axis or reuse "tp" when attention is not head-sharded).
"""

from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   softmax_scale: Optional[float] = None):
    """Blockwise ring attention.

    q, k, v: (b, h, s_local, d) — the LOCAL sequence shard; the global
    sequence is the concatenation over the axis in rank order.
    Returns the local output shard (b, h, s_local, d).
    """
    b, h, s_local, d = q.shape
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    scale = softmax_scale if softmax_scale is not None else 1.0 / math.sqrt(d)

    q32 = q.astype(jnp.float32)
    q_pos = rank * s_local + jnp.arange(s_local)          # global q rows

    def step(carry, i):
        m, l, o, kv = carry
        k_i, v_i = kv
        src = (rank - i) % n                              # chunk origin
        s = jnp.einsum("bhqd,bhkd->bhqk", q32,
                       k_i.astype(jnp.float32)) * scale
        if causal:
            kv_pos = src * s_local + jnp.arange(s_local)
            mask = kv_pos[None, :] > q_pos[:, None]       # (s_local, s_local)
            s = jnp.where(mask[None, None], -1e30, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       v_i.astype(jnp.float32))
        # rotate KV to the next rank (ICI neighbour exchange)
        perm = [(r, (r + 1) % n) for r in range(n)]
        kv_next = jax.tree_util.tree_map(
            lambda x: lax.ppermute(x, axis_name, perm), (k_i, v_i))
        return (m_new, l_new, o_new, kv_next), None

    m0 = jnp.full((b, h, s_local, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    o0 = jnp.zeros((b, h, s_local, d), jnp.float32)
    (m, l, o, _), _ = lax.scan(step, (m0, l0, o0, (k, v)), jnp.arange(n))
    return (o / jnp.maximum(l, 1e-30)).astype(q.dtype)


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      softmax_scale: Optional[float] = None,
                      use_flash: bool = True):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    Inputs are seq-sharded (b, h, s_local, d) with h % axis_size == 0;
    internally heads are scattered so each device sees the FULL sequence
    for h/axis heads, runs (flash) attention, and scatters back.
    """
    n = lax.axis_size(axis_name)
    b, h, s_local, d = q.shape
    assert h % n == 0, "ulysses needs heads divisible by the axis size"

    def seq_to_heads(x):
        # (b, h, s_local, d) → (b, h/n, s_global, d): scatter heads,
        # gather sequence — one tiled all_to_all
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    if use_flash:
        from apex_tpu.ops.flash_attention import flash_attention
        og = flash_attention(qg, kg, vg, causal=causal,
                             softmax_scale=softmax_scale)
    else:
        from apex_tpu.ops.flash_attention import attention_reference
        og = attention_reference(qg, kg, vg, causal=causal,
                                 softmax_scale=softmax_scale)
    return heads_to_seq(og)
