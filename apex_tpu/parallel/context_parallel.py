"""Context parallelism for long sequences — ring attention + Ulysses.

The reference's only long-sequence mechanism is Megatron SP + fixed-size
FMHA kernels (SURVEY §5.7: no ring attention, no Ulysses).  For the TPU
framework long context is first-class:

* `ring_attention` — sequence (and KV) sharded over a mesh axis; KV
  chunks rotate around the ICI ring with `ppermute` while each device
  merges per-chunk blockwise-attention results into its queries'
  running online-softmax state.  v2 design:

  - each ring step runs the SAME blockwise flash kernel as single-chip
    attention (`ops/flash_attention._fwd_impl`) on the resident
    (s_local × s_local) chunk pair — the (s²) score matrix never
    reaches HBM, on any backend (a jnp blockwise scan stands in for
    Pallas off-TPU);
  - a `custom_vjp` recomputes the backward from the saved (o, lse)
    instead of AD-through-scan: per-device residuals are
    q, k, v, o (s_local × d) + lse (s_local) — linear in s_local, NOT
    the O(n · s_local²) of differentiating through the forward scan;
  - causal chunks strictly above the diagonal are SKIPPED (a
    `lax.switch` branch that touches no scores), not masked: a causal
    ring costs ~half the FLOPs of the full ring;
  - segment ids rotate with their KV chunk, so packed-varlen batches
    work across the ring exactly as they do in-kernel;
  - layout="zigzag" (with `zigzag_shard`/`zigzag_unshard`) balances
    the causal load: device r owns the half-chunk pair (r, 2n-1-r),
    every device runs exactly two half-computes per step, and the
    causal ring's wall-clock HALVES vs the contiguous layout (whose
    last rank computes at every step).

  Peak per-device memory: O(s_local · d) tensors + one (block × block)
  score tile — global sequence length scales linearly with ring size.

* `ulysses_attention` — all-to-all head scatter: convert seq-sharding
  to head-sharding with `lax.all_to_all`, run (flash) attention on
  full sequences of the local heads, convert back.  One collective
  pair per attention instead of n ring hops; needs heads % axis == 0.

Both compose with the TP layers (use a separate mesh axis or reuse
"tp" when attention is not head-sharded).  In-kernel attention dropout
works on the ring path too: each chunk hashes its GLOBAL (q, k)
sequence offsets into the coordinate-hash keep mask, so all ring steps
and the backward draw from ONE global mask — bit-identical to
single-chip flash attention over the gathered sequence (tested in
tests/test_context_parallel.py).
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from apex_tpu.ops._common import use_pallas
from apex_tpu.ops.flash_attention import (
    _NEG_INF,
    _bwd_impl,
    _fwd_impl,
    _pick_block,
    dropout_keep_dense,
)


# ------------------------- per-chunk blockwise attention ---------------------

def _jnp_blocks(sk, block_k):
    if block_k is not None and sk % block_k:
        raise ValueError(f"block_k={block_k} does not divide "
                         f"s_local={sk}")
    bk = block_k or _pick_block(sk, cap=1024)
    if bk is None:
        bk = sk  # no power-of-two divisor: single block
    return bk, sk // bk


def _chunk_fwd_jnp(q, k, v, scale, causal, q_seg, kv_seg, block_k,
                   dropout_rate=0.0, seed=None, q_off=0, k_off=0):
    """Blockwise online-softmax forward in plain jnp (the off-TPU stand-in
    for the Pallas kernel): scans k-blocks so peak score memory is
    (sq × block_k), never (sq × sk).  Returns (o, lse).  Dropout uses
    the kernel's global-coordinate hash (dropout_keep_dense), masking p
    before the deferred 1/l normalization (the l denominator stays the
    raw softmax sum, ≡ _fwd_kernel)."""
    b, h, sq, d = q.shape
    sk = k.shape[2]
    bk, nk = _jnp_blocks(sk, block_k)
    q32 = q.astype(jnp.float32)
    qpos = jnp.arange(sq)

    def step(carry, t):
        m, l, o = carry
        k_t = lax.dynamic_slice_in_dim(k, t * bk, bk, 2).astype(jnp.float32)
        v_t = lax.dynamic_slice_in_dim(v, t * bk, bk, 2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_t) * scale
        if q_seg is not None:
            ks_t = lax.dynamic_slice_in_dim(kv_seg, t * bk, bk, 1)
            s = jnp.where(q_seg[:, None, :, None] != ks_t[:, None, None, :],
                          _NEG_INF, s)
        if causal:
            kpos = t * bk + jnp.arange(bk)
            s = jnp.where(kpos[None, :] > qpos[:, None], _NEG_INF, s)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        if dropout_rate > 0.0:
            keep = dropout_keep_dense(seed, b, h, sq, bk, dropout_rate,
                                      q_off, k_off + t * bk)
            p_acc = jnp.where(keep, p, 0.0) / (1.0 - dropout_rate)
        else:
            p_acc = p
        o_new = o * alpha[..., None] + jnp.einsum("bhqk,bhkd->bhqd",
                                                  p_acc, v_t)
        return (m_new, l_new, o_new), None

    m0 = jnp.full((b, h, sq), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h, sq), jnp.float32)
    o0 = jnp.zeros((b, h, sq, d), jnp.float32)
    (m, l, o), _ = lax.scan(step, (m0, l0, o0), jnp.arange(nk))
    l = jnp.maximum(l, 1e-30)
    return (o / l[..., None]).astype(q.dtype), m + jnp.log(l)


def _chunk_bwd_jnp(q, k, v, do, lse, delta, scale, causal, q_seg, kv_seg,
                   block_k, dropout_rate=0.0, seed=None, q_off=0,
                   k_off=0):
    """Blockwise backward against the GLOBAL (lse, delta) — the partials
    this produces sum across ring steps to the exact gradient.  Dropout
    regenerates the forward's coordinate-hash mask (≡ _bwd_dkv_kernel:
    dv uses dropped p, dp is masked before ds)."""
    b, h = q.shape[0], q.shape[1]
    sq = q.shape[2]
    sk = k.shape[2]
    bk, nk = _jnp_blocks(sk, block_k)
    q32 = q.astype(jnp.float32)
    do32 = do.astype(jnp.float32)
    qpos = jnp.arange(q.shape[2])

    def step(dq, t):
        k_t = lax.dynamic_slice_in_dim(k, t * bk, bk, 2).astype(jnp.float32)
        v_t = lax.dynamic_slice_in_dim(v, t * bk, bk, 2).astype(jnp.float32)
        s = jnp.einsum("bhqd,bhkd->bhqk", q32, k_t) * scale
        if q_seg is not None:
            ks_t = lax.dynamic_slice_in_dim(kv_seg, t * bk, bk, 1)
            s = jnp.where(q_seg[:, None, :, None] != ks_t[:, None, None, :],
                          _NEG_INF, s)
        if causal:
            kpos = t * bk + jnp.arange(bk)
            s = jnp.where(kpos[None, :] > qpos[:, None], _NEG_INF, s)
        p = jnp.exp(s - lse[..., None])                    # global-normalized
        dp = jnp.einsum("bhqd,bhkd->bhqk", do32, v_t)
        if dropout_rate > 0.0:
            keep = dropout_keep_dense(seed, b, h, sq, bk, dropout_rate,
                                      q_off, k_off + t * bk)
            inv = 1.0 / (1.0 - dropout_rate)
            p_v = jnp.where(keep, p, 0.0) * inv
            dp = jnp.where(keep, dp, 0.0) * inv
        else:
            p_v = p
        ds = p * (dp - delta[..., None])
        dq = dq + scale * jnp.einsum("bhqk,bhkd->bhqd", ds, k_t)
        dk_t = scale * jnp.einsum("bhqk,bhqd->bhkd", ds, q32)
        dv_t = jnp.einsum("bhqk,bhqd->bhkd", p_v, do32)
        return dq, (dk_t, dv_t)

    dq0 = jnp.zeros(q.shape[:3] + (q.shape[3],), jnp.float32)
    dq, (dk_b, dv_b) = lax.scan(step, dq0, jnp.arange(nk))
    # stacked (nk, b, h, bk, d) → (b, h, sk, d)
    def unblock(x):
        return jnp.moveaxis(x, 0, 2).reshape(k.shape[:2] + (sk, k.shape[3]))
    return dq, unblock(dk_b), unblock(dv_b)


def _chunk_fwd(q, k, v, scale, causal, q_seg, kv_seg, block_q, block_k,
               pallas_path, dropout_rate=0.0, seed=None, q_off=0,
               k_off=0):
    if pallas_path:
        return _fwd_impl(q, k, v, scale, causal, dropout_rate, seed,
                         block_q, block_k, None, q_seg, kv_seg,
                         q_off=q_off, k_off=k_off)
    return _chunk_fwd_jnp(q, k, v, scale, causal, q_seg, kv_seg, block_k,
                          dropout_rate, seed, q_off, k_off)


def _chunk_bwd(q, k, v, o, lse, delta, do, scale, causal, q_seg, kv_seg,
               block_q, block_k, pallas_path, dropout_rate=0.0,
               seed=None, q_off=0, k_off=0):
    if pallas_path:
        # fp32 partials straight from the kernel: per-ring-step grads
        # accumulate across hops at full precision and round to the
        # input dtype ONCE at the end (ADVICE r4 — bf16-per-hop rounding
        # degraded with ring size)
        dq, dk, dv, _ = _bwd_impl(q, k, v, o, lse, do, scale, causal,
                                  dropout_rate, seed, block_q, block_k,
                                  None, q_seg, kv_seg,
                                  grad_dtype=jnp.float32,
                                  q_off=q_off, k_off=k_off)
        return dq, dk, dv
    return _chunk_bwd_jnp(q, k, v, do, lse, delta, scale, causal,
                          q_seg, kv_seg, block_k, dropout_rate, seed,
                          q_off, k_off)


# ------------------------------- ring core ----------------------------------

def _merge(o_acc, lse_acc, o_c, lse_c):
    """Merge a chunk's normalized (o, lse) into the running state —
    the cross-chip half of online softmax."""
    m = jnp.maximum(lse_acc, lse_c)
    w1 = jnp.exp(lse_acc - m)
    w2 = jnp.exp(lse_c - m)
    wsum = w1 + w2
    o = (o_acc * w1[..., None] + o_c.astype(jnp.float32) * w2[..., None]
         ) / wsum[..., None]
    return o, m + jnp.log(wsum)


def _int_zero(x):
    """float0 cotangent for integer (segment-id) primals — the one
    convention both ring variants share."""
    return (None if x is None
            else np.zeros(x.shape, dtype=jax.dtypes.float0))


def _rotate(axis_name, n, tree):
    perm = [(r, (r + 1) % n) for r in range(n)]
    return jax.tree_util.tree_map(
        lambda x: lax.ppermute(x, axis_name, perm), tree)


def _ring_fwd_impl(q, k, v, q_seg, kv_seg, seed, axis_name, causal,
                   scale, block_q, block_k, pallas_path, dropout_rate):
    b, h, s, d = q.shape
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    has_seg = q_seg is not None

    def step(carry, i):
        o_acc, lse_acc, k_c, v_c, kseg_c = carry
        src = (rank - i) % n
        kseg_arg = kseg_c if has_seg else None

        def attend(k_c, v_c, kseg_c, diag):
            # global offsets make the coordinate-hash dropout mask agree
            # across ring steps AND with single-chip attention over the
            # gathered sequence
            return _chunk_fwd(q, k_c, v_c, scale, causal and diag, q_seg,
                              kseg_c, block_q, block_k, pallas_path,
                              dropout_rate, seed, rank * s, src * s)
        if causal:
            # strictly-above-diagonal chunks (src > rank) are fully
            # masked: the skip branch runs NO score work — a causal
            # ring does ~half the FLOPs of a full ring
            def do_skip(_):
                return o_acc, lse_acc

            def do_diag(_):
                return _merge(o_acc, lse_acc,
                              *attend(k_c, v_c, kseg_arg, True))

            def do_full(_):
                return _merge(o_acc, lse_acc,
                              *attend(k_c, v_c, kseg_arg, False))

            idx = jnp.where(src > rank, 0, jnp.where(src == rank, 1, 2))
            o_acc, lse_acc = lax.switch(idx, (do_skip, do_diag, do_full),
                                        None)
        else:
            o_acc, lse_acc = _merge(o_acc, lse_acc,
                                    *attend(k_c, v_c, kseg_arg, False))
        k_c, v_c = _rotate(axis_name, n, (k_c, v_c))
        if has_seg:
            kseg_c = _rotate(axis_name, n, kseg_c)
        return (o_acc, lse_acc, k_c, v_c, kseg_c), None

    o0 = jnp.zeros((b, h, s, d), jnp.float32)
    lse0 = jnp.full((b, h, s), _NEG_INF, jnp.float32)
    kseg0 = kv_seg if has_seg else jnp.zeros((), jnp.int32)
    (o, lse, *_), _ = lax.scan(step, (o0, lse0, k, v, kseg0),
                               jnp.arange(n))
    return o.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11,
                                                    12))
def _ring(q, k, v, q_seg, kv_seg, seed, axis_name, causal, scale,
          block_q, block_k, pallas_path, dropout_rate):
    o, _ = _ring_fwd_impl(q, k, v, q_seg, kv_seg, seed, axis_name,
                          causal, scale, block_q, block_k, pallas_path,
                          dropout_rate)
    return o


def _ring_vjp_fwd(q, k, v, q_seg, kv_seg, seed, axis_name, causal,
                  scale, block_q, block_k, pallas_path, dropout_rate):
    o, lse = _ring_fwd_impl(q, k, v, q_seg, kv_seg, seed, axis_name,
                            causal, scale, block_q, block_k, pallas_path,
                            dropout_rate)
    # residuals are O(s_local · d) per device — blockwise recompute in
    # backward replaces AD-through-scan's O(n · s_local²) saved scores
    return o, (q, k, v, q_seg, kv_seg, seed, o, lse)


def _ring_vjp_bwd(axis_name, causal, scale, block_q, block_k, pallas_path,
                  dropout_rate, res, do):
    q, k, v, q_seg, kv_seg, seed, o, lse = res
    s = q.shape[2]
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    has_seg = q_seg is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    zero_kd = jnp.zeros(k.shape, jnp.float32)

    def step(carry, i):
        # dk/dv accumulators TRAVEL with their kv chunk: after n
        # rotations each has collected every rank's contribution and is
        # back home (≡ ring-attention backward; no gather of n shards)
        dq_acc, k_c, v_c, kseg_c, dk_c, dv_c = carry
        src = (rank - i) % n
        kseg_arg = kseg_c if has_seg else None

        def partials(k_c, v_c, kseg_c, diag):
            return _chunk_bwd(q, k_c, v_c, o, lse, delta, do, scale,
                              causal and diag, q_seg, kseg_c, block_q,
                              block_k, pallas_path, dropout_rate, seed,
                              rank * s, src * s)
        if causal:
            def do_skip(_):
                return (jnp.zeros(q.shape, jnp.float32), zero_kd, zero_kd)

            def do_diag(_):
                return partials(k_c, v_c, kseg_arg, True)

            def do_full(_):
                return partials(k_c, v_c, kseg_arg, False)

            idx = jnp.where(src > rank, 0, jnp.where(src == rank, 1, 2))
            dq_p, dk_p, dv_p = lax.switch(
                idx, (do_skip, do_diag, do_full), None)
        else:
            dq_p, dk_p, dv_p = partials(k_c, v_c, kseg_arg, False)
        dq_acc = dq_acc + dq_p
        dk_c = dk_c + dk_p
        dv_c = dv_c + dv_p
        k_c, v_c, dk_c, dv_c = _rotate(axis_name, n,
                                       (k_c, v_c, dk_c, dv_c))
        if has_seg:
            kseg_c = _rotate(axis_name, n, kseg_c)
        return (dq_acc, k_c, v_c, kseg_c, dk_c, dv_c), None

    kseg0 = kv_seg if has_seg else jnp.zeros((), jnp.int32)
    carry0 = (jnp.zeros(q.shape, jnp.float32), k, v, kseg0,
              zero_kd, zero_kd)
    (dq, _, _, _, dk, dv), _ = lax.scan(step, carry0, jnp.arange(n))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            _int_zero(q_seg), _int_zero(kv_seg), _int_zero(seed))


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


# ------------------- zigzag ring (load-balanced causal) ---------------------
#
# The contiguous causal ring SKIPS above-diagonal chunks, which halves
# total FLOPs but not the critical path: rank n-1 computes at every one
# of the n steps while rank 0 computes once.  Zigzag sharding fixes the
# balance: split the global sequence into 2n half-chunks and give
# device r the PAIR (r, 2n-1-r) — one early half ("a") and one late
# half ("b").  Visiting kv from src carries halves (c=src, d=2n-1-src);
# the causal block structure then decomposes per step into
#   (a,c): skip if src>r, diag if src==r, full if src<r
#   (a,d): always skip          (d ≥ n > a — kv strictly later)
#   (b,c): always full          (c ≤ n-1 < n ≤ b)
#   (b,d): skip if src<r, diag if src==r, full if src>r
# so EVERY device runs exactly two half-computes per step (three on its
# single diagonal step): per-step work is uniform across ranks and the
# causal ring's wall-clock halves vs the contiguous layout.

def _zigzag_perm(n, seq_len):
    """Global positions in zigzag order: device r's contiguous shard is
    global half-chunks (r, 2n-1-r)."""
    if seq_len % (2 * n):
        raise ValueError(
            f"zigzag needs seq_len % (2*n) == 0, got {seq_len} % {2 * n}")
    c = seq_len // (2 * n)
    return np.concatenate([
        np.r_[r * c:(r + 1) * c, (2 * n - 1 - r) * c:(2 * n - r) * c]
        for r in range(n)])


def zigzag_shard(x, n, axis=2):
    """Reorder a GLOBAL sequence axis so a contiguous n-way shard_map
    split gives device r the zigzag pair (r, 2n-1-r).  seq % 2n == 0."""
    return jnp.take(x, jnp.asarray(_zigzag_perm(n, x.shape[axis])),
                    axis=axis)


def zigzag_unshard(x, n, axis=2):
    """Inverse of zigzag_shard."""
    perm = _zigzag_perm(n, x.shape[axis])
    inv = np.empty_like(perm)
    inv[perm] = np.arange(perm.shape[0])
    return jnp.take(x, jnp.asarray(inv), axis=axis)


def _halves(x, half, axis=2):
    if x is None:
        return None, None
    lo = lax.slice_in_dim(x, 0, half, axis=axis)
    hi = lax.slice_in_dim(x, half, x.shape[axis], axis=axis)
    return lo, hi


def _ring_fwd_zigzag(q, k, v, q_seg, kv_seg, seed, axis_name, scale,
                     block_q, block_k, pallas_path, dropout_rate):
    b, h, s, d = q.shape
    half = s // 2
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    has_seg = q_seg is not None
    q_a, q_b = _halves(q, half)
    qs_a, qs_b = _halves(q_seg, half, axis=1)
    # GLOBAL half-chunk offsets (zigzag order: device r owns halves
    # (r, 2n-1-r)) feed the coordinate-hash dropout so the mask agrees
    # across steps and with the gathered-sequence single-chip mask
    qo_a = rank * half
    qo_b = (2 * n - 1 - rank) * half

    def attend(qh, qsh, kh, vh, ksh, causal_flag, q_off, k_off):
        return _chunk_fwd(qh, kh, vh, scale, causal_flag, qsh, ksh,
                          block_q, block_k, pallas_path, dropout_rate,
                          seed, q_off, k_off)

    def gated(idx, o_acc, l_acc, qh, qsh, kh, vh, ksh, q_off, k_off):
        """idx: 0 skip, 1 diag (causal), 2 full."""
        def do_skip(_):
            return o_acc, l_acc

        def do_diag(_):
            return _merge(o_acc, l_acc, *attend(qh, qsh, kh, vh, ksh,
                                                True, q_off, k_off))

        def do_full(_):
            return _merge(o_acc, l_acc, *attend(qh, qsh, kh, vh, ksh,
                                                False, q_off, k_off))

        return lax.switch(idx, (do_skip, do_diag, do_full), None)

    def step(carry, i):
        o_a, l_a, o_b, l_b, k_c, v_c, kseg_c = carry
        src = (rank - i) % n
        ko_lo = src * half
        ko_hi = (2 * n - 1 - src) * half
        k_lo, k_hi = _halves(k_c, half)
        v_lo, v_hi = _halves(v_c, half)
        ks_lo, ks_hi = _halves(kseg_c if has_seg else None, half, axis=1)
        # (b, c): unconditionally full
        o_b, l_b = _merge(o_b, l_b,
                          *attend(q_b, qs_b, k_lo, v_lo, ks_lo, False,
                                  qo_b, ko_lo))
        # (a, c)
        idx_ac = jnp.where(src > rank, 0, jnp.where(src == rank, 1, 2))
        o_a, l_a = gated(idx_ac, o_a, l_a, q_a, qs_a, k_lo, v_lo, ks_lo,
                         qo_a, ko_lo)
        # (b, d)
        idx_bd = jnp.where(src < rank, 0, jnp.where(src == rank, 1, 2))
        o_b, l_b = gated(idx_bd, o_b, l_b, q_b, qs_b, k_hi, v_hi, ks_hi,
                         qo_b, ko_hi)
        k_c, v_c = _rotate(axis_name, n, (k_c, v_c))
        if has_seg:
            kseg_c = _rotate(axis_name, n, kseg_c)
        return (o_a, l_a, o_b, l_b, k_c, v_c, kseg_c), None

    o0 = jnp.zeros((b, h, half, d), jnp.float32)
    l0 = jnp.full((b, h, half), _NEG_INF, jnp.float32)
    kseg0 = kv_seg if has_seg else jnp.zeros((), jnp.int32)
    (o_a, l_a, o_b, l_b, *_), _ = lax.scan(
        step, (o0, l0, o0, l0, k, v, kseg0), jnp.arange(n))
    o = jnp.concatenate([o_a, o_b], axis=2).astype(q.dtype)
    lse = jnp.concatenate([l_a, l_b], axis=2)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8, 9, 10, 11))
def _ring_zz(q, k, v, q_seg, kv_seg, seed, axis_name, scale, block_q,
             block_k, pallas_path, dropout_rate):
    o, _ = _ring_fwd_zigzag(q, k, v, q_seg, kv_seg, seed, axis_name,
                            scale, block_q, block_k, pallas_path,
                            dropout_rate)
    return o


def _ring_zz_vjp_fwd(q, k, v, q_seg, kv_seg, seed, axis_name, scale,
                     block_q, block_k, pallas_path, dropout_rate):
    o, lse = _ring_fwd_zigzag(q, k, v, q_seg, kv_seg, seed, axis_name,
                              scale, block_q, block_k, pallas_path,
                              dropout_rate)
    return o, (q, k, v, q_seg, kv_seg, seed, o, lse)


def _ring_zz_vjp_bwd(axis_name, scale, block_q, block_k, pallas_path,
                     dropout_rate, res, do):
    q, k, v, q_seg, kv_seg, seed, o, lse = res
    half = q.shape[2] // 2
    n = lax.axis_size(axis_name)
    rank = lax.axis_index(axis_name)
    has_seg = q_seg is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1)
    q_a, q_b = _halves(q, half)
    o_a, o_b = _halves(o, half)
    do_a, do_b = _halves(do, half)
    qs_a, qs_b = _halves(q_seg, half, axis=1)
    lse_a, lse_b = _halves(lse, half, axis=2)
    d_a, d_b = _halves(delta, half, axis=2)
    qo_a = rank * half
    qo_b = (2 * n - 1 - rank) * half
    # q and kv shards share (b, h, half, d) — one zero serves the skip
    # branch's dq, dk, and dv partials
    zero_half = jnp.zeros(q_a.shape, jnp.float32)

    def step(carry, i):
        (dq_a, dq_b, k_c, v_c, kseg_c,
         dk_lo, dk_hi, dv_lo, dv_hi) = carry
        src = (rank - i) % n
        ko_lo = src * half
        ko_hi = (2 * n - 1 - src) * half
        k_lo, k_hi = _halves(k_c, half)
        v_lo, v_hi = _halves(v_c, half)
        ks_lo, ks_hi = _halves(kseg_c if has_seg else None, half, axis=1)

        def partials(qh, qsh, oh, lh, dh, doh, kh, vh, ksh, causal_flag,
                     q_off, k_off):
            return _chunk_bwd(qh, kh, vh, oh, lh, dh, doh, scale,
                              causal_flag, qsh, ksh, block_q, block_k,
                              pallas_path, dropout_rate, seed, q_off,
                              k_off)

        def gated(idx, *args):
            def do_skip(_):
                return zero_half, zero_half, zero_half

            def do_diag(_):
                return partials(*args[:-2], True, *args[-2:])

            def do_full(_):
                return partials(*args[:-2], False, *args[-2:])

            return lax.switch(idx, (do_skip, do_diag, do_full), None)

        # (b, c): unconditionally full
        p_q, p_k, p_v = partials(q_b, qs_b, o_b, lse_b, d_b, do_b,
                                 k_lo, v_lo, ks_lo, False, qo_b, ko_lo)
        dq_b = dq_b + p_q
        dk_lo = dk_lo + p_k
        dv_lo = dv_lo + p_v
        # (a, c)
        idx_ac = jnp.where(src > rank, 0, jnp.where(src == rank, 1, 2))
        p_q, p_k, p_v = gated(idx_ac, q_a, qs_a, o_a, lse_a, d_a, do_a,
                              k_lo, v_lo, ks_lo, qo_a, ko_lo)
        dq_a = dq_a + p_q
        dk_lo = dk_lo + p_k
        dv_lo = dv_lo + p_v
        # (b, d)
        idx_bd = jnp.where(src < rank, 0, jnp.where(src == rank, 1, 2))
        p_q, p_k, p_v = gated(idx_bd, q_b, qs_b, o_b, lse_b, d_b, do_b,
                              k_hi, v_hi, ks_hi, qo_b, ko_hi)
        dq_b = dq_b + p_q
        dk_hi = dk_hi + p_k
        dv_hi = dv_hi + p_v
        (k_c, v_c, dk_lo, dk_hi, dv_lo, dv_hi) = _rotate(
            axis_name, n, (k_c, v_c, dk_lo, dk_hi, dv_lo, dv_hi))
        if has_seg:
            kseg_c = _rotate(axis_name, n, kseg_c)
        return (dq_a, dq_b, k_c, v_c, kseg_c,
                dk_lo, dk_hi, dv_lo, dv_hi), None

    kseg0 = kv_seg if has_seg else jnp.zeros((), jnp.int32)
    carry0 = (zero_half, zero_half, k, v, kseg0,
              zero_half, zero_half, zero_half, zero_half)
    (dq_a, dq_b, _, _, _, dk_lo, dk_hi, dv_lo, dv_hi), _ = lax.scan(
        step, carry0, jnp.arange(n))
    dq = jnp.concatenate([dq_a, dq_b], axis=2)
    dk = jnp.concatenate([dk_lo, dk_hi], axis=2)
    dv = jnp.concatenate([dv_lo, dv_hi], axis=2)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype),
            _int_zero(q_seg), _int_zero(kv_seg), _int_zero(seed))


_ring_zz.defvjp(_ring_zz_vjp_fwd, _ring_zz_vjp_bwd)


# -------------------------------- public API --------------------------------

def ring_attention(q, k, v, axis_name: str, *, causal: bool = False,
                   softmax_scale: Optional[float] = None,
                   segment_ids=None, q_segment_ids=None,
                   kv_segment_ids=None,
                   layout: str = "contiguous",
                   block_q: Optional[int] = None,
                   block_k: Optional[int] = None,
                   dropout_rate: float = 0.0,
                   dropout_key=None,
                   use_pallas_override: Optional[bool] = None):
    """Blockwise ring attention (see module docstring for the design).

    q, k, v: (b, h, s_local, d) — the LOCAL sequence shard; the global
    sequence is the concatenation over the axis in rank order.  Segment
    ids are (b, s_local) int per shard, global semantics (tokens attend
    only within equal ids, across shards).  Returns the local output
    shard (b, h, s_local, d).

    layout="zigzag" (causal only): device r holds the global half-chunk
    PAIR (r, 2n-1-r) — shard with `zigzag_shard` (and undo with
    `zigzag_unshard`).  Every device then runs exactly two half-chunk
    computes per ring step, so the causal ring's wall-clock HALVES vs
    the contiguous layout, whose last rank computes at every step (see
    the zigzag section above).  Non-causal attention has no positional
    structure to balance — use the default layout.

    dropout_rate / dropout_key: in-kernel attention dropout.  The
    coordinate-hash keep mask uses each chunk's GLOBAL (q, k) offsets,
    so every ring step (and the backward) sees one consistent global
    mask — identical bits to single-chip flash attention over the
    gathered sequence with the same key.  Pass the SAME key on every
    device (it is replicated state, like the params).
    """
    if layout not in ("contiguous", "zigzag"):
        raise ValueError(f"unknown layout {layout!r}")
    d = q.shape[-1]
    scale = (softmax_scale if softmax_scale is not None
             else 1.0 / math.sqrt(d))
    if segment_ids is not None:
        if q_segment_ids is not None or kv_segment_ids is not None:
            raise ValueError(
                "pass either segment_ids or q_/kv_segment_ids, not both")
        q_segment_ids = kv_segment_ids = segment_ids
    if (q_segment_ids is None) != (kv_segment_ids is None):
        raise ValueError("q_segment_ids and kv_segment_ids go together")
    b, s = q.shape[0], q.shape[2]
    seed = None
    if dropout_rate > 0.0:
        if dropout_key is None:
            raise ValueError("dropout_rate > 0 needs a dropout_key")
        seed = jax.random.randint(dropout_key, (1, 1), -2 ** 31,
                                  2 ** 31 - 1, dtype=jnp.int32)
    if q_segment_ids is not None:
        q_segment_ids = jnp.asarray(q_segment_ids, jnp.int32)
        kv_segment_ids = jnp.asarray(kv_segment_ids, jnp.int32)
        if (q_segment_ids.shape != (b, s)
                or kv_segment_ids.shape != (b, s)):
            raise ValueError(
                f"segment id shapes {q_segment_ids.shape}/"
                f"{kv_segment_ids.shape} != ({b}, {s})")
    if layout == "zigzag":
        if not causal:
            raise ValueError(
                "layout='zigzag' is causal-only: non-causal attention "
                "has no positional imbalance to fix — use the default "
                "contiguous layout (results are identical)")
        if s % 2:
            raise ValueError("zigzag needs an even local sequence")
        pallas_path = bool(use_pallas(use_pallas_override)
                           and _pick_block(s // 2))
        return _ring_zz(q, k, v, q_segment_ids, kv_segment_ids, seed,
                        axis_name, scale, block_q, block_k, pallas_path,
                        float(dropout_rate))
    pallas_path = bool(use_pallas(use_pallas_override)
                       and _pick_block(s))
    return _ring(q, k, v, q_segment_ids, kv_segment_ids, seed, axis_name,
                 causal, scale, block_q, block_k, pallas_path,
                 float(dropout_rate))


def ulysses_attention(q, k, v, axis_name: str, *, causal: bool = False,
                      softmax_scale: Optional[float] = None,
                      segment_ids=None,
                      use_flash: bool = True,
                      use_pallas_override: Optional[bool] = None):
    """All-to-all (DeepSpeed-Ulysses-style) sequence parallelism.

    Inputs are seq-sharded (b, h, s_local, d) with h % axis_size == 0;
    internally heads are scattered so each device sees the FULL sequence
    for h/axis heads, runs (flash) attention, and scatters back.
    segment_ids: (b, s_local) int per shard, global semantics — gathered
    to the full sequence with the heads (packed-varlen works here too).
    """
    n = lax.axis_size(axis_name)
    b, h, s_local, d = q.shape
    assert h % n == 0, "ulysses needs heads divisible by the axis size"

    def seq_to_heads(x):
        # (b, h, s_local, d) → (b, h/n, s_global, d): scatter heads,
        # gather sequence — one tiled all_to_all
        return lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)

    def heads_to_seq(x):
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = seq_to_heads(q), seq_to_heads(k), seq_to_heads(v)
    seg_g = None
    if segment_ids is not None:
        # every device needs the FULL (b, s_global) ids — one gather
        seg_g = lax.all_gather(jnp.asarray(segment_ids, jnp.int32),
                               axis_name, axis=1, tiled=True)
    if use_flash:
        from apex_tpu.ops.flash_attention import flash_attention
        og = flash_attention(qg, kg, vg, causal=causal,
                             softmax_scale=softmax_scale,
                             segment_ids=seg_g,
                             use_pallas_override=use_pallas_override)
    else:
        from apex_tpu.ops.flash_attention import attention_reference
        og = attention_reference(qg, kg, vg, causal=causal,
                                 softmax_scale=softmax_scale,
                                 q_segment_ids=seg_g, kv_segment_ids=seg_g)
    return heads_to_seq(og)
