"""Fused gradient clipping.

≡ apex.contrib.clip_grad.clip_grad_norm_ (apex/contrib/clip_grad/clip_grad.py:16):
multi-tensor L2-norm + scale.  On TPU the norm is one fused XLA
reduction over the flat buffer and the scale fuses into whatever
consumes the grads next.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from apex_tpu.ops import optimizer_kernels as K
from apex_tpu.optimizers import flat as F


def clip_grad_norm(grads, max_norm: float, norm_type: float = 2.0):
    """Returns (clipped_grads, total_norm).

    Matches torch semantics (clip only when total_norm > max_norm);
    inf-norm supported like the reference (clip_grad.py:49-57).
    """
    leaves = jax.tree_util.tree_leaves(grads)
    if norm_type == 2.0:
        total = K.l2norm_flat(F.flatten(grads, jnp.float32))
    elif norm_type == float("inf"):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(l.astype(jnp.float32))) for l in leaves]))
    else:
        total = jnp.power(sum(
            jnp.sum(jnp.power(jnp.abs(l.astype(jnp.float32)), norm_type))
            for l in leaves), 1.0 / norm_type)
    scale = jnp.where(total > max_norm, max_norm / (total + 1e-6), 1.0)
    clipped = jax.tree_util.tree_map(
        lambda g: (g.astype(jnp.float32) * scale).astype(g.dtype), grads)
    return clipped, total


clip_grad_norm_ = clip_grad_norm
