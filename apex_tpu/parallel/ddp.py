"""Data-parallel gradient synchronization + train-step builder.

≡ apex.parallel.DistributedDataParallel (apex/parallel/distributed.py:131-643)
and Reducer (distributed.py:91-128).  The reference registers per-param
autograd hooks, builds flat buckets on the fly, and overlaps NCCL
allreduce with backward on dedicated streams.  Under XLA the same
overlap is the compiler's job: the train step is ONE jitted SPMD program
in which gradient `psum`s are scheduled concurrently with remaining
backward compute (async collectives over ICI).  What remains of DDP is:

  * `sync_gradients`  — pmean/psum over the dp axis (the semantic core)
  * `sync_gradients_bucketed` — explicit flat-bucket parity mode
    (≡ allreduce_bucket + multi_tensor_scale unflatten,
    distributed.py:429-479), useful for collective-count parity tests
  * `Reducer` — manual allreduce on demand (distributed.py:91-128)
  * `make_train_step` — the user-facing builder that fuses forward,
    backward, grad sync, loss scaling, and the fused optimizer into one
    donated jitted step (≡ the whole hot loop of
    examples/imagenet/main_amp.py:330-402)
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from apex_tpu import amp as amp_lib
from apex_tpu.optimizers import flat as F
from apex_tpu.parallel.mesh import DP_AXIS


def _axis_size(axis_name) -> int:
    """Static size of one axis or of a tuple of axes (their product).

    `lax.axis_size` takes a single name; MoE steps sync over the
    combined ("dp", "ep") data axes (mesh.get_data_parallel_axis_names)
    and need the product — the collective primitives themselves take
    the tuple directly."""
    if isinstance(axis_name, (tuple, list)):
        n = 1
        for a in axis_name:
            n *= int(jax.lax.axis_size(a))
        return n
    return int(jax.lax.axis_size(axis_name))


def sync_gradients(grads, axis_name=DP_AXIS, average: bool = True):
    """All-reduce a grad pytree over the data-parallel axis (or axis
    TUPLE — an expert-parallel step averages over ("dp", "ep"): the
    MoE all-to-all's AD transpose already summed each expert's partial
    grads across ep, so one uniform pmean over the combined axes is
    exact for expert and non-expert params alike, docs/moe.md).

    ≡ DDP's bucketed allreduce with gradient_average=True
    (apex/parallel/distributed.py:449-458).  Inside pjit/shard_map only.

    Call under `shard_map(..., check_vma=False)` (the make_train_step
    convention).  Under JAX's default varying-manual-axes tracking,
    differentiating w.r.t. replicated params already inserts a psum
    (the transpose of pvary), so grads arrive pre-summed and a further
    pmean would silently keep the SUM — either disable vma tracking or
    don't re-sync auto-summed grads.
    """
    op = jax.lax.pmean if average else jax.lax.psum
    return jax.tree_util.tree_map(lambda g: op(g, axis_name), grads)


def sync_gradients_bucketed(grads, axis_name: str = DP_AXIS,
                            average: bool = True, num_buckets: int = 1):
    """Flat-bucket allreduce parity mode ≡ allreduce_bucket
    (distributed.py:429-479): flatten → allreduce buckets → unflatten.
    On TPU this changes collective granularity only (XLA fuses either
    way); kept for parity testing against the reference's bucket math.
    """
    spec = F.make_spec(grads)
    flat = F.flatten(grads, jnp.float32)
    n = flat.shape[0]
    per = -(-n // num_buckets)
    pieces = []
    for b in range(num_buckets):
        piece = jax.lax.dynamic_slice(
            flat, (b * per,), (min(per, max(0, n - b * per)) or 1,)
        ) if b * per < n else None
        if piece is not None:
            red = jax.lax.pmean(piece, axis_name) if average else \
                jax.lax.psum(piece, axis_name)
            pieces.append(red)
    flat = jnp.concatenate(pieces)[:n]
    return F.unflatten(flat, spec)


class Reducer:
    """Manual allreduce helper ≡ apex.parallel.Reducer
    (distributed.py:91-128): call .reduce(tree) inside the SPMD region
    whenever you want averaging."""

    def __init__(self, axis_name: str = DP_AXIS):
        self.axis_name = axis_name

    def reduce(self, tree):
        return sync_gradients(tree, self.axis_name, average=True)


class DistributedDataParallel:
    """Facade ≡ apex.parallel.DistributedDataParallel (distributed.py:131).

    Wraps an apply function; `.apply` runs the module, `.sync` averages
    grads over dp.  The reference's delay_allreduce / bucket knobs map to
    `bucketed`/`num_buckets` (collective granularity) — overlap itself
    is XLA-scheduled.
    """

    def __init__(self, apply_fn: Callable, axis_name: str = DP_AXIS,
                 gradient_average: bool = True, bucketed: bool = False,
                 num_buckets: int = 1):
        self.apply_fn = apply_fn
        self.axis_name = axis_name
        self.gradient_average = gradient_average
        self.bucketed = bucketed
        self.num_buckets = num_buckets

    def apply(self, params, *args, **kwargs):
        return self.apply_fn(params, *args, **kwargs)

    __call__ = apply

    def sync(self, grads):
        if self.bucketed:
            return sync_gradients_bucketed(
                grads, self.axis_name, self.gradient_average,
                self.num_buckets)
        return sync_gradients(grads, self.axis_name,
                              self.gradient_average)


def make_train_step(loss_fn: Callable, optimizer, mesh, *,
                    amp_state: Optional[amp_lib.AmpState] = None,
                    axis_name: str = DP_AXIS, donate: bool = True,
                    batch_spec=None, has_aux: bool = False,
                    with_state: bool = False,
                    num_microbatches: int = 1,
                    main_grad_dtype=None,
                    metrics=None,
                    trace=None):
    """Build the fused data-parallel train step.

    `loss_fn(params, batch) -> loss` (or `(loss, aux)` with has_aux;
    with with_state: `loss_fn(params, model_state, batch) ->
    (loss, new_model_state)`, e.g. BN batch stats) is differentiated
    per-shard; grads are pmean'd over `axis_name`; the fused optimizer
    applies the update with loss-scaling/overflow-skip fused in.
    Returns `step(opt_state, amp_scaler_state[, model_state], batch) ->
    (opt_state, scaler_state[, model_state], loss[, aux])`, jitted over
    `mesh` with batch sharded on dp.

    num_microbatches splits each shard's batch into that many
    microbatches along the leading axis and accumulates their grads
    inside the one jitted program (grad sync still happens ONCE, after
    accumulation — no_sync semantics).  main_grad_dtype picks the
    accumulator dtype: None accumulates in each param's own dtype (bf16
    params → bf16 adds), float32 is the Apex main-grad guarantee — the
    microbatch cotangents land in a persistent fp32 buffer regardless
    of param/compute dtype (≡ wgrad_gemm_accum_fp32 into `.main_grad`,
    reference transformer/tensor_parallel/layers.py:415-428).  The fp32
    grads flow to the grad pmean and the fused optimizer as-is (the
    flat kernels take any float grad dtype).

    axis_name may be a TUPLE of mesh axes — an expert-parallel MoE
    step syncs over ("dp", "ep") (mesh.get_data_parallel_axis_names):
    the batch shards over the combined axes, grads pmean over both,
    and a ZeRO optimizer built with num_shards = dp*ep and the same
    tuple shards its flat state over the product axis.  Every
    collective primitive involved takes the tuple natively.

    ZERO-2: `optimizer` may be a sharded optimizer
    (`DistributedFusedAdam` / `DistributedFusedLAMB` — detected via
    their `state_partition_specs`/`full_params` methods).  The step
    then skips the full grad allreduce entirely — the optimizer's
    per-bucket `psum_scatter` IS the grad sync (and with
    `n_buckets > 1` each bucket's collective can overlap the remaining
    backward) — reconstructs full params from the rank shard via
    `full_params`, and the opt-state in/out specs shard the flat
    buffers over `axis_name`.  Initialize the state INSIDE shard_map
    (see docs/optimizers.md):

        sspec = opt.state_partition_specs()
        state = jax.jit(shard_map(opt.init, mesh=mesh, in_specs=(P(),),
                                  out_specs=sspec, check_vma=False))(params)

    With amp, the overflow flag is the psum-OR of each rank's local
    check (grads are never globally materialized); the metrics
    grad-norm is the local pre-reduction norm, while param/update
    norms are exact global values (scalar psum over the rank shards).

    metrics enables on-device telemetry (apex_tpu.monitor): pass True
    or a `monitor.MetricsConfig`.  The returned step then takes a
    trailing `monitor.MetricsState` argument and returns the updated
    one as its LAST output — loss, unscaled global grad norm, master
    param/update norms, loss scale, cumulative overflow/skip counts and
    tokens are folded in INSIDE the jitted program (a few fused scalar
    reductions, no host syncs; device_get only when the host logs).
    When omitted (default) the built step is the identical program as
    before — signature, outputs, and numerics unchanged.

    trace enables the numerics flight recorder (apex_tpu.monitor.trace):
    pass True or a `trace.TraceConfig`.  With `taps` (the default
    config), the step differentiates w.r.t. (params, tap probes) so the
    per-layer tap stats ride out of AD functionally — the step returns
    a `trace.TapState` as an extra trailing output (forward + gradient
    plane stats per tap point plus on-device first-nonfinite
    provenance; `step.tap_names()` gives the row labels after the
    first call).  Param grads are untouched (the tap op is an identity)
    and a loss_fn with no `tap()` calls yields an empty TapState.
    With `rank_timing`, the step takes ONE more trailing input — the
    (n_ranks, timing_dim) per-rank host-measured duration matrix,
    sharded over `axis_name` — and returns its all_gather (replicated)
    as the final output, so every rank's flight recorder sees every
    rank's step/allreduce durations via a single tiny collective (feed
    `trace.StragglerDetector`).  Taps currently require
    num_microbatches == 1 (per-microbatch stat merging is not defined
    yet); rank timing composes with everything.  As with metrics,
    omitting trace (the default) rebuilds the byte-identical pre-trace
    program.

    Argument/output order with everything enabled:
        step(opt_state, scaler_state[, model_state], batch,
             metrics_state, local_timing)
          -> (opt_state, scaler_state[, model_state], loss[, aux],
              metrics, tap_state, rank_timings)

    The returned step also carries the compile & HBM observatory
    handles (apex_tpu.monitor.compile): `step.lower(*args)` lowers
    through the same argument mapping as a call (so
    `monitor.analyze_step(step, args)` AOT-audits the EXACT program
    that will run — HBM budget, donation check, flops cross-check,
    without executing), `step.jitted` exposes the underlying jit for
    the RecompileSentry's cache poll, and `step.donate_argnums` /
    `step.arg_names` label the audit.  None of these touch the
    compiled program — numerics are bitwise identical whether or not
    the step was analyzed (tests/test_compile_report.py).

    ≡ the reference hot loop: DDP.forward → amp.scale_loss → backward
    hooks/allreduce → FusedAdam.step (SURVEY §3.2-3.3), collapsed into
    one compiled program.
    """
    from jax import shard_map

    policy = amp_state.policy if amp_state is not None else None
    dynamic = amp_state.dynamic if amp_state is not None else False
    sharded_opt = (hasattr(optimizer, "state_partition_specs")
                   and hasattr(optimizer, "full_params"))
    # ZeRO optimizers that support it skip the step-tail param gather
    # entirely: the NEXT step's full_params() reconstructs them, letting
    # XLA overlap the all-gather with the start of forward compute
    import inspect
    skip_gather = (sharded_opt and "gather_params"
                   in inspect.signature(optimizer.step).parameters)
    if num_microbatches < 1:
        raise ValueError(f"num_microbatches must be >= 1, got "
                         f"{num_microbatches}")
    metrics_cfg = None
    if metrics is not None and metrics is not False:
        from apex_tpu.monitor import metrics as _mon
        if isinstance(metrics, _mon.MetricsState):
            raise TypeError(
                "make_train_step(metrics=...) takes True or a "
                "MetricsConfig at build time; pass the MetricsState to "
                "the built step as its trailing argument")
        metrics_cfg = _mon.MetricsConfig() if metrics is True else metrics
    trace_cfg = None
    if trace is not None and trace is not False:
        from apex_tpu.monitor.trace import taps as _trc
        trace_cfg = _trc.TraceConfig() if trace is True else trace
        if trace_cfg.taps and num_microbatches != 1:
            raise ValueError(
                "trace taps require num_microbatches == 1 (merging "
                "per-microbatch tap stats across the accumulation scan "
                "is not defined); use TraceConfig(taps=False, "
                "rank_timing=True) for the timing plane alone")
    # host-side label side channel: the tap names are known once the
    # tapped loss has been traced (first call); step.tap_names() reads
    # them for the flight-recorder report
    tap_holder = {"names": None}

    def local_step(opt_state, scaler_state, model_state, batch,
                   *extras):
        ex = list(extras)
        metrics_state = ex.pop(0) if metrics_cfg is not None else None
        local_timing = ex.pop(0) if (
            trace_cfg is not None and trace_cfg.rank_timing) else None
        raw_batch = batch
        if sharded_opt:
            # ZeRO-2: all-gather full params from this rank's shard;
            # XLA schedules the gather under the start of forward
            params = optimizer.full_params(opt_state)
        else:
            params = F.unflatten(opt_state.params, optimizer.spec)
        if policy is not None:
            params = policy.cast_to_param(params)
            if policy.compute_dtype != jnp.float32:
                # O1/O2 compute cast: params + floating batch inputs run
                # in the compute dtype (≡ the patched-op casts of amp O1,
                # apex/amp/lists/torch_overrides.py); norm/loss-class ops
                # re-promote to fp32 internally (FP32_CLASS_OPS contract)
                params = policy.cast_to_compute(params)
                batch = policy.cast_to_compute(batch)

        def scaled_loss_fn(p, mstate, b):
            if with_state:
                loss, new_mstate = loss_fn(p, mstate, b)
                aux = new_mstate
            else:
                out = loss_fn(p, b)
                loss = out[0] if has_aux else out
                aux = out[1] if has_aux else None
            scaled = loss * scaler_state.scale if scaler_state is not None \
                else loss
            return scaled, (aux, loss)

        probe_grads = None
        if num_microbatches == 1:
            if trace_cfg is not None and trace_cfg.taps:
                # numerics taps: differentiate w.r.t. (params, probes) —
                # the probes cotangent IS the per-tap [fwd, grad] stats
                # (ops._common.grad_tap); param grads are untouched
                # because the tap op is an identity on its input
                from apex_tpu.monitor.trace import taps as _trc
                from apex_tpu.ops import _common as _tapc
                probes = _trc.make_probes(trace_cfg.max_taps)

                def tapped_loss(p_probes, mstate, b):
                    p, pr = p_probes
                    ctx = _tapc.TapContext(probes=pr)
                    with _tapc.tap_context(ctx):
                        scaled, payload = scaled_loss_fn(p, mstate, b)
                    tap_holder["names"] = tuple(ctx.names)
                    return scaled, payload

                (grads, probe_grads), (aux, loss) = jax.grad(
                    tapped_loss, has_aux=True)(
                        (params, probes), model_state, batch)
            else:
                # nothing to accumulate: keep the single-shot path (and
                # the bare aux return shape); main_grad_dtype only picks
                # the dtype the grads leave backward in
                grads, (aux, loss) = jax.grad(
                    scaled_loss_fn, has_aux=True)(
                        params, model_state, batch)
            if main_grad_dtype is not None:
                grads = jax.tree_util.tree_map(
                    lambda g: g.astype(main_grad_dtype), grads)
        else:
            m = num_microbatches

            def split(x):
                if x.shape[0] % m:
                    raise ValueError(
                        f"local batch dim {x.shape[0]} not divisible by "
                        f"num_microbatches={m}")
                return x.reshape((m, x.shape[0] // m) + x.shape[1:])

            mbs = jax.tree_util.tree_map(split, batch)
            acc0 = jax.tree_util.tree_map(
                lambda p: jnp.zeros(
                    p.shape, main_grad_dtype or p.dtype), params)

            stack_aux = has_aux and not with_state

            def body(carry, mb):
                g_acc, mstate_c, loss_acc = carry
                g, (aux_mb, loss_mb) = jax.grad(
                    scaled_loss_fn, has_aux=True)(params, mstate_c, mb)
                g_acc = jax.tree_util.tree_map(
                    lambda a, gg: a + gg.astype(a.dtype), g_acc, g)
                mstate_n = aux_mb if with_state else mstate_c
                # stack per-microbatch auxes only when the caller gets
                # them: a stacked copy of large model state as unused
                # scan ys would cost m x its memory
                return (g_acc, mstate_n,
                        loss_acc + loss_mb.astype(jnp.float32)), (
                            aux_mb if stack_aux else None)

            (g_acc, mstate_f, loss_sum), auxs = jax.lax.scan(
                body, (acc0, model_state, jnp.zeros((), jnp.float32)),
                mbs)
            grads = jax.tree_util.tree_map(lambda g: g / m, g_acc)
            loss = loss_sum / m
            # with_state: the threaded final state; has_aux: the stacked
            # per-microbatch auxes (leading dim m)
            aux = mstate_f if with_state else (
                auxs if has_aux else None)
        if not sharded_opt:
            grads = sync_gradients(grads, axis_name, average=True)
        # else: the sharded optimizer's per-bucket psum_scatter IS the
        # grad sync — a prior allreduce would double the collective
        # traffic and defeat the backward overlap

        if scaler_state is not None:
            inv = 1.0 / scaler_state.scale
            found_inf = amp_lib.scaler.check_finite(grads)
            if sharded_opt:
                # local (pre-reduction) check: psum-OR so every rank
                # takes the same skip/scale decision
                found_inf = jax.lax.psum(
                    found_inf.astype(jnp.float32), axis_name) > 0
            new_scaler = amp_lib.scaler.update(scaler_state, found_inf,
                                               dynamic=dynamic)
        else:
            inv = 1.0
            found_inf = jnp.zeros((), bool)
            new_scaler = None

        tap_state = None
        if probe_grads is not None:
            from apex_tpu.monitor.trace import taps as _trc
            # the gradient plane's magnitudes are unscaled here so the
            # report reads in loss units; the nonfinite count stays as
            # observed on the raw scaled grads (what found_inf saw)
            tap_state = _trc.finalize(
                probe_grads, len(tap_holder["names"]), inv_scale=inv)

        step_kw = {"gather_params": False} if skip_gather else {}
        new_params, new_opt_state = optimizer.step(
            opt_state, grads, inv_scale=inv, found_inf=found_inf,
            **step_kw)
        outs = (new_opt_state, new_scaler)
        if with_state:
            outs = outs + (aux,)
        outs = outs + (loss,)
        if has_aux and not with_state:
            outs = outs + (aux,)
        if metrics_cfg is not None:
            from apex_tpu.monitor import metrics as _mon
            if metrics_cfg.tokens_per_step is not None:
                tokens = metrics_cfg.tokens_per_step
            else:
                tokens = (_mon.infer_tokens_per_step(raw_batch)
                          * _axis_size(axis_name))
            # flat optimizers carry the master buffer as state.params;
            # norms read it directly (no per-leaf tree walk).  ZeRO
            # states carry rank SHARDS (params_shard): global norms are
            # sqrt(psum(shard sumsq)) — two scalar psums, noise next to
            # the step's collectives
            p_flat = getattr(opt_state, "params", None)
            p_new = getattr(new_opt_state, "params", None)
            pn_val = un_val = None
            if not metrics_cfg.param_norms:
                p_flat = p_new = None
            elif sharded_opt:
                p_sh = opt_state.params_shard.astype(jnp.float32)
                p_sh_new = new_opt_state.params_shard.astype(jnp.float32)
                sums = jax.lax.psum(jnp.stack([
                    jnp.sum(jnp.square(p_sh)),
                    jnp.sum(jnp.square(p_sh_new - p_sh))]), axis_name)
                pn_val, un_val = jnp.sqrt(sums[0]), jnp.sqrt(sums[1])
                p_flat = p_new = None
            # the step's `loss` output is each shard's LOCAL loss (the
            # P() out-spec takes one shard's value under check_vma=False)
            # — telemetry wants the global dp-mean; a scalar pmean costs
            # nothing next to the grad sync and touches no other output
            global_loss = jax.lax.pmean(loss, axis_name)
            outs = outs + (_mon.update_metrics(
                metrics_state, loss=global_loss, grads=grads,
                inv_scale=inv,
                params_flat=p_flat, new_params_flat=p_new,
                param_norm=pn_val, update_norm=un_val,
                loss_scale=scaler_state.scale if scaler_state is not None
                else 1.0,
                found_inf=found_inf, tokens=tokens),)
        if trace_cfg is not None and trace_cfg.taps:
            outs = outs + (tap_state,)
        if trace_cfg is not None and trace_cfg.rank_timing:
            from apex_tpu.monitor.trace import taps as _trc
            # ONE tiny all_gather per step — the whole cross-rank
            # timing plane; the local (1, k) shard flattens to this
            # rank's vector first.  Trace-time width check: a
            # mismatched matrix would otherwise surface as an opaque
            # downstream shape error
            if local_timing.shape[-1] != trace_cfg.timing_dim:
                raise ValueError(
                    f"local_timing has {local_timing.shape[-1]} "
                    f"columns, TraceConfig.timing_dim is "
                    f"{trace_cfg.timing_dim}; pass a (n_ranks, "
                    f"{trace_cfg.timing_dim}) per-rank duration matrix "
                    "or set timing_dim to match")
            outs = outs + (_trc.gather_rank_timings(
                local_timing.reshape(-1), axis_name),)
        return outs

    # batch sharded over dp; params/opt state replicated — unless the
    # optimizer is a ZeRO variant, whose flat state buffers shard over
    # the dp axis (state_partition_specs)
    if batch_spec is None:
        batch_spec = P(axis_name)

    opt_spec = (optimizer.state_partition_specs() if sharded_opt
                else P())
    out_specs = (opt_spec, P())
    if with_state:
        out_specs += (P(),)
    out_specs += (P(),)  # loss
    if has_aux and not with_state:
        out_specs += (P(),)

    in_specs = (opt_spec, P(), P(), batch_spec)
    if metrics_cfg is not None:
        in_specs += (P(),)       # metrics pytree replicated
        out_specs += (P(),)
    if trace_cfg is not None and trace_cfg.taps:
        out_specs += (P(),)      # TapState (shard-local stats, see doc)
    if trace_cfg is not None and trace_cfg.rank_timing:
        in_specs += (P(axis_name),)  # (n_ranks, k) local timing rows
        out_specs += (P(),)          # gathered matrix, replicated

    smapped = shard_map(
        local_step, mesh=mesh,
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False)

    donate_args = (0,) if donate else ()
    jitted = jax.jit(smapped, donate_argnums=donate_args)

    # compile & HBM observatory labels (ISSUE 5): the budget classifier
    # of monitor.compile.analyze_step keys on these names
    names = ["opt_state", "scaler_state"]
    if with_state:
        names.append("model_state")
    names.append("batch")
    if metrics_cfg is not None:
        names.append("metrics_state")
    if trace_cfg is not None and trace_cfg.rank_timing:
        names.append("local_timing")

    if with_state and metrics_cfg is None and trace_cfg is None:
        # the exact pre-metrics/pre-trace callable.  jax's jit wrapper
        # takes attributes, so the observatory handles ride along; if a
        # jaxlib ever refuses, the audit still works via analyze_step's
        # explicit donated=/arg_names= arguments.
        try:
            jitted.donate_argnums = donate_args
            jitted.arg_names = tuple(names)
            jitted.mesh_axis_names = tuple(
                str(a) for a in mesh.axis_names)
            jitted.mesh_axis_sizes = tuple(
                int(s) for s in mesh.devices.shape)
            jitted.state_partition_specs = opt_spec
        except AttributeError:  # pragma: no cover
            pass
        return jitted

    if with_state:
        def step(opt_state, scaler_state, model_state, batch, *extra):
            return jitted(opt_state, scaler_state, model_state, batch,
                          *extra)

        def lower(opt_state, scaler_state, model_state, batch, *extra):
            return jitted.lower(opt_state, scaler_state, model_state,
                                batch, *extra)
    else:
        def step(opt_state, scaler_state, batch, *extra):
            return jitted(opt_state, scaler_state, None, batch, *extra)

        def lower(opt_state, scaler_state, batch, *extra):
            return jitted.lower(opt_state, scaler_state, None, batch,
                                *extra)

    # flight-recorder label access: the ordered tap names, known after
    # the tapped loss first traces (None before the first call)
    step.tap_names = lambda: tap_holder["names"]
    # AOT observatory handles (monitor.compile.analyze_step): .lower
    # applies the call path's argument mapping, .jitted lets the
    # RecompileSentry poll the real jit cache, donate_argnums/arg_names
    # drive the donation check and the budget table labels
    step.lower = lower
    step.jitted = jitted
    step.donate_argnums = donate_args
    step.arg_names = tuple(names)
    # the static linter's collective pass (apex_tpu.lint CL201) checks
    # every traced psum/all_gather axis against the mesh that will run
    # the program — the builder is the one place both are known; the
    # comms observatory additionally needs the axis SIZES to map
    # optimized-HLO replica groups back to these names (ISSUE 7)
    step.mesh_axis_names = tuple(str(a) for a in mesh.axis_names)
    step.mesh_axis_sizes = tuple(int(s) for s in mesh.devices.shape)
    # preemption-proof checkpointing (ISSUE 9): the opt-state partition
    # specs ARE the checkpoint shard contract — apex_tpu.checkpoint's
    # CheckpointManager splits each state leaf by them (P(dp) leaves
    # persist as per-rank shard files, P() leaves replicated), and the
    # elastic restore places the re-laid state back through the same
    # specs, so a resumed step sees bit-identical shardings and never
    # retraces (the RecompileSentry-enforced resume contract)
    step.state_partition_specs = opt_spec
    return step
