"""SyncBatchNorm — cross-replica batch normalization.

≡ apex.parallel.SyncBatchNorm (apex/parallel/optimized_sync_batchnorm.py:9,
kernel fn optimized_sync_batchnorm_kernel.py:7-119, fallback
sync_batchnorm.py:9) and `convert_syncbn_model` (apex/parallel/__init__.py:21).
The CUDA design: local Welford → all_gather stats → welford_parallel
merge → BN fwd; backward all-reduces (sum_dy, sum_dy_xmu).  The TPU
design: the Pallas stats kernel (ops/welford.py) plus ONE `lax.psum`
merge inside the autodiff region — JAX differentiates through the psum,
emitting exactly the reference's backward collectives.

Also covers the reference's process-group BN variants
(apex.contrib.groupbn BatchNorm2d_NHWC, apex.contrib.cudnn_gbn
GroupBatchNorm2d): pass a sub-axis name (or axis index ranges via
shard_map axis slicing) as `axis_name`.

Layout is channels-last (NHWC), the native TPU conv layout (the
reference's groupbn is NHWC too).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops import welford


def sync_batch_norm(x, scale, bias, running_mean, running_var, *,
                    training: bool = True, momentum: float = 0.1,
                    eps: float = 1e-5, axis_name: Optional[str] = None,
                    channel_axis: int = -1):
    """Functional SyncBN.  Returns (y, new_running_mean, new_running_var).

    ≡ SyncBatchnormFunction.forward
    (apex/parallel/optimized_sync_batchnorm_kernel.py:10-92).  When
    `axis_name` is set (inside shard_map/pjit over the mesh), batch
    statistics are merged across that axis; backward collectives are
    derived by AD.  Running stats use the merged mean and the *unbiased*
    var like the reference (kernel.py:54-60).
    """
    chan = channel_axis % x.ndim
    reduce_axes = tuple(a for a in range(x.ndim) if a != chan)
    if training:
        mean, var, count = welford.batch_stats(x, reduce_axes)
        if axis_name is not None:
            mean, var, count = welford.merge_stats(mean, var, count,
                                                   axis_name)
        count = jnp.asarray(count, jnp.float32)
        unbiased = var * count / jnp.maximum(count - 1.0, 1.0)
        new_rm = (1 - momentum) * running_mean + momentum * jax.lax.stop_gradient(mean)
        new_rv = (1 - momentum) * running_var + momentum * jax.lax.stop_gradient(unbiased)
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var

    shape = [1] * x.ndim
    shape[chan] = x.shape[chan]
    mean_b = mean.reshape(shape)
    rstd_b = jax.lax.rsqrt(var + eps).reshape(shape)
    y = (x.astype(jnp.float32) - mean_b) * rstd_b
    if scale is not None:
        y = y * scale.astype(jnp.float32).reshape(shape)
    if bias is not None:
        y = y + bias.astype(jnp.float32).reshape(shape)
    return y.astype(x.dtype), new_rm, new_rv


class SyncBatchNorm:
    """Module facade ≡ apex.parallel.SyncBatchNorm
    (optimized_sync_batchnorm.py:9-79).

    params: {"scale": (C,), "bias": (C,)}; state: {"running_mean",
    "running_var", "num_batches_tracked"}.
    """

    def __init__(self, num_features: int, eps: float = 1e-5,
                 momentum: float = 0.1, affine: bool = True,
                 track_running_stats: bool = True,
                 axis_name: Optional[str] = None,
                 channel_axis: int = -1):
        self.num_features = num_features
        self.eps = eps
        self.momentum = momentum
        self.affine = affine
        self.track_running_stats = track_running_stats
        self.axis_name = axis_name
        self.channel_axis = channel_axis

    def init(self, key=None, dtype=jnp.float32):
        params = {}
        if self.affine:
            params = {"scale": jnp.ones((self.num_features,), dtype),
                      "bias": jnp.zeros((self.num_features,), dtype)}
        state = {"running_mean": jnp.zeros((self.num_features,), jnp.float32),
                 "running_var": jnp.ones((self.num_features,), jnp.float32)}
        return params, state

    def apply(self, params, state, x, training: bool = True,
              axis_name: Optional[str] = "__unset__"):
        ax = self.axis_name if axis_name == "__unset__" else axis_name
        scale = params.get("scale") if self.affine else None
        bias = params.get("bias") if self.affine else None
        y, rm, rv = sync_batch_norm(
            x, scale, bias, state["running_mean"], state["running_var"],
            training=training and self.track_running_stats or training,
            momentum=self.momentum, eps=self.eps, axis_name=ax,
            channel_axis=self.channel_axis)
        new_state = {"running_mean": rm, "running_var": rv}
        return y, new_state


def convert_syncbn_model(module_tree, axis_name: str):
    """≡ apex.parallel.convert_syncbn_model (apex/parallel/__init__.py:21):
    walk a module pytree and give every SyncBatchNorm the DP axis name."""
    def convert(m):
        if isinstance(m, SyncBatchNorm):
            m.axis_name = axis_name
        return m
    return jax.tree_util.tree_map(
        convert, module_tree,
        is_leaf=lambda m: isinstance(m, SyncBatchNorm))
