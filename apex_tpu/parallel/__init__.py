"""apex_tpu.parallel — mesh, collectives, and data-parallel utilities.

≡ apex.parallel (apex/parallel/__init__.py) + the process-group layer of
apex.transformer.parallel_state, re-based on `jax.sharding.Mesh`.
"""

from apex_tpu.parallel import collectives, mesh
from apex_tpu.parallel.mesh import (
    DP_AXIS,
    PP_AXIS,
    TP_AXIS,
    destroy_model_parallel,
    get_data_parallel_world_size,
    get_mesh,
    get_pipeline_model_parallel_world_size,
    get_rank_info,
    get_tensor_model_parallel_world_size,
    initialize_model_parallel,
    model_parallel_is_initialized,
    named_sharding,
)

__all__ = [
    "mesh", "collectives", "initialize_model_parallel",
    "destroy_model_parallel", "model_parallel_is_initialized", "get_mesh",
    "named_sharding", "DP_AXIS", "PP_AXIS", "TP_AXIS", "get_rank_info",
    "get_data_parallel_world_size", "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
]


def __getattr__(name):
    # Lazy imports for heavier submodules.
    if name in ("DistributedDataParallel", "ddp"):
        from apex_tpu.parallel import ddp as _ddp
        if name == "ddp":
            return _ddp
        return _ddp.DistributedDataParallel
    if name in ("SyncBatchNorm", "sync_batchnorm"):
        from apex_tpu.parallel import sync_batchnorm as _sbn
        if name == "sync_batchnorm":
            return _sbn
        return _sbn.SyncBatchNorm
    if name == "LARC":
        from apex_tpu.parallel.larc import LARC
        return LARC
    if name == "clip_grad":
        from apex_tpu.parallel import clip_grad
        return clip_grad
    raise AttributeError(name)
