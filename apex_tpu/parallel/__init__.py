"""apex_tpu.parallel — mesh, collectives, and data-parallel utilities.

≡ apex.parallel (apex/parallel/__init__.py) + the process-group layer of
apex.transformer.parallel_state, re-based on `jax.sharding.Mesh`.
"""

from apex_tpu.parallel import collectives, mesh
from apex_tpu.parallel.mesh import (
    DP_AXIS,
    EP_AXIS,
    PP_AXIS,
    TP_AXIS,
    destroy_model_parallel,
    get_data_parallel_axis_names,
    get_data_parallel_world_size,
    get_expert_model_parallel_world_size,
    get_mesh,
    get_pipeline_model_parallel_world_size,
    get_rank_info,
    get_tensor_model_parallel_world_size,
    initialize_model_parallel,
    model_parallel_is_initialized,
    named_sharding,
)

__all__ = [
    "mesh", "collectives", "initialize_model_parallel",
    "destroy_model_parallel", "model_parallel_is_initialized", "get_mesh",
    "named_sharding", "DP_AXIS", "PP_AXIS", "TP_AXIS", "EP_AXIS",
    "get_rank_info",
    "get_data_parallel_world_size", "get_tensor_model_parallel_world_size",
    "get_pipeline_model_parallel_world_size",
    "get_expert_model_parallel_world_size", "get_data_parallel_axis_names",
]


def __getattr__(name):
    # Lazy imports for heavier submodules (importlib avoids re-entering
    # this __getattr__ during the submodule's own import).
    import importlib
    if name in ("ddp", "sync_batchnorm", "larc", "clip_grad", "multiproc",
                "context_parallel"):
        return importlib.import_module(f"apex_tpu.parallel.{name}")
    if name == "DistributedDataParallel":
        return importlib.import_module(
            "apex_tpu.parallel.ddp").DistributedDataParallel
    if name == "Reducer":  # ≡ apex.parallel.Reducer (distributed.py:91)
        return importlib.import_module("apex_tpu.parallel.ddp").Reducer
    if name == "SyncBatchNorm":
        return importlib.import_module(
            "apex_tpu.parallel.sync_batchnorm").SyncBatchNorm
    if name == "convert_syncbn_model":  # ≡ apex/parallel/__init__.py:21
        return importlib.import_module(
            "apex_tpu.parallel.sync_batchnorm").convert_syncbn_model
    if name == "LARC":
        return importlib.import_module("apex_tpu.parallel.larc").LARC
    raise AttributeError(name)
