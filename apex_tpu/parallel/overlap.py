"""Chunked compute/collective overlap primitives (ISSUE 18 tentpole).

The TP hot path's tax is a handful of BIG collectives that serialize
against the GEMMs that produce or consume them: the column-parallel
layer's sequence all-gather must finish before its GEMM starts, and
the row-parallel reduce-scatter/all-reduce can't start until its GEMM
finishes.  T3 (arXiv 2401.16677) and partially-synchronized
activations (arXiv 2506.19645) show the cure: split the work along
the batch/sequence dim into `chunks` pieces and software-pipeline —
the collective for chunk k+1 is in flight on ICI while the MXU chews
chunk k.  XLA's async collectives do the actual overlapping; these
primitives just give the scheduler chunk-granular pieces it CAN
overlap (one monolithic dependency edge offers nothing to reorder).

Four fused matmul+collective spellings, one per TP layer shape:

  ring_gather_matmul    column-parallel + sequence_parallel: the
                        all-gather+GEMM becomes p-1 per-chunk
                        `ppermute` ring steps (collectives.ring_
                        exchange) interleaved with partial GEMMs —
                        bytes drop to (p-1)/p of the all-gather and
                        every hop hides behind a GEMM.
  matmul_reduce_scatter row-parallel + sequence_parallel: the down
                        projection runs chunk-by-chunk along the
                        OUTPUT sequence rows; chunk k's psum_scatter
                        overlaps chunk k+1's GEMM.
  matmul_all_reduce     row-parallel, no SP: same pipeline with psum.
  copy_matmul           column-parallel, no SP: forward is the plain
                        local GEMM (no collective to hide); backward
                        chunks the dgrad GEMM against the copy_to
                        psum of dx.

All four are `jax.custom_vjp` (like parallel/collectives.py's region
pairs) so the BACKWARD is pipelined too — AD of a hand-unrolled ring
would otherwise serialize the transposed collectives.  GEMMs
accumulate in fp32 on the MXU (`preferred_element_type`) and weight
grads accumulate across chunks/ring-steps in fp32, so chunked results
are allclose to the monolithic spelling at tight tolerance; the
chunks==1 case is NOT routed here at all — callers keep their
original monolithic code path, byte-identical to pre-overlap
programs (the RecompileSentry anchor).

Chunk counts are tuner-owned: `tune.tuned("overlap_chunks",
tune.overlap_attrs(...))`, heuristic 1 on a miss — CPU and untuned
machines trace exactly the pre-PR program.  `resolve_chunks` applies
the flash-attention block rule to non-dividing requests: fall back to
the largest dividing count and warn once per call site.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax import lax

from apex_tpu.parallel.collectives import ring_exchange

# call sites that already warned about a non-dividing chunk request —
# warn once per (site, requested, dim), not once per trace
_WARNED_SITES = set()


def resolve_chunks(requested: int, dim: int, site: str = "overlap") -> int:
    """Largest divisor of `dim` that is <= `requested` (>= 1).

    The flash-attention block rule: a tuned/forced chunk count that
    does not divide the chunked dim must not crash the trace NOR
    silently change semantics — fall back to the largest dividing
    count and warn once per call site."""
    requested = int(requested)
    dim = int(dim)
    if requested <= 1 or dim <= 1:
        return 1
    c = min(requested, dim)
    while dim % c:
        c -= 1
    if c != requested:
        key = (site, requested, dim)
        if key not in _WARNED_SITES:
            _WARNED_SITES.add(key)
            warnings.warn(
                f"overlap_chunks={requested} does not divide the "
                f"chunked dim ({dim}) at {site!r}; falling back to "
                f"{c} chunks", stacklevel=2)
    return c


def layer_chunks(requested, path: str, rows: int, width: int,
                 axis_name: str, dtype, divisor_of: int) -> int:
    """Trace-time chunk-count decision for one TP layer call site.

    requested None = tuner-owned: consult the `overlap_chunks` cache
    keyed by tune.overlap_attrs (per device kind); heuristic 1 on a
    miss, so untuned paths stay byte-identical to pre-overlap
    programs.  An explicit int is the A/B override and still goes
    through `resolve_chunks` (the non-dividing fallback)."""
    if requested is None:
        from apex_tpu import tune
        try:
            p = int(lax.axis_size(axis_name))
        except NameError:
            p = 1
        cfg = tune.tuned("overlap_chunks",
                         tune.overlap_attrs(path, rows, width, p, dtype))
        requested = int(cfg["chunks"]) if cfg else 1
    requested = int(requested)
    if requested <= 1:
        return 1
    return resolve_chunks(requested, divisor_of, site=path)


def _dot(a, b, out_dtype):
    """The house GEMM spelling: fp32 MXU accumulation, cast back."""
    return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)


def _flat_wgrad(x_rows, g_rows):
    """fp32 (H, O) partial weight grad from matching row blocks."""
    xm = x_rows.reshape(-1, x_rows.shape[-1])
    gm = g_rows.reshape(-1, g_rows.shape[-1])
    return jnp.einsum("th,to->ho", xm, gm,
                      preferred_element_type=jnp.float32)


# --------------------------------------------------------------------------
# column-parallel + sequence_parallel: ppermute-ring gather + GEMM
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def ring_gather_matmul(x, w, axis_name, chunks):
    """all_gather(x, dim 0) @ w, as a chunked ppermute ring.

    x: (s_loc, ..., H) this rank's sequence shard; w: (H, O_loc).
    Returns (p*s_loc, ..., O_loc) — the full-sequence activation
    against the local weight shard, bitwise the same rows as the
    monolithic gather+GEMM (each row is one fp32-accumulated dot).

    Ring step k holds source shard (r+k) mod p; the ppermutes feeding
    step k+1 are issued BEFORE step k's GEMMs so XLA overlaps the hop
    with the math.  `chunks` sub-slices each shard so each hop is a
    smaller, earlier-available piece.  Total bytes: (p-1)/p of the
    all-gather."""
    return _ring_fwd_impl(x, w, axis_name, chunks)


def _ring_fwd_impl(x, w, ax, chunks):
    p = lax.axis_size(ax)
    r = lax.axis_index(ax)
    s = x.shape[0]
    sc = s // chunks
    out = jnp.zeros((p * s,) + x.shape[1:-1] + (w.shape[-1],), x.dtype)
    held = [lax.slice_in_dim(x, j * sc, (j + 1) * sc, axis=0)
            for j in range(chunks)]
    for k in range(p):
        src = (r + k) % p  # traced rank -> dynamic row placement
        nxt = []
        for j in range(chunks):
            if k + 1 < p:
                # issue the hop for step k+1 before this chunk's GEMM
                nxt.append(ring_exchange(held[j], ax, shift=-1))
            y = _dot(held[j], w, x.dtype)
            out = lax.dynamic_update_slice_in_dim(
                out, y, src * s + j * sc, axis=0)
        if nxt:
            held = nxt
    return out


def _ring_fwd(x, w, ax, chunks):
    return _ring_fwd_impl(x, w, ax, chunks), (x, w)


def _ring_bwd(ax, chunks, res, g):
    x, w = res
    p = lax.axis_size(ax)
    r = lax.axis_index(ax)
    s = x.shape[0]
    sc = s // chunks
    # dx = reduce_scatter(g @ w^T, dim 0) — the gather's transpose —
    # chunked so chunk k's psum_scatter overlaps chunk k+1's GEMM.
    # Rows regroup as (p, chunks, sc): the scatter keeps rank-block r
    # of each chunk, i.e. this shard's rows [j*sc, (j+1)*sc).
    gv = g.reshape((p, chunks, sc) + g.shape[1:])
    dx_chunks = []
    for j in range(chunks):
        gj = gv[:, j].reshape((p * sc,) + g.shape[1:])
        z = _dot(gj, w.T, x.dtype)
        dx_chunks.append(
            lax.psum_scatter(z, ax, scatter_dimension=0, tiled=True))
    dx = jnp.concatenate(dx_chunks, axis=0)
    # dw: ring over x again — every rank sees every source shard and
    # each rank's g is the FULL (p*s, ...) cotangent of its local
    # output columns, so the fp32 accumulation is complete with NO
    # trailing psum (the ring IS the reduction's data movement).
    dw = jnp.zeros(w.shape, jnp.float32)
    held = [lax.slice_in_dim(x, j * sc, (j + 1) * sc, axis=0)
            for j in range(chunks)]
    for k in range(p):
        src = (r + k) % p
        nxt = []
        for j in range(chunks):
            if k + 1 < p:
                nxt.append(ring_exchange(held[j], ax, shift=-1))
            g_rows = lax.dynamic_slice_in_dim(
                g, src * s + j * sc, sc, axis=0)
            dw = dw + _flat_wgrad(held[j], g_rows)
        if nxt:
            held = nxt
    return dx, dw.astype(w.dtype)


ring_gather_matmul.defvjp(_ring_fwd, _ring_bwd)


# --------------------------------------------------------------------------
# row-parallel + sequence_parallel: GEMM + chunked reduce-scatter
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_reduce_scatter(x, w, axis_name, chunks):
    """reduce_scatter(x @ w, dim 0), chunked along the OUTPUT rows.

    x: (S, ..., H_loc); w: (H_loc, O).  Returns (S/p, ..., O).  Each
    chunk GEMMs exactly the input rows that feed its output slice
    (rank-block-strided, values identical row-for-row) and scatters
    them while the next chunk's GEMM runs."""
    return _mrs_fwd_impl(x, w, axis_name, chunks)


def _mrs_fwd_impl(x, w, ax, chunks):
    p = lax.axis_size(ax)
    s = x.shape[0]
    so = s // p
    soc = so // chunks
    xv = x.reshape((p, so) + x.shape[1:])
    outs = []
    for j in range(chunks):
        xj = lax.slice_in_dim(xv, j * soc, (j + 1) * soc, axis=1)
        xj = xj.reshape((p * soc,) + x.shape[1:])
        z = _dot(xj, w, x.dtype)
        outs.append(
            lax.psum_scatter(z, ax, scatter_dimension=0, tiled=True))
    return jnp.concatenate(outs, axis=0)


def _mrs_fwd(x, w, ax, chunks):
    return _mrs_fwd_impl(x, w, ax, chunks), (x, w)


def _mrs_bwd(ax, chunks, res, g):
    x, w = res
    p = lax.axis_size(ax)
    s = x.shape[0]
    so = s // p
    soc = so // chunks
    xv = x.reshape((p, so) + x.shape[1:])
    dw = jnp.zeros(w.shape, jnp.float32)
    dx_parts = []
    for j in range(chunks):
        gj = lax.slice_in_dim(g, j * soc, (j + 1) * soc, axis=0)
        # the scatter's transpose: all-gather this output chunk's
        # cotangent, chunk k's gather overlaps chunk k-1's dgrad GEMM
        G = lax.all_gather(gj, ax, axis=0, tiled=True)  # (p*soc, ..., O)
        dxj = _dot(G, w.T, x.dtype)
        dx_parts.append(dxj.reshape((p, soc) + dxj.shape[1:]))
        xj = lax.slice_in_dim(xv, j * soc, (j + 1) * soc, axis=1)
        dw = dw + _flat_wgrad(xj, G.reshape((p, soc) + G.shape[1:]))
    # (p, chunks, soc, ...) -> (S, ...): row (q, j, i) is input row
    # q*so + j*soc + i, the inverse of the forward's regrouping
    dx = jnp.stack(dx_parts, axis=1).reshape((s,) + x.shape[1:])
    return dx, dw.astype(w.dtype)


matmul_reduce_scatter.defvjp(_mrs_fwd, _mrs_bwd)


# --------------------------------------------------------------------------
# row-parallel, no SP: GEMM + chunked all-reduce
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def matmul_all_reduce(x, w, axis_name, chunks):
    """psum(x @ w), chunked along dim 0: chunk k's all-reduce rides
    ICI while chunk k+1's GEMM runs.  x: (S, ..., H_loc); w:
    (H_loc, O); returns (S, ..., O) fully reduced."""
    return _mar_fwd_impl(x, w, axis_name, chunks)


def _mar_fwd_impl(x, w, ax, chunks):
    s = x.shape[0]
    sc = s // chunks
    outs = []
    for j in range(chunks):
        xj = lax.slice_in_dim(x, j * sc, (j + 1) * sc, axis=0)
        outs.append(lax.psum(_dot(xj, w, x.dtype), ax))
    return jnp.concatenate(outs, axis=0)


def _mar_fwd(x, w, ax, chunks):
    return _mar_fwd_impl(x, w, ax, chunks), (x, w)


def _mar_bwd(ax, chunks, res, g):
    # the all-reduce's transpose is the identity (reduce_from's f/g
    # pair): dgrad and wgrad are LOCAL — nothing to overlap, so the
    # backward stays monolithic (chunking it would only shrink GEMMs)
    x, w = res
    dx = _dot(g, w.T, x.dtype)
    dw = _flat_wgrad(x, g).astype(w.dtype)
    return dx, dw


matmul_all_reduce.defvjp(_mar_fwd, _mar_bwd)


# --------------------------------------------------------------------------
# column-parallel, no SP: plain GEMM fwd, chunked psum(dx) bwd
# --------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def copy_matmul(x, w, axis_name, chunks):
    """copy_to(x) @ w.  Forward is the plain local GEMM (copy_to is
    the identity — there is no forward collective to hide); backward
    chunks dx = psum(g @ w^T) so each chunk's all-reduce overlaps the
    next chunk's dgrad GEMM.  x: (S, ..., H) replicated; w:
    (H, O_loc)."""
    return _dot(x, w, x.dtype)


def _cm_fwd(x, w, ax, chunks):
    return _dot(x, w, x.dtype), (x, w)


def _cm_bwd(ax, chunks, res, g):
    x, w = res
    s = x.shape[0]
    sc = s // chunks
    dx_parts = []
    for j in range(chunks):
        gj = lax.slice_in_dim(g, j * sc, (j + 1) * sc, axis=0)
        dx_parts.append(lax.psum(_dot(gj, w.T, x.dtype), ax))
    dx = jnp.concatenate(dx_parts, axis=0)
    dw = _flat_wgrad(x, g).astype(w.dtype)
    return dx, dw


copy_matmul.defvjp(_cm_fwd, _cm_bwd)
