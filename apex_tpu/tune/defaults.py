"""Committed tuned-config defaults, keyed by device kind.

These ship with the package so the benched shapes get their tuned
kernel configs out of the box — the cache file layers user sweeps on
top (cache._merged_for_kind).  Structure mirrors one device-kind
section of the cache file: {kind: {key: {"config": ..., "meta": ...}}}.

To commit defaults for a new chip: run

    python scripts/gpt_anatomy.py tune          # sweeps + writes cache

on the target hardware, then copy the winning entries from the cache
file (``apex_tpu.tune.cache_path()``) into this dict under the chip's
canonical kind (``apex_tpu.tune.device_kind()``).  ``scripts/
gpt_anatomy.py tune --check`` re-sweeps and exits nonzero when these
committed entries drift from fresh measurements.

The v5e flash entries below pack 2 heads per grid step (heads_per_step)
with 512-square blocks: the d=64 per-head score block is VPU-epilogue
and grid-overhead bound (docs/PERF.md roofline: 29–44% of the 7-matmul
mix ceiling), and packing fills the softmax-stat vregs across heads
while keeping the (hp·bk·bq) fp32 score tile at 2 MB of VMEM.
"""

from __future__ import annotations


def _flash(b, h, sq, sk, d, dtype, causal, bias="none", seg=False):
    from apex_tpu import tune
    from apex_tpu.tune.cache import make_key
    return make_key("flash_sdpa",
                    tune.flash_attrs(b, h, sq, sk, d, dtype, causal,
                                     bias=bias, seg=seg))


def _mk(config, note):
    return {"config": config, "meta": {"note": note}}


def _v5e_entries():
    """Only the ATTENTION-KERNEL bench shapes carry packed defaults so
    far — the shapes bench.py measures inside per-metric try/except
    blocks (mha_latencies, long_context) and the ISSUE 3 acceptance
    shape (GPT-1.3B seq-2048, `gpt_anatomy.py roofline 1p3b2k`).  The
    MODEL-step shapes (GPT-350M b12 s1024, 1.3B b7 s512, BERT b32
    s512) deliberately stay on heuristics until a hardware sweep
    (`gpt_anatomy.py tune`) confirms the packed kernel's Mosaic
    compile + win there — the headline bench metrics must never gamble
    on an unmeasured config.  Promote cache winners here per
    docs/tuning.md once measured."""
    note = ("committed v5e default (attention bench shapes); refresh "
            "with scripts/gpt_anatomy.py tune")
    pack2 = {"block_q": 512, "block_k": 512, "heads_per_step": 2}
    e = {}
    # GPT-1.3B seq-2048 (b4 h32 d64 causal): the d=64 plateau shape
    # ISSUE 3's acceptance criterion measures via roofline
    e[_flash(4, 32, 2048, 2048, 64, "bfloat16", True)] = _mk(pack2, note)
    # MHA bench point: b8 h16 s2048 d64 causal (bench.py _mha_latencies)
    e[_flash(8, 16, 2048, 2048, 64, "bfloat16", True)] = _mk(pack2, note)
    # long-context 32k: b1 h8 s32768 d64 causal (bench.py); blocks stay
    # within the sweep's own hp*bq*bk <= 512k score-tile cap
    e[_flash(1, 8, 32768, 32768, 64, "bfloat16", True)] = _mk(pack2, note)
    # flat-optimizer block rows at the 1B Adam bench point: the swept
    # heuristic value, committed so the fingerprint records it
    from apex_tpu.tune.cache import make_key
    e[make_key("opt_flat", dict(kernel="adam", rows=8388608))] = _mk(
        {"block_rows": 512},
        "v5e 1B-param sweep: 512 rows = 721 GB/s (docs/PERF.md)")
    return e


DEFAULTS = {
    "v5e": _v5e_entries(),
}
