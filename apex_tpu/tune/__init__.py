"""apex_tpu.tune — kernel autotuning (ISSUE 3 tentpole).

Three pieces:

  * cache   — persistent JSON config store keyed by (device kind, op,
              shape/dtype attrs); committed defaults for v5e ship in
              defaults.py; $APEX_TPU_TUNE_CACHE overrides the path,
              APEX_TPU_TUNE=0 disables all lookups.
  * tuned() — the trace-time lookup kernels call when the caller passed
              no explicit config: a pure host-side dict access (zero
              collectives, no host syncs inside jitted steps).  Returns
              None on a miss — every kernel then falls back to its
              deterministic heuristic, byte-identical to the un-tuned
              framework.
  * search  — the OFFLINE sweep driver (never times inside a jitted
              step): times candidate configs wall-clock and records the
              winners.  `scripts/gpt_anatomy.py tune` is the CLI.

Tunable surfaces wired in this round: flash attention block_q/block_k +
heads_per_step head packing (ops/flash_attention.py), the softmax and
layer-norm row blocks (via ops._common.tuned_row_block), and the flat
optimizer kernels' rows-per-block (ops/optimizer_kernels.py).
"""

from apex_tpu.tune.cache import (  # noqa: F401
    ENV_CACHE_PATH,
    ENV_DISABLE,
    SCHEMA_VERSION,
    cache_path,
    device_kind,
    fingerprint,
    invalidate,
    lookup,
    make_key,
    record,
    reset_stats,
    stats,
)


def pow2_bucket(n: int) -> int:
    """Round up to the next power of two — the size coordinate of keys
    whose exact value shouldn't fragment the cache (row counts)."""
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def flash_attrs(b, h, sq, sk, d, dtype, causal, bias="none", seg=False):
    """The ONE definition of the flash_sdpa lookup-key attrs — shared
    by the runtime lookup (ops/flash_attention.py), the sweep driver
    (tune/search.py), and the committed defaults (tune/defaults.py).
    A key-schema change here reaches all three or none.  dtype None
    means the bench dtype, bfloat16."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    return dict(b=int(b), h=int(h), sq=int(sq), sk=int(sk), d=int(d),
                dtype=jnp.dtype(dtype).name, causal=bool(causal),
                bias=bias, seg=bool(seg))


def tuned(op: str, attrs=None, **kw):
    """Tuned config for (op, attrs) on this device kind, or None.

    attrs values must be ints/bools/strings (canonicalized into the
    cache key).  Call at TRACE time only with static shapes — the
    lookup itself touches no device state.
    """
    a = dict(attrs or {})
    a.update(kw)
    return lookup(op, a)
