"""apex_tpu.tune — kernel autotuning (ISSUE 3 tentpole).

Three pieces:

  * cache   — persistent JSON config store keyed by (device kind, op,
              shape/dtype attrs); committed defaults for v5e ship in
              defaults.py; $APEX_TPU_TUNE_CACHE overrides the path,
              APEX_TPU_TUNE=0 disables all lookups.
  * tuned() — the trace-time lookup kernels call when the caller passed
              no explicit config: a pure host-side dict access (zero
              collectives, no host syncs inside jitted steps).  Returns
              None on a miss — every kernel then falls back to its
              deterministic heuristic, byte-identical to the un-tuned
              framework.
  * search  — the OFFLINE sweep driver (never times inside a jitted
              step): times candidate configs wall-clock and records the
              winners.  `scripts/gpt_anatomy.py tune` is the CLI.

Tunable surfaces wired in this round: flash attention block_q/block_k +
heads_per_step head packing (ops/flash_attention.py), the softmax and
layer-norm row blocks (via ops._common.tuned_row_block), the flat
optimizer kernels' rows-per-block (ops/optimizer_kernels.py), and the
serving path (ISSUE 8): `flash_decode` heads_per_step (key:
decode_attrs) and the paged KV cache's page size (`serve_page`, key:
serve_page_attrs — the page IS the decode kernel's kv block, so the
one knob tunes both the DMA unit and the pool granularity), and the
MoE top-k router's row block (`moe_router`, key: moe_router_attrs —
softmax + top-k are row-independent, so the tuned blocked path is
byte-identical to the dense reference at every block size).

ISSUE 18 adds the `overlap_chunks` op (key: overlap_attrs): the chunk
count of the TP layers' fused matmul+collective pipelines and the MoE
dispatch/combine micro-chunking (parallel/overlap.py,
moe/dispatch.chunked_expert_exchange).  Heuristic 1 on a miss = the
monolithic pre-overlap program, byte-identical — chunks > 1 is a
measured-win-only setting (per device kind), because each extra chunk
pays a collective launch latency floor that only a hardware sweep can
price against the hidden bandwidth (docs/PERF.md "Chunked overlap").
"""

from apex_tpu.tune.cache import (  # noqa: F401
    ENV_CACHE_PATH,
    ENV_DISABLE,
    SCHEMA_VERSION,
    cache_path,
    device_kind,
    fingerprint,
    invalidate,
    lookup,
    make_key,
    record,
    reset_stats,
    stats,
)


def pow2_bucket(n: int) -> int:
    """Round up to the next power of two — the size coordinate of keys
    whose exact value shouldn't fragment the cache (row counts)."""
    n = max(1, int(n))
    return 1 << (n - 1).bit_length()


def flash_attrs(b, h, sq, sk, d, dtype, causal, bias="none", seg=False):
    """The ONE definition of the flash_sdpa lookup-key attrs — shared
    by the runtime lookup (ops/flash_attention.py), the sweep driver
    (tune/search.py), and the committed defaults (tune/defaults.py).
    A key-schema change here reaches all three or none.  dtype None
    means the bench dtype, bfloat16."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    return dict(b=int(b), h=int(h), sq=int(sq), sk=int(sk), d=int(d),
                dtype=jnp.dtype(dtype).name, causal=bool(causal),
                bias=bias, seg=bool(seg))


def decode_attrs(n_slots, q_len, hq, hkv, d, page_size, dtype):
    """The ONE definition of the `flash_decode` lookup-key attrs —
    shared by the runtime lookup (ops/flash_decode.py), the sweep
    driver (tune/search.py), and committed defaults.  n_slots is
    pow2-bucketed: the continuous-batching engine (apex_tpu.serve)
    keeps the slot count static per deployment, but sweeps shouldn't
    fragment the cache across nearby concurrencies.  dtype None means
    the serving cache dtype, bfloat16."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    return dict(slots=pow2_bucket(n_slots), ql=int(q_len), hq=int(hq),
                hkv=int(hkv), d=int(d), page=int(page_size),
                dtype=jnp.dtype(dtype).name)


def moe_router_attrs(tokens, n_experts, top_k, dtype):
    """The ONE definition of the `moe_router` lookup-key attrs — shared
    by the runtime lookup (moe/router.py) and any sweep driver.  The
    config carries `block_rows`, the row-block the top-k selection is
    chunked by (softmax + top_k are row-independent, so every block
    size is byte-identical to the dense reference — the tuner only
    moves the VMEM-residency/grid-overhead point).  `tokens` is
    pow2-bucketed: the local token count is batch-shape-derived and
    must not fragment the cache across nearby batch sizes.  dtype is
    the COMPUTE dtype of the incoming activations (the gate logits
    themselves are always fp32, the DP105 contract)."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    return dict(rows=pow2_bucket(tokens), experts=int(n_experts),
                k=int(top_k), dtype=jnp.dtype(dtype).name)


def serve_page_attrs(n_kv_heads, head_dim, dtype):
    """Lookup-key attrs for the `serve_page` op — the paged-KV-cache
    page size (serve.KVCacheConfig).  The page size IS the decode
    kernel's kv block size (one page = one DMA unit), so it is keyed
    by the cache layout alone, not by concurrency."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    return dict(hkv=int(n_kv_heads), d=int(head_dim),
                dtype=jnp.dtype(dtype).name)


def overlap_attrs(path, rows, width, axis_size, dtype):
    """The ONE definition of the `overlap_chunks` lookup-key attrs —
    shared by the runtime lookups (parallel/overlap.layer_chunks,
    moe/layer.MoEMLP) and any sweep driver.  The config carries
    `chunks`, the pipeline depth of a fused matmul+collective site.
    `path` names the site shape ("tp_col" ring-gather, "tp_row"
    GEMM+reduce-scatter, "tp_row_ar" GEMM+all-reduce, "tp_col_copy"
    backward-only dgrad psum, "moe" dispatch/combine micro-chunk);
    `rows` is the chunked dim pow2-bucketed (batch-shape-derived, must
    not fragment the cache); `width` the GEMM output width;
    `axis_size` the collective's axis size (overlap economics change
    with ring length).  dtype None means the bench dtype, bfloat16."""
    import jax.numpy as jnp

    dtype = jnp.bfloat16 if dtype is None else dtype
    return dict(path=str(path), rows=pow2_bucket(rows), width=int(width),
                ax=int(axis_size), dtype=jnp.dtype(dtype).name)


def tuned(op: str, attrs=None, **kw):
    """Tuned config for (op, attrs) on this device kind, or None.

    attrs values must be ints/bools/strings (canonicalized into the
    cache key).  Call at TRACE time only with static shapes — the
    lookup itself touches no device state.
    """
    a = dict(attrs or {})
    a.update(kw)
    return lookup(op, a)
