"""Persistent kernel-tuning cache — the storage half of apex_tpu.tune.

One JSON file holds every tuned kernel config, grouped by DEVICE KIND
(a config tuned on v5e must never drive a v4 or CPU run).  Layout:

    {
      "schema": 1,
      "entries": {
        "<device-kind>": {
          "<op>|k1=v1,k2=v2,...": {
            "config": {"block_q": 512, ...},     # what tuned() returns
            "meta":   {"ms": 1.23, "when": ...}  # provenance, ignored
          }
        }
      }
    }

Path resolution: $APEX_TPU_TUNE_CACHE if set, else
``~/.cache/apex_tpu/tune.json``.  A missing, unreadable, corrupt, or
wrong-schema file degrades to an EMPTY cache (warn once) — lookups then
fall through to the committed defaults (defaults.py) and finally to each
kernel's deterministic heuristic, so a broken cache can never change
numerics or crash a run, only lose tuned speed.

``lookup`` is a pure host-side dict access at TRACE time: it adds zero
collectives and no host syncs inside jitted steps.  ``record``/``save``
are for the OFFLINE search driver (tune.search) only — never time or
write inside a jitted step.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
import warnings
from typing import Any, Dict, Optional

SCHEMA_VERSION = 1

ENV_CACHE_PATH = "APEX_TPU_TUNE_CACHE"
ENV_DISABLE = "APEX_TPU_TUNE"          # "0" disables all lookups

_DEVICE_ALIASES = (
    # (substring of jax device_kind, canonical cache key)
    ("v5 lite", "v5e"),
    ("v5litepod", "v5e"),
    ("v5e", "v5e"),
    ("v6 lite", "v6e"),
    ("v6e", "v6e"),
    ("v5p", "v5p"),
    ("v4", "v4"),
)

_lock = threading.RLock()
_state: Dict[str, Any] = {
    "cache": None,         # loaded {key: {"config": ...}} for device kind
    "kind": None,
    "fingerprint": None,   # memoized digest of `cache` (logged per step)
    "hits": 0,
    "misses": 0,
    "warned": set(),
}


def cache_path() -> str:
    env = os.environ.get(ENV_CACHE_PATH)
    if env:
        return env
    return os.path.join(os.path.expanduser("~"), ".cache", "apex_tpu",
                        "tune.json")


def device_kind() -> str:
    """Canonical device-kind key for the current default backend.

    TPU kinds are normalized through _DEVICE_ALIASES so "TPU v5 lite"
    and "TPU v5e" both tune/look up under "v5e"; non-TPU backends use
    the backend name ("cpu", "gpu") so CPU CI can exercise the cache
    machinery without ever matching TPU entries.
    """
    import jax

    try:
        backend = jax.default_backend()
    except Exception:  # pragma: no cover — backend init failure
        return "unknown"
    if backend != "tpu":
        return backend
    kind = jax.devices()[0].device_kind.lower()
    for sub, canon in _DEVICE_ALIASES:
        if sub in kind:
            return canon
    return kind.replace(" ", "-")


def make_key(op: str, attrs: Dict[str, Any]) -> str:
    """Canonical string key: op + sorted k=v attrs (ints/bools/strs)."""
    def fmt(v):
        if isinstance(v, bool):
            return "1" if v else "0"
        return str(v)

    items = ",".join(f"{k}={fmt(v)}" for k, v in sorted(attrs.items()))
    return f"{op}|{items}"


def _warn_once(tag: str, msg: str) -> None:
    if tag not in _state["warned"]:
        _state["warned"].add(tag)
        warnings.warn(msg, stacklevel=3)


def _read_file(path: str) -> Dict[str, Dict[str, Any]]:
    """All device-kind sections of the cache file; {} on any problem."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError) as e:
        _warn_once("corrupt:" + path,
                   f"apex_tpu.tune: ignoring unreadable/corrupt cache "
                   f"{path} ({e!r}); falling back to heuristics")
        return {}
    if not isinstance(raw, dict) or raw.get("schema") != SCHEMA_VERSION:
        _warn_once("schema:" + path,
                   f"apex_tpu.tune: cache {path} has schema "
                   f"{raw.get('schema') if isinstance(raw, dict) else '?'}"
                   f" != {SCHEMA_VERSION}; ignoring it")
        return {}
    entries = raw.get("entries")
    return entries if isinstance(entries, dict) else {}


def _merged_for_kind(kind: str) -> Dict[str, Any]:
    """User-cache entries layered over the committed defaults."""
    from apex_tpu.tune import defaults

    merged = dict(defaults.DEFAULTS.get(kind, {}))
    file_entries = _read_file(cache_path()).get(kind, {})
    if isinstance(file_entries, dict):
        merged.update(file_entries)
    return merged


def _ensure_loaded() -> Dict[str, Any]:
    kind = device_kind()
    with _lock:
        if _state["cache"] is None or _state["kind"] != kind:
            _state["cache"] = _merged_for_kind(kind)
            _state["kind"] = kind
            _state["fingerprint"] = None
        return _state["cache"]


def lookup(op: str, attrs: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Tuned config dict for (op, attrs) on the current device kind, or
    None (→ caller uses its heuristic).  Counts hits/misses for the
    bench fingerprint.  Pure host-side; safe at trace time."""
    if os.environ.get(ENV_DISABLE, "") == "0":
        return None
    cache = _ensure_loaded()
    entry = cache.get(make_key(op, attrs))
    with _lock:
        if entry is None:
            _state["misses"] += 1
            return None
        _state["hits"] += 1
    cfg = entry.get("config")
    return dict(cfg) if isinstance(cfg, dict) else None


@contextlib.contextmanager
def _file_lock(path: str):
    """Advisory cross-PROCESS lock for the cache read-modify-write —
    the in-process threading lock cannot stop two concurrent sweep
    processes from losing each other's entries.  Best-effort: platforms
    without fcntl (or a filesystem refusing flock) fall back to the
    unlocked write rather than failing the sweep."""
    lock_path = path + ".lock"
    f = None
    try:
        try:
            import fcntl
            f = open(lock_path, "w")
            fcntl.flock(f, fcntl.LOCK_EX)
        except Exception:
            f = None
        yield
    finally:
        if f is not None:
            try:
                import fcntl
                fcntl.flock(f, fcntl.LOCK_UN)
            except Exception:
                pass
            f.close()


def record(op: str, attrs: Dict[str, Any], config: Dict[str, Any],
           meta: Optional[Dict[str, Any]] = None,
           kind: Optional[str] = None) -> str:
    """Write one tuned entry to the cache file (read-modify-write under
    an advisory file lock, so concurrent sweep processes compose).
    Returns the key.  OFFLINE only — never call inside a jitted step."""
    kind = kind or device_kind()
    key = make_key(op, attrs)
    path = cache_path()
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with _lock, _file_lock(path):
        entries = _read_file(path)
        entries.setdefault(kind, {})[key] = {
            "config": dict(config), "meta": dict(meta or {})}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"schema": SCHEMA_VERSION, "entries": entries}, f,
                      indent=1, sort_keys=True)
        os.replace(tmp, path)
        invalidate()
    return key


def fingerprint() -> str:
    """12-hex digest of the ACTIVE merged entries (committed defaults +
    user cache for the current device kind) — stamps bench JSON and
    monitor records so two runs' tuned configs are comparable.
    Memoized until invalidate() (MetricsLogger reads it every record)."""
    cache = _ensure_loaded()
    with _lock:
        if _state["fingerprint"] is None:
            if not cache:
                _state["fingerprint"] = "empty"
            else:
                blob = json.dumps(cache, sort_keys=True).encode()
                _state["fingerprint"] = hashlib.sha1(blob).hexdigest()[:12]
        return _state["fingerprint"]


def stats() -> Dict[str, Any]:
    """{"hits", "misses", "fingerprint"} since the last reset — the
    tuner state stamp for bench.py / monitor."""
    with _lock:
        return {"hits": _state["hits"], "misses": _state["misses"],
                "fingerprint": fingerprint()}


def reset_stats() -> None:
    with _lock:
        _state["hits"] = 0
        _state["misses"] = 0


def invalidate() -> None:
    """Drop the in-memory memo (tests; after record/env changes)."""
    with _lock:
        _state["cache"] = None
        _state["kind"] = None
        _state["fingerprint"] = None
