"""Offline kernel-config search — the timing half of apex_tpu.tune.

OFFLINE ONLY: every candidate is compiled and timed wall-clock as its
own jitted program (never inside a training step — a tuner that times
inside jit would perturb exactly what it measures and sync the host).
Winners are written to the persistent cache via cache.record; the
kernels pick them up at their next trace through tune.tuned().

CLI: ``python scripts/gpt_anatomy.py tune [targets...] [--check]``.
"""

from __future__ import annotations

import contextlib
import functools
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from apex_tpu.tune import cache


def _time_fn(fn, args, iters=10, warmup=2, reps=2) -> float:
    """Best-of-reps mean seconds per call, fully synced."""
    import jax

    out = None
    for _ in range(warmup):
        out = fn(*args)
    _ = np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0])
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args)
        _ = np.asarray(jax.tree_util.tree_leaves(out)[0].ravel()[0])
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


@contextlib.contextmanager
def forced(op: str, attrs: Dict[str, Any], config: Dict[str, Any]):
    """Temporarily pin (op, attrs) -> config in the IN-MEMORY cache so a
    kernel with no explicit config knob can be timed at a candidate.
    Re-trace (fresh jit) inside the context — lookups happen at trace
    time."""
    key = cache.make_key(op, attrs)
    mem = cache._ensure_loaded()
    missing = object()
    old = mem.get(key, missing)
    mem[key] = {"config": dict(config)}
    try:
        yield
    finally:
        if old is missing:
            mem.pop(key, None)
        else:
            mem[key] = old


# ------------------------------ flash attention -----------------------------

def flash_candidates(h: int, sq: int, sk: int,
                     max_score_elems: int = 512 * 1024
                     ) -> List[Dict[str, int]]:
    """Candidate (block_q, block_k, heads_per_step) grid: blocks divide
    the sequence, packing divides the head count, and the packed fp32
    score tile (hp·bk·bq) stays within ~2 MB of VMEM."""
    blocks = [b for b in (128, 256, 512, 1024)]
    out = []
    for hp in (1, 2, 4, 8):
        if h % hp:
            continue
        for bq in blocks:
            if sq % bq:
                continue
            for bk in blocks:
                if sk % bk:
                    continue
                if hp * bq * bk > max_score_elems:
                    continue
                out.append({"block_q": bq, "block_k": bk,
                            "heads_per_step": hp})
    return out


def flash_attrs(b, h, s, d, dtype, causal, bias="none", seg=False):
    """Self-attention (sq == sk == s) flash key attrs — delegates to
    the shared definition in apex_tpu.tune.flash_attrs."""
    from apex_tpu.tune import flash_attrs as _shared

    return _shared(b, h, s, s, d, dtype, causal, bias=bias, seg=seg)


def tune_flash(b: int, h: int, s: int, d: int, *, dtype=None,
               causal: bool = True, seg: bool = False,
               iters: int = 10, write: bool = True,
               use_pallas_override: Optional[bool] = None,
               verbose: bool = False
               ) -> Tuple[Dict[str, int], List[Tuple[Dict, float]]]:
    """Sweep flash fwd+bwd configs at one (shape, dtype) point; returns
    (best_config, [(config, seconds), ...]) and records the winner."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops.flash_attention import flash_attention

    dtype = dtype or jnp.bfloat16
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (b, h, s, d), dtype) for kk in ks)
    seg_ids = (jnp.zeros((b, s), jnp.int32) if seg else None)

    results = []
    for cand in flash_candidates(h, s, s):
        def fb(q, k, v, cand=cand):
            def f(q, k, v):
                return flash_attention(
                    q, k, v, causal=causal, segment_ids=seg_ids,
                    block_q=cand["block_q"], block_k=cand["block_k"],
                    heads_per_step=cand["heads_per_step"],
                    use_pallas_override=use_pallas_override)
            out, vjp = jax.vjp(f, q, k, v)
            return (out,) + vjp(out)

        try:
            # deliberate jit-per-candidate: every candidate IS a
            # different program; the sweep pays one compile each
            t = _time_fn(jax.jit(fb), (q, k, v), iters=iters)  # lint: disable=HS405
        except Exception as e:  # candidate may not compile on this chip
            if verbose:
                print(f"  flash {cand}: FAIL {repr(e)[:80]}", flush=True)
            continue
        results.append((cand, t))
        if verbose:
            print(f"  flash {cand}: {t*1e3:.3f} ms", flush=True)
    if not results:
        raise RuntimeError("no flash candidate compiled")
    results.sort(key=lambda r: r[1])
    best, best_t = results[0]
    attrs = flash_attrs(b, h, s, d, dtype, causal, seg=seg)
    if write:
        cache.record("flash_sdpa", attrs, best,
                     meta={"ms": round(best_t * 1e3, 4),
                           "swept": len(results)})
    return best, results


# --------------------------- row-blocked kernels ----------------------------

def _row_block_candidates(rows: int) -> List[int]:
    from apex_tpu.tune import pow2_bucket

    cap = pow2_bucket(rows)
    return [c for c in (64, 128, 256, 512, 1024) if c <= max(cap, 64)]


def tune_row_block(op: str, rows: int, hidden: int, *, dtype=None,
                   iters: int = 10, write: bool = True,
                   use_pallas_override: Optional[bool] = None):
    """Sweep the row-block of the softmax / layer-norm kernels (op in
    {"softmax_fwd", "softmax_bwd", "layer_norm_fwd", "layer_norm_bwd"}).
    fwd and bwd share one fwd+bwd timing sweep per family — the two
    entries are recorded with the same winning block."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.tune import pow2_bucket

    dtype = dtype or jnp.bfloat16
    family = op.rsplit("_", 1)[0]
    x = jax.random.normal(jax.random.PRNGKey(0), (rows, hidden), dtype)
    attrs_f = dict(rows=pow2_bucket(rows), hidden=hidden)

    def fb_factory():
        if family == "softmax":
            from apex_tpu.ops.softmax import scaled_softmax

            def f(x):
                return scaled_softmax(
                    x, 1.0, use_pallas_override=use_pallas_override)
        else:
            from apex_tpu.ops.layer_norm import fused_layer_norm

            w = jnp.ones((hidden,), jnp.float32)
            bb = jnp.zeros((hidden,), jnp.float32)

            def f(x):
                return fused_layer_norm(
                    x, w, bb,
                    use_pallas_override=(True if use_pallas_override
                                         is None else
                                         use_pallas_override))

        def fb(x):
            out, vjp = jax.vjp(f, x)
            return (out,) + vjp(out)
        return fb

    results = []
    for blk in _row_block_candidates(rows):
        cfg = {"block_rows": blk}
        with forced(family + "_fwd", attrs_f, cfg), \
                forced(family + "_bwd", attrs_f, cfg):
            try:
                # deliberate jit-per-candidate sweep (see tune_flash)
                t = _time_fn(jax.jit(fb_factory()), (x,), iters=iters)  # lint: disable=HS405
            except Exception:
                continue
        results.append((cfg, t))
    if not results:
        raise RuntimeError(f"no {family} row-block candidate compiled")
    results.sort(key=lambda r: r[1])
    best, best_t = results[0]
    if write:
        for suffix in ("_fwd", "_bwd"):
            cache.record(family + suffix, attrs_f, best,
                         meta={"ms": round(best_t * 1e3, 4)})
    return best, results


# ------------------------------ flat optimizers -----------------------------

def tune_opt_flat(n: int, *, kernel: str = "adam", iters: int = 10,
                  write: bool = True,
                  use_pallas_override: Optional[bool] = None):
    """Sweep rows-per-block of the flat Adam kernel at `n` params."""
    import jax
    import jax.numpy as jnp

    from apex_tpu.ops import optimizer_kernels as K
    from apex_tpu.tune import pow2_bucket

    n = -(-n // K.FLAT_TILE) * K.FLAT_TILE
    rows = n // K._LANES
    attrs = dict(kernel=kernel, rows=pow2_bucket(rows))
    p = jnp.zeros((n,), jnp.float32)
    m = jnp.zeros((n,), jnp.float32)
    v = jnp.zeros((n,), jnp.float32)
    g = jnp.full((n,), 1e-3, jnp.bfloat16)

    results = []
    for blk in (128, 256, 512):
        if rows % blk:
            continue
        with forced("opt_flat", attrs, {"block_rows": blk}):
            # deliberate jit-per-candidate sweep (see tune_flash)
            step = jax.jit(functools.partial(  # lint: disable=HS405
                K.adam_flat, lr=1e-3, step=10,
                use_pallas_override=use_pallas_override))
            try:
                t = _time_fn(step, (p, m, v, g), iters=iters)
            except Exception:
                continue
        results.append(({"block_rows": blk}, t))
    if not results:
        raise RuntimeError("no opt_flat candidate compiled")
    results.sort(key=lambda r: r[1])
    best, best_t = results[0]
    if write:
        cache.record("opt_flat", attrs, best,
                     meta={"ms": round(best_t * 1e3, 4)})
    return best, results
