"""Pipeline-parallel utilities.

≡ apex/transformer/pipeline_parallel/utils.py: microbatch calculator
globals (58-140), microbatch slicing (122), loss averaging (242),
params-L2-norm across model parallel (213), ltor masks (303).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from apex_tpu.ops.optimizer_kernels import l2norm_flat
from apex_tpu.optimizers.flat import flatten
from apex_tpu.parallel.mesh import DP_AXIS
from apex_tpu.transformer.microbatches import (
    build_num_microbatches_calculator,
)

_GLOBAL_NUM_MICROBATCHES_CALCULATOR = None


def setup_microbatch_calculator(rank: int, rampup_batch_size,
                                global_batch_size: int,
                                micro_batch_size: int,
                                data_parallel_size: int):
    """≡ utils.setup_microbatch_calculator (utils.py:58-76)."""
    global _GLOBAL_NUM_MICROBATCHES_CALCULATOR
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR = build_num_microbatches_calculator(
        rank, rampup_batch_size, global_batch_size, micro_batch_size,
        data_parallel_size)
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR


def get_num_microbatches():
    """≡ utils.get_num_microbatches (utils.py:92)."""
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get()


def get_current_global_batch_size():
    return _GLOBAL_NUM_MICROBATCHES_CALCULATOR.get_current_global_batch_size()


def update_num_microbatches(consumed_samples, consistency_check=True):
    _GLOBAL_NUM_MICROBATCHES_CALCULATOR.update(consumed_samples,
                                               consistency_check)


def get_kth_microbatch(batch, k: int, micro_batch_size: int):
    """≡ utils.get_kth_microbatch (utils.py:122-131)."""
    if batch is None:
        return None
    start = k * micro_batch_size
    return jax.tree_util.tree_map(
        lambda x: x[start:start + micro_batch_size], batch)


def split_into_microbatches(batch, num_microbatches: int):
    """Reshape a global batch (B, ...) to (m, B/m, ...) for the pipeline."""
    return jax.tree_util.tree_map(
        lambda x: x.reshape((num_microbatches, x.shape[0] // num_microbatches)
                            + x.shape[1:]), batch)


def average_losses_across_data_parallel_group(losses,
                                              axis_name: str = DP_AXIS):
    """≡ utils.average_losses_across_data_parallel_group (utils.py:242-250).
    Call inside the SPMD region."""
    stacked = jnp.stack([jnp.asarray(l, jnp.float32) for l in losses])
    return jax.lax.pmean(stacked, axis_name)


def calc_params_l2_norm(params):
    """≡ utils.calc_params_l2_norm (utils.py:213-239) — fused flat-buffer
    norm; for model-parallel params psum the squared local norm over tp
    before sqrt at the call site."""
    return l2norm_flat(flatten(params, jnp.float32))


def get_ltor_masks_and_position_ids(tokens, eod_token: Optional[int] = None,
                                    reset_position_ids: bool = False,
                                    reset_attention_mask: bool = False,
                                    eod_mask_loss: bool = False):
    """≡ utils.get_ltor_masks_and_position_ids (utils.py:303-330),
    simplified to the non-reset fast path (reset variants are documented
    gaps: they need per-document mask rebuilds that are host-side in the
    reference too)."""
    b, s = tokens.shape
    causal = jnp.tril(jnp.ones((s, s), bool))
    attention_mask = jnp.broadcast_to(causal, (b, 1, s, s))
    loss_mask = jnp.ones((b, s), jnp.float32)
    if eod_mask_loss and eod_token is not None:
        loss_mask = jnp.where(tokens == eod_token, 0.0, loss_mask)
    position_ids = jnp.broadcast_to(jnp.arange(s), (b, s))
    return attention_mask, loss_mask, position_ids


def report_memory(name=""):
    """≡ utils.report_memory (utils.py:253-263) — XLA/TPU version."""
    stats = []
    for d in jax.local_devices():
        try:
            m = d.memory_stats()
            stats.append(f"{d}: {m.get('bytes_in_use', 0) / 1e9:.2f}GB in use")
        except Exception:
            stats.append(f"{d}: memory stats unavailable")
    return f"[{name}] " + "; ".join(stats)
